"""Voxelizer invariants (SURVEY.md §4): analytic occupancy, fill, invariance."""

import numpy as np
import pytest

from featurenet_tpu.data import normalize_mesh, voxelize
from featurenet_tpu.data.mesh_primitives import mesh_box, mesh_cylinder


def _iou(a, b):
    return (a & b).sum() / max(1, (a | b).sum())


def test_cube_occupancy_matches_analytic():
    # A cube normalized with margin m fills [m, 1-m]^3 exactly.
    R, m = 16, 0.125
    grid = voxelize(mesh_box(), resolution=R, margin=m, backend="numpy")
    c = (np.arange(R) + 0.5) / R
    X, Y, Z = np.meshgrid(c, c, c, indexing="ij")
    expected = (
        (X > m) & (X < 1 - m) & (Y > m) & (Y < 1 - m) & (Z > m) & (Z < 1 - m)
    )
    # Parity fill is exact center-inside occupancy for a watertight box.
    np.testing.assert_array_equal(grid, expected)


def test_fill_vs_shell():
    R = 32
    solid = voxelize(mesh_box(), resolution=R, fill=True, backend="numpy")
    shell = voxelize(mesh_box(), resolution=R, fill=False, backend="numpy")
    assert solid.sum() > shell.sum()
    # Solid has interior voxels the shell doesn't touch.
    assert (solid & ~shell).sum() > 0
    # Flood fill (conservative) must contain the parity solid for a box.
    flood = voxelize(
        mesh_box(), resolution=R, fill=True, fill_method="flood", backend="numpy"
    )
    assert (solid & ~flood).sum() == 0


def test_cylinder_occupancy():
    R = 32
    grid = voxelize(
        mesh_cylinder(radius=0.25, z0=0.2, z1=0.8, segments=64),
        resolution=R,
        normalize=False,
        backend="numpy",
    )
    c = (np.arange(R) + 0.5) / R
    X, Y, Z = np.meshgrid(c, c, c, indexing="ij")
    expected = (
        ((X - 0.5) ** 2 + (Y - 0.5) ** 2 < 0.25**2) & (Z > 0.2) & (Z < 0.8)
    )
    assert _iou(grid, expected) > 0.8


@pytest.mark.parametrize("shift,scale", [(3.0, 2.0), (-10.0, 0.1)])
def test_normalize_invariance(shift, scale):
    # Voxelization is invariant to rigid translation + uniform scale.
    tris = mesh_box()
    moved = tris * scale + shift
    a = voxelize(tris, resolution=16, backend="numpy")
    b = voxelize(moved, resolution=16, backend="numpy")
    np.testing.assert_array_equal(a, b)


def test_normalize_mesh_bounds():
    tris = normalize_mesh(mesh_box() * 7.3 + 2.0, margin=0.1)
    flat = tris.reshape(-1, 3)
    assert flat.min() >= 0.1 - 1e-5
    assert flat.max() <= 0.9 + 1e-5
