"""Custom-op correctness: s2d stem equivalence, Pallas conv fwd/bwd parity.

All cases run on the CPU test platform (tests/conftest.py); the Pallas kernel
runs in interpret mode there — the same kernel code Mosaic compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from featurenet_tpu.ops.conv3d import conv3d_p, pallas_conv_supported
from featurenet_tpu.ops.stem import SpaceToDepthConv, space_to_depth_conv


def ref_conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride,) * 3, "SAME",
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )


@pytest.mark.parametrize("r,k,s,cin,cout", [
    (16, 7, 2, 1, 8),   # the paper stem shape class
    (8, 5, 2, 2, 4),
    (12, 3, 2, 1, 4),
    (9, 3, 3, 1, 4),    # stride 3, odd grid
    (8, 4, 2, 1, 4),    # even kernel
    (16, 7, 4, 1, 8),   # stride-4 stem (round-3 s4 flagship lever)
    (16, 5, 4, 1, 8),   # 5^3/s4 sprint64 stem: pad_lo=0, even transformed
                        # kernel with asymmetric padding — a distinct plan
                        # branch from every k=7 case (round-4 flagship)
])
def test_s2d_conv_matches_direct(rng, r, k, s, cin, cout):
    x = jnp.asarray(rng.standard_normal((2, r, r, r, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, cin, cout)), jnp.float32)
    got = space_to_depth_conv(x, w, s)
    want = ref_conv(x, w, s)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_s2d_conv_grad_matches_direct(rng):
    r, k, s = 8, 7, 2
    x = jnp.asarray(rng.standard_normal((2, r, r, r, 1)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, 1, 4)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((2, r // s, r // s, r // s, 4)),
                    jnp.float32)
    dw_s2d = jax.grad(lambda w: jnp.vdot(space_to_depth_conv(x, w, s), g))(w)
    dw_ref = jax.grad(lambda w: jnp.vdot(ref_conv(x, w, s), g))(w)
    np.testing.assert_allclose(dw_s2d, dw_ref, rtol=1e-4, atol=1e-4)


def test_s2d_module_param_shape(rng):
    m = SpaceToDepthConv(8, 7, 2, dtype=jnp.float32)
    x = jnp.zeros((1, 16, 16, 16, 1), jnp.float32)
    variables = m.init(jax.random.key(0), x)
    assert variables["params"]["kernel"].shape == (7, 7, 7, 1, 8)
    assert m.apply(variables, x).shape == (1, 8, 8, 8, 8)


@pytest.mark.parametrize("k,cin,cout", [(3, 16, 32), (5, 8, 16)])
def test_pallas_conv_forward(rng, k, cin, cout):
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, cin, cout)) * 0.1,
                    jnp.float32)
    assert pallas_conv_supported(x.shape, k, cout, x.dtype)
    got = conv3d_p(x, w)
    want = ref_conv(x, w)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_pallas_conv_vjp(rng):
    k, cin, cout = 3, 4, 8
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, cin, cout)) * 0.1,
                    jnp.float32)
    g = jnp.asarray(rng.standard_normal((2, 8, 8, 8, cout)), jnp.float32)

    def loss(f):
        return lambda x, w: jnp.vdot(f(x, w), g)

    dx_p, dw_p = jax.grad(loss(conv3d_p), argnums=(0, 1))(x, w)
    dx_r, dw_r = jax.grad(loss(ref_conv), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(dx_p, dx_r, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(dw_p, dw_r, rtol=1e-4, atol=1e-4)


def test_model_s2d_stem_matches_direct(rng):
    """FeatureNet logits agree between s2d and direct stem given same params."""
    from featurenet_tpu.models.featurenet import FeatureNet, FeatureNetArch

    arch_kw = dict(features=(8, 16), kernels=(7, 3), strides=(2, 1),
                   pool_after=(False, True), hidden=32)
    m_s2d = FeatureNet(
        arch=FeatureNetArch(stem_s2d=True, **arch_kw), dtype=jnp.float32)
    m_dir = FeatureNet(
        arch=FeatureNetArch(stem_s2d=False, **arch_kw), dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 16, 1)), jnp.float32)
    v_s2d = m_s2d.init({"params": jax.random.key(0)}, x, train=False)
    v_dir = m_dir.init({"params": jax.random.key(0)}, x, train=False)
    # Same leaf structure/shapes in both trees — carry s2d params over.
    leaves = jax.tree_util.tree_leaves(v_s2d)
    treedef = jax.tree_util.tree_structure(v_dir)
    assert [l.shape for l in leaves] == \
        [l.shape for l in jax.tree_util.tree_leaves(v_dir)]
    v_dir = jax.tree_util.tree_unflatten(treedef, leaves)
    out_s2d = m_s2d.apply(v_s2d, x, train=False)
    out_dir = m_dir.apply(v_dir, x, train=False)
    np.testing.assert_allclose(out_s2d, out_dir, rtol=1e-4, atol=1e-4)


def test_model_pallas_backend(rng):
    """conv_backend='pallas' runs end-to-end and matches the XLA backend."""
    from featurenet_tpu.models.featurenet import FeatureNet, FeatureNetArch

    arch_kw = dict(features=(8, 16), kernels=(3, 3), strides=(1, 1),
                   pool_after=(True, True), hidden=32)
    m_pal = FeatureNet(arch=FeatureNetArch(conv_backend="pallas", **arch_kw),
                       dtype=jnp.float32)
    m_xla = FeatureNet(arch=FeatureNetArch(conv_backend="xla", **arch_kw),
                       dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, 1)), jnp.float32)
    v_pal = m_pal.init({"params": jax.random.key(0)}, x, train=False)
    v_xla = m_xla.init({"params": jax.random.key(0)}, x, train=False)
    leaves = jax.tree_util.tree_leaves(v_pal)
    treedef = jax.tree_util.tree_structure(v_xla)
    assert [l.shape for l in leaves] == \
        [l.shape for l in jax.tree_util.tree_leaves(v_xla)]
    v_xla = jax.tree_util.tree_unflatten(treedef, leaves)
    out_pal = m_pal.apply(v_pal, x, train=False)
    out_xla = m_xla.apply(v_xla, x, train=False)
    np.testing.assert_allclose(out_pal, out_xla, rtol=1e-4, atol=1e-4)


def test_flops_model_hand_check():
    """2·MACs conv counting against a hand-computed tiny stack."""
    from featurenet_tpu.models.featurenet import FeatureNetArch
    from featurenet_tpu.ops.flops import (
        classifier_forward_flops,
        train_step_flops_per_sample,
    )

    arch = FeatureNetArch(
        features=(2,), kernels=(3,), strides=(1,), pool_after=(False,),
        hidden=4, num_classes=3,
    )
    # conv: 2*27*1*2*4^3 = 6912; dense1: 2*(2*4^3)*4 = 1024; dense2: 2*4*3
    expect = 6912 + 1024 + 24
    assert classifier_forward_flops(arch, 4) == expect
    assert train_step_flops_per_sample(arch, 4) == 3 * expect


def test_flops_model_paper_arch_magnitude():
    """The pod64 paper arch lands in the documented ~30-40 GFLOP/sample
    band (BASELINE.md's coarse estimate was 40; the exact 2·MACs count is
    ~31) — catches unit errors (MACs-vs-FLOPs, missing pool halving)."""
    from featurenet_tpu.models.featurenet import FeatureNetArch
    from featurenet_tpu.ops.flops import train_step_flops_per_sample

    g = train_step_flops_per_sample(FeatureNetArch(), 64) / 1e9
    assert 25 < g < 45, g


def test_conv_dw_folded_matches_xla_vjp():
    """Tap-folded Pallas weight grad == XLA conv VJP weight grad."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from featurenet_tpu.ops.conv_dw import conv_dw_folded

    rng = np.random.default_rng(0)
    for (B, D, H, W, Ci, Co, K) in [
        (2, 8, 8, 8, 8, 16, 3),
        (2, 8, 8, 16, 32, 32, 5),
    ]:
        x = jnp.asarray(rng.standard_normal((B, D, H, W, Ci)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((B, D, H, W, Co)), jnp.float32)

        def f(w):
            return jax.lax.conv_general_dilated(
                x, w, (1, 1, 1), "SAME",
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            )

        w0 = jnp.zeros((K, K, K, Ci, Co), jnp.float32)
        ref = jax.vjp(f, w0)[1](g)[0]
        ours = conv_dw_folded(x, g, K)
        err = float(jnp.abs(ours - ref).max() / jnp.abs(ref).max())
        assert err < 1e-5, (B, D, H, W, Ci, Co, K, err)


def test_hybrid_conv_grads_match_xla_conv():
    """conv3d_hybrid: forward and BOTH grads match lax.conv end to end."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from featurenet_tpu.ops.conv3d import conv3d_hybrid

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 3, 8, 16)), jnp.float32)

    def ref_fn(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1, 1), "SAME",
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )

    def loss(fn):
        return lambda x, w: (fn(x, w) ** 2).sum()

    np.testing.assert_allclose(
        np.asarray(conv3d_hybrid(x, w)), np.asarray(ref_fn(x, w)), rtol=1e-5
    )
    gx, gw = jax.grad(loss(conv3d_hybrid), argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss(ref_fn), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=2e-4)


def test_hybrid_backend_trains_smoke():
    """A FeatureNet with conv_backend='hybrid_dw' runs a train step."""
    import dataclasses

    import jax
    import numpy as np

    from featurenet_tpu.models.featurenet import FeatureNetArch, tiny_arch
    from featurenet_tpu.models import FeatureNet
    import jax.numpy as jnp

    arch = dataclasses.replace(tiny_arch(), conv_backend="hybrid_dw")
    model = FeatureNet(arch=arch)
    x = jnp.asarray(
        np.random.default_rng(0).random((2, 16, 16, 16, 1)), jnp.float32
    )
    variables = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        x, train=True,
    )

    def loss(params):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, rngs={"dropout": jax.random.key(2)},
            mutable=["batch_stats"],
        )
        return (out ** 2).mean()

    g = jax.grad(loss)(variables["params"])
    assert all(
        np.isfinite(np.asarray(v)).all() for v in jax.tree_util.tree_leaves(g)
    )


# --- fused33: layout-specialized 3^3 tap-unrolled conv (ISSUE 12) ------------

def test_fused33_conv_fwd_and_grads_match_xla_conv():
    """fused33_conv (ops/conv33.py): forward, dx, and dw all match
    lax.conv to accumulation-order rounding — the specialization changes
    the lowering, never the math."""
    from featurenet_tpu.ops.conv33 import fused33_conv

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, 4)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 3, 4, 6)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fused33_conv(x, w)), np.asarray(ref_conv(x, w)),
        rtol=1e-4, atol=1e-4,
    )

    def loss(fn):
        return lambda x, w: (fn(x, w) ** 2).sum()

    gx, gw = jax.grad(loss(fused33_conv), argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss(ref_conv), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=2e-4, atol=2e-4)
    # Non-3^3 kernels are refused, not silently mis-lowered (ConvBNRelu
    # routes those to nn.Conv).
    w5 = jnp.asarray(rng.standard_normal((5, 5, 5, 4, 6)), jnp.float32)
    with pytest.raises(ValueError, match="3"):
        fused33_conv(x, w5)


def test_fused33_backend_trains_and_matches_xla_numerics():
    """A FeatureNet with conv_backend='fused33' trains (finite grads),
    its param TREE is identical to the xla backend's (Fused33Conv pins
    nn.Conv's scope name, so a checkpoint restores under either backend
    — the A/B the conv_backend identity exemption exists for), and the
    xla model's weights applied through the fused33 model produce the
    same eval logits to working-precision rounding."""
    import dataclasses

    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.models.featurenet import tiny_arch

    arch33 = dataclasses.replace(tiny_arch(), conv_backend="fused33")
    model33 = FeatureNet(arch=arch33)
    model_x = FeatureNet(arch=tiny_arch())
    x = jnp.asarray(
        np.random.default_rng(0).random((2, 16, 16, 16, 1)), jnp.float32
    )
    v33 = model33.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        x, train=True,
    )
    vx = model_x.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        x, train=True,
    )
    # Identical tree: same structure, same leaf shapes — the xla
    # checkpoint drops into the fused33 model verbatim.
    assert (jax.tree_util.tree_structure(v33["params"])
            == jax.tree_util.tree_structure(vx["params"]))
    out33 = model33.apply(
        {"params": vx["params"], "batch_stats": vx["batch_stats"]},
        x, train=False,
    )
    outx = model_x.apply(
        {"params": vx["params"], "batch_stats": vx["batch_stats"]},
        x, train=False,
    )
    np.testing.assert_allclose(np.asarray(out33), np.asarray(outx),
                               rtol=2e-2, atol=2e-2)  # bf16 compute

    def loss(params):
        out, _ = model33.apply(
            {"params": params, "batch_stats": v33["batch_stats"]},
            x, train=True, rngs={"dropout": jax.random.key(2)},
            mutable=["batch_stats"],
        )
        return (out ** 2).mean()

    g = jax.grad(loss)(v33["params"])
    assert all(
        np.isfinite(np.asarray(v)).all() for v in jax.tree_util.tree_leaves(g)
    )


def test_bench_arch_carries_fused33_comparison_rows():
    """ops/bench_arch.py is the harness of record for the stem ladder:
    the fused33 comparison rows exist (fused33 vs paper, k3_fused33 vs
    k3) so TPU round r06 measures the specialization in one pass."""
    from featurenet_tpu.ops.bench_arch import VARIANTS

    assert VARIANTS["fused33"].conv_backend == "fused33"
    assert VARIANTS["k3_fused33"].conv_backend == "fused33"
    assert VARIANTS["k3_fused33"].kernels == (7, 3, 3, 3)
    # The apples-to-apples pairs differ ONLY in the backend.
    import dataclasses

    assert dataclasses.replace(
        VARIANTS["fused33"], conv_backend="xla"
    ) == VARIANTS["paper"]
    assert dataclasses.replace(
        VARIANTS["k3_fused33"], conv_backend="xla"
    ) == VARIANTS["k3"]


@pytest.mark.slow
def test_fused33_cpu_comparison_row_measures():
    """The bench comparison row for the layout-specialized stem, measured
    on CPU (the converged-slope protocol end to end over the fused33
    train_step vs the xla one — TPU r06 pins the real ratio; this proves
    the row's machinery and records a CPU reference in the test log)."""
    import dataclasses

    from featurenet_tpu.benchmark import measure_train_step
    from featurenet_tpu.config import get_config

    cfg = get_config("smoke16")
    rows = {}
    for backend in ("xla", "fused33"):
        bcfg = dataclasses.replace(
            cfg, arch=dataclasses.replace(cfg.arch, conv_backend=backend)
        ).validate()
        rows[backend] = measure_train_step(
            bcfg, batch_per_chip=4, repeats=1, measure=2,
            min_window_sec=0.2,
        )
        assert rows[backend]["samples_per_sec_per_chip"] > 0
    ratio = (rows["fused33"]["samples_per_sec_per_chip"]
             / rows["xla"]["samples_per_sec_per_chip"])
    print(f"fused33 vs xla (CPU, smoke16): {ratio:.2f}x")
