"""Custom-op correctness: s2d stem equivalence, Pallas conv fwd/bwd parity.

All cases run on the CPU test platform (tests/conftest.py); the Pallas kernel
runs in interpret mode there — the same kernel code Mosaic compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from featurenet_tpu.ops.conv3d import conv3d_p, pallas_conv_supported
from featurenet_tpu.ops.stem import SpaceToDepthConv, space_to_depth_conv


def ref_conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride,) * 3, "SAME",
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )


@pytest.mark.parametrize("r,k,s,cin,cout", [
    (16, 7, 2, 1, 8),   # the paper stem shape class
    (8, 5, 2, 2, 4),
    (12, 3, 2, 1, 4),
    (9, 3, 3, 1, 4),    # stride 3, odd grid
    (8, 4, 2, 1, 4),    # even kernel
])
def test_s2d_conv_matches_direct(rng, r, k, s, cin, cout):
    x = jnp.asarray(rng.standard_normal((2, r, r, r, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, cin, cout)), jnp.float32)
    got = space_to_depth_conv(x, w, s)
    want = ref_conv(x, w, s)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_s2d_conv_grad_matches_direct(rng):
    r, k, s = 8, 7, 2
    x = jnp.asarray(rng.standard_normal((2, r, r, r, 1)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, 1, 4)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((2, r // s, r // s, r // s, 4)),
                    jnp.float32)
    dw_s2d = jax.grad(lambda w: jnp.vdot(space_to_depth_conv(x, w, s), g))(w)
    dw_ref = jax.grad(lambda w: jnp.vdot(ref_conv(x, w, s), g))(w)
    np.testing.assert_allclose(dw_s2d, dw_ref, rtol=1e-4, atol=1e-4)


def test_s2d_module_param_shape(rng):
    m = SpaceToDepthConv(8, 7, 2, dtype=jnp.float32)
    x = jnp.zeros((1, 16, 16, 16, 1), jnp.float32)
    variables = m.init(jax.random.key(0), x)
    assert variables["params"]["kernel"].shape == (7, 7, 7, 1, 8)
    assert m.apply(variables, x).shape == (1, 8, 8, 8, 8)


@pytest.mark.parametrize("k,cin,cout", [(3, 16, 32), (5, 8, 16)])
def test_pallas_conv_forward(rng, k, cin, cout):
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, cin, cout)) * 0.1,
                    jnp.float32)
    assert pallas_conv_supported(x.shape, k, cout, x.dtype)
    got = conv3d_p(x, w)
    want = ref_conv(x, w)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_pallas_conv_vjp(rng):
    k, cin, cout = 3, 4, 8
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, k, cin, cout)) * 0.1,
                    jnp.float32)
    g = jnp.asarray(rng.standard_normal((2, 8, 8, 8, cout)), jnp.float32)

    def loss(f):
        return lambda x, w: jnp.vdot(f(x, w), g)

    dx_p, dw_p = jax.grad(loss(conv3d_p), argnums=(0, 1))(x, w)
    dx_r, dw_r = jax.grad(loss(ref_conv), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(dx_p, dx_r, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(dw_p, dw_r, rtol=1e-4, atol=1e-4)


def test_model_s2d_stem_matches_direct(rng):
    """FeatureNet logits agree between s2d and direct stem given same params."""
    from featurenet_tpu.models.featurenet import FeatureNet, FeatureNetArch

    arch_kw = dict(features=(8, 16), kernels=(7, 3), strides=(2, 1),
                   pool_after=(False, True), hidden=32)
    m_s2d = FeatureNet(
        arch=FeatureNetArch(stem_s2d=True, **arch_kw), dtype=jnp.float32)
    m_dir = FeatureNet(
        arch=FeatureNetArch(stem_s2d=False, **arch_kw), dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 16, 1)), jnp.float32)
    v_s2d = m_s2d.init({"params": jax.random.key(0)}, x, train=False)
    v_dir = m_dir.init({"params": jax.random.key(0)}, x, train=False)
    # Same leaf structure/shapes in both trees — carry s2d params over.
    leaves = jax.tree_util.tree_leaves(v_s2d)
    treedef = jax.tree_util.tree_structure(v_dir)
    assert [l.shape for l in leaves] == \
        [l.shape for l in jax.tree_util.tree_leaves(v_dir)]
    v_dir = jax.tree_util.tree_unflatten(treedef, leaves)
    out_s2d = m_s2d.apply(v_s2d, x, train=False)
    out_dir = m_dir.apply(v_dir, x, train=False)
    np.testing.assert_allclose(out_s2d, out_dir, rtol=1e-4, atol=1e-4)


def test_model_pallas_backend(rng):
    """conv_backend='pallas' runs end-to-end and matches the XLA backend."""
    from featurenet_tpu.models.featurenet import FeatureNet, FeatureNetArch

    arch_kw = dict(features=(8, 16), kernels=(3, 3), strides=(1, 1),
                   pool_after=(True, True), hidden=32)
    m_pal = FeatureNet(arch=FeatureNetArch(conv_backend="pallas", **arch_kw),
                       dtype=jnp.float32)
    m_xla = FeatureNet(arch=FeatureNetArch(conv_backend="xla", **arch_kw),
                       dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8, 1)), jnp.float32)
    v_pal = m_pal.init({"params": jax.random.key(0)}, x, train=False)
    v_xla = m_xla.init({"params": jax.random.key(0)}, x, train=False)
    leaves = jax.tree_util.tree_leaves(v_pal)
    treedef = jax.tree_util.tree_structure(v_xla)
    assert [l.shape for l in leaves] == \
        [l.shape for l in jax.tree_util.tree_leaves(v_xla)]
    v_xla = jax.tree_util.tree_unflatten(treedef, leaves)
    out_pal = m_pal.apply(v_pal, x, train=False)
    out_xla = m_xla.apply(v_xla, x, train=False)
    np.testing.assert_allclose(out_pal, out_xla, rtol=1e-4, atol=1e-4)
