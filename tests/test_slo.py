"""Live SLO layer: rolling-window aggregation (obs.windows), declarative
alert rules (obs.alerts), the report/follow/trace surfacing, latency fault
injection, and the supervisor's self-pinning segment gates.

The acceptance spine (ISSUE 5): an injected ``producer_slow`` run emits
``window_summary`` events, fires a data-wait alert visible in both ``cli
report`` (SLO section) and ``--follow``; a supervised 2-segment run
auto-pins its gate baseline after segment 1 and gates segment 2.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

from featurenet_tpu import faults, obs
from featurenet_tpu.config import get_config
from featurenet_tpu.obs import alerts, windows
from featurenet_tpu.obs.report import (
    build_report,
    follow_report,
    follow_slo_line,
    format_report,
    load_events,
    validate_events,
)
from featurenet_tpu.train.loop import Trainer


# Process-wide obs/faults state is reset by conftest's autouse
# _reset_process_state fixture (tests-tree fixture hygiene, PR 7).


# --- rolling windows ---------------------------------------------------------

def test_rolling_window_count_and_age_eviction():
    w = windows.RollingWindow(maxlen=4, max_age_s=10.0)
    for i in range(6):
        w.add(float(i), now=100.0 + i)
    # Count bound: only the last 4 samples survive.
    assert w.values(now=106.0) == [2.0, 3.0, 4.0, 5.0]
    # Age bound: at t=114 samples older than 10s (t<104) are evicted.
    assert w.values(now=114.9) == [5.0]
    s = w.summary(now=114.9)
    assert (s["n"], s["p50"], s["max"]) == (1, 5.0, 5.0)
    # Fully aged out: no summary rather than a stale one.
    assert w.summary(now=300.0) is None


def test_rolling_window_percentiles_nearest_rank():
    w = windows.RollingWindow(maxlen=200, max_age_s=None)
    for i in range(1, 101):
        w.add(float(i), now=0.0)
    s = w.summary(now=0.0)
    assert s["p50"] == 51.0  # nearest-rank on 100 samples: index 50
    assert s["p95"] == 95.0
    assert s["p99"] == 99.0
    assert s["max"] == 100.0
    assert s["mean"] == pytest.approx(50.5)


# --- alert-rule DSL ----------------------------------------------------------

def test_parse_rules_defaults_and_custom():
    default = alerts.parse_rules(None)
    assert default == list(alerts.DEFAULT_RULES)
    assert {r.metric for r in default} >= {
        "data_wait_fraction", "step_p99_ratio", "heartbeat_age_s",
        "data_wait_spread",
    }
    spread = next(r for r in default if r.metric == "data_wait_spread")
    assert spread.scope == "report"  # cross-host: the report judges it

    rules = alerts.parse_rules(
        "data_wait_fraction>0.6:critical,queue_depth<1,serving_ms_p99>20"
    )
    assert [r.metric for r in rules] == [
        "data_wait_fraction", "queue_depth", "serving_ms_p99"
    ]
    assert rules[0].severity == "critical" and rules[0].op == ">"
    assert rules[1].op == "<" and rules[1].severity == "warning"
    assert rules[2].scope == "process"
    assert rules[0].violated(0.7) and not rules[0].violated(0.5)
    assert rules[1].violated(0.0) and not rules[1].violated(2.0)


def test_parse_rules_rejects_typos_at_config_time():
    with pytest.raises(ValueError, match="unknown alert metric"):
        alerts.parse_rules("data_wait_fracton>0.5")
    with pytest.raises(ValueError, match="malformed"):
        alerts.parse_rules("data_wait_fraction=0.5")
    with pytest.raises(ValueError, match="malformed"):
        alerts.parse_rules("data_wait_fraction>lots")
    with pytest.raises(ValueError, match="must be a number"):
        alerts.parse_rules("data_wait_fraction>1e")
    with pytest.raises(ValueError, match="unknown alert severity"):
        alerts.parse_rules("data_wait_fraction>0.5:panic")
    with pytest.raises(ValueError, match="duplicate"):
        alerts.parse_rules("queue_depth<1,queue_depth<2")
    with pytest.raises(ValueError, match="empty"):
        alerts.parse_rules(" , ")
    # And Config.validate applies the same refusal.
    with pytest.raises(ValueError, match="unknown alert metric"):
        get_config("smoke16", alert_rules="tyop>1")


# --- aggregator emission + alert firing --------------------------------------

def test_aggregator_emits_summaries_and_alerts(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    agg = windows.WindowAggregator(
        rules=alerts.parse_rules("data_wait_fraction>0.5,heartbeat_age_s>30"),
        emit_every_s=1e9,  # only flush() emits: deterministic one cycle
    )
    windows.install(agg)
    for _ in range(8):
        obs.observe("step_ms", 100.0)
        obs.observe("data_wait_ms", 80.0)
    obs.observe("heartbeat_age_s", 2.0)  # healthy: must NOT alert
    windows.flush()
    obs.close_run()

    events, bad = load_events(run_dir)
    assert bad == 0
    assert validate_events(events) == []  # new kinds are schema-known
    sums = {e["metric"]: e for e in events if e["ev"] == "window_summary"}
    assert {"step_ms", "data_wait_ms", "heartbeat_age_s"} <= set(sums)
    s = sums["step_ms"]
    assert s["n"] == 8 and s["p50"] == 100.0 and s["p99"] == 100.0
    fired = [e for e in events if e["ev"] == "alert"]
    assert [e["rule"] for e in fired] == ["data_wait_fraction"]
    a = fired[0]
    assert a["value"] == pytest.approx(0.8)
    assert a["threshold"] == 0.5
    assert a["severity"] == "warning"
    assert a["window"] == s["seq"]  # same emission cycle
    assert a["state"] == "fire"


def test_alert_hysteresis_fire_resolve_pairs(tmp_path):
    """A violation lasting N cycles is ONE fire; recovery is its paired
    resolve; a second violation is a fresh pair — never per-cycle
    re-fires (carried-over SLO follow-on)."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    agg = windows.WindowAggregator(
        rules=alerts.parse_rules("data_wait_ms_max>50"),
        window=4, emit_every_s=1e9,
    )
    windows.install(agg)

    def cycle(value):
        for _ in range(4):  # refill the 4-deep ring with one level
            obs.observe("data_wait_ms", value)
        windows.flush()

    cycle(80.0)   # crossing in -> fire
    cycle(90.0)   # still violated -> SILENT (the hysteresis)
    cycle(10.0)   # recovered -> resolve
    cycle(10.0)   # still healthy -> silent
    cycle(70.0)   # second violation -> second fire
    obs.close_run()

    events, _ = load_events(run_dir)
    assert validate_events(events) == []
    transitions = [e["state"] for e in events if e["ev"] == "alert"]
    assert transitions == ["fire", "resolve", "fire"]

    # Report: last transition is an unresolved fire -> ACTIVE, counts
    # split fires from resolves.
    rep = build_report(events)
    a = rep["slo"]["alerts"]["data_wait_ms_max"]
    assert a["count"] == 2 and a["resolves"] == 1 and a["active"] is True
    assert "ACTIVE data_wait_ms_max" in format_report(rep)
    # Drop the trailing fire: the resolved pair alone reads recovered.
    recovered = [e for e in events
                 if not (e["ev"] == "alert" and e["t"] == max(
                     x["t"] for x in events if x["ev"] == "alert"))]
    a2 = build_report(recovered)["slo"]["alerts"]["data_wait_ms_max"]
    assert a2["active"] is False


def test_aggregator_periodic_emission_and_span_hook(tmp_path):
    """The span-exit hook feeds the windows (data_wait/infer_batch), and
    an elapsed emit period triggers a cycle without any flush."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    windows.install(windows.WindowAggregator(emit_every_s=0.0))
    with obs.span("infer_batch", n=4):
        pass
    with obs.span("data_wait"):
        pass
    obs.close_run()
    events, _ = load_events(run_dir)
    sums = {e["metric"] for e in events if e["ev"] == "window_summary"}
    assert "serving_ms" in sums and "data_wait_ms" in sums


def test_span_hook_normalizes_fused_dispatch_per_step(tmp_path):
    """A fused dispatch's data_wait span covers `take` steps at once; the
    window sample must be per-step or data_wait_fraction reads k× too
    high on healthy pipelined runs (step_ms is per-step by construction)."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    agg = windows.WindowAggregator(emit_every_s=1e9)
    windows.install(agg)
    with obs.span("data_wait", take=8):
        pass
    windows.observe_span("data_wait", 0.8, {"take": 8})
    vals = agg._win["data_wait_ms"].values(now=agg._last_emit + 1)
    assert vals[-1] == pytest.approx(100.0)  # 800ms / 8 steps
    # take=1 (or absent) stays un-normalized; serving is per-batch.
    windows.observe_span("data_wait", 0.2, {"take": 1})
    assert agg._win["data_wait_ms"].values(
        now=agg._last_emit + 1)[-1] == pytest.approx(200.0)
    windows.observe_span("infer_batch", 0.4, {"n": 8})
    assert agg._win["serving_ms"].values(
        now=agg._last_emit + 1)[-1] == pytest.approx(400.0)
    # The fraction the default alert judges is now k-invariant.
    for _ in range(8):
        agg.observe("step_ms", 100.0)
    frac = agg.rule_value("data_wait_fraction", agg._last_emit + 1)
    assert frac < 0.5  # ~(100+200+eps)/800


def test_active_flag_ors_across_hosts():
    """A rule still live on host 0 must not be masked by a
    later-timestamped recovered firing on another host."""
    def summary(t, h, seq):
        return {"t": t, "ev": "window_summary", "metric": "step_ms",
                "n": 4, "p50": 1.0, "p95": 1.0, "p99": 1.0, "mean": 1.0,
                "max": 1.0, "seq": seq, "process_index": h}

    def alert(t, h, window):
        return {"t": t, "ev": "alert", "rule": "step_p99_ratio",
                "severity": "warning", "value": 5.0, "threshold": 4.0,
                "window": window, "process_index": h}

    events = [
        summary(1.0, 0, 3), alert(1.0, 0, 3),   # host 0: live at its latest
        summary(2.0, 1, 3), alert(2.0, 1, 3),
        summary(3.0, 1, 9),                     # host 1: recovered since
    ]
    rep = build_report(events)
    assert rep["slo"]["alerts"]["step_p99_ratio"]["active"] is True
    # Both hosts recovered -> inactive.
    rep2 = build_report(events[2:] + [summary(4.0, 0, 9)])
    assert rep2["slo"]["alerts"]["step_p99_ratio"]["active"] is False


def test_init_run_switch_resets_windows(tmp_path):
    """Switching run dirs must not leak run A's ring buffers/seq into run
    B's first summary: A gets a final flushed cycle, B starts fresh."""
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    obs.init_run(a, process_index=0)
    agg_a = windows.WindowAggregator(emit_every_s=1e9)
    windows.install(agg_a)
    for _ in range(5):
        obs.observe("step_ms", 100.0)
    obs.init_run(b, process_index=0)  # no close_run: the switching path
    assert not windows.active() or windows._agg is not agg_a
    obs.observe("step_ms", 7.0)
    windows.flush()
    obs.close_run()
    ev_a, _ = load_events(a)
    ev_b, _ = load_events(b)
    sum_a = [e for e in ev_a if e["ev"] == "window_summary"]
    sum_b = [e for e in ev_b if e["ev"] == "window_summary"]
    assert sum_a and sum_a[-1]["n"] == 5  # A's samples flushed into A
    assert sum_b and sum_b[-1]["n"] == 1  # B sees ONLY its own sample
    assert sum_b[-1]["p50"] == 7.0 and sum_b[-1]["seq"] == 1


def test_observe_without_aggregator_is_noop():
    assert not windows.active()
    obs.observe("step_ms", 1.0)  # no crash, no state
    windows.observe_span("data_wait", 0.1)
    windows.flush()


# --- report SLO section / follow / trace -------------------------------------

def _slo_events(t0=1000.0):
    return [
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 4, "total": 4},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": 0.5},
        {"t": t0 + 1.0, "ev": "window_summary", "metric": "step_ms",
         "n": 4, "p50": 100.0, "p95": 120.0, "p99": 130.0, "mean": 105.0,
         "max": 130.0, "seq": 1},
        {"t": t0 + 1.0, "ev": "alert", "rule": "data_wait_fraction",
         "severity": "warning", "value": 0.8, "threshold": 0.5, "window": 1},
        {"t": t0 + 2.0, "ev": "window_summary", "metric": "step_ms",
         "n": 8, "p50": 90.0, "p95": 95.0, "p99": 99.0, "mean": 91.0,
         "max": 99.0, "seq": 2},
        {"t": t0 + 2.5, "ev": "loop_end", "step": 4, "wall_s": 2.5},
    ]


def test_report_slo_section_counts_and_active_flag():
    rep = build_report(_slo_events())
    slo = rep["slo"]
    # Latest window wins the display.
    assert slo["windows"]["step_ms"]["p50"] == 90.0
    assert slo["windows"]["step_ms"]["seq"] == 2
    a = slo["alerts"]["data_wait_fraction"]
    assert a["count"] == 1 and a["last_value"] == 0.8
    # The alert fired at seq 1; the latest summary is seq 2 — recovered,
    # so it must NOT read as live.
    assert a["active"] is False
    txt = format_report(rep)
    assert "SLO windows" in txt and "step_ms" in txt
    assert "fired  data_wait_fraction" in txt

    # A second alert on the latest cycle IS active.
    ev = _slo_events() + [
        {"t": 1002.1, "ev": "alert", "rule": "data_wait_fraction",
         "severity": "warning", "value": 0.9, "threshold": 0.5, "window": 2},
    ]
    rep2 = build_report(ev)
    a2 = rep2["slo"]["alerts"]["data_wait_fraction"]
    assert a2["count"] == 2 and a2["active"] is True
    assert "ACTIVE data_wait_fraction" in format_report(rep2)
    line = follow_slo_line(rep2)
    assert line.startswith("== slo |")
    assert "step_ms p50 90.0/p99 99.0" in line
    assert "ALERTS: data_wait_fraction" in line
    # No SLO telemetry -> no line (the follow header stays single).
    assert follow_slo_line(build_report(_slo_events()[:2])) is None


def test_report_side_cross_host_spread_alert():
    """The one rule no single process can judge: cross-host data-wait
    spread, evaluated where the streams merge (default threshold 0.25)."""
    def host(idx, dw):
        t0 = 1000.0 + idx * 0.1
        return [
            {"t": t0, "ev": "loop_start", "step": 0, "stop": 4, "total": 4,
             "process_index": idx},
            {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": dw,
             "process_index": idx},
            {"t": t0 + 2.0, "ev": "loop_end", "step": 4, "wall_s": 2.0,
             "process_index": idx},
        ]

    events = host(0, 0.2) + host(1, 1.2)  # fractions 10% vs 60%
    rep = build_report(sorted(events, key=lambda e: e["t"]))
    spread = rep["host_skew"]["data_wait_fraction"]["spread"]
    assert spread == pytest.approx(0.5)
    a = rep["slo"]["alerts"]["data_wait_spread"]
    assert a["active"] and a["source"] == "report"
    assert a["last_value"] == pytest.approx(0.5)
    # ... and the spread is a gateable scalar (ROADMAP obs-next item).
    from featurenet_tpu.obs.gates import evaluate_gates, report_gate_values

    vals = report_gate_values(rep)
    assert vals["data_wait_spread"] == pytest.approx(0.5)
    base = {"gates": {"data_wait_spread": {"value": 0.1, "tolerance": 0.1}}}
    res = evaluate_gates(vals, base)
    assert not res["ok"] and res["failed"] == ["data_wait_spread"]

    # A tight mesh (10% vs 12%) stays quiet.
    calm = host(0, 0.2) + host(1, 0.24)
    rep2 = build_report(sorted(calm, key=lambda e: e["t"]))
    assert "data_wait_spread" not in (rep2.get("slo") or {}).get("alerts", {})


def test_chrome_trace_exports_windows_as_counter_tracks():
    from featurenet_tpu.obs.spans import chrome_trace

    trace = chrome_trace(_slo_events())
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert len(counters) == 2
    assert counters[0]["name"] == "window step_ms"
    assert counters[0]["args"] == {"p50": 100.0, "p95": 120.0, "p99": 130.0}
    assert all(c["ts"] >= 0 for c in counters)
    # Counter-only logs still export (no spans required).
    only = [e for e in _slo_events() if e["ev"] == "window_summary"]
    assert [e["ph"] for e in chrome_trace(only)["traceEvents"]
            if e["ph"] == "C"]


# --- latency fault injection (producer_slow / save_slow) ---------------------

def test_producer_slow_injects_latency_not_death(monkeypatch):
    import time as _time

    from featurenet_tpu.data.dataset import (
        SyntheticVoxelDataset,
        prefetch_to_device,
    )

    monkeypatch.setattr(faults, "SLOW_SLEEP_S", 0.2)
    faults.install("producer_slow@batch=0")
    ds = SyntheticVoxelDataset(resolution=16, global_batch=4)
    t0 = _time.perf_counter()
    it = prefetch_to_device(ds, num_workers=1)
    batch = next(it)
    assert _time.perf_counter() - t0 >= 0.2  # slept, then produced
    assert batch["voxels"].shape[0] == 4  # the batch still arrives
    it.close()


def test_save_slow_off_critical_path_double_buffered(tmp_path, monkeypatch):
    """Acceptance (ISSUE 10): save() under the save_slow@save fault no
    longer stretches the step path. The double-buffered manager's
    host-blocking enqueue (the checkpoint_save span) stays bounded even
    while the PREVIOUS write is still dragging in flight — the injected
    latency lands in the background writer's checkpoint_write span —
    and the checksum sidecars still land for every finalized step."""
    import os as _os
    import time as _time

    from featurenet_tpu.train.checkpoint import _checksum_path

    monkeypatch.setattr(faults, "SLOW_SLEEP_S", 0.6)
    faults.install("save_slow@save=2")
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    cfg = get_config(
        "smoke16", total_steps=1, log_every=10**9, eval_every=10**9,
        checkpoint_every=1, eval_batches=1, data_workers=1, global_batch=8,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    t = Trainer(cfg)
    import jax.numpy as jnp

    # Warm the save path once (config-sidecar write, the snapshot
    # tree_map's first trace, writer-thread start): those are one-time
    # costs of the FIRST save ever, not the previous-write-in-flight
    # property under test — timing them made this assertion flaky.
    t.ckpt.save(t.state, step=1)
    t.ckpt.wait()
    t0 = _time.perf_counter()
    t.ckpt.save(t.state.replace(step=jnp.asarray(2, jnp.int32)), step=2)
    enq1 = _time.perf_counter() - t0
    # Third save WHILE the step-2 write sleeps 0.6 s in the writer: the
    # second snapshot slot absorbs it without waiting the write out.
    t0 = _time.perf_counter()
    t.ckpt.save(t.state.replace(step=jnp.asarray(3, jnp.int32)), step=3)
    enq2 = _time.perf_counter() - t0
    assert enq1 < 0.5 and enq2 < 0.5, (enq1, enq2)
    t.ckpt.wait()
    # Sidecars for every finalized step, written by the writer itself.
    root = str(tmp_path / "ckpt")
    for step in (1, 2, 3):
        assert _os.path.exists(_checksum_path(root, step))
    t.ckpt.close()
    obs.close_run()
    events, _ = load_events(run_dir)
    saves = [e for e in events
             if e["ev"] == "span" and e["name"] == "checkpoint_save"]
    writes = [e for e in events
              if e["ev"] == "span" and e["name"] == "checkpoint_write"]
    # Every enqueue span is bounded; the slowness is ATTRIBUTED — it
    # moved into step 2's checkpoint_write span, off the step path.
    assert len(saves) == 3 and all(s["dur_s"] < 0.5 for s in saves)
    assert writes and max(w["dur_s"] for w in writes) >= 0.6


def test_latency_sites_in_dsl_and_registry():
    parsed = faults.parse_spec("producer_slow@batch=8:every=4,save_slow")
    assert parsed["producer_slow"] == ("batch", 8, 4)
    assert parsed["save_slow"] is None
    assert faults.SITES["producer_slow"] == "batch"
    assert faults.SITES["save_slow"] == "save"


# --- acceptance: producer_slow run fires the data-wait alert e2e -------------

def test_e2e_producer_slow_fires_data_wait_alert(tmp_path, capsys):
    """Satellite 4 / acceptance: a run with producer_slow injected emits
    window_summary events and fires a data-wait alert that shows in the
    report's SLO section AND in --follow — tier-1, CPU, synthetic data."""
    run_dir = str(tmp_path / "run")
    cfg = get_config(
        "smoke16", total_steps=2, log_every=10**9, eval_every=10**9,
        checkpoint_every=10**9, eval_batches=1, data_workers=1,
        global_batch=8, run_dir=run_dir,
        inject_faults="producer_slow@batch=0:every=1",
        # max, not p50: the prefetcher legitimately hides most of the
        # injected latency behind the first compile (only some pops block),
        # and the window's MAX is what a sustained drag can't dodge.
        alert_rules="data_wait_ms_max>50:critical",
    )
    t = Trainer(cfg)
    t.run()
    obs.close_run()

    events, bad = load_events(run_dir)
    assert bad == 0
    sums = [e for e in events if e["ev"] == "window_summary"]
    assert any(e["metric"] == "data_wait_ms" for e in sums)
    fired = [e for e in events if e["ev"] == "alert"]
    assert any(
        e["rule"] == "data_wait_ms_max" and e["severity"] == "critical"
        and e["value"] > 50 for e in fired
    )
    # The schema lint knows the new kinds (satellite 6).
    assert validate_events(events, bad_lines=bad) == []

    from featurenet_tpu.cli import main as cli_main

    cli_main(["report", run_dir])
    out = capsys.readouterr().out
    assert "SLO windows" in out
    assert "data_wait_ms_max" in out and "critical" in out
    cli_main(["report", run_dir, "--validate"])
    assert '"validate": "ok"' in capsys.readouterr().out

    # --follow renders the percentiles + the alert under its header.
    outputs: list = []
    follow_report(run_dir, interval=0.01, out=outputs.append,
                  clock=lambda s: None, max_polls=1, clear=False)
    head_lines = outputs[0].splitlines()
    assert head_lines[1].startswith("== slo |")
    assert "data_wait_ms" in head_lines[1]
    assert "ALERTS: data_wait_ms_max" in head_lines[1]


# --- supervisor self-pinning gates -------------------------------------------

def _loop_stream(t0, step_ms):
    dur = step_ms / 1e3
    return [
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 4, "total": 4},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait",
         "dur_s": dur},
        {"t": t0 + 4 * dur, "ev": "loop_end", "step": 4,
         "wall_s": 4 * dur},
    ]


def test_gate_segment_pins_then_flags_regression(tmp_path):
    from featurenet_tpu.train.supervisor import (
        GATE_BASELINE_FILENAME,
        _gate_segment,
        segment_gate_values,
    )

    run_dir = str(tmp_path)
    path = os.path.join(run_dir, "events.jsonl")
    with open(path, "w") as fh:
        for e in _loop_stream(1000.0, step_ms=100.0):
            fh.write(json.dumps(e) + "\n")
    seg1_end = os.path.getsize(path)

    records: list = []
    logs: list = []

    def record(phase, **fields):
        records.append((phase, fields))

    # Segment 1 (offsets {}): no baseline yet -> auto-pin.
    vals = segment_gate_values(run_dir, {})
    assert vals["step_ms"] == pytest.approx(100.0)
    assert "restarts" not in vals  # supervisor-cumulative: never pinned
    _gate_segment(run_dir, {}, record, logs.append)
    pin_path = os.path.join(run_dir, GATE_BASELINE_FILENAME)
    assert os.path.exists(pin_path)
    assert records[-1][0] == "auto_pin"
    pinned = json.load(open(pin_path))
    assert pinned["gates"]["step_ms"]["value"] == pytest.approx(100.0)

    # Segment 2, steady: gate passes.
    with open(path, "a") as fh:
        for e in _loop_stream(2000.0, step_ms=110.0):
            fh.write(json.dumps(e) + "\n")
    _gate_segment(run_dir, {path: seg1_end}, record, logs.append)
    assert records[-1][0] == "gate" and records[-1][1]["ok"] is True
    seg2_end = os.path.getsize(path)

    # Segment 3, 5x slower: gate_regression — alert, never a verdict.
    with open(path, "a") as fh:
        for e in _loop_stream(3000.0, step_ms=500.0):
            fh.write(json.dumps(e) + "\n")
    _gate_segment(run_dir, {path: seg2_end}, record, logs.append)
    phase, fields = records[-1]
    assert phase == "gate_regression"
    assert "step_ms" in fields["failed"]
    assert fields["values"]["step_ms"] == pytest.approx(500.0)
    assert any('"gate_regression"' in line for line in logs)


def test_gate_segment_never_load_bearing(tmp_path):
    """A garbled baseline degrades to a gate_error log line — the judge
    must never kill (or restart) the run it judges."""
    from featurenet_tpu.train.supervisor import (
        GATE_BASELINE_FILENAME,
        _gate_segment,
    )

    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, "events.jsonl"), "w") as fh:
        for e in _loop_stream(1000.0, step_ms=100.0):
            fh.write(json.dumps(e) + "\n")
    with open(os.path.join(run_dir, GATE_BASELINE_FILENAME), "w") as fh:
        fh.write("{not json")
    logs: list = []
    _gate_segment(run_dir, {}, lambda *a, **k: None, logs.append)
    assert any("gate_error" in line for line in logs)
    # And a segment with no loop (nothing to judge) is silently skipped.
    empty = tmp_path / "empty"
    empty.mkdir()
    with open(os.path.join(str(empty), "events.jsonl"), "w") as fh:
        fh.write(json.dumps({"t": 1.0, "ev": "heartbeat"}) + "\n")
    _gate_segment(str(empty), {}, lambda *a, **k: None, logs.append)
    assert not os.path.exists(
        os.path.join(str(empty), GATE_BASELINE_FILENAME)
    )


_CHILD = """
import json, sys
from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer
over = json.loads(sys.argv[1])
Trainer(get_config("smoke16", **over)).run()
"""


def test_e2e_supervised_two_segments_auto_pin_and_gate(tmp_path):
    """Acceptance: a supervised 2-segment run (restart_every_steps=1,
    total 2) auto-pins its baseline after segment 1 (the planned-restart
    exit) and gates segment 2 against it at the done exit."""
    from featurenet_tpu.train.supervisor import (
        GATE_BASELINE_FILENAME,
        supervise,
    )

    hb = str(tmp_path / "hb")
    run_dir = str(tmp_path / "run")
    over = dict(
        total_steps=2,
        restart_every_steps=1,
        global_batch=8,
        data_workers=1,
        eval_batches=1,
        log_every=10**9,
        eval_every=10**9,
        checkpoint_every=10**9,
        checkpoint_dir=str(tmp_path / "ckpt"),
        run_dir=run_dir,
        heartbeat_file=hb,
    )
    env_patch = {
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    old = {k: os.environ.get(k) for k in env_patch}
    os.environ.update(env_patch)
    records: list = []
    try:
        res = supervise(
            [sys.executable, "-c", _CHILD, json.dumps(over)],
            heartbeat_file=hb,
            stall_timeout_s=120,
            grace_s=600,
            max_restarts=2,
            poll_s=0.2,
            backoff_base_s=0.05,
            log=lambda s: records.append(json.loads(s)),
            run_dir=run_dir,
        )
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert res.exit_code == 0
    assert res.planned == 1 and res.restarts == 0
    # Segment 1 pinned the baseline...
    assert os.path.exists(os.path.join(run_dir, GATE_BASELINE_FILENAME))
    with open(os.path.join(run_dir, "events.jsonl")) as fh:
        events = [json.loads(line) for line in fh]
    phases = [e.get("phase") for e in events if e["ev"] == "supervisor"]
    assert "auto_pin" in phases
    # ...and segment 2 was judged against it (either verdict is a judged
    # segment; regression on a noisy CI box is an alert, not a failure).
    assert "gate" in phases or "gate_regression" in phases
    assert phases.index("auto_pin") < len(phases) - 1
    # The pin precedes the planned_restart record (first clean segment).
    assert phases.index("auto_pin") < phases.index("planned_restart")
    # The run itself completed its budget.
    assert any(e["ev"] == "run_end" and e["step"] == 2 for e in events)
    # The report folds it all: supervisor section + gate counters.
    rep = build_report(sorted(events, key=lambda e: e["t"]))
    assert rep["supervisor"]["planned_restarts"] == 1
    assert "gate_regressions" in rep["supervisor"]


# --- bench gate-summary wiring -----------------------------------------------

def test_bench_window_gate_fields_and_keys(tmp_path):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    from featurenet_tpu.obs import gates

    run_dir = str(tmp_path)
    rows = [
        {"t": 1.0, "ev": "window_summary", "metric": "data_wait_ms",
         "n": 8, "p50": 2.0, "p95": 4.0, "p99": 5.0, "mean": 2.5,
         "max": 5.0, "seq": 1},
        {"t": 2.0, "ev": "window_summary", "metric": "data_wait_ms",
         "n": 16, "p50": 3.0, "p95": 6.0, "p99": 7.0, "mean": 3.5,
         "max": 7.0, "seq": 2},
        {"t": 2.0, "ev": "window_summary", "metric": "queue_depth",
         "n": 16, "p50": 2.0, "p95": 2.0, "p99": 2.0, "mean": 2.0,
         "max": 2.0, "seq": 2},
    ]
    with open(os.path.join(run_dir, "events.jsonl"), "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    fields = bench._window_gate_fields(run_dir)
    assert fields == {
        "window_data_wait_p50_ms": 3.0,  # the LAST window wins
        "window_data_wait_p99_ms": 7.0,
        "window_queue_depth_p50": 2.0,
    }
    # Missing dir degrades to no fields, never an exception.
    assert bench._window_gate_fields(str(tmp_path / "nope")) == {}

    # The new keys are pinnable and directed: window latencies regress
    # upward, queue depth regresses DOWNWARD (starvation reads low), and
    # the spread keys are pinned too (satellite 6).
    summary = {"value": 16000.0, "spread_pct": 3.8,
               "serving_spread_pct": 1.9, **fields}
    vals = gates.bench_gate_values(summary)
    assert {"spread_pct", "serving_spread_pct", "window_data_wait_p50_ms",
            "window_queue_depth_p50"} <= set(vals)
    pin = gates.make_baseline(vals, tolerance=0.15)
    assert pin["gates"]["window_queue_depth_p50"]["direction"] == "min"
    assert pin["gates"]["window_data_wait_p99_ms"]["direction"] == "max"
    assert pin["gates"]["spread_pct"]["direction"] == "max"
    # A starved next round (depth collapses to 0) fails the pin.
    starved = dict(summary, window_queue_depth_p50=0.0)
    res = gates.evaluate_gates(gates.bench_gate_values(starved), pin)
    assert not res["ok"] and "window_queue_depth_p50" in res["failed"]
