"""Run-config persistence: the checkpoint knows its own identity.

Round-1 footgun class under test: a checkpoint restored under guessed flags
(wrong arch/resolution/task) either fails cryptically or — worse — restores
structurally and predicts nonsense. The sidecar `config.json` written by
`CheckpointManager.save` plus `check_identity` closes every path.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

from featurenet_tpu.config import (
    PRESETS,
    check_identity,
    config_from_dict,
    config_to_dict,
    get_config,
)


@pytest.mark.parametrize("name", sorted(PRESETS))
def test_config_json_roundtrip(name):
    cfg = get_config(name)
    d = json.loads(json.dumps(config_to_dict(cfg)))  # through real JSON
    assert config_from_dict(d) == cfg


def test_config_from_dict_drops_unknown_and_defaults_missing():
    d = config_to_dict(get_config("smoke16"))
    d["from_the_future"] = 123
    d["arch"]["also_new"] = True
    del d["eval_batches"]
    cfg = config_from_dict(d)
    assert cfg.name == "smoke16"
    # A missing field takes the dataclass default (forward compatibility).
    from featurenet_tpu.config import Config

    assert cfg.eval_batches == Config().eval_batches


def test_check_identity_passes_on_equal_and_raises_on_mismatch():
    a = get_config("smoke16")
    check_identity(a, get_config("smoke16"))  # no raise
    with pytest.raises(ValueError, match="resolution"):
        check_identity(a, get_config("smoke16", resolution=32))
    with pytest.raises(ValueError, match="arch"):
        check_identity(
            a,
            dataclasses.replace(
                a, arch=dataclasses.replace(a.arch, stem_s2d=False)
            ),
        )


def _train_briefly(ckpt_dir, **over):
    from featurenet_tpu.train.loop import Trainer

    cfg = get_config(
        "smoke16",
        total_steps=2,
        checkpoint_dir=str(ckpt_dir),
        checkpoint_every=2,
        eval_every=10**9,
        log_every=10**9,
        data_workers=1,
        **over,
    )
    t = Trainer(cfg)
    t.run()
    return cfg


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    """One 2-step smoke16 run with a checkpoint+sidecar, shared by every
    read-only consumer in this module (training it per-test dominated the
    suite's wall time)."""
    d = tmp_path_factory.mktemp("persist") / "ckpt"
    cfg = _train_briefly(d)
    return cfg, str(d)


def test_sidecar_written_and_predictor_self_configures(trained_ckpt):
    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.train.checkpoint import load_run_config

    cfg, ckpt = trained_ckpt
    loaded = load_run_config(ckpt)
    assert loaded == cfg

    # No flags, no guessing: the Predictor reads the sidecar.
    p = Predictor.from_checkpoint(ckpt, batch=2)
    assert p.cfg.resolution == 16
    assert p.cfg.name == "smoke16"
    grids = np.zeros((1, 16, 16, 16), np.float32)
    labels, probs = p.predict_voxels(grids)
    assert labels.shape == (1,)
    assert probs.shape[1] == p.cfg.arch.num_classes


def test_predictor_rejects_contradicting_explicit_config(trained_ckpt):
    from featurenet_tpu.infer import Predictor

    _, ckpt = trained_ckpt
    with pytest.raises(ValueError, match="contradict"):
        Predictor.from_checkpoint(ckpt, config=get_config("pod64"), batch=2)


def test_cli_eval_uses_sidecar_and_rejects_mismatched_flags(
    trained_ckpt, capsys
):
    from featurenet_tpu import cli

    _, ckpt = trained_ckpt
    # No --config at all: the sidecar supplies smoke16 (default used to be
    # pod64 — this is the "self-configuring" acceptance case).
    cli.main(["eval", "--checkpoint-dir", ckpt, "--data-workers", "1"])
    out = capsys.readouterr().out
    assert '"eval"' in out
    assert '"smoke16"' in out
    # An explicitly contradicting identity flag is a hard error.
    with pytest.raises(SystemExit, match="contradict"):
        cli.main([
            "eval", "--checkpoint-dir", ckpt, "--resolution", "32",
        ])
    with pytest.raises(SystemExit, match="contradict"):
        cli.main([
            "eval", "--checkpoint-dir", ckpt, "--config", "pod64",
        ])


def test_cli_train_resume_reads_sidecar(tmp_path, capsys):
    """Resume without flags continues the persisted config, not pod64.
    (Own checkpoint dir — resuming advances the step and rewrites the
    sidecar, which would corrupt the shared fixture.)"""
    from featurenet_tpu import cli

    _train_briefly(tmp_path / "ckpt")
    capsys.readouterr()  # drain the setup run's own log lines
    ckpt = str(tmp_path / "ckpt")
    cli.main([
        "train", "--checkpoint-dir", ckpt, "--total-steps", "3",
        "--data-workers", "1",
    ])
    out = capsys.readouterr().out
    cfg_line = json.loads(out.splitlines()[0])
    assert cfg_line["config"]["name"] == "smoke16"
    assert cfg_line["config"]["total_steps"] == 3  # policy override applied


def test_sidecar_scrubs_ephemeral_fields(tmp_path):
    """No training needed: _cfg_from_checkpoint is pure config surgery."""
    from featurenet_tpu.cli import _cfg_from_checkpoint

    cfg = get_config(
        "smoke16",
        heartbeat_file=str(tmp_path / "hb"),
        tb_dir=str(tmp_path / "tb"),
        profile_dir=str(tmp_path / "prof"),
    )

    class _Args:
        pass

    got = _cfg_from_checkpoint(cfg, _Args())
    assert got.heartbeat_file is None
    assert got.tb_dir is None
    assert got.profile_dir is None


def test_conv_backend_is_not_identity(trained_ckpt):
    """conv_backend selects a lowering, not a model: A/B-ing backends on
    one trained checkpoint must be allowed (every backend shares the same
    param tree)."""
    cfg, _ = trained_ckpt
    check_identity(
        cfg,
        dataclasses.replace(
            cfg, arch=dataclasses.replace(cfg.arch, conv_backend="hybrid_dw")
        ),
    )  # no raise


def test_cli_conv_backend_override_reaches_config(trained_ckpt):
    """--conv-backend on a sidecar checkpoint must flow into the returned
    config (it passed the identity check, so dropping it silently would
    make backend A/B runs measure the same lowering twice)."""
    from featurenet_tpu.cli import _cfg_from_checkpoint

    cfg, _ = trained_ckpt

    class _Args:
        conv_backend = "hybrid_dw"

    got = _cfg_from_checkpoint(cfg, _Args())
    assert got.arch.conv_backend == "hybrid_dw"


def test_restart_every_steps_validation_and_sidecar_scrub(tmp_path):
    """restart_every_steps: rejected when non-positive or checkpoint-less;
    scrubbed on sidecar resume (only the supervisor re-passes the flag)."""
    from featurenet_tpu.cli import _cfg_from_checkpoint

    with pytest.raises(ValueError, match="positive"):
        get_config("smoke16", restart_every_steps=-5,
                   checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="checkpoint_dir"):
        get_config("smoke16", restart_every_steps=100)

    cfg = get_config("smoke16", restart_every_steps=100,
                     checkpoint_dir=str(tmp_path))

    class _Args:
        pass

    assert _cfg_from_checkpoint(cfg, _Args()).restart_every_steps is None


def test_check_identity_detail_reports_identity_view_not_raw_repr():
    """The mismatch message must diff the *identity view*: conv_backend is
    deliberately non-identity (a lowering choice), so a repr that shows the
    raw differing conv_backend would point the user at a non-mismatch."""
    a = get_config("smoke16")
    saved = dataclasses.replace(
        a, arch=dataclasses.replace(a.arch, conv_backend="pallas")
    )
    requested = dataclasses.replace(
        a, arch=dataclasses.replace(a.arch, stem_s2d=False)
    )
    with pytest.raises(ValueError) as ei:
        check_identity(saved, requested)
    # Both sides render through the neutralized view (conv_backend='xla');
    # the real differing subfield (stem_s2d) is visible.
    assert "pallas" not in str(ei.value)
    assert "stem_s2d" in str(ei.value)
