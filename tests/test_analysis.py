"""Static-analysis contract linter (featurenet_tpu.analysis).

Two layers of coverage:

1. **Fixture snippets** per rule family: each violation class is caught
   with the offending file:line, each suppression is honored, and a clean
   snippet passes — the linter's own behavioral contract.
2. **Self-clean tier-1 gate**: the installed package lints to zero
   findings. This is the test that makes the contracts *enforced*:
   deleting a ``maybe_fail`` call site surfaces as ``dead_site``, removing
   a required field from an emit surfaces as ``missing_fields``, a new
   Config field with no flag and no exemption surfaces as
   ``unreachable_field`` — all as a red test, not a silent drift.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from featurenet_tpu.analysis import (
    format_findings,
    package_root,
    run_lint,
)


def _write(root, relpath: str, source: str) -> str:
    path = os.path.join(str(root), relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(textwrap.dedent(source))
    return path


def _checks(findings, rule=None):
    return [f.check for f in findings if rule is None or f.rule == rule]


# --- rule: telemetry ---------------------------------------------------------

def _clean_telemetry_source() -> str:
    """One emit per known kind, each carrying its required fields as
    literal keys — the telemetry rule's zero-finding fixture."""
    from featurenet_tpu.obs.report import (
        KNOWN_EVENT_KINDS,
        REQUIRED_EVENT_FIELDS,
    )

    lines = ["from featurenet_tpu import obs", ""]
    for kind in sorted(KNOWN_EVENT_KINDS):
        kw = ", ".join(
            f"{f}=1" for f in REQUIRED_EVENT_FIELDS.get(kind, ())
        )
        lines.append(
            f"obs.emit({kind!r}{', ' + kw if kw else ''})"
        )
    return "\n".join(lines) + "\n"


def test_telemetry_clean_fixture_passes(tmp_path):
    _write(tmp_path, "sites.py", _clean_telemetry_source())
    assert run_lint(str(tmp_path), rules=["telemetry"]) == []


def test_telemetry_unknown_kind_caught_with_location(tmp_path):
    path = _write(tmp_path, "sites.py", _clean_telemetry_source()
                  + 'obs.emit("totally_new_kind", x=1)\n')
    findings = run_lint(str(tmp_path), rules=["telemetry"])
    assert [f.check for f in findings] == ["unknown_kind"]
    assert findings[0].path == path
    assert findings[0].line == len(open(path).read().splitlines())
    assert "totally_new_kind" in findings[0].msg


def test_telemetry_missing_required_field_and_splat_not_enough(tmp_path):
    _write(tmp_path, "sites.py", _clean_telemetry_source()
           + 'obs.emit("gauge", name="q")\n'            # missing value
           + 'fields = {"name": "a", "dur_s": 1}\n'
           + 'obs.emit("span", **fields)\n')            # splat hides keys
    findings = run_lint(str(tmp_path), rules=["telemetry"])
    assert _checks(findings) == ["missing_fields", "missing_fields"]
    assert "['value']" in findings[0].msg
    assert "splat" in findings[1].msg


def test_telemetry_warn_positionals_and_warnings_module_exempt(tmp_path):
    _write(tmp_path, "sites.py", _clean_telemetry_source()
           + 'import warnings\n'
           + 'obs.warn("mesh_warning", "degraded", extra=1)\n'  # name, msg
           + 'warnings.warn("stdlib warning, different contract")\n'
           + 'obs.warn("half_warning")\n')                      # msg missing
    findings = run_lint(str(tmp_path), rules=["telemetry"])
    assert _checks(findings) == ["missing_fields"]
    assert "warn(...)" in findings[0].msg


def test_telemetry_dead_schema_when_kind_has_no_site(tmp_path):
    # A tree that emits only heartbeats: every other kind is dead schema.
    _write(tmp_path, "sites.py", """\
        from featurenet_tpu import obs
        obs.emit("heartbeat", age_s=1.0)
    """)
    findings = run_lint(str(tmp_path), rules=["telemetry"])
    assert set(_checks(findings)) == {"dead_schema"}
    assert any("'preempt'" in f.msg for f in findings)


# --- rule: fault-sites -------------------------------------------------------

def _clean_fault_source() -> str:
    from featurenet_tpu.faults import SITES

    lines = ["from featurenet_tpu import faults", ""]
    for site, counter in sorted(SITES.items()):
        lines.append(f"faults.maybe_fail({site!r}, {counter}=1)")
    return "\n".join(lines) + "\n"


def test_fault_sites_clean_fixture_passes(tmp_path):
    _write(tmp_path, "sites.py", _clean_fault_source())
    assert run_lint(str(tmp_path), rules=["fault-sites"]) == []


def test_fault_sites_unknown_site_caught(tmp_path):
    path = _write(tmp_path, "sites.py", _clean_fault_source()
                  + 'faults.maybe_fail("tyop_site", step=1)\n')
    findings = run_lint(str(tmp_path), rules=["fault-sites"])
    assert [f.check for f in findings] == ["unknown_site"]
    assert findings[0].path == path and findings[0].line > 0


def test_fault_sites_wrong_and_missing_counter(tmp_path):
    _write(tmp_path, "sites.py", _clean_fault_source()
           + 'faults.maybe_fail("sigterm", save=3)\n')
    findings = run_lint(str(tmp_path), rules=["fault-sites"])
    assert set(_checks(findings)) == {"missing_counter", "wrong_counter"}


def test_fault_sites_dead_site_when_call_site_deleted(tmp_path):
    """The acceptance scenario: delete one maybe_fail call site and the
    lint (and therefore the tier-1 self-clean test) goes red."""
    source = _clean_fault_source().replace(
        "faults.maybe_fail('sigterm', step=1)\n", ""
    )
    assert "sigterm" not in source
    _write(tmp_path, "sites.py", source)
    findings = run_lint(str(tmp_path), rules=["fault-sites"])
    assert [f.check for f in findings] == ["dead_site"]
    assert "'sigterm'" in findings[0].msg


# --- rule: host-sync ---------------------------------------------------------

_HOT_SNIPPET = """\
    import jax
    import numpy as np

    def hot(metrics, stats):
        a = metrics.item()
        b = jax.device_get(stats)
        c = jax.block_until_ready(metrics)
        d = np.asarray(metrics)
        return a, b, c, d
"""


def test_host_sync_flags_each_construct_in_hot_modules(tmp_path):
    path = _write(tmp_path, "train/loop.py", _HOT_SNIPPET)
    findings = run_lint(str(tmp_path), rules=["host-sync"])
    assert [f.check for f in findings] == ["host_sync"] * 4
    assert [f.line for f in findings] == [5, 6, 7, 8]
    assert all(f.path == path for f in findings)
    texts = " | ".join(f.msg for f in findings)
    for construct in (".item()", "jax.device_get", "block_until_ready",
                      "np.asarray"):
        assert construct in texts


def test_host_sync_only_designated_modules(tmp_path):
    _write(tmp_path, "data/loader.py", _HOT_SNIPPET)
    assert run_lint(str(tmp_path), rules=["host-sync"]) == []


def test_host_sync_suppression_same_line_and_line_above(tmp_path):
    _write(tmp_path, "infer.py", """\
        import numpy as np

        def serve(dev):
            y = np.asarray(dev)  # lint: allow-host-sync(readback is latency)
            # lint: allow-host-sync(second deliberate sync)
            z = np.asarray(dev)
            return y, z
    """)
    assert run_lint(str(tmp_path), rules=["host-sync"]) == []


def test_host_sync_suppression_needs_reason(tmp_path):
    # An empty-parens suppression doesn't parse as a suppression at all.
    _write(tmp_path, "infer.py", """\
        import numpy as np

        def serve(dev):
            return np.asarray(dev)  # lint: allow-host-sync()
    """)
    findings = run_lint(str(tmp_path), rules=["host-sync"])
    assert [f.check for f in findings] == ["host_sync"]


# --- rule: hygiene -----------------------------------------------------------

def test_hygiene_wall_clock_direct_and_via_variable(tmp_path):
    _write(tmp_path, "timers.py", """\
        import time

        def ages(t0):
            direct = time.time() - t0
            now = time.time()
            indirect = now - t0
            fine = time.perf_counter() - t0
            stamp = time.time()  # no arithmetic: just a stamp
            return direct, indirect, fine, stamp
    """)
    findings = run_lint(str(tmp_path), rules=["hygiene"])
    assert [f.check for f in findings] == ["wall_clock_arith"] * 2
    assert [f.line for f in findings] == [4, 6]


def test_telemetry_foreign_warn_apis_exempt(tmp_path):
    """Only obs.warn / bare warn are under the telemetry contract — a
    stdlib logger's .warn must not be forced into the warning schema."""
    _write(tmp_path, "sites.py", _clean_telemetry_source()
           + 'import logging\n'
           + 'log = logging.getLogger(__name__)\n'
           + 'log.warn("retrying")\n')
    assert run_lint(str(tmp_path), rules=["telemetry"]) == []


def test_hygiene_wall_clock_tracking_is_position_aware(tmp_path):
    """A name rebound to perf_counter after an earlier epoch stamp must
    not taint later subtraction — and the reverse order must."""
    _write(tmp_path, "timers.py", """\
        import time

        def fine(t0, manifest):
            now = time.time()
            manifest["stamp"] = now
            now = time.perf_counter()
            return now - t0

        def bad(t0):
            now = time.perf_counter()
            now = time.time()
            return now - t0
    """)
    findings = run_lint(str(tmp_path), rules=["hygiene"])
    assert [(f.check, f.line) for f in findings] == [
        ("wall_clock_arith", 12),
    ]


def test_hygiene_wall_clock_suppression(tmp_path):
    _write(tmp_path, "timers.py", """\
        import os
        import time

        def mtime_age(path):
            # lint: allow-wall-clock(file mtimes are epoch-based)
            return time.time() - os.path.getmtime(path)
    """)
    assert run_lint(str(tmp_path), rules=["hygiene"]) == []


def test_hygiene_bare_except_and_thread_daemon(tmp_path):
    _write(tmp_path, "workers.py", """\
        import threading

        def spawn(fn):
            try:
                t = threading.Thread(target=fn)
            except:
                t = None
            good = threading.Thread(target=fn, daemon=True)
            return t, good
    """)
    findings = run_lint(str(tmp_path), rules=["hygiene"])
    assert sorted(_checks(findings)) == ["bare_except", "thread_daemon"]


def test_hygiene_fp32_cast_in_hot_step(tmp_path):
    """fp32 casts inside the compiled train step (train/steps.py) must
    be deliberate: unannotated .astype(jnp.float32) / jnp.float32(...)
    are findings there, an allow-precision annotation clears them, and
    the same casts in any other module are out of scope."""
    _write(tmp_path, "train/steps.py", """\
        import jax.numpy as jnp

        def step(x, y):
            a = x.astype(jnp.float32)
            b = jnp.float32(y)
            # lint: allow-precision(loss-land accumulates fp32)
            c = y.astype(jnp.float32)
            d = x.astype(jnp.bfloat16)  # narrowing is not the contract
            return a, b, c, d
    """)
    _write(tmp_path, "other.py", """\
        import jax.numpy as jnp

        def fine(x):
            return x.astype(jnp.float32)
    """)
    findings = run_lint(str(tmp_path), rules=["hygiene"])
    assert [(f.check, f.line) for f in findings] == [
        ("fp32_cast_in_hot_step", 4),
        ("fp32_cast_in_hot_step", 5),
    ]
    assert all("train/steps.py" in f.path for f in findings)


def test_hygiene_fp32_cast_covers_serving_hot_paths(tmp_path):
    """Satellite (ISSUE 12): the precision-cast contract extends to the
    serving hot paths (infer.py, serve/batcher.py, serve/service.py) and
    to numpy-side casts — an unannotated np.float32 cast on the request
    edge is a finding, an allow-precision annotation clears it, and
    modules outside the contract stay out of scope."""
    _write(tmp_path, "serve/service.py", """\
        import numpy as np

        def submit(grid):
            a = grid.astype(np.float32)
            # lint: allow-precision(wire contract: serve input edge is fp32)
            b = grid.astype(np.float32)
            return a, b
    """)
    _write(tmp_path, "infer.py", """\
        import numpy as np

        def forward(x):
            return np.float32(x)
    """)
    _write(tmp_path, "ood.py", """\
        import numpy as np

        def fine(x):
            return x.astype(np.float32)  # not a hot-path module
    """)
    findings = run_lint(str(tmp_path), rules=["hygiene"])
    got = sorted((f.check, f.path.split("/")[-1], f.line) for f in findings)
    assert got == [
        ("fp32_cast_in_hot_step", "infer.py", 4),
        ("fp32_cast_in_hot_step", "service.py", 4),
    ]


def test_config_cli_rule_covers_precision_and_backend_pairs():
    """Satellites (ISSUE 10/12): the config-cli rule's parsed surfaces
    both see the precision flag/choices pairs on the REAL package — the
    CLI choices lists and Config.validate()'s accepted sets agree for
    train_precision, serve_precision, AND the aliased conv_backend
    (validated through the nested ``self.arch.conv_backend`` guard), so
    a drift on any side becomes a choices_drift finding."""
    from featurenet_tpu.analysis.lint import load_tree, package_root
    from featurenet_tpu.analysis.rules import _cli_flags, _validate_sets

    tree = load_tree(package_root())
    flags = {d: choices for _, d, _, choices
             in _cli_flags(tree.module("cli.py"))}
    accepted = _validate_sets(tree.module("config.py"))
    assert set(flags["train_precision"]) == {
        "fp32", "bf16_master", "fp16_scaled"
    }
    assert accepted["train_precision"][0] == set(flags["train_precision"])
    assert set(flags["serve_precision"]) == {"fp32", "bf16", "int8"}
    assert accepted["serve_precision"][0] == set(flags["serve_precision"])
    # The aliased nested pair: --conv-backend narrows arch.conv_backend.
    assert set(flags["conv_backend"]) == {
        "xla", "pallas", "hybrid_dw", "fused33"
    }
    assert accepted["conv_backend"][0] == set(flags["conv_backend"])


def test_config_cli_nested_choices_drift_fires(tmp_path):
    """A sub-config field restricted via ``self.arch.X not in (...)``
    whose aliased flag narrows to a DIFFERENT set is a choices_drift —
    the nested guard is under the same contract as the flat ones."""
    _write(tmp_path, "config.py", """\
        class Config:
            a: int = 1
            def validate(self):
                if self.arch.conv_backend not in ("xla", "fused33"):
                    raise ValueError("bad")
    """)
    _write(tmp_path, "cli.py", """\
        FLAG_ALIASES = {}
        def _add_override_flags(p):
            p.add_argument("--conv-backend", choices=["xla"])
        def _overrides(args):
            keys = []
    """)
    findings = run_lint(str(tmp_path), rules=["config-cli"])
    drift = [f for f in findings if f.check == "choices_drift"]
    assert len(drift) == 1 and "--conv-backend" in drift[0].msg


# --- rule: config-cli --------------------------------------------------------

def _fixture_config(extra_fields: str = "") -> str:
    """A Config class carrying every CLI_EXEMPT_FIELDS entry (so the
    staleness check stays quiet) plus the test's own fields."""
    from featurenet_tpu.analysis.rules import CLI_EXEMPT_FIELDS

    body = "\n".join(f"    {f}: int = 0" for f in sorted(CLI_EXEMPT_FIELDS))
    return (
        "class Config:\n"
        "    resolution: int = 64\n" + body + "\n" + extra_fields
    )


_FIXTURE_CLI = """\
    def _add_override_flags(p):
        p.add_argument("--resolution", type=int)
    {extra_flag}

    def _overrides(args):
        keys = [{keys}]
        return keys
"""


def _write_config_cli(tmp_path, extra_fields="", extra_flag="",
                      keys="'resolution'"):
    _write(tmp_path, "config.py", _fixture_config(extra_fields))
    _write(tmp_path, "cli.py",
           _FIXTURE_CLI.format(extra_flag=extra_flag, keys=keys))


def test_config_cli_clean_fixture_passes(tmp_path):
    _write_config_cli(tmp_path)
    assert run_lint(str(tmp_path), rules=["config-cli"]) == []


def test_config_cli_unmapped_flag(tmp_path):
    _write_config_cli(
        tmp_path, extra_flag='    p.add_argument("--warp-speed", type=int)'
    )
    findings = run_lint(str(tmp_path), rules=["config-cli"])
    assert [f.check for f in findings] == ["unmapped_flag"]
    assert "--warp-speed" in findings[0].msg and findings[0].line > 0


def test_config_cli_stale_override_key_and_unreachable_field(tmp_path):
    _write_config_cli(
        tmp_path,
        extra_fields="    mystery_field: int = 1\n",
        keys="'resolution', 'ghost_key'",
    )
    findings = run_lint(str(tmp_path), rules=["config-cli"])
    assert sorted(_checks(findings)) == [
        "stale_override_key", "unreachable_field",
    ]
    msgs = " | ".join(f.msg for f in findings)
    assert "ghost_key" in msgs and "mystery_field" in msgs


def test_config_cli_stale_exemption_when_field_reachable(tmp_path):
    # log_every is whitelisted as CLI-unreachable; growing it a flag must
    # flag the now-stale exemption.
    _write_config_cli(
        tmp_path, extra_flag='    p.add_argument("--log-every", type=int)'
    )
    findings = run_lint(str(tmp_path), rules=["config-cli"])
    assert [f.check for f in findings] == ["stale_exemption"]
    assert "log_every" in findings[0].msg


def _fixture_config_with_validate(accepted: str) -> str:
    """A Config whose validate() restricts ``flavor`` to a literal set —
    the choices-vs-validate drift fixtures."""
    return (
        _fixture_config("    flavor: str = 'a'\n")
        + "\n"
        + "    def validate(self):\n"
        + f"        if self.flavor not in ({accepted}):\n"
        + "            raise ValueError(self.flavor)\n"
        + "        return self\n"
    )


def test_config_cli_choices_match_validate_passes(tmp_path):
    _write(tmp_path, "config.py",
           _fixture_config_with_validate("'a', 'b'"))
    _write(tmp_path, "cli.py", _FIXTURE_CLI.format(
        extra_flag="    p.add_argument(\"--flavor\", "
                   "choices=['a', 'b'])",
        keys="'resolution', 'flavor'",
    ))
    assert run_lint(str(tmp_path), rules=["config-cli"]) == []


def test_config_cli_choices_drift_caught(tmp_path):
    """The CLI offers a value validate() refuses (and misses one it
    accepts): both directions are one drifted-set finding."""
    _write(tmp_path, "config.py",
           _fixture_config_with_validate("'a', 'b'"))
    _write(tmp_path, "cli.py", _FIXTURE_CLI.format(
        extra_flag="    p.add_argument(\"--flavor\", "
                   "choices=['a', 'zz'])",
        keys="'resolution', 'flavor'",
    ))
    findings = run_lint(str(tmp_path), rules=["config-cli"])
    assert [f.check for f in findings] == ["choices_drift"]
    assert "'zz'" in findings[0].msg and "'b'" in findings[0].msg
    assert findings[0].line > 0


def test_config_cli_missing_choices_caught(tmp_path):
    """A validate()-restricted field whose flag doesn't narrow at all:
    the invalid value parses and only explodes at validate time."""
    _write(tmp_path, "config.py",
           _fixture_config_with_validate("'a', 'b'"))
    _write(tmp_path, "cli.py", _FIXTURE_CLI.format(
        extra_flag='    p.add_argument("--flavor")',
        keys="'resolution', 'flavor'",
    ))
    findings = run_lint(str(tmp_path), rules=["config-cli"])
    assert [f.check for f in findings] == ["missing_choices"]
    assert "flavor" in findings[0].msg


# --- rule: spans (span-name drift) -------------------------------------------

def _clean_span_source() -> str:
    """One call site per loop category (plus a known non-loop span) — the
    spans rule's zero-finding fixture."""
    from featurenet_tpu.obs.report import LOOP_CATEGORIES

    lines = ["from featurenet_tpu import obs", ""]
    for name in (*LOOP_CATEGORIES, "infer_batch"):
        lines.append(f"with obs.span({name!r}):")
        lines.append("    pass")
    return "\n".join(lines) + "\n"


def test_spans_clean_fixture_passes(tmp_path):
    _write(tmp_path, "sites.py", _clean_span_source())
    assert run_lint(str(tmp_path), rules=["spans"]) == []


def test_spans_unknown_span_caught_with_location(tmp_path):
    path = _write(tmp_path, "sites.py", _clean_span_source()
                  + 'with obs.span("data_wiat"):\n    pass\n')
    findings = run_lint(str(tmp_path), rules=["spans"])
    assert [f.check for f in findings] == ["unknown_span"]
    assert findings[0].path == path and findings[0].line > 0
    assert "data_wiat" in findings[0].msg


def test_spans_dead_category_when_call_site_deleted(tmp_path):
    """The drift scenario: delete a loop category's last span site and
    the breakdown row would silently read zero — the lint goes red."""
    source = _clean_span_source().replace(
        "with obs.span('data_wait'):\n    pass\n", ""
    )
    assert "data_wait" not in source
    _write(tmp_path, "sites.py", source)
    findings = run_lint(str(tmp_path), rules=["spans"])
    assert [f.check for f in findings] == ["dead_category"]
    assert "'data_wait'" in findings[0].msg and findings[0].line == 0


# --- rule: alerts (doc examples vs known_metrics) ----------------------------

def test_alert_docs_clean_and_prose_exempt(tmp_path):
    """Valid rule examples pass; prose comparisons with spaced operators
    ('groups > 0') are not rule examples and never match."""
    _write(tmp_path, "docs.py", '''\
        """Set --alert-rules to e.g. data_wait_fraction>0.6:critical or
        serving_p99_ms>20. Unrelated prose: augment_groups > 0 keeps
        rotation on."""
        HELP = "queue_depth<1:info fires when the pipeline starves"
    ''')
    assert run_lint(str(tmp_path), rules=["alerts"]) == []


def test_alert_docs_unknown_metric_caught(tmp_path):
    path = _write(tmp_path, "docs.py",
                  '"""e.g. data_wait_fracton>0.6 starves."""\n')
    findings = run_lint(str(tmp_path), rules=["alerts"])
    assert [f.check for f in findings] == ["unknown_doc_metric"]
    assert findings[0].path == path
    assert "data_wait_fracton" in findings[0].msg


def test_alert_docs_unknown_severity_and_suppression(tmp_path):
    _write(tmp_path, "docs.py",
           'A = "serving_p99_ms>20:panic"\n'
           'B = "step_p99_ratio>4:urgent"'
           '  # lint: allow-alert-doc(deliberate bad example)\n')
    findings = run_lint(str(tmp_path), rules=["alerts"])
    assert [f.check for f in findings] == ["unknown_doc_severity"]
    assert "panic" in findings[0].msg


def test_spans_non_literal_and_foreign_span_apis_exempt(tmp_path):
    """A generic forwarder (non-literal name) and a foreign .span API are
    not under the contract."""
    _write(tmp_path, "sites.py", _clean_span_source() + (
        "def forward(name):\n"
        "    with obs.span(name):\n"
        "        pass\n"
        "class Tracer:\n"
        "    def span(self, name):\n"
        "        return name\n"
        "tracer = Tracer()\n"
        "tracer.span('not_a_known_span')\n"
    ))
    assert run_lint(str(tmp_path), rules=["spans"]) == []


# --- output formats / CLI surface --------------------------------------------

def test_text_and_json_output_carry_file_and_line(tmp_path):
    _write(tmp_path, "train/loop.py", "x = 1\ny = x.item()\n")
    findings = run_lint(str(tmp_path), rules=["host-sync"])
    text = format_findings(findings)
    assert "train/loop.py:2" in text.replace(os.sep, "/")
    assert "finding(s)" in text
    as_json = format_findings(findings, as_json=True).splitlines()
    rows = [json.loads(line) for line in as_json]
    assert rows[0]["line"] == 2 and rows[0]["check"] == "host_sync"
    assert rows[-1] == {"lint": "fail", "findings": 1}
    clean = format_findings([], as_json=True)
    assert json.loads(clean) == {"lint": "ok", "findings": 0}


def test_cli_lint_exit_codes_and_json(tmp_path, capsys):
    from featurenet_tpu.cli import main

    _write(tmp_path, "train/steps.py", "import numpy as np\n"
                                       "z = np.asarray(object())\n")
    with pytest.raises(SystemExit) as exc:
        main(["lint", str(tmp_path), "--json", "--rule", "host-sync"])
    assert exc.value.code == 2
    rows = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert rows[0]["rule"] == "host-sync" and rows[0]["line"] == 2
    assert rows[-1]["findings"] == 1
    # Rule filter: the same tree is clean under an unrelated rule.
    main(["lint", str(tmp_path), "--rule", "hygiene"])
    assert "lint: ok" in capsys.readouterr().out
    # Unknown rule name: a hard error, not a silently-empty lint.
    with pytest.raises(SystemExit, match="unknown lint rule"):
        main(["lint", str(tmp_path), "--rule", "nope"])


def test_lint_subpath_of_package_keeps_contract_semantics(tmp_path,
                                                          monkeypatch):
    """Linting a path INSIDE the package must behave like the package-wide
    lint narrowed to that subtree: the hot-path rule still keys on the
    package-rooted relpath (no false negative on `cli lint train/loop.py`),
    and package-level findings (a dead fault site) still surface."""
    from featurenet_tpu.analysis import lint as lint_mod

    _write(tmp_path, "train/loop.py",
           "import numpy as np\nz = np.asarray(object())\n")
    _write(tmp_path, "data/loader.py", "x = 1\n")
    monkeypatch.setattr(lint_mod, "package_root", lambda: str(tmp_path))
    # Single-file target: relpath stays 'train/loop.py', so host-sync fires.
    findings = run_lint(str(tmp_path / "train" / "loop.py"),
                        rules=["host-sync"])
    assert [f.check for f in findings] == ["host_sync"]
    # Sibling subtree target: the loop.py finding is outside it — narrowed
    # away; package-level (line 0) findings survive the narrowing.
    assert run_lint(str(tmp_path / "data"), rules=["host-sync"]) == []
    dead = run_lint(str(tmp_path / "data"), rules=["fault-sites"])
    assert dead and all(f.check == "dead_site" and f.line == 0
                        for f in dead)


def test_lint_missing_or_empty_target_fails_loudly(tmp_path):
    """A typo'd CI path must error, not lint clean forever."""
    from featurenet_tpu.cli import main

    with pytest.raises(FileNotFoundError, match="does not exist"):
        run_lint(str(tmp_path / "nope"))
    (tmp_path / "empty").mkdir()
    with pytest.raises(FileNotFoundError, match="no .py files"):
        run_lint(str(tmp_path / "empty"))
    with pytest.raises(SystemExit, match="does not exist") as exc:
        main(["lint", str(tmp_path / "nope")])
    assert exc.value.code != 0


def test_rule_registry_populated_at_import():
    from featurenet_tpu.analysis import RULE_NAMES
    from featurenet_tpu.analysis.lint import RULES

    assert set(RULE_NAMES) == {
        "telemetry", "fault-sites", "host-sync", "hygiene", "config-cli",
        "spans", "raw-conn", "alerts", "concurrency", "suppressions",
    }
    assert set(RULES) == set(RULE_NAMES)


def test_lint_repo_checkout_root_reroots_to_package():
    """`cli lint .` from a checkout: the package lives UNDER the target —
    re-rooted to the package, so path-keyed rules stay armed and the
    tests tree's deliberate fixture violations don't read as findings."""
    repo_root = os.path.dirname(package_root())
    findings = run_lint(repo_root)
    assert findings == [], "\n" + format_findings(findings)


def test_lint_subpath_of_real_package_has_no_false_positives():
    """`cli lint featurenet_tpu/train` on the clean repo must exit clean —
    the cross-file existence checks (dead_schema/dead_site, config-cli)
    see the whole package, not the narrowed subtree."""
    sub = os.path.join(package_root(), "train")
    findings = run_lint(sub)
    assert findings == [], "\n" + format_findings(findings)


def test_bench_preamble_fails_round_on_contract_violation(monkeypatch,
                                                          capsys):
    """bench.py lints before measuring: a contract violation ends the
    round with a structured record (same self-policing shape as the gate
    check), never a number built on a broken invariant."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    from featurenet_tpu.analysis.lint import Finding

    bad = Finding("fault-sites", "dead_site", "faults.py", 0,
                  "declared site with no call site")
    monkeypatch.setattr("featurenet_tpu.analysis.run_lint",
                        lambda *a, **k: [bad])
    bench.main()
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["skipped"] is True
    assert row["reason"] == "contract_violation"
    assert row["bench_schema"] == 2
    assert row["lint"]["findings"] == 1
    assert "fault-sites/dead_site" in row["lint"]["first"]


# --- the tier-1 gate: the package itself is clean ----------------------------

def test_package_self_clean():
    """THE enforcement test: the installed package has zero findings.

    This is what turns the contracts into invariants — deleting one
    ``maybe_fail`` call site (``dead_site``), dropping an emit's required
    field (``missing_fields``), adding an unannotated hot-loop host sync
    (``host_sync``), or growing Config a field no flag reaches
    (``unreachable_field``) all land here as a red test with file:line.
    """
    findings = run_lint(package_root())
    assert findings == [], "\n" + format_findings(findings)


def test_package_self_clean_via_cli(capsys):
    from featurenet_tpu.cli import main

    main(["lint"])  # returns (exit 0) — raises SystemExit(2) on findings
    assert "lint: ok" in capsys.readouterr().out


# --- rule: raw-conn ----------------------------------------------------------

def test_raw_conn_outside_pool_caught(tmp_path):
    """Raw HTTPConnection construction outside fleet/pool.py is the
    connect-per-request regression sneaking back in — flagged with the
    pool as the named alternative."""
    path = _write(tmp_path, "client.py", """\
        import http.client
        conn = http.client.HTTPConnection("replica", 8000)
    """)
    findings = run_lint(str(tmp_path), rules=["raw-conn"])
    assert _checks(findings) == ["raw_connection"]
    assert findings[0].path == path and findings[0].line == 2
    assert "fleet/pool.py" in findings[0].msg
    assert "allow-raw-conn" in findings[0].msg


def test_raw_conn_pool_module_and_escape_exempt(tmp_path):
    """The pool module itself may construct connections (it IS the
    factory), and a deliberate one-shot carries the reasoned escape —
    on the line or a pure comment line above."""
    _write(tmp_path, "fleet/pool.py", """\
        import http.client
        conn = http.client.HTTPConnection("replica", 8000)
    """)
    _write(tmp_path, "stream.py", """\
        import http.client
        # lint: allow-raw-conn(single-socket stream client)
        conn = http.client.HTTPConnection("replica", 8000)
        c2 = http.client.HTTPSConnection("replica", 443)  # lint: allow-raw-conn(tls probe)
    """)
    assert run_lint(str(tmp_path), rules=["raw-conn"]) == []


def test_raw_conn_bare_name_and_https_caught(tmp_path):
    _write(tmp_path, "client.py", """\
        from http.client import HTTPConnection, HTTPSConnection
        a = HTTPConnection("h", 80)
        b = HTTPSConnection("h", 443)
    """)
    findings = run_lint(str(tmp_path), rules=["raw-conn"])
    assert _checks(findings) == ["raw_connection", "raw_connection"]


# --- rule: concurrency -------------------------------------------------------

_UNLOCKED_FIXTURE = """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            self._count += 1{suffix}

        def bump(self):
            with self._lock:
                self._count += 1

        def stop(self):
            self._t.join()
"""


def test_concurrency_unlocked_write_caught_with_location(tmp_path):
    path = _write(tmp_path, "w.py",
                  _UNLOCKED_FIXTURE.format(suffix=""))
    findings = run_lint(str(tmp_path), rules=["concurrency"])
    assert _checks(findings) == ["unlocked_write"]
    assert findings[0].path == path and findings[0].line == 14
    assert "Worker._count" in findings[0].msg
    assert "_lock" in findings[0].msg


def test_concurrency_unlocked_write_locked_and_suppressed_pass(tmp_path):
    # Locked variant: the thread-path write holds the lock.
    _write(tmp_path, "w.py", """\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._t = None

            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                with self._lock:
                    self._count += 1

            def bump(self):
                with self._lock:
                    self._count += 1

            def stop(self):
                self._t.join()
    """)
    assert run_lint(str(tmp_path), rules=["concurrency"]) == []
    # Suppressed variant: the escape is honored AND counts as consumed
    # for the suppression audit.
    _write(tmp_path, "w.py", _UNLOCKED_FIXTURE.format(
        suffix="  # lint: allow-unlocked(fixture says single-writer)"))
    assert run_lint(str(tmp_path),
                    rules=["concurrency", "suppressions"]) == []


def test_concurrency_single_writer_and_lockless_class_exempt(tmp_path):
    # One writer method only -> out of contract even on a thread path;
    # a class with no lock at all guards nothing.
    _write(tmp_path, "w.py", """\
        import threading

        class OneWriter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def _run(self):
                self._n += 1

        class NoLock:
            def a(self):
                self._x = 1

            def b(self):
                self._x = 2
    """)
    assert run_lint(str(tmp_path), rules=["concurrency"]) == []


def test_concurrency_condvar_wait_under_if_caught(tmp_path):
    path = _write(tmp_path, "q.py", """\
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []

            def get(self):
                with self._cv:
                    if not self._items:
                        self._cv.wait()
                    return self._items.pop()
    """)
    findings = run_lint(str(tmp_path), rules=["concurrency"])
    assert _checks(findings) == ["condvar_wait_if"]
    assert findings[0].path == path and findings[0].line == 11
    assert "while" in findings[0].msg


def test_concurrency_condvar_wait_in_while_and_wait_for_pass(tmp_path):
    _write(tmp_path, "q.py", """\
        import threading

        cond = threading.Condition()
        items = []

        def get():
            with cond:
                while not items:
                    cond.wait()
                return items.pop()

        def get2():
            with cond:
                cond.wait_for(lambda: items)
                return items.pop()

        def unrelated(ev):
            if True:
                ev.wait()  # Event.wait: level-triggered, not a condvar
    """)
    assert run_lint(str(tmp_path), rules=["concurrency"]) == []


def test_concurrency_lock_order_cycle_with_edge_locations(tmp_path):
    _write(tmp_path, "locks.py", """\
        import threading

        a = threading.Lock()
        b = threading.Lock()

        def one():
            with a:
                with b:
                    pass

        def two():
            with b:
                with a:
                    pass
    """)
    findings = run_lint(str(tmp_path), rules=["concurrency"])
    assert _checks(findings) == ["lock_order_cycle"]
    msg = findings[0].msg
    # Both edges render with file:line so the operator can walk the cycle.
    assert "locks.py:8" in msg and "locks.py:13" in msg
    assert "locks.py:a" in msg and "locks.py:b" in msg


def test_concurrency_lock_order_consistent_and_suppressed_pass(tmp_path):
    # Same nesting order everywhere: a DAG, no finding.
    _write(tmp_path, "locks.py", """\
        import threading

        a = threading.Lock()
        b = threading.Lock()

        def one():
            with a:
                with b:
                    pass

        def two():
            with a:
                with b:
                    pass
    """)
    assert run_lint(str(tmp_path), rules=["concurrency"]) == []
    # A deliberate cycle edge carries a reasoned escape on the inner
    # acquisition line.
    _write(tmp_path, "locks.py", """\
        import threading

        a = threading.Lock()
        b = threading.Lock()

        def one():
            with a:
                with b:  # lint: allow-lock-order(b holders never take a)
                    pass

        def two():
            with b:
                with a:
                    pass
    """)
    assert run_lint(str(tmp_path),
                    rules=["concurrency", "suppressions"]) == []


def test_concurrency_thread_leak_caught_and_snapshot_join_passes(tmp_path):
    path = _write(tmp_path, "d.py", """\
        import threading

        class Daemon:
            def __init__(self):
                self._t = None

            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass
    """)
    findings = run_lint(str(tmp_path), rules=["concurrency"])
    assert _checks(findings) == ["thread_leak"]
    assert findings[0].path == path and findings[0].line == 8
    assert "Daemon._t" in findings[0].msg
    # The race-free shutdown idiom — snapshot the attr, join the local —
    # must count as a join (autoscaler/scraper stop() pattern).
    _write(tmp_path, "d.py", """\
        import threading

        class Daemon:
            def __init__(self):
                self._t = None

            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass

            def stop(self):
                t = self._t
                if t is not None:
                    t.join(timeout=5.0)
    """)
    assert run_lint(str(tmp_path), rules=["concurrency"]) == []


def test_concurrency_fire_and_forget_thread_caught_and_suppressed(tmp_path):
    src = """\
        import threading

        class Spawner:
            def kick(self):
                threading.Thread(target=self._work, daemon=True).start(){s}

            def _work(self):
                pass
    """
    path = _write(tmp_path, "s.py", src.format(s=""))
    findings = run_lint(str(tmp_path), rules=["concurrency"])
    assert _checks(findings) == ["thread_leak"]
    assert findings[0].path == path and findings[0].line == 5
    assert "fire-and-forget" in findings[0].msg
    _write(tmp_path, "s.py", src.format(
        s="  # lint: allow-thread-leak(bounded and self-terminating)"))
    assert run_lint(str(tmp_path),
                    rules=["concurrency", "suppressions"]) == []


# --- rule: suppressions (stale-escape audit) ---------------------------------

def test_suppressions_stale_escape_is_a_finding(tmp_path):
    # The annotated line produces no hygiene finding -> the escape rots.
    path = _write(tmp_path, "m.py", """\
        x = 1  # lint: allow-wall-clock(nothing here needs this)
    """)
    findings = run_lint(str(tmp_path), rules=["hygiene", "suppressions"])
    assert _checks(findings) == ["unused_suppression"]
    assert findings[0].path == path and findings[0].line == 1
    assert "allow-wall-clock" in findings[0].msg


def test_suppressions_live_escape_not_flagged(tmp_path):
    _write(tmp_path, "m.py", """\
        import threading

        def f():
            pass

        t = threading.Thread(target=f)  # lint: allow-thread-daemon(fixture)
    """)
    assert run_lint(str(tmp_path), rules=["hygiene", "suppressions"]) == []


def test_suppressions_unknown_key_always_flagged(tmp_path):
    _write(tmp_path, "m.py", """\
        x = 1  # lint: allow-bogus-key(no rule owns this)
    """)
    findings = run_lint(str(tmp_path), rules=["suppressions"])
    assert _checks(findings) == ["unknown_suppression_key"]
    assert "bogus-key" in findings[0].msg


def test_suppressions_only_judge_selected_families(tmp_path):
    """`--rule hygiene` must not flag another family's (possibly live)
    escapes: the owning rule never ran, so it never had the chance to
    consume them."""
    _write(tmp_path, "m.py", """\
        x = 1  # lint: allow-unlocked(concurrency owns this key)
    """)
    assert run_lint(str(tmp_path),
                    rules=["hygiene", "suppressions"]) == []
    findings = run_lint(str(tmp_path),
                        rules=["concurrency", "suppressions"])
    assert _checks(findings) == ["unused_suppression"]


def test_suppressions_docstring_mention_is_not_an_escape(tmp_path):
    """Documentation that QUOTES the syntax (docstrings, block comments
    explaining a rule) must not register as a live suppression — only a
    comment that IS the directive counts."""
    _write(tmp_path, "m.py", '''\
        """Suppress with ``# lint: allow-wall-clock(reason)``."""

        # Deliberate sites carry # lint: allow-wall-clock(<why>) markers.
        x = 1
    ''')
    assert run_lint(str(tmp_path), rules=["hygiene", "suppressions"]) == []


# --- CLI: --format sarif / --changed -----------------------------------------

def test_cli_lint_sarif_output_parses(tmp_path, capsys):
    from featurenet_tpu.cli import main

    _write(tmp_path, "q.py", """\
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._ok = False

            def get(self):
                with self._cv:
                    if not self._ok:
                        self._cv.wait()
    """)
    with pytest.raises(SystemExit) as exc:
        main(["lint", str(tmp_path), "--format", "sarif",
              "--rule", "concurrency"])
    assert exc.value.code == 2
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "featurenet-lint"
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert ids == ["concurrency/condvar_wait_if"]
    res = run["results"][0]
    assert res["ruleId"] == "concurrency/condvar_wait_if"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("q.py")
    assert loc["region"]["startLine"] == 11
    # A clean tree still emits a valid (empty-results) SARIF log.
    _write(tmp_path, "q.py", "x = 1\n")
    main(["lint", str(tmp_path), "--format", "sarif",
          "--rule", "concurrency"])
    clean = json.loads(capsys.readouterr().out)
    assert clean["runs"][0]["results"] == []


def test_cli_lint_changed_scopes_to_git_diff(tmp_path):
    import subprocess

    git = ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
           "-c", "user.name=t"]
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    violation = 'x = 1  # lint: allow-wall-clock(stale on purpose)\n'
    _write(tmp_path, "a.py", violation)
    subprocess.run(git + ["add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "seed"], check=True)
    # a.py is committed and unchanged: its finding is scoped away.
    assert run_lint(str(tmp_path), rules=["hygiene", "suppressions"],
                    changed_only=True) == []
    # An untracked file's findings ARE in scope.
    _write(tmp_path, "b.py", violation)
    findings = run_lint(str(tmp_path), rules=["hygiene", "suppressions"],
                        changed_only=True)
    assert [os.path.basename(f.path) for f in findings] == ["b.py"]
    # Without --changed the unchanged file's finding is still reported.
    full = run_lint(str(tmp_path), rules=["hygiene", "suppressions"])
    assert sorted(os.path.basename(f.path) for f in full) == \
        ["a.py", "b.py"]


def test_cli_lint_changed_without_git_falls_back_to_full(tmp_path,
                                                         monkeypatch):
    """No work tree (or no git binary): --changed degrades to the full
    lint — never a silently-empty one."""
    from featurenet_tpu.analysis import lint as lint_mod

    _write(tmp_path, "a.py",
           "x = 1  # lint: allow-wall-clock(stale on purpose)\n")
    monkeypatch.setattr(lint_mod, "_git_changed_files", lambda root: None)
    findings = run_lint(str(tmp_path), rules=["hygiene", "suppressions"],
                        changed_only=True)
    assert _checks(findings) == ["unused_suppression"]
