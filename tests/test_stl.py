"""STL reader/writer unit tests (SURVEY.md §4: parser on hand-built meshes)."""

import numpy as np

from featurenet_tpu.data import load_stl, save_stl
from featurenet_tpu.data.mesh_primitives import mesh_box, mesh_cylinder


def test_binary_roundtrip(tmp_path):
    tris = mesh_box()
    p = tmp_path / "box.stl"
    save_stl(str(p), tris)
    back = load_stl(str(p))
    np.testing.assert_allclose(back, tris, rtol=0, atol=0)


def test_binary_detection_solid_header(tmp_path):
    # Binary files whose header starts with 'solid' must still parse as binary.
    tris = mesh_box()
    p = tmp_path / "tricky.stl"
    save_stl(str(p), tris, name="solid looking header")
    back = load_stl(str(p))
    assert back.shape == (12, 3, 3)


def test_ascii_parse(tmp_path):
    tris = np.array(
        [[[0, 0, 0], [1, 0, 0], [0, 1, 0]]], dtype=np.float32
    )
    lines = ["solid t"]
    for tri in tris:
        lines.append("facet normal 0 0 1")
        lines.append("outer loop")
        for v in tri:
            lines.append(f"vertex {v[0]} {v[1]} {v[2]}")
        lines.append("endloop")
        lines.append("endfacet")
    lines.append("endsolid t")
    p = tmp_path / "tri.stl"
    p.write_text("\n".join(lines))
    back = load_stl(str(p))
    np.testing.assert_allclose(back, tris)


def test_cylinder_mesh_shape():
    tris = mesh_cylinder(segments=16)
    assert tris.shape == (64, 3, 3)
