"""Serving front end (featurenet_tpu.serve): continuous batcher scheduling
(flush policy, bucket padding, de-mux, admission control), the
InferenceService over real bucketed AOT executables, the STL upload path,
the HTTP front end, the Poisson open-loop load generator, SLO-gated drain
exit codes (serve + infer), and the bench probe/gate plumbing.

The acceptance spine (ISSUE 7): an open-loop load-gen e2e on CPU where
every accepted request gets exactly one response with the right label,
zero XLA compiles happen after warmup (``program_compile`` events), ≥2
bucket sizes fill; an overload burst produces structured rejections; a
serving alert fires and resolves as a hysteresis pair; and an unresolved
serving alert at drain time yields a nonzero exit code.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.config import get_config
from featurenet_tpu.obs import alerts, windows
from featurenet_tpu.obs.report import build_report, format_report, load_events
from featurenet_tpu.serve.batcher import (
    ContinuousBatcher,
    OverloadError,
    pick_bucket,
)
from featurenet_tpu.serve.loadgen import poisson_load
from featurenet_tpu.serve.service import InferenceService, serve_rules

RES = 16  # smoke16 resolution — every real-model test runs at 16³


def _grid(value: float = 1.0) -> np.ndarray:
    return np.full((RES, RES, RES, 1), value, np.float32)


def _sum_forward(calls=None):
    """Fake forward: row i's answer is row i's sum — any de-mux mixup is
    immediately visible as a wrong value."""

    def forward(bucket, arr):
        if calls is not None:
            calls.append((bucket, arr.shape[0]))
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    return forward


@pytest.fixture(scope="module")
def predictor():
    """Random-init smoke16 Predictor (weights don't matter for scheduling
    and throughput semantics; label agreement is checked against the same
    predictor's batch API)."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model

    cfg = get_config("smoke16", data_workers=1)
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, RES, RES, RES, 1), jnp.float32),
        train=False,
    )
    return Predictor(
        variables["params"], variables["batch_stats"], cfg, batch=4
    )


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    """A real trained smoke16 checkpoint for the CLI-level tests."""
    from featurenet_tpu.train import Trainer

    d = str(tmp_path_factory.mktemp("serve_ckpt") / "ckpt")
    cfg = get_config(
        "smoke16", total_steps=6, eval_every=10**9, checkpoint_every=6,
        log_every=6, checkpoint_dir=d, data_workers=1,
    )
    Trainer(cfg).run()
    return d


@pytest.fixture()
def stl_bytes(tmp_path):
    from featurenet_tpu.data.mesh_primitives import mesh_box
    from featurenet_tpu.data.stl import save_stl

    p = str(tmp_path / "part.stl")
    save_stl(p, mesh_box((0.2, 0.2, 0.2), (0.8, 0.8, 0.7)))
    with open(p, "rb") as fh:
        return fh.read()


# --- batcher: scheduling core (backend-free) ---------------------------------

def test_pick_bucket_ladder():
    assert pick_bucket(1, (1, 4, 16)) == 1
    assert pick_bucket(2, (1, 4, 16)) == 4
    assert pick_bucket(4, (1, 4, 16)) == 4
    assert pick_bucket(5, (1, 4, 16)) == 16
    assert pick_bucket(99, (1, 4, 16)) == 16  # callers cap at the max
    with pytest.raises(ValueError, match="buckets"):
        ContinuousBatcher(_sum_forward(), buckets=())
    with pytest.raises(ValueError, match="queue_limit"):
        ContinuousBatcher(_sum_forward(), queue_limit=0)


def test_flush_on_max_batch_beats_the_deadline():
    """A burst that fills the largest bucket dispatches immediately — it
    must NOT sit out the (deliberately huge) max-wait deadline."""
    calls: list = []
    b = ContinuousBatcher(
        _sum_forward(calls), buckets=(1, 4), max_wait_ms=60_000,
        queue_limit=16,
    )
    t0 = time.perf_counter()
    futs = [b.submit(np.full((2,), float(i))) for i in range(4)]
    vals = [f.result(10) for f in futs]
    assert time.perf_counter() - t0 < 30  # seconds, not the 60s deadline
    assert vals == [0.0, 2.0, 4.0, 6.0]
    assert (4, 4) in calls  # one full bucket-4 dispatch
    b.drain()


def test_flush_on_max_wait_for_partial_batch():
    """A partial batch dispatches at the oldest request's deadline,
    padded to the smallest fitting bucket."""
    calls: list = []
    b = ContinuousBatcher(
        _sum_forward(calls), buckets=(1, 4, 16), max_wait_ms=50,
        queue_limit=16,
    )
    futs = [b.submit(np.full((2,), float(i))) for i in range(2)]
    vals = [f.result(10) for f in futs]
    assert vals == [0.0, 2.0]
    assert calls[0] == (4, 4)  # 2 rows dispatched padded to bucket 4
    # The wait is the flush deadline, not forever: well under a second
    # for a 50 ms deadline even on a loaded box.
    assert all(f.latency_ms < 10_000 for f in futs)
    st = b.drain()
    assert st["occupancy"] == 0.5
    assert st["by_bucket"] == {4: 1}


def test_demux_ordering_under_interleaved_arrivals():
    """Concurrent submitters each get exactly their own answer back —
    row-sum forward makes any cross-wiring a value mismatch."""
    b = ContinuousBatcher(
        _sum_forward(), buckets=(1, 4, 16), max_wait_ms=2, queue_limit=128,
    )
    results: dict[int, float] = {}
    lock = threading.Lock()

    def client(base: int):
        for j in range(10):
            v = float(base * 100 + j)
            got = b.submit(np.full((3,), v)).result(30)
            with lock:
                results[int(v)] = got

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert len(results) == 30
    for v, got in results.items():
        assert got == pytest.approx(3.0 * v)
    st = b.drain()
    assert st["served"] == 30 and st["errors"] == 0


def test_fast_reject_at_queue_bound(tmp_path):
    """At the admission bound, submit() rejects immediately with the
    structured overload response (and an ``overload`` event) instead of
    queueing — and the already-admitted requests still get answers."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    gate = threading.Event()

    def blocked(bucket, arr):
        gate.wait(30)
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(blocked, buckets=(1, 2), max_wait_ms=1,
                          queue_limit=3)
    futs = [b.submit(np.ones((1,))) for _ in range(2)]  # first dispatch
    time.sleep(0.2)  # let the dispatcher pick them up and block
    futs += [b.submit(np.ones((1,))) for _ in range(3)]  # fill the queue
    t0 = time.perf_counter()
    with pytest.raises(OverloadError) as ei:
        b.submit(np.ones((1,)))
    assert time.perf_counter() - t0 < 5  # fast-reject, no deadline wait
    assert ei.value.response == {
        "error": "overload", "queue_depth": 3, "limit": 3,
        # The wire shape grew the shed lane and the server's honest
        # backoff hint (surfaced as the HTTP Retry-After header).
        "lane": "interactive",
        "retry_after_s": ei.value.retry_after_s,
    }
    assert ei.value.retry_after_s and ei.value.retry_after_s >= 0.05
    gate.set()
    for f in futs:
        f.result(30)
    st = b.drain()
    assert st["rejected"] == 1 and st["served"] == 5
    obs.close_run()
    events, _ = load_events(run_dir)
    over = [e for e in events if e["ev"] == "overload"]
    assert len(over) == 1
    assert over[0]["queue_depth"] == 3 and over[0]["limit"] == 3
    # drain is recorded with the final counters
    stop = [e for e in events if e["ev"] == "serve_stop"]
    assert stop and stop[-1]["served"] == 5 and stop[-1]["rejected"] == 1


def test_forward_error_resolves_batch_and_batcher_survives():
    flaky = {"fail": True}

    def forward(bucket, arr):
        if flaky["fail"]:
            flaky["fail"] = False
            raise ValueError("injected forward failure")
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(forward, buckets=(1, 2), max_wait_ms=1,
                          queue_limit=8)
    with pytest.raises(RuntimeError, match="injected forward failure"):
        b.submit(np.ones((2,))).result(10)
    # The dead batch resolved; the next one serves normally.
    assert b.submit(np.full((2,), 3.0)).result(10) == pytest.approx(6.0)
    st = b.drain()
    assert st["errors"] == 1 and st["served"] == 1


def test_hook_error_counter_exact_across_threads():
    """Regression (concurrency lint): ``_hook_errors`` is bumped from the
    submit path (HTTP handler threads, reject hook) AND the dispatcher
    thread (result hook) — both increments must hold ``_cv`` or
    concurrent failures lose counts. Every fired hook raises, so the
    counter must equal exactly (answered requests) + (rejections)."""

    def bad_hook(*a):
        raise RuntimeError("hook boom")

    b = ContinuousBatcher(_sum_forward(), buckets=(1, 4), max_wait_ms=1,
                          queue_limit=1024, on_result=bad_hook,
                          on_reject=bad_hook)
    n_threads, per_thread = 8, 16
    done = []
    lock = threading.Lock()

    def submit_many():
        for _ in range(per_thread):
            b.submit(np.ones((2,))).result(30)
        with lock:
            done.append(1)

    threads = [threading.Thread(target=submit_many, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    st = b.drain()
    assert len(done) == n_threads
    assert st["served"] == n_threads * per_thread
    # One on_result failure per answered request, zero rejects here.
    assert b._hook_errors == n_threads * per_thread

    # The reject path charges the same counter from the caller's thread.
    gate = threading.Event()

    def blocked(bucket, arr):
        gate.wait(30)
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b2 = ContinuousBatcher(blocked, buckets=(1,), max_wait_ms=1,
                           queue_limit=1, on_reject=bad_hook)
    futs = [b2.submit(np.ones((1,)))]  # dispatcher picks this up, blocks
    time.sleep(0.2)
    futs.append(b2.submit(np.ones((1,))))  # fills the queue
    rejects = 0
    for _ in range(5):
        with pytest.raises(OverloadError):
            b2.submit(np.ones((1,)))
        rejects += 1
    gate.set()
    for f in futs:
        f.result(30)
    st2 = b2.drain()
    assert st2["rejected"] == rejects
    assert b2._hook_errors == rejects


def test_drain_refuses_new_requests():
    b = ContinuousBatcher(_sum_forward(), buckets=(1,), max_wait_ms=1)
    b.drain()
    with pytest.raises(RuntimeError, match="draining"):
        b.submit(np.ones((1,)))


def test_deadline_flush_prefers_full_bucket_over_heavy_padding():
    """An awkward deadline-flush count (5 on a 1/4/16 ladder) must not
    pad to the under-half-full fitting bucket (16, 11 zeros): dispatch
    the full bucket-4 and let the leftover — its deadline already past —
    flush immediately as bucket-1. Every row served, zero padding."""
    calls: list = []
    gate = threading.Event()

    def gated(bucket, arr):
        gate.wait(30)  # hold the dispatcher so 5 requests accumulate
        calls.append((bucket, arr.shape[0]))
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(gated, buckets=(1, 4, 16), max_wait_ms=20,
                          queue_limit=32)
    futs = [b.submit(np.full((1,), float(i))) for i in range(1)]
    time.sleep(0.1)  # dispatcher picks up the first request and blocks
    futs += [b.submit(np.full((1,), float(i))) for i in range(1, 6)]
    time.sleep(0.1)  # all 5 are queued and past the flush deadline
    gate.set()
    assert [f.result(10) for f in futs] == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
    st = b.drain()
    # first lone request → bucket 1, then the 5-deep backlog → 4 + 1
    assert calls == [(1, 1), (4, 4), (1, 1)]
    assert st["occupancy"] == 1.0  # no padding anywhere


def test_drain_timeout_reported_and_gates_exit_code(predictor):
    """A wedged forward must not let drain() claim a clean shutdown: the
    join timeout flips ``drain_timeout`` and the service's SLO verdict
    exits nonzero even with every alert quiet."""
    service = InferenceService(
        predictor, buckets=(1,), max_wait_ms=1, queue_limit=4, rules=(),
    )
    gate = threading.Event()
    real_forward = service.batcher.forward

    def wedged(bucket, arr):
        gate.wait(30)
        return real_forward(bucket, arr)

    service.batcher.forward = wedged
    fut = service.submit_voxels(np.zeros((RES, RES, RES), np.float32))
    st = service.drain(timeout_s=0.3)
    assert st["drain_timeout"] is True
    assert st["active_serving_alerts"] == []
    assert st["exit_code"] == 2  # unanswered admitted work = not clean
    gate.set()  # unwedge; the dispatcher answers and exits
    fut.result(30)
    service.batcher._worker.join(10)
    assert service.batcher.drain()["drain_timeout"] is False


# --- windows/alerts: the queue_wait metric and the serving predicate ---------

def test_queue_wait_window_and_serving_metric_predicate():
    assert "queue_wait_ms_p99" in alerts.known_metrics()
    agg = windows.WindowAggregator()
    agg.observe("queue_wait_ms", 5.0)
    assert agg.rule_value(
        "queue_wait_ms_p99", time.perf_counter()
    ) == pytest.approx(5.0)
    assert alerts.is_serving_metric("serving_p99_ms")
    assert alerts.is_serving_metric("serving_ms_p50")
    assert alerts.is_serving_metric("queue_wait_ms_p99")
    assert not alerts.is_serving_metric("data_wait_fraction")
    assert not alerts.is_serving_metric("queue_depth")
    # serve_rules: the defaults plus the two serving rules, SLO threaded.
    rules = serve_rules(slo_p99_ms=42.0)
    by_metric = {r.metric: r for r in rules}
    assert by_metric["serving_p99_ms"].threshold == 42.0
    assert by_metric["serving_p99_ms"].severity == "critical"
    assert by_metric["queue_wait_ms_p99"].threshold == 42.0
    assert "data_wait_fraction" in by_metric  # defaults still present


def test_replica_slow_fault_drags_forward(predictor):
    """The replica_slow injection site (fleet chaos): the Nth dispatched
    forward sleeps SLOW_SLEEP_S — latency, not death; the service keeps
    answering (one-shot: the next dispatch runs at full speed)."""
    from featurenet_tpu import faults

    faults.install("replica_slow@request=1")
    svc = InferenceService(predictor, buckets=(1,), max_wait_ms=1,
                           rules=())
    try:
        grid = _grid()
        t0 = time.perf_counter()
        row = svc.predict(svc.submit_voxels(grid), timeout=30)
        dragged = time.perf_counter() - t0
        assert dragged >= faults.SLOW_SLEEP_S
        assert "label" in row
        # One-shot: the second dispatch does not pay the sleep again.
        t0 = time.perf_counter()
        svc.predict(svc.submit_voxels(grid), timeout=30)
        assert time.perf_counter() - t0 < faults.SLOW_SLEEP_S
    finally:
        faults.uninstall()
        svc.drain()


# --- the service: warm ladder + open-loop load-gen e2e (acceptance) ----------

def test_service_loadgen_e2e_zero_compiles_correct_labels(tmp_path, rng):
    """The acceptance spine: Poisson arrivals + a max-bucket burst through
    a freshly warmed service. Every accepted request gets exactly one
    response whose label matches the batch-mode reference; ≥2 bucket
    sizes fill; and not one ``program_compile`` event lands after
    warmup."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    cfg = get_config("smoke16", data_workers=1)
    variables = build_model(cfg).init(
        jax.random.key(1), jnp.zeros((1, RES, RES, RES, 1), jnp.float32),
        train=False,
    )
    pred = Predictor(
        variables["params"], variables["batch_stats"], cfg, batch=4
    )
    service = InferenceService(
        pred, buckets=(1, 4, 16), max_wait_ms=25, queue_limit=64,
        rules=(),  # keep init_run's ambient aggregator
    )
    events, _ = load_events(run_dir)
    compiles_at_warmup = sum(
        1 for e in events if e["ev"] == "program_compile"
    )
    assert compiles_at_warmup >= 3  # one serve build per bucket (4 shared)

    grids = generate_batch(rng, 24, RES)["voxels"]
    expected, _ = pred.predict_voxels(grids)  # batch-mode reference

    stats, futs = poisson_load(
        service, qps=150.0, n_requests=24,
        rng=np.random.default_rng(7), grids=grids,
    )
    assert stats["rejected"] == 0 and stats["accepted"] == 24
    assert len(futs) == 24
    for i, fut in enumerate(futs):
        probs = fut.result(30)
        assert int(np.argmax(probs)) == int(expected[i % len(grids)])
        assert fut.latency_ms is not None and fut.latency_ms > 0
    # Deterministic bucket-fill: a 17-burst flushes a full 16-bucket
    # immediately and leaves one request for a smaller bucket.
    burst = [service.submit_voxels(grids[i % 24]) for i in range(17)]
    for i, fut in enumerate(burst):
        assert int(np.argmax(fut.result(30))) == int(expected[i % 24])
    st = service.drain()
    assert st["exit_code"] == 0 and st["active_serving_alerts"] == []
    assert len(st["by_bucket"]) >= 2, st  # ≥2 bucket sizes filled
    assert st["served"] == 24 + 17
    assert 0 < st["occupancy"] <= 1.0

    obs.close_run()
    events, bad = load_events(run_dir)
    assert bad == 0
    compiles_total = sum(
        1 for e in events if e["ev"] == "program_compile"
    )
    assert compiles_total == compiles_at_warmup  # ZERO compiles post-warmup
    # The report folds the serving telemetry: serve section with bucket
    # histogram + occupancy, serve_start/stop, window summaries.
    rep = build_report(events)
    assert rep["serve"]["batches"] == sum(st["by_bucket"].values())
    assert rep["serve"]["rows"] == st["served"]
    assert rep["serve"]["occupancy"] == pytest.approx(st["occupancy"])
    assert len(rep["serve"]["by_bucket"]) >= 2
    text = format_report(rep)
    assert "serve:" in text and "by bucket:" in text
    wins = (rep.get("slo") or {}).get("windows") or {}
    assert "serving_ms" in wins and "queue_wait_ms" in wins


def test_service_slo_alert_fire_resolve_and_drain_exit_codes(
    tmp_path, predictor
):
    """A slow forward blows the p99 SLO → ONE alert fires; recovery
    resolves it (hysteresis pair); drain after recovery exits 0. A
    service drained mid-violation exits 2 with the alert named. The
    overload burst rides the slow phase: structured rejections while the
    queue is pinned."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    service = InferenceService(
        predictor, buckets=(1, 2), max_wait_ms=1, queue_limit=4,
        rules=serve_rules(slo_p99_ms=100.0), emit_every_s=0.0,
    )
    real_forward = service.batcher.forward
    slow = {"sleep_s": 0.3}

    def throttled(bucket, arr):
        time.sleep(slow["sleep_s"])
        return real_forward(bucket, arr)

    service.batcher.forward = throttled
    # Overload burst while the forward is slow: the queue (limit 4) pins
    # and later arrivals fast-reject with the structured response.
    futs, rejections = [], []
    for i in range(12):
        try:
            futs.append(service.submit_voxels(_grid(float(i))))
        except OverloadError as e:
            rejections.append(e.response)
    for f in futs:
        f.result(60)
    assert rejections, "the burst must overflow the bounded queue"
    assert all(r["error"] == "overload" and r["limit"] == 4
               for r in rejections)
    windows.flush()
    assert "serving_p99_ms" in windows.active_alerts()
    # Recovery: fast forward, enough samples to evict the slow tail from
    # the 128-deep serving window → the paired resolve fires.
    slow["sleep_s"] = 0.0
    for i in range(140):
        service.submit_voxels(_grid(0.0)).result(30)
    windows.flush()
    assert "serving_p99_ms" not in windows.active_alerts()
    st = service.drain()
    assert st["exit_code"] == 0 and st["active_serving_alerts"] == []
    obs.close_run()

    events, _ = load_events(run_dir)
    fires = [e for e in events if e["ev"] == "alert"
             and e["rule"] == "serving_p99_ms"]
    assert [e["state"] for e in fires] == ["fire", "resolve"]
    assert len([e for e in events if e["ev"] == "overload"]) \
        == len(rejections)

    # Second service, drained while still in violation → exit code 2.
    obs.init_run(str(tmp_path / "run2"), process_index=0)
    service2 = InferenceService(
        predictor, buckets=(1, 2), max_wait_ms=1, queue_limit=8,
        rules=serve_rules(slo_p99_ms=100.0), emit_every_s=0.0,
    )
    fwd2 = service2.batcher.forward
    service2.batcher.forward = \
        lambda bucket, arr: (time.sleep(0.3), fwd2(bucket, arr))[1]
    for _ in range(3):
        service2.submit_voxels(_grid()).result(30)
    st2 = service2.drain()
    assert st2["exit_code"] == 2
    assert "serving_p99_ms" in st2["active_serving_alerts"]
    obs.close_run()


# --- the upload path: STL bytes → voxelize → predict -------------------------

def test_parse_stl_bytes_matches_file_loader(tmp_path, stl_bytes):
    from featurenet_tpu.data.mesh_primitives import mesh_box
    from featurenet_tpu.data.stl import load_stl, parse_stl, save_stl

    p = str(tmp_path / "ref.stl")
    save_stl(p, mesh_box((0.2, 0.2, 0.2), (0.8, 0.8, 0.7)))
    np.testing.assert_array_equal(parse_stl(stl_bytes), load_stl(p))
    # ASCII bytes parse too (the upload path cannot assume an exporter).
    tris = parse_stl(stl_bytes)
    ascii_text = "solid x\n" + "".join(
        "facet normal 0 0 0\nouter loop\n"
        + "".join(f"vertex {v[0]} {v[1]} {v[2]}\n" for v in tri)
        + "endloop\nendfacet\n"
        for tri in tris
    ) + "endsolid x\n"
    np.testing.assert_allclose(
        parse_stl(ascii_text.encode()), tris, rtol=1e-6
    )
    with pytest.raises(ValueError, match="malformed STL"):
        parse_stl(b"this is not an STL at all")
    with pytest.raises(ValueError, match="malformed STL"):
        parse_stl(stl_bytes[:-7])  # truncated binary record


def test_service_stl_upload_end_to_end(tmp_path, predictor, stl_bytes):
    from featurenet_tpu.data.mesh_primitives import mesh_box
    from featurenet_tpu.data.stl import save_stl

    service = InferenceService(
        predictor, buckets=(1, 4), max_wait_ms=2, queue_limit=8, rules=(),
    )
    row = service.predict(service.submit_stl_bytes(stl_bytes), timeout=60)
    assert set(row) == {"label", "class_name", "prob", "top3"}
    assert 0.0 <= row["prob"] <= 1.0 and len(row["top3"]) == 3
    # Same part through the batch-mode STL path → same label.
    p = str(tmp_path / "same.stl")
    save_stl(p, mesh_box((0.2, 0.2, 0.2), (0.8, 0.8, 0.7)))
    (ref,) = predictor.predict_stl([p])
    assert row["label"] == ref.label and row["class_name"] == ref.class_name
    with pytest.raises(ValueError):
        service.submit_stl_bytes(b"garbage bytes")
    with pytest.raises(ValueError, match="expected one"):
        service.submit_voxels(np.zeros((4, 4, 4), np.float32))
    service.drain()


# --- HTTP front end ----------------------------------------------------------

def test_http_predict_stats_and_error_codes(predictor, stl_bytes):
    import http.client

    from featurenet_tpu.serve.http import make_server

    service = InferenceService(
        predictor, buckets=(1, 4), max_wait_ms=2, queue_limit=8, rules=(),
    )
    srv = make_server(service, port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        def request(method, path, body=None):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            payload = json.loads(resp.read().decode())
            conn.close()
            return resp.status, payload

        status, row = request("POST", "/predict", stl_bytes)
        assert status == 200
        assert "class_name" in row and len(row["top3"]) == 3
        status, err = request("POST", "/predict", b"not an stl")
        assert status == 400 and err["error"] == "bad_stl"
        status, st = request("GET", "/stats")
        assert status == 200 and st["ok"] and st["served"] >= 1
        status, err = request("GET", "/nope")
        assert status == 404 and err["error"] == "not_found"
    finally:
        srv.shutdown()
        service.drain()


# --- CLI: serve + infer exit-code gating -------------------------------------

def test_cli_serve_http_roundtrip_and_drain(ckpt_dir, stl_bytes, tmp_path):
    """`cli serve` end to end: boot, answer a real STL upload over HTTP,
    drain at --duration-s, exit clean (no SLO violation at a sane
    threshold)."""
    import http.client
    import socket

    from featurenet_tpu.cli import main as cli_main

    # Reserve an ephemeral port for the server (the CLI prints its bound
    # port on stdout, which a same-process test can't read in time).
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    result: dict = {}

    def client():
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=30
                )
                conn.request("POST", "/predict", body=stl_bytes)
                resp = conn.getresponse()
                result["status"] = resp.status
                result["row"] = json.loads(resp.read().decode())
                conn.close()
                return
            except OSError:
                time.sleep(0.1)

    t = threading.Thread(target=client, daemon=True)
    t.start()
    cli_main([
        "serve", "--checkpoint-dir", ckpt_dir, "--buckets", "1,2",
        "--max-wait-ms", "2", "--port", str(port), "--duration-s", "6",
        "--drain", "--run-dir", str(tmp_path / "run"),
    ])
    t.join(30)
    assert result.get("status") == 200, result
    assert "class_name" in result["row"]
    events, _ = load_events(str(tmp_path / "run"))
    kinds = {e["ev"] for e in events}
    assert {"serve_start", "serve_batch", "serve_stop"} <= kinds


def test_cli_infer_exit_code_gated_by_serving_alert(
    ckpt_dir, tmp_path, stl_bytes
):
    """The carried-over SLO follow-on: serving alert rules drive infer's
    exit code — an unresolved serving_ms alert at drain time exits 2, a
    healthy run exits clean."""
    from featurenet_tpu.cli import main as cli_main

    stl = str(tmp_path / "part.stl")
    with open(stl, "wb") as fh:
        fh.write(stl_bytes)
    # Impossible threshold → the alert fires and cannot resolve → exit 2.
    with pytest.raises(SystemExit) as ei:
        cli_main([
            "infer", stl, "--checkpoint-dir", ckpt_dir,
            "--run-dir", str(tmp_path / "bad"),
            "--alert-rules", "serving_p99_ms>0.0001:critical",
        ])
    assert ei.value.code == 2
    # Generous threshold → same run shape exits clean (returns None).
    assert cli_main([
        "infer", stl, "--checkpoint-dir", ckpt_dir,
        "--run-dir", str(tmp_path / "ok"),
        "--alert-rules", "serving_p99_ms>1e9",
    ]) is None
    # --alert-rules without --run-dir is a refusal, not a silent no-gate.
    with pytest.raises(SystemExit, match="alert-rules"):
        cli_main([
            "infer", stl, "--checkpoint-dir", ckpt_dir,
            "--alert-rules", "serving_p99_ms>1e9",
        ])


# --- report: per-host window summaries (carried-over follow-on) --------------

def test_report_per_host_window_summaries():
    t0 = 1000.0
    events = []
    for host in (0, 1):
        events.append({"t": t0, "ev": "run_start", "process_index": host})
        events.append({
            "t": t0 + 1, "ev": "window_summary", "metric": "serving_ms",
            "n": 50, "p50": 5.0 + host * 20, "p95": 8.0,
            "p99": 9.0 + host * 40, "mean": 5.5, "max": 10.0, "seq": 1,
            "process_index": host,
        })
    rep = build_report(events)
    assert rep["hosts"][0]["windows"]["serving_ms"]["p50"] == 5.0
    assert rep["hosts"][1]["windows"]["serving_ms"]["p50"] == 25.0
    assert rep["hosts"][1]["windows"]["serving_ms"]["p99"] == 49.0
    text = format_report(rep)
    assert "host windows (latest p50/p99):" in text
    assert "serving_ms 25.0/49.0" in text


# --- bench: serve gate keys + probe robustness (BENCH_r05 satellite) ---------

def test_bench_gate_serve_keys_and_directions():
    from featurenet_tpu.obs import gates

    summary = {
        "value": 16000.0,
        "serve_qps_sustained": 900.0,
        "serve_p50_ms": 4.2,
        "serve_p99_ms": 11.0,
        "serve_occupancy": 0.71,
        "serve_rejected": 0.0,
    }
    vals = gates.bench_gate_values(summary)
    for k in summary:
        assert k in vals, k
    pin = gates.make_baseline(vals)["gates"]
    assert pin["serve_qps_sustained"]["direction"] == "min"
    assert pin["serve_p99_ms"]["direction"] == "max"
    assert pin["serve_occupancy"]["direction"] == "min"
    assert pin["serve_rejected"]["direction"] == "max"
    # A QPS collapse or a p99 blowup is a regression; the reverse passes.
    worse = dict(vals, serve_qps_sustained=450.0, serve_p99_ms=33.0)
    res = gates.evaluate_gates(worse, {"gates": pin})
    assert not res["ok"]
    assert {"serve_qps_sustained", "serve_p99_ms"} <= set(res["failed"])
    better = dict(vals, serve_qps_sustained=1200.0, serve_p99_ms=6.0)
    assert gates.evaluate_gates(better, {"gates": pin})["ok"]


R05_TRACEBACK_TAIL = (
    "Traceback (most recent call last):\n"
    '  File "jaxlib/xla_client.py", line 161, in make_c_api_client\n'
    "    return _xla.get_c_api_client(\n"
    "jax.errors.JaxRuntimeError: UNAVAILABLE: TPU backend setup/compile "
    "error (Unavailable).\n"
    "RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE: TPU "
    "backend setup/compile error (Unavailable).\n"
)


class _FakeProc:
    def __init__(self, returncode, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def _bench_record(capsys):
    lines = [
        ln for ln in capsys.readouterr().out.strip().splitlines() if ln
    ]
    return json.loads(lines[-1])


def test_bench_probe_skip_record_on_plugin_init_failure(monkeypatch, capsys):
    """The BENCH_r05 shape: the probe child dies rc=1 with a raw
    make_c_api_client traceback. bench.main() must end in ONE structured
    skipped record, never an unhandled traceback."""
    import subprocess

    import bench

    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: _FakeProc(1, stderr=R05_TRACEBACK_TAIL),
    )
    bench.main()  # must not raise
    rec = _bench_record(capsys)
    assert rec["skipped"] is True
    assert rec["reason"] == "tpu_backend_unavailable"
    assert rec["backend"] == "cpu_fallback"
    assert "UNAVAILABLE" in rec["error"]


def test_bench_probe_child_reports_its_own_init_error(monkeypatch, capsys):
    """The hardened child catches make_c_api_client raising during plugin
    init and answers in JSON (rc 0) — the parent turns it into the same
    structured skip."""
    import subprocess

    import bench

    child_line = json.dumps({
        "probe_error": "JaxRuntimeError: UNAVAILABLE: TPU backend "
                       "setup/compile error (Unavailable).",
    })
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: _FakeProc(0, stdout="plugin noise\n" + child_line),
    )
    bench.main()
    rec = _bench_record(capsys)
    assert rec["skipped"] is True
    assert rec["reason"] == "tpu_backend_unavailable"
    assert "UNAVAILABLE" in rec["error"]


def test_bench_probe_parses_platform_through_noise(monkeypatch, capsys):
    """A healthy CPU-only box: the platform JSON line is found even under
    plugin chatter, and the round records the no-accelerator skip."""
    import subprocess

    import bench

    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: _FakeProc(
            0, stdout="W warning spam\n" + json.dumps({"platform": "cpu"})
        ),
    )
    bench.main()
    rec = _bench_record(capsys)
    assert rec["skipped"] is True
    assert rec["reason"] == "no_accelerator_platform"
    assert rec["error"] is None


# --- serving precision ladder + fleet-shared exec cache (ISSUE 12) -----------

def test_bf16_service_e2e_zero_compiles_agreement_gated(tmp_path, rng):
    """bf16 serving acceptance: a service built at precision="bf16"
    answers real requests with labels matching the fp32 batch-mode
    reference at the paper's >= 96.7% bar, and not one program_compile
    event lands after warmup (the AOT contract holds for the bf16
    bucket ladder exactly as for fp32)."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.quantize import PAPER_TOP1_TARGET
    from featurenet_tpu.runtime.registry import build_model

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    cfg = get_config("smoke16", data_workers=1)
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, RES, RES, RES, 1), jnp.float32),
        train=False,
    )
    fp = Predictor(
        variables["params"], variables["batch_stats"], cfg, batch=4
    )
    bf = Predictor(
        variables["params"], variables["batch_stats"], cfg, batch=4,
        precision="bf16",
    )
    assert bf.agreement(n=24, seed=0) >= PAPER_TOP1_TARGET
    service = InferenceService(
        bf, buckets=(1, 4), max_wait_ms=25, queue_limit=64, rules=(),
    )
    events, _ = load_events(run_dir)
    warm = sum(1 for e in events if e["ev"] == "program_compile")

    grids = generate_batch(rng, 12, RES)["voxels"]
    expected, _ = fp.predict_voxels(grids)  # fp32 reference labels
    futs = [service.submit_voxels(g) for g in grids]
    got = np.array([service.predict(f)["label"] for f in futs])
    assert (got == expected).mean() >= PAPER_TOP1_TARGET
    service.drain()
    obs.close_run()
    events, bad = load_events(run_dir)
    assert bad == 0
    total = sum(1 for e in events if e["ev"] == "program_compile")
    assert total == warm  # ZERO compiles post-warmup


def test_fleet_shared_exec_cache_second_service_all_hits(tmp_path):
    """Fleet-shared exec cache (carried follow-on): N services sharing
    one --exec-cache-dir coexist safely — the probe-verified loads
    already guard the files — and a SECOND service over the same dir
    warms every bucket from cache: one cache_hit per bucket executable,
    ZERO program_compile events, and the deserialized ladder still
    answers requests correctly."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model

    cache_dir = str(tmp_path / "exec")
    cfg = get_config("smoke16", data_workers=1,
                     exec_cache_dir=cache_dir)
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, RES, RES, RES, 1), jnp.float32),
        train=False,
    )
    buckets = (1, 2)

    def build_service(run_dir):
        obs.init_run(run_dir, process_index=0)
        pred = Predictor(
            variables["params"], variables["batch_stats"], cfg, batch=2,
        )
        return InferenceService(
            pred, buckets=buckets, max_wait_ms=25, queue_limit=16,
            rules=(),
        )

    # Service A: compiles and populates the shared dir.
    svc_a = build_service(str(tmp_path / "run_a"))
    svc_a.drain()
    obs.close_run()
    events_a, _ = load_events(str(tmp_path / "run_a"))
    assert sum(1 for e in events_a
               if e["ev"] == "program_compile") >= len(buckets)

    # Service B, same dir: every bucket deserializes — cache_hit per
    # bucket, zero compiles anywhere in its window.
    svc_b = build_service(str(tmp_path / "run_b"))
    fut = svc_b.submit_voxels(_grid(1.0))
    row = svc_b.predict(fut)
    assert "label" in row
    svc_b.drain()
    obs.close_run()
    events_b, bad = load_events(str(tmp_path / "run_b"))
    assert bad == 0
    assert sum(1 for e in events_b if e["ev"] == "program_compile") == 0
    hits = [e for e in events_b if e["ev"] == "cache_hit"]
    assert len(hits) >= len(buckets)
    assert not [e for e in events_b if e["ev"] == "cache_reject"]


# --- persistent connections: keep-alive contract + the stream protocol -------

def _fake_voxel_service(resolution: int = 4, mode: str = "ok"):
    """A scripted service for HTTP-layer tests: submit_voxels resolves
    immediately with the grid's sum as the 'row' (any de-mux or framing
    mixup is a wrong label), 'draining' raises the batcher's refusal,
    'overload' fast-rejects every submit."""
    import types

    from featurenet_tpu.serve.batcher import PendingRequest

    class Svc:
        class cfg:
            pass

        replica = None

        def __init__(self):
            self.cfg.resolution = resolution
            self.batcher = types.SimpleNamespace(retry_after_s=0.1)
            self.calls = 0

        def submit_voxels(self, grid, trace_id=None, lane="interactive"):
            self.calls += 1
            if mode == "draining":
                raise RuntimeError("batcher is draining")
            if mode == "overload":
                raise OverloadError(4, 4, trace_id=trace_id, lane=lane,
                                    retry_after_s=0.05)
            p = PendingRequest(
                np.asarray(grid),
                ctx=types.SimpleNamespace(trace_id=trace_id),
            )
            p.value = float(np.asarray(grid).sum())
            p.t_done = time.perf_counter()
            p._event.set()
            return p

        def format_row(self, row):
            return {"label": int(row)}

        def health(self):
            return {"ready": True, "uptime_s": 1.0, "window_seq": 0}

        def stats(self):
            return {"served": self.calls, "rejected": 0, "errors": 0,
                    "queue_depth": 0, "occupancy": None, "by_bucket": {}}

    return Svc()


def _voxel_body(resolution: int = 4, value: float = 1.0) -> bytes:
    return np.full((resolution,) * 3, value, "<f4").tobytes()


def test_http_keepalive_one_socket_serves_sequential_requests():
    """The keep-alive contract: HTTP/1.1 + exact Content-Length means
    ONE client socket serves N sequential /predict_voxels requests —
    the server never closes mid-stream, and GETs ride the same channel."""
    import http.client

    from featurenet_tpu.serve.http import make_server

    service = _fake_voxel_service()
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        sock = None
        for i in range(6):
            conn.request("POST", "/predict_voxels",
                         body=_voxel_body(value=float(i)))
            resp = conn.getresponse()
            assert resp.status == 200 and resp.version == 11
            body = json.loads(resp.read().decode())
            assert body["label"] == i * 4 ** 3
            assert resp.getheader("Connection") != "close"
            if sock is None:
                sock = conn.sock
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200
        resp.read()
        # Same socket end to end: zero reconnects for the whole burst.
        assert conn.sock is sock
        assert service.calls == 6
        conn.close()
    finally:
        srv.shutdown()


def test_http_draining_503_closes_channel_overload_keeps_it():
    """The two 503 flavors part ways on the keep-alive contract: a
    DRAINING refusal closes the channel (the server is going away), an
    overload rejection keeps it open (the polite retry should ride the
    warm channel)."""
    import http.client

    from featurenet_tpu.serve.http import make_server

    draining = _fake_voxel_service(mode="draining")
    srv = make_server(draining, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        conn.request("POST", "/predict_voxels", body=_voxel_body())
        resp = conn.getresponse()
        assert resp.status == 503
        assert json.loads(resp.read().decode())["error"] == "draining"
        assert resp.getheader("Connection") == "close"
        assert resp.will_close
        conn.close()
    finally:
        srv.shutdown()

    overloaded = _fake_voxel_service(mode="overload")
    srv = make_server(overloaded, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        conn.request("POST", "/predict_voxels", body=_voxel_body())
        resp = conn.getresponse()
        assert resp.status == 503
        assert json.loads(resp.read().decode())["error"] == "overload"
        assert resp.getheader("Connection") != "close"
        sock = conn.sock
        conn.request("POST", "/predict_voxels", body=_voxel_body())
        resp = conn.getresponse()
        assert resp.status == 503 and conn.sock is sock
        resp.read()
        conn.close()
    finally:
        srv.shutdown()


def test_stream_protocol_frames_labels_and_trace_ids():
    """The stream wire format end to end against a scripted service:
    every length-prefixed frame answers one JSON line with its own
    ``<stream>.<i>`` trace id and the right label, in frame order."""
    from featurenet_tpu.serve.http import make_server
    from featurenet_tpu.serve.loadgen import stream_load

    service = _fake_voxel_service()
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        grids = [np.full((4, 4, 4), float(i), np.float32)
                 for i in range(5)]
        out = stream_load("127.0.0.1", srv.server_address[1], grids,
                          trace_id="stream-test-1")
        assert out["status"] == 200
        assert out["stream_id"] == "stream-test-1"
        assert out["answered"] == 5 and out["errors"] == 0
        assert out["reconnects"] == 0
        for i, line in enumerate(out["lines"]):
            assert line["frame"] == i
            assert line["trace"] == f"stream-test-1.{i}"
            assert line["label"] == i * 4 ** 3
    finally:
        srv.shutdown()


def test_stream_torn_frame_structured_400():
    """Framing errors are a structured 400, not a dropped socket or a
    numpy traceback: torn length prefix, short payload, wrong declared
    size, and the empty stream each name their failure."""
    import http.client
    import struct

    from featurenet_tpu.serve.http import make_server

    service = _fake_voxel_service()
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def post_stream(body: bytes):
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        try:
            conn.request("POST", "/predict_voxels_stream", body=body)
            resp = conn.getresponse()
            doc = json.loads(resp.read().decode())
            return resp.status, doc, resp.getheader("Connection")
        finally:
            conn.close()

    try:
        frame = _voxel_body()
        ok_frame = struct.pack("<I", len(frame)) + frame
        # Torn prefix: 2 trailing bytes where a 4-byte length belongs.
        status, doc, conn_hdr = post_stream(ok_frame + b"\x01\x02")
        assert status == 400 and doc["error"] == "bad_stream"
        assert "torn length prefix" in doc["detail"]
        assert doc["frames_admitted"] == 1
        assert conn_hdr == "close"  # the byte stream is unreliable now
        # Short payload: the prefix promises more bytes than the body.
        status, doc, _ = post_stream(struct.pack("<I", len(frame))
                                     + frame[:10])
        assert status == 400 and "remain in the body" in doc["detail"]
        # Wrong declared size: not a [R]^3 float32 grid.
        status, doc, _ = post_stream(struct.pack("<I", 12) + b"x" * 12)
        assert status == 400 and "float32 grid" in doc["detail"]
        # Empty stream.
        status, doc, _ = post_stream(b"")
        assert status == 400 and "empty stream" in doc["detail"]
    finally:
        srv.shutdown()


def test_stream_per_frame_overload_is_an_error_line():
    """A shed frame is that frame's structured error LINE (with its
    trace id), never a dead stream: the client learns which parts to
    resubmit without losing the socket."""
    from featurenet_tpu.serve.http import make_server
    from featurenet_tpu.serve.loadgen import stream_load

    service = _fake_voxel_service(mode="overload")
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        grids = [np.full((4, 4, 4), 1.0, np.float32)] * 3
        out = stream_load("127.0.0.1", srv.server_address[1], grids)
        assert out["status"] == 200
        assert out["answered"] == 0 and out["errors"] == 3
        for i, line in enumerate(out["lines"]):
            assert line["frame"] == i
            assert line["error"] == "overload"
            assert line["retry_after_s"] == 0.05
    finally:
        srv.shutdown()


def test_stream_e2e_100_frames_one_socket_zero_compiles(
    tmp_path, rng, predictor
):
    """ISSUE 15 acceptance: ≥100 voxel frames pipelined over ONE client
    socket through a real warmed service — every frame answered with
    the reference label and its own stream-tied trace id, zero
    ``program_compile`` events after warmup, and every frame's
    admit/dispatch/done timeline in the run stream under its trace."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.serve.http import make_server
    from featurenet_tpu.serve.loadgen import stream_load

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    service = InferenceService(
        predictor, buckets=(1, 4, 16), max_wait_ms=5, queue_limit=256,
        rules=(),
    )
    events, _ = load_events(run_dir)
    compiles_at_warmup = sum(
        1 for e in events if e["ev"] == "program_compile"
    )
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        base = generate_batch(rng, 24, RES)["voxels"]
        expected, _ = predictor.predict_voxels(base)
        n_frames = 120
        grids = [base[i % len(base)] for i in range(n_frames)]
        out = stream_load("127.0.0.1", srv.server_address[1], grids,
                          trace_id="corpus-1")
        assert out["status"] == 200
        assert out["frames"] == n_frames
        assert out["answered"] == n_frames and out["errors"] == 0
        assert out["reconnects"] == 0  # one socket by construction
        traces = set()
        for i, line in enumerate(out["lines"]):
            assert line["frame"] == i
            assert line["trace"] == f"corpus-1.{i}"
            traces.add(line["trace"])
            assert line["label"] == int(expected[i % len(base)]), i
        assert len(traces) == n_frames  # every frame its OWN trace id
    finally:
        srv.shutdown()
        st = service.drain()
    obs.close_run()
    assert st["served"] >= n_frames
    events, bad = load_events(run_dir)
    assert bad == 0
    compiles_total = sum(
        1 for e in events if e["ev"] == "program_compile"
    )
    assert compiles_total == compiles_at_warmup  # ZERO post-warmup
    # The per-frame timelines are in the stream, tied to the stream id.
    done = {e["trace"] for e in events if e["ev"] == "request_done"}
    assert {f"corpus-1.{i}" for i in range(n_frames)} <= done


def test_http_404_with_body_keeps_channel_in_sync():
    """A POST to an unknown path drains its body before the 404: an
    unread body on a keep-alive channel would be parsed as the NEXT
    request's request line (channel desync)."""
    import http.client

    from featurenet_tpu.serve.http import make_server

    service = _fake_voxel_service()
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        conn.request("POST", "/predict_voxel_typo", body=b"x" * 512)
        resp = conn.getresponse()
        assert resp.status == 404
        json.loads(resp.read().decode())
        sock = conn.sock
        # The channel survives, in sync: the next request parses clean.
        conn.request("POST", "/predict_voxels", body=_voxel_body())
        resp = conn.getresponse()
        assert resp.status == 200 and conn.sock is sock
        resp.read()
        conn.close()
    finally:
        srv.shutdown()
