"""Training-loop tests: overfit, end-to-end smoke, checkpoint roundtrip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from featurenet_tpu.config import get_config
from featurenet_tpu.data.synthetic import generate_batch
from featurenet_tpu.models.featurenet import FeatureNet, tiny_arch
from featurenet_tpu.train import Trainer
from featurenet_tpu.train.state import create_state
from featurenet_tpu.train.steps import (
    make_optimizer,
    make_train_step,
)


def test_single_batch_overfit(rng):
    """Loss on one fixed batch must collapse (numeric tier, SURVEY.md §4).

    12 samples / 120 steps: small enough that the single-core CPU executes
    the loop in seconds, large enough that collapsing loss still proves the
    full fwd+bwd+opt path optimizes."""
    batch = generate_batch(rng, 12, resolution=16)
    cfg = get_config("smoke16", warmup_steps=5, total_steps=120, peak_lr=3e-3)
    model = FeatureNet(arch=tiny_arch(), dtype=jnp.float32)
    tx = make_optimizer(cfg)
    state = create_state(
        model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0)
    )
    step = jax.jit(make_train_step(model, "classify"), donate_argnums=(0,))
    rng_key = jax.random.key(1)
    first = None
    for _ in range(120):
        state, metrics = step(state, batch, rng_key)
        if first is None:
            first = float(metrics["loss"])
    final = float(metrics["loss"])
    assert final < 0.2, (first, final)
    assert float(metrics["accuracy"]) > 0.95


def test_smoke16_end_to_end(tmp_path):
    """Config-1 integration: a short run must beat chance by a clear margin
    and produce a resumable checkpoint (BASELINE.json config 1)."""
    cfg = get_config(
        "smoke16",
        total_steps=60,
        eval_every=60,
        checkpoint_every=30,
        log_every=20,
        eval_batches=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        data_workers=2,
        heartbeat_file=str(tmp_path / "heartbeat"),
    )
    trainer = Trainer(cfg)
    last = trainer.run()
    # Liveness heartbeat (train.supervisor contract): the run must have
    # touched the file at its confirmed-progress points.
    assert (tmp_path / "heartbeat").exists()
    # Chance is 1/24 ≈ 4.2%; a working pipeline clears 2.5x chance even
    # this short (measured ~20% at step 60).
    assert last["eval_accuracy"] > 2.5 / 24, last

    # Checkpoint roundtrip: a fresh Trainer resumes at the saved step with
    # identical params.
    trainer2 = Trainer(cfg)
    resumed = trainer2.resume_if_available()
    assert resumed == 60
    for a, b in zip(jax.tree_util.tree_leaves(trainer.state.params),
                    jax.tree_util.tree_leaves(trainer2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ev1 = trainer.evaluate()
    ev2 = trainer2.evaluate()
    assert ev1["accuracy"] == pytest.approx(ev2["accuracy"])


def test_steps_per_dispatch_matches_single_step(tmp_path):
    """The k-fused dispatch (make_multi_train_step) is the SAME math as k
    sequential single-step dispatches: two Trainers with identical
    seed/config but steps_per_dispatch 1 vs 3 must land on numerically
    equal params after the same number of steps (to one-ulp tolerance —
    XLA reassociates fused matmuls across step boundaries; measured max
    divergence 1.5e-8 on the Dense kernels, everything else bitwise) —
    including a non-divisible total (7 = 2 fused groups + 1 remainder
    single step) so the segment-remainder path is exercised, plus cadence
    crossings (log/checkpoint fire on dispatch boundaries with step
    semantics intact)."""
    base = dict(
        total_steps=7,
        log_every=2,
        eval_every=10**9,
        checkpoint_every=5,
        eval_batches=1,
        data_workers=1,
    )
    cfg1 = get_config("smoke16", checkpoint_dir=str(tmp_path / "a"), **base)
    cfgk = get_config("smoke16", checkpoint_dir=str(tmp_path / "b"),
                      steps_per_dispatch=3, **base)
    t1, tk = Trainer(cfg1), Trainer(cfgk)
    t1.run()
    tk.run()
    assert int(t1.state.step) == int(tk.state.step) == 7
    for a, b in zip(jax.tree_util.tree_leaves(t1.state.params),
                    jax.tree_util.tree_leaves(tk.state.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )
    for a, b in zip(jax.tree_util.tree_leaves(t1.state.opt_state),
                    jax.tree_util.tree_leaves(tk.state.opt_state)):
        np.testing.assert_allclose(
            np.asarray(a, np.float64), np.asarray(b, np.float64),
            rtol=1e-6, atol=1e-7,
        )
    # Cadence: the step-5 checkpoint boundary falls inside the second fused
    # group (steps 4-6) — it must still have been saved (on the dispatch
    # boundary, at step 6) and the final save lands at 7.
    assert tk.ckpt.latest_step() == 7


def test_hbm_resident_training(tmp_path):
    """Device-resident dataset mode: the packed train split uploads once
    (sharded P('data') over the 8-device mesh), batches are drawn on
    device (shard_map block-stratified sampling), fused k steps per
    dispatch — and the whole thing is run-to-run deterministic. Covers
    materialize_split's trim/shuffle, the hbm jit variants, and the run
    loop's no-stream branch."""
    from featurenet_tpu.data.offline import export_synthetic_cache

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=4, resolution=16)
    cfg = get_config(
        "smoke16", data_cache=cache, hbm_cache=True, steps_per_dispatch=4,
        global_batch=16, total_steps=10, log_every=5, eval_every=10**9,
        checkpoint_every=10**9, data_workers=1, augment_noise=0.01,
    )
    t = Trainer(cfg)
    last = t.run()
    assert int(t.state.step) == 10
    assert np.isfinite(last["loss"])
    t2 = Trainer(cfg)
    t2.run()
    for a, b in zip(jax.tree_util.tree_leaves(t.state.params),
                    jax.tree_util.tree_leaves(t2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_segmenter_levers_shapes(rng):
    """Round-4 seg levers: projection/coord context channels and extra
    decoder/bottleneck blocks keep the dense-output contract."""
    from featurenet_tpu.models.segmenter import FeatureNetSegmenter

    x = jnp.asarray(rng.random((2, 16, 16, 16, 1)) < 0.5, jnp.float32)
    for ctx in ("proj", "proj_coords"):
        m = FeatureNetSegmenter(
            features=(8, 16), dtype=jnp.float32, input_context=ctx,
            decoder_blocks=2, bottleneck_blocks=2,
        )
        vs = m.init({"params": jax.random.key(0)}, x, train=False)
        y = m.apply(vs, x, train=False)
        assert y.shape == (2, 16, 16, 16, 25)
        assert np.isfinite(np.asarray(y)).all()


def test_hbm_resident_seg_training(tmp_path):
    """Segment-task HBM residency: voxels + per-voxel targets resident,
    paired device rotation (augment=True), fused dispatch."""
    from featurenet_tpu.data.offline import export_seg_cache

    cache = str(tmp_path / "segc")
    export_seg_cache(cache, num_parts=24, resolution=16, num_features=2)
    cfg = get_config(
        "seg64", resolution=16, global_batch=8, data_cache=cache,
        hbm_cache=True, steps_per_dispatch=2, total_steps=4, log_every=2,
        eval_every=10**9, checkpoint_every=10**9, data_workers=1,
        seg_features=(8, 16),
    )
    t = Trainer(cfg)
    last = t.run()
    assert int(t.state.step) == 4
    assert np.isfinite(last["loss"])
    # Round-5: segment affine augmentation (paired trilinear/nearest warp
    # inside the compiled step) trains through the same path.
    aff = get_config(
        "seg64", resolution=16, global_batch=8, data_cache=cache,
        hbm_cache=True, total_steps=2, log_every=2, eval_every=10**9,
        checkpoint_every=10**9, data_workers=1, seg_features=(8, 16),
        augment_affine=True, augment_affine_prob=0.5,
        augment_translate_vox=1.0,
    )
    ta = Trainer(aff)
    last = ta.run()
    assert int(ta.state.step) == 2
    assert np.isfinite(last["loss"])


def test_bf16_master_tracks_fp32_with_eval_parity(rng):
    """Mixed-precision acceptance (ISSUE 10): the bf16_master policy —
    fp32 masters in the optimizer, bf16 working copy + bf16 gradient
    storage inside the step — must (a) start from the IDENTICAL first
    loss (the forward math is unchanged; only gradient storage moved to
    bf16), (b) track the fp32 loss trajectory within tolerance over a
    short run, (c) converge to the same overfit plateau, and (d) pass
    the int8-agreement-style prediction gate against the fp32 model on
    the same inputs (paper target >= 96.7% stays the TPU-round bar)."""
    batch = generate_batch(rng, 12, resolution=16)
    cfg = get_config("smoke16", warmup_steps=5, total_steps=120,
                     peak_lr=3e-3)
    model = FeatureNet(arch=tiny_arch())  # production bf16 compute dtype
    tx = make_optimizer(cfg)
    step = jax.jit(make_train_step(model, "classify"), donate_argnums=(0,))
    rng_key = jax.random.key(1)
    runs = {}
    for prec in ("fp32", "bf16_master"):
        state = create_state(
            model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0),
            precision=prec,
        )
        assert state.precision == prec
        losses = []
        for _ in range(100):
            state, metrics = step(state, batch, rng_key)
            losses.append(float(metrics["loss"]))
        # Masters stay fp32 under every policy — they are what persists.
        for leaf in jax.tree_util.tree_leaves(state.params):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.float32, leaf.dtype
        runs[prec] = (losses, state)
    l32, lbf = runs["fp32"][0], runs["bf16_master"][0]
    assert l32[0] == pytest.approx(lbf[0], abs=1e-3)  # same forward math
    # Trajectory tracking: bf16 gradient storage diverges slowly, never
    # wildly (measured max |delta| ~0.28 mid-descent on this seed).
    assert max(abs(a - b) for a, b in zip(l32, lbf)) < 0.6
    assert l32[-1] < 0.2 and lbf[-1] < 0.2  # both overfit
    # Eval gate, int8-agreement style: top-1 predictions of the two
    # trained models agree on the training inputs at the paper bar.
    def preds(state):
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            jnp.asarray(batch["voxels"]), train=False,
        )
        return np.asarray(jnp.argmax(logits, axis=-1))

    agreement = (preds(runs["fp32"][1])
                 == preds(runs["bf16_master"][1])).mean()
    assert agreement >= 0.967, f"cross-precision agreement {agreement}"


def test_checkpoint_cross_precision_restore(tmp_path):
    """Checkpoints persist the fp32 MASTERS under every precision policy,
    so a bf16_master run's checkpoint restores BITWISE into an fp32 run
    (and vice versa) — including the resume path when only the other
    mode's checkpoints exist, and the corrupt-latest walk-back."""
    def run_one(precision, ckpt_dir, total=2):
        cfg = get_config(
            "smoke16", train_precision=precision, total_steps=total,
            checkpoint_every=1, eval_every=10**9, log_every=10**9,
            data_workers=1, global_batch=8, eval_batches=1,
            checkpoint_dir=str(ckpt_dir),
        )
        t = Trainer(cfg)
        t.run()
        return t

    for src, dst in (("bf16_master", "fp32"), ("fp32", "bf16_master")):
        ckpt = tmp_path / f"ckpt_{src}"
        trained = run_one(src, ckpt)
        cfg2 = get_config(
            "smoke16", train_precision=dst, total_steps=2,
            checkpoint_every=1, eval_every=10**9, log_every=10**9,
            data_workers=1, global_batch=8, eval_batches=1,
            checkpoint_dir=str(ckpt),
        )
        t2 = Trainer(cfg2)
        assert t2.resume_if_available() == 2
        assert t2.state.precision == dst  # policy is the run's, not disk's
        for a, b in zip(jax.tree_util.tree_leaves(trained.state.params),
                        jax.tree_util.tree_leaves(t2.state.params)):
            assert np.asarray(a).dtype == np.float32
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Walk-back across precisions: truncate the latest (bf16_master-made)
    # step; an fp32 resume must fall back cleanly to the previous one.
    from featurenet_tpu.train.checkpoint import _step_dir

    ckpt = tmp_path / "ckpt_bf16_master"
    step2 = _step_dir(str(ckpt), 2)
    assert step2 is not None
    import os

    for dirpath, _, files in os.walk(step2):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "r+b") as fh:
                fh.truncate(os.path.getsize(p) // 2)
    cfg3 = get_config(
        "smoke16", train_precision="fp32", total_steps=2,
        checkpoint_every=1, eval_every=10**9, log_every=10**9,
        data_workers=1, global_batch=8, eval_batches=1,
        checkpoint_dir=str(ckpt),
    )
    t3 = Trainer(cfg3)
    assert t3.resume_if_available() == 1  # clean walk-back, wrong-mode disk


def test_fp16_scaled_tracks_fp32_with_eval_parity(rng):
    """fp16+loss-scaling acceptance (ISSUE 12): the fp16_scaled policy —
    fp32 masters, float16 working copy + float16 gradient storage,
    dynamic loss scaling around the backward — must track the fp32 loss
    trajectory within the bf16_master tolerance, converge to the same
    overfit plateau with the scale healthy (no terminal collapse), and
    pass the cross-precision prediction gate at the paper bar."""
    batch = generate_batch(rng, 12, resolution=16)
    cfg = get_config("smoke16", warmup_steps=5, total_steps=120,
                     peak_lr=3e-3)
    model = FeatureNet(arch=tiny_arch())  # production bf16 compute dtype
    tx = make_optimizer(cfg)
    step = jax.jit(make_train_step(model, "classify"), donate_argnums=(0,))
    rng_key = jax.random.key(1)
    runs = {}
    for prec in ("fp32", "fp16_scaled"):
        state = create_state(
            model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0),
            precision=prec,
        )
        losses = []
        for _ in range(100):
            state, metrics = step(state, batch, rng_key)
            losses.append(float(metrics["loss"]))
        for leaf in jax.tree_util.tree_leaves(state.params):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.float32, leaf.dtype  # masters
        runs[prec] = (losses, state)
    l32, l16 = runs["fp32"][0], runs["fp16_scaled"][0]
    # The f16 boundary cast double-rounds (f32→f16→bf16), so the first
    # loss is near- but not bit-identical; measured ~1.2e-3 at this seed.
    assert l32[0] == pytest.approx(l16[0], abs=5e-3)
    # Same trajectory bound the bf16_master acceptance uses (measured
    # max |delta| ~0.30 at this seed).
    assert max(abs(a - b) for a, b in zip(l32, l16)) < 0.6
    assert l32[-1] < 0.2 and l16[-1] < 0.2  # both overfit
    fin = runs["fp16_scaled"][1]
    # The scale stayed healthy end-to-end: never collapsed to the floor
    # (a run skipping every step would sit at LOSS_SCALE_MIN), and the
    # metrics stream carried it.
    from featurenet_tpu.train.precision import LOSS_SCALE_MIN

    assert float(fin.loss_scale) > LOSS_SCALE_MIN

    def preds(state):
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            jnp.asarray(batch["voxels"]), train=False,
        )
        return np.asarray(jnp.argmax(logits, axis=-1))

    agreement = (preds(runs["fp32"][1]) == preds(fin)).mean()
    assert agreement >= 0.967, f"cross-precision agreement {agreement}"


def test_loss_scale_skip_is_bitwise_and_scale_recovers(rng):
    """Loss-scaling edge cases (ISSUE 12 satellite): an overflowed
    backward — injected by forcing an absurd loss scale, the exact
    mechanism a too-high scale fails by in production — must (a) skip
    the update BITWISE (masters, optimizer slots, and BN stats keep
    their exact bits; only step and scale state move), (b) halve the
    scale, and (c) recover: subsequent steps halve until finite, then
    train normally. The growth ladder doubles after
    LOSS_SCALE_GROWTH_INTERVAL clean steps and is capped."""
    from featurenet_tpu.train.precision import (
        LOSS_SCALE_GROWTH_INTERVAL,
        LOSS_SCALE_MAX,
    )

    batch = generate_batch(rng, 8, resolution=16)
    cfg = get_config("smoke16")
    model = FeatureNet(arch=tiny_arch())
    tx = make_optimizer(cfg)
    state = create_state(
        model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0),
        precision="fp16_scaled",
    )
    step = jax.jit(make_train_step(model, "classify"))  # no donation:
    # the pre-step state must stay readable for the bitwise compare
    state, _ = step(state, batch, jax.random.key(1))  # settle one step

    inject = state.replace(loss_scale=jnp.asarray(2.0 ** 30, jnp.float32))
    before = [np.asarray(x).copy() for x in jax.tree_util.tree_leaves(
        (inject.params, inject.opt_state, inject.batch_stats))]
    after, metrics = step(inject, batch, jax.random.key(1))
    assert float(metrics["grads_finite"]) == 0.0
    assert float(after.loss_scale) == 2.0 ** 29  # halved
    assert int(after.good_steps) == 0
    assert int(after.step) == int(inject.step) + 1  # schedule advances
    for a, b in zip(before, jax.tree_util.tree_leaves(
            (after.params, after.opt_state, after.batch_stats))):
        np.testing.assert_array_equal(a, np.asarray(b))  # bitwise skip

    # Recovery: keep stepping; the scale halves until the f16 backward
    # survives, then finite steps resume (grads_finite flips to 1).
    st = after
    for _ in range(24):
        st, m = step(st, batch, jax.random.key(1))
        if float(m["grads_finite"]) == 1.0:
            break
    assert float(m["grads_finite"]) == 1.0
    assert float(st.loss_scale) < 2.0 ** 29

    # Growth: one finite step at the interval boundary doubles the scale
    # (capped at LOSS_SCALE_MAX) and resets the streak.
    primed = st.replace(
        good_steps=jnp.asarray(LOSS_SCALE_GROWTH_INTERVAL - 1, jnp.int32)
    )
    grown, m = step(primed, batch, jax.random.key(1))
    assert float(m["grads_finite"]) == 1.0
    assert float(grown.loss_scale) == min(
        float(st.loss_scale) * 2.0, LOSS_SCALE_MAX
    )
    assert int(grown.good_steps) == 0


def test_loss_scale_state_survives_checkpoint_and_cross_precision(tmp_path):
    """The skip/scale state rides TrainState: a checkpoint persists the
    adapted loss scale, restores it into a resumed fp16_scaled run, and
    round-trips UNTOUCHED through a cross-precision restore (fp16_scaled
    → fp32 and back) with the masters bitwise-equal throughout."""
    def run_one(precision, ckpt_dir, total=2):
        cfg = get_config(
            "smoke16", train_precision=precision, total_steps=total,
            checkpoint_every=1, eval_every=10**9, log_every=10**9,
            data_workers=1, global_batch=8, eval_batches=1,
            checkpoint_dir=str(ckpt_dir),
        )
        t = Trainer(cfg)
        t.run()
        return t

    ckpt = tmp_path / "ckpt_fp16"
    trained = run_one("fp16_scaled", ckpt)
    from featurenet_tpu.train.precision import LOSS_SCALE_INIT

    scale_disk = float(trained.state.loss_scale)
    assert scale_disk <= LOSS_SCALE_INIT  # init, or halved by warm-in

    # fp16_scaled → fp32: masters bitwise, scale leaf carried inert.
    cfg32 = get_config(
        "smoke16", train_precision="fp32", total_steps=2,
        checkpoint_every=1, eval_every=10**9, log_every=10**9,
        data_workers=1, global_batch=8, eval_batches=1,
        checkpoint_dir=str(ckpt),
    )
    t32 = Trainer(cfg32)
    assert t32.resume_if_available() == 2
    assert t32.state.precision == "fp32"
    assert float(t32.state.loss_scale) == scale_disk
    for a, b in zip(jax.tree_util.tree_leaves(trained.state.params),
                    jax.tree_util.tree_leaves(t32.state.params)):
        assert np.asarray(a).dtype == np.float32
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # … and back: an fp16_scaled resume gets its adapted scale, not a
    # fresh LOSS_SCALE_INIT.
    cfg16 = get_config(
        "smoke16", train_precision="fp16_scaled", total_steps=2,
        checkpoint_every=1, eval_every=10**9, log_every=10**9,
        data_workers=1, global_batch=8, eval_batches=1,
        checkpoint_dir=str(ckpt),
    )
    t16 = Trainer(cfg16)
    assert t16.resume_if_available() == 2
    assert t16.state.precision == "fp16_scaled"
    assert float(t16.state.loss_scale) == scale_disk
    for a, b in zip(jax.tree_util.tree_leaves(trained.state.params),
                    jax.tree_util.tree_leaves(t16.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fp16_scaled_run_recovers_from_injected_overflow(tmp_path):
    """fp16 e2e (ISSUE 12 acceptance): a Trainer run whose loss scale is
    forced into overflow territory mid-flight skips the poisoned step,
    halves its way back to a survivable scale, and still completes its
    full step budget with a finite loss."""
    cfg = get_config(
        "smoke16", train_precision="fp16_scaled", total_steps=8,
        eval_every=10**9, checkpoint_every=10**9, log_every=2,
        data_workers=1, global_batch=8, eval_batches=1,
        run_dir=str(tmp_path / "run"),
    )
    t = Trainer(cfg)
    t.state = t.state.replace(
        loss_scale=jnp.asarray(2.0 ** 30, jnp.float32)
    )
    last = t.run()
    assert int(t.state.step) == 8
    assert np.isfinite(last["loss"])
    # The injected scale is gone: at least one halving happened and the
    # run ended at a survivable scale.
    assert float(t.state.loss_scale) < 2.0 ** 30


def test_membytes_master_split_vs_measured_peak():
    """Satellite (ISSUE 10): the HBM byte model knows the master/working
    split — bf16_master costs masters(4)+working(2)+grads(2+4) vs fp32's
    params(4)+grads(4) — and the analytic fused-step estimate brackets
    the executable's own measured peak (conservative: the clamp must
    over-, never under-estimate on the calibrated side)."""
    from featurenet_tpu.ops.membytes import fused_step_bytes, state_bytes
    from featurenet_tpu.runtime import Runtime
    from featurenet_tpu.train.state import param_count

    n = 1_000_000
    assert state_bytes(n, "adamw", "fp32") == n * 16
    assert state_bytes(n, "adamw", "bf16_master") == n * 20
    assert state_bytes(n, "sgd", "bf16_master") == n * 16
    # fp16_scaled shares the split byte-for-byte (f16 == bf16 == 2 bytes;
    # the loss-scale state is two scalars, not a term).
    assert state_bytes(n, "adamw", "fp16_scaled") == n * 20

    measured = {}
    for prec in ("fp32", "bf16_master"):
        cfg = get_config("smoke16", train_precision=prec)
        rt = Runtime(cfg, cache=None)
        prog = rt.build("train_step")
        params_n = param_count(rt.abstract_state.params)
        est = fused_step_bytes(cfg, 1, params_n)
        measured[prec] = (est, prog.cost.get("peak_bytes"))
    est32, peak32 = measured["fp32"]
    est16, peak16 = measured["bf16_master"]
    # The split raises the analytic state term by exactly 4 bytes/param.
    assert est16 - est32 == 4 * param_count(
        Runtime(get_config("smoke16"), cache=None).abstract_state.params
    )
    if peak32 is None or peak16 is None:
        pytest.skip("backend reports no memory analysis")
    # First-order honesty band against XLA's own buffer assignment:
    # conservative (>= measured) but within 4x of it, both precisions.
    for est, peak in ((est32, peak32), (est16, peak16)):
        assert peak <= est <= 4 * peak, (est, peak)


def test_dispatch_k_membytes_model():
    """ops/membytes reproduces the measured round-4/5 dispatch decisions:
    the combined seg64 model cannot fuse dispatches (XLA memory_analysis
    measured temp 14.70 G at k=2 against the 15.75 G budget) while the 64³
    classify flagships fuse k=8 with ~4× headroom. Params/rows pinned to
    the calibration probe's values (membytes docstring table)."""
    from featurenet_tpu.ops.membytes import fused_step_bytes, max_feasible_k

    seg = get_config("seg64", data_cache="x", hbm_cache=True,
                     steps_per_dispatch=8)
    assert max_feasible_k(seg, params_n=3_837_113, n_rows=3840) == 1
    warp = get_config("warp64", data_cache="x", hbm_cache=True,
                      steps_per_dispatch=8)
    assert max_feasible_k(warp, params_n=4_402_424, n_rows=19200) == 8
    # First-order accuracy: the analytic estimate must stay within ±30% of
    # XLA's own buffer assignment on both calibration points, or the clamp
    # decisions above are luck, not model.
    seg_measured = 13.16e9 + 1.185e9  # temp(k=1) + args
    est = fused_step_bytes(seg, 1, params_n=3_837_113, n_rows=3840)
    assert abs(est - seg_measured) / seg_measured < 0.30
    warp_measured = 1.817e9 + 0.685e9  # temp(k=8) + args
    est = fused_step_bytes(warp, 8, params_n=4_402_424, n_rows=19200)
    assert abs(est - warp_measured) / warp_measured < 0.60  # conservative


def test_trainer_clamps_dispatch_k(monkeypatch, capsys):
    """The Trainer degrades steps_per_dispatch against the byte model with
    a logged warning instead of letting the fused executable OOM — the
    clamp_model_axis pattern applied to dispatch fusion."""
    from featurenet_tpu.ops import membytes

    monkeypatch.setattr(membytes, "HBM_BYTES", 1e6)  # nothing >k=1 fits
    cfg = get_config("smoke16", steps_per_dispatch=4, total_steps=4,
                     data_workers=1, eval_batches=1)
    t = Trainer(cfg)
    assert t._k == 1
    assert "dispatch_warning" in capsys.readouterr().err


def test_recalibrate_bn(tmp_path):
    """BN recalibration: clean-stream forwards move only batch_stats;
    the CLI writes a restorable new checkpoint at the same step."""
    from featurenet_tpu.cli import main as cli_main

    src = str(tmp_path / "src")
    cfg = get_config(
        "smoke16", total_steps=2, eval_every=10**9, checkpoint_every=2,
        log_every=1, data_workers=1, eval_batches=1, checkpoint_dir=src,
    )
    t = Trainer(cfg)
    t.run()
    params_before = [np.asarray(x) for x in
                     jax.tree_util.tree_leaves(t.state.params)]
    stats_before = [np.asarray(x) for x in
                    jax.tree_util.tree_leaves(t.state.batch_stats)]
    t.recalibrate_bn(batches=3)
    for a, b in zip(params_before,
                    jax.tree_util.tree_leaves(t.state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(stats_before,
                        jax.tree_util.tree_leaves(t.state.batch_stats))
    )
    out = str(tmp_path / "recal")
    cli_main(["recalibrate", "--checkpoint-dir", src, "--out-dir", out,
              "--batches", "2"])
    restored = Trainer(get_config(
        "smoke16", data_workers=1, eval_batches=1, checkpoint_dir=out,
    ))
    assert restored.resume_if_available() == 2


def test_measure_e2e_smoke():
    """The e2e wall-clock benchmark runs the Trainer's own dispatch path
    and returns a positive rate with in-artifact spread (CPU, tiny)."""
    from featurenet_tpu.benchmark import measure_e2e

    cfg = get_config("smoke16", global_batch=8, data_workers=1,
                     eval_batches=1)
    out = measure_e2e(cfg, steps=4, warmup=2, repeats=2)
    assert out["e2e_samples_per_sec"] > 0
    assert out["e2e_spread_pct"] >= 0
    assert out["steps"] == 4 and not out["hbm_resident"]


def test_hbm_cache_config_guards():
    """hbm_cache misconfiguration fails at validate time, not mid-run."""
    with pytest.raises(ValueError, match="data_cache"):
        get_config("pod64", hbm_cache=True)
    with pytest.raises(ValueError, match="spatial"):
        get_config("pod64", data_cache="x", hbm_cache=True, spatial=True,
                   mesh_model=2)
    # augment=True without the device path would be silently ignored (the
    # resident dataset has no host augmentation) — must refuse instead.
    with pytest.raises(ValueError, match="augment"):
        get_config("pod64", data_cache="x", hbm_cache=True,
                   augment_device=False)
    # augment_affine without active device augmentation would be silently
    # ignored (synthetic streaming / --no-augment) — must refuse.
    with pytest.raises(ValueError, match="silently ignored"):
        get_config("warp64", augment_affine=True)
    # augment_noise is a probability, not a percentage.
    with pytest.raises(ValueError, match="bit-flip"):
        get_config("pod64", augment_noise=5.0)


def test_eval_deterministic():
    cfg = get_config("smoke16", total_steps=1, eval_batches=2)
    trainer = Trainer(cfg)
    e1 = trainer.evaluate()
    e2 = trainer.evaluate()
    assert e1 == e2


def test_segmentation_step_runs(rng):
    """seg64 path at toy scale: loss finite and decreasing-ish."""
    from featurenet_tpu.models.segmenter import FeatureNetSegmenter

    batch = generate_batch(rng, 4, resolution=16, num_features=2)
    cfg = get_config("seg64", resolution=16, global_batch=4,
                     warmup_steps=2, total_steps=30)
    model = FeatureNetSegmenter(features=(8, 16), dtype=jnp.float32)
    tx = make_optimizer(cfg)
    state = create_state(
        model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0)
    )
    step = jax.jit(make_train_step(model, "segment"), donate_argnums=(0,))
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch, jax.random.key(1))
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_tensorboard_events(tmp_path):
    """tb_dir writes TB event files alongside the JSON-lines stream."""
    import os

    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    cfg = get_config(
        "smoke16", total_steps=4, log_every=2, eval_every=10**9,
        checkpoint_every=10**9, data_workers=1, global_batch=8,
        tb_dir=str(tmp_path / "tb"),
    )
    Trainer(cfg).run()
    files = os.listdir(tmp_path / "tb")
    assert any("tfevents" in f for f in files), files


def test_segmentation_loss_variants():
    """Dice variants: ~0 on perfect predictions, positive and finite on
    wrong ones, unknown variant refused."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from featurenet_tpu.train.steps import segmentation_loss

    rng = np.random.default_rng(0)
    seg = jnp.asarray(rng.integers(0, 3, size=(2, 4, 4, 4)), jnp.int32)
    perfect = jax.nn.one_hot(seg, 4) * 50.0  # near-delta softmax
    wrong = jax.nn.one_hot((seg + 1) % 3, 4) * 50.0
    for variant in ("balanced_ce", "ce_dice", "dice"):
        lp, _ = segmentation_loss(perfect, seg, variant=variant)
        lw, _ = segmentation_loss(wrong, seg, variant=variant)
        assert float(lp) < 0.05, (variant, float(lp))
        assert float(lw) > 0.5, (variant, float(lw))
        g = jax.grad(
            lambda lo: segmentation_loss(lo, seg, variant=variant)[0]
        )(wrong)
        assert np.isfinite(np.asarray(g)).all()
    with pytest.raises(ValueError, match="variant"):
        segmentation_loss(perfect, seg, variant="nope")


@pytest.fixture
def no_persistent_compile_cache():
    """Disable the persistent compilation cache for tests that build a
    SECOND Trainer over identical computations in one process: the rebuilt
    jits then execute executables DESERIALIZED from the cache, and the
    AOT loader's machine-feature mismatch (documented in conftest.py as
    log noise) can escalate to a fatal process abort in this sandbox.
    The enable flag is only consulted when the cache object initializes,
    so it must be paired with reset_cache() to take effect mid-process."""
    from jax._src import compilation_cache as cc

    jax.config.update("jax_enable_compilation_cache", False)
    cc.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", True)
    cc.reset_cache()


def test_trainer_planned_restart_segments(tmp_path,
                                          no_persistent_compile_cache):
    """restart_every_steps: the run stops at the segment boundary with a
    checkpoint exactly there and SystemExit(RESTART_EXIT_CODE); resuming
    continues to completion."""
    import pytest

    from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE

    cfg = get_config(
        "smoke16",
        total_steps=5,
        restart_every_steps=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=10**9,
        eval_every=10**9,
        log_every=10**9,
        data_workers=1,
        global_batch=8,
    )
    t = Trainer(cfg)
    with pytest.raises(SystemExit) as e:
        t.run()
    assert e.value.code == RESTART_EXIT_CODE
    assert t.ckpt.latest_step() == 2

    t2 = Trainer(cfg)
    with pytest.raises(SystemExit):
        t2.run()  # 2 -> 4
    assert t2.ckpt.latest_step() == 4

    t3 = Trainer(cfg)
    last = t3.run()  # 4 -> 5: finishes, no exit
    assert int(t3.state.step) == 5
    assert "loss" in last


def test_converged_slope_protocol():
    """The shared slope protocol: window floors at ~min_window_sec of
    device work, contaminated (non-positive) draws are dropped, the
    headline is the MEAN of the two agreeing best draws (not the min),
    and both spread views are reported."""
    from featurenet_tpu.benchmark import _converged_slope

    # Fake device: 10 ms/call, with one stalled short-probe draw (walled(1)
    # slower than walled(N+1) -> negative slope) injected first.
    calls = {"n": 0}

    def walled(k):
        calls["n"] += 1
        if calls["n"] == 3:  # first measurement draw's short probe stalls
            return 10.0
        return 0.010 * k

    out = _converged_slope(walled, measure=20, repeats=2,
                           min_window_sec=1.0)
    # Window grew to ~1 s of 10 ms calls.
    assert out["window_calls"] >= 100
    assert abs(out["per_call"] - 0.010) / 0.010 < 0.01
    assert out["spread_pct"] <= 3.0
    assert out["spread_minmax_pct"] >= out["spread_pct"]

    # Two clean draws with slightly different rates: headline is their
    # mean, not the min.
    rates = iter([0.010, 0.010, 0.010, 0.0102] * 50)

    def walled2(k):
        return next(rates) * k

    out2 = _converged_slope(walled2, measure=10, repeats=2,
                            min_window_sec=0.0)
    assert out2["per_call"] > 0.010  # min would be exactly 0.010

    def always_stalled(k):
        return 1.0 if k == 1 else 0.5

    with pytest.raises(RuntimeError, match="contaminated"):
        _converged_slope(always_stalled, measure=5, repeats=2,
                         min_window_sec=0.0)


def test_measure_train_step_rejects_segment_config():
    """benchmark.measure_train_step builds a classifier on the classify wire
    format unconditionally — a segment config must be refused, not silently
    benchmarked as the wrong model (round-2 advice)."""
    import pytest

    from featurenet_tpu.benchmark import measure_train_step
    from featurenet_tpu.config import get_config

    with pytest.raises(ValueError, match="classify"):
        measure_train_step(get_config("seg64"))


def test_seg_diagnose_confusion_math():
    """Family detection, collapse, and IoU-from-confusion on a hand-built
    voxel confusion matrix (4 labels: stock + 3 classes; classes 2 and 3
    confuse both ways above threshold, class 1 is clean)."""
    import numpy as np

    from featurenet_tpu.train.seg_diagnose import (
        _collapse,
        _families,
        _mean_iou_from_confusion,
    )

    conf = np.array([
        [100, 0, 0, 0],
        [0, 50, 0, 0],
        [0, 0, 40, 10],   # 20% of true-2 predicted 3
        [0, 0, 15, 35],   # 30% of true-3 predicted 2
    ], dtype=np.int64)
    fams = _families(conf, threshold=0.1)
    assert fams == [[2, 3]]
    miou, iou = _mean_iou_from_confusion(conf)
    # class 2: inter 40, union 50+55-40=65; class 3: 35 / (50+45-35)=60
    np.testing.assert_allclose(iou[2], 40 / 65)
    np.testing.assert_allclose(iou[3], 35 / 60)
    collapsed = _collapse(conf, fams)
    assert collapsed.shape == (3, 3)
    m2, iou2 = _mean_iou_from_confusion(collapsed)
    np.testing.assert_allclose(iou2[-1], 1.0)  # merged family is exact
    assert m2 > miou
    # Classes below threshold stay separate.
    assert _families(conf, threshold=0.5) == []
    # Two disjoint families: the mapping-based collapse must merge each
    # family's own members (the positional-deletion scheme it replaced
    # merged the wrong classes for every family after the first).
    conf2 = np.zeros((6, 6), np.int64)
    np.fill_diagonal(conf2, 50)
    conf2[1, 2] = 20
    conf2[2, 1] = 15
    conf2[4, 5] = 20
    conf2[5, 4] = 25
    fams2 = _families(conf2, threshold=0.1)
    assert fams2 == [[1, 2], [4, 5]]
    out2 = _collapse(conf2, fams2)
    assert out2.shape == (4, 4)
    _, iou_all = _mean_iou_from_confusion(out2)
    np.testing.assert_allclose(iou_all, 1.0)
