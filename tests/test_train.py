"""Training-loop tests: overfit, end-to-end smoke, checkpoint roundtrip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from featurenet_tpu.config import get_config
from featurenet_tpu.data.synthetic import generate_batch
from featurenet_tpu.models.featurenet import FeatureNet, tiny_arch
from featurenet_tpu.train import Trainer
from featurenet_tpu.train.state import create_state
from featurenet_tpu.train.steps import (
    make_optimizer,
    make_train_step,
)


def test_single_batch_overfit(rng):
    """Loss on one fixed batch must collapse (numeric tier, SURVEY.md §4)."""
    batch = generate_batch(rng, 24, resolution=16)
    cfg = get_config("smoke16", warmup_steps=5, total_steps=150, peak_lr=3e-3)
    model = FeatureNet(arch=tiny_arch(), dtype=jnp.float32)
    tx = make_optimizer(cfg)
    state = create_state(
        model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0)
    )
    step = jax.jit(make_train_step(model, "classify"), donate_argnums=(0,))
    rng_key = jax.random.key(1)
    first = None
    for _ in range(150):
        state, metrics = step(state, batch, rng_key)
        if first is None:
            first = float(metrics["loss"])
    final = float(metrics["loss"])
    assert final < 0.2, (first, final)
    assert float(metrics["accuracy"]) > 0.95


def test_smoke16_end_to_end(tmp_path):
    """Config-1 integration: a short run must beat chance by a clear margin
    and produce a resumable checkpoint (BASELINE.json config 1)."""
    cfg = get_config(
        "smoke16",
        total_steps=120,
        eval_every=120,
        checkpoint_every=60,
        log_every=40,
        eval_batches=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        data_workers=2,
        heartbeat_file=str(tmp_path / "heartbeat"),
    )
    trainer = Trainer(cfg)
    last = trainer.run()
    # Liveness heartbeat (train.supervisor contract): the run must have
    # touched the file at its confirmed-progress points.
    assert (tmp_path / "heartbeat").exists()
    # Chance is 1/24 ≈ 4.2%; a working pipeline clears 3x chance even this short.
    assert last["eval_accuracy"] > 3 / 24, last

    # Checkpoint roundtrip: a fresh Trainer resumes at the saved step with
    # identical params.
    trainer2 = Trainer(cfg)
    resumed = trainer2.resume_if_available()
    assert resumed == 120
    for a, b in zip(jax.tree_util.tree_leaves(trainer.state.params),
                    jax.tree_util.tree_leaves(trainer2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ev1 = trainer.evaluate()
    ev2 = trainer2.evaluate()
    assert ev1["accuracy"] == pytest.approx(ev2["accuracy"])


def test_eval_deterministic():
    cfg = get_config("smoke16", total_steps=1, eval_batches=2)
    trainer = Trainer(cfg)
    e1 = trainer.evaluate()
    e2 = trainer.evaluate()
    assert e1 == e2


def test_segmentation_step_runs(rng):
    """seg64 path at toy scale: loss finite and decreasing-ish."""
    from featurenet_tpu.models.segmenter import FeatureNetSegmenter

    batch = generate_batch(rng, 4, resolution=16, num_features=2)
    cfg = get_config("seg64", resolution=16, global_batch=4,
                     warmup_steps=2, total_steps=30)
    model = FeatureNetSegmenter(features=(8, 16), dtype=jnp.float32)
    tx = make_optimizer(cfg)
    state = create_state(
        model, tx, jnp.asarray(batch["voxels"]), jax.random.key(0)
    )
    step = jax.jit(make_train_step(model, "segment"), donate_argnums=(0,))
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch, jax.random.key(1))
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_tensorboard_events(tmp_path):
    """tb_dir writes TB event files alongside the JSON-lines stream."""
    import os

    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    cfg = get_config(
        "smoke16", total_steps=4, log_every=2, eval_every=10**9,
        checkpoint_every=10**9, data_workers=1, global_batch=8,
        tb_dir=str(tmp_path / "tb"),
    )
    Trainer(cfg).run()
    files = os.listdir(tmp_path / "tb")
    assert any("tfevents" in f for f in files), files
