"""Input-pipeline tests: prefetch termination, errors, determinism, sharding."""

import numpy as np
import pytest

from featurenet_tpu.data import SyntheticVoxelDataset, prefetch_to_device


def test_finite_iterator_terminates():
    batches = [{"x": np.full((2,), i)} for i in range(5)]
    got = list(prefetch_to_device(iter(batches)))
    assert len(got) == 5
    np.testing.assert_array_equal(got[3]["x"], batches[3]["x"])


def test_producer_exception_propagates():
    def bad_gen():
        yield {"x": np.zeros(1)}
        raise RuntimeError("boom")

    it = prefetch_to_device(bad_gen())
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_multiworker_deterministic():
    def take(n, workers):
        ds = SyntheticVoxelDataset(resolution=16, global_batch=4, seed=11)
        it = prefetch_to_device(ds, num_workers=workers)
        return [next(it)["label"] for _ in range(n)]

    a = take(6, 3)
    b = take(6, 3)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_multiworker_interleave_matches_worker_streams():
    # Ticket residue classes: batch k comes from worker k % W's stream.
    ds = SyntheticVoxelDataset(resolution=16, global_batch=4, seed=5)
    W = 2
    it = prefetch_to_device(ds, num_workers=W)
    merged = [next(it)["voxels"] for _ in range(4)]
    # Batch k comes from worker (k % W)'s independent stream.
    s0 = next(ds.worker_iter(0, W))
    s1 = next(ds.worker_iter(1, W))
    np.testing.assert_array_equal(merged[0], s0["voxels"])
    np.testing.assert_array_equal(merged[1], s1["voxels"])


def test_host_sharding_decorrelated():
    a = next(iter(SyntheticVoxelDataset(resolution=16, global_batch=8, num_hosts=2, host_id=0, seed=3)))
    b = next(iter(SyntheticVoxelDataset(resolution=16, global_batch=8, num_hosts=2, host_id=1, seed=3)))
    assert a["voxels"].shape[0] == 4
    assert not np.array_equal(a["voxels"], b["voxels"])


def test_device_put_with_sharding():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("data",))
    sharding = NamedSharding(mesh, P("data"))
    ds = SyntheticVoxelDataset(resolution=16, global_batch=8, seed=0)
    it = prefetch_to_device(
        ds,
        sharding={"voxels": NamedSharding(mesh, P("data")),
                  "label": sharding,
                  "seg": sharding,
                  "mask": sharding},
    )
    batch = next(it)
    shards = batch["voxels"].addressable_shards
    assert len(shards) == 4
    assert shards[0].data.shape == (2, 16, 16, 16, 1)
    assert batch["label"].addressable_shards[0].data.shape == (2,)
