"""Incident plane (ISSUE 20): alert-triggered diagnostic bundles
(``obs.incidents``) + the host-side thread-stack sampler
(``obs.stacksampler``).

Unit coverage: the sampler (folded stacks with thread names, the
render/parse round-trip, the hard wall-clock deadline keeping a partial
profile), the manager's flap damping + cooldown through the REAL alert
funnel (``alerts.fire`` → event tap), one-shot gate-regression and
replica-loss-storm incidents, bundle contents and the atomic manifest,
the go-dark discipline on bundle-write failure, degraded bundles (torn
manifest, pruned pieces) rendering with a named ``missing`` section,
oldest-first pruning, the ``alerts_active`` tsdb mirror, the
``incidents_open`` /metrics gauge, the report's incidents section, the
dash incidents line (friendly empty state included), and the CLI
surfacing. The real-fleet acceptance e2e rides
``test_fleet.test_fleet_e2e_burn_rate_scrape_alert_and_dash``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from types import SimpleNamespace

import pytest

from featurenet_tpu import obs
from featurenet_tpu.obs import alerts as _alerts
from featurenet_tpu.obs import events as _events
from featurenet_tpu.obs import incidents, stacksampler, tracing
from featurenet_tpu.obs import tsdb as _tsdb

RULE = _alerts.AlertRule("serving_p99_ms", ">", 50.0, "critical")


def _fire(value: float = 123.0, window: int = 1, state: str = "fire",
          rule=RULE) -> None:
    """Drive the manager through the REAL funnel: threshold and burn
    rules both land on ``alerts.fire``, which emits the ``alert`` event
    the tap dispatches on."""
    _alerts.fire(rule, value, window, state=state)


def _wait(pred, timeout_s: float = 10.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while not pred():
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        time.sleep(0.02)


def _wait_captured(run_dir: str, incident_id: str,
                   timeout_s: float = 15.0) -> dict:
    """Until the capture thread has written the full bundle (the
    manifest's ``files`` inventory is the capture-done marker)."""

    def done():
        b = incidents.load_bundle(run_dir, incident_id)
        return bool((b["manifest"] or {}).get("files"))

    _wait(done, timeout_s, f"capture of {incident_id}")
    return incidents.load_bundle(run_dir, incident_id)


# --- the stack sampler -------------------------------------------------------

def test_stacksampler_names_threads_and_folds():
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(100))

    th = threading.Thread(target=spin, name="busy-loop", daemon=True)
    th.start()
    try:
        profile = stacksampler.sample_stacks(0.25, hz=100.0)
    finally:
        stop.set()
        th.join()
    assert profile["samples"] > 0 and profile["ticks"] > 0
    assert not profile["truncated"]
    totals = stacksampler.thread_totals(profile["folded"])
    assert "busy-loop" in totals, totals
    # The sampler never profiles itself (the calling thread).
    assert "MainThread" not in totals, totals
    # Folded frames are outermost-first ;-joined file:func entries.
    busy = [s for s in profile["folded"] if s.startswith("busy-loop;")]
    assert busy and any("spin" in s for s in busy), busy


def test_stacksampler_render_parse_roundtrip():
    folded = {"a;x.py:f;y.py:g": 7, "b;z.py:h": 2}
    text = stacksampler.render_folded(
        {"folded": folded, "samples": 9, "ticks": 9,
         "duration_s": 1.0, "truncated": False}
    )
    # Count-descending "stack count" lines — the flamegraph idiom.
    lines = text.strip().splitlines()
    assert lines[0].endswith(" 7") and lines[1].endswith(" 2")
    assert stacksampler.parse_folded(text) == folded
    # Tolerant parse: junk lines are skipped, not raised on.
    assert stacksampler.parse_folded("garbage\n" + text) == folded
    assert stacksampler.thread_totals(folded) == {"a": 7, "b": 2}


def test_stacksampler_hard_deadline_keeps_partial_profile():
    # A 5 s profile against a 0.2 s wall: the sampler must stop AT the
    # deadline and keep what it has, marked truncated — the recovery-
    # matrix row for a sampler overrun.
    t0 = time.monotonic()
    profile = stacksampler.sample_stacks(5.0, hz=50.0, max_wall_s=0.2)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, elapsed
    assert profile["truncated"]
    assert profile["duration_s"] < 5.0


# --- manager: open/close through the alert funnel ----------------------------

def test_incident_lifecycle_flap_damping_and_cooldown(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    mgr = incidents.arm(run_dir, cooldown_s=0.4, sample_s=0.05)
    assert incidents.arm(run_dir) is mgr  # idempotent per run_dir

    _fire(state="fire")
    assert mgr.open_count() == 1
    assert tracing.force_all()  # incident mode: every request sampled
    (inc_id,) = mgr.open_ids()
    # A second fire of the SAME rule while open never opens another.
    _fire(value=200.0, window=2, state="fire")
    assert mgr.open_count() == 1
    b = _wait_captured(run_dir, inc_id)
    man = b["manifest"]
    assert man["rule"] == "serving_p99_ms"
    assert man["severity"] == "critical"
    assert man["value"] == 123.0 and man["threshold"] == 50.0
    assert man["state"] == "open" and man["pid"] == os.getpid()
    assert set(man["files"]) >= {"tsdb.json", "windows.json",
                                 "events_tail.jsonl", "stacks.folded"}
    # Resolve closes with a real duration and drops force-sampling.
    _fire(value=1.0, window=3, state="resolve")
    assert mgr.open_count() == 0
    assert not tracing.force_all()
    entry = [e for e in incidents.list_incidents(run_dir)
             if e["id"] == inc_id][0]
    assert entry["state"] == "closed" and entry["duration_s"] >= 0.0
    # Cooldown: an immediate re-fire is damped...
    _fire(state="fire")
    assert mgr.open_count() == 0
    assert mgr.stats()["opened_total"] == 1
    # ...and after the cooldown the same rule may open again.
    time.sleep(0.45)
    _fire(state="fire")
    assert mgr.open_count() == 1
    _fire(state="resolve")
    incidents.disarm(mgr)
    assert len(incidents.list_incidents(run_dir)) == 2
    # The incident lifecycle joined the event stream.
    from featurenet_tpu.obs.report import load_events

    events, bad = load_events(run_dir)
    assert bad == 0
    kinds = [e["ev"] for e in events]
    assert kinds.count("incident_open") == 2
    assert kinds.count("incident_close") == 2
    assert "incident_capture" in kinds
    obs.close_run()


def test_incident_bundle_contents(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    # Seed the store and a membership roster so the bundle has both.
    store = _tsdb.TimeSeriesStore.open(run_dir)
    for i in range(10):
        store.append("serving_ms", 100.0 + i, {"q": "0.99", "replica": "0"})
    store.close()
    from featurenet_tpu.elastic.membership import (
        Membership,
        write_membership,
    )

    write_membership(run_dir, Membership(
        generation=3, members=(0, 1), min_world_size=1, reason="test",
    ))
    for _ in range(40):
        obs.emit("probe", n=1)  # something for the events tail
    mgr = incidents.arm(run_dir, sample_s=0.1, lookback_s=300.0)
    _fire(state="fire")
    (inc_id,) = mgr.open_ids()
    b = _wait_captured(run_dir, inc_id)
    assert b["missing"] == []
    # tsdb slice: the seeded series, samples included, bounded lookback.
    assert b["tsdb"]["lookback_s"] == 300.0
    (series,) = [s for s in b["tsdb"]["series"]
                 if s["metric"] == "serving_ms"]
    assert len(series["samples"]) == 10
    # roster verbatim; events tail re-tagged with its stream.
    assert b["roster"]["generation"] == 3
    tails = {r["stream"] for r in b["events_tail"]}
    assert tails == {"events.jsonl"}
    assert any(r["ev"] == "probe" for r in b["events_tail"])
    # stacks: folded, thread-named (the capture thread samples, so the
    # test's main thread IS visible here).
    totals = stacksampler.thread_totals(b["stacks"])
    assert totals, b["stacks"]
    man = b["manifest"]
    assert man["capture"]["stack_samples"] == sum(b["stacks"].values())
    _fire(state="resolve")
    incidents.disarm(mgr)
    # The rendered post-mortem holds every section, no missing line.
    text = incidents.format_incident(
        incidents.load_bundle(run_dir, inc_id))
    assert inc_id in text and "tsdb slice: " in text
    assert "roster: 2 member(s)" in text
    assert "events tail: " in text and "stacks: " in text
    assert "missing:" not in text
    obs.close_run()


def test_one_shot_gate_regression_and_loss_storm(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    mgr = incidents.arm(run_dir, sample_s=0.05)
    # The supervisor's gate_regression phase (its own standalone sink
    # still routes through EventSink.emit, where the tap lives).
    obs.emit("supervisor", phase="gate_regression",
             failed=["mfu", "value"])
    _wait(lambda: mgr.open_count() == 0 and mgr.stats()["opened_total"] == 1,
          what="gate_regression capture+self-close")
    (entry,) = incidents.list_incidents(run_dir)
    assert entry["rule"] == "gate_regression"
    assert entry["one_shot"] and entry["state"] == "closed"
    b = incidents.load_bundle(run_dir, entry["id"])
    assert b["manifest"]["failed"] == ["mfu", "value"]
    assert "one-shot capture" in incidents.format_incident(b)
    # Replica-loss storm: two losses are business as usual...
    obs.emit("fleet_replica_loss", slot=0, inflight=0)
    obs.emit("fleet_replica_loss", slot=1, inflight=0)
    assert mgr.stats()["opened_total"] == 1
    # ...the third inside the window is a correlated failure.
    obs.emit("fleet_replica_loss", slot=0, inflight=0)
    _wait(lambda: mgr.stats()["opened_total"] == 2 and mgr.open_count() == 0,
          what="storm capture+self-close")
    storm = [e for e in incidents.list_incidents(run_dir)
             if e["rule"] == "replica_loss_storm"]
    assert len(storm) == 1 and storm[0]["value"] == 3.0
    incidents.disarm(mgr)
    obs.close_run()


def test_manager_goes_dark_on_bundle_write_failure(tmp_path, capsys):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    # <run_dir>/incidents is a FILE: every bundle makedirs fails — the
    # ENOSPC shape without faking ENOSPC.
    with open(incidents.incidents_dir(run_dir), "w") as fh:
        fh.write("not a directory")
    mgr = incidents.arm(run_dir, sample_s=0.05)
    _fire(state="fire")
    _wait(lambda: mgr.stats()["dark"], what="go-dark transition")
    st = mgr.stats()
    assert st["dropped"] >= 1
    # One stderr warning, JSON like the sink's.
    err = capsys.readouterr().err
    warn = [ln for ln in err.splitlines() if "incident_error" in ln]
    assert len(warn) == 1 and json.loads(warn[0])["dir"] == mgr.dir
    # Dark: later fires drop silently, resolve doesn't raise, and the
    # serving path never noticed (nothing above raised).
    _fire(state="resolve")
    time.sleep(0.45)
    _fire(state="fire")
    assert mgr.stats()["opened_total"] == 1
    incidents.disarm(mgr)
    obs.close_run()


def test_bundle_pruning_keeps_newest(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    mgr = incidents.arm(run_dir, cooldown_s=0.0, sample_s=0.02,
                        max_bundles=2)
    for i in range(4):
        _fire(value=100.0 + i, window=i, state="fire")
        (inc_id,) = mgr.open_ids()
        _wait_captured(run_dir, inc_id)
        _fire(window=i, state="resolve")
        time.sleep(0.002)  # distinct epoch-ms ids
    incidents.disarm(mgr)
    kept = incidents.list_incidents(run_dir)
    assert len(kept) == 2, kept
    # Ids sort chronologically; the two NEWEST survive.
    assert kept[-1]["value"] == 103.0
    obs.close_run()


# --- degraded bundles (satellite: damage renders, never tracebacks) ----------

def _one_closed_incident(run_dir: str) -> str:
    mgr = incidents.arm(run_dir, sample_s=0.05)
    _fire(state="fire")
    (inc_id,) = mgr.open_ids()
    _wait_captured(run_dir, inc_id)
    _fire(state="resolve")
    incidents.disarm(mgr)
    return inc_id


def test_degraded_bundles_name_whats_missing(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    inc_id = _one_closed_incident(run_dir)
    obs.close_run()
    bundle = os.path.join(incidents.incidents_dir(run_dir), inc_id)
    # Torn manifest (half a JSON object), pruned tsdb slice, vanished
    # stacks: the three damage shapes of a crashed/pruned capture.
    with open(os.path.join(bundle, "manifest.json"), "w") as fh:
        fh.write('{"id": "torn...')
    os.unlink(os.path.join(bundle, "tsdb.json"))
    os.unlink(os.path.join(bundle, "stacks.folded"))
    b = incidents.load_bundle(run_dir, inc_id)
    assert "manifest.json (torn/unparseable JSON)" in b["missing"]
    assert "tsdb.json (absent)" in b["missing"]
    assert "stacks.folded (absent)" in b["missing"]
    # The list survives too: a damaged manifest is a named state.
    (entry,) = incidents.list_incidents(run_dir)
    assert entry["state"] == "damaged"
    # And the CLI renders the post-mortem NAMING the damage — exit 0,
    # no traceback.
    cli_main(["incident", "show", run_dir, inc_id])
    out = capsys.readouterr().out
    assert "missing:" in out
    assert "tsdb.json (absent)" in out
    assert "manifest.json (torn/unparseable JSON)" in out
    cli_main(["incident", "list", run_dir])
    assert "state=damaged" in capsys.readouterr().out


def test_cli_incident_empty_and_unknown(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main

    run_dir = str(tmp_path / "empty")
    os.makedirs(run_dir)
    cli_main(["incident", "list", run_dir])
    assert "no incident bundles" in capsys.readouterr().out
    with pytest.raises(SystemExit, match="no bundles"):
        cli_main(["incident", "show", run_dir])
    with pytest.raises(SystemExit, match="no bundle 'inc-x'"):
        cli_main(["incident", "show", run_dir, "inc-x"])


def test_cli_incident_show_json_and_latest_default(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    inc_id = _one_closed_incident(run_dir)
    obs.close_run()
    # show with no id renders the latest bundle; --json round-trips.
    cli_main(["incident", "show", run_dir])
    assert inc_id in capsys.readouterr().out
    cli_main(["incident", "show", run_dir, "--json"])
    b = json.loads(capsys.readouterr().out)
    assert b["id"] == inc_id and b["missing"] == []
    cli_main(["incident", "list", run_dir, "--json"])
    (entry,) = json.loads(capsys.readouterr().out)
    assert entry["id"] == inc_id and entry["state"] == "closed"


# --- surfacing: mirror series, /metrics, report, dash ------------------------

def test_alerts_active_mirror_series(tmp_path):
    run_dir = str(tmp_path / "run")
    store = _tsdb.TimeSeriesStore.open(run_dir)
    _alerts.set_store(store)
    try:
        _alerts.fire(RULE, 123.0, 1, state="fire")
        _alerts.fire(RULE, 1.0, 2, state="resolve")
    finally:
        _alerts.set_store(None)
        store.close()
    reader = _tsdb.TimeSeriesStore.open(run_dir)
    samples = reader.query("alerts_active",
                           {"rule": "serving_p99_ms"}, since_s=3600.0)
    assert [v for _t, v in samples] == [1.0, 0.0]
    # Detached: firing writes nothing (and raises nothing).
    _alerts.fire(RULE, 99.0, 3, state="fire")
    reader2 = _tsdb.TimeSeriesStore.open(run_dir)
    assert len(reader2.query("alerts_active",
                             {"rule": "serving_p99_ms"},
                             since_s=3600.0)) == 2


def test_metrics_export_incidents_open_gauge(tmp_path):
    from featurenet_tpu.serve.metrics import METRIC_NAMES, render_metrics

    assert "incidents_open" in METRIC_NAMES
    assert "alerts_active" in METRIC_NAMES  # the mirror's series name

    stub = SimpleNamespace(
        cfg=SimpleNamespace(
            serve_precision="fp32",
            arch=SimpleNamespace(conv_backend="reference"),
        ),
        health=lambda: {"ready": True, "uptime_s": 1.0, "window_seq": 0},
        stats=lambda: {"served": 0, "rejected": 0, "errors": 0,
                       "queue_depth": 0, "occupancy": 0.0},
    )
    (line,) = [ln for ln in render_metrics(stub).splitlines()
               if ln.startswith("featurenet_incidents_open ")]
    assert line.endswith(" 0")
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    mgr = incidents.arm(run_dir, sample_s=0.05)
    _fire(state="fire")
    (line,) = [ln for ln in render_metrics(stub).splitlines()
               if ln.startswith("featurenet_incidents_open ")]
    assert line.endswith(" 1")
    _fire(state="resolve")
    incidents.disarm(mgr)
    obs.close_run()


def test_report_incidents_section(tmp_path):
    from featurenet_tpu.obs.report import (
        build_report,
        build_report_dir,
        format_report,
    )

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    inc_id = _one_closed_incident(run_dir)
    obs.close_run()
    from featurenet_tpu.obs.report import load_events

    events, bad = load_events(run_dir)
    assert bad == 0
    rep = build_report(events)
    sec = rep["incidents"]
    assert sec["opened"] == 1 and sec["closed"] == 1
    assert sec["by_rule"] == {"serving_p99_ms": 1}
    assert sec["still_open"] == []
    assert sec["durations_s"] and sec["durations_s"][0] >= 0.0
    text = format_report(rep)
    assert "incidents: 1 opened, 1 closed" in text
    # build_report_dir also inventories the on-disk bundles.
    rep_d = build_report_dir(run_dir)
    (bundle,) = rep_d["incidents"]["bundles"]
    assert bundle["id"] == inc_id
    assert inc_id in format_report(rep_d)
    # An open-without-close event trail renders a STILL OPEN flag.
    open_only = [e for e in events if e["ev"] != "incident_close"]
    rep2 = build_report(open_only)
    assert rep2["incidents"]["still_open"] == [inc_id]
    assert "STILL OPEN" in format_report(rep2)


def test_dash_incident_line(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs.dash import render_frame

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    # Friendly empty states on BOTH axes: no tsdb series AND no
    # incidents — `cli dash --once` must stay CI-renderable anywhere.
    frame = render_frame(empty)
    assert "incidents: none recorded" in frame
    cli_main(["dash", empty, "--once"])
    assert "incidents: none recorded" in capsys.readouterr().out
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, extra={"cmd": "t"}, process_index=0)
    store = _tsdb.TimeSeriesStore.open(run_dir)
    store.append("ready", 1.0, {"replica": "0"})
    store.close()
    inc_id = _one_closed_incident(run_dir)
    obs.close_run()
    frame = render_frame(run_dir)
    assert (f"incidents: 0 open · 1 recent · last {inc_id} "
            f"(serving_p99_ms, closed)") in frame


# --- registries + the overhead probe's precondition --------------------------

def test_incident_kinds_in_event_registry():
    from featurenet_tpu.obs.report import (
        KNOWN_EVENT_KINDS,
        REQUIRED_EVENT_FIELDS,
    )

    for kind in ("incident_open", "incident_capture", "incident_close"):
        assert kind in KNOWN_EVENT_KINDS
    assert REQUIRED_EVENT_FIELDS["incident_open"] == (
        "id", "rule", "severity", "value")
    assert REQUIRED_EVENT_FIELDS["incident_capture"] == ("id", "files")
    assert REQUIRED_EVENT_FIELDS["incident_close"] == (
        "id", "rule", "duration_s")


def test_incident_overhead_probe_refuses_active_run(tmp_path):
    from featurenet_tpu.serve.loadgen import measure_incident_overhead

    obs.init_run(str(tmp_path / "run"), extra={"cmd": "t"},
                 process_index=0)
    with pytest.raises(RuntimeError, match="close_run"):
        measure_incident_overhead(None)
    obs.close_run()
