"""Request-level distributed tracing (featurenet_tpu.obs.tracing) + the
serving /metrics exporter and /healthz readiness split.

The acceptance spine (ISSUE 13): a request submitted with a caller trace
id gets it echoed in the HTTP response; `cli report --request <id>`
renders the full admit→dispatch→done timeline with batch attribution;
`GET /metrics` parses as Prometheus text and its serving_ms quantiles
match the report's window summary; sampling is deterministic across
processes and tail-biased (rejections / errors / SLO breaches are
always kept); and the loadgen's client-observed p99 dominates the
server-side p99 (the skew is real queueing on one clock). The tracing
e2e's run dir is schema-linted through `cli report --validate` —
tier-1's wiring for the new event kinds.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.config import get_config
from featurenet_tpu.obs import tracing, windows
from featurenet_tpu.obs.report import (
    build_report,
    format_report,
    format_request_timeline,
    load_events,
    request_timeline,
    validate_events,
)
from featurenet_tpu.serve.batcher import ContinuousBatcher, OverloadError
from featurenet_tpu.serve.loadgen import poisson_load
from featurenet_tpu.serve.service import InferenceService

RES = 16  # smoke16 resolution — every real-model test runs at 16³

_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.eE+-]+|NaN)$"
)


def _grid(value: float = 1.0) -> np.ndarray:
    return np.full((RES, RES, RES, 1), value, np.float32)


def _sum_forward():
    def forward(bucket, arr):
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    return forward


def _parse_prom(text: str) -> dict:
    """{(name, labels): float} for every sample line; asserts the whole
    body is well-formed exposition text."""
    out = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        assert m, f"unparseable Prometheus line: {line!r}"
        out[(m.group(1), m.group(2) or "")] = float(m.group(3))
    return out


@pytest.fixture(scope="module")
def predictor():
    """Random-init smoke16 Predictor (weights don't matter for tracing
    and exporter semantics)."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model

    cfg = get_config("smoke16", data_workers=1)
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, RES, RES, RES, 1), jnp.float32),
        train=False,
    )
    return Predictor(
        variables["params"], variables["batch_stats"], cfg, batch=4
    )


@pytest.fixture()
def stl_bytes(tmp_path):
    from featurenet_tpu.data.mesh_primitives import mesh_box
    from featurenet_tpu.data.stl import save_stl

    p = str(tmp_path / "part.stl")
    save_stl(p, mesh_box((0.2, 0.2, 0.2), (0.8, 0.8, 0.7)))
    with open(p, "rb") as fh:
        return fh.read()


# --- ids + sampling (unit) ---------------------------------------------------

def test_trace_id_mint_normalize_and_config_guard():
    a, b = tracing.mint_trace_id(), tracing.mint_trace_id()
    assert a != b and re.fullmatch(r"[0-9a-f]{16}", a)
    # Well-formed supplied ids are adopted; garbage is replaced.
    assert tracing.normalize_trace_id("router-7.42_a") == "router-7.42_a"
    for bad in (None, "", "a b", "x" * 65, "péché", "a\njson-inject"):
        got = tracing.normalize_trace_id(bad)
        assert got != bad and re.fullmatch(r"[0-9a-f]{16}", got)
    with pytest.raises(ValueError, match="trace_sample"):
        get_config("smoke16", trace_sample=1.5)
    with pytest.raises(ValueError, match="trace_sample"):
        ContinuousBatcher(_sum_forward(), buckets=(1,), trace_sample=-0.1)


def test_sampling_deterministic_across_processes():
    """The rate decision is a pure hash of the trace id: a second
    process (the future fleet router, another serving host) reaches the
    same verdicts with no coordination."""
    ids = [tracing.mint_trace_id() for _ in range(64)]
    here = [tracing.sampled(i, 0.5) for i in ids]
    # Rate 0.5 over 64 ids: both outcomes must actually occur, or the
    # determinism check below would be vacuous.
    assert any(here) and not all(here)
    src = (
        "import json,sys\n"
        "from featurenet_tpu.obs.tracing import sampled\n"
        "ids=json.loads(sys.argv[1])\n"
        "print(json.dumps([sampled(i,0.5) for i in ids]))\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", src, json.dumps(ids)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1]) == here
    # Boundary rates short-circuit.
    assert tracing.sampled(ids[0], 1.0) and not tracing.sampled(ids[0], 0.0)


# --- tail-biased sampling through the batcher --------------------------------

def test_rate_zero_drops_healthy_but_always_samples_reject_and_error(
    tmp_path
):
    """trace_sample=0: a healthy request leaves NO request_* events; a
    rejection and a forward error are always sampled (tail bias)."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    gate = threading.Event()
    flaky = {"fail": False}

    def forward(bucket, arr):
        gate.wait(30)
        if flaky["fail"]:
            flaky["fail"] = False
            raise ValueError("injected forward failure")
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(forward, buckets=(1, 2), max_wait_ms=1,
                          queue_limit=2, trace_sample=0.0,
                          trace_slo_ms=10_000.0)
    gate.set()
    b.submit(np.ones((1,))).result(30)  # healthy: dropped by rate 0
    gate.clear()
    first = b.submit(np.ones((1,)))  # occupies the dispatcher
    time.sleep(0.2)
    fill = [b.submit(np.ones((1,))) for _ in range(2)]
    with pytest.raises(OverloadError) as ei:
        b.submit(np.ones((1,)))
    assert ei.value.trace_id  # the reject carries its id
    gate.set()
    for f in [first] + fill:
        f.result(30)
    gate.clear()
    flaky["fail"] = True
    gate.set()
    bad = b.submit(np.ones((1,)))
    with pytest.raises(RuntimeError, match="injected forward failure"):
        bad.result(30)
    b.drain()
    obs.close_run()
    events, _ = load_events(run_dir)
    done = [e for e in events if e["ev"] == "request_done"]
    rejects = [e for e in events if e["ev"] == "request_reject"]
    # Exactly the error completed a sampled timeline; the 4 healthy
    # requests were dropped by the rate.
    assert [e["outcome"] for e in done] == ["error"]
    assert done[0]["forced"] is True
    assert len(rejects) == 1
    assert rejects[0]["trace"] == ei.value.trace_id
    assert rejects[0]["queue_depth"] == 2 and rejects[0]["limit"] == 2
    # Every sampled timeline is complete: its admit (and, for the error,
    # dispatch) flushed with it despite the late decision.
    admits = {e["trace"] for e in events if e["ev"] == "request_admit"}
    assert admits == {done[0]["trace"], rejects[0]["trace"]}
    assert [e["trace"] for e in events
            if e["ev"] == "request_dispatch"] == [done[0]["trace"]]


def test_slo_breach_always_sampled_at_rate_zero(tmp_path):
    """A request breaching trace_slo_ms is kept at any rate — the p99
    exemplars are the point of tracing."""
    obs_dir = str(tmp_path / "run")
    obs.init_run(obs_dir, process_index=0)

    def slow(bucket, arr):
        time.sleep(0.05)
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(slow, buckets=(1,), max_wait_ms=1,
                          queue_limit=4, trace_sample=0.0,
                          trace_slo_ms=1.0)
    b.submit(np.ones((1,))).result(30)
    b.drain()
    obs.close_run()
    events, _ = load_events(obs_dir)
    done = [e for e in events if e["ev"] == "request_done"]
    assert len(done) == 1 and done[0]["forced"] is True
    assert done[0]["outcome"] == "ok" and done[0]["total_ms"] > 1.0


def test_batch_seq_ties_requests_to_their_dispatch(tmp_path):
    """One dispatch fans in N trace ids: every request_dispatch of a
    batch carries the same batch_seq as its serve_batch event and
    serve_dispatch span."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    gate = threading.Event()

    def gated(bucket, arr):
        gate.wait(30)
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(gated, buckets=(1, 4), max_wait_ms=5,
                          queue_limit=16)
    first = b.submit(np.ones((1,)))
    time.sleep(0.15)  # dispatcher picks it up and blocks
    burst = [b.submit(np.ones((1,))) for _ in range(4)]
    gate.set()
    for f in [first] + burst:
        f.result(30)
    b.drain()
    obs.close_run()
    events, _ = load_events(run_dir)
    disp = [e for e in events if e["ev"] == "request_dispatch"]
    sb = {e["batch_seq"]: e for e in events if e["ev"] == "serve_batch"}
    spans = {e.get("batch_seq"): e for e in events
             if e["ev"] == "span" and e.get("name") == "serve_dispatch"}
    assert len(disp) == 5 and len(sb) == 2
    by_seq: dict[int, list] = {}
    for e in disp:
        by_seq.setdefault(e["batch_seq"], []).append(e)
    # The 4-burst rode ONE dispatch; its pad/bucket agree everywhere.
    sizes = sorted(len(v) for v in by_seq.values())
    assert sizes == [1, 4]
    for seq, evs in by_seq.items():
        assert seq in sb and seq in spans
        assert {e["bucket"] for e in evs} == {sb[seq]["bucket"]}
        assert {e["pad"] for e in evs} == {sb[seq]["pad"]}
    # Old logs without batch_seq keep validating (legacy-optional).
    legacy = [{"t": 1.0, "ev": "serve_batch", "bucket": 4, "n": 2}]
    assert validate_events(legacy) == []


# --- HTTP: header roundtrip, /healthz readiness, /metrics --------------------

def test_http_trace_header_roundtrip_healthz_and_metrics(
    tmp_path, predictor, stl_bytes
):
    import http.client

    from featurenet_tpu.serve.http import make_server

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    service = InferenceService(
        predictor, buckets=(1, 4), max_wait_ms=2, queue_limit=8,
        rules=(),
    )
    assert service.ready() is True
    srv = make_server(service, port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()

    def request(method, path, body=None, headers=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read().decode()
        echo = resp.getheader("X-Featurenet-Trace")
        conn.close()
        return resp.status, raw, echo

    try:
        # Supplied id echoed on 200 and present in the events.
        status, body, echo = request(
            "POST", "/predict", stl_bytes,
            {"X-Featurenet-Trace": "caller-42"},
        )
        assert status == 200 and echo == "caller-42"
        # No header → the server mints and still echoes.
        status, _, echo2 = request("POST", "/predict", stl_bytes)
        assert status == 200 and re.fullmatch(r"[0-9a-f]{16}", echo2)
        # A malformed body still echoes the (sanitized) id on the 400.
        status, err, echo3 = request(
            "POST", "/predict", b"not an stl",
            {"X-Featurenet-Trace": "caller-43"},
        )
        assert status == 400 and echo3 == "caller-43"
        assert json.loads(err)["error"] == "bad_stl"

        # /healthz: ready while serving…
        status, body, _ = request("GET", "/healthz")
        health = json.loads(body)
        assert status == 200 and health["ready"] is True
        assert health["uptime_s"] > 0
        # …503 while warming (simulated via the same flag construction
        # clears) and from the moment drain begins.
        service._ready = False
        status, body, _ = request("GET", "/healthz")
        assert status == 503 and json.loads(body)["ready"] is False
        service._ready = True

        # /metrics parses as Prometheus text, its names stay inside the
        # registry, and the serving_ms quantiles match the report's
        # window summary exactly (same windows, same formula).
        windows.flush()
        status, text, _ = request("GET", "/metrics")
        assert status == 200
        samples = _parse_prom(text)
        from featurenet_tpu.serve.metrics import METRIC_NAMES

        for (name, _labels) in samples:
            assert name.startswith("featurenet_")
            base = name[len("featurenet_"):]
            assert base in METRIC_NAMES, base
        assert samples[("featurenet_ready", "")] == 1.0
        assert samples[("featurenet_requests_total",
                        '{outcome="served"}')] >= 2
        assert samples[("featurenet_trace_sampled_total", "")] >= 2
        # The ladder warmed through the registry while the sink was up
        # (bucket 4 is memoized on the shared predictor fixture; bucket
        # 1 compiles under THIS sink and lands in the counter).
        assert samples[("featurenet_program_compiles_total", "")] >= 1
    finally:
        srv.shutdown()
        st = service.drain()
    assert service.ready() is False
    obs.close_run()

    events, bad = load_events(run_dir)
    assert bad == 0
    done = {e["trace"]: e for e in events if e["ev"] == "request_done"}
    assert "caller-42" in done and echo2 in done
    # The scraped serving_ms quantiles equal the LAST window_summary the
    # report folds (drain's flush emits nothing new: no samples landed
    # after the pre-scrape flush).
    rep = build_report(events)
    win = rep["slo"]["windows"]["serving_ms"]
    assert samples[("featurenet_serving_ms", '{q="0.99"}')] == win["p99"]
    assert samples[("featurenet_serving_ms", '{q="0.5"}')] == win["p50"]
    assert samples[("featurenet_serving_ms_count", "")] == win["n"]
    assert st["exit_code"] == 0


# --- the acceptance e2e: loadgen + report --request + --validate -------------

def test_loadgen_trace_e2e_report_request_and_validate(
    tmp_path, predictor, capsys
):
    from featurenet_tpu.cli import main as cli_main

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    service = InferenceService(
        predictor, buckets=(1, 4, 16), max_wait_ms=10, queue_limit=64,
        rules=(),
    )
    grids = np.stack([_grid(float(i % 3)) for i in range(8)])
    stats, futs = poisson_load(
        service, qps=200.0, n_requests=24,
        rng=np.random.default_rng(3), grids=grids,
    )
    service.drain()
    obs.close_run()

    # Client-observed latency per trace id, p50/p99 beside the server
    # windows — and the client p99 DOMINATES the server p99: the same
    # monotonic clock stamps both ends, so the skew is real queueing.
    assert stats["accepted"] == 24
    assert len(stats["client_by_trace"]) == 24
    assert stats["client_p99_ms"] >= stats["p99_ms"]
    for f in futs:
        assert stats["client_by_trace"][f.trace_id] >= f.latency_ms - 1e-6

    events, bad = load_events(run_dir)
    assert bad == 0
    # Every accepted request's timeline is in the stream (rate 1.0).
    done = [e for e in events if e["ev"] == "request_done"]
    assert len(done) == 24
    # The loadgen's client summary landed and the report states the skew.
    rep = build_report(events)
    tr = rep["traces"]
    assert tr["sampled"] == 24
    assert len(tr["slowest"]) == 10
    assert all(row["batch_seq"] is not None for row in tr["slowest"])
    assert tr["client"]["n"] == 24
    assert tr["client"]["skew_p99_ms"] is not None
    text = format_report(rep)
    assert "traces: 24 sampled request(s)" in text
    assert "client (loadgen):" in text

    # `cli report --request <id>`: the full admit→dispatch→done timeline
    # with batch attribution, straight off the run dir.
    tid = futs[0].trace_id
    tl = request_timeline(events, tid)
    assert tl["found"]
    assert [e["event"] for e in tl["events"]] == [
        "request_admit", "request_dispatch", "request_done",
    ]
    disp = tl["events"][1]
    assert disp["batch_seq"] >= 1 and disp["bucket"] in (1, 4, 16)
    rendered = format_request_timeline(tl)
    assert tid in rendered and "request_dispatch" in rendered
    cli_main(["report", run_dir, "--request", tid])
    out = capsys.readouterr().out
    assert tid in out and "request_done" in out
    with pytest.raises(SystemExit) as ei:
        cli_main(["report", run_dir, "--request", "no-such-trace"])
    assert ei.value.code == 2
    assert "sampling" in capsys.readouterr().out

    # Tier-1 wiring: the new kinds schema-lint clean against a REAL log.
    cli_main(["report", run_dir, "--validate"])
    assert '"validate": "ok"' in capsys.readouterr().out

    # The Chrome trace links the requests as async flow events.
    from featurenet_tpu.obs.spans import chrome_trace

    ct = chrome_trace(events)
    reqs = [e for e in ct["traceEvents"] if e.get("cat") == "request"]
    assert {"b", "e", "s", "f"} <= {e["ph"] for e in reqs}
    assert any(e.get("id") == tid for e in reqs)


def test_traces_section_suppresses_skew_on_biased_sample():
    """Below rate 1.0 the sampled request_done set is tail-biased by
    design — its percentiles are labeled biased and the client-vs-server
    skew is suppressed rather than reported against them."""
    evs = [
        {"t": 1.0, "ev": "request_done", "trace": "a", "queue_wait_ms": 1,
         "dispatch_ms": 400, "total_ms": 401.0, "outcome": "ok",
         "forced": True},
        {"t": 2.0, "ev": "loadgen", "n": 100, "client_p50_ms": 3.0,
         "client_p99_ms": 12.0},
    ]
    manifest = {"config": {"trace_sample": 0.1}}
    tr = build_report(evs, manifest)["traces"]
    assert tr["sample_biased"] is True and tr["sample_rate"] == 0.1
    assert "skew_p99_ms" not in tr["client"]
    assert "tail-biased sample" in format_report(
        build_report(evs, manifest)
    )
    # At rate 1.0 (or no manifest) the set is complete: skew reported.
    tr_full = build_report(evs)["traces"]
    assert tr_full["client"]["skew_p99_ms"] == pytest.approx(-389.0)
    assert "sample_biased" not in tr_full


# --- trace overhead measurement (the bench pin's source) ---------------------

def test_measure_trace_overhead_shape(tmp_path):
    from featurenet_tpu.serve.loadgen import measure_trace_overhead

    cfg = get_config("smoke16", data_workers=1)
    # The probe owns the process obs state: a caller with a live run
    # gets a refusal, never a silently-torn-down sink.
    obs.init_run(str(tmp_path / "live"), process_index=0)
    with pytest.raises(RuntimeError, match="close_run"):
        measure_trace_overhead(cfg, n_requests=8, buckets=(1,))
    obs.close_run()
    row = measure_trace_overhead(cfg, n_requests=32, buckets=(1, 4))
    assert row["trace_dark_qps"] > 0 and row["trace_sampled_qps"] > 0
    assert row["trace_overhead_pct"] is not None
    assert row["trace_overhead_pct"] >= 0.0
    assert row["trace_overhead_requests"] == 32


def test_init_run_switch_resets_tracing_counters(tmp_path):
    """Run B's /metrics must not report run A's sampled totals: both
    the close_run path and the init_run run-SWITCH path zero the
    tracing counters alongside the fresh sink's per-kind counts."""
    obs.init_run(str(tmp_path / "a"), process_index=0)
    ctx = tracing.admit(None, 1.0)
    tracing.done(ctx, 1.0, 1.0, 2.0, "ok")
    assert tracing.counters()["admitted"] == 1
    obs.init_run(str(tmp_path / "b"), process_index=0)
    assert tracing.counters() == {
        "admitted": 0, "done": 0, "sampled": 0, "forced": 0,
        "rejected": 0,
    }
    obs.close_run()


def test_bench_gate_trace_and_client_keys():
    from featurenet_tpu.obs import gates

    summary = {
        "trace_overhead_pct": 1.4,
        "serve_client_p99_ms": 12.0,
        "serve_p99_ms": 9.0,
    }
    vals = gates.bench_gate_values(summary)
    assert set(summary) <= set(vals)
    pin = gates.make_baseline(vals)["gates"]
    assert pin["trace_overhead_pct"]["direction"] == "max"
    assert pin["serve_client_p99_ms"]["direction"] == "max"
    worse = dict(vals, trace_overhead_pct=25.0)
    res = gates.evaluate_gates(worse, {"gates": pin})
    assert not res["ok"] and "trace_overhead_pct" in res["failed"]


# --- bench-history -----------------------------------------------------------

def test_bench_history_table_and_skipped_reasons(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs.bench_history import (
        format_history,
        load_rounds,
    )

    d = str(tmp_path)
    # r1: driver-wrapped healthy round; r2: structured skip; r3: the
    # pre-hardening outage shape (parsed null); r4: bare (unwrapped)
    # record with a gate verdict.
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "rc": 0,
        "parsed": {"metric": "m", "value": 2372.3, "mfu": 0.29},
    }))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 2, "rc": 0,
        "parsed": {"skipped": True, "reason": "tpu_backend_unavailable",
                   "error": "UNAVAILABLE: lease lapsed"},
    }))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "n": 3, "rc": 1, "parsed": None,
    }))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "metric": "m", "value": 16669.0, "mfu": 0.41,
        "serve_qps_sustained": 905.0, "trace_overhead_pct": 1.2,
        "gate": {"ok": False, "failed": ["serve_p99_ms"]},
    }))
    # Unpadded and two-digit rounds must sort NUMERICALLY (r10 after
    # r9), not by filename.
    (tmp_path / "BENCH_r9.json").write_text(json.dumps({
        "metric": "m", "value": 1.0,
    }))
    (tmp_path / "BENCH_r10.json").write_text(json.dumps({
        "metric": "m", "value": 2.0,
    }))
    # Valid JSON that is not a record (a corrupted write): an
    # unparseable round, never an AttributeError.
    (tmp_path / "BENCH_r11.json").write_text("[1, 2, 3]")
    rows = load_rounds(d)
    assert [r["round"] for r in rows] == [
        "r01", "r02", "r03", "r04", "r09", "r10", "r11",
    ]
    assert [r["status"] for r in rows][:4] == [
        "ok", "skipped", "unparseable", "ok",
    ]
    assert rows[-1]["status"] == "unparseable"
    assert "list JSON" in rows[-1]["reason"]
    assert rows[1]["reason"] == "tpu_backend_unavailable"
    assert "rc=1" in rows[2]["reason"]
    assert rows[3]["gate_ok"] is False
    table = format_history(rows)
    lines = table.splitlines()
    assert len(lines) == 8  # header + one line per round, none vanish
    assert "tpu_backend_unavailable" in table
    assert "FAIL serve_p99_ms" in table
    cli_main(["bench-history", d])
    assert "r03    unparseable" in capsys.readouterr().out
    cli_main(["bench-history", d, "--json"])
    out_rows = [json.loads(ln) for ln in
                capsys.readouterr().out.strip().splitlines()]
    assert out_rows[0]["value"] == 2372.3
    # An empty dir renders a named absence, not a crash.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert "no BENCH_r*.json" in format_history(load_rounds(str(empty)),
                                                bench_dir=str(empty))
