"""Fault-injection registry + unit-level recovery paths.

The e2e inject-and-recover runs live in test_recovery_e2e.py; this file
covers the registry's semantics (DSL, one-shot, zero-overhead off) and each
hardened layer in isolation: sink degradation, producer structured errors,
checkpoint fallback, supervisor backoff / spawn-fail / telemetry verdict /
stall re-read.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

from featurenet_tpu import faults, obs


# Process-wide obs/faults state is reset by conftest's autouse
# _reset_process_state fixture (tests-tree fixture hygiene, PR 7).


# --- registry ----------------------------------------------------------------

def test_spec_parse_and_errors():
    spec = ("checkpoint_corrupt@save=2,producer_hang@batch=40,"
            "sigterm@step=120,sink_enospc@emit=10")
    parsed = faults.parse_spec(spec)
    assert parsed["checkpoint_corrupt"] == ("save", 2)
    assert parsed["sigterm"] == ("step", 120)
    assert faults.parse_spec("producer_crash") == {"producer_crash": None}
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.parse_spec("tyop_site@x=1")
    with pytest.raises(ValueError, match="counts 'step'"):
        faults.parse_spec("sigterm@save=1")
    with pytest.raises(ValueError, match="integer"):
        faults.parse_spec("sigterm@step=soon")
    with pytest.raises(ValueError, match="duplicate"):
        faults.parse_spec("sigterm@step=1,sigterm@step=2")
    with pytest.raises(ValueError, match="empty"):
        faults.parse_spec(" , ")


def test_maybe_fail_off_exact_match_and_one_shot():
    # Off: nothing installed => False, always (the zero-overhead contract).
    assert not faults.maybe_fail("sigterm", step=1)
    faults.install("sigterm@step=3")
    assert not faults.maybe_fail("sigterm", step=2)
    assert not faults.maybe_fail("producer_crash", batch=3)  # other site
    assert faults.maybe_fail("sigterm", step=3)
    assert not faults.maybe_fail("sigterm", step=3)  # fired once
    faults.install("producer_crash")  # bare site: first check fires
    assert faults.maybe_fail("producer_crash", batch=7)
    assert not faults.maybe_fail("producer_crash", batch=8)


def test_trigger_is_threshold_crossing_not_equality():
    """Counters may stride past N (fused dispatch: step += k; worker w's
    tickets: w, w+W, …) — the trigger fires at the first value >= N, so a
    spec can't silently never fire on an off-grid counter."""
    faults.install("sigterm@step=120")
    assert not faults.maybe_fail("sigterm", step=112)
    assert faults.maybe_fail("sigterm", step=124)  # crossed, not equal
    assert not faults.maybe_fail("sigterm", step=124)  # still one-shot
    faults.install("sigterm@step=120")
    assert not faults.maybe_fail("sigterm")  # counter not supplied


def test_install_only_filters_sites():
    """The supervisor installs the shared spec with only={'spawn_fail'}:
    child-side sites must not fire (and burn their one-shot marker) in
    the supervisor process."""
    faults.install("sink_enospc@emit=1,spawn_fail@spawn=1",
                   only={"spawn_fail"})
    assert not faults.maybe_fail("sink_enospc", emit=1)
    assert faults.maybe_fail("spawn_fail", spawn=1)


def test_marker_makes_faults_one_shot_per_run(tmp_path):
    """A respawned child re-executes the same argv (same spec); the marker
    file in the shared state_dir is what keeps attempt 2 clean."""
    d = str(tmp_path)
    faults.install("producer_crash@batch=1", state_dir=d)
    assert faults.maybe_fail("producer_crash", batch=1)
    assert os.path.exists(tmp_path / "fault_producer_crash.fired")
    # "New process": a fresh plan over the same run dir.
    faults.install("producer_crash@batch=1", state_dir=d)
    assert not faults.maybe_fail("producer_crash", batch=1)


def test_repeatable_trigger_parse_and_errors():
    """Satellite (soak testing): ``site@counter=N:every=M`` re-fires on the
    threshold ladder N, N+M, …; the grammar fails loudly on typos."""
    parsed = faults.parse_spec("sigterm@step=100:every=50")
    assert parsed["sigterm"] == ("step", 100, 50)
    with pytest.raises(ValueError, match="expected site@counter=N:every=M"):
        faults.parse_spec("sigterm@step=100:evry=50")
    with pytest.raises(ValueError, match="must be an integer"):
        faults.parse_spec("sigterm@step=100:every=soon")
    with pytest.raises(ValueError, match="must be positive"):
        faults.parse_spec("sigterm@step=100:every=0")


def test_repeatable_trigger_refires_on_stride():
    faults.install("sigterm@step=2:every=3")
    fired = [s for s in range(1, 10) if faults.maybe_fail("sigterm", step=s)]
    assert fired == [2, 5, 8]  # the ladder N, N+M, N+2M
    # Several rungs crossed in one stride (fused dispatch jumping k steps)
    # collapse into ONE firing, at the highest rung crossed; a counter that
    # then runs backwards never re-fires a lower rung.
    faults.install("sigterm@step=2:every=3")
    assert faults.maybe_fail("sigterm", step=20)
    assert not faults.maybe_fail("sigterm", step=20)
    assert not faults.maybe_fail("sigterm", step=5)
    assert faults.maybe_fail("sigterm", step=23)


def test_repeatable_trigger_markers_are_per_firing(tmp_path):
    """A respawned child (same argv, same spec, same run dir) skips the
    rungs this run already fired but still fires the later ones — the
    property that makes a soak spec survive supervisor restarts."""
    d = str(tmp_path)
    faults.install("sigterm@step=2:every=3", state_dir=d)
    assert faults.maybe_fail("sigterm", step=2)
    assert os.path.exists(tmp_path / "fault_sigterm.fired.2")
    faults.install("sigterm@step=2:every=3", state_dir=d)  # "new process"
    assert not faults.maybe_fail("sigterm", step=2)  # rung 2 already taken
    assert faults.maybe_fail("sigterm", step=5)      # rung 5 still live
    assert os.path.exists(tmp_path / "fault_sigterm.fired.5")


def test_config_validates_inject_spec():
    from featurenet_tpu.config import get_config

    with pytest.raises(ValueError, match="unknown fault site"):
        get_config("smoke16", inject_faults="tyop@x=1")
    cfg = get_config("smoke16", inject_faults="sigterm@step=5")
    assert cfg.inject_faults == "sigterm@step=5"


def test_cli_carries_inject_faults_and_keeps_it_ephemeral():
    import argparse

    from featurenet_tpu.cli import _overrides

    ns = argparse.Namespace(inject_faults="sigterm@step=5")
    assert _overrides(ns)["inject_faults"] == "sigterm@step=5"
    # The checkpoint sidecar must not leak a chaos spec into later
    # resumes/evals: _cfg_from_checkpoint nulls it like heartbeat_file.
    import inspect

    from featurenet_tpu import cli

    src = inspect.getsource(cli._cfg_from_checkpoint)
    assert "inject_faults" in src


# --- obs sink degradation ----------------------------------------------------

def test_sink_enospc_degrades_to_noop_with_one_warning(tmp_path, capsys):
    from featurenet_tpu.obs.events import EventSink

    sink = EventSink(str(tmp_path))
    faults.install("sink_enospc@emit=2")
    sink.emit("gauge", name="a", value=1)
    sink.emit("gauge", name="a", value=2)  # injected ENOSPC fires here
    sink.emit("gauge", name="a", value=3)  # already dark: silent no-op
    sink.close()
    err = capsys.readouterr().err
    assert err.count("sink_error") == 1  # exactly one warning
    lines = open(tmp_path / "events.jsonl").read().splitlines()
    assert len(lines) == 1  # only the pre-fault emit landed
    json.loads(lines[0])  # and it is a complete record


def test_real_oserror_on_write_also_degrades(tmp_path, capsys, monkeypatch):
    """The hardening is not injection-specific: any OSError from os.write
    takes the same degrade path."""
    from featurenet_tpu.obs import events as ev_mod

    sink = ev_mod.EventSink(str(tmp_path))

    def boom(fd, data):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(ev_mod.os, "write", boom)
    sink.emit("gauge", name="a", value=1)  # must not raise
    monkeypatch.undo()
    sink.emit("gauge", name="a", value=2)  # dark, still no raise
    sink.close()
    assert "sink_error" in capsys.readouterr().err


# --- producer resilience -----------------------------------------------------

def test_producer_crash_surfaces_structured_error(tmp_path):
    from featurenet_tpu.data import SyntheticVoxelDataset, prefetch_to_device
    from featurenet_tpu.data.dataset import ProducerError

    obs.init_run(str(tmp_path / "run"))
    try:
        faults.install("producer_crash@batch=1")
        ds = SyntheticVoxelDataset(resolution=16, global_batch=2, seed=0)
        it = prefetch_to_device(ds, num_workers=1)
        next(it)  # ticket 0 is clean
        with pytest.raises(ProducerError) as exc:
            next(it)
        # The consumer-side raise carries the WORKER's traceback and the
        # original exception chained — the operator sees the real culprit.
        assert "InjectedFault" in str(exc.value)
        assert exc.value.worker == 0
        assert isinstance(exc.value.__cause__, faults.InjectedFault)
    finally:
        obs.close_run()
    events = [json.loads(l) for l in
              open(tmp_path / "run" / "events.jsonl")]
    warn = [e for e in events
            if e["ev"] == "warning" and e["name"] == "producer_error"]
    assert len(warn) == 1 and warn[0]["worker"] == 0


def test_producer_hang_site_starves_but_close_returns(tmp_path):
    import time

    from featurenet_tpu.data import SyntheticVoxelDataset, prefetch_to_device

    faults.install("producer_hang@batch=1")
    ds = SyntheticVoxelDataset(resolution=16, global_batch=2, seed=0)
    it = prefetch_to_device(ds, num_workers=1)
    next(it)  # ticket 0 produced before the hang
    # The worker is now hung (the real recovery is the supervisor's stale-
    # heartbeat kill — e2e-tested); the consumer-side generator must still
    # shut down cleanly, releasing the hung worker via the stop event.
    t0 = time.monotonic()
    it.close()
    assert time.monotonic() - t0 < 5.0


def test_cache_read_error_propagates_through_producer(tmp_path):
    from featurenet_tpu.data.dataset import ProducerError, prefetch_to_device
    from featurenet_tpu.data.offline import (
        VoxelCacheDataset,
        export_synthetic_cache,
    )

    out = str(tmp_path / "cache")
    export_synthetic_cache(out, per_class=2, resolution=16, seed=7)
    ds = VoxelCacheDataset(out, global_batch=4, split="train",
                           augment=False, seed=0)
    faults.install("cache_read_error@read=2")
    it = prefetch_to_device(ds, num_workers=1)
    next(it)
    with pytest.raises(ProducerError, match="cache_read_error"):
        next(it)
        next(it)


# --- checkpoint fallback -----------------------------------------------------

def _tiny_state():
    import jax

    from featurenet_tpu.config import get_config
    from featurenet_tpu.models.featurenet import FeatureNet, tiny_arch
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer

    cfg = get_config("smoke16")
    model = FeatureNet(arch=tiny_arch())
    sample = np.zeros((2, 16, 16, 16, 1), np.float32)
    return create_state(model, make_optimizer(cfg), sample,
                        jax.random.key(0))


def test_truncated_latest_step_falls_back_with_event(tmp_path):
    """Satellite: truncate the latest Orbax step dir on disk; restore()
    must fall back one step and the checkpoint_fallback event must carry
    both step numbers."""
    import jax.numpy as jnp

    from featurenet_tpu.train.checkpoint import CheckpointManager, _step_dir

    state = _tiny_state()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
    mgr.save(state.replace(step=jnp.asarray(1, jnp.int32)), step=1)
    mgr.save(state.replace(step=jnp.asarray(2, jnp.int32)), step=2)
    mgr.wait()
    step2 = _step_dir(str(tmp_path / "ck"), 2)
    assert step2 is not None
    for dirpath, _, files in os.walk(step2):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "r+b") as fh:
                fh.truncate(os.path.getsize(p) // 2)

    obs.init_run(str(tmp_path / "run"))
    try:
        # cleanup=True is what the resume-to-train caller passes (it will
        # re-save the walked-past step numbers; Orbax refuses collisions).
        restored = mgr.restore(state, cleanup=True)
    finally:
        obs.close_run()
    assert int(restored.step) == 1
    assert mgr.latest_step() == 1  # the corrupt step dir was dropped
    events = [json.loads(l) for l in
              open(tmp_path / "run" / "events.jsonl")]
    fb = [e for e in events if e["ev"] == "checkpoint_fallback"]
    assert len(fb) == 1
    assert fb[0]["from_step"] == 2 and fb[0]["to_step"] == 1
    mgr.close()


def test_injected_restore_error_falls_back_without_disk_damage(tmp_path):
    import jax.numpy as jnp

    from featurenet_tpu.train.checkpoint import CheckpointManager

    state = _tiny_state()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
    mgr.save(state.replace(step=jnp.asarray(1, jnp.int32)), step=1)
    mgr.save(state.replace(step=jnp.asarray(2, jnp.int32)), step=2)
    mgr.wait()
    faults.install("checkpoint_restore_error@restore=1")
    restored = mgr.restore(state)
    assert int(restored.step) == 1
    # Default (read-only callers: eval/infer/warm start) never deletes —
    # a transient read error must not destroy another run's checkpoints.
    assert mgr.latest_step() == 2
    mgr.close()


def test_explicit_step_request_never_falls_back(tmp_path):
    import jax.numpy as jnp

    from featurenet_tpu.train.checkpoint import CheckpointManager

    state = _tiny_state()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
    mgr.save(state.replace(step=jnp.asarray(1, jnp.int32)), step=1)
    mgr.save(state.replace(step=jnp.asarray(2, jnp.int32)), step=2)
    mgr.wait()
    faults.install("checkpoint_restore_error@restore=1")
    with pytest.raises(faults.InjectedFault):
        mgr.restore(state, step=2)  # the caller named it: error, not swap
    mgr.close()


def test_all_checkpoints_corrupt_raises_chained(tmp_path):
    from featurenet_tpu.train.checkpoint import CheckpointManager

    state = _tiny_state()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
    mgr.save(state, step=1)
    mgr.wait()
    faults.install("checkpoint_restore_error@restore=1")
    with pytest.raises(RuntimeError, match="every retained checkpoint"):
        mgr.restore(state)
    mgr.close()


# --- checkpoint content verification (checksum sidecar) ----------------------

def _flip_byte_same_size(step_dir: str) -> str:
    """Silent corruption: flip one byte of the largest file, size kept —
    the failure mode Orbax's structural checks cannot see."""
    files = []
    for dirpath, _, names in os.walk(step_dir):
        files += [os.path.join(dirpath, n) for n in names]
    target = max(files, key=os.path.getsize)
    with open(target, "r+b") as fh:
        data = bytearray(fh.read())
        data[len(data) // 2] ^= 0xFF
        fh.seek(0)
        fh.write(data)
    return target


def test_checksum_sidecar_written_at_save(tmp_path):
    from featurenet_tpu.train.checkpoint import (
        CheckpointManager,
        _checksum_path,
    )

    state = _tiny_state()
    root = str(tmp_path / "ck")
    mgr = CheckpointManager(root, keep=2)
    mgr.save(state, step=1)
    mgr.wait()
    assert os.path.exists(_checksum_path(root, 1))
    with open(_checksum_path(root, 1)) as fh:
        sums = json.load(fh)
    assert sums and all(len(v) == 64 for v in sums.values())
    # An untouched checkpoint restores cleanly through the verification.
    restored = mgr.restore(state)
    assert int(restored.step) == int(state.step)
    mgr.close()


def test_silent_corruption_caught_by_checksum_with_fallback(tmp_path):
    """Same-size byte flip in the latest step: the sidecar verification
    fails it BEFORE Orbax restores garbage, and resume falls back to the
    previous retained step with the existing checkpoint_fallback event."""
    import jax.numpy as jnp

    from featurenet_tpu.train.checkpoint import CheckpointManager, _step_dir

    state = _tiny_state()
    root = str(tmp_path / "ck")
    mgr = CheckpointManager(root, keep=3)
    mgr.save(state.replace(step=jnp.asarray(1, jnp.int32)), step=1)
    mgr.wait()
    mgr.save(state.replace(step=jnp.asarray(2, jnp.int32)), step=2)
    mgr.wait()
    _flip_byte_same_size(_step_dir(root, 2))

    obs.init_run(str(tmp_path / "run"))
    try:
        restored = mgr.restore(state, cleanup=True)
    finally:
        obs.close_run()
    assert int(restored.step) == 1
    events = [json.loads(l) for l in
              open(tmp_path / "run" / "events.jsonl")]
    fb = [e for e in events if e["ev"] == "checkpoint_fallback"]
    assert len(fb) == 1 and fb[0]["from_step"] == 2
    assert "ChecksumMismatch" in fb[0].get("error", "")
    mgr.close()


def test_checksum_mismatch_on_explicit_step_raises(tmp_path):
    from featurenet_tpu.train.checkpoint import (
        CheckpointManager,
        ChecksumMismatch,
        _step_dir,
    )

    state = _tiny_state()
    root = str(tmp_path / "ck")
    mgr = CheckpointManager(root, keep=2)
    mgr.save(state, step=1)
    mgr.wait()
    _flip_byte_same_size(_step_dir(root, 1))
    with pytest.raises(ChecksumMismatch, match="content verification"):
        mgr.restore(state, step=1)  # the caller named it: error, not swap
    mgr.close()


def test_legacy_dir_without_sidecar_restores(tmp_path):
    from featurenet_tpu.train.checkpoint import (
        CheckpointManager,
        _checksum_path,
    )

    state = _tiny_state()
    root = str(tmp_path / "ck")
    mgr = CheckpointManager(root, keep=2)
    mgr.save(state, step=1)
    mgr.wait()
    os.unlink(_checksum_path(root, 1))  # pre-sidecar checkpoint layout
    restored = mgr.restore(state)
    assert int(restored.step) == int(state.step)
    mgr.close()


# --- supervisor: backoff, spawn_fail, telemetry verdict, stall re-read -------

def _records_log():
    records = []

    def log(line):
        records.append(json.loads(line))

    return records, log


def test_supervisor_backoff_grows_and_is_capped(tmp_path):
    from featurenet_tpu.train.supervisor import supervise

    hb = tmp_path / "hb"
    # Beats, then crashes — every restart is an unplanned one.
    code = (
        "import os, sys, time\n"
        f"hb={str(hb)!r}\n"
        "time.sleep(0.2); os.utime(hb, None); time.sleep(0.1); sys.exit(9)\n"
    )
    records, log = _records_log()
    res = supervise(
        [sys.executable, "-c", code],
        stall_timeout_s=10,
        max_restarts=3,
        heartbeat_file=str(hb),
        poll_s=0.05,
        log=log,
        backoff_base_s=0.05,
        backoff_cap_s=0.12,
        run_dir=str(tmp_path / "run"),
    )
    assert res.exit_code == 9 and res.restarts == 3
    backoffs = [r for r in records if r.get("supervisor") == "backoff"]
    assert len(backoffs) == 3
    delays = [b["delay_s"] for b in backoffs]
    assert [b["consecutive_failures"] for b in backoffs] == [1, 2, 3]
    # Jitter keeps delays in [0.5x, 1x) of the exponential; the cap binds
    # the third (0.05 * 4 = 0.2 > 0.12).
    assert 0.025 <= delays[0] <= 0.05
    assert delays[2] <= 0.12
    # And the same decisions landed in the run's event log.
    events = [json.loads(l) for l in
              open(tmp_path / "run" / "events.jsonl")]
    phases = [e["phase"] for e in events if e["ev"] == "supervisor"]
    assert phases.count("backoff") == 3


def test_supervisor_planned_restart_skips_backoff_and_resets(tmp_path):
    from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE, supervise

    hb = tmp_path / "hb"
    attempts = tmp_path / "attempts"
    code = (
        "import os, sys, time\n"
        f"a={str(attempts)!r}; hb={str(hb)!r}\n"
        "n = len(open(a).read()) if os.path.exists(a) else 0\n"
        "open(a, 'a').write('x')\n"
        "time.sleep(0.2); os.utime(hb, None)\n"
        f"sys.exit(0 if n >= 2 else {RESTART_EXIT_CODE})\n"
    )
    records, log = _records_log()
    res = supervise(
        [sys.executable, "-c", code],
        stall_timeout_s=10,
        max_restarts=0,
        heartbeat_file=str(hb),
        poll_s=0.05,
        log=log,
    )
    assert res.exit_code == 0 and res.planned == 2
    assert not any(r.get("supervisor") == "backoff" for r in records)


def test_supervisor_spawn_fail_site_burns_one_attempt(tmp_path):
    from featurenet_tpu.train.supervisor import supervise

    faults.install("spawn_fail@spawn=1")
    records, log = _records_log()
    res = supervise(
        [sys.executable, "-c", "pass"],
        stall_timeout_s=5,
        max_restarts=3,
        heartbeat_file=str(tmp_path / "hb"),
        poll_s=0.05,
        log=log,
        backoff_base_s=0.01,
    )
    # Attempt 1 is the injected instantly-dying stub (exit 13, no beat);
    # attempt 2 is the real child, which finishes.
    assert res.exit_code == 0
    assert res.restarts == 1
    assert any(r.get("reason") == "exit_13" for r in records
               if r.get("supervisor") == "restart")


def test_supervisor_telemetry_corrupt_counts_as_crash(tmp_path):
    """Satellite: a child that exits 0 but wrote torn telemetry is not
    trusted — telemetry_corrupt is recorded and the child restarts on the
    failure budget; the clean retry ends the run."""
    from featurenet_tpu.train.supervisor import supervise

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    attempts = tmp_path / "attempts"
    hb = tmp_path / "hb"
    code = (
        "import os, time\n"
        f"a={str(attempts)!r}; hb={str(hb)!r}\n"
        f"ev={str(run_dir / 'events.jsonl')!r}\n"
        "n = len(open(a).read()) if os.path.exists(a) else 0\n"
        "open(a, 'a').write('x')\n"
        "time.sleep(0.2); os.utime(hb, None)\n"
        "if n == 0:\n"
        "    open(ev, 'a').write('{torn json garbage\\n')\n"
    )
    records, log = _records_log()
    res = supervise(
        [sys.executable, "-c", code],
        stall_timeout_s=10,
        max_restarts=3,
        heartbeat_file=str(hb),
        poll_s=0.05,
        log=log,
        run_dir=str(run_dir),
        backoff_base_s=0.01,
    )
    assert res.exit_code == 0
    assert res.restarts == 1
    tc = [r for r in records if r.get("supervisor") == "telemetry_corrupt"]
    assert len(tc) == 1 and tc[0]["findings"] >= 1
    restart = [r for r in records if r.get("supervisor") == "restart"]
    assert restart and restart[0]["reason"] == "telemetry_corrupt"
    # The verdict is windowed: attempt 2's lint does NOT re-count attempt
    # 1's garbage (or the run could never complete) — proven by exit 0.
    events = [json.loads(l) for l in open(run_dir / "events.jsonl")
              if not l.startswith("{torn")]
    phases = [e.get("phase") for e in events if e.get("ev") == "supervisor"]
    assert "telemetry_corrupt" in phases and "done" in phases


def test_telemetry_lint_tolerates_torn_trailing_fragment(tmp_path):
    """A torn fragment at EOF is the legitimate signature of the sink's
    ENOSPC degrade path (short write, then dark by design) — it must NOT
    read as corruption; a torn line FOLLOWED by more lines must."""
    import time as _t

    from featurenet_tpu.train.supervisor import _telemetry_findings

    ev = tmp_path / "events.jsonl"
    good = json.dumps({"t": _t.time(), "ev": "heartbeat"}) + "\n"
    ev.write_text(good + '{"t": 123, "ev": "gau')  # short write at EOF
    assert _telemetry_findings(str(tmp_path), {}) == []
    ev.write_text('{torn mid-stream\n' + good)  # garbage, then more lines
    findings = _telemetry_findings(str(tmp_path), {})
    assert len(findings) == 1 and findings[0]["check"] == "parse"


def test_stall_verdict_rereads_heartbeat_before_kill(tmp_path, monkeypatch):
    """Satellite: a beat landing inside the final poll window must not
    cause a spurious kill. Forced deterministically: the primary mtime
    sample lies 'stale' exactly once; the verdict re-read sees the truth."""
    import os.path as osp

    from featurenet_tpu.train import supervisor as sup_mod

    hb = tmp_path / "hb"
    code = (
        "import os, time\n"
        f"hb={str(hb)!r}\n"
        "for _ in range(30):\n"
        "    open(hb, 'a').close(); os.utime(hb, None); time.sleep(0.05)\n"
    )
    real = osp.getmtime
    state = {"base": None, "fresh_returns": 0, "lied": False}

    def flaky_getmtime(path):
        t = real(path)
        if str(path) != str(hb):
            return t
        if state["base"] is None:
            state["base"] = t  # the supervisor's baseline read
            return t
        if t > state["base"]:
            if state["fresh_returns"] >= 1 and not state["lied"]:
                # The supervisor has already seen a real beat (so
                # first_beat_seen is set); THIS primary sample lies
                # "ancient" — only the verdict re-read sees the truth.
                state["lied"] = True
                return t - 9999.0
            state["fresh_returns"] += 1
        return t

    monkeypatch.setattr(sup_mod.os.path, "getmtime", flaky_getmtime)
    res = sup_mod.supervise(
        [sys.executable, "-c", code],
        stall_timeout_s=1.0,
        max_restarts=1,
        heartbeat_file=str(hb),
        poll_s=0.1,
        grace_s=30.0,
        log=lambda _: None,
    )
    assert state["lied"], "the stale-sample lie must have been exercised"
    assert res.stalls == 0 and res.exit_code == 0
