"""End-to-end fault → recovery: inject via Config.inject_faults, assert the
run COMPLETES with the matching recovery event in events.jsonl.

One test per fault class of the recovery matrix (README "Fault tolerance"):

  corrupt checkpoint → restore falls back a step   → checkpoint_fallback
  SIGTERM preemption → drain-to-checkpoint, exit 75 → preempt + planned
  producer death     → structured crash, restart    → producer_error + restart
  sink ENOSPC        → telemetry dark, run finishes → stderr sink_error

The supervised scenarios run a real supervisor over real spawned training
processes (2-process: supervisor + child), so what is proven is the whole
loop: fault fires → process-level recovery → Orbax resume → full step
budget reached. Children are smoke16-sized (16³, tiny arch, ≤4 steps) to
keep the tier-1 budget honest.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import pytest

from featurenet_tpu import faults, obs
from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer
from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE, supervise


# Process-wide obs/faults state is reset by conftest's autouse
# _reset_process_state fixture (tests-tree fixture hygiene, PR 7).


@pytest.fixture
def no_persistent_compile_cache():
    """Same rationale as test_train.py: a second Trainer over identical
    computations in one process would execute executables deserialized
    from the persistent cache, which fatally aborts in this sandbox."""
    from jax._src import compilation_cache as cc

    jax.config.update("jax_enable_compilation_cache", False)
    cc.reset_cache()
    yield
    jax.config.update("jax_enable_compilation_cache", True)
    cc.reset_cache()


def _mini_cfg(tmp_path, **kw):
    base = dict(
        total_steps=4,
        global_batch=8,
        data_workers=1,
        eval_batches=1,
        log_every=10**9,
        eval_every=10**9,
        checkpoint_every=10**9,
        checkpoint_dir=str(tmp_path / "ckpt"),
        run_dir=str(tmp_path / "run"),
    )
    base.update(kw)
    return get_config("smoke16", **base)


def _events(tmp_path):
    out = []
    for line in open(os.path.join(str(tmp_path), "run", "events.jsonl")):
        out.append(json.loads(line))
    return out


# --- fault class 1: corrupt checkpoint ---------------------------------------

def test_e2e_corrupt_checkpoint_fallback_and_completion(
        tmp_path, no_persistent_compile_cache):
    """Run 1 saves at steps 2 and 4; the injected fault corrupts the step-4
    checkpoint after it finalizes. Run 2 resumes: restore() must fall back
    to step 2 (emitting checkpoint_fallback with both steps), retrain
    2 → 4, and complete."""
    cfg = _mini_cfg(
        tmp_path,
        checkpoint_every=2,
        inject_faults="checkpoint_corrupt@save=2",
    )
    t1 = Trainer(cfg)
    t1.run()
    obs.close_run()
    assert t1.ckpt.latest_step() == 4  # corrupt, but still the latest dir

    t2 = Trainer(cfg)  # marker in run_dir keeps the fault one-shot
    last = t2.run()
    obs.close_run()
    assert int(t2.state.step) == 4 and "loss" in last

    events = _events(tmp_path)
    fb = [e for e in events if e["ev"] == "checkpoint_fallback"]
    assert len(fb) == 1
    assert fb[0]["from_step"] == 4 and fb[0]["to_step"] == 2
    # Run 2 really did restart from the fallback step...
    starts = [e["step"] for e in events if e["ev"] == "loop_start"]
    assert starts == [0, 2]
    # ...and really did finish its full budget.
    assert any(e["ev"] == "run_end" and e["step"] == 4 for e in events)


# --- fault class 2: SIGTERM preemption (in-process drain) --------------------

def test_preemption_drains_to_checkpoint_and_exits_75(tmp_path):
    """The loop-level half of the preemption contract, without processes:
    an injected SIGTERM (a real signal through the real handler) makes the
    run checkpoint at the step boundary and exit RESTART_EXIT_CODE."""
    cfg = _mini_cfg(tmp_path, inject_faults="sigterm@step=2",
                    heartbeat_file=str(tmp_path / "hb"))
    t = Trainer(cfg)
    with pytest.raises(SystemExit) as e:
        t.run()
    obs.close_run()
    assert e.value.code == RESTART_EXIT_CODE
    assert t.ckpt.latest_step() == 2  # exactly-here state, not step 4
    assert os.path.exists(tmp_path / "hb")  # beat: supervisor sees planned
    pre = [e for e in _events(tmp_path) if e["ev"] == "preempt"]
    assert len(pre) == 1 and pre[0]["step"] == 2


# --- supervised scenarios: a real supervisor over real child processes -------

_CHILD = """
import json, sys
from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer
over = json.loads(sys.argv[1])
Trainer(get_config("smoke16", **over)).run()
"""


def _supervised(tmp_path, inject, total_steps=2, max_restarts=2):
    """Run the full 2-process loop: supervise() in this process, training
    children spawned from the CLI-equivalent entry (fresh JAX each)."""
    hb = str(tmp_path / "hb")
    over = dict(
        total_steps=total_steps,
        global_batch=8,
        data_workers=1,
        eval_batches=1,
        log_every=10**9,
        eval_every=10**9,
        checkpoint_every=10**9,
        checkpoint_dir=str(tmp_path / "ckpt"),
        run_dir=str(tmp_path / "run"),
        heartbeat_file=hb,
        inject_faults=inject,
    )
    env_patch = {
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    old = {k: os.environ.get(k) for k in env_patch}
    os.environ.update(env_patch)
    records = []
    try:
        res = supervise(
            [sys.executable, "-c", _CHILD, json.dumps(over)],
            heartbeat_file=hb,
            stall_timeout_s=120,
            grace_s=600,
            max_restarts=max_restarts,
            poll_s=0.2,
            backoff_base_s=0.05,
            log=lambda s: records.append(json.loads(s)),
            run_dir=str(tmp_path / "run"),
        )
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return res, records


def test_e2e_sigterm_preemption_resumes_as_planned(tmp_path):
    """Satellite: supervisor + child; the child is SIGTERMed mid-run (the
    injected fault delivers a real signal at step 1), exits 75, is
    respawned as planned — not a counted restart — and resumes from the
    preemption checkpoint to the full budget."""
    res, records = _supervised(tmp_path, "sigterm@step=1", total_steps=2)
    assert res.exit_code == 0
    assert res.planned == 1  # the preemption was a FREE restart...
    assert res.restarts == 0  # ...not one on the failure budget
    events = _events(tmp_path)
    pre = [e for e in events if e["ev"] == "preempt"]
    assert len(pre) == 1 and pre[0]["step"] == 1
    phases = [e.get("phase") for e in events if e["ev"] == "supervisor"]
    assert "planned_restart" in phases and "done" in phases
    # Child 2 resumed from the preemption checkpoint, then finished.
    starts = [e["step"] for e in events if e["ev"] == "loop_start"]
    assert starts == [0, 1]
    assert any(e["ev"] == "run_end" and e["step"] == 2 for e in events)


def test_e2e_producer_death_restart_and_completion(tmp_path):
    """The prefetch producer dies mid-run (injected crash on its second
    ticket): the train loop surfaces the worker's traceback (no deadlock),
    the child exits nonzero, the supervisor backs off and restarts, the
    fresh child (fault marker: one-shot per run) completes the budget."""
    res, records = _supervised(tmp_path, "producer_crash@batch=1",
                               total_steps=2)
    assert res.exit_code == 0
    assert res.restarts == 1 and res.planned == 0
    events = _events(tmp_path)
    warn = [e for e in events
            if e["ev"] == "warning" and e.get("name") == "producer_error"]
    assert len(warn) == 1 and warn[0]["worker"] == 0
    phases = [e.get("phase") for e in events if e["ev"] == "supervisor"]
    assert "backoff" in phases and "restart" in phases and "done" in phases
    assert any(e["ev"] == "run_end" and e["step"] == 2 for e in events)


# --- fault class 4: sink ENOSPC ----------------------------------------------

def test_e2e_sink_enospc_training_survives(tmp_path, capsys):
    """Telemetry is never load-bearing: the event sink hits (injected)
    ENOSPC mid-run, degrades to a one-time stderr warning + no-op, and the
    run still completes. The stream on disk stays whole-line valid."""
    cfg = _mini_cfg(tmp_path, inject_faults="sink_enospc@emit=12")
    t = Trainer(cfg)
    last = t.run()
    obs.close_run()
    assert int(t.state.step) == 4 and "loss" in last
    err = capsys.readouterr().err
    assert err.count("sink_error") == 1
    assert "fault_injected" in err
    events = _events(tmp_path)  # every line before the fault parses clean
    assert len(events) == 11  # emits 1..11 landed; #12 died; then dark
    assert not any(e["ev"] == "run_end" for e in events)  # post-fault


# --- soak: repeatable preemption through many cycles --------------------------

def test_e2e_soak_repeatable_sigterm_three_cycles(tmp_path):
    """Soak e2e (carried-over ROADMAP follow-on): a repeatable SIGTERM
    (``sigterm@step=1:every=1``) preempts the run at EVERY step; one
    supervised run must ride >= 3 preempt/resume cycles — each a drained
    checkpoint + free planned respawn — and still reach its full step
    budget. Gated on the report's recovery section: cycle count, zero
    unplanned restarts/stalls, terminal run_end at the budget."""
    res, records = _supervised(tmp_path, "sigterm@step=1:every=1",
                               total_steps=4, max_restarts=2)
    assert res.exit_code == 0
    assert res.planned == 3      # three preempt/resume cycles...
    assert res.restarts == 0     # ...none of them on the failure budget
    events = _events(tmp_path)
    from featurenet_tpu.obs.report import build_report

    rep = build_report(events)
    assert rep["recovery"]["preempts"] == 3
    assert rep["supervisor"]["planned_restarts"] == 3
    assert rep["supervisor"]["restarts"] == 0
    assert rep["supervisor"]["stalls"] == 0
    assert any(e["ev"] == "run_end" and e["step"] == 4 for e in events)
    # The same verdict through the gate machinery: pin "no unplanned
    # recovery activity" and judge the soak's own report against it.
    from featurenet_tpu.obs import gates as obs_gates

    gate = obs_gates.evaluate_gates(
        obs_gates.report_gate_values(rep),
        obs_gates.make_baseline({"restarts": 0.0, "stalls": 0.0},
                                tolerance=0.0),
    )
    assert gate["ok"], gate
