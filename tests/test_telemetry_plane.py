"""Telemetry control plane (ISSUE 16): the run_dir time-series store
(append/rotate/prune/torn-tail reads), the fleet /metrics scraper and
its closed-registry filter, multi-window burn-rate SLO parsing + math +
fire/resolve hysteresis, the exposition-compliance contract over both
exporters (parser-based: names ⊆ METRIC_NAMES, exactly one HELP/TYPE
pair per family), the ``cli dash`` frame, the bench-history trend gate,
and the report's store-only fleet timeline.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from featurenet_tpu import obs
from featurenet_tpu.fleet.pool import ConnectionPool
from featurenet_tpu.fleet.scraper import (
    ROUTER_TARGET,
    MetricsScraper,
    parse_exposition,
)
from featurenet_tpu.obs import alerts as _alerts
from featurenet_tpu.obs import tsdb as _tsdb
from featurenet_tpu.obs import windows as _windows
from featurenet_tpu.obs.report import load_events
from featurenet_tpu.serve.metrics import (
    _PREFIX,
    METRIC_NAMES,
    render_metrics,
    render_router_metrics,
)

T0 = 1_700_000_000.0  # fixed epoch anchor: every series test pins `now`


# --- the time-series store ---------------------------------------------------

def test_tsdb_append_query_roundtrip(tmp_path):
    store = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    for i in range(5):
        assert store.append("serving_ms", 10.0 + i,
                            {"q": "0.99", "replica": "0"}, t=T0 + i)
    # Same metric, different label set = a different series.
    store.append("serving_ms", 99.0, {"q": "0.99", "replica": "1"},
                 t=T0 + 2)
    # Superset label match merges replicas; exact labels isolate one.
    merged = store.query("serving_ms", {"q": "0.99"})
    assert len(merged) == 6
    assert [t for t, _ in merged] == sorted(t for t, _ in merged)
    only0 = store.query("serving_ms", {"q": "0.99", "replica": "0"})
    assert [v for _, v in only0] == [10.0, 11.0, 12.0, 13.0, 14.0]
    # Look-back window restriction against an explicit `now`.
    recent = store.query("serving_ms", {"replica": "0"}, since_s=2.0,
                         now=T0 + 4)
    assert [v for _, v in recent] == [12.0, 13.0, 14.0]
    # latest() is the newest sample across matching series.
    assert store.latest("serving_ms", {"q": "0.99"}) == (T0 + 4, 14.0)
    assert store.latest("nope") is None
    # series() lists every (metric, labels) on disk.
    assert (("serving_ms", {"q": "0.99", "replica": "1"})
            in store.series())
    st = store.stats()
    assert st["appended"] == 6 and st["dropped"] == 0
    assert not st["dark"] and st["series"] == 2
    store.close()


def test_tsdb_percentile_is_nearest_rank(tmp_path):
    store = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    for i in range(101):  # values 0..100
        store.append("serving_ms", float(i), {"q": "0.5"}, t=T0 + i)
    assert store.percentile("serving_ms", 50, {"q": "0.5"}) == 50.0
    assert store.percentile("serving_ms", 99, {"q": "0.5"}) == 99.0
    assert store.percentile("serving_ms", 99, {"q": "0.95"}) is None
    store.close()


def test_tsdb_series_key_roundtrip_and_sanitize():
    key = _tsdb.series_key("serving_ms", {"replica": "0", "q": "0.99"})
    # Sorted label order: dict order never splits a series.
    assert key == "serving_ms;q=0.99;replica=0"
    assert _tsdb.parse_series_key(key) == (
        "serving_ms", {"q": "0.99", "replica": "0"})
    # Unsafe chars collapse to "_" — the key IS a filename.
    assert _tsdb.series_key("bad name", {"k/": "a b"}) == \
        "bad_name;k_=a_b"


def test_tsdb_reader_skips_torn_tail_and_garbage(tmp_path):
    store = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    for i in range(3):
        store.append("ready", 1.0, {"replica": "0"}, t=T0 + i)
    store.close()
    (seg,) = [os.path.join(store.root, n)
              for n in os.listdir(store.root)]
    with open(seg, "ab") as fh:
        fh.write(b"not json at all\n")          # foreign line: skipped
        fh.write(b'{"t":' + str(T0).encode())   # torn tail: no newline
    samples = store.query("ready", {"replica": "0"})
    assert len(samples) == 3
    # A reopened writer appends past the torn tail; the new sample is
    # readable, the tear stays skipped.
    store2 = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    store2.append("ready", 0.0, {"replica": "0"}, t=T0 + 9)
    assert store2.query("ready")[-1] == (T0 + 9, 0.0)
    store2.close()


def test_tsdb_rotation_resume_and_prune(tmp_path):
    root = str(tmp_path / "ts")
    store = _tsdb.TimeSeriesStore(root, segment_bytes=64,
                                  max_bytes=10**9)
    for i in range(10):
        store.append("ready", float(i), t=T0 + i)
    segs = sorted(os.listdir(root))
    assert len(segs) > 1, segs  # rotated
    assert all(re.fullmatch(r"ready\.\d{6}\.jsonl", n) for n in segs)
    assert [v for _, v in store.query("ready")] == \
        [float(i) for i in range(10)]
    store.close()
    # A reopened store resumes the HIGHEST segment, not segment 0.
    store2 = _tsdb.TimeSeriesStore(root, segment_bytes=64,
                                   max_bytes=10**9)
    store2.append("ready", 10.0, t=T0 + 10)
    assert sorted(os.listdir(root)) == segs  # no new file yet
    assert store2.query("ready")[-1][1] == 10.0
    store2.close()
    # Ring prune: a tight byte budget drops the OLDEST closed segments
    # on rotation; the newest samples always survive.
    proot = str(tmp_path / "pruned")
    pstore = _tsdb.TimeSeriesStore(proot, segment_bytes=64,
                                   max_bytes=150)
    for i in range(30):
        pstore.append("ready", float(i), t=T0 + i)
    vals = [v for _, v in pstore.query("ready")]
    assert vals[-1] == 29.0
    assert len(vals) < 30          # something was pruned
    assert 0.0 not in vals         # and it was the oldest
    pstore.close()


def test_tsdb_goes_dark_on_oserror(tmp_path):
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    # Root "under" a regular file: the first append's makedirs raises,
    # the store degrades dark and counts drops — it never raises.
    store = _tsdb.TimeSeriesStore(str(blocker / "ts"))
    assert store.append("ready", 1.0) is False
    assert store.append("ready", 1.0) is False
    st = store.stats()
    assert st["dark"] and st["dropped"] == 2 and st["appended"] == 0
    assert store.query("ready") == []
    store.close()


def test_tsdb_drop_counter_exact_under_concurrent_appends(tmp_path):
    """Regression (concurrency lint): ``dropped`` is bumped on the dark
    path from whatever thread held the sample — concurrent appenders
    are part of the store's contract, so the counter read-modify-write
    must hold ``_lock`` (as ``appended`` always did) and come out
    exact."""
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    store = _tsdb.TimeSeriesStore(str(blocker / "ts"))
    store.append("ready", 1.0)  # first append trips the dark latch
    before = store.stats()["dropped"]
    n_threads, per_thread = 8, 200

    def hammer():
        for i in range(per_thread):
            store.append("ready", float(i))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    st = store.stats()
    assert st["dropped"] == before + n_threads * per_thread
    assert st["appended"] == 0
    store.close()


# --- exposition parsing ------------------------------------------------------

def test_parse_exposition_labels_escapes_and_garbage():
    text = "\n".join([
        "# HELP featurenet_x doc",
        "# TYPE featurenet_x counter",
        "featurenet_x 3",
        'featurenet_y{a="1",b="with,comma"} 2.5',
        'featurenet_z{msg="esc\\"aped"} 1 1700000000',  # timestamp ok
        "malformed_no_value",
        'featurenet_bad{a=unquoted} 1',
        "featurenet_nan notanumber",
        "",
    ])
    out = parse_exposition(text)
    assert ("featurenet_x", {}, 3.0) in out
    assert ("featurenet_y", {"a": "1", "b": "with,comma"}, 2.5) in out
    assert ("featurenet_z", {"msg": 'esc"aped'}, 1.0) in out
    assert len(out) == 3  # the malformed lines vanished, not raised


def test_parse_exposition_exotic_but_legal_text():
    """Prometheus text a foreign exporter could legally emit: exponent
    floats, millisecond timestamps, untyped families, non-finite
    values, and the escaped-backslash-before-n trap."""
    import math

    text = "\n".join([
        'featurenet_z{msg="a\\\\nb"} 1e3 1700000000123',
        "featurenet_naked NaN",         # no HELP/TYPE, non-finite value
        "featurenet_up +Inf",
        "featurenet_down -Inf",
        'featurenet_bad{unclosed="x} 1',   # brace inside the quotes
        'featurenet_noval{a="b"}',         # sample with no value
    ])
    out = parse_exposition(text)
    # The escaped backslash survives as a backslash followed by a
    # LITERAL n — not a newline (single-pass unescape, not sequential
    # replaces).
    assert ("featurenet_z", {"msg": "a\\nb"}, 1000.0) in out
    by_name = {n: v for n, _, v in out}
    assert math.isnan(by_name["featurenet_naked"])
    assert by_name["featurenet_up"] == float("inf")
    assert by_name["featurenet_down"] == float("-inf")
    assert len(out) == 4  # both malformed lines skipped


def test_label_escaping_roundtrips_through_parser():
    """Exporter → scraper round-trip for every escape the exposition
    format defines, including values where an escaped backslash
    precedes a quote or an ``n``."""
    from featurenet_tpu.serve.metrics import _escape_label

    for raw in ("plain", 'quo"te', "new\nline", "back\\slash",
                "a\\nb", "trail\\", '\\"mix\n\\'):
        line = f'featurenet_x{{v="{_escape_label(raw)}"}} 1'
        ((_, labels, value),) = parse_exposition(line)
        assert labels["v"] == raw, raw
        assert value == 1.0


def test_exporter_formats_nonfinite_values():
    """Both exporters lean on one value formatter; NaN/±Inf must render
    as the exposition spellings, never as Python's ``nan``/``inf`` (a
    strict scraper rejects those)."""
    from featurenet_tpu.serve.metrics import _fmt

    assert _fmt(float("nan")) == "NaN"
    assert _fmt(float("inf")) == "+Inf"
    assert _fmt(float("-inf")) == "-Inf"
    assert _fmt(True) == "1"
    assert _fmt(3) == "3"
    # And the scraper's parser takes every spelling straight back.
    for s in ("NaN", "+Inf", "-Inf", "1"):
        assert parse_exposition(f"featurenet_x {s}")


# --- exposition compliance (satellite: both exporters) -----------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})? (?P<value>-?[0-9.eE+-]+|NaN|[+-]?Inf)$"
)


def _check_exposition(text: str) -> set:
    """The strict consumer the scraper deliberately isn't: every line
    well-formed, every family ⊆ the closed registry with exactly one
    HELP/TYPE pair, HELP before TYPE before the first sample."""
    first_help: dict = {}
    first_type: dict = {}
    first_sample: dict = {}
    helps, types = [], []
    lines = text.splitlines()
    assert text.endswith("\n") and lines
    for i, line in enumerate(lines):
        assert line == line.strip() and line, repr(line)
        if line.startswith("# HELP "):
            name, _, doc = line[len("# HELP "):].partition(" ")
            assert doc.strip(), line
            helps.append(name)
            first_help.setdefault(name, i)
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            assert kind in ("counter", "gauge"), line
            types.append(name)
            first_type.setdefault(name, i)
            continue
        assert not line.startswith("#"), line
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name = m.group("name")
        assert parse_exposition(line), line
        assert name.startswith(_PREFIX), line
        assert name[len(_PREFIX):] in METRIC_NAMES, line
        first_sample.setdefault(name, i)
    # Exactly one HELP/TYPE pair per family; every sampled family has
    # one and vice versa (no orphan metadata, no bare samples).
    assert len(helps) == len(set(helps)), helps
    assert len(types) == len(set(types)), types
    assert set(helps) == set(types) == set(first_sample)
    for name in first_sample:
        assert first_help[name] < first_type[name] < first_sample[name]
    return set(first_sample)


class _StubService:
    cfg = SimpleNamespace(
        serve_precision="fp32",
        arch=SimpleNamespace(conv_backend="reference"),
    )

    def health(self):
        return {"ready": True, "uptime_s": 12.5, "window_seq": 3}

    def stats(self):
        return {"served": 10, "rejected": 1, "errors": 0,
                "queue_depth": 2, "occupancy": 0.5}


class _StubFleet:
    def candidates(self):
        return []

    def note_inflight(self, slot, delta):
        pass

    def note_failure(self, slot):
        pass

    def ready_count(self):
        return 1

    def stats(self):
        return {"replicas": 1}


def test_service_exposition_compliance():
    # Give the window gauges something to export: compliance must hold
    # WITH the quantile families present, not just the counters.
    _windows.install(_windows.WindowAggregator(rules=[]))
    for v in (5.0, 10.0, 50.0):
        _windows.observe("serving_ms", v)
    families = _check_exposition(render_metrics(_StubService()))
    assert f"{_PREFIX}build_info" in families
    assert f"{_PREFIX}serving_ms" in families
    assert f"{_PREFIX}serving_ms_count" in families
    text = render_metrics(_StubService())
    # build_info: constant 1, labels carry the build identity triplet.
    (bi,) = [ln for ln in text.splitlines()
             if ln.startswith(f"{_PREFIX}build_info")]
    (_, labels, value), = parse_exposition(bi)
    assert value == 1.0
    assert labels["serve_precision"] == "fp32"
    assert labels["conv_backend"] == "reference"
    assert labels["jax_version"] not in ("", "unknown")


def test_router_exposition_compliance():
    from featurenet_tpu.fleet.router import FleetRouter

    router = FleetRouter(_StubFleet(), rules=(), scale_every_s=3600.0)
    try:
        families = _check_exposition(render_router_metrics(router))
    finally:
        router.drain()
    assert f"{_PREFIX}fleet_requests_total" in families
    assert f"{_PREFIX}build_info" in families
    # The empty retired-reason family still emits (a counter that can
    # never be scraped as absent).
    assert f"{_PREFIX}connections_retired_total" in families


# --- burn-rate SLOs ----------------------------------------------------------

def test_parse_slos_accepts_and_refuses():
    (r,) = _alerts.parse_slos("serving_p99_ms<250@99%")
    assert (r.metric, r.op, r.threshold) == ("serving_p99_ms", "<",
                                             250.0)
    assert r.objective == pytest.approx(0.99)
    assert r.budget == pytest.approx(0.01)
    assert r.severity == "critical" and r.name == "serving_p99_ms_burn"
    (q,) = _alerts.parse_slos("queue_wait_ms_p95<50@95%:warning",
                              fast_s=30.0, slow_s=600.0)
    assert q.severity == "warning"
    assert (q.fast_s, q.slow_s) == (30.0, 600.0)
    # None/empty = the default objective, windows threaded through.
    (d,) = _alerts.parse_slos(None, fast_s=5.0, slow_s=60.0)
    assert d.metric == "serving_p99_ms" and d.fast_s == 5.0
    for bad, why in [
        ("serving_p99_ms=250@99%", "malformed"),
        ("made_up_metric<250@99%", "unknown burn-rate metric"),
        ("serving_p99_ms<250@99%,serving_p99_ms<9@50%", "duplicate"),
        ("serving_p99_ms<250@100%", "error budget"),
        ("serving_p99_ms<250@99%:fatal", "unknown SLO severity"),
        (",", "empty"),
    ]:
        with pytest.raises(ValueError, match=why):
            _alerts.parse_slos(bad)


def test_burn_selector_maps_percentile_stats():
    assert _alerts.burn_selector("serving_p99_ms") == \
        ("serving_ms", {"q": "0.99"})
    assert _alerts.burn_selector("queue_wait_ms_p50") == \
        ("queue_wait_ms", {"q": "0.5"})
    assert _alerts.burn_selector("serving_ms_mean") is None
    assert "serving_p99_ms" in _alerts.known_burn_metrics()


def test_burn_rate_math_and_honest_absence():
    rule = _alerts.BurnRateRule("serving_p99_ms", "<", 100.0, 0.99)
    # 2 bad of 100 → bad fraction 0.02 over a 0.01 budget → burn 2.0.
    samples = [(T0 - i, 50.0) for i in range(98)] + \
        [(T0 - 1, 400.0), (T0 - 2, 400.0)]
    assert _alerts.burn_rate(samples, rule, 300.0, now=T0) == \
        pytest.approx(2.0)
    # An empty window is None, not zero: absence can't resolve anything.
    assert _alerts.burn_rate([], rule, 300.0, now=T0) is None
    assert _alerts.burn_rate(samples, rule, 300.0, now=T0 + 10_000) \
        is None
    # op states the GOOD direction.
    up = _alerts.BurnRateRule("serving_p99_ms", ">", 10.0, 0.5)
    assert up.bad(5.0) and not up.bad(20.0)


def test_burn_evaluator_fire_resolve_hysteresis(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    store = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    rule = _alerts.BurnRateRule("serving_p99_ms", "<", 250.0, 0.99,
                                fast_s=10.0, slow_s=60.0)
    ev = _alerts.BurnEvaluator(store, [rule])
    # No samples: both windows None, nothing fires.
    res = ev.evaluate(now=T0)["serving_p99_ms"]
    assert res == {"fast": None, "slow": None, "firing": False,
                   "active": False}
    # Sustained badness across BOTH windows.
    for i in range(20):
        store.append("serving_ms", 400.0, {"q": "0.99", "replica": "0"},
                     t=T0 - i)
    res = ev.evaluate(now=T0)["serving_p99_ms"]
    assert res["firing"] and res["fast"] > 1.0 and res["slow"] > 1.0
    assert ev.active_alerts() == ["serving_p99_ms"]
    # Hysteresis: still firing → no second fire event.
    ev.evaluate(now=T0)
    # Recovery floods the FAST window with good samples; the slow
    # window still burns, so firing drops (both must burn) → resolve.
    for i in range(20):
        store.append("serving_ms", 50.0, {"q": "0.99", "replica": "0"},
                     t=T0 + 30 + i * 0.4)
    res = ev.evaluate(now=T0 + 40)["serving_p99_ms"]
    assert not res["firing"] and res["fast"] == 0.0
    assert res["slow"] is not None and res["slow"] > 1.0
    assert ev.active_alerts() == []
    store.close()
    obs.close_run()
    events, bad = load_events(run_dir)
    assert bad == 0
    alerts = [e for e in events if e["ev"] == "alert"]
    assert [(e["rule"], e["state"]) for e in alerts] == \
        [("serving_p99_ms_burn", "fire"),
         ("serving_p99_ms_burn", "resolve")]
    assert alerts[0]["severity"] == "critical"
    assert alerts[0]["threshold"] == 1.0  # max_burn, not the SLO ms


# --- the scraper -------------------------------------------------------------

def _exporter(text: str):
    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def do_GET(self):  # noqa: N802
            body = text.encode()
            code = 200 if self.path == "/metrics" else 404
            self.send_response(code)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _dead_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_scraper_collects_filters_and_records_failures(tmp_path):
    srv = _exporter(
        "# HELP featurenet_ready doc\n"
        "# TYPE featurenet_ready gauge\n"
        "featurenet_ready 1\n"
        'featurenet_serving_ms{q="0.99"} 12.5\n'
        "featurenet_not_registered_total 7\n"
        "half a line\n"
    )
    store = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    pool = ConnectionPool()
    targets = {"0": srv.server_address[1], ROUTER_TARGET: _dead_port()}
    sc = MetricsScraper(store, pool, lambda: targets, interval_s=0.05)
    try:
        n = sc.scrape_once()
        assert n == 2  # ready + serving_ms; the unregistered one skipped
        assert sc.skipped == 1
        # Samples land labeled with the emitting target.
        assert store.latest("ready", {"replica": "0"})[1] == 1.0
        assert store.latest(
            "serving_ms", {"q": "0.99", "replica": "0"})[1] == 12.5
        # The dead router target: a failure is itself a series.
        assert store.latest("scrape_failures_total",
                            {"replica": ROUTER_TARGET})[1] == 1.0
        sc.scrape_once()
        assert store.latest("scrape_failures_total",
                            {"replica": ROUTER_TARGET})[1] == 2.0
        # Collection wall per live target, every round.
        assert len(store.query("scrape_duration_ms",
                               {"replica": "0"})) == 2
        st = sc.stats()
        assert st["rounds"] == 2 and st["samples"] == 4
        assert st["failures"] == {ROUTER_TARGET: 2}
        # Every series the scraper wrote is in the closed registry.
        for metric, _labels in store.series():
            assert metric in METRIC_NAMES, metric
        # Thread lifecycle: runs jittered rounds, stop() takes a final
        # synchronous round.
        sc.start()
        deadline = time.monotonic() + 10
        while sc.rounds < 4 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sc.rounds >= 4
        sc.pause(True)
        assert sc.stats()["paused"]
        sc.stop()  # final_round=True
    finally:
        sc.stop(final_round=False)
        pool.close()
        store.close()
        srv.shutdown()


def test_scraper_survives_targets_callable_raising(tmp_path):
    store = _tsdb.TimeSeriesStore(str(tmp_path / "ts"))
    pool = ConnectionPool()

    def boom():
        raise RuntimeError("roster race")

    sc = MetricsScraper(store, pool, boom)
    assert sc.scrape_once() == 0  # a round, not a raise
    assert sc.rounds == 1
    pool.close()
    store.close()


# --- cli dash ----------------------------------------------------------------

def _synthetic_fleet_store(run_dir: str, now: float) -> None:
    store = _tsdb.TimeSeriesStore.open(run_dir)
    for i in range(10):
        t = now - 10 + i
        store.append("requests_total", i * 5.0,
                     {"outcome": "served", "replica": "0"}, t=t)
        store.append("serving_ms", 20.0 + i,
                     {"q": "0.99", "replica": "0"}, t=t)
        store.append("serve_queue_depth", 1.0, {"replica": "0"}, t=t)
        store.append("fleet_requests_total", i * 9.0,
                     {"outcome": "answered", "replica": "router"}, t=t)
        store.append("serving_ms", 30.0,
                     {"q": "0.99", "replica": "router"}, t=t)
    store.append("ready", 1.0, {"replica": "0"}, t=now)
    store.append("connections_opened_total", 2.0,
                 {"replica": "router"}, t=now)
    store.append("connections_reused_total", 8.0,
                 {"replica": "router"}, t=now)
    store.append("scrape_failures_total", 3.0,
                 {"replica": "router"}, t=now)
    store.close()


def test_render_frame_from_store_alone(tmp_path):
    from featurenet_tpu.obs.dash import render_frame

    run_dir = str(tmp_path / "run")
    _synthetic_fleet_store(run_dir, T0)
    frame = render_frame(run_dir, now=T0)
    lines = frame.splitlines()
    assert lines[0].startswith(f"fleet dash · {run_dir}")
    assert "2 target(s)" in lines[0]
    # Replicas first, router last; per-target last-value columns.
    rows = [ln for ln in lines if ln.startswith(("0 ", "router"))]
    assert len(rows) == 2 and rows[0].startswith("0")
    assert "29.0" in rows[0]      # last replica p99
    assert "30.0" in rows[1]      # router p99 gauge
    # The burn gauge uses the SAME math the router verdicts judge.
    (burn,) = [ln for ln in lines if ln.startswith("burn ")]
    assert "burn serving_p99_ms (<250@99%)" in burn
    assert "[ok]" in burn
    assert "conn reuse: 0.800 (opened 2, reused 8)" in frame
    assert "roster: 1/1 replicas ready · scrape failures: 3" in frame


def test_cli_dash_once_smoke(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main

    run_dir = str(tmp_path / "run")
    _synthetic_fleet_store(run_dir, time.time())
    cli_main(["dash", run_dir, "--once"])
    out = capsys.readouterr().out
    assert out.startswith("fleet dash ·")
    assert "roster: 1/1 replicas ready" in out
    # An empty run_dir still renders (0 targets, honest absence).
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    cli_main(["dash", empty, "--once"])
    assert "0 target(s)" in capsys.readouterr().out
    # A bad --slos spec is a config-time refusal, not a stacktrace.
    with pytest.raises(SystemExit, match="dash:"):
        cli_main(["dash", run_dir, "--once", "--slos",
                  "made_up<1@99%"])


def test_cli_report_renders_fleet_timeline(tmp_path, capsys):
    # The user-facing `cli report` path must fold the tsdb timeline in —
    # not just build_report_dir (which only tests call). Regression pin
    # for the CLI wiring.
    from featurenet_tpu.cli import main as cli_main

    run_dir = str(tmp_path / "run")
    _synthetic_fleet_store(run_dir, time.time())
    with open(os.path.join(run_dir, "events.jsonl"), "w") as fh:
        fh.write(json.dumps({"t": T0, "ev": "run_start", "pid": 1,
                             "process_index": 0}) + "\n")
    cli_main(["report", run_dir])
    out = capsys.readouterr().out
    assert "fleet timeline (tsdb" in out
    assert "router" in out
    # A store-less run_dir reports without the section (honest absence).
    bare = str(tmp_path / "bare")
    os.makedirs(bare)
    with open(os.path.join(bare, "events.jsonl"), "w") as fh:
        fh.write(json.dumps({"t": T0, "ev": "run_start", "pid": 1,
                             "process_index": 0}) + "\n")
    cli_main(["report", bare])
    assert "fleet timeline" not in capsys.readouterr().out


# --- bench-history trend gate ------------------------------------------------

def _write_round(d: str, n: int, record: dict) -> None:
    with open(os.path.join(d, f"BENCH_r{n}.json"), "w") as fh:
        json.dump(record, fh)


def test_trend_gate_judges_last_two_parseable_rounds(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs.bench_history import (
        format_trend_gate,
        load_rounds,
        trend_gate,
    )

    d = str(tmp_path)
    _write_round(d, 1, {"value": 1000.0, "serve_p99_ms": 10.0,
                        "mfu": 0.30, "scrape_overhead_pct": 2.0})
    _write_round(d, 2, {"skipped": True, "reason": "no accelerator"})
    # Throughput halves; p99 drifts but inside tolerance + abs slack;
    # mfu vanishes (dropped), a new key appears (gained).
    _write_round(d, 3, {"value": 500.0, "serve_p99_ms": 10.5,
                        "scrape_overhead_pct": 3.0,
                        "serve_qps_sustained": 900.0})
    rows = load_rounds(d)
    res = trend_gate(rows)
    assert not res["ok"]
    assert res["failed"] == ["value"]
    assert (res["baseline_round"], res["candidate_round"]) == \
        ("r01", "r03")  # the skipped round is not a baseline
    assert res["dropped"] == ["mfu"]
    assert res["gained"] == ["serve_qps_sustained"]
    text = format_trend_gate(res)
    assert text.startswith("trend gate (r03 vs r01): FAIL")
    assert "FAIL value" in text
    assert "no longer measured: mfu" in text
    # The CLI gate is CI-able: exit 2 on regression, no baseline file.
    with pytest.raises(SystemExit) as ei:
        cli_main(["bench-history", d, "--gate"])
    assert ei.value.code == 2
    capsys.readouterr()
    # Fewer than two parseable rounds: trivially ok, with the note.
    solo = trend_gate(rows[:2])
    assert solo["ok"] and "nothing to trend" in solo["note"]
    assert "trend gate: ok" in format_trend_gate(solo)


def test_trend_gate_passes_within_slack(tmp_path):
    from featurenet_tpu.obs.bench_history import load_rounds, trend_gate

    d = str(tmp_path)
    _write_round(d, 1, {"value": 1000.0, "scrape_overhead_pct": 1.0})
    # Throughput within 10% relative; scrape tax jumps but sits inside
    # the shared NOISY_KEY_ABS_SLACK room (same table as the self-pin).
    _write_round(d, 2, {"value": 950.0, "scrape_overhead_pct": 6.0})
    res = trend_gate(load_rounds(d))
    assert res["ok"], res


def test_trend_gate_skips_latest_skipped_and_unparseable_rounds(
    tmp_path, capsys
):
    """A TPU outage as the LATEST round (structured skip or the bare
    driver wrapper with ``parsed: null``) must not fail --gate: the gate
    judges the last two PARSEABLE rounds, and the table still renders
    the outage rows with their reasons."""
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs.bench_history import load_rounds, trend_gate

    d = str(tmp_path)
    _write_round(d, 1, {"value": 1000.0})
    _write_round(d, 2, {"value": 990.0})
    _write_round(d, 3, {"skipped": True, "reason": "no accelerator"})
    _write_round(d, 4, {"n": 4, "cmd": "python bench.py", "rc": 1,
                        "tail": "boom", "parsed": None})
    rows = load_rounds(d)
    assert [r["status"] for r in rows] == \
        ["ok", "ok", "skipped", "unparseable"]
    res = trend_gate(rows)
    assert res["ok"]
    assert (res["baseline_round"], res["candidate_round"]) == \
        ("r01", "r02")
    assert cli_main(["bench-history", d, "--gate"]) is None  # exit 0
    out = capsys.readouterr().out
    assert "no accelerator" in out       # the outage keeps its row
    assert "unparseable" in out


# --- report: the store-only fleet timeline -----------------------------------

def test_fleet_timeline_section_from_store_alone(tmp_path):
    from featurenet_tpu.obs.report import fleet_timeline_section

    # No store at all → None (no fleet ran).
    assert fleet_timeline_section(str(tmp_path / "nowhere")) is None
    run_dir = str(tmp_path / "run")
    _synthetic_fleet_store(run_dir, T0)
    sec = fleet_timeline_section(run_dir)
    assert sec is not None
    assert sorted(sec["targets"]) == ["0", "router"]
    rep0 = sec["targets"]["0"]
    assert rep0["samples"] == 10
    assert rep0["p99_ms_last"] == 29.0
    assert rep0["p99_ms_max"] == 29.0
    assert rep0["spark"].strip()
    assert sec["scrape_failures"] == 3
    # "now" pins to the store's LAST sample, not the reading wall clock.
    assert sec["t_end"] == pytest.approx(T0)
