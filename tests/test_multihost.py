"""Multi-host training path: 2 real processes, 2 CPU devices each.

The reference's multi-node story is torchrun + NCCL rendezvous (SURVEY.md §2
C5). Ours is ``jax.distributed.initialize`` + a global mesh + per-host data
sharding assembled with ``make_array_from_process_local_data``
(``data.dataset.put_batch``). That path has process_count()==1 shortcuts
everywhere, so a single-process CI run never touches it — this test spawns
two coordinated worker processes on the CPU backend (2 virtual devices each
→ a 4-device global mesh) and runs real training steps through the
multi-process branches.

Every cross-process value the compiled step produces (loss, accuracy,
grad_norm are global means/sums over the data axis) must agree bitwise
across hosts — the TPU-native equivalent of "DDP keeps replicas in sync".
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys


_WORKER = r"""
import json, sys
pid, nproc, port, steps, cache = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], int(sys.argv[4]),
    sys.argv[5],
)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 2 * nproc, jax.devices()

from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer

cfg = get_config(
    "smoke16",
    global_batch=8,
    total_steps=steps,
    data_workers=1,
    log_every=1,
    eval_every=10**9,
    checkpoint_every=10**9,
    eval_batches=1,
    data_cache=cache or None,
)
trainer = Trainer(cfg)
last = trainer.run()
print("FINAL " + json.dumps(
    {k: float(v) for k, v in last.items()
     if isinstance(v, (int, float)) and not isinstance(v, bool)}
))
if cache:
    # Host-sharded exact eval: each host walks its decimation of the
    # held-out split; global sums must agree bitwise AND count every
    # sample exactly once (the confusion total is the proof).
    import numpy as np
    ev = trainer.evaluate()
    print("EVAL " + json.dumps({
        "accuracy": ev["accuracy"],
        "loss": ev["loss"],
        "n_evaluated": int(np.asarray(ev["confusion"]).sum()),
    }))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(
    port: int, steps: int, nproc: int, cache: str = ""
) -> list[str]:
    """Spawn, concurrently drain, and always reap the worker processes.

    Concurrent draining matters: a worker that fills its unread stdout pipe
    blocks, stalling its peer at the next collective. The finally block
    guarantees no orphan survives a timeout or assertion (an orphan would
    pin the coordinator port and wedge later runs).
    """
    import threading

    env = {
        **os.environ,
        # Subprocesses must dodge both the axon TPU plugin (PYTHONPATH
        # bypass) and this test process's own forced-CPU config.
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), str(nproc), str(port),
             str(steps), cache],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = [""] * nproc

    def drain(i: int, p: subprocess.Popen) -> None:
        outs[i] = p.communicate()[0]

    threads = [
        threading.Thread(target=drain, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    try:
        import time

        for t in threads:
            t.start()
        deadline = 600
        end = time.monotonic() + deadline  # shared bound, not per-thread
        for t in threads:
            t.join(timeout=max(0.0, end - time.monotonic()))
        if any(t.is_alive() for t in threads):
            raise AssertionError(
                f"workers did not finish within {deadline}s: "
                + " | ".join(o[-500:] for o in outs)
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=30)
    return outs


def test_two_process_training_stays_in_sync(tmp_path):
    from featurenet_tpu.data.offline import (
        VoxelCacheDataset,
        export_synthetic_cache,
    )

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=2, resolution=16)
    held_out = len(VoxelCacheDataset(cache, global_batch=8, split="test"))

    steps, nproc = 3, 2
    outs = []
    # The free-port probe races with the coordinator's bind (TOCTOU);
    # retry once on a fresh port if the rendezvous itself failed to bind.
    for attempt in range(2):
        outs = _run_workers(_free_port(), steps, nproc, cache=cache)
        if not any("ddress already in use" in o for o in outs):
            break
    for i, out in enumerate(outs):
        assert "FINAL " in out, f"worker {i} failed:\n{out}"

    finals, evals = [], []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("FINAL ")]
        assert lines, out
        finals.append(json.loads(lines[-1][len("FINAL "):]))
        ev_lines = [l for l in out.splitlines() if l.startswith("EVAL ")]
        assert ev_lines, out
        evals.append(json.loads(ev_lines[-1][len("EVAL "):]))
    # Global metrics must agree across hosts bitwise: each host ran the
    # same compiled step over the same global (sharded) batch.
    assert finals[0].keys() == finals[1].keys()
    for k in finals[0]:
        if k == "samples_per_sec":  # host-local wall clock, never synced
            continue
        assert finals[0][k] == finals[1][k], (k, finals)
    # And training actually happened: the final loss is a finite number
    # produced by `steps` real optimizer updates.
    assert finals[0]["loss"] > 0.0
    # Host-sharded exact eval: bitwise-identical global results on every
    # host, and the confusion total proves each held-out sample was
    # counted exactly once (the round-1 path counted them nproc times).
    assert evals[0] == evals[1], evals
    assert evals[0]["n_evaluated"] == held_out, (evals, held_out)
