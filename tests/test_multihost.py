"""Multi-host training path: 2 real processes, 2 CPU devices each.

The reference's multi-node story is torchrun + NCCL rendezvous (SURVEY.md §2
C5). Ours is ``jax.distributed.initialize`` + a global mesh + per-host data
sharding assembled with ``make_array_from_process_local_data``
(``data.dataset.put_batch``). That path has process_count()==1 shortcuts
everywhere, so a single-process CI run never touches it — this test spawns
two coordinated worker processes on the CPU backend (2 virtual devices each
→ a 4-device global mesh) and runs real training steps through the
multi-process branches.

Every cross-process value the compiled step produces (loss, accuracy,
grad_norm are global means/sums over the data axis) must agree bitwise
across hosts — the TPU-native equivalent of "DDP keeps replicas in sync".
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys


_WORKER = r"""
import json, os, sys
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
over = json.loads(sys.argv[4])
want_eval = over.pop("_eval", False)
ndev = over.pop("_devices", 2)
import os
# Portable device-count forcing: the jax_num_cpu_devices config option
# only exists on newer jax (and rejects being combined with this flag);
# the XLA flag alone works everywhere and must be set before backend init.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={ndev}"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    # 0.4.x CPU backends refuse multiprocess computations unless the gloo
    # collectives implementation is selected; newer jax dropped the knob.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
repo = os.environ["PYTHONPATH"].split(os.pathsep)[0]  # set by the test
cache_dir = os.path.join(repo, ".cache", "jax_compile")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
if over.pop("_no_cache", False):
    # Executing an executable DESERIALIZED from the persistent cache can
    # fatally abort in this sandbox (the AOT-loader machine-feature issue
    # conftest.py documents); a respawned segment hits exactly that, so
    # resume tests compile fresh.
    jax.config.update("jax_enable_compilation_cache", False)
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == ndev * nproc, jax.devices()

from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer

base = dict(data_workers=1, log_every=1, eval_every=10**9,
            checkpoint_every=10**9, eval_batches=1)
base.update(over)
cfg = get_config("smoke16", **base)
trainer = Trainer(cfg)
try:
    last = trainer.run()
except SystemExit as e:
    # Planned-restart segment boundary: report and propagate the exit code
    # so the harness (playing supervisor) can respawn the process group.
    print("RESTART_EXIT " + json.dumps({
        "code": int(e.code), "step": int(trainer.state.step)}))
    raise
print("FINAL " + json.dumps(
    {k: float(v) for k, v in last.items()
     if isinstance(v, (int, float)) and not isinstance(v, bool)}
))
if want_eval:
    # Host-sharded exact eval: each feed group walks its decimation of the
    # held-out split; global sums must agree bitwise AND count every
    # sample exactly once (the confusion total is the proof).
    import numpy as np
    ev = trainer.evaluate()
    out = {"accuracy": ev["accuracy"], "loss": ev["loss"]}
    if "confusion" in ev:
        out["n_evaluated"] = int(np.asarray(ev["confusion"]).sum())
    print("EVAL " + json.dumps(out))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(
    port: int, nproc: int, overrides: dict | None = None
) -> tuple[list[str], list[int]]:
    """Spawn, concurrently drain, and always reap the worker processes.

    Concurrent draining matters: a worker that fills its unread stdout pipe
    blocks, stalling its peer at the next collective. The finally block
    guarantees no orphan survives a timeout or assertion (an orphan would
    pin the coordinator port and wedge later runs). Returns (stdouts,
    returncodes) — planned-restart segments exit 75 on purpose.
    """
    import threading

    env = {
        **os.environ,
        # Subprocesses must dodge both the axon TPU plugin (PYTHONPATH
        # bypass) and this test process's own forced-CPU config.
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    blob = json.dumps(overrides or {})
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), str(nproc), str(port),
             blob],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = [""] * nproc

    def drain(i: int, p: subprocess.Popen) -> None:
        outs[i] = p.communicate()[0]

    threads = [
        threading.Thread(target=drain, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    try:
        import time

        for t in threads:
            t.start()
        deadline = 900
        end = time.monotonic() + deadline  # shared bound, not per-thread
        for t in threads:
            t.join(timeout=max(0.0, end - time.monotonic()))
        if any(t.is_alive() for t in threads):
            raise AssertionError(
                f"workers did not finish within {deadline}s: "
                + " | ".join(o[-500:] for o in outs)
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=30)
    return outs, [p.returncode for p in procs]


def test_two_process_training_stays_in_sync(tmp_path):
    from featurenet_tpu.data.offline import (
        VoxelCacheDataset,
        export_synthetic_cache,
    )

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=2, resolution=16)
    held_out = len(VoxelCacheDataset(cache, global_batch=8, split="test"))

    nproc = 2
    over = {"global_batch": 8, "total_steps": 3, "data_cache": cache,
            "_eval": True}
    outs, _ = _retry_port(nproc, over)
    for i, out in enumerate(outs):
        assert "FINAL " in out, f"worker {i} failed:\n{out}"

    finals, evals = [], []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("FINAL ")]
        assert lines, out
        finals.append(json.loads(lines[-1][len("FINAL "):]))
        ev_lines = [l for l in out.splitlines() if l.startswith("EVAL ")]
        assert ev_lines, out
        evals.append(json.loads(ev_lines[-1][len("EVAL "):]))
    # Global metrics must agree across hosts bitwise: each host ran the
    # same compiled step over the same global (sharded) batch.
    assert finals[0].keys() == finals[1].keys()
    for k in finals[0]:
        if k == "samples_per_sec":  # host-local wall clock, never synced
            continue
        assert finals[0][k] == finals[1][k], (k, finals)
    # And training actually happened: the final loss is a finite number
    # produced by `steps` real optimizer updates.
    assert finals[0]["loss"] > 0.0
    # Host-sharded exact eval: bitwise-identical global results on every
    # host, and the confusion total proves each held-out sample was
    # counted exactly once (the round-1 path counted them nproc times).
    assert evals[0] == evals[1], evals
    assert evals[0]["n_evaluated"] == held_out, (evals, held_out)


def _collect(outs: list[str], tag: str) -> list[dict]:
    vals = []
    for i, out in enumerate(outs):
        lines = [l for l in out.splitlines() if l.startswith(tag + " ")]
        assert lines, f"worker {i}: no {tag}:\n{out[-2000:]}"
        vals.append(json.loads(lines[-1][len(tag) + 1:]))
    return vals


def _retry_port(nproc: int, over: dict) -> tuple[list[str], list[int]]:
    """Retry on rendezvous-infrastructure failures: a TOCTOU-raced
    coordinator port, a gloo key-value DEADLINE_EXCEEDED, or an outright
    worker-group timeout — all observed when many workers cold-compile on
    one core oversubscribed by the rest of the suite (infrastructure
    flakes, not logic bugs; a logic failure reproduces on the retry)."""
    last_err, last_result = None, None
    for attempt in range(3):
        try:
            outs, codes = _run_workers(_free_port(), nproc, over)
        except AssertionError as e:  # worker-group deadline in _run_workers
            last_err = e
            continue
        last_result = (outs, codes)
        transient = any(
            "ddress already in use" in o or "DEADLINE_EXCEEDED" in o
            for o in outs
        )
        if not transient:
            return outs, codes
    if last_result is not None:  # completed attempts beat stale timeouts
        return last_result
    raise last_err


def test_four_process_model_axis_spans_processes():
    """mesh_model=4 over 4 hosts x 2 devices: tensor-parallel kernels and
    the spatially-sharded depth axis both span process boundaries, so every
    model-axis collective (column-parallel matmuls, conv halo exchange)
    rides the cross-process path, and hosts in the same data-row group must
    feed identical rows with put_batch narrowing each to its depth block
    (parallel.mesh.feed_shards + dataset._local_block — the round-2
    verdict's untested case)."""
    nproc = 4
    over = {"global_batch": 8, "total_steps": 2, "mesh_model": 4,
            "spatial": True}
    outs, codes = _retry_port(nproc, over)
    for i, out in enumerate(outs):
        assert "FINAL " in out, f"worker {i} failed:\n{out[-2000:]}"
    assert codes == [0] * nproc
    finals = _collect(outs, "FINAL")
    for f in finals[1:]:
        for k in finals[0]:
            if k == "samples_per_sec":
                continue
            assert f[k] == finals[0][k], (k, finals)
    assert finals[0]["loss"] > 0.0


def test_four_process_eval_matches_single_process(tmp_path):
    """Assembly correctness, not just cross-host consistency: the 4-process
    spatial mesh's exact eval must reproduce a *single-process* 8-device run
    of the same mesh shape on the same cache — a feed mis-assembly that is
    globally consistent (every host sees the same wrongly-assembled batch)
    passes the sync test above but fails this one. Eval runs at init params
    (total_steps=0, same seed → identical init) over the deterministic
    epoch pass, so any metric divergence is the feed, not training. Global
    row order differs between shardings (decimated vs sequential epoch
    walk), so masked-sum metrics match to reduction-order tolerance, while
    the confusion total must match exactly."""
    from featurenet_tpu.data.offline import export_synthetic_cache

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=3, resolution=16)
    over = {"global_batch": 8, "total_steps": 0, "mesh_model": 4,
            "spatial": True, "data_cache": cache, "_eval": True}
    outs4, codes4 = _retry_port(4, over)
    assert codes4 == [0] * 4, (codes4, [o[-1500:] for o in outs4])
    evals4 = _collect(outs4, "EVAL")
    outs1, codes1 = _retry_port(1, {**over, "_devices": 8})
    assert codes1 == [0], outs1[0][-1500:]
    ev1 = _collect(outs1, "EVAL")[0]
    for ev in evals4:
        assert ev["n_evaluated"] == ev1["n_evaluated"], (ev, ev1)
        assert abs(ev["accuracy"] - ev1["accuracy"]) < 1e-6, (ev, ev1)
        assert abs(ev["loss"] - ev1["loss"]) < 1e-5, (ev, ev1)


def test_multiprocess_checkpoint_resume_and_planned_restart(tmp_path):
    """The C5 production path, multi-process: a segmented run checkpoints,
    the whole process group exits 75 (planned restart), a fresh group
    resumes from the Orbax checkpoint + config sidecar and finishes. Covers
    Orbax save/restore coordination across processes and the supervisor
    handoff (the harness plays the per-deployment supervisor)."""
    nproc = 2
    ckpt = str(tmp_path / "ck")
    # _no_cache: the resumed group would execute train steps deserialized
    # from the persistent compile cache (written by segment 1), which can
    # fatally abort in this sandbox — compile fresh instead (see _WORKER).
    over = {"global_batch": 8, "total_steps": 5, "checkpoint_every": 2,
            "checkpoint_dir": ckpt, "restart_every_steps": 3,
            "_no_cache": True}
    # Segment 1: trains to step 3, saves, exits RESTART_EXIT_CODE (75).
    outs, codes = _retry_port(nproc, over)
    assert codes == [75] * nproc, (codes, [o[-1500:] for o in outs])
    restarts = _collect(outs, "RESTART_EXIT")
    assert all(r == {"code": 75, "step": 3} for r in restarts), restarts
    # Segment 2: a fresh process group must RESUME at step 3 (not retrain
    # from 0) and complete to 5 with bitwise-identical global metrics.
    outs, codes = _retry_port(nproc, over)
    assert codes == [0] * nproc, (codes, [o[-1500:] for o in outs])
    finals = _collect(outs, "FINAL")
    assert finals[0] == finals[1] or all(
        finals[0][k] == finals[1][k]
        for k in finals[0] if k != "samples_per_sec"
    ), finals
    # The fresh group RESUMED: every train-step log in segment 2 is past
    # the restart point (a retrain-from-0 would log steps 1..3 again at
    # log_every=1).
    for out in outs:
        steps = [
            json.loads(l)["step"] for l in out.splitlines()
            if l.startswith("{") and '"kind": "train"' in l
        ]
        assert steps and min(steps) > 3, steps
    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(ckpt)
    assert mgr.latest_step() == 5
    mgr.close()


def test_two_process_obs_per_host_streams_merge(tmp_path):
    """Multi-host telemetry e2e: a 2-process run with a run_dir writes one
    event stream per host (host 0 keeps events.jsonl + run.json, host 1
    gets events.1.jsonl), every host's data-wait/dispatch/heartbeat lands,
    and the merged report renders a per-host breakdown — the blind spot
    where only host 0's telemetry survived is closed."""
    run_dir = str(tmp_path / "obsrun")
    over = {"global_batch": 8, "total_steps": 2, "run_dir": run_dir}
    outs, codes = _retry_port(2, over)
    assert codes == [0, 0], (codes, [o[-1500:] for o in outs])

    assert os.path.exists(os.path.join(run_dir, "events.jsonl"))
    assert os.path.exists(os.path.join(run_dir, "events.1.jsonl"))
    assert os.path.exists(os.path.join(run_dir, "run.json"))

    from featurenet_tpu.obs.report import (
        build_report,
        format_report,
        load_events,
        load_manifest,
        validate_events,
    )

    events, bad = load_events(run_dir)
    assert bad == 0
    assert {e["process_index"] for e in events} == {0, 1}
    manifest = load_manifest(run_dir)
    assert manifest["jax"]["process_count"] == 2

    rep = build_report(events, manifest)
    assert sorted(rep["hosts"]) == [0, 1]
    for h in rep["hosts"].values():
        assert h["steps"] == 2
        assert "data_wait" in h["fractions"]  # every host's wait is seen
        assert "heartbeat" in h
    assert "host_skew" in rep
    txt = format_report(rep)
    assert "hosts: 2" in txt

    # Both hosts completed the budget → terminal event per host, and the
    # whole merged stream passes the schema lint.
    assert sum(1 for e in events if e["ev"] == "run_end") == 2
    assert validate_events(events, bad_lines=bad) == []
