"""Model shape/precision tests (SURVEY.md §4 unit tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from featurenet_tpu.models import FeatureNet, FeatureNetArch, FeatureNetSegmenter
from featurenet_tpu.models.featurenet import tiny_arch
from featurenet_tpu.train.state import param_count


def _init_and_apply(model, x, train=False):
    variables = model.init(
        {"params": jax.random.key(0)}, x, train=False
    )
    rngs = {"dropout": jax.random.key(1)} if train else None
    out = model.apply(variables, x, train=train, rngs=rngs,
                      mutable=["batch_stats"] if train else False)
    return variables, out


@pytest.mark.parametrize("res", [16, 32, 64])
def test_classifier_output_shape(res):
    """Contract (SURVEY.md §3.3): R³ grid in → [B, 24] logits out, any R."""
    model = FeatureNet(arch=tiny_arch())
    x = jnp.zeros((2, res, res, res, 1), jnp.float32)
    _, logits = _init_and_apply(model, x)
    assert logits.shape == (2, 24)
    assert logits.dtype == jnp.float32


def test_classifier_param_count_in_contract_range():
    """The published-shape arch must land in the ~1–5M param band (SURVEY §3.3)."""
    model = FeatureNet()  # default paper-shape arch at 64³
    x = jnp.zeros((1, 64, 64, 64, 1), jnp.float32)
    variables = model.init({"params": jax.random.key(0)}, x, train=False)
    n = param_count(variables["params"])
    assert 1_000_000 <= n <= 8_000_000, n


def test_classifier_train_mode_updates_batch_stats():
    model = FeatureNet(arch=tiny_arch())
    x = jnp.asarray(np.random.default_rng(0).random((4, 16, 16, 16, 1)),
                    jnp.float32)
    variables, (logits, mutated) = _init_and_apply(model, x, train=True)
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))


def test_classifier_params_and_bn_are_fp32():
    model = FeatureNet(arch=tiny_arch())
    x = jnp.zeros((1, 16, 16, 16, 1), jnp.float32)
    variables = model.init({"params": jax.random.key(0)}, x, train=False)
    for leaf in jax.tree_util.tree_leaves(variables):
        assert leaf.dtype == jnp.float32, leaf.dtype


def test_bf16_vs_fp32_logit_drift_bounded():
    """bf16 compute must stay close to an fp32 reference forward (SURVEY §4)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((2, 16, 16, 16, 1)), jnp.float32)
    arch = tiny_arch()
    m16 = FeatureNet(arch=arch, dtype=jnp.bfloat16)
    m32 = FeatureNet(arch=arch, dtype=jnp.float32)
    variables = m16.init({"params": jax.random.key(0)}, x, train=False)
    l16 = m16.apply(variables, x, train=False)
    l32 = m32.apply(variables, x, train=False)
    assert np.max(np.abs(np.asarray(l16) - np.asarray(l32))) < 0.15


def test_segmenter_output_shape():
    model = FeatureNetSegmenter(features=(8, 16))
    x = jnp.zeros((2, 16, 16, 16, 1), jnp.float32)
    _, logits = _init_and_apply(model, x)
    assert logits.shape == (2, 16, 16, 16, 25)
    assert logits.dtype == jnp.float32


def test_custom_arch_validation():
    with pytest.raises(ValueError):
        FeatureNetArch(features=(32,), kernels=(3, 3))


def test_gap_residual_arch_trains_where_flatten_head_matches_shape():
    """deep_arch-style head/skips (abc128): GAP head output is
    resolution-independent, residual skips add no params, and gradients
    reach the stem (the flatten-head collapse starved it — BASELINE.md)."""
    arch = FeatureNetArch(
        features=(8, 8, 8),
        kernels=(3, 3, 3),
        strides=(2, 1, 1),
        pool_after=(False, True, True),
        hidden=16,
        dropout=0.0,
        head_gap=True,
        residual=True,
    )
    model = FeatureNet(arch=arch)
    x16 = jnp.asarray(np.random.default_rng(0).random((2, 16, 16, 16, 1)),
                      jnp.float32)
    x32 = jnp.asarray(np.random.default_rng(1).random((2, 32, 32, 32, 1)),
                      jnp.float32)
    v16 = model.init({"params": jax.random.key(0)}, x16, train=False)
    # GAP head: the same param tree must serve any resolution (a flatten
    # head would need a different Dense shape at 32³).
    assert model.apply(v16, x32, train=False).shape == (2, 24)

    # Residual skips are identity branches: param tree identical to the
    # same arch without skips.
    import dataclasses

    v_noskip = FeatureNet(
        arch=dataclasses.replace(arch, residual=False)
    ).init({"params": jax.random.key(0)}, x16, train=False)
    assert jax.tree_util.tree_structure(
        v16["params"]
    ) == jax.tree_util.tree_structure(v_noskip["params"])

    # Gradients reach the stem conv (nonzero), i.e. the skip path did not
    # detach the tower from the loss.
    def loss(params):
        out = model.apply(
            {"params": params, "batch_stats": v16["batch_stats"]},
            x16, train=True, rngs={"dropout": jax.random.key(2)},
            mutable=["batch_stats"],
        )[0]
        return jnp.sum(out ** 2)

    grads = jax.grad(loss)(v16["params"])
    stem_grads = jax.tree_util.tree_leaves(grads["ConvBNRelu_0"])
    assert any(float(jnp.max(jnp.abs(g))) > 0.0 for g in stem_grads)
