"""Distributed correctness on the 8-device virtual CPU mesh (SURVEY.md §4).

The TPU-native "fake backend": conftest.py forces 8 CPU devices, so these
tests exercise the *same* GSPMD partitioning paths a real pod uses — gradient
reduction over ``data``, tensor-parallel kernels over ``model``, spatially
partitioned convs — with no TPU attached.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from featurenet_tpu.config import get_config
from featurenet_tpu.data.synthetic import generate_batch
from featurenet_tpu.models import FeatureNet
from featurenet_tpu.models.featurenet import tiny_arch
from featurenet_tpu.parallel.mesh import (
    batch_shardings,
    make_mesh,
    param_shardings,
    replicated,
    state_shardings,
)
from featurenet_tpu.train import Trainer
from featurenet_tpu.train.steps import make_optimizer, make_train_step
from featurenet_tpu.train.state import create_state


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape == {"data": 8, "model": 1}
    mesh = make_mesh(model=2)
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(model=3)


def test_param_shardings_rule():
    model = FeatureNet()  # default arch has 64-wide convs and 128-wide FC
    x = jnp.zeros((1, 32, 32, 32, 1), jnp.float32)
    params = model.init({"params": jax.random.key(0)}, x, train=False)["params"]
    mesh = make_mesh(model=2)
    shardings = param_shardings(params, mesh)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    sharded = [
        "/".join(getattr(k, "key", str(k)) for k in path)
        for path, s in flat
        if s.spec != jax.sharding.PartitionSpec()
    ]
    # At least the wide convs and the two Dense kernels must be column-sharded.
    assert any("Dense" in p and p.endswith("kernel") for p in sharded)
    assert any("Conv" in p for p in sharded)
    # Biases and BN state never shard.
    assert not any("bias" in p or "scale" in p or "mean" in p for p in sharded)


def _grads_and_loss(mesh, model_axis, batch, spatial=False):
    """Init + one train step on the given mesh layout; return state and metrics."""
    cfg = get_config("smoke16", global_batch=batch["voxels"].shape[0])
    model = FeatureNet(arch=tiny_arch(), dtype=jnp.float32)
    tx = make_optimizer(cfg)

    def init_fn(rng):
        sample = jnp.zeros(batch["voxels"].shape, jnp.float32)
        return create_state(model, tx, sample, rng)

    abstract = jax.eval_shape(init_fn, jax.random.key(0))
    st_sh = state_shardings(abstract, mesh)
    state = jax.jit(init_fn, out_shardings=st_sh)(jax.random.key(0))
    b_sh = batch_shardings(mesh, spatial=spatial)
    step = jax.jit(
        make_train_step(model, "classify"),
        in_shardings=(st_sh, b_sh, replicated(mesh)),
        out_shardings=(st_sh, replicated(mesh)),
    )
    dev_batch = jax.device_put(batch, b_sh)
    rng = jax.device_put(jax.random.key(1), replicated(mesh))
    new_state, metrics = step(state, dev_batch, rng)
    return new_state, jax.block_until_ready(metrics)


def _flat_params(state):
    return np.concatenate([
        np.asarray(x).ravel()
        for x in jax.tree_util.tree_leaves(state.params)
    ])


def test_dp8_matches_single_device(rng):
    """8-way data parallel must produce the same update as 1 device on the
    same global batch — the grad-psum parity test (SURVEY.md §4)."""
    batch = generate_batch(rng, 16, resolution=16)
    mesh8 = make_mesh()  # data=8
    mesh1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    s8, m8 = _grads_and_loss(mesh8, 1, batch)
    s1, m1 = _grads_and_loss(mesh1, 1, batch)
    np.testing.assert_allclose(m8["loss"], m1["loss"], rtol=2e-5)
    np.testing.assert_allclose(
        _flat_params(s8), _flat_params(s1), rtol=3e-4, atol=3e-6
    )


def test_tp_matches_single_device(rng):
    """data=4 × model=2 tensor parallel must match the 1-device update."""
    batch = generate_batch(rng, 16, resolution=16)
    mesh42 = make_mesh(model=2)
    mesh1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    s42, m42 = _grads_and_loss(mesh42, 2, batch)
    s1, m1 = _grads_and_loss(mesh1, 1, batch)
    np.testing.assert_allclose(m42["loss"], m1["loss"], rtol=2e-5)
    np.testing.assert_allclose(
        _flat_params(s42), _flat_params(s1), rtol=3e-4, atol=3e-6
    )


def test_spatial_partitioning_matches_single_device(rng):
    """Sharding the voxel depth axis over 'model' (XLA halo exchange for the
    convs) must be numerically identical to unsharded execution."""
    batch = generate_batch(rng, 8, resolution=16)
    mesh42 = make_mesh(model=2)
    mesh1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    s_sp, m_sp = _grads_and_loss(mesh42, 2, batch, spatial=True)
    s1, m1 = _grads_and_loss(mesh1, 1, batch)
    np.testing.assert_allclose(m_sp["loss"], m1["loss"], rtol=2e-5)
    np.testing.assert_allclose(
        _flat_params(s_sp), _flat_params(s1), rtol=3e-4, atol=3e-6
    )


def test_bn_stats_are_global_batch(rng):
    """BN must see the *global* batch: stats after one step on an 8-way
    sharded batch must equal the single-device stats (the SyncBatchNorm
    semantics, here for free from GSPMD)."""
    batch = generate_batch(rng, 16, resolution=16)
    s8, _ = _grads_and_loss(make_mesh(), 1, batch)
    s1, _ = _grads_and_loss(
        make_mesh(data=1, model=1, devices=jax.devices()[:1]), 1, batch
    )
    for a, b in zip(jax.tree_util.tree_leaves(s8.batch_stats),
                    jax.tree_util.tree_leaves(s1.batch_stats)):
        # Sharded means reduce in a different order; allow float noise only.
        # (Local-batch — i.e. unsynced — stats would differ at the 1e-1
        # level here; 1e-4 cleanly separates semantics from summation order.)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_clamp_model_axis():
    from featurenet_tpu.parallel.mesh import clamp_model_axis

    assert clamp_model_axis(1, 1) == 1
    assert clamp_model_axis(2, 1) == 1  # abc128 preset on a single chip
    assert clamp_model_axis(2, 8) == 2
    assert clamp_model_axis(3, 8) == 2  # largest divisor <= requested
    assert clamp_model_axis(5, 8) == 4
    assert clamp_model_axis(16, 8) == 8
    assert clamp_model_axis(2, 6) == 2
    assert clamp_model_axis(4, 6) == 3
    with pytest.raises(ValueError):
        clamp_model_axis(0, 8)


def test_tp_probe_localizes_dropout_divergence():
    """The numerics-bisection probe (analysis/tp_probe.py, ISSUE 10) for
    the two known-failing TP parity tests above: every eval-mode module
    intermediate must match between the model=2 mesh and a single device,
    train mode WITHOUT dropout must match to float noise, and the first
    diverging stage must be the dropout mask — which
    jax_threefry_partitionable=True closes (the recorded fix, deferred:
    flipping it changes every seeded RNG stream in the suite)."""
    from featurenet_tpu.analysis.tp_probe import probe

    out = probe(resolution=16, batch=8, tolerance=1e-3)
    rows = {r["stage"]: r["max_abs_diff"] for r in out["rows"]}
    # Layer-by-layer: no eval-mode intermediate diverges.
    eval_rows = {k: v for k, v in rows.items()
                 if k.startswith("forward/eval")}
    assert eval_rows and max(eval_rows.values()) <= 1e-3
    assert rows["forward/train-no-dropout"] <= 1e-3
    assert rows["forward/train-dropout"] > 1e-2  # the real divergence
    assert out["verdict"]["first_divergence"] == "forward/train-dropout"
    assert out["verdict"]["fixed_by_threefry_partitionable"] is True


def test_trainer_clamps_nondividing_model_axis(capsys):
    """A preset whose mesh_model doesn't divide the device count starts
    anyway on the widest feasible axis (round-1: abc128 crashed on 1 chip)."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train.loop import Trainer

    cfg = get_config("smoke16", mesh_model=3, data_workers=1)
    t = Trainer(cfg)
    assert t.mesh.shape == {"data": 4, "model": 2}
    assert "mesh_warning" in capsys.readouterr().err
