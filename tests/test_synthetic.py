"""Synthetic 24-class generator tests: determinism, class coverage, geometry."""

import numpy as np

from featurenet_tpu.data import (
    CLASS_NAMES,
    NUM_CLASSES,
    generate_batch,
    generate_sample,
)
from featurenet_tpu.data.synthetic import stock_mask


def test_24_classes():
    assert NUM_CLASSES == 24
    assert len(set(CLASS_NAMES)) == 24


def test_every_class_carves_material(rng):
    # Each feature must remove a nontrivial volume from the stock but leave
    # a nontrivial part behind.
    R = 32
    stock = stock_mask(R)
    for cls in range(NUM_CLASSES):
        part, labels, seg = generate_sample(rng, R, label=cls, orient=False)
        removed = int(stock.sum()) - int(part.sum())
        assert removed > 8, f"{CLASS_NAMES[cls]} removed nothing"
        assert part.sum() > 0.2 * stock.sum(), f"{CLASS_NAMES[cls]} ate the part"
        assert labels[0] == cls
        # Seg labels live exactly where material was removed from stock.
        assert (seg == cls + 1).sum() == removed


def test_determinism():
    a = generate_batch(np.random.default_rng(7), 8, resolution=16)
    b = generate_batch(np.random.default_rng(7), 8, resolution=16)
    np.testing.assert_array_equal(a["voxels"], b["voxels"])
    np.testing.assert_array_equal(a["label"], b["label"])


def test_batch_shapes_and_balance(rng):
    B, R = 48, 16
    batch = generate_batch(rng, B, resolution=R, balanced=True)
    assert batch["voxels"].shape == (B, R, R, R, 1)
    assert batch["voxels"].dtype == np.float32
    assert batch["label"].shape == (B,)
    assert batch["seg"].shape == (B, R, R, R)
    # Balanced: first 48 samples cover each class exactly twice.
    counts = np.bincount(batch["label"], minlength=24)
    assert (counts == 2).all()


def test_multi_feature_seg(rng):
    part, labels, seg = generate_sample(rng, 32, num_features=3)
    assert labels.shape == (3,)
    present = set(np.unique(seg)) - {0}
    # At least one feature's label must appear (features may overlap/occlude).
    assert len(present) >= 1
    assert present <= {int(l) + 1 for l in labels}


def test_orientation_preserves_counts():
    r1 = np.random.default_rng(3)
    r2 = np.random.default_rng(3)
    p_plain, _, _ = generate_sample(r1, 16, label=1, orient=False)
    p_rot, _, _ = generate_sample(r2, 16, label=1, orient=True)
    assert p_plain.sum() == p_rot.sum()


def test_wire_pack_unpack_roundtrip(rng):
    """Classify wire format: pack on host, unpack on device, bit-exact."""
    import numpy as np

    from featurenet_tpu.data.synthetic import generate_batch, to_wire
    from featurenet_tpu.train.steps import unpack_voxels

    b = generate_batch(rng, 4, resolution=16)
    wire = to_wire(b, "classify")
    assert wire["voxels"].shape == (4, 16, 16, 2)
    assert wire["voxels"].dtype == np.uint8
    assert "seg" not in wire
    un = np.asarray(unpack_voxels(wire["voxels"]))
    np.testing.assert_array_equal(un, b["voxels"])


def test_wire_segment_format(rng):
    import numpy as np

    from featurenet_tpu.data.synthetic import generate_batch, to_wire
    from featurenet_tpu.train.steps import unpack_voxels

    b = generate_batch(rng, 2, resolution=16, num_features=2)
    wire = to_wire(b, "segment")
    assert wire["voxels"].dtype == np.uint8
    assert wire["voxels"].shape == (2, 16, 16, 2)  # bit-packed
    np.testing.assert_array_equal(
        np.asarray(unpack_voxels(wire["voxels"])), b["voxels"]
    )
    assert wire["seg"].dtype == np.int8
    np.testing.assert_array_equal(wire["seg"], b["seg"])  # ids fit int8


def test_generate_sample_with_removals_matches_generate_sample():
    """Same rng stream; carve(labels, removals) reproduces (part, seg); the
    observable part is order-invariant while seg may not be."""
    from featurenet_tpu.data.synthetic import (
        carve,
        generate_sample,
        generate_sample_with_removals,
    )

    for nf in (1, 3):
        r1 = np.random.default_rng(11)
        r2 = np.random.default_rng(11)
        p1, l1, s1 = generate_sample(r1, 16, num_features=nf)
        p2, l2, s2, rem = generate_sample_with_removals(r2, 16, num_features=nf)
        assert (p1 == p2).all() and (l1 == l2).all() and (s1 == s2).all()
        pc, sc = carve(l2, rem)
        assert (pc == p2).all() and (sc == s2).all()
        pr, _ = carve(l2, rem, order=list(reversed(range(nf))))
        assert (pr == p2).all()  # part is order-invariant


def test_seg_oracle_detects_order_ambiguity():
    """The ceiling is < 1 with overlapping multi-feature parts and the
    ambiguous fraction is positive; single-feature parts are unambiguous."""
    from featurenet_tpu.data.seg_oracle import measure_ceiling

    multi = measure_ceiling(resolution=16, num_features=3, samples=24, seed=3)
    assert 0.5 < multi["iou_random_pair"] < 1.0
    assert multi["ambiguous_voxel_fraction"] > 0.0
    single = measure_ceiling(resolution=16, num_features=1, samples=8, seed=3)
    assert single["iou_random_pair"] == 1.0
    assert single["ambiguous_voxel_fraction"] == 0.0
