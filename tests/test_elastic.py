"""Elastic multi-host training (featurenet_tpu.elastic).

Three layers, cheapest first:

1. Planner/membership units: world feasibility (global batch preserved),
   slot selection, the atomic membership file.
2. Coordinator state machine over FAKE children (``python -c`` scripts
   coordinating through heartbeat files — no JAX, seconds per case):
   loss → shrink, rejoin at the planned boundary, full-world loss →
   restart at strength, deterministic startup failure → give up.
3. The real thing (tier-1, CPU, 2 processes): ``host_loss`` injected
   mid-run kills one host of a live 2-process mesh; the coordinator
   re-forms at world size 1 from the latest checkpoint and the run
   completes its full step budget — and a companion grow test re-admits
   the lost host at the next generation boundary. Both assert the
   ``mesh_reform`` timeline and that the global batch survived every
   re-form.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

from featurenet_tpu.elastic import (
    ElasticCoordinator,
    Membership,
    heartbeat_path,
    read_membership,
    write_membership,
)
from featurenet_tpu.elastic.planner import (
    InfeasibleWorld,
    feasible_world_sizes,
    per_host_batch,
    plan_world,
)


# --- planner -----------------------------------------------------------------

def test_feasible_world_sizes_respect_global_batch():
    # 8-sample global batch over 2-device hosts: 1, 2, or 4 hosts divide.
    assert feasible_world_sizes(8, 2, 6) == [1, 2, 4]
    assert feasible_world_sizes(96, 1, 5) == [1, 2, 3, 4]


def test_plan_world_keeps_low_slots_and_preserves_global_batch():
    # 3 survivors of a 4-host world, batch 8 over 2-device hosts: 3 hosts
    # don't divide, so the plan drops to 2 — keeping the LOWEST slots
    # (rank 0 owns the primary stream) — and the global batch is intact.
    members = plan_world([0, 2, 3], min_world_size=1, global_batch=8,
                         local_devices=2)
    assert members == (0, 2)
    assert per_host_batch(8, len(members)) == 4  # rescaled, not shrunk


def test_plan_world_refuses_below_min_world_size():
    with pytest.raises(InfeasibleWorld):
        plan_world([0], min_world_size=2, global_batch=8, local_devices=2)
    with pytest.raises(InfeasibleWorld):
        # 3 survivors, batch 25, min 2: only a 1-host world divides.
        plan_world([0, 1, 2], min_world_size=2, global_batch=25,
                   local_devices=1)


# --- membership file ---------------------------------------------------------

def test_membership_roundtrip_and_torn_file_reads_none(tmp_path):
    m = Membership(generation=3, members=(0, 2), min_world_size=1,
                   reason="host_loss")
    write_membership(str(tmp_path), m)
    got = read_membership(str(tmp_path))
    assert got == m and got.world_size == 2
    # Garbage (something else wrote here) must read as unknown, not crash.
    with open(tmp_path / "membership.json", "w") as fh:
        fh.write('{"generation": 1, "mem')
    assert read_membership(str(tmp_path)) is None
    assert read_membership(str(tmp_path / "nope")) is None


# --- coordinator over fake children ------------------------------------------

def _beat_then(code: str, hb: str) -> list[str]:
    """A fake child: prove liveness (touch the heartbeat strictly after
    the coordinator's baseline), then run ``code``."""
    return [sys.executable, "-c",
            "import os, time\n"
            f"hb = {hb!r}\n"
            "time.sleep(0.25); open(hb, 'a').close(); os.utime(hb, None)\n"
            "time.sleep(0.1)\n"
            + code]


def _coordinator(tmp_path, scenario, n_hosts=2, **kw):
    """Coordinator whose children act out ``scenario``:
    ``(generation, slot) -> python code`` (default: exit 0)."""
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)

    def spawn(members, rank, generation, port):
        slot = members[rank]
        code = scenario.get((generation, slot), "raise SystemExit(0)")
        return _beat_then(code, heartbeat_path(run_dir, slot))

    kw.setdefault("min_world_size", 1)
    kw.setdefault("global_batch", 8)
    kw.setdefault("local_devices", 2)
    kw.setdefault("poll_s", 0.1)
    kw.setdefault("grace_s", 30.0)
    kw.setdefault("stall_timeout_s", 30.0)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("log", lambda _: None)
    return ElasticCoordinator(n_hosts, spawn, run_dir, **kw), run_dir


def _events(run_dir: str, kind=None) -> list[dict]:
    out = []
    with open(os.path.join(run_dir, "events.jsonl")) as fh:
        for line in fh:
            e = json.loads(line)
            if kind is None or e.get("ev") == kind:
                out.append(e)
    return out


_HANG = "import time; time.sleep(60)"


def test_coordinator_shrinks_on_host_loss_and_survivor_finishes(tmp_path):
    # Gen 0: slot 1 crashes after beating (slot 0 hangs in its
    # "collective" and is killed as part of the re-form); gen 1: the
    # survivor completes. One loss verdict, one shape change, exit 0.
    coord, run_dir = _coordinator(tmp_path, {
        (0, 0): _HANG,
        (0, 1): "raise SystemExit(7)",
    })
    res = coord.run()
    assert res.exit_code == 0
    assert res.losses == 1 and res.rejoins == 0 and res.reforms == 1
    assert res.generations == 2
    reforms = [(e["from_n"], e["to_n"], e["reason"])
               for e in _events(run_dir, "mesh_reform")]
    assert reforms == [(0, 2, "start"), (2, 1, "host_loss")]
    leaves = _events(run_dir, "host_leave")
    assert len(leaves) == 1 and leaves[0]["host"] == 1
    m = read_membership(run_dir)
    assert m.generation == 1 and m.members == (0,) \
        and m.reason == "host_loss"


def test_coordinator_readmits_lost_host_at_planned_boundary(tmp_path):
    # Gen 0: slot 1 lost. Gen 1: the survivor reaches a planned cut
    # (exit 75) — the boundary where the recovered host rejoins. Gen 2:
    # full strength again, both finish.
    coord, run_dir = _coordinator(tmp_path, {
        (0, 0): _HANG,
        (0, 1): "raise SystemExit(9)",
        (1, 0): "raise SystemExit(75)",
    })
    res = coord.run()
    assert res.exit_code == 0
    assert res.losses == 1 and res.planned == 1 and res.rejoins == 1
    reforms = [(e["from_n"], e["to_n"], e["reason"])
               for e in _events(run_dir, "mesh_reform")]
    assert reforms == [(0, 2, "start"), (2, 1, "host_loss"),
                       (1, 2, "host_rejoin")]
    joins = _events(run_dir, "host_join")
    assert len(joins) == 1 and joins[0]["host"] == 1 \
        and joins[0]["generation"] == 2
    m = read_membership(run_dir)
    assert m.generation == 2 and m.members == (0, 1)


def test_coordinator_full_world_loss_restarts_at_strength(tmp_path):
    # min_world_size=2: losing a host leaves no admissible shrink, so the
    # coordinator re-admits everything and restarts the full world (the
    # plain supervisor's move) instead of giving up.
    coord, run_dir = _coordinator(tmp_path, {
        (0, 0): _HANG,
        (0, 1): "raise SystemExit(5)",
    }, min_world_size=2)
    res = coord.run()
    assert res.exit_code == 0
    assert res.losses == 1 and res.rejoins == 1
    m = read_membership(run_dir)
    assert m.generation == 1 and m.members == (0, 1) \
        and m.reason == "restart"


def test_coordinator_gives_up_on_deterministic_startup_failure(tmp_path):
    # No child ever beats: a config error, not a host dying under load —
    # two attempts, then the give-up verdict with the child's exit code
    # (shrinking would misdiagnose it and burn the world to nothing).
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)
    coord = ElasticCoordinator(
        2, lambda members, rank, generation, port:
            [sys.executable, "-c", "raise SystemExit(3)"],
        run_dir, global_batch=8, local_devices=2, poll_s=0.1,
        grace_s=30.0, backoff_base_s=0.05, log=lambda _: None,
    )
    res = coord.run()
    assert res.exit_code == 3
    assert res.generations == 2 and res.losses == 0
    phases = [e["phase"] for e in _events(run_dir, "supervisor")]
    assert phases.count("giving_up") == 1


# --- the real thing: a live 2-process CPU mesh -------------------------------

# The elastic training child: rank/world/port/generation/slot from the
# coordinator, config overrides as JSON. Forces 2 CPU devices per
# process and joins the generation's explicit jax.distributed world.
# Generation 0 uses the suite's persistent compile cache (fresh runs
# load/store safely — test_multihost's sync workers do the same); later
# generations RESUME, and a resumed segment executing a deserialized
# executable can fatally abort in this sandbox (see test_multihost.py),
# so they compile fresh.
_WORKER = r"""
import json, os, sys
rank, world, port, gen, slot = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], int(sys.argv[4]),
    int(sys.argv[5]),
)
over = json.loads(sys.argv[6])
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
if world > 1:
    # gloo needs the distributed client; a world-of-one generation has
    # none (and the flag would break CPU backend init outright).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        pass
if gen == 0:
    repo = os.environ["PYTHONPATH"].split(os.pathsep)[0]
    cache = os.path.join(repo, ".cache", "jax_compile")
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
else:
    jax.config.update("jax_enable_compilation_cache", False)
if world > 1:
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=world, process_id=rank,
    )
from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer
Trainer(get_config("smoke16", **over)).run()
"""


def _elastic_run(tmp_path, inject: str, extra: dict | None = None):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }

    def spawn(members, rank, generation, port):
        slot = members[rank]
        over = dict(
            total_steps=4,
            global_batch=8,
            data_workers=1,
            eval_batches=1,
            log_every=10**9,
            eval_every=10**9,
            checkpoint_every=1,
            checkpoint_dir=str(tmp_path / "ckpt"),
            run_dir=run_dir,
            heartbeat_file=heartbeat_path(run_dir, slot),
            inject_faults=inject,
        )
        over.update(extra or {})
        return [sys.executable, "-c", _WORKER, str(rank),
                str(len(members)), str(port), str(generation), str(slot),
                json.dumps(over)]

    coord = ElasticCoordinator(
        2, spawn, run_dir,
        min_world_size=1, global_batch=8, local_devices=2,
        stall_timeout_s=120.0, grace_s=600.0, poll_s=0.2,
        max_reforms=3, backoff_base_s=0.05, env=env, log=lambda _: None,
    )
    return coord.run(), run_dir


def _merged(run_dir):
    from featurenet_tpu.obs.report import build_report, load_events

    events, bad = load_events(run_dir)
    assert bad == 0
    return events, build_report(events)


def test_elastic_e2e_host_loss_shrinks_to_one_and_completes(tmp_path):
    """The tentpole e2e: one host of a live 2-process mesh is SIGKILLed
    mid-run (``host_loss`` at step 3, after the step-2 checkpoint); the
    coordinator re-forms at world size 1 from the latest checkpoint and
    the run completes its full 4-step budget with no intervention. The
    merged report carries the ``mesh_reform`` timeline, both hosts'
    streams (with the dead host's truncation attributed in the skew
    section), and a preserved global batch at both mesh shapes."""
    res, run_dir = _elastic_run(tmp_path, "host_loss@step=3")
    assert res.exit_code == 0
    assert res.losses == 1 and res.rejoins == 0 and res.generations == 2

    events, rep = _merged(run_dir)
    reforms = [(e["from_n"], e["to_n"], e["reason"])
               for e in events if e["ev"] == "mesh_reform"]
    assert reforms == [(0, 2, "start"), (2, 1, "host_loss")]
    assert sum(1 for e in events if e["ev"] == "host_leave") == 1
    # Full budget reached in the re-formed world.
    assert any(e["ev"] == "run_end" and e["step"] == 4 for e in events)
    # Global batch preserved across the re-form: every generation's loop
    # ran the same global batch, at different world shapes.
    starts = [e for e in events if e["ev"] == "loop_start"]
    assert {e["global_batch"] for e in starts} == {8}
    assert {e["mesh"]["processes"] for e in starts} == {1, 2}
    # Resumed from the latest checkpoint, not from scratch: the second
    # generation's loop starts past step 0.
    assert max(e["step"] for e in starts) >= 2
    # Report: the recovery section shows the re-form timeline, and both
    # hosts' streams merged with the dead host's truncation attributed.
    assert rep["recovery"]["mesh_reforms"] == 2
    assert rep["recovery"]["host_leaves"] == 1
    assert sorted(rep["hosts"]) == [0, 1]
    assert rep["host_skew"].get("step_mismatch")  # host 1 fell out
    # The scaling gate's cross-host scalar exists on this run's report.
    from featurenet_tpu.obs.gates import report_gate_values

    assert "data_wait_spread" in report_gate_values(rep)
    m = read_membership(run_dir)
    assert m.world_size == 1 and m.reason == "host_loss"


def test_elastic_e2e_grow_readmits_host_at_generation_boundary(tmp_path):
    """The companion grow path: after the loss, the shrunken world hits a
    planned segment cut (``restart_every_steps``) and the recovered host
    is re-admitted there — generation 2 trains at full strength again
    and finishes the budget."""
    res, run_dir = _elastic_run(
        tmp_path, "host_loss@step=1", extra={"restart_every_steps": 2},
    )
    assert res.exit_code == 0
    assert res.losses == 1 and res.rejoins == 1 and res.planned >= 1

    events, rep = _merged(run_dir)
    reasons = [e["reason"] for e in events if e["ev"] == "mesh_reform"]
    assert reasons == ["start", "host_loss", "host_rejoin"]
    grown = [e for e in events if e["ev"] == "mesh_reform"][-1]
    assert grown["to_n"] == 2
    joins = [e for e in events if e["ev"] == "host_join"]
    assert len(joins) == 1 and joins[0]["generation"] >= 2
    assert any(e["ev"] == "run_end" and e["step"] == 4 for e in events)
    starts = [e for e in events if e["ev"] == "loop_start"]
    assert {e["global_batch"] for e in starts} == {8}
    m = read_membership(run_dir)
    assert m.world_size == 2 and m.reason == "host_rejoin"
    assert rep["recovery"]["host_joins"] == 1


# --- CLI wiring (parse-time refusals; no backend, no processes) --------------

def test_cli_elastic_requires_checkpoint_and_run_dir(tmp_path):
    from featurenet_tpu import cli

    with pytest.raises(SystemExit, match="checkpoint-dir"):
        cli.main(["train", "--config", "smoke16", "--elastic",
                  "--world-size", "2"])
    with pytest.raises(SystemExit, match="run-dir"):
        cli.main(["train", "--config", "smoke16", "--elastic",
                  "--world-size", "2",
                  "--checkpoint-dir", str(tmp_path / "ck")])
    with pytest.raises(SystemExit, match="drop --supervise"):
        cli.main(["train", "--config", "smoke16", "--elastic",
                  "--supervise",
                  "--checkpoint-dir", str(tmp_path / "ck"),
                  "--run-dir", str(tmp_path / "run")])
    # An undividable full-strength world is refused up front: plan_world
    # would otherwise silently form generation 0 BELOW the requested
    # world size (it keeps the largest feasible world).
    with pytest.raises(SystemExit, match="not.*divisible"):
        cli.main(["train", "--config", "smoke16", "--elastic",
                  "--world-size", "3", "--local-devices", "1",
                  "--global-batch", "8",
                  "--checkpoint-dir", str(tmp_path / "ck"),
                  "--run-dir", str(tmp_path / "run")])


def test_config_min_world_size_guards():
    import dataclasses

    from featurenet_tpu.config import get_config

    with pytest.raises(ValueError, match="min_world_size"):
        get_config("smoke16", min_world_size=0)
    with pytest.raises(ValueError, match="elastic"):
        get_config("smoke16", min_world_size=2)
    cfg = get_config("smoke16", elastic=True, min_world_size=2)
    assert dataclasses.asdict(cfg)["min_world_size"] == 2
