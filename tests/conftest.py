"""Test harness: force an 8-device virtual CPU platform before JAX imports.

This is the TPU-native analog of a fake distributed backend (SURVEY.md §4):
``--xla_force_host_platform_device_count=8`` gives pmap/shard_map/pjit eight
real (CPU) devices, so collective correctness (grad psum parity, halo
exchange, BN sync) runs in CI with no TPU attached.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Silence XLA:CPU AOT-loader noise from the persistent compilation cache
# below: it logs a benign "machine feature +prefer-no-scatter … SIGILL"
# error-level line per cache hit (compiler preference pseudo-features the
# host probe doesn't list; same physical machine, results verified equal).
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone doesn't displace out-of-tree TPU plugins (the "axon"
# platform registers regardless); the config update before first backend
# initialization does.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: this suite is compile-dominated (the model
# zoo × train/eval/init graphs), and the graphs are identical run to run —
# caching them makes the reflexive `pytest tests/` fast after the first run
# while changing nothing about what executes. Lives under the gitignored
# .cache/ next to the dataset caches.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".cache", "jax_compile")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from featurenet_tpu import faults, obs  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# --- process-wide state hygiene ----------------------------------------------
# The obs sink, the window aggregator, and the fault plan are deliberately
# process-wide singletons; a test that leaks one poisons every later test
# in the worker (a dark-sink test suddenly writing into a dead tmpdir, a
# fault plan firing in an unrelated e2e). One shared autouse reset here
# replaces the per-file teardown fixtures PR 5/6 accumulated — both sides
# of the yield, so a leaky PREVIOUS file can't contaminate the first test
# of the next one either. obs.close_run() also drops the aggregator
# (windows.uninstall) and flushes nothing when no sink is active, so the
# reset is a no-op for the already-clean majority.

@pytest.fixture(autouse=True)
def _reset_process_state():
    # incidents.reset() disarms any leaked manager (uninstalling the
    # event tap + clearing tracing force-all); alerts.set_store(None)
    # drops a leaked alerts_active mirror that would otherwise write
    # into a dead store across tests — the incident plane mirrors
    # obs.close_run's discipline for its own process-wide slots.
    from featurenet_tpu.obs import alerts as _alerts
    from featurenet_tpu.obs import incidents as _incidents

    obs.close_run()
    faults.uninstall()
    _incidents.reset()
    _alerts.set_store(None)
    yield
    obs.close_run()
    faults.uninstall()
    _incidents.reset()
    _alerts.set_store(None)


# --- slow tier ---------------------------------------------------------------
# Default `pytest tests/` is the reflexive tier (target < ~3 min on this
# single-core box); tests marked @pytest.mark.slow only run with --slow.
# Keep the default tier the one that exercises every subsystem — slow means
# "long-running variant/e2e whose coverage is duplicated in miniature by a
# fast test", never "the only test of X".

def pytest_addoption(parser):
    parser.addoption(
        "--slow", action="store_true", default=False,
        help="also run tests marked slow (the full tier)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test; excluded unless --slow is given"
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--slow"):
        return
    skip = pytest.mark.skip(reason="slow tier: re-run with --slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
