"""Test harness: force an 8-device virtual CPU platform before JAX imports.

This is the TPU-native analog of a fake distributed backend (SURVEY.md §4):
``--xla_force_host_platform_device_count=8`` gives pmap/shard_map/pjit eight
real (CPU) devices, so collective correctness (grad psum parity, halo
exchange, BN sync) runs in CI with no TPU attached.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone doesn't displace out-of-tree TPU plugins (the "axon"
# platform registers regardless); the config update before first backend
# initialization does.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
