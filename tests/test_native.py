"""Native C++ voxelizer vs numpy reference (SURVEY.md §2 native ledger)."""

import numpy as np
import pytest

from featurenet_tpu.data.mesh_primitives import mesh_box, mesh_cylinder
from featurenet_tpu.data.voxelize import (
    _rasterize_surface,
    _voxelize_parity,
    normalize_mesh,
    voxelize,
)

native = pytest.importorskip("featurenet_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="g++ toolchain not available"
)


@pytest.mark.parametrize("R", [8, 16, 32])
def test_fill_matches_numpy_parity_exactly(R):
    """Same jitter, same rule → bit-identical solids on watertight meshes."""
    for tris in (mesh_box(), mesh_cylinder(), mesh_box((0.3, 0.1, 0.2), (0.9, 0.75, 0.66))):
        t = normalize_mesh(tris)
        ref = _voxelize_parity(t, R)
        got = native.voxelize_native(t, R, fill=True)
        np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("R", [8, 16])
def test_surface_superset_of_sampling(R):
    """Exact SAT shell must cover every voxel the sampling rasterizer marks
    (samples lie on the triangle, so sampled voxels truly intersect it)."""
    for tris in (mesh_box(), mesh_cylinder()):
        t = normalize_mesh(tris)
        sampled = _rasterize_surface(t, R)
        exact = native.voxelize_native(t, R, fill=False)
        assert (sampled & ~exact).sum() == 0


def test_surface_is_a_shell_not_solid():
    t = normalize_mesh(mesh_box())
    shell = native.voxelize_native(t, 16, fill=False)
    solid = native.voxelize_native(t, 16, fill=True)
    assert 0 < shell.sum() < solid.sum()
    # Interior of the box must be empty in the shell.
    assert not shell[8, 8, 8]


def test_voxelize_auto_backend_dispatches_native():
    tris = mesh_box()
    via_auto = voxelize(tris, 16, fill=True, backend="auto")
    via_native = voxelize(tris, 16, fill=True, backend="native")
    via_numpy = voxelize(tris, 16, fill=True, backend="numpy")
    np.testing.assert_array_equal(via_auto, via_native)
    np.testing.assert_array_equal(via_native, via_numpy)


def test_native_throughput_exceeds_numpy():
    """The point of native: don't starve the TPU (SURVEY.md §7 hard part 1)."""
    import time

    t = normalize_mesh(mesh_cylinder())
    # Warm both paths (native includes one-time g++ build via available()).
    native.voxelize_native(t, 64, fill=True)
    _voxelize_parity(t, 64)
    t0 = time.perf_counter()
    for _ in range(5):
        native.voxelize_native(t, 64, fill=True)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        _voxelize_parity(t, 64)
    t_numpy = time.perf_counter() - t0
    assert t_native < t_numpy, (t_native, t_numpy)
