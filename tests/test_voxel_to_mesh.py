"""Voxel→mesh surface extraction: exact roundtrip, watertightness, export.

The geometry contract under test (voxel_to_mesh module docstring): faces on
cell-boundary planes j/R, parity-fill rays through cell centers (i+0.5)/R →
``voxelize(voxels_to_mesh(g), fill=True, normalize=False)`` must equal ``g``
bit for bit.
"""

import json

import numpy as np

from featurenet_tpu.data.synthetic import CLASS_NAMES, generate_sample
from featurenet_tpu.data.voxel_to_mesh import export_stl_tree, voxels_to_mesh
from featurenet_tpu.data.voxelize import voxelize


def test_empty_and_full_grids():
    assert voxels_to_mesh(np.zeros((4, 4, 4), bool)).shape == (0, 3, 3)
    # A solid 2³ cube exposes 6 sides × 2×2 faces × 2 triangles.
    tris = voxels_to_mesh(np.ones((2, 2, 2), bool))
    assert tris.shape == (48, 3, 3)
    assert tris.min() >= 0.0 and tris.max() <= 1.0


def test_roundtrip_is_exact(rng):
    for label in (0, 7, 19):
        grid, _, _ = generate_sample(rng, 16, label=label)
        back = voxelize(
            voxels_to_mesh(grid), 16, fill=True, normalize=False,
            fill_method="parity", backend="numpy",
        )
        np.testing.assert_array_equal(back, grid.astype(bool))


def test_surface_is_watertight_and_outward(rng):
    grid, _, _ = generate_sample(rng, 8, label=3)
    tris = voxels_to_mesh(grid, scale=1.0)  # integer-corner coords

    # Watertight: every undirected edge is shared by an even number of
    # triangles (2 for manifold edges; 4 where voxels touch diagonally).
    q = np.round(tris).astype(np.int64)
    edges = {}
    for tri in q:
        for a, b in ((0, 1), (1, 2), (2, 0)):
            e = (tuple(tri[a]), tuple(tri[b]))
            e = (min(e), max(e))
            edges[e] = edges.get(e, 0) + 1
    assert edges and all(c % 2 == 0 for c in edges.values())

    # Outward orientation: signed volume of the closed surface equals the
    # voxel count (divergence theorem on unit cubes).
    v0, v1, v2 = tris[:, 0], tris[:, 1], tris[:, 2]
    signed = np.einsum("ij,ij->i", v0, np.cross(v1, v2)).sum() / 6.0
    assert abs(signed - grid.sum()) < 1e-3, (signed, grid.sum())


def test_export_stl_tree_feeds_build_cache(tmp_path):
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.data.offline import build_cache

    stl_root = tmp_path / "stl"
    index = export_stl_tree(
        str(stl_root), per_class=2, resolution=16, seed=0
    )
    assert set(index["counts"]) == set(CLASS_NAMES)
    assert all(n == 2 for n in index["counts"].values())

    cache = build_cache(str(stl_root), str(tmp_path / "cache"), resolution=16)
    assert cache["counts"] == index["counts"]

    # The CLI command produces the same tree shape.
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli_main([
            "export-stl-data", "--out", str(tmp_path / "stl2"),
            "--per-class", "1", "--resolution", "16",
        ])
    out = json.loads(buf.getvalue().splitlines()[-1])
    assert set(out["exported"]) == set(CLASS_NAMES)
