"""Serving fleet (featurenet_tpu.fleet): router health-gating, spillover
and re-submit-once semantics over fake replicas, priority-lane shed order
(batcher lane caps + router-level shed), Retry-After propagation and the
loadgen honor path, the membership ready-signal re-admission protocol,
scale verdicts — plus the acceptance spine (ISSUE 14): a REAL 2-replica
CPU fleet under open-loop HTTP load that survives a ``replica_loss``
injection with zero admitted-request drops, a roster timeline in the
report, and the killed replica rejoining from the fleet-shared exec
cache with zero fresh compiles.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from featurenet_tpu import faults, obs
from featurenet_tpu.elastic.membership import (
    Membership,
    read_membership,
    ready_slots,
    signal_ready,
    write_membership,
)
from featurenet_tpu.fleet.replica import Candidate, ReplicaManager
from featurenet_tpu.fleet.router import FleetRouter, scale_verdict
from featurenet_tpu.obs.report import (
    build_report,
    format_report,
    load_events,
)
from featurenet_tpu.serve.batcher import ContinuousBatcher, OverloadError

RES = 16


# --- fakes -------------------------------------------------------------------

def _fake_replica(respond):
    """A scripted replica HTTP server: ``respond(path, body, headers) ->
    (status, payload_dict, headers_dict)``. Returns (server, port,
    hits) — ``hits`` collects one record per POST. Speaks HTTP/1.1
    keep-alive like the real replicas, so pooled-channel reuse is
    exercised by every router test."""
    hits: list = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length)
            hits.append({"path": self.path,
                         "headers": dict(self.headers)})
            status, payload, extra = respond(
                self.path, body, dict(self.headers)
            )
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1], hits


def _dead_port() -> int:
    """A port with nothing listening (bound, then closed) — connecting
    to it is the replica-just-died shape (connection refused)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class FakeFleet:
    """The router's provider contract, scripted: a mutable candidate
    list plus recordings of note_failure / kill_one calls."""

    def __init__(self, cands):
        self.cands = list(cands)
        self.failed: list[int] = []
        self.inflight: dict[int, int] = {}
        self.killed = 0

    def candidates(self):
        return sorted(self.cands, key=lambda c: (c.score, c.slot))

    def note_inflight(self, slot, delta):
        self.inflight[slot] = self.inflight.get(slot, 0) + delta

    def note_failure(self, slot):
        self.failed.append(slot)
        self.cands = [c for c in self.cands if c.slot != slot]

    def kill_one(self):
        self.killed += 1
        return None

    def ready_count(self):
        return len(self.cands)

    def stats(self):
        return {"replicas": len(self.cands)}


def _router(fleet, **kw):
    # rules=() keeps the unit tests from installing a process-wide
    # window aggregator; a huge scale period keeps the verdict thread
    # quiet unless a test asks for it.
    kw.setdefault("rules", ())
    kw.setdefault("scale_every_s", 3600.0)
    return FleetRouter(fleet, **kw)


# --- batcher priority lanes --------------------------------------------------

def test_batcher_lane_caps_shed_batch_first():
    """The batch lane rejects at its own cap while interactive traffic
    still has the rest of the queue — the shed order, at the replica."""
    gate = threading.Event()

    def blocked(bucket, arr):
        gate.wait(30)
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(blocked, buckets=(1,), max_wait_ms=0,
                          queue_limit=6, lane_limits={"batch": 2})
    futs = [b.submit(np.ones((1,)))]  # occupies the dispatcher
    time.sleep(0.2)
    futs += [b.submit(np.ones((1,)), lane="batch") for _ in range(2)]
    with pytest.raises(OverloadError) as ei:
        b.submit(np.ones((1,)), lane="batch")
    assert ei.value.lane == "batch"
    assert ei.value.retry_after_s and ei.value.retry_after_s >= 0.05
    assert ei.value.response["lane"] == "batch"
    # Interactive still has headroom: the global bound is 6, only 2 are
    # queued — the batch cap tripped first, exactly the shed order.
    futs.append(b.submit(np.ones((1,))))
    st = b.stats()
    assert st["by_lane"]["batch"]["rejected"] == 1
    assert st["by_lane"]["batch"]["limit"] == 2
    gate.set()
    for f in futs:
        f.result(30)
    st = b.drain()
    assert st["served"] == 4 and st["rejected"] == 1


def test_unknown_lane_normalizes_to_interactive():
    b = ContinuousBatcher(lambda bucket, arr: arr, buckets=(1,),
                          max_wait_ms=1, queue_limit=2)
    fut = b.submit(np.ones((1,)), lane="totally-bogus")
    assert fut.lane == "interactive"
    b.drain()
    with pytest.raises(ValueError, match="lane"):
        ContinuousBatcher(lambda bucket, arr: arr, buckets=(1,),
                          lane_limits={"bogus": 1})


# --- HTTP overload contract: Retry-After + replica field ---------------------

def test_http_503_carries_retry_after_and_replica(tmp_path):
    """The overload satellite: the 503 body grows lane/retry_after_s/
    replica and the Retry-After header carries the same hint."""
    import http.client
    import types

    from featurenet_tpu.serve.http import make_server

    def reject(data, trace_id=None, lane="interactive"):
        raise OverloadError(5, 4, trace_id=trace_id, lane=lane,
                            retry_after_s=0.1)

    service = types.SimpleNamespace(
        replica="r7",
        batcher=types.SimpleNamespace(retry_after_s=0.1),
        submit_stl_bytes=reject,
    )
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        conn.request("POST", "/predict", body=b"x",
                     headers={"X-Featurenet-Priority": "batch",
                              "X-Featurenet-Trace": "fleet-test-1"})
        resp = conn.getresponse()
        body = json.loads(resp.read().decode())
        assert resp.status == 503
        assert body["error"] == "overload"
        assert body["replica"] == "r7"
        assert body["lane"] == "batch"
        assert body["retry_after_s"] == 0.1
        assert float(resp.getheader("Retry-After")) == pytest.approx(0.1)
        assert resp.getheader("X-Featurenet-Trace") == "fleet-test-1"
        conn.close()
    finally:
        srv.shutdown()


def test_poisson_loadgen_honors_retry_after():
    """A rejection carrying retry_after_s is retried once after the
    backoff instead of booking a blind rejection."""
    from featurenet_tpu.serve.batcher import PendingRequest
    from featurenet_tpu.serve.loadgen import poisson_load

    class Service:
        class cfg:
            resolution = 4

        def __init__(self):
            self.calls = 0

        def submit_voxels(self, grid, trace_id=None, lane="interactive"):
            self.calls += 1
            if self.calls == 1:
                raise OverloadError(4, 4, trace_id="t1",
                                    retry_after_s=0.02)
            p = PendingRequest(grid)
            p.value = 0
            p.t_done = time.perf_counter()
            p._event.set()
            return p

        def stats(self):
            return {"occupancy": None, "by_bucket": {}}

    svc = Service()
    stats, futs = poisson_load(svc, qps=500, n_requests=3)
    assert stats["rejected"] == 0 and stats["retried"] == 1
    assert len(futs) == 3
    svc2 = Service()
    stats2, _ = poisson_load(svc2, qps=500, n_requests=3,
                             honor_retry_after=False)
    assert stats2["rejected"] == 1 and stats2["retried"] == 0


# --- router: health gating / spillover / re-submit / lanes -------------------

def _ok_replica(label=3):
    def respond(path, body, headers):
        return 200, {"label": label,
                     "trace": headers.get("X-Featurenet-Trace")}, {}
    return _fake_replica(respond)


def test_router_health_gates_and_picks_least_queue():
    srv_a, port_a, hits_a = _ok_replica(1)
    srv_b, port_b, hits_b = _ok_replica(2)
    srv_c, port_c, hits_c = _ok_replica(9)  # NOT in the candidate set
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", port_a, 0),
        Candidate(1, "127.0.0.1", port_b, 5),
    ])
    router = _router(fleet)
    try:
        for _ in range(4):
            status, data, headers = router.route("/predict_voxels", b"g")
            assert status == 200
            assert json.loads(data.decode())["label"] == 1
        # Least-queue wins everything at these scores; the unlisted
        # (unhealthy) replica never sees a byte.
        assert len(hits_a) == 4 and not hits_b and not hits_c
        assert router.stats()["answered"] == 4
    finally:
        router.drain()
        for s in (srv_a, srv_b, srv_c):
            s.shutdown()


def test_router_spillover_preserves_trace(tmp_path):
    """A replica's overload 503 becomes 'try the next healthy replica'
    with the SAME trace id; the fleet answers 200."""
    obs.init_run(str(tmp_path / "run"), process_index=0)

    def overloaded(path, body, headers):
        return 503, {"error": "overload", "queue_depth": 9, "limit": 8,
                     "retry_after_s": 0.07}, {"Retry-After": "0.070"}

    srv_a, port_a, hits_a = _fake_replica(overloaded)
    srv_b, port_b, hits_b = _ok_replica(5)
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", port_a, 0),   # least loaded → tried 1st
        Candidate(1, "127.0.0.1", port_b, 3),
    ])
    router = _router(fleet)
    try:
        status, data, headers = router.route(
            "/predict_voxels", b"g", trace_id="spill-trace-7"
        )
        assert status == 200
        body = json.loads(data.decode())
        # The replica that answered saw the ORIGINAL trace id.
        assert body["trace"] == "spill-trace-7"
        assert headers["X-Featurenet-Trace"] == "spill-trace-7"
        assert len(hits_a) == 1 and len(hits_b) == 1
        assert router.stats()["spillovers"] == 1
    finally:
        router.drain()
        obs.close_run()
        srv_a.shutdown()
        srv_b.shutdown()
    events, _ = load_events(str(tmp_path / "run"))
    sp = [e for e in events if e["ev"] == "fleet_spillover"]
    assert len(sp) == 1 and sp[0]["trace"] == "spill-trace-7" \
        and sp[0]["from_replica"] == 0


def test_router_fleet_wide_503_when_every_lane_full():
    def overloaded(path, body, headers):
        return 503, {"error": "overload", "queue_depth": 9,
                     "limit": 8}, {"Retry-After": "0.090"}

    srv_a, port_a, _ = _fake_replica(overloaded)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0)])
    router = _router(fleet)
    try:
        status, data, headers = router.route("/predict_voxels", b"g")
        assert status == 503
        body = json.loads(data.decode())
        assert body["error"] == "overload" and body["fleet"] is True
        # The walk's last replica hint rides out on the fleet answer.
        assert float(headers["Retry-After"]) == pytest.approx(0.09)
        st = router.stats()
        assert st["rejected"] == 1 and st["spillovers"] == 1
    finally:
        router.drain()
        srv_a.shutdown()


def test_router_resubmits_once_to_survivor(tmp_path):
    """The replica-loss path: a connection dying mid-request re-submits
    ONCE to a survivor (idempotent — classification is pure); the dead
    replica is gated out of the candidate set immediately."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    srv_b, port_b, hits_b = _ok_replica(4)
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", _dead_port(), 0),  # dies on connect
        Candidate(1, "127.0.0.1", port_b, 2),
    ])
    router = _router(fleet)
    try:
        status, data, headers = router.route(
            "/predict_voxels", b"g", trace_id="resubmit-trace-1"
        )
        assert status == 200
        assert json.loads(data.decode())["trace"] == "resubmit-trace-1"
        st = router.stats()
        assert st["resubmits"] == 1 and st["dropped"] == 0
        assert fleet.failed == [0]
        assert len(hits_b) == 1
    finally:
        router.drain()
        obs.close_run()
        srv_b.shutdown()
    events, _ = load_events(str(tmp_path / "run"))
    rs = [e for e in events if e["ev"] == "fleet_resubmit"]
    assert len(rs) == 1 and rs[0]["from_replica"] == 0


def test_router_drops_after_second_connection_death():
    """Re-submit ONCE means once: two replicas dying under the same
    request is an honest 502 drop — the third healthy replica is NOT
    tried (no retry storms), and the drop lands in the counter the
    gate pins at zero."""
    srv_c, port_c, hits_c = _ok_replica(1)
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", _dead_port(), 0),
        Candidate(1, "127.0.0.1", _dead_port(), 1),
        Candidate(2, "127.0.0.1", port_c, 2),
    ])
    router = _router(fleet)
    try:
        status, data, _ = router.route("/predict_voxels", b"g")
        assert status == 502
        assert json.loads(data.decode())["error"] == "replica_lost"
        st = router.stats()
        assert st["dropped"] == 1 and st["resubmits"] == 1
        assert not hits_c  # once means once
    finally:
        router.drain()
        srv_c.shutdown()


def test_router_sheds_batch_lane_first(tmp_path):
    """Router-level shed order: when every healthy replica sits above
    the batch pressure bar, batch is shed immediately (503 +
    Retry-After, no replica touched) while interactive still routes."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    srv_a, port_a, hits_a = _ok_replica(2)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 9)])
    router = _router(fleet, batch_shed_depth=8)
    try:
        status, data, headers = router.route(
            "/predict_voxels", b"g", lane="batch"
        )
        assert status == 503
        body = json.loads(data.decode())
        assert body["shed"] is True and body["lane"] == "batch"
        assert "Retry-After" in headers
        assert not hits_a  # shed before any replica was occupied
        status, _, _ = router.route("/predict_voxels", b"g",
                                    lane="interactive")
        assert status == 200 and len(hits_a) == 1
        st = router.stats()
        assert st["shed"] == 1 and st["answered"] == 1
    finally:
        router.drain()
        obs.close_run()
        srv_a.shutdown()
    events, _ = load_events(str(tmp_path / "run"))
    shed = [e for e in events if e["ev"] == "fleet_shed"]
    assert len(shed) == 1 and shed[0]["lane"] == "batch"


# --- the connection pool (fleet.pool) ----------------------------------------

def test_pool_reuses_keepalive_channels():
    """Sequential pooled POSTs to one endpoint pay ONE handshake: the
    channel is checked back in and reused, and the counters prove it."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port, hits = _ok_replica(1)
    pool = ConnectionPool()
    try:
        for _ in range(4):
            status, raw, _ = pool.post(
                "127.0.0.1", port, "/predict_voxels", b"g", {}, 10.0
            )
            assert status == 200
        st = pool.stats()
        assert st["opened"] == 1 and st["reused"] == 3, st
        assert st["reuse_ratio"] == pytest.approx(0.75)
        assert len(hits) == 4
    finally:
        pool.close()
        srv.shutdown()
    assert pool.stats()["retired"].get("shutdown") == 1


def test_pool_max_idle_and_max_age_eviction():
    """The bounded-idle and max-age retirement units: a check-in beyond
    the idle bound retires the extra channel (idle_overflow); an idle
    channel older than max_age_s is retired at the next checkout and a
    fresh one opened (max_age)."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port, _ = _ok_replica(1)
    pool = ConnectionPool(max_idle_per_endpoint=1, max_age_s=0.2)
    try:
        a = pool.checkout("127.0.0.1", port)
        b = pool.checkout("127.0.0.1", port)
        pool.checkin(a)
        pool.checkin(b)
        st = pool.stats()
        assert st["opened"] == 2
        assert st["retired"].get("idle_overflow") == 1
        assert st["idle"] == 1
        time.sleep(0.25)  # the surviving idle channel outlives max_age_s
        c = pool.checkout("127.0.0.1", port)
        st = pool.stats()
        assert st["retired"].get("max_age") == 1
        assert st["opened"] == 3 and st["reused"] == 0
        pool.retire(c, "shutdown")
    finally:
        pool.close()
        srv.shutdown()


def _closing_server():
    """A scripted raw-socket server that answers one keep-alive-looking
    response per CONNECTION and then hangs up — the stale-channel shape
    (a peer may close an idle keep-alive connection at any time)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]

    def run():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return  # listener closed: test over
            with c:
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    data += chunk
                if b"\r\n\r\n" not in data:
                    continue
                head, rest = data.split(b"\r\n\r\n", 1)
                want = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        want = int(line.split(b":")[1])
                while len(rest) < want:
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    rest += chunk
                c.sendall(b"HTTP/1.1 200 OK\r\n"
                          b"Content-Length: 2\r\n\r\nok")
            # the with-block closed the socket: the client's pooled
            # channel is now stale without knowing it

    threading.Thread(target=run, daemon=True).start()
    return srv, port


def test_pool_stale_reuse_retries_fresh_never_raises():
    """A keep-alive peer closing an idle channel between requests must
    NOT surface as a connection failure (it would burn the router's one
    re-submit on a healthy replica): the pool retires the stale channel
    and retries once on a fresh connection, transparently."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port = _closing_server()
    pool = ConnectionPool()
    try:
        for i in range(3):
            status, raw, _ = pool.post(
                "127.0.0.1", port, "/x", b"body", {}, 10.0
            )
            assert status == 200 and raw == b"ok", (i, status, raw)
        st = pool.stats()
        # Every request after the first found a stale channel, retired
        # it (broken), and succeeded on a fresh connection.
        assert st["opened"] == 3, st
        assert st["retired"].get("broken") == 2, st
    finally:
        pool.close()
        srv.close()


def test_pool_fresh_connection_failure_raises():
    """A FRESH connection failing is the real replica-loss shape and
    must raise — the router's re-submit-once semantics key off it."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    pool = ConnectionPool()
    with pytest.raises(OSError):
        pool.post("127.0.0.1", _dead_port(), "/x", b"g", {}, 2.0)
    pool.close()


def test_pool_retire_endpoint_drops_idle_channels():
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port, _ = _ok_replica(1)
    pool = ConnectionPool()
    try:
        pool.post("127.0.0.1", port, "/predict_voxels", b"g", {}, 10.0)
        assert pool.stats()["idle"] == 1
        assert pool.retire_endpoint("127.0.0.1", port,
                                    "probe_failure") == 1
        st = pool.stats()
        assert st["idle"] == 0
        assert st["retired"].get("probe_failure") == 1
        # The next request starts clean on a fresh connection.
        status, _, _ = pool.post("127.0.0.1", port, "/predict_voxels",
                                 b"g", {}, 10.0)
        assert status == 200 and pool.stats()["opened"] == 2
    finally:
        pool.close()
        srv.shutdown()


def test_router_front_end_keepalive_and_metrics():
    """The router front end speaks HTTP/1.1: one client socket serves
    several routed requests, and GET /metrics exports the pool's
    channel counters."""
    import http.client

    srv_a, port_a, hits_a = _ok_replica(7)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0)])
    router = _router(fleet)
    srv = router.make_server("127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        sock = None
        for _ in range(3):
            conn.request("POST", "/predict_voxels", body=b"g")
            resp = conn.getresponse()
            assert resp.status == 200 and resp.version == 11
            resp.read()
            if sock is None:
                sock = conn.sock
        # Same client socket throughout: the front end never closed it.
        assert conn.sock is sock
        # Router-side: 3 forwards over a pooled channel = 1 handshake.
        st = router.stats()["pool"]
        assert st["opened"] == 1 and st["reused"] == 2, st
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        text = resp.read().decode()
        assert resp.status == 200
        assert "featurenet_connections_opened_total 1" in text
        assert "featurenet_connections_reused_total 2" in text
        assert 'featurenet_fleet_requests_total{outcome="answered"} 3' \
            in text
        conn.close()
    finally:
        router.drain()
        srv.shutdown()
        srv_a.shutdown()


def test_report_folds_connection_events(tmp_path):
    """conn_open/conn_reuse/conn_retire land in the report: top-level
    connections summary, mirrored under the fleet section, rendered."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    srv_a, port_a, _ = _ok_replica(3)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0)])
    router = _router(fleet)
    try:
        for _ in range(4):
            status, _, _ = router.route("/predict_voxels", b"g")
            assert status == 200
    finally:
        router.drain()
        obs.close_run()
        srv_a.shutdown()
    events, bad = load_events(str(tmp_path / "run"))
    assert bad == 0
    assert sum(e["ev"] == "conn_open" for e in events) == 1
    assert sum(e["ev"] == "conn_reuse" for e in events) == 3
    opens = [e for e in events if e["ev"] == "conn_open"]
    assert opens[0]["endpoint"] == f"127.0.0.1:{port_a}"
    assert opens[0]["connect_ms"] >= 0
    retires = [e for e in events if e["ev"] == "conn_retire"]
    assert retires and all(e["reason"] == "shutdown" for e in retires)
    rep = build_report(events)
    assert rep["connections"]["opened"] == 1
    assert rep["connections"]["reused"] == 3
    assert rep["connections"]["reuse_ratio"] == pytest.approx(0.75)
    assert rep["connections"]["retired"].get("shutdown") == 1
    text = format_report(rep)
    assert "connections: 1 opened, 3 reused" in text


def test_scale_verdict_units():
    # (burn_fast, burn_slow, queue_depth, ready) → verdict.
    # No routable replica → add, regardless of burn history.
    assert scale_verdict(None, None, 0.0, 0) == "add"
    # BOTH windows burning past max_burn → sustained capacity problem.
    assert scale_verdict(5.0, 2.0, 0.0, 2) == "add"
    # A fast-window spike alone is a blip, not a capacity problem.
    assert scale_verdict(5.0, 0.5, 0.0, 2) == "hold"
    assert scale_verdict(0.5, 5.0, 0.0, 2) == "hold"
    # An empty window (None) can never justify an add on its own.
    assert scale_verdict(5.0, None, 0.0, 2) == "hold"
    # Queue pressure building → add, even with cold burn windows.
    assert scale_verdict(0.0, 0.0, 20.0, 2) == "add"
    # Oversized: multiple replicas, idle queues, a slow window that has
    # burned essentially nothing → shed; honest absence doesn't block.
    assert scale_verdict(0.0, 0.05, 0.0, 3) == "shed"
    assert scale_verdict(None, None, 0.0, 3) == "shed"
    # A single replica never sheds below 1.
    assert scale_verdict(0.0, 0.0, 0.0, 1) == "hold"
    # Budget spend inside the allowed rate → hold.
    assert scale_verdict(0.8, 0.6, 2.0, 2) == "hold"
    # A custom max_burn moves the add threshold with it.
    assert scale_verdict(1.5, 1.5, 0.0, 2, max_burn=2.0) == "hold"


def test_router_healthz_reports_roster_summary(tmp_path):
    """Satellite: GET /healthz answers "is this fleet degraded" without
    /metrics parsing — healthy/total counts plus the draining flag."""
    srv_a, port_a, _ = _fake_replica(
        lambda p, b, h: (200, {"ok": True}, {})
    )
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0.0)])
    router = _router(fleet)
    front = router.make_server("127.0.0.1", 0)
    threading.Thread(target=front.serve_forever, daemon=True).start()
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{front.server_address[1]}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            doc = json.loads(resp.read())
        assert doc["ready"] is True and doc["fleet"] is True
        assert doc["healthy"] == 1 and doc["total"] == 1
        assert doc["draining"] is False
        # Degraded roster: candidates gone → 503 with the counts still
        # readable (the WHY, not just the refusal).
        fleet.cands = []
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            doc = json.loads(e.read())
        assert doc["ready"] is False and doc["healthy"] == 0
    finally:
        front.shutdown()
        router.drain()
        srv_a.shutdown()


# --- membership ready-signal re-admission ------------------------------------

def test_membership_ready_signal_roundtrip(tmp_path):
    rd = str(tmp_path)
    # No membership yet: nothing to signal against; the agent polls.
    assert signal_ready(rd, 1) is False
    write_membership(rd, Membership(0, (0, 2), 1, "start"))
    assert ready_slots(rd) == set()
    assert signal_ready(rd, 1) is True
    assert ready_slots(rd) == {1}
    m = read_membership(rd)
    assert m.members == (0, 2) and m.ready == (1,)
    # A serving member has nothing to signal; idempotent for signals.
    assert signal_ready(rd, 0) is True
    assert signal_ready(rd, 1) is True
    assert ready_slots(rd) == {1}
    # Pre-agent documents (no "ready" key) keep reading.
    with open(os.path.join(rd, "membership.json")) as fh:
        doc = json.load(fh)
    del doc["ready"]
    with open(os.path.join(rd, "membership.json"), "w") as fh:
        json.dump(doc, fh)
    assert read_membership(rd).ready == ()


def test_coordinator_agent_readmit_waits_for_signal(tmp_path):
    """readmit='agent': a lost slot stays out at the first boundary (no
    signal) and rejoins at the boundary AFTER its agent writes the slot
    into membership.json — the external-host re-admission satellite."""
    from featurenet_tpu.elastic import ElasticCoordinator, heartbeat_path

    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)

    def beat_then(code, hb):
        return [sys.executable, "-c",
                "import os, time\n"
                f"hb = {hb!r}\n"
                "time.sleep(0.25); open(hb, 'a').close(); "
                "os.utime(hb, None)\n"
                "time.sleep(0.1)\n"
                + code]

    signal_code = (
        "from featurenet_tpu.elastic.membership import signal_ready\n"
        f"signal_ready({run_dir!r}, 1)\n"
        "raise SystemExit(75)"
    )
    scenario = {
        (0, 0): "import time; time.sleep(60)",   # killed in the re-form
        (0, 1): "raise SystemExit(9)",           # the loss
        (1, 0): "raise SystemExit(75)",          # boundary, NO signal yet
        (2, 0): signal_code,                     # agent signals, boundary
        # gen 3: both slots default to exit 0 → done at full strength.
    }

    def spawn(members, rank, generation, port):
        slot = members[rank]
        code = scenario.get((generation, slot), "raise SystemExit(0)")
        return beat_then(code, heartbeat_path(run_dir, slot))

    res = ElasticCoordinator(
        2, spawn, run_dir, min_world_size=1, global_batch=8,
        local_devices=2, poll_s=0.1, grace_s=30.0, stall_timeout_s=30.0,
        backoff_base_s=0.05, readmit="agent", log=lambda _: None,
    ).run()
    assert res.exit_code == 0
    assert res.losses == 1 and res.rejoins == 1
    # Two planned cuts: the unsignaled boundary held the world at 1.
    assert res.planned == 2
    reforms = []
    with open(os.path.join(run_dir, "events.jsonl")) as fh:
        for line in fh:
            e = json.loads(line)
            if e.get("ev") == "mesh_reform":
                reforms.append((e["from_n"], e["to_n"], e["reason"]))
            if e.get("ev") == "host_join":
                assert e["host"] == 1 and e["generation"] == 3
    assert reforms == [(0, 2, "start"), (2, 1, "host_loss"),
                       (1, 2, "host_rejoin")]
    m = read_membership(run_dir)
    assert m.members == (0, 1)
    # The admission consumed the signal.
    assert m.ready == ()
    with pytest.raises(ValueError, match="readmit"):
        ElasticCoordinator(2, spawn, run_dir, readmit="bogus")


def test_cli_fleet_requires_run_dir():
    from featurenet_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="run-dir"):
        cli_main(["fleet", "--checkpoint-dir", "/nonexistent"])


# --- the acceptance e2e ------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_ckpt(tmp_path_factory):
    """A real trained smoke16 checkpoint the replica children serve."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    d = str(tmp_path_factory.mktemp("fleet_ckpt") / "ckpt")
    cfg = get_config(
        "smoke16", total_steps=6, eval_every=10**9, checkpoint_every=6,
        log_every=6, checkpoint_dir=d, data_workers=1,
    )
    Trainer(cfg).run()
    return d


def test_fleet_e2e_replica_loss_zero_drops_cached_rejoin(
    fleet_ckpt, tmp_path
):
    """ISSUE 14 acceptance: a 2-replica CPU fleet under open-loop HTTP
    load survives a ``replica_loss`` injection — zero admitted-request
    drops, the in-flight work re-submits to the survivor, the killed
    replica rejoins from the fleet-SHARED exec cache with zero fresh
    compiles, and the report renders the roster timeline."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv

    run_dir = str(tmp_path / "run")
    cache_dir = str(tmp_path / "exec_cache")
    obs.init_run(run_dir, process_index=0, extra={"cmd": "fleet-e2e"})
    # The chaos arm: SIGKILL a live replica at the router's 40th routed
    # request (the router-side site; the manager's marker dir keeps it
    # one-shot for the run).
    faults.install("replica_loss@request=40", state_dir=run_dir,
                   only={"replica_loss"})

    def spawn(slot, hb):
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=cache_dir, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64,
        )

    manager = ReplicaManager(2, spawn, run_dir)
    router = FleetRouter(manager, slo_p99_ms=2000.0, scale_every_s=0.5)
    srv = None
    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        obs.emit("fleet_start", replicas=2, host="127.0.0.1", port=port)
        grids = generate_batch(
            np.random.default_rng(0), 16, RES
        )["voxels"]
        stats, outcomes = http_load(
            "127.0.0.1", port, qps=80.0, n_requests=240, grids=grids
        )
        # The whole promise: NOTHING admitted was dropped, through a
        # replica SIGKILLed mid-stream.
        assert stats["dropped"] == 0, (stats, router.stats())
        assert stats["answered"] + stats["rejected"] == 240
        assert stats["answered"] >= 200, stats
        assert stats["p99_ms"] is not None
        for o in outcomes:
            if o and o.get("status") == 200:
                assert isinstance(o["label"], int)
        # The kill fired and at least one in-flight request re-submitted
        # to the survivor.
        st = router.stats()
        assert manager.stats()["losses"] >= 1, manager.stats()
        assert st["resubmits"] >= 1, st
        # Rejoin: the respawned replica comes back ready (seconds — it
        # warms its whole bucket ladder from the shared exec cache).
        t_rejoin = time.monotonic() + 300
        while manager.ready_count() < 2:
            assert time.monotonic() < t_rejoin, \
                f"rejoin timed out: {manager.stats()}"
            time.sleep(0.25)
        assert manager.stats()["rejoins"] >= 1
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        assert st["dropped"] == 0
        # The pooled data plane carried the whole run: channels were
        # REUSED (not one handshake per forward), and the kill retired
        # channels instead of leaking corpse sockets into later
        # forwards — the zero-drop assertion above is the oracle that
        # retirement preserved the re-submit-once semantics.
        assert st["pool"]["reused"] > st["pool"]["opened"], st["pool"]
        assert st["pool"]["reuse_ratio"] > 0.5, st["pool"]
    finally:
        if srv is not None:
            srv.shutdown()
        manager.stop()
        obs.close_run()
        faults.uninstall()
    # --- post-hoc: roster timeline, zero fresh compiles on rejoin ----------
    events, bad = load_events(run_dir)
    assert bad == 0
    losses = [e for e in events if e["ev"] == "fleet_replica_loss"]
    readies = [e for e in events if e["ev"] == "fleet_replica_ready"]
    assert losses, "no fleet_replica_loss event"
    t_loss = losses[0]["t"]
    # 2 initial readies + the rejoin (all loss victims eventually ready).
    assert len(readies) >= 3
    assert any(e["t"] > t_loss for e in readies)
    # Zero fresh compiles after the loss: the respawned replica warms
    # every bucket from the fleet-shared exec cache (cache_hit events),
    # never the XLA compiler.
    compiles_after = [e for e in events
                     if e["ev"] == "program_compile" and e["t"] > t_loss]
    assert not compiles_after, compiles_after
    assert [e for e in events
            if e["ev"] == "cache_hit" and e["t"] > t_loss]
    # Scale verdicts were advisory events, not load-bearing.
    assert [e for e in events if e["ev"] == "fleet_scale"]
    # The channel lifecycle is in the stream: opens with their
    # connect_ms, reuses, and the kill's retirements (broken and/or
    # replica_loss/probe_failure — the loss was discovered somewhere).
    assert [e for e in events if e["ev"] == "conn_open"]
    assert [e for e in events if e["ev"] == "conn_reuse"]
    retire_reasons = {e["reason"] for e in events
                      if e["ev"] == "conn_retire"}
    assert retire_reasons & {"broken", "replica_loss", "probe_failure"}, \
        retire_reasons
    # The roster file is the elastic schema, final state = full strength.
    m = read_membership(run_dir)
    assert m is not None and m.members == (0, 1)
    assert m.reason == "replica_rejoin"
    # The report folds it all: fleet section + mesh-style timeline.
    rep = build_report(events)
    assert rep["fleet"]["losses"] >= 1
    assert rep["fleet"]["resubmits"] >= 1
    assert rep["fleet"]["dropped"] == 0
    assert any(e["event"] == "fleet_replica_loss"
               for e in rep["fleet"]["timeline"])
    text = format_report(rep)
    assert "fleet:" in text and "scale verdicts" in text


def test_fleet_e2e_burn_rate_scrape_alert_and_dash(
    fleet_ckpt, tmp_path, capsys
):
    """ISSUE 16 acceptance: a real 2-replica CPU fleet with
    ``replica_slow`` injected on one replica — the scraper populates the
    run_dir time-series store from all three /metrics endpoints, the
    burn-rate SLO fires during the slowdown and resolves after recovery,
    ``fleet_scale`` flips to ``add`` on sustained burn and ``hold``
    after, and the dashboard + report fleet timeline render from the
    store ALONE once every serving process has exited."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv
    from featurenet_tpu.fleet.scraper import ROUTER_TARGET, MetricsScraper
    from featurenet_tpu.obs import alerts as _alerts
    from featurenet_tpu.obs import tsdb as _tsdb
    from featurenet_tpu.obs.dash import render_frame
    from featurenet_tpu.obs.report import build_report_dir

    run_dir = str(tmp_path / "run")
    cache_dir = str(tmp_path / "exec_cache")
    obs.init_run(run_dir, process_index=0,
                 extra={"cmd": "fleet-e2e-burn"})
    # The chaos arm rides the CHILD argv: slot 1 sleeps 250 ms on every
    # forward. Mutable so the recovery respawn comes up clean.
    fault_for = {1: "replica_slow@request=1:every=1"}

    def spawn(slot, hb):
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=cache_dir, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64, inject_faults=fault_for.get(slot),
        )

    store = _tsdb.TimeSeriesStore.open(run_dir)
    # Tight windows so the e2e exercises the real multi-window shape in
    # seconds. The 200 ms objective sits between the fleet's clean p99
    # under light CPU load (~tens of ms) and the injected 250 ms
    # forwards; the fast window proves "now", the slow "sustained".
    rule = _alerts.BurnRateRule("serving_p99_ms", "<", 200.0, 0.99,
                                "critical", fast_s=5.0, slow_s=120.0)
    manager = ReplicaManager(2, spawn, run_dir)
    # slo_p99_ms=2000 keeps the THRESHOLD alerts (and the drain gate)
    # out of the story — this test is about the burn layer.
    router = FleetRouter(manager, slo_p99_ms=2000.0,
                         scale_every_s=3600.0, store=store, slos=[rule])
    srv = None
    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        scraper = MetricsScraper(
            store, manager.pool,
            lambda: {
                **{str(s): p
                   for s, p in manager.stats()["ports"].items()},
                ROUTER_TARGET: port,
            },
        )
        router._scale_tick()  # baseline verdict, cold windows
        grids = generate_batch(
            np.random.default_rng(0), 16, RES
        )["voxels"]
        # --- slowdown: load + scrape until the verdict flips to add ---
        t_end = time.monotonic() + 240
        while router._last_verdict != "add":
            assert time.monotonic() < t_end, (
                router.scale_state(), scraper.stats())
            stats, _ = http_load("127.0.0.1", port, qps=80.0,
                                 n_requests=24, grids=grids)
            assert stats["dropped"] == 0, stats
            scraper.scrape_once()
            router._scale_tick()
        st_scale = router.scale_state()
        assert st_scale["burn_fast"] > 1.0, st_scale
        assert st_scale["burn_slow"] > 1.0, st_scale
        assert router._burn.active_alerts() == ["serving_p99_ms"]
        # --- recovery: clear the fault, recycle the slow replica ------
        del fault_for[1]
        assert manager.kill_one() == 1  # highest live slot = the slow one
        t_rejoin = time.monotonic() + 300
        while manager.ready_count() < 2:
            assert time.monotonic() < t_rejoin, \
                f"rejoin timed out: {manager.stats()}"
            time.sleep(0.25)
        # Flush the router's 128-sample serving window with fast
        # traffic, then collect clean rounds: the fast window drains,
        # the slow window still remembers — resolve + hold, not shed.
        # Gentle but long: enough requests to flush every 128-sample
        # window past warmup/slowdown residue, at a rate the CPU fleet
        # serves WITHIN the objective (a hammering burst would queue its
        # way over the threshold and look like the outage it is
        # flushing).
        stats, _ = http_load("127.0.0.1", port, qps=40.0,
                             n_requests=300, grids=grids)
        assert stats["dropped"] == 0, stats
        # Let the slowdown-era scrapes age out of the FAST window (the
        # whole injection phase can fit inside it on a warm machine),
        # then collect rounds that read the now-clean gauges.
        time.sleep(rule.fast_s + 0.5)
        for _ in range(3):
            scraper.scrape_once()
            time.sleep(0.2)
        router._scale_tick()
        st_scale = router.scale_state()
        assert router._last_verdict == "hold", st_scale
        assert st_scale["burn_fast"] is not None
        assert st_scale["burn_fast"] < 1.0, st_scale
        assert router._burn.active_alerts() == []
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        st["scrape"] = scraper.stats()
        assert st["scrape"]["samples"] > 0
        assert not store.stats()["dark"], store.stats()
    finally:
        if srv is not None:
            srv.shutdown()
        manager.stop()
        store.close()
        obs.close_run()
    # --- post-hoc, from the run_dir alone -----------------------------------
    events, bad = load_events(run_dir)
    assert bad == 0
    burn_alerts = [e for e in events if e["ev"] == "alert"
                   and e["rule"] == "serving_p99_ms_burn"]
    assert [e["state"] for e in burn_alerts] == ["fire", "resolve"], \
        burn_alerts
    verdicts = [e["verdict"] for e in events
                if e["ev"] == "fleet_scale"]
    assert "add" in verdicts and verdicts[-1] == "hold", verdicts
    # The store outlived every serving process: all three endpoints'
    # series are on disk, p99 history included.
    reader = _tsdb.TimeSeriesStore.open(run_dir)
    scraped = {lb.get("replica") for _m, lb in reader.series()
               if lb.get("replica") is not None}
    assert {"0", "1", ROUTER_TARGET} <= scraped, scraped
    for target in ("0", "1", ROUTER_TARGET):
        assert reader.query("serving_ms",
                            {"q": "0.99", "replica": target}), target
        assert reader.query("scrape_duration_ms",
                            {"replica": target}), target
    # The dashboard renders from the store alone — module and CLI.
    frame = render_frame(run_dir)
    assert frame.splitlines()[0].startswith("fleet dash")
    assert "burn serving_p99_ms" in frame
    from featurenet_tpu.cli import main as cli_main

    cli_main(["dash", run_dir, "--once"])
    out = capsys.readouterr().out
    assert "3 target(s)" in out and "router" in out
    # And the report's fleet timeline, store-only too.
    rep = build_report_dir(run_dir)
    tl = rep.get("fleet_timeline")
    assert tl and ROUTER_TARGET in tl["targets"]
    assert tl["targets"]["1"]["samples"] > 0
    assert "fleet timeline" in format_report(rep)
