"""Serving fleet (featurenet_tpu.fleet): router health-gating, spillover
and re-submit-once semantics over fake replicas, priority-lane shed order
(batcher lane caps + router-level shed), Retry-After propagation and the
loadgen honor path, the membership ready-signal re-admission protocol,
scale verdicts — plus the acceptance spine (ISSUE 14): a REAL 2-replica
CPU fleet under open-loop HTTP load that survives a ``replica_loss``
injection with zero admitted-request drops, a roster timeline in the
report, and the killed replica rejoining from the fleet-shared exec
cache with zero fresh compiles.

ISSUE 18 closes the control loop: the ``Autoscaler`` state machine
(hysteresis, honest hold, cooldown-since-last-ACTION), the manager's
``add_one``/``shed_one`` park-and-revive levers, live ``swap_params``
hot-swap + ``POST /admin/reload``, the scraper's per-target ``version``
label, and the chaos-gate e2es: autoscale-on-load-ramp, a rolling
``cli fleet rollout`` with canary verdicts and a forced ``swap_corrupt``
rollback, and a replica death mid-rollout re-converging to one version.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from featurenet_tpu import faults, obs
from featurenet_tpu.elastic.membership import (
    Membership,
    read_membership,
    ready_slots,
    signal_ready,
    write_membership,
)
from featurenet_tpu.fleet.replica import (
    Autoscaler,
    Candidate,
    ReplicaManager,
)
from featurenet_tpu.fleet.router import FleetRouter, scale_verdict
from featurenet_tpu.obs.report import (
    build_report,
    format_report,
    load_events,
)
from featurenet_tpu.serve.batcher import ContinuousBatcher, OverloadError

RES = 16


# --- fakes -------------------------------------------------------------------

def _fake_replica(respond):
    """A scripted replica HTTP server: ``respond(path, body, headers) ->
    (status, payload_dict, headers_dict)``. Returns (server, port,
    hits) — ``hits`` collects one record per POST. Speaks HTTP/1.1
    keep-alive like the real replicas, so pooled-channel reuse is
    exercised by every router test."""
    hits: list = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length)
            hits.append({"path": self.path,
                         "headers": dict(self.headers)})
            status, payload, extra = respond(
                self.path, body, dict(self.headers)
            )
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1], hits


def _dead_port() -> int:
    """A port with nothing listening (bound, then closed) — connecting
    to it is the replica-just-died shape (connection refused)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class FakeFleet:
    """The router's provider contract, scripted: a mutable candidate
    list plus recordings of note_failure / kill_one calls."""

    def __init__(self, cands):
        self.cands = list(cands)
        self.failed: list[int] = []
        self.inflight: dict[int, int] = {}
        self.killed = 0

    def candidates(self):
        return sorted(self.cands, key=lambda c: (c.score, c.slot))

    def note_inflight(self, slot, delta):
        self.inflight[slot] = self.inflight.get(slot, 0) + delta

    def note_failure(self, slot):
        self.failed.append(slot)
        self.cands = [c for c in self.cands if c.slot != slot]

    def kill_one(self):
        self.killed += 1
        return None

    def ready_count(self):
        return len(self.cands)

    def stats(self):
        return {"replicas": len(self.cands)}


def _router(fleet, **kw):
    # rules=() keeps the unit tests from installing a process-wide
    # window aggregator; a huge scale period keeps the verdict thread
    # quiet unless a test asks for it.
    kw.setdefault("rules", ())
    kw.setdefault("scale_every_s", 3600.0)
    return FleetRouter(fleet, **kw)


# --- batcher priority lanes --------------------------------------------------

def test_batcher_lane_caps_shed_batch_first():
    """The batch lane rejects at its own cap while interactive traffic
    still has the rest of the queue — the shed order, at the replica."""
    gate = threading.Event()

    def blocked(bucket, arr):
        gate.wait(30)
        return arr.reshape(arr.shape[0], -1).sum(axis=1)

    b = ContinuousBatcher(blocked, buckets=(1,), max_wait_ms=0,
                          queue_limit=6, lane_limits={"batch": 2})
    futs = [b.submit(np.ones((1,)))]  # occupies the dispatcher
    time.sleep(0.2)
    futs += [b.submit(np.ones((1,)), lane="batch") for _ in range(2)]
    with pytest.raises(OverloadError) as ei:
        b.submit(np.ones((1,)), lane="batch")
    assert ei.value.lane == "batch"
    assert ei.value.retry_after_s and ei.value.retry_after_s >= 0.05
    assert ei.value.response["lane"] == "batch"
    # Interactive still has headroom: the global bound is 6, only 2 are
    # queued — the batch cap tripped first, exactly the shed order.
    futs.append(b.submit(np.ones((1,))))
    st = b.stats()
    assert st["by_lane"]["batch"]["rejected"] == 1
    assert st["by_lane"]["batch"]["limit"] == 2
    gate.set()
    for f in futs:
        f.result(30)
    st = b.drain()
    assert st["served"] == 4 and st["rejected"] == 1


def test_unknown_lane_normalizes_to_interactive():
    b = ContinuousBatcher(lambda bucket, arr: arr, buckets=(1,),
                          max_wait_ms=1, queue_limit=2)
    fut = b.submit(np.ones((1,)), lane="totally-bogus")
    assert fut.lane == "interactive"
    b.drain()
    with pytest.raises(ValueError, match="lane"):
        ContinuousBatcher(lambda bucket, arr: arr, buckets=(1,),
                          lane_limits={"bogus": 1})


# --- HTTP overload contract: Retry-After + replica field ---------------------

def test_http_503_carries_retry_after_and_replica(tmp_path):
    """The overload satellite: the 503 body grows lane/retry_after_s/
    replica and the Retry-After header carries the same hint."""
    import http.client
    import types

    from featurenet_tpu.serve.http import make_server

    def reject(data, trace_id=None, lane="interactive"):
        raise OverloadError(5, 4, trace_id=trace_id, lane=lane,
                            retry_after_s=0.1)

    service = types.SimpleNamespace(
        replica="r7",
        batcher=types.SimpleNamespace(retry_after_s=0.1),
        submit_stl_bytes=reject,
    )
    srv = make_server(service, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        conn.request("POST", "/predict", body=b"x",
                     headers={"X-Featurenet-Priority": "batch",
                              "X-Featurenet-Trace": "fleet-test-1"})
        resp = conn.getresponse()
        body = json.loads(resp.read().decode())
        assert resp.status == 503
        assert body["error"] == "overload"
        assert body["replica"] == "r7"
        assert body["lane"] == "batch"
        assert body["retry_after_s"] == 0.1
        assert float(resp.getheader("Retry-After")) == pytest.approx(0.1)
        assert resp.getheader("X-Featurenet-Trace") == "fleet-test-1"
        conn.close()
    finally:
        srv.shutdown()


def test_poisson_loadgen_honors_retry_after():
    """A rejection carrying retry_after_s is retried once after the
    backoff instead of booking a blind rejection."""
    from featurenet_tpu.serve.batcher import PendingRequest
    from featurenet_tpu.serve.loadgen import poisson_load

    class Service:
        class cfg:
            resolution = 4

        def __init__(self):
            self.calls = 0

        def submit_voxels(self, grid, trace_id=None, lane="interactive"):
            self.calls += 1
            if self.calls == 1:
                raise OverloadError(4, 4, trace_id="t1",
                                    retry_after_s=0.02)
            p = PendingRequest(grid)
            p.value = 0
            p.t_done = time.perf_counter()
            p._event.set()
            return p

        def stats(self):
            return {"occupancy": None, "by_bucket": {}}

    svc = Service()
    stats, futs = poisson_load(svc, qps=500, n_requests=3)
    assert stats["rejected"] == 0 and stats["retried"] == 1
    assert len(futs) == 3
    svc2 = Service()
    stats2, _ = poisson_load(svc2, qps=500, n_requests=3,
                             honor_retry_after=False)
    assert stats2["rejected"] == 1 and stats2["retried"] == 0


# --- router: health gating / spillover / re-submit / lanes -------------------

def _ok_replica(label=3):
    def respond(path, body, headers):
        return 200, {"label": label,
                     "trace": headers.get("X-Featurenet-Trace")}, {}
    return _fake_replica(respond)


def test_router_health_gates_and_picks_least_queue():
    srv_a, port_a, hits_a = _ok_replica(1)
    srv_b, port_b, hits_b = _ok_replica(2)
    srv_c, port_c, hits_c = _ok_replica(9)  # NOT in the candidate set
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", port_a, 0),
        Candidate(1, "127.0.0.1", port_b, 5),
    ])
    router = _router(fleet)
    try:
        for _ in range(4):
            status, data, headers = router.route("/predict_voxels", b"g")
            assert status == 200
            assert json.loads(data.decode())["label"] == 1
        # Least-queue wins everything at these scores; the unlisted
        # (unhealthy) replica never sees a byte.
        assert len(hits_a) == 4 and not hits_b and not hits_c
        assert router.stats()["answered"] == 4
    finally:
        router.drain()
        for s in (srv_a, srv_b, srv_c):
            s.shutdown()


def test_router_spillover_preserves_trace(tmp_path):
    """A replica's overload 503 becomes 'try the next healthy replica'
    with the SAME trace id; the fleet answers 200."""
    obs.init_run(str(tmp_path / "run"), process_index=0)

    def overloaded(path, body, headers):
        return 503, {"error": "overload", "queue_depth": 9, "limit": 8,
                     "retry_after_s": 0.07}, {"Retry-After": "0.070"}

    srv_a, port_a, hits_a = _fake_replica(overloaded)
    srv_b, port_b, hits_b = _ok_replica(5)
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", port_a, 0),   # least loaded → tried 1st
        Candidate(1, "127.0.0.1", port_b, 3),
    ])
    router = _router(fleet)
    try:
        status, data, headers = router.route(
            "/predict_voxels", b"g", trace_id="spill-trace-7"
        )
        assert status == 200
        body = json.loads(data.decode())
        # The replica that answered saw the ORIGINAL trace id.
        assert body["trace"] == "spill-trace-7"
        assert headers["X-Featurenet-Trace"] == "spill-trace-7"
        assert len(hits_a) == 1 and len(hits_b) == 1
        assert router.stats()["spillovers"] == 1
    finally:
        router.drain()
        obs.close_run()
        srv_a.shutdown()
        srv_b.shutdown()
    events, _ = load_events(str(tmp_path / "run"))
    sp = [e for e in events if e["ev"] == "fleet_spillover"]
    assert len(sp) == 1 and sp[0]["trace"] == "spill-trace-7" \
        and sp[0]["from_replica"] == 0


def test_router_fleet_wide_503_when_every_lane_full():
    def overloaded(path, body, headers):
        return 503, {"error": "overload", "queue_depth": 9,
                     "limit": 8}, {"Retry-After": "0.090"}

    srv_a, port_a, _ = _fake_replica(overloaded)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0)])
    router = _router(fleet)
    try:
        status, data, headers = router.route("/predict_voxels", b"g")
        assert status == 503
        body = json.loads(data.decode())
        assert body["error"] == "overload" and body["fleet"] is True
        # The walk's last replica hint rides out on the fleet answer.
        assert float(headers["Retry-After"]) == pytest.approx(0.09)
        st = router.stats()
        assert st["rejected"] == 1 and st["spillovers"] == 1
    finally:
        router.drain()
        srv_a.shutdown()


def test_router_resubmits_once_to_survivor(tmp_path):
    """The replica-loss path: a connection dying mid-request re-submits
    ONCE to a survivor (idempotent — classification is pure); the dead
    replica is gated out of the candidate set immediately."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    srv_b, port_b, hits_b = _ok_replica(4)
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", _dead_port(), 0),  # dies on connect
        Candidate(1, "127.0.0.1", port_b, 2),
    ])
    router = _router(fleet)
    try:
        status, data, headers = router.route(
            "/predict_voxels", b"g", trace_id="resubmit-trace-1"
        )
        assert status == 200
        assert json.loads(data.decode())["trace"] == "resubmit-trace-1"
        st = router.stats()
        assert st["resubmits"] == 1 and st["dropped"] == 0
        assert fleet.failed == [0]
        assert len(hits_b) == 1
    finally:
        router.drain()
        obs.close_run()
        srv_b.shutdown()
    events, _ = load_events(str(tmp_path / "run"))
    rs = [e for e in events if e["ev"] == "fleet_resubmit"]
    assert len(rs) == 1 and rs[0]["from_replica"] == 0


def test_router_drops_after_second_connection_death():
    """Re-submit ONCE means once: two replicas dying under the same
    request is an honest 502 drop — the third healthy replica is NOT
    tried (no retry storms), and the drop lands in the counter the
    gate pins at zero."""
    srv_c, port_c, hits_c = _ok_replica(1)
    fleet = FakeFleet([
        Candidate(0, "127.0.0.1", _dead_port(), 0),
        Candidate(1, "127.0.0.1", _dead_port(), 1),
        Candidate(2, "127.0.0.1", port_c, 2),
    ])
    router = _router(fleet)
    try:
        status, data, _ = router.route("/predict_voxels", b"g")
        assert status == 502
        assert json.loads(data.decode())["error"] == "replica_lost"
        st = router.stats()
        assert st["dropped"] == 1 and st["resubmits"] == 1
        assert not hits_c  # once means once
    finally:
        router.drain()
        srv_c.shutdown()


def test_router_sheds_batch_lane_first(tmp_path):
    """Router-level shed order: when every healthy replica sits above
    the batch pressure bar, batch is shed immediately (503 +
    Retry-After, no replica touched) while interactive still routes."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    srv_a, port_a, hits_a = _ok_replica(2)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 9)])
    router = _router(fleet, batch_shed_depth=8)
    try:
        status, data, headers = router.route(
            "/predict_voxels", b"g", lane="batch"
        )
        assert status == 503
        body = json.loads(data.decode())
        assert body["shed"] is True and body["lane"] == "batch"
        assert "Retry-After" in headers
        assert not hits_a  # shed before any replica was occupied
        status, _, _ = router.route("/predict_voxels", b"g",
                                    lane="interactive")
        assert status == 200 and len(hits_a) == 1
        st = router.stats()
        assert st["shed"] == 1 and st["answered"] == 1
    finally:
        router.drain()
        obs.close_run()
        srv_a.shutdown()
    events, _ = load_events(str(tmp_path / "run"))
    shed = [e for e in events if e["ev"] == "fleet_shed"]
    assert len(shed) == 1 and shed[0]["lane"] == "batch"


# --- the connection pool (fleet.pool) ----------------------------------------

def test_pool_reuses_keepalive_channels():
    """Sequential pooled POSTs to one endpoint pay ONE handshake: the
    channel is checked back in and reused, and the counters prove it."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port, hits = _ok_replica(1)
    pool = ConnectionPool()
    try:
        for _ in range(4):
            status, raw, _ = pool.post(
                "127.0.0.1", port, "/predict_voxels", b"g", {}, 10.0
            )
            assert status == 200
        st = pool.stats()
        assert st["opened"] == 1 and st["reused"] == 3, st
        assert st["reuse_ratio"] == pytest.approx(0.75)
        assert len(hits) == 4
    finally:
        pool.close()
        srv.shutdown()
    assert pool.stats()["retired"].get("shutdown") == 1


def test_pool_max_idle_and_max_age_eviction():
    """The bounded-idle and max-age retirement units: a check-in beyond
    the idle bound retires the extra channel (idle_overflow); an idle
    channel older than max_age_s is retired at the next checkout and a
    fresh one opened (max_age)."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port, _ = _ok_replica(1)
    pool = ConnectionPool(max_idle_per_endpoint=1, max_age_s=0.2)
    try:
        a = pool.checkout("127.0.0.1", port)
        b = pool.checkout("127.0.0.1", port)
        pool.checkin(a)
        pool.checkin(b)
        st = pool.stats()
        assert st["opened"] == 2
        assert st["retired"].get("idle_overflow") == 1
        assert st["idle"] == 1
        time.sleep(0.25)  # the surviving idle channel outlives max_age_s
        c = pool.checkout("127.0.0.1", port)
        st = pool.stats()
        assert st["retired"].get("max_age") == 1
        assert st["opened"] == 3 and st["reused"] == 0
        pool.retire(c, "shutdown")
    finally:
        pool.close()
        srv.shutdown()


def _closing_server():
    """A scripted raw-socket server that answers one keep-alive-looking
    response per CONNECTION and then hangs up — the stale-channel shape
    (a peer may close an idle keep-alive connection at any time)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]

    def run():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return  # listener closed: test over
            with c:
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    data += chunk
                if b"\r\n\r\n" not in data:
                    continue
                head, rest = data.split(b"\r\n\r\n", 1)
                want = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        want = int(line.split(b":")[1])
                while len(rest) < want:
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    rest += chunk
                c.sendall(b"HTTP/1.1 200 OK\r\n"
                          b"Content-Length: 2\r\n\r\nok")
            # the with-block closed the socket: the client's pooled
            # channel is now stale without knowing it

    threading.Thread(target=run, daemon=True).start()
    return srv, port


def test_pool_stale_reuse_retries_fresh_never_raises():
    """A keep-alive peer closing an idle channel between requests must
    NOT surface as a connection failure (it would burn the router's one
    re-submit on a healthy replica): the pool retires the stale channel
    and retries once on a fresh connection, transparently."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port = _closing_server()
    pool = ConnectionPool()
    try:
        for i in range(3):
            status, raw, _ = pool.post(
                "127.0.0.1", port, "/x", b"body", {}, 10.0
            )
            assert status == 200 and raw == b"ok", (i, status, raw)
        st = pool.stats()
        # Every request after the first found a stale channel, retired
        # it (broken), and succeeded on a fresh connection.
        assert st["opened"] == 3, st
        assert st["retired"].get("broken") == 2, st
    finally:
        pool.close()
        srv.close()


def test_pool_fresh_connection_failure_raises():
    """A FRESH connection failing is the real replica-loss shape and
    must raise — the router's re-submit-once semantics key off it."""
    from featurenet_tpu.fleet.pool import ConnectionPool

    pool = ConnectionPool()
    with pytest.raises(OSError):
        pool.post("127.0.0.1", _dead_port(), "/x", b"g", {}, 2.0)
    pool.close()


def test_pool_retire_endpoint_drops_idle_channels():
    from featurenet_tpu.fleet.pool import ConnectionPool

    srv, port, _ = _ok_replica(1)
    pool = ConnectionPool()
    try:
        pool.post("127.0.0.1", port, "/predict_voxels", b"g", {}, 10.0)
        assert pool.stats()["idle"] == 1
        assert pool.retire_endpoint("127.0.0.1", port,
                                    "probe_failure") == 1
        st = pool.stats()
        assert st["idle"] == 0
        assert st["retired"].get("probe_failure") == 1
        # The next request starts clean on a fresh connection.
        status, _, _ = pool.post("127.0.0.1", port, "/predict_voxels",
                                 b"g", {}, 10.0)
        assert status == 200 and pool.stats()["opened"] == 2
    finally:
        pool.close()
        srv.shutdown()


def test_router_front_end_keepalive_and_metrics():
    """The router front end speaks HTTP/1.1: one client socket serves
    several routed requests, and GET /metrics exports the pool's
    channel counters."""
    import http.client

    srv_a, port_a, hits_a = _ok_replica(7)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0)])
    router = _router(fleet)
    srv = router.make_server("127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=10
        )
        sock = None
        for _ in range(3):
            conn.request("POST", "/predict_voxels", body=b"g")
            resp = conn.getresponse()
            assert resp.status == 200 and resp.version == 11
            resp.read()
            if sock is None:
                sock = conn.sock
        # Same client socket throughout: the front end never closed it.
        assert conn.sock is sock
        # Router-side: 3 forwards over a pooled channel = 1 handshake.
        st = router.stats()["pool"]
        assert st["opened"] == 1 and st["reused"] == 2, st
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        text = resp.read().decode()
        assert resp.status == 200
        assert "featurenet_connections_opened_total 1" in text
        assert "featurenet_connections_reused_total 2" in text
        assert 'featurenet_fleet_requests_total{outcome="answered"} 3' \
            in text
        conn.close()
    finally:
        router.drain()
        srv.shutdown()
        srv_a.shutdown()


def test_report_folds_connection_events(tmp_path):
    """conn_open/conn_reuse/conn_retire land in the report: top-level
    connections summary, mirrored under the fleet section, rendered."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    srv_a, port_a, _ = _ok_replica(3)
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0)])
    router = _router(fleet)
    try:
        for _ in range(4):
            status, _, _ = router.route("/predict_voxels", b"g")
            assert status == 200
    finally:
        router.drain()
        obs.close_run()
        srv_a.shutdown()
    events, bad = load_events(str(tmp_path / "run"))
    assert bad == 0
    assert sum(e["ev"] == "conn_open" for e in events) == 1
    assert sum(e["ev"] == "conn_reuse" for e in events) == 3
    opens = [e for e in events if e["ev"] == "conn_open"]
    assert opens[0]["endpoint"] == f"127.0.0.1:{port_a}"
    assert opens[0]["connect_ms"] >= 0
    retires = [e for e in events if e["ev"] == "conn_retire"]
    assert retires and all(e["reason"] == "shutdown" for e in retires)
    rep = build_report(events)
    assert rep["connections"]["opened"] == 1
    assert rep["connections"]["reused"] == 3
    assert rep["connections"]["reuse_ratio"] == pytest.approx(0.75)
    assert rep["connections"]["retired"].get("shutdown") == 1
    text = format_report(rep)
    assert "connections: 1 opened, 3 reused" in text


def test_scale_verdict_units():
    # (burn_fast, burn_slow, queue_depth, ready) → verdict.
    # No routable replica → add, regardless of burn history.
    assert scale_verdict(None, None, 0.0, 0) == "add"
    # BOTH windows burning past max_burn → sustained capacity problem.
    assert scale_verdict(5.0, 2.0, 0.0, 2) == "add"
    # A fast-window spike alone is a blip, not a capacity problem.
    assert scale_verdict(5.0, 0.5, 0.0, 2) == "hold"
    assert scale_verdict(0.5, 5.0, 0.0, 2) == "hold"
    # An empty window (None) can never justify an add on its own.
    assert scale_verdict(5.0, None, 0.0, 2) == "hold"
    # Queue pressure building → add, even with cold burn windows.
    assert scale_verdict(0.0, 0.0, 20.0, 2) == "add"
    # Oversized: multiple replicas, idle queues, a slow window that has
    # burned essentially nothing → shed; honest absence doesn't block.
    assert scale_verdict(0.0, 0.05, 0.0, 3) == "shed"
    assert scale_verdict(None, None, 0.0, 3) == "shed"
    # A single replica never sheds below 1.
    assert scale_verdict(0.0, 0.0, 0.0, 1) == "hold"
    # Budget spend inside the allowed rate → hold.
    assert scale_verdict(0.8, 0.6, 2.0, 2) == "hold"
    # A custom max_burn moves the add threshold with it.
    assert scale_verdict(1.5, 1.5, 0.0, 2, max_burn=2.0) == "hold"


def test_router_healthz_reports_roster_summary(tmp_path):
    """Satellite: GET /healthz answers "is this fleet degraded" without
    /metrics parsing — healthy/total counts plus the draining flag."""
    srv_a, port_a, _ = _fake_replica(
        lambda p, b, h: (200, {"ok": True}, {})
    )
    fleet = FakeFleet([Candidate(0, "127.0.0.1", port_a, 0.0)])
    router = _router(fleet)
    front = router.make_server("127.0.0.1", 0)
    threading.Thread(target=front.serve_forever, daemon=True).start()
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{front.server_address[1]}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            doc = json.loads(resp.read())
        assert doc["ready"] is True and doc["fleet"] is True
        assert doc["healthy"] == 1 and doc["total"] == 1
        assert doc["draining"] is False
        # Degraded roster: candidates gone → 503 with the counts still
        # readable (the WHY, not just the refusal).
        fleet.cands = []
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            doc = json.loads(e.read())
        assert doc["ready"] is False and doc["healthy"] == 0
    finally:
        front.shutdown()
        router.drain()
        srv_a.shutdown()


# --- membership ready-signal re-admission ------------------------------------

def test_membership_ready_signal_roundtrip(tmp_path):
    rd = str(tmp_path)
    # No membership yet: nothing to signal against; the agent polls.
    assert signal_ready(rd, 1) is False
    write_membership(rd, Membership(0, (0, 2), 1, "start"))
    assert ready_slots(rd) == set()
    assert signal_ready(rd, 1) is True
    assert ready_slots(rd) == {1}
    m = read_membership(rd)
    assert m.members == (0, 2) and m.ready == (1,)
    # A serving member has nothing to signal; idempotent for signals.
    assert signal_ready(rd, 0) is True
    assert signal_ready(rd, 1) is True
    assert ready_slots(rd) == {1}
    # Pre-agent documents (no "ready" key) keep reading.
    with open(os.path.join(rd, "membership.json")) as fh:
        doc = json.load(fh)
    del doc["ready"]
    with open(os.path.join(rd, "membership.json"), "w") as fh:
        json.dump(doc, fh)
    assert read_membership(rd).ready == ()


def test_coordinator_agent_readmit_waits_for_signal(tmp_path):
    """readmit='agent': a lost slot stays out at the first boundary (no
    signal) and rejoins at the boundary AFTER its agent writes the slot
    into membership.json — the external-host re-admission satellite."""
    from featurenet_tpu.elastic import ElasticCoordinator, heartbeat_path

    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)

    def beat_then(code, hb):
        return [sys.executable, "-c",
                "import os, time\n"
                f"hb = {hb!r}\n"
                "time.sleep(0.25); open(hb, 'a').close(); "
                "os.utime(hb, None)\n"
                "time.sleep(0.1)\n"
                + code]

    signal_code = (
        "from featurenet_tpu.elastic.membership import signal_ready\n"
        f"signal_ready({run_dir!r}, 1)\n"
        "raise SystemExit(75)"
    )
    scenario = {
        (0, 0): "import time; time.sleep(60)",   # killed in the re-form
        (0, 1): "raise SystemExit(9)",           # the loss
        (1, 0): "raise SystemExit(75)",          # boundary, NO signal yet
        (2, 0): signal_code,                     # agent signals, boundary
        # gen 3: both slots default to exit 0 → done at full strength.
    }

    def spawn(members, rank, generation, port):
        slot = members[rank]
        code = scenario.get((generation, slot), "raise SystemExit(0)")
        return beat_then(code, heartbeat_path(run_dir, slot))

    res = ElasticCoordinator(
        2, spawn, run_dir, min_world_size=1, global_batch=8,
        local_devices=2, poll_s=0.1, grace_s=30.0, stall_timeout_s=30.0,
        backoff_base_s=0.05, readmit="agent", log=lambda _: None,
    ).run()
    assert res.exit_code == 0
    assert res.losses == 1 and res.rejoins == 1
    # Two planned cuts: the unsignaled boundary held the world at 1.
    assert res.planned == 2
    reforms = []
    with open(os.path.join(run_dir, "events.jsonl")) as fh:
        for line in fh:
            e = json.loads(line)
            if e.get("ev") == "mesh_reform":
                reforms.append((e["from_n"], e["to_n"], e["reason"]))
            if e.get("ev") == "host_join":
                assert e["host"] == 1 and e["generation"] == 3
    assert reforms == [(0, 2, "start"), (2, 1, "host_loss"),
                       (1, 2, "host_rejoin")]
    m = read_membership(run_dir)
    assert m.members == (0, 1)
    # The admission consumed the signal.
    assert m.ready == ()
    with pytest.raises(ValueError, match="readmit"):
        ElasticCoordinator(2, spawn, run_dir, readmit="bogus")


def test_cli_fleet_requires_run_dir():
    from featurenet_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="run-dir"):
        cli_main(["fleet", "--checkpoint-dir", "/nonexistent"])


# --- the acceptance e2e ------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_ckpt(tmp_path_factory):
    """A real trained smoke16 checkpoint the replica children serve."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    d = str(tmp_path_factory.mktemp("fleet_ckpt") / "ckpt")
    cfg = get_config(
        "smoke16", total_steps=6, eval_every=10**9, checkpoint_every=6,
        log_every=6, checkpoint_dir=d, data_workers=1,
    )
    Trainer(cfg).run()
    return d


def test_fleet_e2e_replica_loss_zero_drops_cached_rejoin(
    fleet_ckpt, tmp_path
):
    """ISSUE 14 acceptance: a 2-replica CPU fleet under open-loop HTTP
    load survives a ``replica_loss`` injection — zero admitted-request
    drops, the in-flight work re-submits to the survivor, the killed
    replica rejoins from the fleet-SHARED exec cache with zero fresh
    compiles, and the report renders the roster timeline."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv

    run_dir = str(tmp_path / "run")
    cache_dir = str(tmp_path / "exec_cache")
    obs.init_run(run_dir, process_index=0, extra={"cmd": "fleet-e2e"})
    # The chaos arm: SIGKILL a live replica at the router's 40th routed
    # request (the router-side site; the manager's marker dir keeps it
    # one-shot for the run).
    faults.install("replica_loss@request=40", state_dir=run_dir,
                   only={"replica_loss"})

    def spawn(slot, hb):
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=cache_dir, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64,
        )

    manager = ReplicaManager(2, spawn, run_dir)
    router = FleetRouter(manager, slo_p99_ms=2000.0, scale_every_s=0.5)
    srv = None
    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        obs.emit("fleet_start", replicas=2, host="127.0.0.1", port=port)
        grids = generate_batch(
            np.random.default_rng(0), 16, RES
        )["voxels"]
        stats, outcomes = http_load(
            "127.0.0.1", port, qps=80.0, n_requests=240, grids=grids
        )
        # The whole promise: NOTHING admitted was dropped, through a
        # replica SIGKILLed mid-stream.
        assert stats["dropped"] == 0, (stats, router.stats())
        assert stats["answered"] + stats["rejected"] == 240
        assert stats["answered"] >= 200, stats
        assert stats["p99_ms"] is not None
        for o in outcomes:
            if o and o.get("status") == 200:
                assert isinstance(o["label"], int)
        # The kill fired and at least one in-flight request re-submitted
        # to the survivor.
        st = router.stats()
        assert manager.stats()["losses"] >= 1, manager.stats()
        assert st["resubmits"] >= 1, st
        # Rejoin: the respawned replica comes back ready (seconds — it
        # warms its whole bucket ladder from the shared exec cache).
        t_rejoin = time.monotonic() + 300
        while manager.ready_count() < 2:
            assert time.monotonic() < t_rejoin, \
                f"rejoin timed out: {manager.stats()}"
            time.sleep(0.25)
        assert manager.stats()["rejoins"] >= 1
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        assert st["dropped"] == 0
        # The pooled data plane carried the whole run: channels were
        # REUSED (not one handshake per forward), and the kill retired
        # channels instead of leaking corpse sockets into later
        # forwards — the zero-drop assertion above is the oracle that
        # retirement preserved the re-submit-once semantics.
        assert st["pool"]["reused"] > st["pool"]["opened"], st["pool"]
        assert st["pool"]["reuse_ratio"] > 0.5, st["pool"]
    finally:
        if srv is not None:
            srv.shutdown()
        manager.stop()
        obs.close_run()
        faults.uninstall()
    # --- post-hoc: roster timeline, zero fresh compiles on rejoin ----------
    events, bad = load_events(run_dir)
    assert bad == 0
    losses = [e for e in events if e["ev"] == "fleet_replica_loss"]
    readies = [e for e in events if e["ev"] == "fleet_replica_ready"]
    assert losses, "no fleet_replica_loss event"
    t_loss = losses[0]["t"]
    # 2 initial readies + the rejoin (all loss victims eventually ready).
    assert len(readies) >= 3
    assert any(e["t"] > t_loss for e in readies)
    # Zero fresh compiles after the loss: the respawned replica warms
    # every bucket from the fleet-shared exec cache (cache_hit events),
    # never the XLA compiler.
    compiles_after = [e for e in events
                     if e["ev"] == "program_compile" and e["t"] > t_loss]
    assert not compiles_after, compiles_after
    assert [e for e in events
            if e["ev"] == "cache_hit" and e["t"] > t_loss]
    # Scale verdicts were advisory events, not load-bearing.
    assert [e for e in events if e["ev"] == "fleet_scale"]
    # The channel lifecycle is in the stream: opens with their
    # connect_ms, reuses, and the kill's retirements (broken and/or
    # replica_loss/probe_failure — the loss was discovered somewhere).
    assert [e for e in events if e["ev"] == "conn_open"]
    assert [e for e in events if e["ev"] == "conn_reuse"]
    retire_reasons = {e["reason"] for e in events
                      if e["ev"] == "conn_retire"}
    assert retire_reasons & {"broken", "replica_loss", "probe_failure"}, \
        retire_reasons
    # The roster file is the elastic schema, final state = full strength.
    m = read_membership(run_dir)
    assert m is not None and m.members == (0, 1)
    assert m.reason == "replica_rejoin"
    # The report folds it all: fleet section + mesh-style timeline.
    rep = build_report(events)
    assert rep["fleet"]["losses"] >= 1
    assert rep["fleet"]["resubmits"] >= 1
    assert rep["fleet"]["dropped"] == 0
    assert any(e["event"] == "fleet_replica_loss"
               for e in rep["fleet"]["timeline"])
    text = format_report(rep)
    assert "fleet:" in text and "scale verdicts" in text


def test_fleet_e2e_burn_rate_scrape_alert_and_dash(
    fleet_ckpt, tmp_path, capsys
):
    """ISSUE 16 acceptance: a real 2-replica CPU fleet with
    ``replica_slow`` injected on one replica — the scraper populates the
    run_dir time-series store from all three /metrics endpoints, the
    burn-rate SLO fires during the slowdown and resolves after recovery,
    ``fleet_scale`` flips to ``add`` on sustained burn and ``hold``
    after, and the dashboard + report fleet timeline render from the
    store ALONE once every serving process has exited.

    ISSUE 20 rides the same fleet: the router owns an incident manager
    (``run_dir=``), so the burn fire opens exactly ONE flap-damped
    incident whose bundle carries the tsdb slice + events tail + folded
    thread stacks; the replicas own their own managers (low in-process
    SLO), so a replica-side bundle's stacks name the ``serve-batcher``
    dispatcher thread; resolve closes the burn incident with a real
    duration; and ``cli incident list/show`` render post-mortems from
    the bundle directory alone after every serving process exited."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv
    from featurenet_tpu.fleet.scraper import ROUTER_TARGET, MetricsScraper
    from featurenet_tpu.obs import alerts as _alerts
    from featurenet_tpu.obs import tsdb as _tsdb
    from featurenet_tpu.obs.dash import render_frame
    from featurenet_tpu.obs.report import build_report_dir

    run_dir = str(tmp_path / "run")
    cache_dir = str(tmp_path / "exec_cache")
    obs.init_run(run_dir, process_index=0,
                 extra={"cmd": "fleet-e2e-burn"})
    # The chaos arm rides the CHILD argv: slot 1 sleeps 250 ms on every
    # forward. Mutable so the recovery respawn comes up clean.
    fault_for = {1: "replica_slow@request=1:every=1"}

    def spawn(slot, hb):
        # slo_p99_ms=100 sits under the injected 250 ms forwards: the
        # slow replica's own threshold alert fires IN-PROCESS, so its
        # incident manager captures that process's stacks — the bundle
        # that can name the serve-batcher dispatcher thread.
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=cache_dir, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64, slo_p99_ms=100.0,
            inject_faults=fault_for.get(slot),
        )

    store = _tsdb.TimeSeriesStore.open(run_dir)
    # Tight windows so the e2e exercises the real multi-window shape in
    # seconds. The 200 ms objective sits between the fleet's clean p99
    # under light CPU load (~tens of ms) and the injected 250 ms
    # forwards; the fast window proves "now", the slow "sustained".
    rule = _alerts.BurnRateRule("serving_p99_ms", "<", 200.0, 0.99,
                                "critical", fast_s=5.0, slow_s=120.0)
    manager = ReplicaManager(2, spawn, run_dir)
    # slo_p99_ms=2000 keeps the THRESHOLD alerts (and the drain gate)
    # out of the story — this test is about the burn layer.
    router = FleetRouter(manager, slo_p99_ms=2000.0,
                         scale_every_s=3600.0, store=store, slos=[rule],
                         run_dir=run_dir)
    srv = None
    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        scraper = MetricsScraper(
            store, manager.pool,
            lambda: {
                **{str(s): p
                   for s, p in manager.stats()["ports"].items()},
                ROUTER_TARGET: port,
            },
        )
        router._scale_tick()  # baseline verdict, cold windows
        grids = generate_batch(
            np.random.default_rng(0), 16, RES
        )["voxels"]
        # --- slowdown: load + scrape until the verdict flips to add ---
        t_end = time.monotonic() + 240
        while router._last_verdict != "add":
            assert time.monotonic() < t_end, (
                router.scale_state(), scraper.stats())
            stats, _ = http_load("127.0.0.1", port, qps=80.0,
                                 n_requests=24, grids=grids)
            assert stats["dropped"] == 0, stats
            scraper.scrape_once()
            router._scale_tick()
        st_scale = router.scale_state()
        assert st_scale["burn_fast"] > 1.0, st_scale
        assert st_scale["burn_slow"] > 1.0, st_scale
        assert router._burn.active_alerts() == ["serving_p99_ms"]
        # Before recycling the slow replica (a SIGKILL — no drain, so no
        # capture-thread join), wait for ITS incident plane to finish a
        # bundle: the in-process threshold rule only evaluates on the
        # window-emit cadence inside observe() and the stack capture
        # itself takes ~2 s, so on a loaded CI host the kill could land
        # mid-capture and tear the one bundle whose folded stacks this
        # test's post-hoc assertions need. No scrapes here: the burn
        # layer's store state must not move while we wait.
        from featurenet_tpu.obs import incidents as _incidents
        from featurenet_tpu.obs import stacksampler as _stacksampler

        def _replica_stacks_ready():
            for b in _incidents.list_incidents(run_dir):
                lb = _incidents.load_bundle(run_dir, b["id"])
                if lb["stacks"] and "serve-batcher" in \
                        _stacksampler.thread_totals(lb["stacks"]):
                    return True
            return False

        t_cap = time.monotonic() + 180
        while not _replica_stacks_ready():
            assert time.monotonic() < t_cap, (
                "no replica bundle with serve-batcher stacks before "
                f"recycle: {_incidents.list_incidents(run_dir)}")
            # A trickle keeps the slow replica's windows emitting (the
            # threshold rule never evaluates on an idle service).
            stats, _ = http_load("127.0.0.1", port, qps=20.0,
                                 n_requests=8, grids=grids)
            assert stats["dropped"] == 0, stats
        # --- recovery: clear the fault, recycle the slow replica ------
        del fault_for[1]
        assert manager.kill_one() == 1  # highest live slot = the slow one
        t_rejoin = time.monotonic() + 300
        while manager.ready_count() < 2:
            assert time.monotonic() < t_rejoin, \
                f"rejoin timed out: {manager.stats()}"
            time.sleep(0.25)
        # Flush the router's 128-sample serving window with fast
        # traffic, then collect clean rounds: the fast window drains,
        # the slow window still remembers — resolve + hold, not shed.
        # Gentle but long: enough requests to flush every 128-sample
        # window past warmup/slowdown residue, at a rate the CPU fleet
        # serves WITHIN the objective (a hammering burst would queue its
        # way over the threshold and look like the outage it is
        # flushing).
        stats, _ = http_load("127.0.0.1", port, qps=40.0,
                             n_requests=300, grids=grids)
        assert stats["dropped"] == 0, stats
        # Let the slowdown-era scrapes age out of the FAST window (the
        # whole injection phase can fit inside it on a warm machine),
        # then collect rounds that read the now-clean gauges.
        time.sleep(rule.fast_s + 0.5)
        for _ in range(3):
            scraper.scrape_once()
            time.sleep(0.2)
        router._scale_tick()
        st_scale = router.scale_state()
        assert router._last_verdict == "hold", st_scale
        assert st_scale["burn_fast"] is not None
        assert st_scale["burn_fast"] < 1.0, st_scale
        assert router._burn.active_alerts() == []
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        st["scrape"] = scraper.stats()
        assert st["scrape"]["samples"] > 0
        assert not store.stats()["dark"], store.stats()
    finally:
        if srv is not None:
            srv.shutdown()
        manager.stop()
        store.close()
        obs.close_run()
    # --- post-hoc, from the run_dir alone -----------------------------------
    events, bad = load_events(run_dir)
    assert bad == 0
    burn_alerts = [e for e in events if e["ev"] == "alert"
                   and e["rule"] == "serving_p99_ms_burn"]
    assert [e["state"] for e in burn_alerts] == ["fire", "resolve"], \
        burn_alerts
    verdicts = [e["verdict"] for e in events
                if e["ev"] == "fleet_scale"]
    assert "add" in verdicts and verdicts[-1] == "hold", verdicts
    # The store outlived every serving process: all three endpoints'
    # series are on disk, p99 history included.
    reader = _tsdb.TimeSeriesStore.open(run_dir)
    scraped = {lb.get("replica") for _m, lb in reader.series()
               if lb.get("replica") is not None}
    assert {"0", "1", ROUTER_TARGET} <= scraped, scraped
    for target in ("0", "1", ROUTER_TARGET):
        assert reader.query("serving_ms",
                            {"q": "0.99", "replica": target}), target
        assert reader.query("scrape_duration_ms",
                            {"replica": target}), target
    # The dashboard renders from the store alone — module and CLI.
    frame = render_frame(run_dir)
    assert frame.splitlines()[0].startswith("fleet dash")
    assert "burn serving_p99_ms" in frame
    from featurenet_tpu.cli import main as cli_main

    cli_main(["dash", run_dir, "--once"])
    out = capsys.readouterr().out
    assert "3 target(s)" in out and "router" in out
    # And the report's fleet timeline, store-only too.
    rep = build_report_dir(run_dir)
    tl = rep.get("fleet_timeline")
    assert tl and ROUTER_TARGET in tl["targets"]
    assert tl["targets"]["1"]["samples"] > 0
    assert "fleet timeline" in format_report(rep)

    # --- ISSUE 20: the incident plane, from the bundle dirs alone -----------
    from featurenet_tpu.obs import incidents as _incidents
    from featurenet_tpu.obs import stacksampler as _stacksampler

    bundles = _incidents.list_incidents(run_dir)
    assert bundles, "the burn fire should have opened an incident"
    burn_b = [b for b in bundles if b.get("rule") == "serving_p99_ms_burn"]
    # Flap damping: one fire/resolve pair -> exactly ONE incident, with
    # the resolve closing it at a real duration.
    assert len(burn_b) == 1, bundles
    assert burn_b[0]["state"] == "closed", burn_b
    assert burn_b[0]["duration_s"] > 0, burn_b
    loaded = _incidents.load_bundle(run_dir, burn_b[0]["id"])
    assert loaded["missing"] == [], loaded["missing"]
    # The bundle is self-contained: a tsdb slice with real samples, the
    # (force-sampled) request timelines in the events tail, the roster,
    # and folded stacks of the capturing process.
    slice_samples = sum(len(s["samples"])
                       for s in loaded["tsdb"]["series"])
    assert slice_samples > 0, loaded["tsdb"]
    tail_kinds = {r.get("ev") for r in loaded["events_tail"]}
    assert "request_done" in tail_kinds, sorted(tail_kinds)
    assert loaded["roster"] is not None
    assert loaded["stacks"], "folded stacks missing from the bundle"
    # The replica-side incident (in-process threshold SLO breach on the
    # slow replica) sampled ITS process: the batcher's dispatcher thread
    # is named in some bundle's folded stacks.
    all_threads: set = set()
    for b in bundles:
        lb = _incidents.load_bundle(run_dir, b["id"])
        if lb["stacks"]:
            all_threads |= set(_stacksampler.thread_totals(lb["stacks"]))
    assert "serve-batcher" in all_threads, sorted(all_threads)
    # The incident_open/close events joined the streams, and the report
    # folds them into its incidents section.
    assert rep["incidents"]["opened"] >= 1
    assert "serving_p99_ms_burn" in rep["incidents"]["by_rule"]
    assert "incidents:" in format_report(rep)
    # The dash line knows about them too.
    assert "incidents:" in render_frame(run_dir)
    # And the CLI renders the post-mortem from the bundle dir alone.
    cli_main(["incident", "list", run_dir])
    out = capsys.readouterr().out
    assert burn_b[0]["id"] in out
    cli_main(["incident", "show", run_dir, burn_b[0]["id"]])
    out = capsys.readouterr().out
    assert burn_b[0]["id"] in out
    assert "tsdb slice" in out and "stacks:" in out
    assert "missing:" not in out


# --- ISSUE 18: the acting autoscaler (unit) ----------------------------------

class _ScaleManagerFake:
    """The two levers the Autoscaler pulls, scripted: counts calls,
    optionally refuses to shed (the manager's last-replica guard)."""

    def __init__(self, n: int = 2, shed_refuses: bool = False):
        self.n = n
        self.calls: list = []
        self.shed_refuses = shed_refuses

    def add_one(self):
        self.calls.append("add")
        self.n += 1
        return self.n - 1

    def shed_one(self, drain_wait_s: float = 10.0):
        if self.shed_refuses:
            return None
        self.calls.append("shed")
        self.n -= 1
        return self.n


def _scale_st(verdict, bf=2.0, bs=1.5, qd=0.0):
    return {"verdict": verdict, "burn_fast": bf, "burn_slow": bs,
            "queue_depth": qd, "replicas": 2}


def test_autoscaler_validation():
    m = _ScaleManagerFake()
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(m, lambda: {}, min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        Autoscaler(m, lambda: {}, min_replicas=4, max_replicas=3)


def test_autoscaler_hysteresis_and_honest_hold():
    # Hysteresis: two adds + an interruption never act; three in a row
    # do, exactly once, with the sustained reason on the record.
    m = _ScaleManagerFake(n=2)
    a = Autoscaler(m, lambda: {}, min_replicas=1, max_replicas=8,
                   hysteresis=3, cooldown_s=0.0)
    assert a.step(_scale_st("add"), 0.0) is None
    assert a.step(_scale_st("add"), 1.0) is None
    assert a.step(_scale_st("hold"), 2.0) is None  # streak broken
    assert a.step(_scale_st("add"), 3.0) is None
    assert a.step(_scale_st("add"), 4.0) is None
    act = a.step(_scale_st("add"), 5.0)
    assert act is not None
    assert (act["action"], act["from_n"], act["to_n"]) == ("add", 2, 3)
    assert act["reason"].startswith("sustained_add(")
    assert m.calls == ["add"] and a.actions == 1
    # Honest hold: a shed verdict with BOTH burns None is missing
    # telemetry, not idle capacity — it never acts, however sustained.
    m2 = _ScaleManagerFake(n=3)
    a2 = Autoscaler(m2, lambda: {}, min_replicas=1, max_replicas=8,
                    hysteresis=1, cooldown_s=0.0)
    for t in range(5):
        assert a2.step(_scale_st("shed", bf=None, bs=None), float(t)) \
            is None
    assert m2.calls == []
    assert a2.stats()["streak_verdict"] == "hold"
    # ...while a shed with real burn data stands...
    act = a2.step(_scale_st("shed", bf=0.02, bs=0.01), 6.0)
    assert act is not None and act["action"] == "shed"
    assert m2.calls == ["shed"]
    # ...a naked add (no burns, nothing queued — the cold fleet
    # mid-warmup shape) is equally held: absence of capacity is not
    # evidence of demand...
    m3 = _ScaleManagerFake(n=2)
    a3 = Autoscaler(m3, lambda: {}, min_replicas=1, max_replicas=8,
                    hysteresis=1, cooldown_s=0.0)
    for t in range(5):
        assert a3.step(_scale_st("add", bf=None, bs=None, qd=0.0),
                       float(t)) is None
    assert m3.calls == []
    # ...but a burn-less ADD backed by a deep queue stands (queued work
    # is direct observation, not absence).
    m4 = _ScaleManagerFake(n=1)
    a4 = Autoscaler(m4, lambda: {}, min_replicas=1, max_replicas=8,
                    hysteresis=2, cooldown_s=0.0)
    assert a4.step(_scale_st("add", bf=None, bs=None, qd=20.0), 0.0) \
        is None
    act = a4.step(_scale_st("add", bf=None, bs=None, qd=20.0), 1.0)
    assert act is not None and act["action"] == "add"


def test_autoscaler_cooldown_elapses_since_last_action_not_verdict():
    """The flap fix: an oscillating verdict (add, hold, add, hold, ...)
    re-arms a change-based cooldown on every rising edge and thrashes;
    the cooldown must run from the last ACTION. At hysteresis=1 and a
    30 s cooldown over 70 oscillating 1 s ticks, a correct clock fires
    at exactly t=0, 30, 60."""
    m = _ScaleManagerFake(n=2)
    a = Autoscaler(m, lambda: {}, min_replicas=1, max_replicas=99,
                   hysteresis=1, cooldown_s=30.0)
    fired = []
    for t in range(70):
        verdict = "add" if t % 2 == 0 else "hold"
        if a.step(_scale_st(verdict), float(t)) is not None:
            fired.append(t)
    assert fired == [0, 30, 60], fired
    assert m.calls == ["add", "add", "add"]
    assert a.actions == 3


def test_autoscaler_bounds_and_manager_refusal_do_not_arm_cooldown():
    # At the bounds the verdict is refused silently: no lever pulled,
    # no event, and — critically — no cooldown armed.
    m = _ScaleManagerFake(n=3)
    a = Autoscaler(m, lambda: {}, min_replicas=3, max_replicas=3,
                   hysteresis=1, cooldown_s=1000.0)
    assert a.step(_scale_st("add"), 0.0) is None
    assert a.step(_scale_st("shed", bf=0.02, bs=0.01), 1.0) is None
    assert m.calls == [] and a.actions == 0
    # A manager-side shed refusal (None) is equally not an action: the
    # very next sustained add fires despite the huge cooldown.
    m2 = _ScaleManagerFake(n=2, shed_refuses=True)
    a2 = Autoscaler(m2, lambda: {}, min_replicas=1, max_replicas=4,
                    hysteresis=1, cooldown_s=1000.0)
    assert a2.step(_scale_st("shed", bf=0.02, bs=0.01), 0.0) is None
    assert m2.calls == [] and a2.actions == 0
    act = a2.step(_scale_st("add"), 1.0)
    assert act is not None and act["action"] == "add"
    assert a2.actions == 1
    # ...and a TAKEN action does arm it.
    assert a2.step(_scale_st("add"), 2.0) is None


def test_manager_shed_parks_and_add_revives(tmp_path):
    """The roster levers without a fleet: ``shed_one`` parks the highest
    ready slot (roster written as ``scale_down``, no loss charged, the
    tick loop leaves it alone), a second shed refuses to take the last
    replica, ``add_one`` revives the parked slot first and mints a
    fresh one after."""
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)

    def spawn(slot, hb):
        return [sys.executable, "-c", "import time; time.sleep(600)"]

    manager = ReplicaManager(2, spawn, run_dir)
    try:
        # Hand-spawn (no supervision thread: ports stay None, so no
        # probes run to fight the manual ready flags).
        for r in manager._replicas.values():
            manager._spawn(r)
            r.ready = True
        shed = manager.shed_one(drain_wait_s=0.1)
        assert shed == 1  # highest slot drains first
        st = manager.stats()
        assert st["replicas"] == 1 and st["parked"] == 1
        assert st["ready"] == 1 and st["losses"] == 0
        m = read_membership(run_dir)
        assert m is not None and m.members == (0,)
        assert m.reason == "scale_down"
        # The tick loop must NOT resurrect (or charge) a parked slot.
        manager._tick()
        assert manager._replicas[1].proc is None
        assert manager.stats()["losses"] == 0
        # Never below one replica: the manager's own floor.
        assert manager.shed_one(drain_wait_s=0.1) is None
        # Revival reuses the parked slot identity...
        assert manager.add_one() == 1
        st = manager.stats()
        assert st["replicas"] == 2 and st["parked"] == 0
        assert manager._replicas[1].proc is not None
        assert manager._replicas[1].ready is False  # must re-probe
        # ...and only a parked-free roster mints a new slot.
        assert manager.add_one() == 2
        assert manager.stats()["replicas"] == 3
        assert sorted(manager._replicas) == [0, 1, 2]
    finally:
        manager.stop()


# --- ISSUE 18: version tags on the wire (unit) -------------------------------

def _fake_metrics_target(text: str):
    """A scripted GET /metrics endpoint (exposition text, keep-alive)."""

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def do_GET(self):  # noqa: N802
            data = text.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def test_scraper_stamps_version_label_from_build_info(tmp_path):
    """Every series scraped from a target whose ``build_info`` carries a
    real ``model_version`` gets a ``version`` label that round; the
    router's ``n/a`` stamps nothing."""
    from featurenet_tpu.fleet.pool import ConnectionPool
    from featurenet_tpu.fleet.scraper import ROUTER_TARGET, MetricsScraper
    from featurenet_tpu.obs import tsdb as _tsdb

    replica_srv, replica_port = _fake_metrics_target(
        'featurenet_build_info{model_version="ckpt@6-aaaa1111",'
        'precision="fp32"} 1\n'
        "featurenet_serve_queue_depth 3\n"
    )
    router_srv, router_port = _fake_metrics_target(
        'featurenet_build_info{model_version="n/a",precision="n/a"} 1\n'
        "featurenet_serve_queue_depth 1\n"
    )
    store = _tsdb.TimeSeriesStore.open(str(tmp_path))
    pool = ConnectionPool()
    try:
        scraper = MetricsScraper(
            store, pool,
            lambda: {"0": replica_port, ROUTER_TARGET: router_port},
        )
        assert scraper.scrape_once() > 0
        depth = {lb["replica"]: lb for m, lb in store.series()
                 if m == "serve_queue_depth"}
        # Series labels come back filename-sanitized ("@" -> "_"): the
        # label is the series identity on disk.
        assert depth["0"].get("version") == "ckpt_6-aaaa1111", depth
        assert "version" not in depth[ROUTER_TARGET], depth
    finally:
        pool.close()
        store.close()
        replica_srv.shutdown()
        router_srv.shutdown()


def test_admin_reload_endpoint_contract():
    """The HTTP shape of the hot-swap endpoint, against a stub service:
    400 on garbage, 409 ``swap_refused`` naming the refusal kind and
    the STILL-SERVING version, 200 with the new identity — every body
    stamped with the replica id."""
    from featurenet_tpu.serve.http import make_server

    class _StubPredictor:
        model_version = "old@1-aaaa1111"

    class _StubService:
        predictor = _StubPredictor()
        replica = 7

        def reload(self, checkpoint_dir):
            if "corrupt" in checkpoint_dir:
                raise ValueError("injected: candidate fails verify")
            return {"ok": True, "model_version": "new@2-bbbb2222",
                    "from_version": "old@1-aaaa1111", "swap_ms": 12.5}

    srv = make_server(_StubService(), "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def post(data: bytes):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/admin/reload", data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        status, doc = post(b"{nope")
        assert status == 400 and doc["error"] == "bad_json"
        status, doc = post(json.dumps({"other": 1}).encode())
        assert status == 400 and doc["error"] == "bad_reload"
        status, doc = post(
            json.dumps({"checkpoint_dir": "/tmp/corrupt"}).encode()
        )
        assert status == 409, doc
        assert doc["error"] == "swap_refused"
        assert doc["kind"] == "ValueError"
        assert doc["model_version"] == "old@1-aaaa1111"
        assert doc["replica"] == 7
        status, doc = post(
            json.dumps({"checkpoint_dir": "/tmp/good"}).encode()
        )
        assert status == 200, doc
        assert doc["ok"] is True
        assert doc["model_version"] == "new@2-bbbb2222"
        assert doc["replica"] == 7
    finally:
        srv.shutdown()


def test_swap_params_flips_version_and_keeps_predictions(
    fleet_ckpt, tmp_path
):
    """The live double-buffer: ``swap_params`` to a checkpoint COPY
    flips ``model_version``/``checkpoint_dir`` (new deploy identity,
    same content hash), predictions are bit-identical (same weights),
    and a failed swap leaves the serving generation untouched."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.infer import Predictor

    cand = str(tmp_path / "cand")
    shutil.copytree(fleet_ckpt, cand)
    pred = Predictor.from_checkpoint(fleet_ckpt, batch=8)
    v1 = pred.model_version
    assert v1.startswith(os.path.basename(fleet_ckpt) + "@")
    grids = generate_batch(np.random.default_rng(3), 8, RES)["voxels"]
    labels1, probs1 = pred.predict_voxels(grids)
    v2 = pred.swap_params(cand)
    assert pred.model_version == v2
    assert v2 != v1 and v2.startswith("cand@")
    # A copy is a new deploy of the same content: only the basename half
    # of <name>@<step>-<sha8> may differ.
    assert v2.split("@", 1)[1] == v1.split("@", 1)[1], (v1, v2)
    assert pred.checkpoint_dir == cand  # what a rollback re-submits
    labels2, probs2 = pred.predict_voxels(grids)
    assert np.array_equal(np.asarray(labels1), np.asarray(labels2))
    assert np.allclose(np.asarray(probs1), np.asarray(probs2))
    # A swap that cannot restore raises BEFORE the flip: still v2.
    with pytest.raises(Exception):
        pred.swap_params(str(tmp_path / "missing"))
    assert pred.model_version == v2


# --- ISSUE 18: registry + trend-gate wiring ----------------------------------

def test_rollout_registry_and_trend_gate_wiring(tmp_path):
    from featurenet_tpu.obs import bench_history as _bh
    from featurenet_tpu.obs import gates as _gates
    from featurenet_tpu.obs.report import (
        KNOWN_EVENT_KINDS,
        REQUIRED_EVENT_FIELDS,
    )

    # The two new chaos sites ride the swap counter (mirrors the
    # test_slo pin pattern; the fault-sites lint derives from SITES, so
    # both directions are auto-covered there).
    assert faults.SITES["swap_corrupt"] == "swap"
    assert faults.SITES["replica_loss_rollout"] == "swap"
    parsed = faults.parse_spec("swap_corrupt@swap=2,replica_loss_rollout")
    assert parsed["swap_corrupt"] == ("swap", 2)
    assert parsed["replica_loss_rollout"] is None
    # Event kinds + required fields: the report validates what the
    # control loop emits.
    assert {"fleet_autoscale", "swap", "rollout_start", "rollout_step",
            "rollout_rollback", "rollout_done"} <= KNOWN_EVENT_KINDS
    assert REQUIRED_EVENT_FIELDS["fleet_autoscale"] == \
        ("action", "from_n", "to_n", "reason")
    assert REQUIRED_EVENT_FIELDS["swap"] == \
        ("ok", "from_version", "swap_ms")
    assert REQUIRED_EVENT_FIELDS["rollout_start"] == \
        ("checkpoint_dir", "replicas")
    assert REQUIRED_EVENT_FIELDS["rollout_step"] == ("replica", "ok")
    assert REQUIRED_EVENT_FIELDS["rollout_rollback"] == \
        ("reason", "rolled_back")
    assert REQUIRED_EVENT_FIELDS["rollout_done"] == ("ok", "swapped")
    # bench-history columns + gate keys + slack + directions, one row
    # per new pin.
    for key in ("fleet_scale_actions", "rollout_swap_ms",
                "rollout_agreement"):
        assert key in _gates.BENCH_GATE_KEYS
        assert key in _gates.NOISY_KEY_ABS_SLACK
        assert any(col == key for col, _h, _f in _bh._COLUMNS)
    assert _gates.DIRECTIONS["fleet_scale_actions"] == "max"
    assert _gates.DIRECTIONS["rollout_swap_ms"] == "max"
    assert _gates.DIRECTIONS["rollout_agreement"] == "min"
    # The trend gate actually judges them: one borderline autoscale
    # action is legal (abs slack), a swap-wall blowout and an agreement
    # collapse are not.
    d = str(tmp_path)
    with open(os.path.join(d, "BENCH_r1.json"), "w") as fh:
        json.dump({"value": 1000.0, "fleet_scale_actions": 1.0,
                   "rollout_swap_ms": 3000.0,
                   "rollout_agreement": 1.0}, fh)
    with open(os.path.join(d, "BENCH_r2.json"), "w") as fh:
        json.dump({"value": 1000.0, "fleet_scale_actions": 2.0,
                   "rollout_swap_ms": 5600.0,
                   "rollout_agreement": 0.85}, fh)
    res = _bh.trend_gate(_bh.load_rounds(d))
    assert not res["ok"]
    assert set(res["failed"]) == {"rollout_swap_ms",
                                  "rollout_agreement"}, res


# --- ISSUE 18: the chaos-gate e2es -------------------------------------------

@pytest.fixture(scope="module")
def fleet_cache(tmp_path_factory):
    """One exec cache shared by the ISSUE-18 e2es: the first fleet pays
    the XLA compiles, every later replica (and every respawn) warms
    from disk."""
    return str(tmp_path_factory.mktemp("fleet_cache"))


def _run_rollout(run_dir: str, ckpt: str, timeout_s: float = 600.0):
    """``cli fleet rollout`` as a REAL subprocess (the orchestrator owns
    its own obs stream; in-process it would steal the test's) + the
    parsed one-line JSON verdict off its stdout."""
    proc = subprocess.run(
        [sys.executable, "-m", "featurenet_tpu.cli", "fleet", "rollout",
         ckpt, "--run-dir", run_dir, "--batch", "16",
         "--converge-timeout-s", "240"],
        capture_output=True, text=True, timeout=timeout_s,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    doc = None
    for line in proc.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and "fleet_rollout" in d:
            doc = d["fleet_rollout"]
    return proc, doc


def test_fleet_e2e_autoscale_add_on_load_ramp(
    fleet_ckpt, fleet_cache, tmp_path
):
    """ISSUE 18 chaos gate (load ramp): a 2-replica CPU fleet, one
    replica dragging (``replica_slow``: the contended-host shape), hit
    with a 4x open-loop traffic step — the acting autoscaler turns the
    router's sustained burn verdict into a REAL third replica, nothing
    admitted is dropped through the ramp or the spawn, and the scaled
    fleet holds the p99 pin under the settled rate."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv
    from featurenet_tpu.obs import alerts as _alerts

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0,
                 extra={"cmd": "fleet-e2e-autoscale"})
    # Replica 1 drags SLOW_SLEEP_S on every forward: the deterministic
    # under-capacity shape (whether THIS box absorbs 4x clean is a
    # hardware lottery; a dragging replica under a 4x step is not).
    fault_for = {1: "replica_slow@request=1:every=1"}

    def spawn(slot, hb):
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=fleet_cache, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64, inject_faults=fault_for.get(slot),
        )

    # Store-less burn: the router's own serving_ms ring feeds the same
    # burn math the tsdb path runs. The 200 ms / 95% objective sits
    # between the fleet's clean walls (tens of ms) and the dragged
    # forward (SLOW_SLEEP_S = 250 ms). slo_p99_ms=5000 keeps the
    # threshold alerts (and the drain gate) out of the story.
    rule = _alerts.BurnRateRule("serving_p99_ms", "<", 200.0, 0.95,
                                "critical", fast_s=5.0, slow_s=45.0)
    manager = ReplicaManager(2, spawn, run_dir)
    router = FleetRouter(manager, slo_p99_ms=5000.0, scale_every_s=0.5,
                         slos=[rule])
    autoscaler = Autoscaler(manager, router.scale_state,
                            min_replicas=2, max_replicas=3,
                            hysteresis=2, cooldown_s=120.0,
                            interval_s=0.25)
    srv = None
    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        obs.emit("fleet_start", replicas=2, host="127.0.0.1", port=port)
        grids = generate_batch(
            np.random.default_rng(0), 16, RES
        )["voxels"]
        # --- base rate: a 2-replica fleet absorbs it, zero drops ------
        # (no-action-on-clean-verdicts discipline is unit-pinned above;
        # the autoscaler arms at the step so the burn it acts on is the
        # step's, not the warmup transient's)
        stats, _ = http_load("127.0.0.1", port, qps=20.0,
                             n_requests=60, grids=grids)
        assert stats["dropped"] == 0, stats
        assert manager.stats()["replicas"] == 2, manager.stats()
        # --- the 4x step: hammer until the sustained add lands --------
        autoscaler.start()
        t_end = time.monotonic() + 240
        while manager.stats()["replicas"] < 3:
            assert time.monotonic() < t_end, (
                router.scale_state(), autoscaler.stats())
            stats, _ = http_load("127.0.0.1", port, qps=80.0,
                                 n_requests=48, grids=grids)
            assert stats["dropped"] == 0, stats
        t_ready = time.monotonic() + 300
        while manager.ready_count() < 3:
            assert time.monotonic() < t_ready, \
                f"scale-out warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        # Exactly one action: the cooldown + max_replicas bound pin the
        # roster through the rest of the ramp.
        assert autoscaler.actions == 1, autoscaler.stats()
        autoscaler.stop()  # freeze the roster for the settle asserts
        # --- settled: the 3-replica fleet under the base rate ---------
        stats, _ = http_load("127.0.0.1", port, qps=30.0,
                             n_requests=120, grids=grids)
        assert stats["dropped"] == 0, stats
        assert stats["answered"] >= 100, stats
        assert stats["p99_ms"] is not None and stats["p99_ms"] < 2000.0, \
            stats
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        assert st["dropped"] == 0
    finally:
        if srv is not None:
            srv.shutdown()
        autoscaler.stop()
        manager.stop()
        obs.close_run()
    # --- post-hoc: the action is on the record, the replica is real --------
    events, bad = load_events(run_dir)
    assert bad == 0
    acts = [e for e in events if e["ev"] == "fleet_autoscale"]
    assert len(acts) == 1, acts
    assert acts[0]["action"] == "add"
    assert (acts[0]["from_n"], acts[0]["to_n"]) == (2, 3)
    assert acts[0]["reason"].startswith("sustained_add(")
    readies = [e for e in events if e["ev"] == "fleet_replica_ready"]
    assert any(e["replica"] == 2 for e in readies), readies
    m = read_membership(run_dir)
    assert m is not None and 2 in m.members
    rep = build_report(events)
    assert rep["fleet"]["autoscale_actions"] == {"add": 1}
    assert any(e["event"] == "fleet_autoscale"
               for e in rep["fleet"]["timeline"])
    assert "fleet:" in format_report(rep)


def test_fleet_e2e_rollout_canary_swap_then_corrupt_rollback(
    fleet_ckpt, fleet_cache, tmp_path
):
    """ISSUE 18 acceptance (rollout): ``cli fleet rollout`` hot-swaps a
    LIVE 2-replica fleet to a checkpoint copy one replica at a time —
    replay-canaried against each replica's own capture ring, zero
    admitted drops while each replica cordons and drains through the
    router's spillover path, zero post-warmup compiles in the swapped
    replicas, model_version threaded through /healthz and the scraped
    store (mixed-version window observable; converged after) — then a
    SECOND rollout whose candidate arrives checksum-corrupt on replica
    1 rolls the already-swapped replica 0 back and exits 2,
    re-converging the fleet on the serving generation."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv
    from featurenet_tpu.fleet.scraper import ROUTER_TARGET, MetricsScraper
    from featurenet_tpu.obs import tsdb as _tsdb

    run_dir = str(tmp_path / "run")
    cand = str(tmp_path / "cand")
    cand3 = str(tmp_path / "cand3")
    shutil.copytree(fleet_ckpt, cand)
    shutil.copytree(fleet_ckpt, cand3)
    obs.init_run(run_dir, process_index=0,
                 extra={"cmd": "fleet-e2e-rollout"})
    # Slot 1's SECOND reload arrives checksum-broken: rollout 1 is swap
    # #1 everywhere (clean), so the fault fires during rollout 2 AFTER
    # slot 0 already swapped — forcing the rollback path. Slot 0
    # carries no spec, so its own rollback swap cannot trip.
    fault_for = {1: "swap_corrupt@swap=2"}

    def spawn(slot, hb):
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=fleet_cache, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64, capture=True, capture_sample=1.0,
            inject_faults=fault_for.get(slot),
        )

    store = _tsdb.TimeSeriesStore.open(run_dir)
    manager = ReplicaManager(2, spawn, run_dir)
    router = FleetRouter(manager, slo_p99_ms=5000.0,
                         scale_every_s=3600.0)
    srv = None
    port = None

    def _healthz():
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as resp:
            return json.loads(resp.read())

    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        obs.emit("fleet_start", replicas=2, host="127.0.0.1", port=port)
        scraper = MetricsScraper(
            store, manager.pool,
            lambda: {
                **{str(s): p
                   for s, p in manager.stats()["ports"].items()},
                ROUTER_TARGET: port,
            },
        )
        grids = generate_batch(
            np.random.default_rng(0), 16, RES
        )["voxels"]
        # Fill both capture rings (capture_sample=1.0 records every
        # answered request) and scrape the v1 world into the store.
        stats, _ = http_load("127.0.0.1", port, qps=40.0,
                             n_requests=80, grids=grids)
        assert stats["dropped"] == 0, stats
        scraper.scrape_once()
        versions0 = _healthz().get("versions") or {}
        assert set(versions0) == {"0", "1"}, versions0
        assert len(set(versions0.values())) == 1, versions0
        v1 = versions0["0"]
        assert v1.startswith(os.path.basename(fleet_ckpt) + "@"), v1
        for slot in (0, 1):
            ring = os.path.join(run_dir, "capture", f"replica{slot}")
            assert os.path.isdir(ring) and os.listdir(ring), \
                f"no capture ring for replica {slot}"
        # --- rollout 1: rolling swap under live load, watchers on -----
        snapshots: list = []
        load_stats: list = []
        stop_bg = threading.Event()

        def _poll():
            while not stop_bg.is_set():
                try:
                    snapshots.append(dict(
                        _healthz().get("versions") or {}
                    ))
                    scraper.scrape_once()
                except Exception:
                    pass  # one blipped poll must not kill the watcher
                stop_bg.wait(0.2)

        def _pump():
            while not stop_bg.is_set():
                s, _o = http_load("127.0.0.1", port, qps=20.0,
                                  n_requests=20, grids=grids)
                load_stats.append(s)

        watchers = [threading.Thread(target=_poll, daemon=True),
                    threading.Thread(target=_pump, daemon=True)]
        for t in watchers:
            t.start()
        proc, doc = _run_rollout(run_dir, cand)
        stop_bg.set()
        for t in watchers:
            t.join(timeout=120)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert doc is not None and doc["ok"] is True, proc.stdout
        assert doc["swapped"] == [0, 1]
        assert doc["converged"] is True
        v2 = doc["version"]
        assert v2.startswith("cand@") and v2 != v1, (v1, v2)
        # A copy is a new deploy of the same content: the step-hash half
        # of the tag is shared, only the deploy name moved.
        assert v2.split("@", 1)[1] == v1.split("@", 1)[1], (v1, v2)
        steps = {s["replica"]: s for s in doc["steps"]}
        for slot in (0, 1):
            assert steps[slot]["canary_n"] > 0, steps
            assert steps[slot]["agreement"] >= 0.967, steps
            assert steps[slot]["swap_ms"] > 0, steps
            assert steps[slot]["model_version"] == v2, steps
        # ZERO admitted drops while each replica cordoned + drained.
        assert load_stats, "load pump never completed a burst"
        assert all(s["dropped"] == 0 for s in load_stats), load_stats
        # The mixed-version window was OBSERVABLE at the router: some
        # /healthz snapshot saw both generations side by side...
        assert any(len(set(s.values())) == 2 for s in snapshots), \
            snapshots
        # ...and it CLOSED: one version everywhere now.
        assert set((_healthz().get("versions") or {}).values()) == {v2}
        # Post-swap traffic serves the new generation with ZERO fresh
        # compiles in the replica processes: the AOT programs take the
        # weights as arguments, so the flip touched no executable.
        stats, _ = http_load("127.0.0.1", port, qps=40.0,
                             n_requests=60, grids=grids)
        assert stats["dropped"] == 0, stats
        scraper.scrape_once()
        events_mid, _bad = load_events(run_dir)
        swaps_ok = [e for e in events_mid
                    if e["ev"] == "swap" and e.get("ok")]
        assert len(swaps_ok) >= 2, swaps_ok
        t_first_swap = min(e["t"] for e in swaps_ok)
        replica_pids = {e["pid"] for e in swaps_ok}
        late = [e for e in events_mid
                if e["ev"] == "program_compile"
                and e.get("pid") in replica_pids
                and e["t"] > t_first_swap]
        assert not late, late
        # One passing replay-canary verdict per replica, zero
        # post-warmup compiles on the scoring path either.
        rvs = [e for e in events_mid if e["ev"] == "replay_verdict"
               and e.get("replica") is not None]
        assert {e["replica"] for e in rvs} == {0, 1}, rvs
        for e in rvs:
            assert e["ok"] and e["agreement"] >= e["min_agreement"], e
            assert e["post_warmup_compiles"] == 0, e
        # The store carries the version label on every replica series —
        # BOTH generations per replica (the before/after evidence) —
        # and none on the router's own ("n/a" is not a version). Labels
        # read back filename-sanitized ("@" -> "_").
        seen: dict = {}
        for _m, lb in store.series():
            r = lb.get("replica")
            if r is not None and lb.get("version"):
                seen.setdefault(r, set()).add(lb["version"])
        want = {v1.replace("@", "_"), v2.replace("@", "_")}
        assert want <= seen.get("0", set()), seen
        assert want <= seen.get("1", set()), seen
        assert ROUTER_TARGET not in seen, seen
        # --- rollout 2: candidate refused mid-roll -> rollback, exit 2
        proc, doc = _run_rollout(run_dir, cand3)
        assert proc.returncode == 2, (proc.stdout, proc.stderr)
        assert doc is not None and doc["ok"] is False, proc.stdout
        assert "swap_refused(replica=1,kind=ChecksumMismatch)" \
            in doc["reason"], doc
        assert doc["rolled_back"] == [0], doc
        assert doc["rollback_failed"] == [], doc
        assert doc["converged"] is True, doc
        assert set((_healthz().get("versions") or {}).values()) == {v2}
        stats, _ = http_load("127.0.0.1", port, qps=40.0,
                             n_requests=40, grids=grids)
        assert stats["dropped"] == 0, stats
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        assert st["dropped"] == 0
    finally:
        if srv is not None:
            srv.shutdown()
        manager.stop()
        store.close()
        obs.close_run()
    # --- post-hoc: the rollout arc in the stream and the report -------------
    events, bad = load_events(run_dir)
    assert bad == 0
    starts = [e for e in events if e["ev"] == "rollout_start"]
    assert len(starts) == 2
    assert all(e["replicas"] == [0, 1] for e in starts), starts
    rollbacks = [e for e in events if e["ev"] == "rollout_rollback"]
    assert len(rollbacks) == 1, rollbacks
    assert rollbacks[0]["rolled_back"] == [0]
    assert "swap_refused" in rollbacks[0]["reason"]
    dones = [e for e in events if e["ev"] == "rollout_done"]
    assert [bool(e["ok"]) for e in dones] == [True, False], dones
    refused = [e for e in events
               if e["ev"] == "swap" and not e.get("ok")]
    assert len(refused) == 1, refused
    assert "swap_corrupt" in str(refused[0].get("error")), refused
    rep = build_report(events)
    ro = rep["fleet"]["rollout"]
    assert ro["rollbacks"] == 1
    assert ro["swaps_refused"] == 1
    assert ro["swaps_ok"] >= 4, ro  # 2 roll + 1 cand3 + 1 rollback
    assert ro["ok"] is False  # the LAST arc on record is the refusal
    tl = {e["event"] for e in rep["fleet"]["timeline"]}
    assert {"swap", "rollout_start", "rollout_step",
            "rollout_rollback", "rollout_done"} <= tl, tl
    assert "fleet:" in format_report(rep)


def test_fleet_e2e_replica_death_mid_rollout_rolls_back(
    fleet_ckpt, fleet_cache, tmp_path
):
    """ISSUE 18 chaos gate (kill-during-rollout): a replica SIGKILLed
    by the ``replica_loss_rollout`` fault mid-swap — the orchestrator
    rolls the already-swapped replica back and exits 2, the manager
    respawns the victim on its ORIGINAL argv from the shared cache, and
    the fleet re-converges on ONE version: the old one."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.loadgen import http_load, replica_argv

    run_dir = str(tmp_path / "run")
    candk = str(tmp_path / "candk")
    shutil.copytree(fleet_ckpt, candk)
    obs.init_run(run_dir, process_index=0,
                 extra={"cmd": "fleet-e2e-kill-rollout"})
    # Slot 1 dies on its FIRST reload — which arrives after slot 0
    # (lower slot) already swapped, forcing the rollback. Mutable so
    # the respawn argv comes up clean.
    fault_for = {1: "replica_loss_rollout@swap=1"}

    def spawn(slot, hb):
        return replica_argv(
            fleet_ckpt, slot, hb, run_dir=run_dir,
            exec_cache_dir=fleet_cache, buckets="1,2", max_wait_ms=3.0,
            queue_limit=64, inject_faults=fault_for.get(slot),
        )

    manager = ReplicaManager(2, spawn, run_dir)
    router = FleetRouter(manager, slo_p99_ms=5000.0,
                         scale_every_s=3600.0)
    srv = None
    try:
        manager.start()
        deadline = time.monotonic() + 420
        while manager.ready_count() < 2:
            assert time.monotonic() < deadline, \
                f"fleet warmup timed out: {manager.stats()}"
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        obs.emit("fleet_start", replicas=2, host="127.0.0.1", port=port)
        versions = dict(manager.stats()["versions"])
        assert set(versions) == {0, 1}, versions
        old = versions[0]
        assert old.startswith(os.path.basename(fleet_ckpt) + "@")
        # The RUNNING replicas have the fault armed (it rode their
        # argv); clearing it now means the respawn comes up clean.
        del fault_for[1]
        proc, doc = _run_rollout(run_dir, candk)
        assert proc.returncode == 2, (proc.stdout, proc.stderr)
        assert doc is not None and doc["ok"] is False, proc.stdout
        assert doc["reason"] == "replica_lost(replica=1)", doc
        assert doc["rolled_back"] == [0], doc
        assert doc["converged"] is True, doc
        # The victim rejoins (old argv, shared cache) and the fleet
        # settles on ONE version — the old one, everywhere.
        t_rejoin = time.monotonic() + 300
        while manager.ready_count() < 2:
            assert time.monotonic() < t_rejoin, \
                f"rejoin timed out: {manager.stats()}"
            time.sleep(0.25)
        ms = manager.stats()
        assert ms["losses"] >= 1 and ms["rejoins"] >= 1, ms
        assert set(ms["versions"].values()) == {old}, ms
        grids = generate_batch(
            np.random.default_rng(1), 16, RES
        )["voxels"]
        stats, _ = http_load("127.0.0.1", port, qps=40.0,
                             n_requests=60, grids=grids)
        assert stats["dropped"] == 0, stats
        srv.shutdown()
        srv = None
        st = router.drain()
        assert st["exit_code"] == 0, st
        assert st["dropped"] == 0
    finally:
        if srv is not None:
            srv.shutdown()
        manager.stop()
        obs.close_run()
    events, bad = load_events(run_dir)
    assert bad == 0
    bad_steps = [e for e in events if e["ev"] == "rollout_step"
                 and not e.get("ok")]
    assert len(bad_steps) == 1 and bad_steps[0]["replica"] == 1, \
        bad_steps
    assert str(bad_steps[0].get("reason", "")).startswith(
        "replica_lost"
    )
    assert [e for e in events if e["ev"] == "fleet_replica_loss"
            and e.get("replica") == 1]
    rollbacks = [e for e in events if e["ev"] == "rollout_rollback"]
    assert len(rollbacks) == 1
    assert rollbacks[0]["rolled_back"] == [0]
    assert rollbacks[0]["reason"] == "replica_lost(replica=1)"
    dones = [e for e in events if e["ev"] == "rollout_done"]
    assert len(dones) == 1 and dones[0]["ok"] is False
    rep = build_report(events)
    assert rep["fleet"]["rollout"]["rollbacks"] == 1
    assert rep["fleet"]["rollout"]["ok"] is False


def test_spawn_and_loss_counters_exact_under_concurrent_threads(tmp_path):
    """Regression (concurrency lint): ``_spawn`` runs on both the tick
    thread (respawns) and the autoscaler thread (``add_one``), and
    ``_lose``'s failure/backoff bookkeeping is read by router and
    autoscaler threads — both now take ``_lock`` for their
    read-modify-writes, so the counters must come out exact."""
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)

    def spawn(slot, hb):
        return [sys.executable, "-c", "pass"]  # exits immediately

    n = 12
    manager = ReplicaManager(n, spawn, run_dir)
    try:
        replicas = list(manager._replicas.values())
        errs: list = []

        def spawn_some(rs):
            try:
                for r in rs:
                    manager._spawn(r)
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=spawn_some, args=(replicas[i::4],),
                             daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert errs == []
        assert manager.stats()["spawns"] == n

        def lose_some(rs):
            try:
                for r in rs:
                    manager._lose(r, "test_loss")
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=lose_some, args=(replicas[i::4],),
                             daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert errs == []
        st = manager.stats()
        assert st["losses"] == n
        # Per-replica bookkeeping landed too: one charged failure each,
        # with a respawn backoff scheduled from that count.
        assert all(r.failures == 1 and r.respawn_due > 0
                   for r in replicas)
    finally:
        manager.stop(timeout_s=10.0)
