"""Observability layer: event schema, span nesting/thread-safety, report
aggregation, and the end-to-end Trainer ``run_dir`` contract — plus the
advisor-r5 satellite fixes that rode along with the obs PR (explicit
dispatch-k honor, clean-stream recalibration, seg-OOD rotation controls,
recalibrate dropping a stale ``init_from``)."""

import json
import os
import threading

import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.config import get_config
from featurenet_tpu.obs.report import (
    build_report,
    build_report_dir,
    load_events,
)
from featurenet_tpu.obs.spans import chrome_trace
from featurenet_tpu.train import Trainer


@pytest.fixture(autouse=True)
def _isolated_sink():
    """Obs state is process-wide; no test may leak an active sink into the
    rest of the suite (every other test file runs without a run_dir and
    must stay on the zero-overhead null path)."""
    obs.close_run()
    yield
    obs.close_run()


def test_events_schema_roundtrip(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, config={"name": "unit", "task": "classify"})
    obs.gauge("prefetch_queue_depth", 3, worker=1)
    obs.emit("heartbeat", age_s=0.5)
    obs.warn("mesh_warning", "degraded", requested=8)
    obs.close_run()

    manifest = json.load(open(os.path.join(run_dir, "run.json")))
    assert manifest["config"]["name"] == "unit"
    assert "start_time" in manifest and "argv" in manifest
    assert "process_index" in manifest["jax"]

    events, bad = load_events(run_dir)
    assert bad == 0
    assert [e["ev"] for e in events] == [
        "run_start", "gauge", "heartbeat", "warning"
    ]
    for e in events:
        assert isinstance(e["t"], float)
    g = events[1]
    assert (g["name"], g["value"], g["worker"]) == (
        "prefetch_queue_depth", 3, 1
    )
    assert events[3]["msg"] == "degraded"

    # Re-init of the same dir appends (restart semantics) and keeps the
    # original manifest rather than rewriting it.
    start0 = manifest["start_unix"]
    obs.init_run(run_dir, config={"name": "other"})
    obs.close_run()
    manifest2 = json.load(open(os.path.join(run_dir, "run.json")))
    assert manifest2["start_unix"] == start0
    events2, _ = load_events(run_dir)
    assert sum(1 for e in events2 if e["ev"] == "run_start") == 2


def test_span_nesting_and_thread_safety(tmp_path):
    obs.init_run(str(tmp_path / "run"))
    with obs.span("outer", take=4):
        with obs.span("inner"):
            pass

    # Hammer the sink from 8 threads: every line must land whole (the
    # lock serializes writers) and each thread's parent tracking must be
    # independent (thread-local stacks).
    def worker(i):
        for j in range(50):
            with obs.span("t_outer", thread_no=i):
                with obs.span("t_inner", j=j):
                    pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    obs.close_run()

    events, bad = load_events(str(tmp_path / "run"))
    assert bad == 0
    spans = [e for e in events if e["ev"] == "span"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert len(by_name["t_outer"]) == 400
    assert len(by_name["t_inner"]) == 400
    assert by_name["inner"][0]["parent"] == "outer"
    assert by_name["outer"][0]["parent"] is None
    assert by_name["outer"][0]["take"] == 4
    assert all(s["parent"] == "t_outer" for s in by_name["t_inner"])
    assert all(s["dur_s"] >= 0 for s in spans)

    # Chrome export: one complete event per span, rebased to t=0.
    trace = chrome_trace(events)
    assert len(trace["traceEvents"]) == len(spans)
    assert all(ev["ph"] == "X" and ev["ts"] >= 0
               for ev in trace["traceEvents"])


def test_inactive_sink_is_noop():
    assert not obs.active()
    # No exceptions, no files, and the null span is a shared singleton —
    # the hot loop pays one None check, not an allocation.
    obs.emit("anything", x=1)
    obs.gauge("g", 2)
    assert obs.span("a") is obs.span("b")
    with obs.span("a"):
        pass


def test_report_aggregation_synthetic():
    t0 = 1000.0
    ev = [
        {"t": t0, "ev": "run_start", "pid": 1},
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 4, "total": 4},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": 0.5},
        {"t": t0 + 0.6, "ev": "span", "name": "dispatch", "dur_s": 0.2},
        {"t": t0 + 0.8, "ev": "span", "name": "readback", "dur_s": 0.1},
        {"t": t0 + 0.9, "ev": "span", "name": "eval", "dur_s": 0.5},
        {"t": t0 + 1.4, "ev": "span", "name": "checkpoint", "dur_s": 0.2},
        {"t": t0 + 0.2, "ev": "gauge", "name": "prefetch_queue_depth",
         "value": 0},
        {"t": t0 + 0.3, "ev": "gauge", "name": "prefetch_queue_depth",
         "value": 2},
        {"t": t0 + 0.4, "ev": "gauge", "name": "prefetch_queue_depth",
         "value": 4},
        {"t": t0 + 0.2, "ev": "gauge", "name": "producer_batch_s",
         "value": 0.05, "worker": 0},
        {"t": t0 + 0.5, "ev": "heartbeat", "age_s": 1.0},
        {"t": t0 + 1.5, "ev": "heartbeat", "age_s": 5.0},
        {"t": t0 + 1.6, "ev": "supervisor", "phase": "spawn", "pid": 7},
        {"t": t0 + 1.7, "ev": "supervisor", "phase": "stall",
         "heartbeat_age_s": 700.0},
        {"t": t0 + 1.8, "ev": "supervisor", "phase": "restart",
         "attempt": 2, "reason": "stall"},
        {"t": t0 + 2.0, "ev": "loop_end", "step": 4, "wall_s": 2.0},
        # Serving spans live outside any loop window.
        {"t": t0 + 3.0, "ev": "span", "name": "infer_batch", "dur_s": 0.010,
         "n": 32},
        {"t": t0 + 3.1, "ev": "span", "name": "infer_batch", "dur_s": 0.030,
         "n": 8},
        {"t": t0 + 3.2, "ev": "metrics", "kind": "train", "step": 4,
         "loss": 0.5},
    ]
    rep = build_report(ev)
    assert rep["loop"] == {
        "windows": 1, "truncated_windows": 0, "wall_s": 2.0, "steps": 4,
        "step_ms": 500.0,
    }
    bd = rep["breakdown"]
    assert bd["data_wait"]["fraction"] == 0.25
    assert bd["dispatch"]["fraction"] == 0.1
    assert bd["readback"]["fraction"] == 0.05
    assert bd["eval"]["fraction"] == 0.25
    assert bd["checkpoint"]["fraction"] == 0.1
    assert bd["other"]["fraction"] == 0.25
    assert sum(v["fraction"] for v in bd.values()) == pytest.approx(1.0)
    assert rep["attributed_fraction"] == 0.75
    assert rep["prefetch_queue_depth"]["p50"] == 2
    assert rep["prefetch_queue_depth"]["max"] == 4
    assert rep["producer_batch_s"]["n"] == 1
    assert rep["heartbeat"] == {"beats": 2, "max_age_s": 5.0}
    sup = rep["supervisor"]
    assert (sup["stalls"], sup["restarts"]) == (1, 1)
    assert [e["phase"] for e in sup["timeline"]] == [
        "spawn", "stall", "restart"
    ]
    sv = rep["serving_latency_ms"]
    assert sv["batches"] == 2 and sv["rows"] == 40
    assert sv["max"] == 30.0
    assert rep["metrics"]["last"]["train"]["loss"] == 0.5


def test_report_truncated_window_still_attributes():
    """A loop_start with no loop_end (the run was SIGKILLed mid-loop — the
    supervisor's stall verdict) must still get a breakdown, bounded at the
    last event: that segment is exactly the one the operator diagnoses."""
    t0 = 2000.0
    ev = [
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 8, "total": 8},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": 0.8},
        {"t": t0 + 0.9, "ev": "span", "name": "dispatch", "dur_s": 0.1},
        {"t": t0 + 1.0, "ev": "metrics", "kind": "train", "step": 3,
         "loss": 1.0},
        {"t": t0 + 2.0, "ev": "heartbeat", "age_s": 1.0},
    ]
    rep = build_report(ev)
    assert rep["loop"]["windows"] == 1
    assert rep["loop"]["truncated_windows"] == 1
    assert rep["loop"]["steps"] == 3  # highest step any event reported
    assert rep["loop"]["wall_s"] == pytest.approx(2.0)
    assert rep["breakdown"]["data_wait"]["fraction"] == pytest.approx(0.4)

    # Mid-sequence kill: a respawn's loop_start closes the dead segment's
    # window at the respawn boundary, so its spans stay attributed.
    t1 = t0 + 10.0
    ev2 = ev + [
        {"t": t1, "ev": "loop_start", "step": 3, "stop": 8, "total": 8},
        {"t": t1 + 0.5, "ev": "span", "name": "dispatch", "dur_s": 0.5},
        {"t": t1 + 1.0, "ev": "loop_end", "step": 8, "wall_s": 1.0},
    ]
    rep2 = build_report(ev2)
    assert rep2["loop"]["windows"] == 2
    assert rep2["loop"]["truncated_windows"] == 1
    # Killed window closes at the respawn's t (wall 10s), clean one at 1s.
    assert rep2["loop"]["wall_s"] == pytest.approx(11.0)
    assert rep2["loop"]["steps"] == 3 + 5
    assert rep2["breakdown"]["data_wait"]["seconds"] == pytest.approx(0.8)
    assert rep2["breakdown"]["dispatch"]["seconds"] == pytest.approx(0.6)


def test_trainer_run_dir_end_to_end(tmp_path, capsys):
    """The acceptance contract: a 2-step CPU run with run_dir produces
    run.json + events.jsonl, and the report's attributed fractions
    (data_wait + dispatch + readback + eval + checkpoint + other) account
    for >= 90% of loop wall time."""
    run_dir = str(tmp_path / "run")
    cfg = get_config(
        "smoke16",
        total_steps=2,
        log_every=1,
        eval_every=2,
        checkpoint_every=2,
        eval_batches=1,
        data_workers=1,
        global_batch=8,
        run_dir=run_dir,
        checkpoint_dir=str(tmp_path / "ckpt"),
        heartbeat_file=str(tmp_path / "hb"),
    )
    t = Trainer(cfg)
    t.run()
    obs.close_run()

    assert os.path.exists(os.path.join(run_dir, "run.json"))
    events, bad = load_events(run_dir)
    assert bad == 0
    kinds = {e["ev"] for e in events}
    assert {"run_start", "loop_start", "loop_end", "span", "gauge",
            "metrics", "heartbeat"} <= kinds
    names = {e.get("name") for e in events if e["ev"] == "span"}
    assert {"data_wait", "dispatch", "eval", "checkpoint",
            "checkpoint_save"} <= names
    assert any(
        e["ev"] == "gauge" and e["name"] == "prefetch_queue_depth"
        for e in events
    )

    rep = build_report_dir(run_dir)
    assert rep["loop"]["steps"] == 2
    assert rep["loop"]["wall_s"] > 0
    fracs = {k: v["fraction"] for k, v in rep["breakdown"].items()}
    assert sum(fracs.values()) >= 0.90
    assert rep["metrics"]["count"] >= 2  # setup + train/eval records

    # The CLI entry prints a parseable breakdown from the same artifact.
    from featurenet_tpu.cli import main as cli_main

    trace_path = str(tmp_path / "trace.json")
    cli_main(["report", run_dir, "--trace", trace_path])
    out = capsys.readouterr().out
    assert "step-time breakdown" in out
    assert "data_wait" in out and "checkpoint" in out
    trace = json.load(open(trace_path))
    assert trace["traceEvents"], "spans must export as Chrome trace events"
    cli_main(["report", run_dir, "--json"])
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["breakdown"].keys() == rep["breakdown"].keys()


def test_run_without_run_dir_stays_dark(tmp_path):
    """No run_dir => the obs layer never activates: no sink, no obs files,
    the dispatch path keeps its null-span fast path."""
    cfg = get_config(
        "smoke16", total_steps=1, log_every=1, eval_every=10**9,
        checkpoint_every=10**9, eval_batches=1, data_workers=1,
        global_batch=8,
    )
    assert not obs.active()
    Trainer(cfg).run()
    assert not obs.active()
    assert list(tmp_path.iterdir()) == []


def test_explicit_dispatch_k_is_honored(monkeypatch, capsys):
    """clamp_dispatch_k=False (the CLI's behavior for an explicit
    --steps-per-dispatch) keeps the requested k even when the membytes
    model says it does not fit — with the warning still emitted."""
    from featurenet_tpu.ops import membytes

    monkeypatch.setattr(membytes, "HBM_BYTES", 1e6)  # nothing >k=1 fits
    base = dict(steps_per_dispatch=2, total_steps=2, data_workers=1,
                eval_batches=1, global_batch=8)
    clamped = Trainer(get_config("smoke16", **base))
    assert clamped._k == 1
    assert "dispatch_warning" in capsys.readouterr().err
    pinned = Trainer(get_config("smoke16", clamp_dispatch_k=False, **base))
    assert pinned._k == 2
    err = capsys.readouterr().err
    assert "dispatch_warning" in err and "honoring" in err


def test_cli_sets_clamp_false_for_explicit_k():
    from featurenet_tpu.cli import _overrides

    class A:
        steps_per_dispatch = 4
        total_steps = 2

    over = _overrides(A())
    assert over["steps_per_dispatch"] == 4
    assert over["clamp_dispatch_k"] is False

    class B:
        total_steps = 2

    assert "clamp_dispatch_k" not in _overrides(B())


def test_recalibrate_bn_clean_stream_clone(tmp_path):
    """An API caller whose Trainer streams HOST-augmented batches still
    gets clean-stream recalibration: the pass feeds a non-augmenting
    clone and never mutates the training dataset."""
    from featurenet_tpu.data.offline import export_synthetic_cache

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=3, resolution=16)
    cfg = get_config(
        "smoke16", data_cache=cache, augment=True, augment_device=False,
        total_steps=2, data_workers=1, eval_batches=1, global_batch=8,
    )
    t = Trainer(cfg)
    assert t.train_data.augment is True  # host-side augmentation active
    stats_before = [
        np.asarray(x) for x in
        __import__("jax").tree_util.tree_leaves(t.state.batch_stats)
    ]
    t.recalibrate_bn(batches=2)
    assert t.train_data.augment is True  # clone, not mutation
    import jax

    assert any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(stats_before,
                        jax.tree_util.tree_leaves(t.state.batch_stats))
    )


def test_recalibrate_cli_drops_stale_init_from(tmp_path):
    """A checkpoint whose persisted config carries init_from must not
    re-run (or crash on) the warm-start restore during recalibration —
    the weights come from checkpoint_dir (advisor r5)."""
    from featurenet_tpu.cli import main as cli_main

    src = str(tmp_path / "src")
    cfg = get_config(
        "smoke16", total_steps=2, eval_every=10**9, checkpoint_every=2,
        log_every=1, data_workers=1, eval_batches=1, checkpoint_dir=src,
        global_batch=8,
    )
    Trainer(cfg).run()
    sidecar = os.path.join(src, "config.json")
    saved = json.load(open(sidecar))
    saved["init_from"] = str(tmp_path / "long_gone")  # dir no longer exists
    json.dump(saved, open(sidecar, "w"))
    out = str(tmp_path / "recal")
    cli_main(["recalibrate", "--checkpoint-dir", src, "--out-dir", out,
              "--batches", "1"])
    restored = Trainer(get_config(
        "smoke16", data_workers=1, eval_batches=1, checkpoint_dir=out,
        global_batch=8,
    ))
    assert restored.resume_if_available() == 2


def test_seg_ood_rotation_delta_vs_scale_control():
    from featurenet_tpu.ood import (
        ROTATION_PRESCALE,
        _annotate_delta,
        _annotate_rotation_control,
    )

    rows = [
        {"family": "clean", "level": None, "mean_iou": 0.9},
        {"family": "rotation", "level": 15.0, "mean_iou": 0.5},
        {"family": "rotation", "level": "so3", "mean_iou": 0.4},
        {"family": "scale", "level": ROTATION_PRESCALE, "mean_iou": 0.8},
    ]
    out = _annotate_rotation_control(
        _annotate_delta(rows, "mean_iou"), "mean_iou"
    )
    rot15 = next(r for r in out if r["level"] == 15.0)
    # vs clean the row mixes scale+rotation cost; vs the 0.7 pre-scale
    # control it isolates rotation.
    assert rot15["delta_vs_clean"] == pytest.approx(-0.4)
    assert rot15["delta_vs_scale_control"] == pytest.approx(-0.3)
    assert next(
        r for r in out if r["family"] == "scale"
    ).get("delta_vs_scale_control") is None
    # Without the control row the annotation degrades gracefully.
    no_ctrl = _annotate_rotation_control(
        [{"family": "rotation", "level": 5.0, "mean_iou": 0.5}], "mean_iou"
    )
    assert "delta_vs_scale_control" not in no_ctrl[0]
