"""Observability layer: event schema, span nesting/thread-safety, report
aggregation, and the end-to-end Trainer ``run_dir`` contract — plus the
advisor-r5 satellite fixes that rode along with the obs PR (explicit
dispatch-k honor, clean-stream recalibration, seg-OOD rotation controls,
recalibrate dropping a stale ``init_from``)."""

import json
import os
import threading

import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.config import get_config
from featurenet_tpu.obs.report import (
    build_report,
    build_report_dir,
    load_events,
)
from featurenet_tpu.obs.spans import chrome_trace
from featurenet_tpu.train import Trainer


# Process-wide obs/faults state is reset by conftest's autouse
# _reset_process_state fixture (tests-tree fixture hygiene, PR 7).


def test_events_schema_roundtrip(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, config={"name": "unit", "task": "classify"})
    obs.gauge("prefetch_queue_depth", 3, worker=1)
    obs.emit("heartbeat", age_s=0.5)
    obs.warn("mesh_warning", "degraded", requested=8)
    obs.close_run()

    manifest = json.load(open(os.path.join(run_dir, "run.json")))
    assert manifest["config"]["name"] == "unit"
    assert "start_time" in manifest and "argv" in manifest
    assert "process_index" in manifest["jax"]

    events, bad = load_events(run_dir)
    assert bad == 0
    assert [e["ev"] for e in events] == [
        "run_start", "gauge", "heartbeat", "warning"
    ]
    for e in events:
        assert isinstance(e["t"], float)
    g = events[1]
    assert (g["name"], g["value"], g["worker"]) == (
        "prefetch_queue_depth", 3, 1
    )
    assert events[3]["msg"] == "degraded"

    # Re-init of the same dir appends (restart semantics) and keeps the
    # original manifest rather than rewriting it.
    start0 = manifest["start_unix"]
    obs.init_run(run_dir, config={"name": "other"})
    obs.close_run()
    manifest2 = json.load(open(os.path.join(run_dir, "run.json")))
    assert manifest2["start_unix"] == start0
    events2, _ = load_events(run_dir)
    assert sum(1 for e in events2 if e["ev"] == "run_start") == 2


def test_span_nesting_and_thread_safety(tmp_path):
    obs.init_run(str(tmp_path / "run"))
    with obs.span("outer", take=4):
        with obs.span("inner"):
            pass

    # Hammer the sink from 8 threads: every line must land whole (the
    # lock serializes writers) and each thread's parent tracking must be
    # independent (thread-local stacks).
    def worker(i):
        for j in range(50):
            with obs.span("t_outer", thread_no=i):
                with obs.span("t_inner", j=j):
                    pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    obs.close_run()

    events, bad = load_events(str(tmp_path / "run"))
    assert bad == 0
    spans = [e for e in events if e["ev"] == "span"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert len(by_name["t_outer"]) == 400
    assert len(by_name["t_inner"]) == 400
    assert by_name["inner"][0]["parent"] == "outer"
    assert by_name["outer"][0]["parent"] is None
    assert by_name["outer"][0]["take"] == 4
    assert all(s["parent"] == "t_outer" for s in by_name["t_inner"])
    assert all(s["dur_s"] >= 0 for s in spans)

    # Chrome export: one complete event per span, rebased to t=0, plus
    # per-track ("M") metadata naming each (host, pid) writer.
    trace = chrome_trace(events)
    complete = [ev for ev in trace["traceEvents"] if ev["ph"] == "X"]
    assert len(complete) == len(spans)
    assert all(ev["ts"] >= 0 for ev in complete)
    meta = [ev for ev in trace["traceEvents"] if ev["ph"] == "M"]
    assert {m["name"] for m in meta} == {
        "process_name", "process_sort_index"
    }


def test_inactive_sink_is_noop():
    assert not obs.active()
    # No exceptions, no files, and the null span is a shared singleton —
    # the hot loop pays one None check, not an allocation.
    obs.emit("anything", x=1)
    obs.gauge("g", 2)
    assert obs.span("a") is obs.span("b")
    with obs.span("a"):
        pass


def test_report_aggregation_synthetic():
    t0 = 1000.0
    ev = [
        {"t": t0, "ev": "run_start", "pid": 1},
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 4, "total": 4},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": 0.5},
        {"t": t0 + 0.6, "ev": "span", "name": "dispatch", "dur_s": 0.2},
        {"t": t0 + 0.8, "ev": "span", "name": "readback", "dur_s": 0.1},
        {"t": t0 + 0.9, "ev": "span", "name": "eval", "dur_s": 0.5},
        {"t": t0 + 1.4, "ev": "span", "name": "checkpoint", "dur_s": 0.2},
        {"t": t0 + 0.2, "ev": "gauge", "name": "prefetch_queue_depth",
         "value": 0},
        {"t": t0 + 0.3, "ev": "gauge", "name": "prefetch_queue_depth",
         "value": 2},
        {"t": t0 + 0.4, "ev": "gauge", "name": "prefetch_queue_depth",
         "value": 4},
        {"t": t0 + 0.2, "ev": "gauge", "name": "producer_batch_s",
         "value": 0.05, "worker": 0},
        {"t": t0 + 0.5, "ev": "heartbeat", "age_s": 1.0},
        {"t": t0 + 1.5, "ev": "heartbeat", "age_s": 5.0},
        {"t": t0 + 1.6, "ev": "supervisor", "phase": "spawn", "pid": 7},
        {"t": t0 + 1.7, "ev": "supervisor", "phase": "stall",
         "heartbeat_age_s": 700.0},
        {"t": t0 + 1.8, "ev": "supervisor", "phase": "restart",
         "attempt": 2, "reason": "stall"},
        {"t": t0 + 2.0, "ev": "loop_end", "step": 4, "wall_s": 2.0},
        # Serving spans live outside any loop window.
        {"t": t0 + 3.0, "ev": "span", "name": "infer_batch", "dur_s": 0.010,
         "n": 32},
        {"t": t0 + 3.1, "ev": "span", "name": "infer_batch", "dur_s": 0.030,
         "n": 8},
        {"t": t0 + 3.2, "ev": "metrics", "kind": "train", "step": 4,
         "loss": 0.5},
    ]
    rep = build_report(ev)
    assert rep["loop"] == {
        "windows": 1, "truncated_windows": 0, "wall_s": 2.0, "steps": 4,
        "step_ms": 500.0,
    }
    bd = rep["breakdown"]
    assert bd["data_wait"]["fraction"] == 0.25
    assert bd["dispatch"]["fraction"] == 0.1
    assert bd["readback"]["fraction"] == 0.05
    assert bd["eval"]["fraction"] == 0.25
    assert bd["checkpoint"]["fraction"] == 0.1
    assert bd["other"]["fraction"] == 0.25
    assert sum(v["fraction"] for v in bd.values()) == pytest.approx(1.0)
    assert rep["attributed_fraction"] == 0.75
    assert rep["prefetch_queue_depth"]["p50"] == 2
    assert rep["prefetch_queue_depth"]["max"] == 4
    assert rep["producer_batch_s"]["n"] == 1
    assert rep["heartbeat"] == {"beats": 2, "max_age_s": 5.0}
    sup = rep["supervisor"]
    assert (sup["stalls"], sup["restarts"]) == (1, 1)
    assert [e["phase"] for e in sup["timeline"]] == [
        "spawn", "stall", "restart"
    ]
    sv = rep["serving_latency_ms"]
    assert sv["batches"] == 2 and sv["rows"] == 40
    assert sv["max"] == 30.0
    assert rep["metrics"]["last"]["train"]["loss"] == 0.5


def test_report_truncated_window_still_attributes():
    """A loop_start with no loop_end (the run was SIGKILLed mid-loop — the
    supervisor's stall verdict) must still get a breakdown, bounded at the
    last event: that segment is exactly the one the operator diagnoses."""
    t0 = 2000.0
    ev = [
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 8, "total": 8},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": 0.8},
        {"t": t0 + 0.9, "ev": "span", "name": "dispatch", "dur_s": 0.1},
        {"t": t0 + 1.0, "ev": "metrics", "kind": "train", "step": 3,
         "loss": 1.0},
        {"t": t0 + 2.0, "ev": "heartbeat", "age_s": 1.0},
    ]
    rep = build_report(ev)
    assert rep["loop"]["windows"] == 1
    assert rep["loop"]["truncated_windows"] == 1
    assert rep["loop"]["steps"] == 3  # highest step any event reported
    assert rep["loop"]["wall_s"] == pytest.approx(2.0)
    assert rep["breakdown"]["data_wait"]["fraction"] == pytest.approx(0.4)

    # Mid-sequence kill: a respawn's loop_start closes the dead segment's
    # window at the respawn boundary, so its spans stay attributed.
    t1 = t0 + 10.0
    ev2 = ev + [
        {"t": t1, "ev": "loop_start", "step": 3, "stop": 8, "total": 8},
        {"t": t1 + 0.5, "ev": "span", "name": "dispatch", "dur_s": 0.5},
        {"t": t1 + 1.0, "ev": "loop_end", "step": 8, "wall_s": 1.0},
    ]
    rep2 = build_report(ev2)
    assert rep2["loop"]["windows"] == 2
    assert rep2["loop"]["truncated_windows"] == 1
    # Killed window closes at the respawn's t (wall 10s), clean one at 1s.
    assert rep2["loop"]["wall_s"] == pytest.approx(11.0)
    assert rep2["loop"]["steps"] == 3 + 5
    assert rep2["breakdown"]["data_wait"]["seconds"] == pytest.approx(0.8)
    assert rep2["breakdown"]["dispatch"]["seconds"] == pytest.approx(0.6)


def test_trainer_run_dir_end_to_end(tmp_path, capsys):
    """The acceptance contract: a 2-step CPU run with run_dir produces
    run.json + events.jsonl, and the report's attributed fractions
    (data_wait + dispatch + readback + eval + checkpoint + other) account
    for >= 90% of loop wall time."""
    run_dir = str(tmp_path / "run")
    cfg = get_config(
        "smoke16",
        total_steps=2,
        log_every=1,
        eval_every=2,
        checkpoint_every=2,
        eval_batches=1,
        data_workers=1,
        global_batch=8,
        run_dir=run_dir,
        checkpoint_dir=str(tmp_path / "ckpt"),
        heartbeat_file=str(tmp_path / "hb"),
    )
    t = Trainer(cfg)
    t.run()
    obs.close_run()

    assert os.path.exists(os.path.join(run_dir, "run.json"))
    events, bad = load_events(run_dir)
    assert bad == 0
    kinds = {e["ev"] for e in events}
    assert {"run_start", "loop_start", "loop_end", "run_end", "span",
            "gauge", "metrics", "heartbeat"} <= kinds
    names = {e.get("name") for e in events if e["ev"] == "span"}
    assert {"data_wait", "dispatch", "eval", "checkpoint",
            "checkpoint_save"} <= names
    assert any(
        e["ev"] == "gauge" and e["name"] == "prefetch_queue_depth"
        for e in events
    )

    rep = build_report_dir(run_dir)
    assert rep["loop"]["steps"] == 2
    assert rep["loop"]["wall_s"] > 0
    fracs = {k: v["fraction"] for k, v in rep["breakdown"].items()}
    assert sum(fracs.values()) >= 0.90
    assert rep["metrics"]["count"] >= 2  # setup + train/eval records

    # The CLI entry prints a parseable breakdown from the same artifact.
    from featurenet_tpu.cli import main as cli_main

    trace_path = str(tmp_path / "trace.json")
    cli_main(["report", run_dir, "--trace", trace_path])
    out = capsys.readouterr().out
    assert "step-time breakdown" in out
    assert "data_wait" in out and "checkpoint" in out
    trace = json.load(open(trace_path))
    assert trace["traceEvents"], "spans must export as Chrome trace events"
    cli_main(["report", run_dir, "--json"])
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["breakdown"].keys() == rep["breakdown"].keys()

    # The real run's telemetry passes the schema lint (the tier-1 guard
    # that malformed events fail fast instead of corrupting reports).
    cli_main(["report", run_dir, "--validate"])
    assert '"validate": "ok"' in capsys.readouterr().out


def test_run_without_run_dir_stays_dark(tmp_path):
    """No run_dir => the obs layer never activates: no sink, no obs files,
    the dispatch path keeps its null-span fast path."""
    cfg = get_config(
        "smoke16", total_steps=1, log_every=1, eval_every=10**9,
        checkpoint_every=10**9, eval_batches=1, data_workers=1,
        global_batch=8,
    )
    assert not obs.active()
    Trainer(cfg).run()
    assert not obs.active()
    assert list(tmp_path.iterdir()) == []


def test_explicit_dispatch_k_is_honored(monkeypatch, capsys):
    """clamp_dispatch_k=False (the CLI's behavior for an explicit
    --steps-per-dispatch) keeps the requested k even when the membytes
    model says it does not fit — with the warning still emitted."""
    from featurenet_tpu.ops import membytes

    monkeypatch.setattr(membytes, "HBM_BYTES", 1e6)  # nothing >k=1 fits
    base = dict(steps_per_dispatch=2, total_steps=2, data_workers=1,
                eval_batches=1, global_batch=8)
    clamped = Trainer(get_config("smoke16", **base))
    assert clamped._k == 1
    assert "dispatch_warning" in capsys.readouterr().err
    pinned = Trainer(get_config("smoke16", clamp_dispatch_k=False, **base))
    assert pinned._k == 2
    err = capsys.readouterr().err
    assert "dispatch_warning" in err and "honoring" in err


def test_cli_sets_clamp_false_for_explicit_k():
    from featurenet_tpu.cli import _overrides

    class A:
        steps_per_dispatch = 4
        total_steps = 2

    over = _overrides(A())
    assert over["steps_per_dispatch"] == 4
    assert over["clamp_dispatch_k"] is False

    class B:
        total_steps = 2

    assert "clamp_dispatch_k" not in _overrides(B())


def test_recalibrate_bn_clean_stream_clone(tmp_path):
    """An API caller whose Trainer streams HOST-augmented batches still
    gets clean-stream recalibration: the pass feeds a non-augmenting
    clone and never mutates the training dataset."""
    from featurenet_tpu.data.offline import export_synthetic_cache

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=3, resolution=16)
    cfg = get_config(
        "smoke16", data_cache=cache, augment=True, augment_device=False,
        total_steps=2, data_workers=1, eval_batches=1, global_batch=8,
    )
    t = Trainer(cfg)
    assert t.train_data.augment is True  # host-side augmentation active
    stats_before = [
        np.asarray(x) for x in
        __import__("jax").tree_util.tree_leaves(t.state.batch_stats)
    ]
    t.recalibrate_bn(batches=2)
    assert t.train_data.augment is True  # clone, not mutation
    import jax

    assert any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(stats_before,
                        jax.tree_util.tree_leaves(t.state.batch_stats))
    )


def test_recalibrate_cli_drops_stale_init_from(tmp_path):
    """A checkpoint whose persisted config carries init_from must not
    re-run (or crash on) the warm-start restore during recalibration —
    the weights come from checkpoint_dir (advisor r5)."""
    from featurenet_tpu.cli import main as cli_main

    src = str(tmp_path / "src")
    cfg = get_config(
        "smoke16", total_steps=2, eval_every=10**9, checkpoint_every=2,
        log_every=1, data_workers=1, eval_batches=1, checkpoint_dir=src,
        global_batch=8,
    )
    Trainer(cfg).run()
    sidecar = os.path.join(src, "config.json")
    saved = json.load(open(sidecar))
    saved["init_from"] = str(tmp_path / "long_gone")  # dir no longer exists
    json.dump(saved, open(sidecar, "w"))
    out = str(tmp_path / "recal")
    cli_main(["recalibrate", "--checkpoint-dir", src, "--out-dir", out,
              "--batches", "1"])
    restored = Trainer(get_config(
        "smoke16", data_workers=1, eval_batches=1, checkpoint_dir=out,
        global_batch=8,
    ))
    assert restored.resume_if_available() == 2


def test_seg_ood_rotation_delta_vs_scale_control():
    from featurenet_tpu.ood import (
        ROTATION_PRESCALE,
        _annotate_delta,
        _annotate_rotation_control,
    )

    rows = [
        {"family": "clean", "level": None, "mean_iou": 0.9},
        {"family": "rotation", "level": 15.0, "mean_iou": 0.5},
        {"family": "rotation", "level": "so3", "mean_iou": 0.4},
        {"family": "scale", "level": ROTATION_PRESCALE, "mean_iou": 0.8},
    ]
    out = _annotate_rotation_control(
        _annotate_delta(rows, "mean_iou"), "mean_iou"
    )
    rot15 = next(r for r in out if r["level"] == 15.0)
    # vs clean the row mixes scale+rotation cost; vs the 0.7 pre-scale
    # control it isolates rotation.
    assert rot15["delta_vs_clean"] == pytest.approx(-0.4)
    assert rot15["delta_vs_scale_control"] == pytest.approx(-0.3)
    assert next(
        r for r in out if r["family"] == "scale"
    ).get("delta_vs_scale_control") is None
    # Without the control row the annotation degrades gracefully.
    no_ctrl = _annotate_rotation_control(
        [{"family": "rotation", "level": 5.0, "mean_iou": 0.5}], "mean_iou"
    )
    assert "delta_vs_scale_control" not in no_ctrl[0]


# --- multi-host telemetry (PR 2) ---------------------------------------------


def _write_stream(run_dir, filename, events):
    with open(os.path.join(run_dir, filename), "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")


def test_init_run_per_host_streams(tmp_path):
    """Host i>0 writes its own events.<i>.jsonl and never touches
    run.json (host 0 is the manifest's sole owner); the loader tags each
    record with the stream it came from."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=1)
    obs.gauge("g", 1)
    obs.close_run()
    assert os.path.exists(os.path.join(run_dir, "events.1.jsonl"))
    assert not os.path.exists(os.path.join(run_dir, "events.jsonl"))
    assert not os.path.exists(os.path.join(run_dir, "run.json"))

    obs.init_run(run_dir, config={"name": "unit"}, process_index=0)
    obs.emit("heartbeat")
    obs.close_run()
    assert os.path.exists(os.path.join(run_dir, "events.jsonl"))
    assert os.path.exists(os.path.join(run_dir, "run.json"))

    from featurenet_tpu.obs.report import load_events

    events, bad = load_events(run_dir)
    assert bad == 0
    gauge = next(e for e in events if e["ev"] == "gauge")
    beat = next(e for e in events if e["ev"] == "heartbeat")
    assert gauge["process_index"] == 1
    assert beat["process_index"] == 0
    # Both hosts' run_start spawns are visible.
    assert sum(1 for e in events if e["ev"] == "run_start") == 2


def _host_events(t0, offset, dw, steps=4):
    return [
        {"t": t0 + offset, "ev": "run_start"},
        {"t": t0 + offset, "ev": "loop_start", "step": 0, "stop": steps,
         "total": steps},
        {"t": t0 + offset + 0.1, "ev": "span", "name": "data_wait",
         "dur_s": dw},
        {"t": t0 + offset + 0.1 + dw, "ev": "span", "name": "dispatch",
         "dur_s": 0.2},
        {"t": t0 + offset + 0.5, "ev": "heartbeat", "age_s": 0.5},
        {"t": t0 + offset + 1.5, "ev": "heartbeat", "age_s": 1.0},
        {"t": t0 + offset + 2.0, "ev": "loop_end", "step": steps,
         "wall_s": 2.0},
    ]


def test_three_host_merged_log_aggregation(tmp_path):
    """Synthetic 3-host run dir: the loader merges all streams by time and
    tags records; the report carries per-host fractions, heartbeat gaps,
    and cross-host skew — while the primary (host 0) view is unchanged by
    the merge."""
    from featurenet_tpu.obs.report import format_report, load_events

    run_dir = str(tmp_path)
    t0 = 1000.0
    _write_stream(run_dir, "events.jsonl", _host_events(t0, 0.0, 0.5))
    _write_stream(run_dir, "events.1.jsonl", _host_events(t0, 0.2, 1.0))
    _write_stream(run_dir, "events.2.jsonl", _host_events(t0, 0.4, 0.25))

    events, bad = load_events(run_dir)
    assert bad == 0
    assert {e["process_index"] for e in events} == {0, 1, 2}
    ts = [e["t"] for e in events]
    assert ts == sorted(ts)  # merged by timestamp, not concatenated

    rep = build_report(events)
    # Primary host's sections are computed from its own stream only: the
    # other hosts' loop_starts must not read as respawns of host 0.
    assert rep["loop"]["windows"] == 1
    assert rep["loop"]["truncated_windows"] == 0
    assert rep["loop"]["steps"] == 4
    assert rep["breakdown"]["data_wait"]["fraction"] == 0.25
    # Respawn semantics survive the merge: one spawn per host must read
    # as zero restarts, so the counter stays primary-host-scoped.
    assert rep["process_starts"] == 1

    hosts = rep["hosts"]
    assert sorted(hosts) == [0, 1, 2]
    assert hosts[1]["fractions"]["data_wait"] == 0.5
    assert hosts[2]["fractions"]["data_wait"] == 0.125
    assert hosts[0]["heartbeat"]["beats"] == 2
    assert hosts[0]["heartbeat"]["max_gap_s"] == pytest.approx(1.0)
    assert all(h["steps"] == 4 for h in hosts.values())

    skew = rep["host_skew"]
    assert skew["loop_start_skew_s"] == pytest.approx(0.4)
    assert skew["data_wait_fraction"]["min"] == 0.125
    assert skew["data_wait_fraction"]["max"] == 0.5
    assert skew["data_wait_fraction"]["spread"] == pytest.approx(0.375)
    assert "step_mismatch" not in skew

    txt = format_report(rep)
    assert "hosts: 3" in txt
    assert "host skew" in txt

    # A host falling out of step is surfaced, not averaged away.
    _write_stream(run_dir, "events.2.jsonl", _host_events(t0, 0.4, 0.25,
                                                          steps=3))
    events2, _ = load_events(run_dir)
    rep2 = build_report(events2)
    assert rep2["host_skew"]["step_mismatch"] == {0: 4, 1: 4, 2: 3}
    assert "STEP MISMATCH" in format_report(rep2)


def test_report_per_host_only_layout(tmp_path, capsys):
    """A run dir holding only non-zero hosts' streams (host 0 wrote to a
    different filesystem) still loads and reports, anchored on the lowest
    index present."""
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs.report import load_events

    run_dir = str(tmp_path)
    t0 = 50.0
    _write_stream(run_dir, "events.1.jsonl", _host_events(t0, 0.0, 0.5))
    _write_stream(run_dir, "events.2.jsonl", _host_events(t0, 0.1, 0.8))
    events, bad = load_events(run_dir)
    rep = build_report(events, bad_lines=bad)
    assert rep["loop"]["steps"] == 4
    assert rep["breakdown"]["data_wait"]["fraction"] == 0.25  # host 1
    assert sorted(rep["hosts"]) == [1, 2]
    cli_main(["report", run_dir])
    assert "hosts: 2" in capsys.readouterr().out


def test_cli_report_lists_what_it_found(tmp_path):
    from featurenet_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="not a directory"):
        cli_main(["report", str(tmp_path / "never_made")])
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit, match="directory is empty"):
        cli_main(["report", str(empty)])
    stale = tmp_path / "stale"
    stale.mkdir()
    (stale / "run.json").write_text("{}")
    (stale / "trace.json").write_text("{}")
    with pytest.raises(SystemExit, match="found: run.json, trace.json"):
        cli_main(["report", str(stale)])


def test_interleaved_sink_writers_never_shear_lines(tmp_path):
    """Several EventSinks on the SAME file (the supervisor + supervised
    child shape: independent O_APPEND fds) hammered concurrently: every
    line must land whole — each emit is a single append write()."""
    import threading as th

    from featurenet_tpu.obs.report import load_events

    run_dir = str(tmp_path / "run")
    sinks = [obs.EventSink(run_dir) for _ in range(4)]
    pad = "x" * 512  # long enough to straddle any buffering boundary

    def worker(i):
        for j in range(100):
            sinks[i % 4].emit("gauge", name=f"w{i}", value=j, pad=pad)

    threads = [th.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for s in sinks:
        s.close()

    events, bad = load_events(run_dir)
    assert bad == 0
    assert len(events) == 800
    counts: dict = {}
    for e in events:
        counts[e["name"]] = counts.get(e["name"], 0) + 1
        assert e["pad"] == pad  # intact payload, not a resynced fragment
    assert all(v == 100 for v in counts.values())


def test_two_process_writers_line_atomic(tmp_path):
    """Real cross-process interleaving (not just cross-fd): two python
    processes append through EventSink simultaneously; the merged file
    parses clean with every record intact."""
    import subprocess
    import sys

    run_dir = str(tmp_path / "run")
    code = (
        "import sys\n"
        "from featurenet_tpu.obs.events import EventSink\n"
        "sink = EventSink(sys.argv[1])\n"
        "for j in range(300):\n"
        "    sink.emit('gauge', name='p' + sys.argv[2], value=j,\n"
        "              pad='y' * 256)\n"
        "sink.close()\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen([sys.executable, "-c", code, run_dir, str(i)],
                         cwd=repo)
        for i in range(2)
    ]
    assert [p.wait(timeout=120) for p in procs] == [0, 0]

    from featurenet_tpu.obs.report import load_events

    events, bad = load_events(run_dir)
    assert bad == 0
    per_writer: dict = {}
    for e in events:
        per_writer.setdefault(e["name"], []).append(e["value"])
    assert sorted(per_writer) == ["p0", "p1"]
    assert all(sorted(v) == list(range(300)) for v in per_writer.values())


def test_event_tail_incremental(tmp_path):
    """The live tail consumes only newly appended COMPLETE lines: a torn
    trailing line waits for the writer to finish it, nothing is ever
    re-parsed, and a per-host stream appearing mid-run is discovered."""
    from featurenet_tpu.obs.report import EventTail

    d = str(tmp_path)
    path = os.path.join(d, "events.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"t": 1.0, "ev": "gauge", "name": "g",
                             "value": 1}) + "\n")
    tail = EventTail(d)
    assert [e["value"] for e in tail.poll()] == [1]
    assert tail.poll() == []  # no new bytes, no work
    with open(path, "a") as fh:
        fh.write(json.dumps({"t": 2.0, "ev": "gauge", "name": "g",
                             "value": 2}) + "\n")
        fh.write('{"t": 3.0, "ev": "gau')  # writer caught mid-line
    assert [e["value"] for e in tail.poll()] == [2]
    with open(path, "a") as fh:
        fh.write('ge", "name": "g", "value": 3}\n')
    assert [e["value"] for e in tail.poll()] == [3]
    assert tail.bad == 0
    with open(os.path.join(d, "events.1.jsonl"), "w") as fh:
        fh.write(json.dumps({"t": 4.0, "ev": "heartbeat"}) + "\n")
    new = tail.poll()
    assert [e["ev"] for e in new] == ["heartbeat"]
    assert new[0]["process_index"] == 1
    assert len(tail.events) == 4


def test_follow_report_renders_and_exits_on_run_end(tmp_path):
    """--follow re-renders as the file grows and returns when a terminal
    event (run_end) lands; the injected clock plays the writer."""
    from featurenet_tpu.obs.report import follow_report

    d = str(tmp_path)
    path = os.path.join(d, "events.jsonl")
    t0 = 100.0
    with open(path, "w") as fh:
        fh.write(json.dumps({"t": t0, "ev": "loop_start", "step": 0,
                             "stop": 2, "total": 2}) + "\n")
    outputs: list = []

    def clock(_interval):
        with open(path, "a") as fh:
            fh.write(json.dumps({"t": t0 + 0.5, "ev": "span",
                                 "name": "data_wait", "dur_s": 0.5}) + "\n")
            fh.write(json.dumps({"t": t0 + 1.0, "ev": "loop_end",
                                 "step": 2, "wall_s": 1.0}) + "\n")
            fh.write(json.dumps({"t": t0 + 1.0, "ev": "run_end",
                                 "step": 2}) + "\n")

    follow_report(d, interval=0.01, out=outputs.append, clock=clock,
                  max_polls=50, clear=False)
    assert any("follow exiting" in o for o in outputs)
    assert any("data_wait" in o for o in outputs)  # re-rendered breakdown
    # And a run with no terminal event stops at max_polls instead of
    # spinning forever (the test-harness escape hatch).
    hot = tmp_path / "hot"
    hot.mkdir()
    _write_stream(str(hot), "events.jsonl",
                  [{"t": 1.0, "ev": "heartbeat"}])
    follow_report(str(hot), interval=0.01, out=[].append,
                  clock=lambda s: None, max_polls=2, clear=False)


def test_follow_header_surfaces_skew_and_data_wait_spread(tmp_path):
    """Satellite (ROADMAP obs-next): the live tail's header line carries
    the per-host loop-start skew and cross-host data-wait spread — the
    lockstep-mesh health signals — while a single-host run says so."""
    from featurenet_tpu.obs.report import build_report, follow_header

    # Single host: no skew to report, header says single host.
    single = build_report(_host_events(100.0, 0.0, 0.5))
    head = follow_header(single, "rd")
    assert head.startswith("==") and "single host" in head

    run_dir = str(tmp_path)
    t0 = 1000.0
    _write_stream(run_dir, "events.jsonl", _host_events(t0, 0.0, 0.5))
    _write_stream(run_dir, "events.1.jsonl", _host_events(t0, 0.2, 1.0))
    _write_stream(run_dir, "events.2.jsonl", _host_events(t0, 0.4, 0.25))
    from featurenet_tpu.obs.report import follow_report, load_events

    events, _ = load_events(run_dir)
    rep = build_report(events)
    head = follow_header(rep, run_dir)
    assert "3 hosts" in head
    assert "loop-start skew 0.4s" in head
    # data_wait fractions 12.5%–50% => spread 37.5pp.
    assert "data-wait spread 37.5pp (12.5%–50.0%)" in head

    # And the live tail actually renders it as the first line.
    outputs: list = []
    follow_report(run_dir, interval=0.01, out=outputs.append,
                  clock=lambda s: None, max_polls=1, clear=False)
    first_line = outputs[0].splitlines()[0]
    assert "loop-start skew" in first_line
    assert "data-wait spread" in first_line


def test_gates_pass_fail_and_tolerance_edge():
    from featurenet_tpu.obs import gates

    base = {"gates": {"step_ms": {"value": 100.0, "tolerance": 0.10}}}
    assert gates.evaluate_gates({"step_ms": 90.0}, base)["ok"]
    # Tolerance edge: exactly at the limit passes; a hair over fails.
    assert gates.evaluate_gates({"step_ms": 110.0}, base)["ok"]
    r = gates.evaluate_gates({"step_ms": 110.01}, base)
    assert not r["ok"] and r["failed"] == ["step_ms"]
    assert r["gates"][0]["limit"] == pytest.approx(110.0)

    # direction=min (throughputs): lower is the regression.
    tb = {"gates": {"e2e_samples_per_sec": {"value": 1000.0,
                                            "tolerance": 0.10}}}
    assert gates.evaluate_gates({"e2e_samples_per_sec": 900.0}, tb)["ok"]
    assert not gates.evaluate_gates({"e2e_samples_per_sec": 899.0},
                                    tb)["ok"]

    # Absolute slack is the only meaningful tolerance on a 0 baseline.
    zb = {"gates": {"restarts": {"value": 0, "tolerance_abs": 1}}}
    assert gates.evaluate_gates({"restarts": 1}, zb)["ok"]
    assert not gates.evaluate_gates({"restarts": 2}, zb)["ok"]

    # A pinned metric the report lacks must not pass silently.
    r = gates.evaluate_gates({}, base)
    assert not r["ok"] and r["gates"][0]["status"] == "missing"

    # Flat {metric: value} baselines work (default tolerance/direction).
    assert gates.evaluate_gates({"step_ms": 105.0}, {"step_ms": 100.0})["ok"]
    txt = gates.format_gates(r)
    assert "FAIL" in txt and "MISSING" in txt


def _serving_run_events(t0=1000.0):
    return [
        {"t": t0, "ev": "loop_start", "step": 0, "stop": 4, "total": 4},
        {"t": t0 + 0.1, "ev": "span", "name": "data_wait", "dur_s": 0.5},
        {"t": t0 + 0.6, "ev": "span", "name": "dispatch", "dur_s": 0.2},
        {"t": t0 + 2.0, "ev": "loop_end", "step": 4, "wall_s": 2.0},
        {"t": t0 + 3.0, "ev": "span", "name": "infer_batch",
         "dur_s": 0.010, "n": 32},
        {"t": t0 + 3.1, "ev": "span", "name": "infer_batch",
         "dur_s": 0.030, "n": 8},
        {"t": t0 + 3.2, "ev": "run_end", "step": 4},
    ]


def test_cli_report_gate_exit_codes(tmp_path, capsys):
    """Acceptance: --gate exits non-zero on an injected p99/data-wait
    regression vs a pinned baseline, and passes its own numbers."""
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs import gates

    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    _write_stream(run_dir, "events.jsonl", _serving_run_events())

    # Pin a baseline stricter than this run: p99 30ms vs pinned 10ms and
    # data-wait 25% vs pinned 10% are both regressions.
    strict = str(tmp_path / "strict.json")
    with open(strict, "w") as fh:
        json.dump({"gates": {
            "serving_p99_ms": {"value": 10.0, "tolerance": 0.10},
            "data_wait_fraction": {"value": 0.10, "tolerance": 0.10},
        }}, fh)
    with pytest.raises(SystemExit) as exc:
        cli_main(["report", run_dir, "--gate", strict])
    assert exc.value.code == 2
    out = capsys.readouterr().out
    assert "gate: FAIL" in out
    assert "serving_p99_ms" in out and "data_wait_fraction" in out

    # A baseline pinned from the run's own report passes (round-trip).
    rep = build_report_dir(run_dir)
    pin = gates.make_baseline(gates.report_gate_values(rep))
    own = str(tmp_path / "own.json")
    with open(own, "w") as fh:
        json.dump(pin, fh)
    cli_main(["report", run_dir, "--gate", own])  # must not raise
    assert "gate: PASS" in capsys.readouterr().out

    # An empty baseline is an operator error, said out loud.
    hollow = str(tmp_path / "hollow.json")
    with open(hollow, "w") as fh:
        json.dump({}, fh)
    with pytest.raises(ValueError, match="pins no gates"):
        cli_main(["report", run_dir, "--gate", hollow])


def test_validate_events_lint(tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main
    from featurenet_tpu.obs.report import validate_events

    # Clean nesting: child inside its parent's interval.
    clean = [
        {"t": 1.0, "ev": "run_start"},
        {"t": 10.0, "ev": "span", "name": "outer", "dur_s": 1.0,
         "thread": 7},
        {"t": 10.2, "ev": "span", "name": "inner", "dur_s": 0.5,
         "thread": 7, "parent": "outer"},
        {"t": 11.0, "ev": "gauge", "name": "g", "value": 1},
    ]
    assert validate_events(clean) == []

    dirty = [
        {"t": 1.0, "ev": "mystery"},                      # unknown kind
        {"t": 2.0, "ev": "span", "name": "x"},            # no dur_s
        {"t": 3.0, "ev": "gauge", "name": "g"},           # no value
        {"t": 4.0, "ev": "span", "name": "neg", "dur_s": -0.5},
        {"t": 10.0, "ev": "span", "name": "outer", "dur_s": 1.0,
         "thread": 7},
        {"t": 12.0, "ev": "span", "name": "escaped", "dur_s": 0.5,
         "thread": 7, "parent": "outer"},                 # outside parent
        {"t": 13.0, "ev": "span", "name": "orphan", "dur_s": 0.1,
         "thread": 7, "parent": "never_was"},
    ]
    findings = validate_events(dirty, bad_lines=1)
    checks = [f["check"] for f in findings]
    for want in ("parse", "unknown_kind", "missing_fields",
                 "negative_duration", "span_nesting", "orphan_parent"):
        assert want in checks, (want, checks)

    # CLI: a clean dir reports ok; a corrupted one exits non-zero.
    good = str(tmp_path / "good")
    os.makedirs(good)
    _write_stream(good, "events.jsonl", clean)
    cli_main(["report", good, "--validate"])
    assert '"validate": "ok"' in capsys.readouterr().out
    bad_dir = str(tmp_path / "bad")
    os.makedirs(bad_dir)
    _write_stream(bad_dir, "events.jsonl", dirty)
    with pytest.raises(SystemExit, match="finding"):
        cli_main(["report", bad_dir, "--validate"])


def test_bench_gate_summary_and_self_check():
    """bench.py's wiring: a summary yields a pin-ready baseline; the next
    round's regressed summary fails against it, a steady one passes."""
    from featurenet_tpu.obs import gates

    round1 = {"value": 16600.0, "mfu": 0.31,
              "serving_inferences_per_sec_per_chip": 48900.0,
              "e2e_samples_per_sec": 9878.0, "spread_pct": 3.8}
    vals = gates.bench_gate_values(round1)
    # Spreads are pinned too (PR 5 satellite): measurement quality is
    # itself gated, direction max, with bench adding an absolute slack.
    assert vals["spread_pct"] == 3.8
    pin = gates.make_baseline(vals, tolerance=0.15)
    assert pin["gates"]["value"]["direction"] == "min"
    assert pin["gates"]["spread_pct"]["direction"] == "max"

    steady = dict(round1, value=16000.0)
    assert gates.evaluate_gates(gates.bench_gate_values(steady), pin)["ok"]
    regressed = dict(round1, value=10000.0)
    res = gates.evaluate_gates(gates.bench_gate_values(regressed), pin)
    assert not res["ok"] and res["failed"] == ["value"]
