"""Model-quality observability plane (ISSUE 17): confidence/drift
telemetry (obs.quality), the flight-recorder capture ring
(serve.recorder), the quality alert pair firing and resolving through
the window hysteresis engine, the report's quality section, the dash
quality panel and friendly empty state, and the ``cli pin-quality`` /
``cli replay`` canary loop (agreement gate, zero post-warmup compiles,
exit 2 below the gate).

The acceptance spine: a served run with a skewed class mix pushes the
TV drift score over the ceiling and the ``quality_drift_score_p50``
alert fires; the mix returning to baseline resolves it — one hysteresis
pair, visible in the report. A capture ring replayed against the bf16
candidate of the same checkpoint reports full agreement with zero
compiles after warmup; a ring whose recorded labels disagree with the
candidate exits 2.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.config import get_config
from featurenet_tpu.data.synthetic import CLASS_NAMES, generate_batch
from featurenet_tpu.obs import quality as _quality
from featurenet_tpu.obs import tsdb as _tsdb
from featurenet_tpu.obs import windows as _windows
from featurenet_tpu.obs.report import (
    build_report_dir,
    format_report,
    load_events,
)
from featurenet_tpu.serve.recorder import (
    FlightRecorder,
    capture_dir,
    pack_grid,
    read_captures,
    unpack_grid,
)

RES = 16  # smoke16 resolution — every real-model test runs at 16³
NUM_CLASSES = len(CLASS_NAMES)
T0 = 1_700_000_000.0


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    """A real trained smoke16 checkpoint for the CLI-level canary
    tests (pin-quality and replay load through from_checkpoint)."""
    from featurenet_tpu.train import Trainer

    d = str(tmp_path_factory.mktemp("quality_ckpt") / "ckpt")
    cfg = get_config(
        "smoke16", total_steps=6, eval_every=10**9, checkpoint_every=6,
        log_every=6, checkpoint_dir=d, data_workers=1,
    )
    Trainer(cfg).run()
    return d


# --- confidence statistics and drift math ------------------------------------

def test_confidence_stats_top1_margin_entropy():
    top1, margin, ent = _quality.confidence_stats([0.7, 0.2, 0.1])
    assert top1 == pytest.approx(0.7)
    assert margin == pytest.approx(0.5)
    # -sum p ln p; zero-probability classes contribute nothing.
    assert ent == pytest.approx(0.8018, abs=1e-3)
    assert _quality.confidence_stats([]) == (0.0, 0.0, 0.0)
    # A one-hot row: certain, maximal margin, zero entropy.
    assert _quality.confidence_stats([0.0, 1.0, 0.0]) == (1.0, 1.0, 0.0)


def test_drift_score_bounds_and_width_mismatch():
    uniform = [0.25] * 4
    assert _quality.drift_score([5, 5, 5, 5], uniform) == \
        pytest.approx(0.0)
    # Disjoint support: all mass where the baseline has none.
    assert _quality.drift_score([10, 0, 0, 0], [0.0, 0.0, 0.5, 0.5]) \
        == pytest.approx(1.0)
    # Width mismatch: classes beyond either vector count as zero.
    assert _quality.drift_score([10], [0.5, 0.5]) == pytest.approx(0.5)
    assert _quality.drift_score([5, 5], [1.0]) == pytest.approx(0.5)
    # No observations yet: score 0, not a crash or a false alarm.
    assert _quality.drift_score([0, 0], [0.5, 0.5]) == 0.0


def test_baseline_save_load_roundtrip_and_refusals(tmp_path):
    path = str(tmp_path / "quality_baseline.json")
    rec = _quality.save_baseline(
        path, [3, 1, 0, 0], class_names=["a", "b", "c", "d"],
        source={"n": 4},
    )
    assert rec["n"] == 4
    assert rec["dist"] == [0.75, 0.25, 0.0, 0.0]
    loaded = _quality.load_baseline(path)
    assert loaded["dist"] == rec["dist"]
    assert loaded["class_names"] == ["a", "b", "c", "d"]
    # Refusals are config-time ValueErrors, never silent no-ops.
    with pytest.raises(ValueError, match="at least one prediction"):
        _quality.save_baseline(str(tmp_path / "x.json"), [0, 0])
    with pytest.raises(ValueError, match="unreadable"):
        _quality.load_baseline(str(tmp_path / "nope.json"))
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as fh:
        json.dump({"dist": [0.2, 0.2]}, fh)  # sums to 0.4, not ~1
    with pytest.raises(ValueError, match="sums to"):
        _quality.load_baseline(bad)
    with open(bad, "w") as fh:
        json.dump({"dist": "not a vector"}, fh)
    with pytest.raises(ValueError, match="no usable 'dist'"):
        _quality.load_baseline(bad)


def test_quality_rules_pair_and_drift_gating():
    conf, drift = _quality.quality_rules()
    assert (conf.metric, conf.op, conf.threshold) == \
        ("confidence_p50", "<", 0.5)
    assert (drift.metric, drift.op, drift.threshold) == \
        ("quality_drift_score_p50", ">", 0.25)
    assert conf.severity == drift.severity == "warning"
    # No baseline pinned → no drift rule (an SLO on a score that can
    # never compute would fire on absence).
    (only_conf,) = _quality.quality_rules(with_drift=False)
    assert only_conf.metric == "confidence_p50"
    # Quality alerts page, they never fail a serving drain.
    from featurenet_tpu.obs.alerts import is_serving_metric
    assert not is_serving_metric(conf.metric)
    assert not is_serving_metric(drift.metric)


def test_quality_tracker_rolls_window_and_emits(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    tracker = _quality.QualityTracker(
        3, baseline=[1.0, 0.0, 0.0], window=4, emit_every=4,
    )
    # Four on-baseline predictions: score 0.
    for _ in range(4):
        score = tracker.observe(0, 0.9, 0.8, 0.1)
    assert score == pytest.approx(0.0)
    # Four off-baseline ones displace them from the 4-wide ring: 1.0.
    for _ in range(4):
        score = tracker.observe(2, 0.9, 0.8, 0.1)
    assert score == pytest.approx(1.0)
    # Out-of-range labels are counted as seen but never in the ring.
    tracker.observe(99, 0.5, 0.1, 0.2)
    st = tracker.stats()
    assert st == {"seen": 9, "window_n": 4,
                  "drift_score": pytest.approx(1.0), "baseline": True}
    obs.close_run()
    events, bad = load_events(run_dir)
    assert bad == 0
    qd = [e for e in events if e["ev"] == "quality_drift"]
    assert len(qd) == 2  # every emit_every=4th observation
    assert qd[-1]["score"] == pytest.approx(1.0)
    assert qd[-1]["top_class"] == 2
    # No baseline → observe returns None and emits no drift events.
    bare = _quality.QualityTracker(3)
    assert bare.observe(1, 0.9, 0.8, 0.1) is None
    assert bare.stats()["baseline"] is False


# --- flight recorder ---------------------------------------------------------

def _grid(rng=None, fill=1.0):
    if rng is None:
        return np.full((RES, RES, RES, 1), fill, np.float32)
    return (rng.random((RES, RES, RES, 1)) > 0.5).astype(np.float32)


def test_pack_unpack_grid_lossless(rng):
    g = _grid(rng)
    rec = pack_grid(g)
    assert rec["shape"] == [RES, RES, RES, 1]
    np.testing.assert_array_equal(unpack_grid(rec), g)
    # ~32× smaller than float32 on the wire (bit-packed + base64).
    assert len(rec["bits"]) < g.nbytes / 20


def test_recorder_capture_policy_is_tail_biased(tmp_path):
    rec = FlightRecorder(str(tmp_path / "cap"), sample=0.0,
                         confidence_floor=0.35, slo_ms=100.0)
    # Forced reasons, in priority order; sampling off → healthy drops.
    assert rec.reason_for("t1", 0.9, 10.0, outcome="rejected") == \
        "rejected"
    assert rec.reason_for("t1", 0.9, 10.0, outcome="error") == "error"
    assert rec.reason_for("t1", 0.1, 10.0) == "low_confidence"
    assert rec.reason_for("t1", 0.9, 500.0) == "slo_breach"
    assert rec.reason_for("t1", 0.9, 10.0) is None
    assert not rec.maybe_capture(_grid(), "t1", confidence=0.9)
    assert rec.stats()["skipped"] == 1
    # sample=1.0 keeps every healthy request, deterministically.
    keep = FlightRecorder(str(tmp_path / "cap2"), sample=1.0)
    assert keep.reason_for("t1", 0.9, 10.0) == "sampled"
    assert keep.maybe_capture(_grid(), "t1", label=3, confidence=0.9,
                              total_ms=12.5)
    keep.close()
    (r,) = read_captures(keep.root)
    assert (r["reason"], r["label"], r["confidence"], r["trace"]) == \
        ("sampled", 3, 0.9, "t1")
    with pytest.raises(ValueError, match="sample"):
        FlightRecorder(str(tmp_path / "cap3"), sample=1.5)


def test_recorder_rotates_prunes_and_reader_survives_tears(tmp_path):
    root = str(tmp_path / "cap")
    one_line = len(json.dumps(
        {"t": 0.0, "trace": "t000", "reason": "low_confidence",
         "voxels": pack_grid(_grid())}, separators=(",", ":"),
    )) + 20
    rec = FlightRecorder(root, confidence_floor=1.0,
                         segment_bytes=one_line * 2,
                         max_bytes=one_line * 5)
    for i in range(10):
        assert rec.maybe_capture(_grid(fill=float(i % 2)), f"t{i:03d}",
                                 label=i, confidence=0.0)
    rec.close()
    segs = sorted(n for n in os.listdir(root)
                  if n.startswith("capture."))
    assert len(segs) >= 2  # rotated
    total = sum(os.path.getsize(os.path.join(root, n)) for n in segs)
    assert total <= one_line * 5 + one_line * 2  # pruned to ~budget
    recs = read_captures(root)
    assert len(recs) < 10  # oldest segments pruned
    # Newest-first survivors, in capture order, payloads intact.
    labels = [r["label"] for r in recs]
    assert labels == sorted(labels) and labels[-1] == 9
    np.testing.assert_array_equal(
        unpack_grid(recs[-1]["voxels"]), _grid(fill=1.0))
    # A torn tail + foreign garbage: skipped, never raised.
    with open(os.path.join(root, segs[-1]), "ab") as fh:
        fh.write(b"garbage\n")
        fh.write(b'{"torn": ')
    assert [r["label"] for r in read_captures(root)] == labels
    # A respawned writer resumes the ring past the tear.
    rec2 = FlightRecorder(root, confidence_floor=1.0,
                          segment_bytes=one_line * 2,
                          max_bytes=one_line * 5)
    assert rec2.maybe_capture(_grid(), "t999", label=99, confidence=0.0)
    rec2.close()
    assert read_captures(root)[-1]["label"] == 99


def test_recorder_goes_dark_on_disk_error_not_down(tmp_path):
    blocker = str(tmp_path / "file")
    with open(blocker, "w") as fh:
        fh.write("not a directory")
    rec = FlightRecorder(blocker, confidence_floor=1.0)
    # First write hits the OSError → dark; later writes are counters.
    assert not rec.maybe_capture(_grid(), "t1", confidence=0.0)
    assert not rec.maybe_capture(_grid(), "t2", confidence=0.0)
    st = rec.stats()
    assert st["dark"] and st["dropped"] == 2 and st["captured"] == 0
    assert read_captures(blocker) == []
    rec.close()


# --- acceptance: skewed mix fires the drift alert, recovery resolves it ------

def test_quality_drift_alert_fires_and_resolves_e2e(tmp_path):
    """The hysteresis pair on the quality plane: single-class traffic
    against a uniform baseline pushes the rolling TV score over the
    ceiling (ONE fire), the mix returning to baseline brings the window
    median back under it (ONE resolve) — and the report renders both the
    alert pair and the quality section."""
    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    agg = _windows.WindowAggregator(
        rules=list(_quality.quality_rules()), window=32, emit_every_s=0.0,
    )
    _windows.install(agg)
    tracker = _quality.QualityTracker(
        NUM_CLASSES, baseline=[1.0 / NUM_CLASSES] * NUM_CLASSES,
        window=2 * NUM_CLASSES, emit_every=8,
    )
    # Skewed phase: every prediction lands on one class.
    for _ in range(64):
        tracker.observe(0, 0.9, 0.6, 0.3)
    assert agg.active_alerts() == ["quality_drift_score_p50"]
    # Recovery phase: a balanced round-robin refills the tracker ring
    # with the baseline mix; the score decays and the alert resolves.
    for i in range(2000):
        tracker.observe(i % NUM_CLASSES, 0.9, 0.6, 0.3)
        if not agg.active_alerts():
            break
    assert agg.active_alerts() == []
    obs.close_run()

    events, bad = load_events(run_dir)
    assert bad == 0
    pair = [(e["state"], e["value"]) for e in events
            if e["ev"] == "alert" and e["rule"] ==
            "quality_drift_score_p50"]
    assert [s for s, _ in pair] == ["fire", "resolve"]  # exactly one each
    assert pair[0][1] > 0.25 >= pair[1][1]
    # Healthy confidence never trips the collapse rule.
    assert not any(e["ev"] == "alert" and e["rule"] == "confidence_p50"
                   for e in events)
    rep = build_report_dir(run_dir)
    q = rep["quality"]
    assert q["drift"]["snapshots"] >= 2
    assert q["drift"]["max_score"] > 0.25
    assert q["drift"]["last_score"] < 0.25
    assert q["confidence"]["p50"] == pytest.approx(0.9)
    text = format_report(rep)
    assert "quality:" in text and "drift:" in text


def test_confidence_collapse_alert_without_baseline(tmp_path):
    obs.init_run(str(tmp_path / "run"), process_index=0)
    agg = _windows.WindowAggregator(
        rules=list(_quality.quality_rules(with_drift=False)),
        window=16, emit_every_s=0.0,
    )
    _windows.install(agg)
    tracker = _quality.QualityTracker(NUM_CLASSES)  # no baseline
    for _ in range(16):
        tracker.observe(1, 0.08, 0.01, 3.1)  # near-uniform softmax
    assert agg.active_alerts() == ["confidence_p50"]
    for _ in range(32):
        tracker.observe(1, 0.95, 0.9, 0.1)
    assert agg.active_alerts() == []
    obs.close_run()


# --- the serving path feeds both planes --------------------------------------

def test_service_quality_and_capture_e2e(tmp_path, rng):
    """The wiring acceptance: a real (random-init) service with the
    tracker and the recorder attached — every answered request reaches
    both, rejections reach the ring, and the report folds the capture
    counts without reading the ring."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model
    from featurenet_tpu.serve.service import InferenceService

    run_dir = str(tmp_path / "run")
    obs.init_run(run_dir, process_index=0)
    cfg = get_config("smoke16", data_workers=1)
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, RES, RES, RES, 1), jnp.float32),
        train=False,
    )
    pred = Predictor(
        variables["params"], variables["batch_stats"], cfg, batch=4
    )
    quality = _quality.QualityTracker(
        NUM_CLASSES, baseline=[1.0 / NUM_CLASSES] * NUM_CLASSES,
        window=32, emit_every=4,
    )
    # confidence_floor=1.0 forces every answered request into the ring —
    # the test wants captured == served, not a sampling estimate.
    recorder = FlightRecorder(capture_dir(run_dir), sample=0.0,
                              confidence_floor=1.0)
    service = InferenceService(
        pred, buckets=(1, 4), max_wait_ms=5, queue_limit=64, rules=(),
        quality=quality, recorder=recorder,
    )
    grids = generate_batch(rng, 12, RES)["voxels"]
    futs = [service.submit_voxels(g) for g in grids]
    for fut in futs:
        fut.result(60)
    st = service.drain()
    assert st["quality"]["seen"] == 12
    assert st["quality"]["drift_score"] is not None
    assert st["capture"]["captured"] == 12
    assert not st["capture"]["dark"]
    obs.close_run()

    recs = read_captures(recorder.root)
    assert len(recs) == 12
    assert all(r["reason"] == "low_confidence" for r in recs)
    assert all(0 <= r["label"] < NUM_CLASSES and
               0.0 <= r["confidence"] <= 1.0 for r in recs)
    # Payloads are the served grids, losslessly (order-insensitive:
    # batching may reorder across buckets).
    want = sorted(float((g > 0.5).sum()) for g in grids)
    got = sorted(float(unpack_grid(r["voxels"]).sum()) for r in recs)
    assert got == want
    rep = build_report_dir(run_dir)
    assert rep["quality"]["captures"] == {
        "count": 12, "by_reason": {"low_confidence": 12}}
    assert rep["quality"]["drift"]["snapshots"] == 3
    assert "captures: 12 (low_confidence×12)" in format_report(rep)


def test_service_refuses_quality_on_non_classify():
    from types import SimpleNamespace

    from featurenet_tpu.serve.service import InferenceService

    pred = SimpleNamespace(cfg=SimpleNamespace(task="segment"))
    with pytest.raises(ValueError, match="classify"):
        InferenceService(pred, buckets=(1,),
                         quality=_quality.QualityTracker(2))


# --- cli pin-quality ---------------------------------------------------------

def test_cli_pin_quality_writes_baseline(ckpt_dir, tmp_path, capsys):
    from featurenet_tpu.cli import main as cli_main

    out = str(tmp_path / "quality_baseline.json")
    assert cli_main([
        "pin-quality", "--checkpoint-dir", ckpt_dir,
        "--n", "16", "--batch", "8", "--out", out,
    ]) is None
    printed = json.loads(capsys.readouterr().out)["quality_baseline"]
    assert printed["path"] == out and printed["n"] == 16
    assert printed["top"][0]["p"] > 0
    rec = _quality.load_baseline(out)  # validates shape + normalization
    assert len(rec["dist"]) == NUM_CLASSES
    assert sum(rec["dist"]) == pytest.approx(1.0, abs=0.01)
    assert rec["class_names"] == list(CLASS_NAMES)
    assert rec["source"]["checkpoint_dir"] == ckpt_dir


# --- cli replay: the canary loop ---------------------------------------------

def _record_ring(ckpt_dir, ring: str, grids, falsify: bool = False):
    """Score grids with the pinned checkpoint and write them into a
    capture ring the way a serving process would — optionally with the
    recorded labels falsified (the deliberately-broken-candidate case:
    a candidate that agrees with nothing)."""
    from featurenet_tpu.infer import Predictor

    pred = Predictor.from_checkpoint(ckpt_dir, batch=8)
    labels, probs = pred.predict_voxels(grids)
    rec = FlightRecorder(ring, sample=0.0, confidence_floor=1.1)
    for i in range(len(grids)):
        label = int(labels[i])
        if falsify:
            label = (label + 1) % NUM_CLASSES
        rec.maybe_capture(
            grids[i], f"t{i:04d}", label=label,
            confidence=float(probs[i, labels[i]]), total_ms=5.0,
        )
    rec.close()
    return [int(lb) for lb in labels]


def test_cli_replay_agreement_gate_and_zero_compiles(
    ckpt_dir, tmp_path, rng, capsys
):
    """Acceptance: replaying the ring against the bf16 candidate of the
    same checkpoint clears the 0.967 agreement gate with ZERO
    post-warmup compiles and a clean exit; the same ring with falsified
    labels (a candidate that agrees with nothing) exits 2 and records
    its verdict in the run log."""
    from featurenet_tpu.cli import main as cli_main

    grids = generate_batch(rng, 12, RES)["voxels"]
    ring = str(tmp_path / "ring")
    _record_ring(ckpt_dir, ring, grids)
    assert cli_main([
        "replay", ring, "--checkpoint-dir", ckpt_dir,
        "--precision", "bf16", "--batch", "8",
    ]) is None
    verdict = json.loads(capsys.readouterr().out)["replay"]
    assert verdict["n"] == 12
    assert verdict["agreement"] >= 0.967
    assert verdict["ok"] is True
    assert verdict["post_warmup_compiles"] == 0
    assert verdict["candidate"]["precision"] == "bf16"
    assert verdict["confidence_delta"]["max_abs"] < 0.05

    # The broken candidate: recorded labels disagree everywhere.
    bad = str(tmp_path / "bad_ring")
    _record_ring(ckpt_dir, bad, grids, falsify=True)
    run_dir = str(tmp_path / "run")
    with pytest.raises(SystemExit) as ei:
        cli_main([
            "replay", bad, "--checkpoint-dir", ckpt_dir,
            "--batch", "8", "--run-dir", run_dir,
        ])
    assert ei.value.code == 2
    verdict = json.loads(capsys.readouterr().out)["replay"]
    assert verdict["agreement"] == 0.0 and verdict["ok"] is False
    assert verdict["flips"]  # every disagreement is attributed
    assert sum(verdict["flips"].values()) == 12
    # The verdict is telemetry too: event in the run log, folded by the
    # report's quality section.
    events, _ = load_events(run_dir)
    (rv,) = [e for e in events if e["ev"] == "replay_verdict"]
    assert rv["agreement"] == 0.0 and rv["ok"] is False
    rep = build_report_dir(run_dir)
    assert rep["quality"]["replay"] == {
        "runs": 1, "agreement": 0.0, "n": 12, "ok": False}
    assert "BELOW GATE" in format_report(rep)


def test_cli_replay_refusals(ckpt_dir, tmp_path):
    from featurenet_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="no re-scorable capture"):
        cli_main(["replay", str(tmp_path / "empty"),
                  "--checkpoint-dir", ckpt_dir])
    with pytest.raises(SystemExit, match="min-agreement"):
        cli_main(["replay", str(tmp_path / "empty"),
                  "--checkpoint-dir", ckpt_dir, "--min-agreement", "2"])


# --- dash: quality panel + friendly empty state ------------------------------

def test_dash_empty_state_is_friendly(tmp_path):
    from featurenet_tpu.obs.dash import render_frame

    # A typo'd / never-created run_dir.
    missing = str(tmp_path / "nowhere")
    frame = render_frame(missing, now=T0)
    assert "0 target(s)" in frame
    assert "no such directory" in frame and "fleet scraper" in frame
    # A store directory that exists but was never written.
    empty = str(tmp_path / "run")
    os.makedirs(_tsdb.store_dir(empty))
    frame = render_frame(empty, now=T0)
    assert "0 target(s)" in frame and "no samples yet" in frame


def test_dash_quality_panel_only_when_plane_is_on(tmp_path):
    from featurenet_tpu.obs.dash import render_frame

    run_dir = str(tmp_path / "run")
    store = _tsdb.TimeSeriesStore(_tsdb.store_dir(run_dir))
    for i in range(10):
        t = T0 - 10 + i
        store.append("requests_total", i * 5.0,
                     {"outcome": "served", "replica": "0"}, t=t)
        store.append("serving_ms", 20.0, {"q": "0.99", "replica": "0"},
                     t=t)
    store.close()
    frame = render_frame(run_dir, now=T0)
    assert "confidence p50" not in frame  # plane off: no quality panel
    store = _tsdb.TimeSeriesStore(_tsdb.store_dir(run_dir))
    for i in range(10):
        t = T0 - 10 + i
        store.append("confidence", 0.9 - i * 0.05,
                     {"q": "0.5", "replica": "0"}, t=t)
        store.append("quality_drift_score", 0.1 * i,
                     {"q": "0.5", "replica": "0"}, t=t)
    store.close()
    frame = render_frame(run_dir, now=T0)
    lines = frame.splitlines()
    (head,) = [ln for ln in lines
               if ln.startswith("quality") and "confidence p50" in ln]
    assert "drift p50" in head
    (row,) = [ln for ln in lines[lines.index(head) + 1:]
              if ln.startswith("0 ")]
    assert "0.450" in row and "0.900" in row  # last conf p50, last drift


# --- registries + bench gate wiring ------------------------------------------

def test_quality_plane_registry_wiring():
    """The closed registries every satellite leans on: window metrics,
    exporter families, event schema, lint kinds, bench gate keys."""
    from featurenet_tpu.obs import gates as _gates
    from featurenet_tpu.obs.alerts import WINDOW_METRICS
    from featurenet_tpu.obs.bench_history import _COLUMNS
    from featurenet_tpu.obs.report import (
        KNOWN_EVENT_KINDS,
        REQUIRED_EVENT_FIELDS,
    )
    from featurenet_tpu.serve.metrics import METRIC_NAMES

    windows_new = {"confidence", "confidence_margin",
                   "prediction_entropy", "quality_drift_score"}
    assert windows_new <= set(WINDOW_METRICS)
    assert windows_new <= METRIC_NAMES
    assert {f"{m}_count" for m in windows_new} <= METRIC_NAMES
    assert {"quality_drift", "capture", "replay_verdict"} <= \
        KNOWN_EVENT_KINDS
    assert REQUIRED_EVENT_FIELDS["quality_drift"] == ("score", "n")
    assert REQUIRED_EVENT_FIELDS["capture"] == ("trace", "reason")
    assert REQUIRED_EVENT_FIELDS["replay_verdict"] == \
        ("agreement", "n", "ok")
    assert _gates.DIRECTIONS["quality_overhead_pct"] == "max"
    assert "quality_overhead_pct" in _gates.BENCH_GATE_KEYS
    assert "quality_overhead_pct" in _gates.NOISY_KEY_ABS_SLACK
    assert any(key == "quality_overhead_pct" for key, _, _ in _COLUMNS)
