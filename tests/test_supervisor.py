"""Failure-recovery supervisor: stall detection, crash restart, give-up.

Children are tiny ``python -c`` scripts coordinating through files in
tmp_path, so every scenario runs in seconds with no device and no Trainer.
"""

from __future__ import annotations

import os
import sys

from featurenet_tpu.train.supervisor import child_argv_from_cli, supervise


def _child(code: str) -> list[str]:
    return [sys.executable, "-c", code]


def test_clean_exit_no_restart(tmp_path):
    hb = tmp_path / "hb"
    res = supervise(
        _child("pass"),
        stall_timeout_s=5,
        max_restarts=2,
        heartbeat_file=str(hb),
        poll_s=0.1,
        log=lambda _: None,
    )
    assert res.exit_code == 0
    assert res.restarts == 0
    assert res.stalls == 0


def test_crash_then_success_restarts_once(tmp_path):
    marker = tmp_path / "attempted"
    code = (
        "import os,sys\n"
        f"m={str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m,'w').close(); sys.exit(3)\n"
    )
    res = supervise(
        _child(code),
        stall_timeout_s=5,
        max_restarts=3,
        heartbeat_file=str(tmp_path / "hb"),
        poll_s=0.1,
        log=lambda _: None,
    )
    assert res.exit_code == 0
    assert res.restarts == 1
    assert res.stalls == 0


def test_stalled_child_is_killed_and_restarted(tmp_path):
    marker = tmp_path / "attempted"
    hb = tmp_path / "hb"
    # Attempt 1: beat once, then hang far past the stall timeout.
    # Attempt 2: beat and exit cleanly.
    code = (
        "import os,time\n"
        f"m={str(marker)!r}; hb={str(hb)!r}\n"
        "os.utime(hb, None)\n"
        "if not os.path.exists(m):\n"
        "    open(m,'w').close(); time.sleep(120)\n"
    )
    # Margins sized for a loaded single-core box: the interpreter start of
    # attempt 2 can take seconds, and only the *hang* (attempt 1 sleeping
    # past stall_timeout after its beat) should count as a stall.
    res = supervise(
        _child(code),
        stall_timeout_s=2.5,
        max_restarts=3,
        heartbeat_file=str(hb),
        poll_s=0.2,
        grace_s=30.0,
        log=lambda _: None,
    )
    assert res.exit_code == 0
    assert res.restarts == 1
    assert res.stalls == 1
    # The hung child must actually be gone (killed, not orphaned).
    assert not _any_descendant_running(code)


def _any_descendant_running(code_fragment: str) -> bool:
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                if code_fragment.encode() in f.read():
                    return True
        except OSError:
            continue
    return False


def test_gives_up_after_max_restarts(tmp_path):
    # The child beats first so the crash counts as a *run* failure (startup
    # failures short-circuit after two attempts — tested separately).
    hb = tmp_path / "hb"
    code = (
        "import os, sys, time\n"
        f"hb={str(hb)!r}\n"
        "time.sleep(0.2); os.utime(hb, None); time.sleep(0.2); sys.exit(7)\n"
    )
    res = supervise(
        _child(code),
        stall_timeout_s=5,
        max_restarts=2,
        heartbeat_file=str(hb),
        poll_s=0.05,
        log=lambda _: None,
    )
    assert res.exit_code == 7
    assert res.restarts == 2


def test_child_argv_strips_supervision_flags():
    argv = [
        "train", "--config", "pod64", "--supervise",
        "--stall-timeout", "30", "--max-restarts=9",
        "--checkpoint-dir", "runs/x",
    ]
    child = child_argv_from_cli(argv, "/tmp/hb")
    assert child[:3] == [sys.executable, "-m", "featurenet_tpu.cli"]
    tail = child[3:]
    assert "--supervise" not in tail
    assert "--stall-timeout" not in tail
    assert "30" not in tail
    assert not any(a.startswith("--max-restarts") for a in tail)
    assert tail[-3:] == ["--heartbeat-file", "/tmp/hb", "--supervised-child"]
    assert "--checkpoint-dir" in tail and "runs/x" in tail


def test_startup_failure_is_permanent_after_two_attempts(tmp_path):
    """A child that dies before its first heartbeat is a deterministic
    startup failure — one retry tolerates a transient, two ends the run
    instead of burning max_restarts full JAX inits."""
    attempts = tmp_path / "attempts"
    code = (
        f"import sys; a={str(attempts)!r}\n"
        "open(a, 'a').write('x'); sys.exit(3)\n"
    )
    res = supervise(
        _child(code),
        stall_timeout_s=5,
        max_restarts=10,
        heartbeat_file=str(tmp_path / "hb"),
        poll_s=0.05,
        log=lambda _: None,
    )
    assert res.exit_code == 3
    assert attempts.read_text() == "xx"  # exactly two attempts, not eleven
    assert res.restarts == 1


def test_startup_failure_counter_resets_after_a_beat(tmp_path):
    """Crashes *after* a heartbeat are run failures, not startup failures —
    they keep the full restart budget."""
    attempts = tmp_path / "attempts"
    hb = tmp_path / "hb"
    code = (
        "import os, sys, time\n"
        f"a={str(attempts)!r}; hb={str(hb)!r}\n"
        "n = len(open(a).read()) if os.path.exists(a) else 0\n"
        "open(a, 'a').write('x')\n"
        "time.sleep(0.3); os.utime(hb, None)  # beat\n"
        "sys.exit(0 if n >= 3 else 5)\n"
    )
    res = supervise(
        _child(code),
        stall_timeout_s=10,
        max_restarts=5,
        heartbeat_file=str(hb),
        poll_s=0.05,
        log=lambda _: None,
    )
    assert res.exit_code == 0
    assert res.restarts == 3


def test_deleted_heartbeat_file_is_recreated_not_fatal(tmp_path):
    """An external /tmp cleaner deleting the heartbeat must not kill the
    supervisor (which would orphan the detached child)."""
    import threading
    import time as _time

    hb = tmp_path / "hb"
    code = (
        # create-or-touch (the real Trainer's touch_heartbeat semantics):
        # a bare os.utime would crash if the touch lands in the window
        # between the deleter's unlink and the supervisor's recreation.
        "import os, time\n"
        f"hb={str(hb)!r}\n"
        "for _ in range(20):\n"
        "    open(hb, 'a').close(); os.utime(hb, None); time.sleep(0.1)\n"
    )

    def deleter():
        _time.sleep(0.6)
        try:
            os.unlink(hb)
        except OSError:
            pass

    t = threading.Thread(target=deleter)
    t.start()
    res = supervise(
        _child(code),
        stall_timeout_s=10,
        max_restarts=1,
        heartbeat_file=str(hb),
        poll_s=0.1,
        log=lambda _: None,
    )
    t.join()
    assert res.exit_code == 0
    assert res.restarts == 0


def test_planned_restart_exit_code_is_free(tmp_path):
    """A child exiting RESTART_EXIT_CODE after beating is respawned without
    consuming the restart budget; one that never beat is a failure."""
    from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE

    attempts = tmp_path / "attempts"
    hb = tmp_path / "hb"
    code = (
        "import os, sys, time\n"
        f"a={str(attempts)!r}; hb={str(hb)!r}\n"
        "n = len(open(a).read()) if os.path.exists(a) else 0\n"
        "open(a, 'a').write('x')\n"
        "time.sleep(0.3); os.utime(hb, None)  # beat\n"
        f"sys.exit(0 if n >= 3 else {RESTART_EXIT_CODE})\n"
    )
    res = supervise(
        _child(code),
        stall_timeout_s=10,
        max_restarts=0,  # planned respawns must not need any budget
        heartbeat_file=str(hb),
        poll_s=0.05,
        log=lambda _: None,
    )
    assert res.exit_code == 0
    assert res.restarts == 0
    assert res.planned == 3
    assert attempts.read_text() == "xxxx"


def test_planned_exit_before_first_beat_is_a_failure(tmp_path):
    """RESTART_EXIT_CODE without a heartbeat means the child never made
    progress — treating it as free would loop forever."""
    from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE

    res = supervise(
        _child(f"import sys; sys.exit({RESTART_EXIT_CODE})"),
        stall_timeout_s=5,
        max_restarts=5,
        heartbeat_file=str(tmp_path / "hb"),
        poll_s=0.05,
        log=lambda _: None,
    )
    assert res.exit_code == RESTART_EXIT_CODE
    assert res.planned == 0
    assert res.restarts == 1  # two startup failures -> permanent


def test_cli_refuses_restart_every_without_supervise(tmp_path):
    """--restart-every on an unsupervised train dies with exit 75 at the
    first segment boundary and nothing respawns it; the CLI refuses at
    parse time instead (round-2 advice)."""
    import pytest

    from featurenet_tpu import cli

    with pytest.raises(SystemExit, match="supervise"):
        cli.main(["train", "--config", "smoke16", "--restart-every", "5",
                  "--checkpoint-dir", str(tmp_path / "ck")])


def test_heartbeat_monitor_first_beat_vs_grace_split(tmp_path):
    """The shared state machine (train.heartbeat) both watchers drive:
    before the first beat only the grace window governs; after it, the
    stall timeout does — and `beaten` is sticky."""
    import time as _time

    from featurenet_tpu.train.heartbeat import (
        HeartbeatMonitor,
        touch_heartbeat,
    )

    hb = str(tmp_path / "hb")
    mon = HeartbeatMonitor(hb, stall_timeout_s=0.2, grace_s=0.5)
    mon.reset()
    # Un-beaten within grace: ok, even though the baseline mtime is "old"
    # relative to the (shorter) stall timeout.
    _time.sleep(0.3)
    assert mon.poll() == "ok" and not mon.beaten
    # A beat (newer mtime than the baseline) flips beaten.
    touch_heartbeat(hb)
    assert mon.poll() == "ok" and mon.beaten
    # Silence past the stall timeout after a beat is the stall verdict.
    _time.sleep(0.3)
    assert mon.poll() == "stall"
    assert mon.age_s > 0.2
    # Never-came-up: a fresh monitor past grace with no beat stalls too.
    mon2 = HeartbeatMonitor(hb, stall_timeout_s=60.0, grace_s=0.1)
    mon2.reset()
    _time.sleep(0.25)
    assert mon2.poll() == "stall" and not mon2.beaten


def test_heartbeat_monitor_recreates_deleted_file_and_rechecks(tmp_path):
    import time as _time

    from featurenet_tpu.train.heartbeat import (
        HeartbeatMonitor,
        touch_heartbeat,
    )

    hb = str(tmp_path / "hb")
    mon = HeartbeatMonitor(hb, stall_timeout_s=60.0, grace_s=60.0)
    mon.reset()
    os.unlink(hb)
    # Deletion is never fatal: the file is recreated with a fresh
    # baseline and the verdict stays ok.
    assert mon.poll() == "ok"
    assert os.path.exists(hb)
    # recheck() catches a beat that landed after the last poll — the
    # startup-vs-run-failure discriminator both watchers consult after
    # a child exit.
    assert mon.recheck() is False
    _time.sleep(0.05)
    touch_heartbeat(hb)
    assert mon.recheck() is True
    # And recheck on a deleted file degrades to the sticky value.
    os.unlink(hb)
    assert mon.recheck() is True


def test_supervised_child_passes_restart_every_guard(tmp_path):
    """The supervisor's respawned child carries --restart-every with
    --supervise stripped (child_argv_from_cli re-passes it each spawn) plus
    the --supervised-child marker; the parse-time guard must let it through
    — otherwise every supervised planned-restart run dies at startup.
    Proof of passage: parsing proceeds far enough to reject the bogus
    preset name (KeyError from get_config), i.e. past the guard."""
    import pytest

    from featurenet_tpu import cli

    with pytest.raises(KeyError, match="no-such-preset"):
        cli.main([
            "train", "--config", "no-such-preset", "--restart-every", "5",
            "--checkpoint-dir", str(tmp_path / "ck"), "--supervised-child",
            "--heartbeat-file", str(tmp_path / "hb"),
        ])
