"""Runtime registry + persistent executable cache + int8 serving path.

Acceptance coverage for the compiled-program runtime (ISSUE 6):

- registry enumeration builds every applicable program on CPU;
- cache roundtrip (miss → compile+store → hit) with the guarded load;
- corrupted / stale-fingerprint entries fall back to a FRESH COMPILE with
  a ``cache_reject`` event — the sandbox-abort hazard's required
  degradation, proven with no abort path reachable;
- int8 serving agrees with the fp32 path within the paper's >= 96.7%
  held-out target on synthetic data (deterministic seed → not flaky);
- the Trainer / Predictor / benchmark entry points all build through the
  registry.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.config import get_config
from featurenet_tpu.runtime import (
    ExecutableCache,
    Runtime,
    list_programs,
)
from featurenet_tpu.runtime.cache import PROBE_ENV


@pytest.fixture
def run_events(tmp_path):
    """An active obs run; returns a reader for its event stream."""
    run_dir = tmp_path / "run"
    obs.init_run(str(run_dir), process_index=0)
    yield lambda: [
        json.loads(line)
        for line in open(run_dir / "events.jsonl", encoding="utf-8")
    ]
    obs.close_run()


def _cache_events(events):
    return [
        (e["ev"], e.get("program"), e.get("reason"))
        for e in events
        if e["ev"] in ("cache_hit", "cache_miss", "cache_reject",
                       "program_compile")
    ]


def _zeros_args(prog):
    return jax.tree_util.tree_map(
        lambda a: np.zeros(a.shape, a.dtype), prog.spec.abstract_args
    )


# --- enumeration -------------------------------------------------------------

def test_enumeration_builds_every_applicable_program():
    """The acceptance walk: every program the catalog lists for a config
    (including the k-fused multi step) builds on CPU through warmup()."""
    cfg = get_config("smoke16", steps_per_dispatch=2)
    rt = Runtime(cfg, cache=None)
    names = rt.programs()
    assert names == [r["program"] for r in list_programs(cfg)
                     if r["applicable"]]
    assert "multi_train_step" in names and "serve_int8" in names
    built = rt.warmup()
    assert set(built) == set(names)
    for name, rec in built.items():
        if name == "hbm_train_step":
            continue
        assert rec["source"] == "fresh", (name, rec)
        assert rec["build_s"] > 0


def test_enumeration_gates_inapplicable_programs():
    cfg = get_config("smoke16")  # k=1, no hbm, classify
    rt = Runtime(cfg, cache=None)
    assert "multi_train_step" not in rt.programs()
    assert "hbm_train_step" not in rt.programs()
    with pytest.raises(ValueError, match="not applicable"):
        rt.spec("multi_train_step")
    with pytest.raises(KeyError, match="unknown program"):
        rt.spec("warp_drive")
    seg = get_config("smoke16")
    import dataclasses

    seg = dataclasses.replace(seg, task="segment", num_features=2).validate()
    assert "serve_packed" not in Runtime(seg, cache=None).programs()


def test_hbm_program_requires_resident_arrays():
    cfg = get_config("smoke16", steps_per_dispatch=2)
    rt = Runtime(cfg, cache=None)
    # warmup() must SKIP (not crash on) the resident-shape program when
    # enumerating a hbm config; building it without arrays is an error.
    with pytest.raises(ValueError, match="resident arrays"):
        from featurenet_tpu.runtime.registry import _spec_hbm_train_step

        _spec_hbm_train_step(rt, num_steps=1)


# --- cache roundtrip + guarded degradation -----------------------------------

def test_cache_roundtrip_hit_serves_working_program(tmp_path, run_events):
    """miss → compile+store, then a NEW Runtime loads the entry and the
    deserialized program computes the same answer."""
    cfg = get_config("smoke16")
    cache_dir = str(tmp_path / "exec")
    x = np.random.default_rng(0).random((4, 16, 16, 16, 1)).astype(
        np.float32
    )

    rt = Runtime(cfg, cache=ExecutableCache(cache_dir))
    p1 = rt.build("serve", batch=4)
    assert p1.source == "fresh"
    params = jax.tree_util.tree_map(
        lambda a: np.zeros(a.shape, a.dtype), rt.abstract_state.params
    )
    stats = jax.tree_util.tree_map(
        lambda a: np.zeros(a.shape, a.dtype), rt.abstract_state.batch_stats
    )
    ref = np.asarray(p1(params, stats, x))

    rt2 = Runtime(cfg, cache=ExecutableCache(cache_dir))
    p2 = rt2.build("serve", batch=4)
    assert p2.source == "cache"
    np.testing.assert_allclose(np.asarray(p2(params, stats, x)), ref)

    kinds = _cache_events(run_events())
    assert ("cache_miss", "serve", None) in kinds
    assert ("cache_hit", "serve", None) in kinds
    # the hit skipped XLA: exactly one compile for the two builds
    assert sum(k[0] == "program_compile" for k in kinds) == 1


def test_corrupted_entry_degrades_to_fresh_compile(tmp_path, run_events):
    """The load-bearing hazard path: a torn cache entry must emit
    cache_reject and compile fresh — never crash, never abort."""
    cfg = get_config("smoke16")
    cache_dir = str(tmp_path / "exec")
    rt = Runtime(cfg, cache=ExecutableCache(cache_dir))
    rt.build("serve", batch=4)
    entry = [f for f in os.listdir(cache_dir) if f.endswith(".jexec")]
    assert len(entry) == 1
    path = os.path.join(cache_dir, entry[0])
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 3)

    rt2 = Runtime(cfg, cache=ExecutableCache(cache_dir))
    p = rt2.build("serve", batch=4)
    assert p.source == "fresh"  # degraded, and the program still works
    out = p(*_zeros_args(p))
    assert np.asarray(out).shape == (4, 24)
    rejects = [k for k in _cache_events(run_events())
               if k[0] == "cache_reject"]
    # Truncation into the payload surfaces at the subprocess probe; into
    # the header, at the file parse — both are the guarded degradation.
    assert rejects and rejects[0][2].split(":")[0] in (
        "corrupt_entry", "probe_failed", "deserialize_error"
    )
    # the fresh compile REPLACED the torn entry: next build hits
    p3 = Runtime(cfg, cache=ExecutableCache(cache_dir)).build(
        "serve", batch=4
    )
    assert p3.source == "cache"
    # Header-level corruption (torn magic/length) is caught before any
    # subprocess spawns:
    with open(path, "r+b") as fh:
        fh.truncate(10)
    p4 = Runtime(cfg, cache=ExecutableCache(cache_dir)).build(
        "serve", batch=4
    )
    assert p4.source == "fresh"
    assert any(r[2].startswith("corrupt_entry")
               for r in _cache_events(run_events())
               if r[0] == "cache_reject")


def test_stale_fingerprint_rejects_and_recompiles(tmp_path, run_events):
    """A jax upgrade / arch change lands on the same filename with a
    different fingerprint: reject + overwrite, never a silent load."""
    from featurenet_tpu.runtime.cache import MAGIC, _read_entry

    cfg = get_config("smoke16")
    cache_dir = str(tmp_path / "exec")
    Runtime(cfg, cache=ExecutableCache(cache_dir)).build("serve", batch=4)
    entry = [f for f in os.listdir(cache_dir) if f.endswith(".jexec")][0]
    path = os.path.join(cache_dir, entry)
    header, payload = _read_entry(path)
    header["fingerprint"] = "deadbeef" * 8
    raw = json.dumps(header).encode()
    with open(path, "wb") as fh:
        fh.write(MAGIC)
        fh.write(len(raw).to_bytes(8, "little"))
        fh.write(raw)
        fh.write(payload)

    p = Runtime(cfg, cache=ExecutableCache(cache_dir)).build(
        "serve", batch=4
    )
    assert p.source == "fresh"
    rejects = [k for k in _cache_events(run_events())
               if k[0] == "cache_reject"]
    assert ("cache_reject", "serve", "stale_fingerprint") in rejects


def test_probe_reject_env_gate(tmp_path, monkeypatch, run_events):
    """FEATURENET_EXEC_CACHE_PROBE=reject: the env gate refuses every
    load (known-bad sandbox mode) but stores keep working, and the build
    degrades to fresh with the reject recorded."""
    cfg = get_config("smoke16")
    cache_dir = str(tmp_path / "exec")
    monkeypatch.setenv(PROBE_ENV, "reject")
    rt = Runtime(cfg, cache=ExecutableCache(cache_dir))
    assert rt.build("serve", batch=4).source == "fresh"
    assert any(f.endswith(".jexec") for f in os.listdir(cache_dir))
    p2 = Runtime(cfg, cache=ExecutableCache(cache_dir)).build(
        "serve", batch=4
    )
    assert p2.source == "fresh"
    assert ("cache_reject", "serve", "probe_rejected") in _cache_events(
        run_events()
    )
    with pytest.raises(ValueError, match="probe mode"):
        ExecutableCache(str(tmp_path / "x"), probe="yolo")


def test_exec_cache_separates_train_precisions(tmp_path, run_events):
    """Acceptance (ISSUE 10): the fp32 and bf16_master train executables
    have IDENTICAL avals (fp32 masters in and out) — only the policy in
    the fingerprint separates them. A bf16-master world must never load
    an fp32 program: the cross-precision build is a fresh compile (its
    own entry file, no stale-reject eviction), and each mode then hits
    its OWN entry."""
    cache_dir = str(tmp_path / "exec")
    cfg32 = get_config("smoke16")
    cfg16 = get_config("smoke16", train_precision="bf16_master")

    p32 = Runtime(cfg32, cache=ExecutableCache(cache_dir)).build(
        "train_step"
    )
    assert p32.source == "fresh" and p32.precision == "fp32"
    p16 = Runtime(cfg16, cache=ExecutableCache(cache_dir)).build(
        "train_step"
    )
    assert p16.source == "fresh" and p16.precision == "bf16_master"
    # Both modes re-load their own entries — two files coexist.
    assert Runtime(cfg32, cache=ExecutableCache(cache_dir)).build(
        "train_step").source == "cache"
    assert Runtime(cfg16, cache=ExecutableCache(cache_dir)).build(
        "train_step").source == "cache"
    entries = [f for f in os.listdir(cache_dir) if f.endswith(".jexec")]
    assert len(entries) == 2
    kinds = _cache_events(run_events())
    # No cross-precision hit and no stale-fingerprint eviction anywhere:
    # exactly two misses, two hits, two compiles, zero rejects.
    assert sum(k[0] == "cache_miss" for k in kinds) == 2
    assert sum(k[0] == "cache_hit" for k in kinds) == 2
    assert sum(k[0] == "program_compile" for k in kinds) == 2
    assert not [k for k in kinds if k[0] == "cache_reject"]


def test_cli_programs_enumerates_precision_variants(capsys):
    """`cli programs --train-precision bf16_master` lists the train
    programs (init included — its compiled output treedef bakes the
    policy) under the policy while serving/eval stay fp32/int8."""
    from featurenet_tpu.cli import main

    main(["programs", "--config", "smoke16",
          "--train-precision", "bf16_master"])
    rows = {r["program"]: r for r in (
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    )}
    for name in ("init", "train_step", "multi_train_step",
                 "hbm_train_step"):
        assert rows[name]["precision"] == "bf16_master"
    assert rows["eval_step"]["precision"] == "fp32"
    assert rows["serve"]["precision"] == "fp32"
    assert rows["serve_int8"]["precision"] == "int8"


def test_no_cache_no_files(tmp_path):
    """Default config (no exec_cache_dir): nothing serialized anywhere."""
    cfg = get_config("smoke16")
    rt = Runtime(cfg, cache=None)
    assert rt.cache is None
    from featurenet_tpu.runtime import cache_from_config

    assert cache_from_config(cfg) is None
    cfg2 = get_config("smoke16",
                      exec_cache_dir=str(tmp_path / "from_cfg"))
    assert cache_from_config(cfg2) is not None
    assert Runtime(cfg2).cache is not None


# --- serving precision ladder (bf16 working-copy rung) -----------------------

def test_registry_precisions_mirror_serve_precisions():
    """registry.PRECISIONS is a literal mirror of
    train.precision.SERVE_PRECISIONS (importing it would cycle through
    train/__init__) — pin them equal so the mirror cannot drift."""
    from featurenet_tpu.runtime.registry import PRECISIONS
    from featurenet_tpu.train.precision import SERVE_PRECISIONS

    assert PRECISIONS == SERVE_PRECISIONS


def test_bf16_serving_agreement_meets_paper_target():
    """The precision-agnostic agreement gate (ISSUE 12 acceptance): bf16
    serving must agree with the fp32 forward on held-out-style parts at
    the paper's >= 96.7% bar, through the same gate the int8 rung uses —
    and the bf16 Predictor's actual predictions must match the fp32
    Predictor's labels on the reference inputs."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.quantize import PAPER_TOP1_TARGET

    cfg = get_config("smoke16")
    rt = Runtime(cfg, cache=None)
    state = rt.build("init")(jax.random.key(0))
    bf = Predictor(state.params, state.batch_stats, cfg, batch=8,
                   precision="bf16")
    assert bf.precision == "bf16"
    agreement = bf.agreement(n=48, seed=0)
    assert agreement >= PAPER_TOP1_TARGET, (
        f"bf16 agreement {agreement} < paper target"
    )
    # And on real predictions: same labels as the fp32 path.
    grids = generate_batch(
        np.random.default_rng(1), 6, cfg.resolution
    )["voxels"]
    fp = Predictor(state.params, state.batch_stats, cfg, batch=8)
    lf, pf = fp.predict_voxels(grids)
    lb, pb = bf.predict_voxels(grids)
    assert (lf == lb).mean() >= PAPER_TOP1_TARGET
    np.testing.assert_allclose(pf, pb, atol=0.05)  # probs move, argmax not


def test_predictor_precision_defaults_to_config_serve_precision():
    """Predictor(precision=None) serves Config.serve_precision — the
    config is the fleet-wide source; an explicit argument still wins."""
    cfg = get_config("smoke16", serve_precision="bf16")
    rt = Runtime(cfg, cache=None)
    state = rt.build("init")(jax.random.key(0))
    from featurenet_tpu.infer import Predictor

    p = Predictor(state.params, state.batch_stats, cfg, batch=4)
    assert p.precision == "bf16"
    assert p.program_for(4).name == "serve_bf16"
    explicit = Predictor(state.params, state.batch_stats, cfg, batch=4,
                         precision="fp32")
    assert explicit.precision == "fp32"


def test_cli_programs_serve_precision_variants(capsys):
    """`cli programs` renders the serve-precision variants — serve /
    serve_bf16 / serve_int8 and their packed forms — with the precision
    column, and --serve-precision flips eval_step's variant the way
    --train-precision flips the train programs'."""
    from featurenet_tpu.cli import main

    main(["programs", "--config", "smoke16", "--serve-precision", "bf16"])
    rows = {r["program"]: r for r in (
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    )}
    assert rows["eval_step"]["precision"] == "bf16"
    assert rows["serve"]["precision"] == "fp32"
    assert rows["serve_bf16"]["precision"] == "bf16"
    assert rows["serve_packed_bf16"]["precision"] == "bf16"
    assert rows["serve_int8"]["precision"] == "int8"
    assert rows["serve_packed_int8"]["precision"] == "int8"
    # The train programs are untouched by the serving policy.
    assert rows["train_step"]["precision"] == "fp32"


def test_eval_step_serve_precision_no_cross_precision_cache_hit(
        tmp_path, run_events):
    """eval_step's serving precision lands in the exec-cache fingerprint
    AND the entry filename exactly as train_precision does: two configs
    differing only in serve_precision sharing one cache dir coexist —
    two misses, two compiles, two entries, zero rejects, and never a
    cross-precision hit."""
    cache_dir = str(tmp_path / "exec")
    for prec in ("fp32", "bf16"):
        cfg = get_config("smoke16", serve_precision=prec)
        rt = Runtime(cfg, cache=ExecutableCache(cache_dir))
        prog = rt.build("eval_step")
        assert prog.source == "fresh"
        assert prog.precision == prec
    entries = [f for f in os.listdir(cache_dir) if f.endswith(".jexec")]
    assert len(entries) == 2
    kinds = _cache_events(run_events())
    assert sum(k[0] == "cache_miss" for k in kinds) == 2
    assert sum(k[0] == "program_compile" for k in kinds) == 2
    assert not [k for k in kinds if k[0] == "cache_reject"]


# --- int8 serving path -------------------------------------------------------

def test_quantize_per_channel_shapes_and_error_bound():
    from featurenet_tpu.runtime.quantize import (
        dequantize_tree,
        quantize_tree,
    )

    rng = np.random.default_rng(0)
    params = {
        "Conv_0": {"kernel": rng.normal(0, 0.1, (3, 3, 3, 1, 8))
                   .astype(np.float32) * np.logspace(-2, 0, 8),
                   "bias": rng.normal(size=(8,)).astype(np.float32)},
    }
    q, s = quantize_tree(params)
    assert q["Conv_0"]["kernel"].dtype == np.int8
    assert s["Conv_0"]["kernel"].shape == (1, 1, 1, 1, 8)  # per-channel
    assert q["Conv_0"]["bias"].dtype == np.float32  # vectors pass through
    deq = dequantize_tree(q, s)
    np.testing.assert_array_equal(deq["Conv_0"]["bias"],
                                  params["Conv_0"]["bias"])
    # per-channel symmetric int8: error <= scale/2 per element, even with
    # channel dynamic ranges spanning two orders of magnitude
    err = np.abs(np.asarray(deq["Conv_0"]["kernel"])
                 - params["Conv_0"]["kernel"])
    assert (err <= np.asarray(s["Conv_0"]["kernel"]) / 2 + 1e-7).all()


def test_int8_serving_agreement_meets_paper_target():
    """fp32 vs int8 top-1 agreement on synthetic held-out-style parts:
    must clear the paper's 96.7% target (deterministic seeds)."""
    from featurenet_tpu.infer import Predictor

    cfg = get_config("smoke16")
    rt = Runtime(cfg, cache=None)
    state = rt.build("init")(jax.random.key(0))
    p = Predictor(state.params, state.batch_stats, cfg, batch=8,
                  precision="int8")
    agreement = p.int8_agreement(n=48, seed=0)
    assert agreement >= 0.967, f"int8 agreement {agreement} < paper target"


def test_int8_predictor_matches_fp32_predictions():
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.infer import Predictor

    cfg = get_config("smoke16")
    rt = Runtime(cfg, cache=None)
    state = rt.build("init")(jax.random.key(0))
    grids = generate_batch(
        np.random.default_rng(1), 6, cfg.resolution
    )["voxels"]
    fp = Predictor(state.params, state.batch_stats, cfg, batch=8)
    i8 = Predictor(state.params, state.batch_stats, cfg, batch=8,
                   precision="int8")
    assert fp.precision == "fp32" and i8.precision == "int8"
    lf, pf = fp.predict_voxels(grids)
    l8, p8 = i8.predict_voxels(grids)
    assert (lf == l8).mean() >= 0.967
    np.testing.assert_allclose(pf, p8, atol=0.05)  # probs move, argmax not
    with pytest.raises(ValueError, match="precision"):
        Predictor(state.params, state.batch_stats, cfg, precision="fp8")


@pytest.mark.slow
def test_int8_serving_measurement_runs():
    """Full converged-slope protocol over the int8 serving program (the
    ≥3 s measurement windows dominate); the program itself builds in the
    fast tier via enumeration + the Predictor int8 tests."""
    from featurenet_tpu.benchmark import measure_inference

    r = measure_inference(get_config("smoke16"), batch_per_chip=4,
                          repeats=1, measure=2, precision="int8")
    assert r["precision"] == "int8"
    assert r["inferences_per_sec_per_chip"] > 0


# --- entry points build through the registry ---------------------------------

def test_trainer_builds_through_registry(tmp_path):
    from featurenet_tpu.runtime.registry import CompiledProgram
    from featurenet_tpu.train.loop import Trainer

    cfg = get_config("smoke16", total_steps=2, eval_batches=1)
    tr = Trainer(cfg)
    assert tr.rt.mesh is tr.mesh and tr.rt.model is tr.model
    assert tr._programs == {}  # lazy: nothing compiled before dispatch
    tr.run()  # two steps end-to-end through the registry programs
    assert isinstance(tr._program("train_step"), CompiledProgram)
    assert isinstance(tr._program("eval_step"), CompiledProgram)
    # Memoized per (name, kwargs): exactly these two programs were built.
    assert {name for name, _ in tr._programs} == {"train_step", "eval_step"}


@pytest.mark.parametrize("precision,program", [
    ("fp32", "serve_packed"),
    ("bf16", "serve_packed_bf16"),
])
def test_ttfs_warm_start_hits_cache(tmp_path, precision, program):
    """measure_ttfs: the warm build must actually come from the cache
    (this is the headline the bench pins) — per serving precision, since
    a fleet replica warms ONE precision's ladder (the bf16 bucket ladder
    is what a bf16 fleet actually deserializes)."""
    from featurenet_tpu.benchmark import measure_ttfs

    t = measure_ttfs(get_config("smoke16"), batch_per_chip=4,
                     precision=precision)
    assert t["program"] == program
    assert t["precision"] == precision
    assert t["ttfs_cold_s"] > 0 and t["ttfs_warm_s"] > 0
    assert t["warm_source"] == "cache"


def test_cli_programs_lists_and_warms(tmp_path, capsys):
    from featurenet_tpu.cli import main

    main(["programs", "--config", "smoke16"])
    rows = [json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()]
    names = {r["program"] for r in rows}
    assert {"train_step", "serve", "serve_int8"} <= names
    assert all({"doc", "precision", "applicable"} <= set(r) for r in rows)

    cache_dir = str(tmp_path / "exec")
    main(["programs", "--config", "smoke16", "--warm",
          "--exec-cache-dir", cache_dir])
    out = capsys.readouterr().out.strip().splitlines()
    warm = json.loads(out[-1])["warmup"]
    assert warm["serve"]["source"] == "fresh"
    assert any(f.endswith(".jexec") for f in os.listdir(cache_dir))
