"""Offline cache pipeline: STL tree → npz cache → file-backed dataset."""

import json
import os

import numpy as np
import pytest

from featurenet_tpu.data.mesh_primitives import mesh_box, mesh_cylinder
from featurenet_tpu.data.offline import (
    VoxelCacheDataset,
    build_cache,
    export_synthetic_cache,
)
from featurenet_tpu.data.stl import save_stl


@pytest.fixture
def stl_tree(tmp_path):
    """A 2-class STL tree (boxy / roundy) in the reference benchmark layout."""
    rng = np.random.default_rng(0)
    for cls, maker in (("boxy", mesh_box), ("roundy", mesh_cylinder)):
        d = tmp_path / "stl" / cls
        d.mkdir(parents=True)
        for i in range(4):
            if maker is mesh_box:
                lo = rng.uniform(0.1, 0.3, 3)
                hi = rng.uniform(0.6, 0.9, 3)
                tris = mesh_box(lo, hi)
            else:
                tris = mesh_cylinder(radius=float(rng.uniform(0.15, 0.3)))
            save_stl(str(d / f"part{i}.stl"), tris)
    return str(tmp_path / "stl")


def test_build_cache_from_stl_tree(stl_tree, tmp_path):
    out = str(tmp_path / "cache")
    index = build_cache(stl_tree, out, resolution=16)
    assert index["classes"] == ["boxy", "roundy"]
    assert index["counts"] == {"boxy": 4, "roundy": 4}
    # Storage is the bit-packed wire format, one .npy per class.
    packed = np.load(os.path.join(out, "boxy.npy"))
    assert packed.shape == (4, 16, 16, 2)
    assert packed.dtype == np.uint8
    # A filled box occupies a solid chunk of the grid.
    assert np.unpackbits(packed[0], axis=-1).mean() > 0.1
    # Provenance sidecar lists the source files in order.
    files = json.load(open(os.path.join(out, "boxy.files.json")))
    assert files == [f"part{i}.stl" for i in range(4)]
    idx = json.load(open(os.path.join(out, "index.json")))
    assert idx["resolution"] == 16
    assert idx["storage"] == "packed"


def test_build_cache_parallel_is_bit_identical(stl_tree, tmp_path):
    """Process-pool ingest must produce byte-identical caches: the pool
    preserves file order and per-file rasterization is independent."""
    serial = str(tmp_path / "serial")
    par = str(tmp_path / "par")
    build_cache(stl_tree, serial, resolution=16, workers=1)
    build_cache(stl_tree, par, resolution=16, workers=2)
    for cls in ("boxy", "roundy"):
        np.testing.assert_array_equal(
            np.load(os.path.join(serial, f"{cls}.npy")),
            np.load(os.path.join(par, f"{cls}.npy")),
        )


def test_cache_dataset_contract(stl_tree, tmp_path):
    out = str(tmp_path / "cache")
    build_cache(stl_tree, out, resolution=16)
    ds = VoxelCacheDataset(out, global_batch=4, split="train",
                           test_fraction=0.25)
    b = next(iter(ds))
    # Classify wire format: bit-packed voxels, no per-voxel target.
    assert b["voxels"].shape == (4, 16, 16, 2)
    assert b["voxels"].dtype == np.uint8
    assert b["label"].shape == (4,)
    assert "seg" not in b
    # Unpacking recovers a plausible solid-part occupancy.
    unpacked = np.unpackbits(b["voxels"], axis=-1)
    assert unpacked.shape == (4, 16, 16, 16)
    assert unpacked.mean() > 0.05


def test_split_disjoint_and_complete(stl_tree, tmp_path):
    out = str(tmp_path / "cache")
    build_cache(stl_tree, out, resolution=16)
    tr = VoxelCacheDataset(out, global_batch=4, split="train", test_fraction=0.25)
    te = VoxelCacheDataset(out, global_batch=4, split="test", test_fraction=0.25)
    assert len(tr) + len(te) == 8
    assert len(te) > 0


def test_export_synthetic_cache_roundtrip(tmp_path):
    out = str(tmp_path / "syn")
    index = export_synthetic_cache(out, per_class=2, resolution=16, seed=7)
    assert len(index["classes"]) == 24
    ds = VoxelCacheDataset(out, global_batch=8, split="train",
                           test_fraction=0.0)
    assert len(ds) == 48
    b = next(iter(ds))
    assert set(np.unique(b["label"])).issubset(set(range(24)))
    # Determinism: re-export with same seed gives identical packed grids.
    out2 = str(tmp_path / "syn2")
    export_synthetic_cache(out2, per_class=2, resolution=16, seed=7)
    np.testing.assert_array_equal(
        np.load(os.path.join(out, "o_ring.npy")),
        np.load(os.path.join(out2, "o_ring.npy")),
    )


def test_augmented_stream_preserves_content(tmp_path):
    """Pose augmentation permutes voxels (same occupancy count, same label)
    and is deterministic under the stream seed."""
    out = str(tmp_path / "syn")
    export_synthetic_cache(out, per_class=2, resolution=16, seed=5)
    plain = VoxelCacheDataset(out, global_batch=8, split="train",
                              test_fraction=0.0, seed=11, augment=False)
    aug = VoxelCacheDataset(out, global_batch=8, split="train",
                            test_fraction=0.0, seed=11, augment=True)
    bp, ba = next(iter(plain)), next(iter(aug))
    # Rotation is volume-preserving: per-sample occupancy counts match
    # (popcount of the packed bytes).
    count = lambda b: np.unpackbits(b["voxels"], axis=-1).sum(axis=(1, 2, 3))
    np.testing.assert_array_equal(count(bp), count(ba))
    # Augmentation consumes extra RNG draws, so the *sample index* streams
    # diverge after batch 1 — only compare labels of the first batch.
    np.testing.assert_array_equal(bp["label"], ba["label"])
    # Deterministic: same seed → identical augmented batch.
    ba2 = next(iter(VoxelCacheDataset(out, global_batch=8, split="train",
                                      test_fraction=0.0, seed=11, augment=True)))
    np.testing.assert_array_equal(ba["voxels"], ba2["voxels"])


def test_epoch_batches_deterministic(tmp_path):
    out = str(tmp_path / "syn")
    export_synthetic_cache(out, per_class=2, resolution=16, seed=1)
    ds = VoxelCacheDataset(out, global_batch=8, split="train", test_fraction=0.0)
    e1 = [b["label"] for b in ds.epoch_batches(8)]
    e2 = [b["label"] for b in ds.epoch_batches(8)]
    assert len(e1) == 6  # 48 samples / 8
    for a, b in zip(e1, e2):
        np.testing.assert_array_equal(a, b)


def test_trainer_from_cache_with_per_class_metrics(tmp_path):
    """Cache-backed Trainer: exact test-split eval with confusion matrix."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    out = str(tmp_path / "syn")
    export_synthetic_cache(out, per_class=6, resolution=16, seed=3)
    cfg = get_config(
        "smoke16",
        total_steps=20,
        eval_every=20,
        log_every=10,
        checkpoint_every=10**9,
        data_cache=out,
        test_fraction=0.3,
        global_batch=16,
        data_workers=1,
    )
    tr = Trainer(cfg)
    tr.run()
    ev = tr.evaluate()
    assert "per_class_accuracy" in ev and len(ev["per_class_accuracy"]) == 24
    conf = np.asarray(ev["confusion"])
    assert conf.shape == (24, 24)
    # Every held-out sample counts exactly once per epoch pass (the final
    # partial batch is padded with mask=0 rows).
    n_eval = conf.sum()
    assert n_eval == len(tr.eval_data)
    assert ev["mean_class_accuracy"] >= 0.0


def test_seg_cache_roundtrip_and_dataset(tmp_path):
    """Segmentation cache: export, wire contract, joint pose augmentation."""
    from featurenet_tpu.data.offline import SegCacheDataset, export_seg_cache

    out = str(tmp_path / "segc")
    index = export_seg_cache(out, num_parts=24, resolution=16,
                             num_features=2, shard_size=10, seed=4)
    assert sum(s["count"] for s in index["shards"]) == 24
    ds = SegCacheDataset(out, global_batch=8, split="train", test_fraction=0.25)
    b = next(iter(ds))
    assert b["voxels"].shape == (8, 16, 16, 2)  # bit-packed wire
    assert b["voxels"].dtype == np.uint8
    assert b["seg"].shape == (8, 16, 16, 16)
    assert b["seg"].dtype == np.int8
    # Per-voxel truth is real: some feature voxels present, ids in range.
    assert b["seg"].max() >= 1 and b["seg"].min() >= 0
    # Augmentation rotates voxels and seg jointly: feature voxels stay
    # carved out of the part (seg>0 implies voxel==0 post-rotation too).
    aug = SegCacheDataset(out, global_batch=8, split="train",
                          test_fraction=0.25, augment=True, seed=9)
    ba = next(iter(aug))
    unpacked = np.unpackbits(ba["voxels"], axis=-1)
    assert not np.any((ba["seg"] > 0) & (unpacked > 0))
    # Splits are disjoint and complete.
    te = SegCacheDataset(out, global_batch=8, split="test", test_fraction=0.25)
    assert len(ds) + len(te) == 24


def test_trainer_segment_from_cache(tmp_path):
    """Cache-backed segmentation training end to end with exact IoU eval."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.data.offline import export_seg_cache
    from featurenet_tpu.train import Trainer

    out = str(tmp_path / "segc")
    export_seg_cache(out, num_parts=16, resolution=16, num_features=2,
                     shard_size=8, seed=2)
    cfg = get_config(
        "seg64", resolution=16, global_batch=8, total_steps=6,
        log_every=3, eval_every=10**9, checkpoint_every=10**9,
        data_cache=out, data_workers=1, seg_features=(8, 16),
    )
    tr = Trainer(cfg)
    last = tr.run()
    assert np.isfinite(last["loss"])
    ev = tr.evaluate()
    assert "mean_iou" in ev and 0.0 <= ev["mean_iou"] <= 1.0


def test_build_cache_orders_known_classes_canonically(tmp_path):
    """Class ids are positional; known names must take CLASS_NAMES order
    (alphabetical ordering permuted labels: cache-trained checkpoints then
    mapped logits to the wrong names in infer — the bug this pins down)."""
    from featurenet_tpu.data.mesh_primitives import mesh_box
    from featurenet_tpu.data.stl import save_stl
    from featurenet_tpu.data.synthetic import CLASS_NAMES

    # Alphabetically, blind_hole < o_ring; canonically o_ring comes first.
    chosen = ["o_ring", "blind_hole", "chamfer"]
    assert sorted(chosen) != [
        c for c in CLASS_NAMES if c in chosen
    ], "pick classes whose two orders differ or the test is vacuous"
    for cls in chosen + ["zz_custom"]:
        d = tmp_path / "stl" / cls
        d.mkdir(parents=True)
        save_stl(str(d / "p.stl"), mesh_box((0.2,) * 3, (0.8,) * 3))
    index = build_cache(str(tmp_path / "stl"), str(tmp_path / "cache"),
                        resolution=16)
    assert index["classes"] == [
        c for c in CLASS_NAMES if c in chosen
    ] + ["zz_custom"]
    # Even in this PARTIAL tree, every known class trains under its
    # canonical id (what the Predictor will report), not its position;
    # the unknown class gets the first id past the canonical block.
    assert index["label_ids"] == {
        **{c: CLASS_NAMES.index(c) for c in chosen},
        "zz_custom": len(CLASS_NAMES),
    }
    ds = VoxelCacheDataset(
        str(tmp_path / "cache"), global_batch=8, split="train",
        test_fraction=0.0,
    )
    want = {CLASS_NAMES.index(c) for c in chosen} | {len(CLASS_NAMES)}
    assert set(ds.labels.tolist()) == want


def test_trainer_refuses_out_of_range_cache_labels(stl_tree, tmp_path):
    """boxy/roundy are non-canonical names → ids 24/25; a 24-way head must
    refuse them up front instead of training them silently wrong."""
    import pytest

    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    out = str(tmp_path / "cache")
    build_cache(stl_tree, out, resolution=16)
    cfg = get_config("smoke16", global_batch=8, data_cache=out,
                     total_steps=1, data_workers=1)
    with pytest.raises(ValueError, match="label id 2[45]"):
        Trainer(cfg)


def test_legacy_cache_with_permuting_order_is_refused(tmp_path):
    """Pre-label_ids caches whose positional order disagrees with the
    canonical ids must be refused: positional labels would silently permute
    (eval self-consistent, infer wrong) — the round-1 disease."""
    out = str(tmp_path / "cache")
    export_synthetic_cache(out, per_class=2, resolution=16)
    with open(os.path.join(out, "index.json")) as fh:
        index = json.load(fh)
    del index["label_ids"]  # simulate a pre-fix cache…
    index["classes"] = index["classes"][::-1]  # …stored in a permuted order
    with open(os.path.join(out, "index.json"), "w") as fh:
        json.dump(index, fh)
    with pytest.raises(ValueError, match="permute"):
        VoxelCacheDataset(out, global_batch=4, split="train")


def test_legacy_cache_in_canonical_order_still_loads(tmp_path):
    """Old caches whose order already matches the canonical ids keep
    working via the positional fallback."""
    out = str(tmp_path / "cache")
    export_synthetic_cache(out, per_class=2, resolution=16)
    with open(os.path.join(out, "index.json")) as fh:
        index = json.load(fh)
    del index["label_ids"]
    with open(os.path.join(out, "index.json"), "w") as fh:
        json.dump(index, fh)
    ds = VoxelCacheDataset(out, global_batch=4, split="train")
    from featurenet_tpu.data.synthetic import CLASS_NAMES
    assert ds.labels.max() == len(CLASS_NAMES) - 1


def test_sharded_epoch_batches_partition_exactly(tmp_path):
    """Multi-host eval sharding: the union of all shards' masked samples is
    the full split, each sample exactly once, and every shard emits the
    same number of batches (hosts dispatch the eval step in lockstep)."""
    out = str(tmp_path / "cache")
    export_synthetic_cache(out, per_class=3, resolution=16)
    ds = VoxelCacheDataset(out, global_batch=4, split="test")
    full = []
    for b in ds.epoch_batches(4):
        full.extend(b["label"][b["mask"] > 0].tolist())
    for shards in (2, 3):
        seen = []
        counts = []
        for sid in range(shards):
            n = 0
            for b in ds.epoch_batches(4, num_shards=shards, shard_id=sid):
                seen.extend(b["label"][b["mask"] > 0].tolist())
                n += 1
            counts.append(n)
        assert len(set(counts)) == 1, counts  # lockstep
        assert sorted(seen) == sorted(full)


def test_packed_cache_is_memmapped_not_materialized(tmp_path):
    """v2 caches open as read-only memmaps: training from a reference-scale
    128³ cache must not load it all (round-2 verdict item 5). The gather
    copies out only the drawn rows."""
    out = str(tmp_path / "syn")
    export_synthetic_cache(out, per_class=2, resolution=16)
    ds = VoxelCacheDataset(out, global_batch=4, split="train",
                           test_fraction=0.0)
    assert all(isinstance(a, np.memmap) for a in ds._packed)
    b = next(iter(ds))
    assert isinstance(b["voxels"], np.ndarray)
    assert not isinstance(b["voxels"], np.memmap)  # a real copy left mmap


def test_seg_packed_cache_is_memmapped(tmp_path):
    from featurenet_tpu.data.offline import SegCacheDataset, export_seg_cache

    out = str(tmp_path / "segc")
    export_seg_cache(out, num_parts=8, resolution=16, num_features=2,
                     shard_size=4, seed=0)
    ds = SegCacheDataset(out, global_batch=4, split="train",
                         test_fraction=0.25)
    assert all(isinstance(a, np.memmap) for a in ds._voxels)
    assert all(isinstance(a, np.memmap) for a in ds._seg)


def test_legacy_unpacked_npz_cache_still_loads(tmp_path):
    """Round-1/2 caches stored unpacked uint8 voxels in deflated npz; the
    reader must keep loading them (packed once at open) and emit batches
    identical to packing the stored grids."""
    from featurenet_tpu.data.synthetic import CLASS_NAMES

    out = tmp_path / "legacy"
    out.mkdir()
    rng = np.random.default_rng(3)
    stored = {}
    for cls in CLASS_NAMES[:2]:
        grids = (rng.random((3, 16, 16, 16)) < 0.3).astype(np.uint8)
        stored[cls] = grids
        np.savez_compressed(out / f"{cls}.npz", voxels=grids,
                            files=np.asarray(["a", "b", "c"]))
    index = {
        "resolution": 16,
        "classes": list(CLASS_NAMES[:2]),
        "counts": {c: 3 for c in CLASS_NAMES[:2]},
        "label_ids": {c: CLASS_NAMES.index(c) for c in CLASS_NAMES[:2]},
    }  # no "storage" key — the legacy layout
    with open(out / "index.json", "w") as fh:
        json.dump(index, fh)
    ds = VoxelCacheDataset(str(out), global_batch=6, split="train",
                           test_fraction=0.0)
    got = {}
    for b in ds.epoch_batches(6):
        for v, lab, m in zip(b["voxels"], b["label"], b["mask"]):
            if m > 0:
                got.setdefault(int(lab), []).append(v)
    for cls in CLASS_NAMES[:2]:
        want = np.packbits(stored[cls].astype(bool), axis=-1)
        have = np.sort(np.stack(got[CLASS_NAMES.index(cls)]), axis=0)
        np.testing.assert_array_equal(np.sort(want, axis=0), have)


def test_legacy_seg_npz_cache_still_loads(tmp_path):
    """Legacy seg shards ({"file": x.npz} entries, unpacked voxels) keep
    loading through the shard-list reader."""
    from featurenet_tpu.data.offline import SegCacheDataset

    out = tmp_path / "legacyseg"
    out.mkdir()
    rng = np.random.default_rng(5)
    voxels = (rng.random((4, 16, 16, 16)) < 0.4).astype(np.uint8)
    seg = (rng.integers(0, 3, (4, 16, 16, 16))).astype(np.int8)
    seg[voxels > 0] = 0  # features are carved out of the part
    np.savez_compressed(out / "seg_0000.npz", voxels=voxels, seg=seg)
    index = {"kind": "segment", "resolution": 16, "num_features": 2,
             "shards": [{"file": "seg_0000.npz", "count": 4}], "seed": 0}
    with open(out / "index.json", "w") as fh:
        json.dump(index, fh)
    ds = SegCacheDataset(str(out), global_batch=4, split="train",
                         test_fraction=0.0)
    b = next(ds.epoch_batches(4))
    np.testing.assert_array_equal(
        b["voxels"], np.packbits(voxels.astype(bool), axis=-1))
    np.testing.assert_array_equal(b["seg"], seg)


def test_measure_host_feed_matches_trainer_policy(tmp_path):
    """measure_host_feed builds its dataset the way the Trainer does (one
    shared Config.device_augment rule): device augmentation on → the host
    path is the pure packed gather; forcing host augmentation must also
    work and be slower-or-equal in rate terms (not asserted — timing), and
    both must report the policy they measured."""
    from featurenet_tpu.benchmark import measure_host_feed
    from featurenet_tpu.config import get_config

    out = str(tmp_path / "syn")
    export_synthetic_cache(out, per_class=3, resolution=16)
    cfg = get_config("smoke16", data_cache=out, global_batch=8)
    r = measure_host_feed(cfg, batches=4, warmup=1)
    assert r["host_augment"] is False  # device augmentation is the default
    assert r["host_samples_per_sec"] > 0
    r2 = measure_host_feed(
        get_config("smoke16", data_cache=out, global_batch=8,
                   augment_device=False),
        batches=4, warmup=1,
    )
    assert r2["host_augment"] is True

    # Segmentation: host-side joint rotation policy.
    from featurenet_tpu.data.offline import export_seg_cache

    seg = str(tmp_path / "segc")
    export_seg_cache(seg, num_parts=8, resolution=16, num_features=2,
                     shard_size=4)
    r3 = measure_host_feed(
        get_config("seg64", resolution=16, data_cache=seg, global_batch=4,
                   seg_features=(8, 16)),
        batches=4, warmup=1,
    )
    assert r3["host_augment"] is True
    assert r3["host_samples_per_sec"] > 0


def test_canonical_label_order_removes_order_ambiguity(tmp_path):
    """Canonical-order export differs from generation-order only on
    multi-covered voxels, and is deterministic given the geometry."""
    from featurenet_tpu.data.offline import _generate_seg_sample

    rng1 = np.random.default_rng(5)
    rng2 = np.random.default_rng(5)
    p_gen, s_gen = _generate_seg_sample(rng1, 16, 3, "generation")
    p_can, s_can = _generate_seg_sample(rng2, 16, 3, "canonical")
    assert (p_gen == p_can).all()  # identical observable part
    diff = s_gen != s_can
    # Wherever they differ, both label a feature voxel (never background).
    assert np.all((s_gen[diff] > 0) & (s_can[diff] > 0))


def test_seg_stl_tree_ingest_reproduces_voxel_cache(tmp_path):
    """export_seg_stl_tree → build_seg_cache == export_seg_cache, bit for
    bit (the STL modality and the voxel-native cache are the same dataset),
    and the result trains through SegCacheDataset."""
    from featurenet_tpu.data.offline import (
        SegCacheDataset,
        build_seg_cache,
        export_seg_cache,
    )
    from featurenet_tpu.data.voxel_to_mesh import export_seg_stl_tree

    native = str(tmp_path / "native")
    export_seg_cache(native, num_parts=12, resolution=16, num_features=2,
                     shard_size=5, seed=6)
    tree = str(tmp_path / "tree")
    export_seg_stl_tree(tree, num_parts=12, resolution=16, num_features=2,
                        shard_size=5, seed=6)
    built = str(tmp_path / "built")
    index = build_seg_cache(tree, built, workers=1)
    assert sum(s["count"] for s in index["shards"]) == 12
    for stem in ("seg_0000", "seg_0001", "seg_0002"):
        for suffix in (".voxels.npy", ".seg.npy"):
            a = np.load(os.path.join(native, stem + suffix))
            b = np.load(os.path.join(built, stem + suffix))
            assert (np.asarray(a) == np.asarray(b)).all(), (stem, suffix)
    ds = SegCacheDataset(built, global_batch=4, split="train",
                         test_fraction=0.25)
    b = next(iter(ds))
    assert b["voxels"].shape == (4, 16, 16, 2)
    assert b["seg"].dtype == np.int8


def test_build_seg_cache_refuses_misaligned_sidecars(tmp_path):
    """A sidecar labeling voxels that are occupied in the voxelized mesh is
    a hard error — silently training on shifted labels is invisible."""
    from featurenet_tpu.data.offline import build_seg_cache
    from featurenet_tpu.data.voxel_to_mesh import export_seg_stl_tree

    tree = str(tmp_path / "tree")
    export_seg_stl_tree(tree, num_parts=2, resolution=16, num_features=2,
                        seed=1)
    # Corrupt one sidecar: label a voxel that is solid in the part.
    stem = os.path.join(tree, "parts", "part_0000000")
    import numpy as np2

    from featurenet_tpu.data.stl import load_stl
    from featurenet_tpu.data.voxelize import voxelize

    part = voxelize(load_stl(stem + ".stl"), 16, fill=True, normalize=False)
    seg = np2.load(stem + ".seg.npy")
    solid = np2.argwhere(part)
    seg[tuple(solid[0])] = 3
    np2.save(stem + ".seg.npy", seg)
    with pytest.raises(ValueError, match="misaligned"):
        build_seg_cache(tree, str(tmp_path / "out"), workers=1)


def test_build_seg_cache_refuses_classify_tree(stl_tree, tmp_path):
    from featurenet_tpu.data.offline import build_seg_cache

    with pytest.raises((ValueError, FileNotFoundError)):
        build_seg_cache(stl_tree, str(tmp_path / "out"), workers=1)
