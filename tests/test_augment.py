"""Device-side cube-group augmentation (ops/augment.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from featurenet_tpu.ops.augment import (
    CUBE_GROUP,
    apply_rotation,
    random_rotate_batch,
    rotate_grids,
)


def test_group_has_24_distinct_elements(rng):
    x = jnp.asarray(rng.standard_normal((1, 5, 5, 5, 1)), jnp.float32)
    outs = {np.asarray(apply_rotation(x, p, f)).tobytes()
            for p, f in CUBE_GROUP}
    assert len(outs) == 24
    assert ((0, 1, 2), (False, False, False)) in CUBE_GROUP  # identity


def test_rotations_preserve_occupancy(rng):
    g = (rng.random((2, 8, 8, 8, 1)) > 0.7).astype(np.float32)
    x = jnp.asarray(g)
    for code in range(24):
        y = rotate_grids(x, jnp.int32(code))
        assert float(y.sum()) == float(x.sum())


def test_rotations_are_proper(rng):
    """Every element is a rotation, not a reflection: the induced 3x3
    signed-permutation matrix must have determinant +1 (mirrored training
    parts would flip chirality-sensitive features)."""
    for p, f in CUBE_GROUP:
        m = np.zeros((3, 3))
        for out_axis, in_axis in enumerate(p):
            m[out_axis, in_axis] = -1.0 if f[out_axis] else 1.0
        assert np.isclose(np.linalg.det(m), 1.0), (p, f)


def test_random_rotate_batch_jits(rng):
    x = jnp.asarray(rng.standard_normal((8, 6, 6, 6, 1)), jnp.float32)
    f = jax.jit(lambda x, k: random_rotate_batch(x, k, groups=4))
    y = f(x, jax.random.key(0))
    assert y.shape == x.shape
    # Sorted voxel multiset per sample is rotation-invariant.
    np.testing.assert_allclose(
        np.sort(np.asarray(y).reshape(8, -1), axis=1),
        np.sort(np.asarray(x).reshape(8, -1), axis=1),
        rtol=1e-6,
    )


def test_trainer_device_augment_path(tmp_path, rng):
    """Cache-backed training with device augmentation runs end to end."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.data.offline import export_synthetic_cache
    from featurenet_tpu.train import Trainer

    cache = str(tmp_path / "cache")
    export_synthetic_cache(cache, per_class=4, resolution=16)
    cfg = get_config(
        "smoke16", data_cache=cache, total_steps=3, log_every=1,
        eval_every=10**9, checkpoint_every=10**9, data_workers=1,
        global_batch=8,
    )
    tr = Trainer(cfg)
    assert tr._device_aug
    assert tr.train_data.augment is False  # host rotation disabled
    last = tr.run()
    assert np.isfinite(last["loss"])
