"""Inference path: checkpoint → Predictor → voxel/STL predictions."""

import numpy as np

from featurenet_tpu.config import get_config
from featurenet_tpu.data.mesh_primitives import mesh_box
from featurenet_tpu.data.stl import save_stl
from featurenet_tpu.data.synthetic import NUM_CLASSES, generate_batch
from featurenet_tpu.infer import Predictor
from featurenet_tpu.train import Trainer


def test_predictor_from_checkpoint(tmp_path, rng):
    cfg = get_config(
        "smoke16",
        total_steps=60,
        eval_every=10**9,
        checkpoint_every=60,
        log_every=30,
        checkpoint_dir=str(tmp_path / "ckpt"),
        data_workers=1,
    )
    trainer = Trainer(cfg)
    trainer.run()

    pred = Predictor.from_checkpoint(str(tmp_path / "ckpt"), cfg, batch=8)

    # Voxel path: odd N exercises pad/chunk; probs are a valid distribution.
    batch = generate_batch(rng, 11, resolution=16)
    labels, probs = pred.predict_voxels(batch["voxels"][..., 0])
    assert labels.shape == (11,)
    assert probs.shape == (11, NUM_CLASSES)
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, atol=1e-4)

    # The predictor must hold the *trained* weights, not re-initialized
    # ones: every param leaf matches the trainer's final state exactly.
    import jax

    trained = jax.tree_util.tree_leaves(trainer.state.params)
    restored = jax.tree_util.tree_leaves(pred._params)
    assert len(trained) == len(restored)
    for a, b in zip(trained, restored):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Empty input is a no-op, not a crash.
    labels0, probs0 = pred.predict_voxels(
        np.zeros((0, 16, 16, 16), np.float32)
    )
    assert labels0.shape == (0,) and probs0.shape == (0, NUM_CLASSES)
    assert pred.predict_stl([]) == []


def test_predict_stl_end_to_end(tmp_path, rng):
    """STL → voxelize → classify runs end-to-end and returns sane records."""
    cfg = get_config(
        "smoke16",
        total_steps=10,
        eval_every=10**9,
        checkpoint_every=10,
        log_every=10,
        checkpoint_dir=str(tmp_path / "ckpt"),
        data_workers=1,
    )
    Trainer(cfg).run()
    pred = Predictor.from_checkpoint(str(tmp_path / "ckpt"), cfg, batch=4)

    paths = []
    for i in range(2):
        p = str(tmp_path / f"part{i}.stl")
        save_stl(p, mesh_box((0.2, 0.2, 0.2), (0.8, 0.8, 0.7 + 0.1 * i)))
        paths.append(p)
    results = pred.predict_stl(paths)
    assert len(results) == 2
    for r in results:
        assert 0 <= r.label < NUM_CLASSES
        assert r.class_name
        assert 0.0 <= r.prob <= 1.0
        assert len(r.top3) == 3
        assert r.top3[0][1] >= r.top3[1][1] >= r.top3[2][1]


def test_segmentation_inference_end_to_end(tmp_path, rng):
    """Segment checkpoint → per-voxel labels, via grids, STL and the CLI."""
    cfg = get_config(
        "seg64",
        resolution=16,
        global_batch=8,
        total_steps=8,
        eval_every=10**9,
        checkpoint_every=8,
        log_every=8,
        checkpoint_dir=str(tmp_path / "ckpt"),
        data_workers=1,
        # Narrow decoder: this test is about the inference plumbing, not
        # segmentation quality — full-width U-Net compiles dominated the
        # suite (round-1: 102 s for this test alone).
        seg_features=(8, 16),
    )
    Trainer(cfg).run()
    pred = Predictor.from_checkpoint(str(tmp_path / "ckpt"), cfg, batch=2)

    # Grid path: odd N exercises pad/chunk; labels land in [0, NUM_CLASSES].
    batch = generate_batch(rng, 3, resolution=16, num_features=2)
    labels = pred.predict_voxels_seg(batch["voxels"][..., 0])
    assert labels.shape == (3, 16, 16, 16)
    assert labels.dtype == np.int8
    assert labels.min() >= 0 and labels.max() <= NUM_CLASSES

    # Classification API must refuse a segment checkpoint (and vice versa:
    # covered by the classify tests' Predictor which lacks the seg method).
    try:
        pred.predict_voxels(batch["voxels"][..., 0])
        raise AssertionError("predict_voxels accepted a segment checkpoint")
    except ValueError:
        pass

    # STL path returns SegPrediction with counts matching the label grid.
    p = str(tmp_path / "part.stl")
    save_stl(p, mesh_box((0.2, 0.2, 0.2), (0.8, 0.8, 0.8)))
    (r,) = pred.predict_stl([p])
    assert r.path == p
    assert sum(r.voxel_counts.values()) == int((r.labels > 0).sum())

    # CLI: one JSON line per part + saved label grid via --seg-out.
    import io
    import json
    from contextlib import redirect_stdout

    from featurenet_tpu import cli

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main([
            "infer", p,
            "--checkpoint-dir", str(tmp_path / "ckpt"),
            "--config", "seg64",
            "--resolution", "16",
            "--seg-out", str(tmp_path / "segs"),
        ])
    rows = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert rows and "voxel_counts" in rows[-1]
    saved = np.load(rows[-1]["labels_path"])["labels"]
    assert saved.shape == (16, 16, 16)
