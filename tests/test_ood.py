"""OOD/robustness harness: param_range windows, perturbations, report."""

import numpy as np
import pytest

from featurenet_tpu.data import synthetic as syn
from featurenet_tpu.ood import dilate, erode, evaluate_ood, rotate_part


def test_param_range_window_and_tails():
    rng = np.random.default_rng(0)
    with syn.param_range((0.2, 0.6)):
        vals = [syn._u(rng, 10.0, 20.0) for _ in range(200)]
    assert min(vals) >= 12.0 - 1e-6 and max(vals) <= 16.0 + 1e-6
    lo, hi = syn.PARAM_MID
    with syn.param_range("tails"):
        vals = [syn._u(rng, 0.0, 1.0) for _ in range(500)]
    assert all(v < lo or v > hi for v in vals)
    assert any(v < lo for v in vals) and any(v > hi for v in vals)
    # Context restored: full-range draws again.
    vals = [syn._u(rng, 0.0, 1.0) for _ in range(500)]
    assert any(lo < v < hi for v in vals)
    with pytest.raises(ValueError):
        syn.param_range((0.9, 0.1))


def test_param_range_changes_geometry_not_stream_shape():
    """Same seed, different windows: both generate valid parts of the same
    class, and mid-window parts differ from tail-window parts."""
    a = syn.generate_sample(np.random.default_rng(7), 16, label=1,
                            param_range="mid")[0]
    b = syn.generate_sample(np.random.default_rng(7), 16, label=1,
                            param_range="tails")[0]
    assert a.shape == b.shape == (16, 16, 16)
    assert a.any() and b.any()
    assert (a != b).any()


def test_param_range_ambient_context_is_inherited():
    """A caller's `with param_range(...)` around a generation entry point
    must take effect — the kwarg default inherits the ambient window
    instead of resetting it to full range (round-4 review finding)."""
    with syn.param_range("tails"):
        a = syn.generate_sample(np.random.default_rng(7), 16, label=1)[0]
    b = syn.generate_sample(
        np.random.default_rng(7), 16, label=1, param_range="tails"
    )[0]
    np.testing.assert_array_equal(a, b)
    # Explicit None forces full range even under an ambient window.
    with syn.param_range("tails"):
        c = syn.generate_sample(
            np.random.default_rng(7), 16, label=1, param_range=None
        )[0]
    d = syn.generate_sample(np.random.default_rng(7), 16, label=1)[0]
    np.testing.assert_array_equal(c, d)
    with pytest.raises(ValueError, match="mid"):
        syn.generate_sample(np.random.default_rng(0), 16, label=0,
                            param_range="mids")


def test_random_affine_batch():
    """Device-side SO(3)+scale augmentation: shape-preserving, values in
    [0,1], volume scales ~s^3 within the configured range, jit-safe."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.ops.augment import random_affine_batch

    g = np.zeros((4, 16, 16, 16, 1), np.float32)
    g[:, 5:11, 5:11, 5:11] = 1.0
    out = np.asarray(jax.jit(
        lambda v, k: random_affine_batch(v, k, groups=4)
    )(jnp.asarray(g), jax.random.key(1)))
    assert out.shape == g.shape
    assert out.min() >= 0.0 and out.max() <= 1.0 + 1e-5
    for i in range(4):
        r = out[i].sum() / g[i].sum()
        assert 0.25 < r < 1.3, r  # scale range (0.7, 1.05) -> s^3 bounds
    # Deterministic under the same key.
    again = np.asarray(jax.jit(
        lambda v, k: random_affine_batch(v, k, groups=4)
    )(jnp.asarray(g), jax.random.key(1)))
    np.testing.assert_array_equal(out, again)


def test_random_affine_batch_paired():
    """Round-5 affine levers: paired voxel+seg warping shares transforms
    (labels follow geometry, nearest-neighbor keeps the label set exact),
    rotate=False + identity-scale + translate is exactly the identity at
    prob-selected groups, and prob=1 vs the ramp path agree."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.ops.augment import random_affine_batch_paired

    g = np.zeros((4, 16, 16, 16, 1), np.float32)
    g[:, 5:11, 5:11, 5:11] = 1.0
    seg = np.zeros((4, 16, 16, 16), np.int8)
    seg[:, 5:11, 5:11, 5:11] = 3
    vox_j, seg_j = jnp.asarray(g), jnp.asarray(seg)

    # Pure translation: both arrays move together, labels stay {0, 3}.
    out_v, out_s = jax.jit(
        lambda v, s, k: random_affine_batch_paired(
            v, s, k, groups=2, rotate=False, scale_range=(1.0, 1.0),
            translate_vox=3.0,
        )
    )(vox_j, seg_j, jax.random.key(2))
    out_v, out_s = np.asarray(out_v), np.asarray(out_s)
    assert set(np.unique(out_s)) <= {0, 3}
    # Labels follow geometry: seg-foreground sits where voxels are solid.
    solid = out_v[..., 0] > 0.5
    assert ((out_s == 3) & ~solid).mean() < 0.05
    # Shared transform: occupied volume preserved under pure translation
    # (interior box, translation <= 3 voxels keeps it in-grid).
    np.testing.assert_allclose(out_v.sum(), g.sum(), rtol=1e-5)

    # prob as a traced scalar 0.0 -> identity (the ramp's step-0 case).
    id_v, id_s = jax.jit(
        lambda v, s, k: random_affine_batch_paired(
            v, s, k, groups=2, translate_vox=2.0, prob=0.0
        )
    )(vox_j, seg_j, jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(id_v), g)
    np.testing.assert_array_equal(np.asarray(id_s), seg)


def test_warm_start_init_from(tmp_path):
    """cfg.init_from loads params+batch_stats from a checkpoint while step
    and optimizer slots start fresh — and refuses an identity mismatch."""
    import jax
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    src_dir = str(tmp_path / "src")
    cfg = get_config(
        "smoke16", total_steps=2, eval_every=10**9, checkpoint_every=2,
        log_every=1, data_workers=1, eval_batches=1, checkpoint_dir=src_dir,
    )
    t0 = Trainer(cfg)
    t0.run()
    warm = Trainer(get_config(
        "smoke16", total_steps=2, data_workers=1, eval_batches=1,
        init_from=src_dir,
    ))
    assert int(warm.state.step) == 0
    for a, b in zip(jax.tree_util.tree_leaves(t0.state.params),
                    jax.tree_util.tree_leaves(warm.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="persisted"):
        Trainer(get_config(
            "smoke16", resolution=32, data_workers=1, eval_batches=1,
            init_from=src_dir,
        ))


def test_canonicalize_recovers_pose():
    """Min-AABB canonicalization undoes an arbitrary rotation up to the
    cube group: the canonicalized rotated part must overlap some cube-group
    orientation of the (remeshed) original at near the double-rasterization
    ceiling, and far better than the rotated input does."""
    from featurenet_tpu.data.canonicalize import canonicalize
    from featurenet_tpu.ood import remesh, rotate_part

    from featurenet_tpu.ops.augment import CUBE_GROUP

    def best_cube_iou(a, b):
        # Proper rotations only (the real CUBE_GROUP): a reflected result
        # must NOT pass — TTA never presents mirror images to the model.
        best = 0.0
        for perm, flips in CUBE_GROUP:
            x = np.transpose(a, perm)
            ax = [i for i, f in enumerate(flips) if f]
            if ax:
                x = np.flip(x, ax)
            best = max(
                best,
                float((x & b).sum()) / max(float((x | b).sum()), 1),
            )
        return best

    rng = np.random.default_rng(3)
    part, _, _ = syn.generate_sample(rng, 32, label=7)
    ref = remesh(part.astype(bool))
    rot = rotate_part(part.astype(bool), rng, None)
    can = canonicalize(rot)
    assert best_cube_iou(can, ref) > 0.6
    assert best_cube_iou(can, ref) > best_cube_iou(rot, ref) + 0.15


def test_predictor_tta_and_canonicalize_smoke(tmp_path):
    """predict_voxels robust modes: TTA probabilities are a valid
    distribution and cube-rotation-invariant by construction; the
    canonicalize path runs end to end."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.train import Trainer

    cfg = get_config(
        "smoke16", total_steps=2, eval_every=10**9, checkpoint_every=2,
        log_every=1, data_workers=1, eval_batches=1,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    Trainer(cfg).run()
    p = Predictor.from_checkpoint(str(tmp_path / "ck"), batch=8)
    g = np.zeros((2, 16, 16, 16), np.float32)
    g[:, 4:12, 4:12, 4:12] = 1.0
    g[0, 6:10, 6:10, 4:8] = 0.0  # a carve so rotations differ
    _, probs = p.predict_voxels(g, tta_rotations=True)
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-4)
    # TTA output is invariant to a cube-group rotation of the input.
    rot = np.flip(np.transpose(g, (0, 2, 1, 3)), 1)
    _, probs_rot = p.predict_voxels(
        np.ascontiguousarray(rot), tta_rotations=True
    )
    np.testing.assert_allclose(probs, probs_rot, atol=1e-5)
    labels, _ = p.predict_voxels(g, canonicalize=True)
    assert labels.shape == (2,)


def test_dilate_erode():
    g = np.zeros((12, 12, 12), bool)
    g[4:8, 4:8, 4:8] = True
    d, e = dilate(g), erode(g)
    assert d.sum() > g.sum() > e.sum()
    assert (g & ~d).sum() == 0 and (e & ~g).sum() == 0
    # Convex interior box away from the boundary: closing restores it.
    np.testing.assert_array_equal(erode(dilate(g)), g)


def test_rotate_part_geometry():
    part, _, _ = syn.generate_sample(np.random.default_rng(3), 16, label=0)
    rng = np.random.default_rng(4)
    # Angle 0 = pure remesh+revoxelize roundtrip (normalization rescales
    # slightly); the part must still broadly overlap itself.
    r0 = rotate_part(part, rng, 0.0)
    iou = (part & r0).sum() / (part | r0).sum()
    assert iou > 0.5, iou
    # A random SO(3) rotation then re-normalization shrinks the part (the
    # rotated AABB grows by up to sqrt(3), and normalize_mesh refits it to
    # the unit cube — exactly what the real pipeline does to a rotated CAD
    # part). The solid must survive as a substantial, bounded volume.
    r = rotate_part(part, rng, None)
    assert 0.15 * part.sum() < r.sum() < 1.2 * part.sum(), (
        r.sum(), part.sum()
    )


def test_affine_resample_pair_identity_and_pairing():
    """Grid-space eval resampler: identity transform is exact for both
    arrays; a pure scale keeps labels riding on geometry."""
    from featurenet_tpu.ood import affine_resample_pair

    rng = np.random.default_rng(0)
    vox = rng.random((16, 16, 16)) < 0.3
    seg = (vox & (rng.random((16, 16, 16)) < 0.5)).astype(np.int8) * 5
    v, s = affine_resample_pair(vox, seg, rot=None, scale=1.0)
    np.testing.assert_array_equal(v, vox)
    np.testing.assert_array_equal(s, seg)
    # Structured part: shrink by 0.8 — label voxels stay inside geometry.
    vox = np.zeros((16, 16, 16), bool)
    vox[4:12, 4:12, 4:12] = True
    seg = np.zeros((16, 16, 16), np.int8)
    seg[6:10, 6:10, 6:10] = 3
    v, s = affine_resample_pair(vox, seg, rot=None, scale=0.8)
    assert v.sum() < vox.sum()  # shrunk
    assert set(np.unique(s)) <= {0, 3}
    assert ((s == 3) & ~v).sum() == 0  # labels inside the shrunk solid


def test_evaluate_ood_seg_report(tmp_path):
    """Seg robustness report mechanics on a briefly-trained tiny seg
    checkpoint: rows for every family, clean anchors the delta, IoU and
    voxel accuracy are valid fractions."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.ood import evaluate_ood_seg
    from featurenet_tpu.train import Trainer

    cfg = get_config(
        "seg64", resolution=16, global_batch=8, seg_features=(8, 16),
        total_steps=2, eval_every=10**9, checkpoint_every=2, log_every=1,
        data_workers=1, eval_batches=1,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    Trainer(cfg).run()
    rows = evaluate_ood_seg(
        str(tmp_path / "ck"), parts=4, seed=5, batch=4,
        levels=[("clean", None), ("rotation", "so3"), ("scale", 0.7),
                ("noise", 0.01), ("tails", None)],
    )
    assert [r["family"] for r in rows] == [
        "clean", "rotation", "scale", "noise", "tails"
    ]
    for r in rows:
        assert 0.0 <= r["mean_iou"] <= 1.0
        assert 0.0 <= r["voxel_accuracy"] <= 1.0
        assert r["n"] == 4
    assert rows[0]["delta_vs_clean"] == 0.0


def test_evaluate_ood_report(tmp_path):
    """End-to-end report mechanics on a briefly-trained tiny checkpoint:
    every requested family produces a row, clean row is the delta anchor,
    counts are exact."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train import Trainer

    cfg = get_config(
        "smoke16", total_steps=2, eval_every=10**9, checkpoint_every=2,
        log_every=1, data_workers=1, eval_batches=1,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    Trainer(cfg).run()
    rows = evaluate_ood(
        str(tmp_path / "ck"), per_class=2, seed=1,
        levels=[("clean", None), ("noise", 0.01), ("morph", "erode"),
                ("tails", None), ("rotation", "so3")],
        batch=16,
    )
    fams = [r["family"] for r in rows]
    assert fams == ["clean", "noise", "morph", "tails", "rotation"]
    for r in rows:
        assert r["n"] == 2 * 24
        assert 0.0 <= r["accuracy"] <= 1.0
        assert r["worst_class"] in syn.CLASS_NAMES
    clean = rows[0]
    assert clean["delta_vs_clean"] == 0.0
    # Reproducible across invocations (stable CRC seeding, not hash()),
    # and independent of which other rows the report includes.
    again = evaluate_ood(
        str(tmp_path / "ck"), per_class=2, seed=1,
        levels=[("clean", None), ("noise", 0.01)], batch=16,
    )
    assert again[0] == rows[0] and again[1] == rows[1]
    with pytest.raises(ValueError, match="unknown OOD families"):
        evaluate_ood(str(tmp_path / "ck"), per_class=1, seed=1,
                     families=["moprh"])
