"""Performance attribution layer (featurenet_tpu.obs.perf).

Three tiers, cheapest first:

1. Capture-degradation units: a backend with no ``cost_analysis``, no
   ``memory_analysis``, or a cost dict missing ``flops`` yields an
   honestly partial (possibly empty) record — never a crash, never a
   fabricated MFU. The unknown device tier produces NO mfu samples.
2. Report/gate plumbing over synthetic events: the per-program table,
   roofline verdicts, the explicit ``mfu: unknown`` tier, the live
   follow readout, Chrome-trace memory counters, and the
   ``mfu_train``/``serve_mfu``/``hbm_peak_train_bytes`` gate pins.
3. The real thing: a 2-step CPU run's report renders a perf section with
   per-program flops/peak-memory rows and ``mfu: unknown (cpu)`` — the
   acceptance contract.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from featurenet_tpu import obs
from featurenet_tpu.obs import perf
from featurenet_tpu.obs import windows as obs_windows


# --- capture degradation -----------------------------------------------------

class _NoAnalyses:
    """A compiled object with neither analysis method."""


class _Raising:
    def cost_analysis(self):
        raise NotImplementedError("backend cannot say")

    def memory_analysis(self):
        raise NotImplementedError("backend cannot say")


class _Mem:
    argument_size_in_bytes = 100
    output_size_in_bytes = 10
    temp_size_in_bytes = 50
    generated_code_size_in_bytes = 5
    # Donation: 15 of the argument bytes are the SAME memory as the
    # output (a donated state) — peak must not count them twice.
    alias_size_in_bytes = 15


class _NoFlops:
    """cost_analysis answers, but without a flops entry."""

    def cost_analysis(self):
        return [{"bytes accessed": 1000.0}]

    def memory_analysis(self):
        return _Mem()


class _Full:
    def cost_analysis(self):
        return [{"flops": 2e9, "bytes accessed": 4e6,
                 "optimal_seconds": 0.001}]

    def memory_analysis(self):
        return _Mem()


def test_program_cost_degrades_to_partial_never_raises():
    assert perf.program_cost(_NoAnalyses()) == {}
    assert perf.program_cost(_Raising()) == {}
    partial = perf.program_cost(_NoFlops())
    assert "flops" not in partial
    assert partial["bytes"] == 1000.0
    # arg + out + temp + generated MINUS the donated alias: 165 - 15.
    assert partial["peak_bytes"] == 150
    full = perf.program_cost(_Full())
    assert full["flops"] == 2e9 and full["bytes"] == 4e6
    assert full["optimal_seconds"] == 0.001
    assert full["temp_bytes"] == 50 and full["alias_bytes"] == 15


def test_peak_bytes_never_negative_on_alias_only_capture():
    """A partial memory_analysis exposing only the alias field must yield
    an ABSENT peak, never a negative fabricated one."""

    class _AliasOnlyMem:
        alias_size_in_bytes = 500

    class _AliasOnly:
        def memory_analysis(self):
            return _AliasOnlyMem()

    cost = perf.program_cost(_AliasOnly())
    assert cost.get("alias_bytes") == 500
    assert "peak_bytes" not in cost


def test_mfu_value_single_formula():
    """The one MFU formula observe_dispatch and both bench measurements
    share: value when everything is known, None on any missing input."""
    known = perf.device_peaks("TPU v5e")
    assert perf.mfu_value({"flops": 1.97e12}, 1.0, known) == \
        pytest.approx(0.01)
    assert perf.mfu_value(None, 1.0, known) is None
    assert perf.mfu_value({"bytes": 1e6}, 1.0, known) is None
    assert perf.mfu_value({"flops": 1e9}, 0.0, known) is None
    assert perf.mfu_value({"flops": 1e9}, 1.0,
                          perf.device_peaks("cpu")) is None


def test_device_peaks_known_and_unknown_tier():
    known = perf.device_peaks("TPU v5e")
    assert known["tier"] == "known"
    assert known["peak_flops"] == 197e12
    assert known["ridge_flops_per_byte"] > 0
    unknown = perf.device_peaks("cpu")
    assert unknown["tier"] == "unknown"
    assert unknown["peak_flops"] is None
    assert "ridge_flops_per_byte" not in unknown
    assert perf.device_peaks(None)["device_kind"] == "unknown"


def test_roofline_verdict_and_honest_absence():
    peaks = perf.device_peaks("TPU v5e")
    ridge = peaks["ridge_flops_per_byte"]
    assert perf.roofline(1e9, 1e9 / (2 * ridge), peaks) == "compute-bound"
    assert perf.roofline(1e9, 2 * 1e9 / ridge, peaks) == "memory-bound"
    # Any missing input — flops, bytes, or a known peak — means NO verdict.
    assert perf.roofline(None, 1e6, peaks) is None
    assert perf.roofline(1e9, None, peaks) is None
    assert perf.roofline(1e9, 1e6, perf.device_peaks("cpu")) is None


def test_observe_dispatch_never_fabricates_mfu():
    obs_windows.install(obs_windows.WindowAggregator())
    try:
        known = perf.device_peaks("TPU v5e")
        # Unknown peak tier: no sample, even with full counters.
        assert perf.observe_dispatch(
            {"flops": 1e9}, 0.01, peaks=perf.device_peaks("cpu")) == {}
        # Missing flops: no mfu; bytes still feed the bandwidth fraction.
        out = perf.observe_dispatch({"bytes": 1e6}, 0.01, peaks=known)
        assert "mfu" not in out and out["achieved_bw_fraction"] > 0
        # No cost at all / zero wall: nothing.
        assert perf.observe_dispatch(None, 0.01, peaks=known) == {}
        assert perf.observe_dispatch({"flops": 1e9}, 0.0, peaks=known) == {}
        # The real thing: mfu = flops / wall / peak.
        out = perf.observe_dispatch({"flops": 1.97e12}, 1.0, peaks=known)
        assert out["mfu"] == pytest.approx(0.01)
        win = obs_windows._agg._win["mfu"]
        assert len(win._samples) == 1
    finally:
        obs_windows.uninstall()


def test_mfu_alert_rule_validates_and_rule_value_reads_median():
    from featurenet_tpu.obs.alerts import known_metrics, parse_rules

    assert "mfu" in known_metrics()
    assert "achieved_bw_fraction_p99" in known_metrics()
    rules = parse_rules("mfu<0.3:warning")
    assert rules[0].metric == "mfu" and rules[0].op == "<"
    agg = obs_windows.WindowAggregator(rules=rules)
    assert agg.rule_value("mfu", 0.0) is None  # no samples yet
    for v in (0.1, 0.2, 0.3):
        agg.observe("mfu", v)
    assert agg.rule_value("mfu", __import__("time").perf_counter()) == 0.2


def test_roofline_constants_single_source():
    """Satellite (ISSUE 10): the TPU v5e roofline constants live ONCE in
    obs.perf's peak tables; ops/flops.py and ops/profile_step.py derive
    theirs from it — a spec correction can no longer land in one copy
    and miss the others."""
    from featurenet_tpu.obs.perf import (
        PEAK_BYTES_PER_SEC_BY_KIND,
        PEAK_FLOPS_BY_KIND,
    )
    from featurenet_tpu.ops import flops, profile_step

    assert flops.PEAK_BF16_FLOPS == PEAK_FLOPS_BY_KIND["TPU v5e"]
    assert profile_step.PEAK_BF16_TFLOPS == \
        PEAK_FLOPS_BY_KIND["TPU v5e"] / 1e12
    assert profile_step.HBM_GBPS == \
        PEAK_BYTES_PER_SEC_BY_KIND["TPU v5e"] / 1e9
    assert profile_step.RIDGE_FLOP_PER_BYTE == pytest.approx(
        PEAK_FLOPS_BY_KIND["TPU v5e"]
        / PEAK_BYTES_PER_SEC_BY_KIND["TPU v5e"]
    )


def test_program_cost_precision_attributed_in_report():
    """The per-program perf table carries the executable's precision
    label (fp32 / bf16_master / int8) so a precision-rung delta is
    attributable to the program that ran it."""
    from featurenet_tpu.obs.report import build_report, format_report

    events = [
        {"t": 1.0, "ev": "program_cost", "program": "train_step",
         "device_kind": "TPU v5e", "precision": "bf16_master",
         "flops": 1e12, "bytes": 1e9, "peak_bytes": 2e9,
         "process_index": 0},
    ]
    rep = build_report(events)
    assert rep["perf"]["programs"]["train_step"]["precision"] == \
        "bf16_master"
    assert "bf16_master" in format_report(rep)
    # A legacy stream without the field renders the column as absent.
    legacy = build_report([
        {"t": 1.0, "ev": "program_cost", "program": "train_step",
         "device_kind": "TPU v5e", "flops": 1e12, "process_index": 0},
    ])
    assert "precision" not in legacy["perf"]["programs"]["train_step"]


def test_perf_table_renders_serve_precision_variants():
    """Satellite (ISSUE 12): the serve-precision variants (serve /
    serve_bf16 / serve_int8) land in the perf table as distinct rows
    with their precision column, mirroring how the train variants list
    — built from the REAL registry's own program_cost emissions, not
    synthetic events."""
    import jax

    from featurenet_tpu import obs
    from featurenet_tpu.config import get_config
    from featurenet_tpu.obs.report import (
        build_report,
        format_report,
        load_events,
    )
    from featurenet_tpu.runtime import Runtime

    import tempfile

    run_dir = tempfile.mkdtemp(prefix="fn_perf_prec_")
    obs.init_run(run_dir, process_index=0)
    rt = Runtime(get_config("smoke16"), cache=None)
    for name in ("serve", "serve_bf16", "serve_int8"):
        rt.build(name, batch=2)
    obs.close_run()
    events, _ = load_events(run_dir)
    rep = build_report(events)
    progs = rep["perf"]["programs"]
    assert progs["serve_bf16"]["precision"] == "bf16"
    assert progs["serve_int8"]["precision"] == "int8"
    rendered = format_report(rep)
    assert "serve_bf16" in rendered and "serve_int8" in rendered
    import shutil

    shutil.rmtree(run_dir, ignore_errors=True)


# --- report / trace / follow plumbing over synthetic events ------------------

def _synthetic_events(device_kind="TPU v5e"):
    t = 1000.0
    return [
        {"t": t, "ev": "program_compile", "program": "train_step",
         "dur_s": 2.5, "process_index": 0},
        {"t": t + 1, "ev": "program_cost", "program": "train_step",
         "device_kind": device_kind, "flops": 1e12, "bytes": 1e9,
         "temp_bytes": 5e8, "peak_bytes": 2e9, "process_index": 0},
        # A degraded capture: no flops, no verdict — the row must still
        # render with its one honest field.
        {"t": t + 2, "ev": "program_cost", "program": "serve",
         "device_kind": device_kind, "peak_bytes": 1e8,
         "process_index": 0},
        {"t": t + 3, "ev": "window_summary", "metric": "mfu", "n": 8,
         "p50": 0.41, "p95": 0.5, "p99": 0.55, "mean": 0.4, "max": 0.6,
         "seq": 1, "process_index": 0},
        {"t": t + 4, "ev": "device_memory", "device": 0,
         "bytes_in_use": 4e8, "peak_bytes_in_use": 6e8,
         "bytes_limit": 16e9, "process_index": 0},
        {"t": t + 5, "ev": "device_memory", "device": 0,
         "bytes_in_use": 3e8, "process_index": 0},
    ]


def test_report_perf_section_table_roofline_and_watermark():
    from featurenet_tpu.obs.report import (
        build_report,
        follow_perf_line,
        format_report,
    )

    rep = build_report(_synthetic_events())
    pf = rep["perf"]
    assert pf["tier"] == "known" and pf["device_kind"] == "TPU v5e"
    row = pf["programs"]["train_step"]
    assert row["flops"] == 1e12 and row["peak_bytes"] == 2e9
    assert row["compile_s"] == 2.5
    # intensity 1e12/1e9 = 1000 flops/byte >> the v5e ridge (~240).
    assert row["roofline"] == "compute-bound"
    # The degraded program renders with what it has — no verdict, no flops.
    srow = pf["programs"]["serve"]
    assert "flops" not in srow and "roofline" not in srow
    assert srow["peak_bytes"] == 1e8
    assert pf["mfu"]["p50"] == 0.41
    mem = pf["device_memory"]["0/0"]
    assert mem["watermark_bytes"] == 6e8  # peak wins over later samples
    assert mem["samples"] == 2

    text = format_report(rep)
    assert "perf: device TPU v5e" in text
    assert "mfu p50 0.41" in text
    assert "compute-bound" in text
    assert "device memory watermark" in text

    line = follow_perf_line(rep)
    assert line.startswith("== perf | ")
    assert "mfu p50 0.41" in line and "watermark 600.0 MB" in line


def test_report_perf_unknown_tier_is_explicit_not_numeric():
    from featurenet_tpu.obs.report import (
        build_report,
        follow_perf_line,
        format_report,
    )

    events = [
        {"t": 1.0, "ev": "program_cost", "program": "train_step",
         "device_kind": "cpu", "flops": 1e9, "bytes": 1e6,
         "peak_bytes": 5e6, "process_index": 0},
    ]
    rep = build_report(events)
    pf = rep["perf"]
    assert pf["tier"] == "unknown"
    assert "mfu" not in pf  # never synthesized
    assert "roofline" not in pf["programs"]["train_step"]
    text = format_report(rep)
    assert "mfu: unknown (cpu)" in text
    assert "unknown (cpu)" in follow_perf_line(rep)


def test_chrome_trace_exports_device_memory_counters():
    from featurenet_tpu.obs.spans import chrome_trace

    trace = chrome_trace(_synthetic_events())
    counters = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
    mem = [e for e in counters if e["name"] == "device 0 memory"]
    assert len(mem) == 2
    assert mem[0]["args"]["bytes_in_use"] == 4e8
    assert mem[0]["args"]["peak_bytes_in_use"] == 6e8
    # The mfu window rides the existing window-counter export.
    assert any(e["name"] == "window mfu" for e in counters)


def test_validate_accepts_partial_program_cost_events():
    """The schema must not condemn a degraded capture: program_cost with
    only its program name, device_memory with only device+bytes."""
    from featurenet_tpu.obs.report import validate_events

    events = [
        {"t": 1.0, "ev": "program_cost", "program": "serve"},
        {"t": 2.0, "ev": "device_memory", "device": 0,
         "bytes_in_use": 100},
    ]
    assert validate_events(events) == []
    # But a program_cost with no program is corrupt.
    bad = validate_events([{"t": 1.0, "ev": "program_cost"}])
    assert bad and bad[0]["check"] == "missing_fields"


# --- gate plumbing -----------------------------------------------------------

def test_perf_gate_keys_directions_and_lowered_pin_fails():
    """mfu_train / serve_mfu / hbm_peak_train_bytes ride BENCH_GATE_KEYS
    into gate_summary; utilization regresses downward, the memory
    footprint upward — a deliberately lowered MFU (or a grown footprint)
    fails the pin."""
    from featurenet_tpu.obs import gates

    summary = {
        "value": 16000.0,
        "mfu_train": 0.41,
        "serve_mfu": 0.55,
        "hbm_peak_train_bytes": 2.0e9,
        "train_roofline": "compute-bound",  # non-numeric: never a gate
        # The bf16-master training row (ISSUE 10) pins like its fp32
        # siblings: throughput/MFU min, peak bytes max.
        "train_sps_bf16_master": 18000.0,
        "mfu_train_bf16_master": 0.45,
        "hbm_peak_train_bytes_bf16_master": 1.8e9,
        "train_roofline_bf16_master": "compute-bound",
    }
    vals = gates.bench_gate_values(summary)
    for key in ("mfu_train", "serve_mfu", "hbm_peak_train_bytes",
                "train_sps_bf16_master", "mfu_train_bf16_master",
                "hbm_peak_train_bytes_bf16_master"):
        assert key in gates.BENCH_GATE_KEYS and key in vals
    assert "train_roofline" not in vals
    baseline = gates.make_baseline(vals)
    assert baseline["gates"]["mfu_train"]["direction"] == "min"
    assert baseline["gates"]["serve_mfu"]["direction"] == "min"
    assert baseline["gates"]["hbm_peak_train_bytes"]["direction"] == "max"
    assert baseline["gates"]["train_sps_bf16_master"]["direction"] == "min"
    assert baseline["gates"]["hbm_peak_train_bytes_bf16_master"][
        "direction"] == "max"
    res = gates.evaluate_gates({**vals, "mfu_train": 0.2}, baseline)
    assert "mfu_train" in res["failed"]
    res = gates.evaluate_gates(
        {**vals, "hbm_peak_train_bytes": 4.0e9}, baseline
    )
    assert "hbm_peak_train_bytes" in res["failed"]
    res = gates.evaluate_gates(vals, baseline)
    assert res["ok"]


def test_report_gate_values_carry_mfu_and_train_peak():
    from featurenet_tpu.obs.gates import report_gate_values
    from featurenet_tpu.obs.report import build_report

    rep = build_report(_synthetic_events())
    vals = report_gate_values(rep)
    assert vals["mfu"] == 0.41
    assert vals["hbm_peak_train_bytes"] == 2e9  # train_step, not serve
    # A CPU run (no mfu window, degraded capture) keeps the keys absent —
    # a gate pinning them then fails as "missing", never a crash.
    cpu = build_report([
        {"t": 1.0, "ev": "program_cost", "program": "serve",
         "device_kind": "cpu", "process_index": 0},
    ])
    cpu_vals = report_gate_values(cpu)
    assert "mfu" not in cpu_vals
    assert "hbm_peak_train_bytes" not in cpu_vals


def test_cli_report_gate_fails_on_lowered_mfu_pin(tmp_path, capsys):
    """The acceptance shape: an MFU regression fails --gate (exit 2)
    exactly like a throughput regression."""
    from featurenet_tpu import cli

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    with open(run_dir / "events.jsonl", "w") as fh:
        for e in _synthetic_events():
            fh.write(json.dumps(e) + "\n")
    baseline = tmp_path / "baseline.json"
    # The pin demands twice the MFU this run achieved.
    baseline.write_text(json.dumps({
        "gates": {"mfu": {"value": 0.82, "direction": "min",
                          "tolerance": 0.1}}
    }))
    with pytest.raises(SystemExit) as exc:
        cli.main(["report", str(run_dir), "--gate", str(baseline)])
    assert exc.value.code == 2
    assert "mfu" in capsys.readouterr().out
    # The same run passes a pin at its own level.
    baseline.write_text(json.dumps({
        "gates": {"mfu": {"value": 0.41, "direction": "min",
                          "tolerance": 0.1}}
    }))
    cli.main(["report", str(run_dir), "--gate", str(baseline)])
    assert "PASS" in capsys.readouterr().out


# --- serving batcher feed ----------------------------------------------------

def test_batcher_feeds_mfu_through_injected_cost():
    from featurenet_tpu.serve.batcher import ContinuousBatcher

    obs_windows.install(obs_windows.WindowAggregator())
    try:
        batcher = ContinuousBatcher(
            lambda bucket, arr: np.zeros((bucket, 4), np.float32),
            buckets=(1, 4), max_wait_ms=1.0,
            cost_for=lambda bucket: {"flops": 1e9, "bytes": 1e6},
            peaks=perf.device_peaks("TPU v5e"),
        )
        fut = batcher.submit(np.zeros((2, 2, 2, 1), np.float32))
        fut.result(timeout=10.0)
        batcher.drain(timeout_s=10.0)
        assert len(obs_windows._agg._win["mfu"]._samples) >= 1
        assert len(
            obs_windows._agg._win["achieved_bw_fraction"]._samples
        ) >= 1
    finally:
        obs_windows.uninstall()


def test_batcher_without_cost_stays_silent():
    from featurenet_tpu.serve.batcher import ContinuousBatcher

    obs_windows.install(obs_windows.WindowAggregator())
    try:
        batcher = ContinuousBatcher(
            lambda bucket, arr: np.zeros((bucket, 4), np.float32),
            buckets=(1, 4), max_wait_ms=1.0,
        )
        batcher.submit(np.zeros((2, 2, 2, 1), np.float32)).result(10.0)
        batcher.drain(timeout_s=10.0)
        assert len(obs_windows._agg._win["mfu"]._samples) == 0
    finally:
        obs_windows.uninstall()


# --- device-memory poller ----------------------------------------------------

def test_sample_device_memory_silent_on_cpu(tmp_path):
    """CPU's memory_stats() is None: the opt-in poller degrades to no
    events and no rows — never a crash."""
    obs.init_run(str(tmp_path / "run"), process_index=0)
    try:
        assert perf.sample_device_memory() == []
    finally:
        obs.close_run()
    events = [
        json.loads(line)
        for line in open(tmp_path / "run" / "events.jsonl")
    ]
    assert not [e for e in events if e["ev"] == "device_memory"]


def test_sample_device_memory_emits_per_device(tmp_path, monkeypatch):
    import jax

    class FakeDev:
        def __init__(self, i, stats):
            self.id = i
            self._stats = stats

        def memory_stats(self):
            if isinstance(self._stats, Exception):
                raise self._stats
            return self._stats

    devs = [
        FakeDev(0, {"bytes_in_use": 100, "peak_bytes_in_use": 200,
                    "bytes_limit": 1000}),
        FakeDev(1, None),                      # no stats: skipped
        FakeDev(2, RuntimeError("boom")),      # raising: skipped
        FakeDev(3, {"num_allocs": 5}),         # no bytes_in_use: skipped
    ]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    obs.init_run(str(tmp_path / "run"), process_index=0)
    try:
        rows = perf.sample_device_memory()
    finally:
        obs.close_run()
    assert rows == [{"device": 0, "bytes_in_use": 100,
                     "peak_bytes_in_use": 200, "bytes_limit": 1000}]
    events = [
        json.loads(line)
        for line in open(tmp_path / "run" / "events.jsonl")
        if json.loads(line)["ev"] == "device_memory"
    ]
    assert len(events) == 1 and events[0]["device"] == 0


def test_loop_mfu_samples_only_on_paced_readback_iterations(tmp_path):
    """Async dispatch: until the pipeline backpressures, an iteration's
    wall is enqueue time alone — sampling it would fabricate MFU >> 1.
    The loop must feed the mfu window only on iterations whose wall was
    bounded by the paced readback."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.train.loop import Trainer

    base = dict(total_steps=2, log_every=10**9, eval_every=10**9,
                checkpoint_every=10**9, eval_batches=1, data_workers=1,
                global_batch=8, run_dir=str(tmp_path / "r1"))
    # Default max_inflight (8): a 2-step run never pays a paced readback,
    # so even with a known peak tier there must be NO samples.
    t = Trainer(get_config("smoke16", **base))
    t._peaks = perf.device_peaks("TPU v5e")  # pretend the peak is known
    t.run()
    obs.close_run()
    # Re-run with max_inflight_steps=1: iteration 2 paces, one sample
    # lands, and it is a sane fraction (CPU walls vs a 197 TF/s peak).
    base["run_dir"] = str(tmp_path / "r2")
    t2 = Trainer(get_config("smoke16", max_inflight_steps=1, **base))
    t2._peaks = perf.device_peaks("TPU v5e")
    agg2 = obs_windows.WindowAggregator()
    obs_windows.install(agg2)
    t2.run()
    samples = [v for _, v in agg2._win["mfu"]._samples]
    obs.close_run()
    assert len(samples) == 1
    assert 0 < samples[0] < 1.0
    # And the unpaced run really produced none: its stream carries no
    # mfu window_summary.
    events = [
        json.loads(line)
        for line in open(tmp_path / "r1" / "events.jsonl")
    ]
    assert not [e for e in events
                if e["ev"] == "window_summary" and e.get("metric") == "mfu"]


# --- the real thing: 2-step CPU run ------------------------------------------

def test_two_step_cpu_run_report_renders_perf_section(tmp_path, capsys):
    """The acceptance contract: a real 2-step CPU run's report carries a
    perf section with per-program flops/peak-memory rows and the explicit
    ``mfu: unknown (cpu)`` tier — and the run's telemetry still passes
    the schema lint."""
    from featurenet_tpu.config import get_config
    from featurenet_tpu.obs.report import build_report_dir
    from featurenet_tpu.train.loop import Trainer

    run_dir = str(tmp_path / "run")
    cfg = get_config(
        "smoke16", total_steps=2, log_every=1, eval_every=10**9,
        checkpoint_every=10**9, eval_batches=1, data_workers=1,
        global_batch=8, run_dir=run_dir,
        poll_device_memory=True,  # opt-in; degrades silently on CPU
    )
    Trainer(cfg).run()
    obs.close_run()

    rep = build_report_dir(run_dir)
    pf = rep["perf"]
    assert pf["device_kind"] == "cpu" and pf["tier"] == "unknown"
    row = pf["programs"]["train_step"]
    assert row["flops"] > 0          # CPU XLA answers cost analysis
    assert row["peak_bytes"] > 0     # and memory analysis
    assert "mfu" not in pf           # unknown tier: never fabricated
    assert "device_memory" not in pf  # CPU memory_stats is None

    from featurenet_tpu.cli import main as cli_main

    cli_main(["report", run_dir])
    out = capsys.readouterr().out
    assert "mfu: unknown (cpu)" in out
    assert "train_step" in out
    cli_main(["report", run_dir, "--validate"])
    assert '"validate": "ok"' in capsys.readouterr().out
