"""bench.py hardening + the scaling-efficiency gate plumbing.

The BENCH_r05 artifact died with a raw traceback and ``parsed: null``
when the backend was lost MID-measurement (probe passed, then
``jax.devices()`` raised inside ``measure_train_step``): these tests pin
the structured ``{"skipped": true, "reason": "backend_lost", ...}``
degradation, and the round's new scaling gate keys (samples/sec per
mesh shape + cross-host data-wait spread) flowing into ``gate_summary``
via ``BENCH_GATE_KEYS`` with the right regression directions.
"""

from __future__ import annotations

import json

import pytest

import bench


def _last_record(capsys) -> dict:
    out = capsys.readouterr().out
    for line in reversed(out.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise AssertionError(f"no JSON record in bench output: {out!r}")


@pytest.fixture
def quiet_lint(monkeypatch):
    """Skip the round's lint preamble (covered by test_analysis; here it
    only adds seconds to every bench.main() call)."""
    import featurenet_tpu.analysis as analysis

    monkeypatch.setattr(analysis, "run_lint", lambda *a, **k: [])


def test_mid_measurement_backend_loss_is_structured_skip(
        monkeypatch, capsys, quiet_lint):
    """The r05 shape: the probe says the TPU is fine, then the backend
    dies inside the measurement. The artifact must be one parseable line
    with reason backend_lost — never a raw traceback."""
    monkeypatch.setattr(bench, "_probe_backend", lambda: ("tpu", None))

    def lost(platform):
        raise RuntimeError(
            "Unable to initialize backend 'axon': UNAVAILABLE: TPU "
            "backend setup/compile error (Unavailable)."
        )

    monkeypatch.setattr(bench, "_measure_round", lost)
    bench.main()  # must not raise
    rec = _last_record(capsys)
    assert rec["skipped"] is True
    assert rec["reason"] == "backend_lost"
    assert rec["backend"] == "tpu"
    assert "UNAVAILABLE" in rec["error"]


def test_non_backend_measurement_error_keeps_generic_reason(
        monkeypatch, capsys, quiet_lint):
    """A bug in the measurement itself must not masquerade as an infra
    outage — the two reasons route to different operators."""
    monkeypatch.setattr(bench, "_probe_backend", lambda: ("tpu", None))

    def bug(platform):
        raise ValueError("shape mismatch in slope window")

    monkeypatch.setattr(bench, "_measure_round", bug)
    bench.main()
    rec = _last_record(capsys)
    assert rec["skipped"] is True
    assert rec["reason"] == "measurement_error"
    assert "shape mismatch" in rec["error"]


def test_backend_loss_classifier_signatures():
    assert bench._is_backend_loss(
        "jax.errors.JaxRuntimeError: UNAVAILABLE: ..."
    )
    assert bench._is_backend_loss("RuntimeError: Unable to initialize "
                                  "backend 'axon'")
    assert not bench._is_backend_loss("ValueError: bad shape (4, 3)")


# --- scaling-efficiency gate plumbing ----------------------------------------

def test_scaling_gate_keys_flow_into_gate_summary():
    """The MULTICHIP series' numbers, as pins: per-shape samples/sec and
    the efficiency ratio regress downward, the cross-host data-wait
    spread upward — and all of them ride BENCH_GATE_KEYS into the
    pin-ready gate_summary."""
    from featurenet_tpu.obs import gates

    summary = {
        "value": 16000.0,
        "scaling_sps_per_chip_1x": 100.0,
        "scaling_sps_per_chip_2x": 96.0,
        "scaling_sps_per_chip_4x": 91.0,
        "scaling_efficiency": 0.91,
        "data_wait_spread": 0.02,
        "unrelated": "dropped",
    }
    vals = gates.bench_gate_values(summary)
    for key in ("scaling_sps_per_chip_1x", "scaling_sps_per_chip_2x",
                "scaling_sps_per_chip_4x", "scaling_efficiency",
                "data_wait_spread"):
        assert key in gates.BENCH_GATE_KEYS
        assert vals[key] == summary[key]
    assert "unrelated" not in vals
    baseline = gates.make_baseline(vals)
    for key in ("scaling_sps_per_chip_1x", "scaling_efficiency"):
        assert baseline["gates"][key]["direction"] == "min"
    assert baseline["gates"]["data_wait_spread"]["direction"] == "max"
    # A lockstep mesh leaking throughput (retention collapse) fails.
    res = gates.evaluate_gates(
        {**vals, "scaling_efficiency": 0.5}, baseline
    )
    assert "scaling_efficiency" in res["failed"]
    # A widening spread fails too.
    res = gates.evaluate_gates(
        {**vals, "data_wait_spread": 0.5}, baseline
    )
    assert "data_wait_spread" in res["failed"]


@pytest.mark.slow
def test_measure_scaling_sweeps_mesh_shapes():
    """Real sweep over the suite's 8 virtual CPU devices (tiny windows —
    the protocol, not the numbers, is under test)."""
    from featurenet_tpu.benchmark import measure_scaling
    from featurenet_tpu.config import get_config

    sc = measure_scaling(get_config("smoke16"), batch_per_chip=4,
                         repeats=2, shapes=[1, 2], min_window_sec=0.1)
    assert set(sc["shapes"]) == {1, 2}
    assert sc["scaling_efficiency"] > 0
    for row in sc["shapes"].values():
        assert row["samples_per_sec_per_chip"] > 0


@pytest.mark.slow
def test_measure_host_spread_probe_two_processes():
    """The 2-process CPU probe behind the gate's data_wait_spread key."""
    from featurenet_tpu.benchmark import measure_host_spread

    row = measure_host_spread()
    assert row["n_hosts"] == 2
    assert 0.0 <= row["data_wait_spread"] <= 1.0
