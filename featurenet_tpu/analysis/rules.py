"""The contract rules (see the package docstring for the catalog).

Each rule is a pure function ``(Tree) -> [Finding]`` registered under its
family name. The contract *sources* are imported, not duplicated: the
telemetry rule reads ``KNOWN_EVENT_KINDS`` / ``REQUIRED_EVENT_FIELDS``
straight from ``obs.report`` and the fault rule reads ``faults.SITES`` —
both stdlib-only modules — so the linter can never drift from the schema it
enforces. The one contract that cannot be imported cheaply is ``Config``
(importing ``featurenet_tpu.config`` drags in the flax model zoo), so the
config/CLI rule parses ``config.py``'s AST for the field list instead; the
linter stays runnable where no ML stack exists.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from featurenet_tpu.analysis.lint import Finding, Module, Tree, register


def _call_name(call: ast.Call) -> Optional[str]:
    """Trailing name of the called thing: ``emit`` for ``obs.emit(...)``
    and for bare ``emit(...)``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _call_owner(call: ast.Call) -> Optional[str]:
    """``obs`` for ``obs.emit(...)``; None for a bare name call."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


def _owner_tail(call: ast.Call) -> Optional[str]:
    """Trailing name of the owner expression: ``store`` for both
    ``store.append(...)`` and ``self.store.append(...)``. ``_call_owner``
    resolves only bare names, but long-lived handles (the time-series
    store a scraper holds) usually live on ``self``."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    v = f.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return None


def _str_arg(call: ast.Call, index: int = 0) -> Optional[str]:
    if len(call.args) > index:
        a = call.args[index]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


def _kwarg_names(call: ast.Call) -> set[str]:
    return {kw.arg for kw in call.keywords if kw.arg is not None}


# --- rule 1: telemetry contract ----------------------------------------------

@register("telemetry")
def telemetry_rule(tree: Tree) -> list[Finding]:
    """Emit sites vs the event schema in ``obs.report``.

    Every ``emit(...)`` whose kind is a string literal must name a known
    kind and carry that kind's required fields as *literal keyword keys* —
    a ``**splat`` doesn't count, because the schema check must be decidable
    here, not at runtime. ``warn(...)`` sites are ``warning`` events with
    ``name``/``msg`` as their leading positionals. Kinds with no emit site
    anywhere are dead schema: either the event was removed without its
    declaration, or the declaration was added without its producer.

    Rolling-window feed sites are under the same contract:
    ``observe(<literal>, ...)`` through the obs layer must name a metric
    in ``alerts.WINDOW_METRICS`` — the aggregator silently ignores
    unknown metrics by design (instrumentation must never crash), so a
    typo'd name is a window that never fills and an SLO/perf metric that
    silently watches nothing (the perf layer's ``mfu`` /
    ``achieved_bw_fraction`` feeds ride this check).

    Time-series store writes are under the same closed registry: a
    literal series name handed to a store handle's ``append(...)``
    (``store`` / ``_store`` / ``tsdb`` / ``_tsdb`` owners, including
    ``self.``-rooted ones) must be in ``serve.metrics.METRIC_NAMES`` —
    the scraper filters scraped names against the registry at runtime,
    so a typo'd literal append is a series the dashboard and burn-rate
    readers would never look for.
    """
    from featurenet_tpu.obs.alerts import WINDOW_METRICS
    from featurenet_tpu.obs.report import (
        KNOWN_EVENT_KINDS,
        REQUIRED_EVENT_FIELDS,
    )
    from featurenet_tpu.serve.metrics import METRIC_NAMES

    _STORE_OWNERS = ("store", "_store", "tsdb", "_tsdb")

    findings: list[Finding] = []
    seen_kinds: set[str] = set()
    for mod in tree.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "observe":
                # Only the obs layer's window feed: bare observe()
                # (imported from obs), obs.observe, or the windows
                # module's own entry point — a foreign .observe() API
                # is not under this contract.
                if _call_owner(node) not in (None, "obs", "windows",
                                             "_windows"):
                    continue
                metric = _str_arg(node)
                if metric is not None and metric not in WINDOW_METRICS:
                    findings.append(Finding(
                        "telemetry", "unknown_window_metric", mod.path,
                        node.lineno,
                        f"observe of unknown window metric {metric!r} — "
                        "the aggregator would silently drop every sample; "
                        "add it to alerts.WINDOW_METRICS or fix the typo",
                    ))
                continue
            if name == "append":
                # Store-handle appends only: list.append and friends are
                # everywhere, so the check keys on the owner's trailing
                # name being a store handle AND the first arg being a
                # string literal (a scraped variable name is filtered
                # against the registry at runtime instead).
                if _owner_tail(node) not in _STORE_OWNERS:
                    continue
                metric = _str_arg(node)
                if metric is not None and metric not in METRIC_NAMES:
                    findings.append(Finding(
                        "telemetry", "unknown_tsdb_series", mod.path,
                        node.lineno,
                        f"tsdb append of series {metric!r} which is not "
                        "in serve.metrics.METRIC_NAMES — the dashboard/"
                        "burn-rate readers key on the closed registry; "
                        "register the name or fix the typo",
                    ))
                continue
            if name == "warn":
                # Only the obs layer's warn is under this contract: bare
                # ``warn(...)`` (imported from obs) or ``obs.warn(...)``.
                # Foreign warn APIs — ``warnings.warn``, a stdlib
                # ``logger.warn`` — must not be forced into the telemetry
                # schema.
                if _call_owner(node) not in (None, "obs"):
                    continue
                seen_kinds.add("warning")
                have = _kwarg_names(node)
                # Positionals fill (name, msg) in order.
                pos = ["name", "msg"][: len(node.args)]
                missing = [
                    f for f in REQUIRED_EVENT_FIELDS.get("warning", ())
                    if f not in have and f not in pos
                ]
                if missing:
                    findings.append(Finding(
                        "telemetry", "missing_fields", mod.path, node.lineno,
                        f"warn(...) site lacks required field(s) {missing} "
                        "for its 'warning' event",
                    ))
                continue
            if name != "emit":
                continue
            kind = _str_arg(node)
            if kind is None:
                # Generic forwarder (emit(ev, **fields)) — unresolvable
                # here by design; the concrete sites it forwards are the
                # ones checked.
                continue
            seen_kinds.add(kind)
            if kind not in KNOWN_EVENT_KINDS:
                findings.append(Finding(
                    "telemetry", "unknown_kind", mod.path, node.lineno,
                    f"emit of unknown event kind {kind!r} — add it to "
                    "obs.report.KNOWN_EVENT_KINDS (and its required "
                    "fields) or fix the typo",
                ))
                continue
            have = _kwarg_names(node)
            missing = [
                f for f in REQUIRED_EVENT_FIELDS.get(kind, ())
                if f not in have
            ]
            if missing:
                findings.append(Finding(
                    "telemetry", "missing_fields", mod.path, node.lineno,
                    f"emit({kind!r}, ...) lacks required field(s) "
                    f"{missing} as literal keyword keys "
                    "(REQUIRED_EVENT_FIELDS); a **splat does not satisfy "
                    "the static contract",
                ))
    for kind in sorted(KNOWN_EVENT_KINDS - seen_kinds):
        findings.append(Finding(
            "telemetry", "dead_schema", tree.root, 0,
            f"event kind {kind!r} is declared in KNOWN_EVENT_KINDS but "
            "has no emit site in the package (dead schema)",
        ))
    return findings


# --- rule 2: fault-site cross-check ------------------------------------------

@register("fault-sites")
def fault_sites_rule(tree: Tree) -> list[Finding]:
    """``maybe_fail`` call sites vs ``faults.SITES`` — both directions.

    A call naming an undeclared site would never fire (the spec parser
    rejects it before any run), and a declared site with no call site is a
    chaos test that passes by testing nothing. The counter keyword must be
    the declared one: ``maybe_fail("sigterm", save=n)`` would parse, fire
    never, and look exactly like a passing test.
    """
    from featurenet_tpu.faults import SITES

    findings: list[Finding] = []
    called: set[str] = set()
    for mod in tree.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) != "maybe_fail":
                continue
            site = _str_arg(node)
            if site is None:
                continue  # the registry's own generic def/check paths
            if site not in SITES:
                findings.append(Finding(
                    "fault-sites", "unknown_site", mod.path, node.lineno,
                    f"maybe_fail site {site!r} is not declared in "
                    "faults.SITES — the injection would never fire",
                ))
                continue
            called.add(site)
            declared = SITES[site]
            have = _kwarg_names(node)
            if declared not in have:
                findings.append(Finding(
                    "fault-sites", "missing_counter", mod.path, node.lineno,
                    f"maybe_fail({site!r}, ...) does not pass the declared "
                    f"counter {declared!r} — a threshold spec for this "
                    "site could never fire",
                ))
            wrong = sorted(have - {declared})
            if wrong:
                findings.append(Finding(
                    "fault-sites", "wrong_counter", mod.path, node.lineno,
                    f"maybe_fail({site!r}, ...) passes counter(s) {wrong} "
                    f"but the site declares {declared!r} (faults.SITES)",
                ))
    for site in sorted(set(SITES) - called):
        findings.append(Finding(
            "fault-sites", "dead_site", tree.root, 0,
            f"faults.SITES declares {site!r} but no maybe_fail call site "
            "exists — the chaos spec would install and test nothing",
        ))
    return findings


# --- rule 3: host-sync discipline --------------------------------------------

# Modules whose functions sit on (or next to) the dispatch hot path: every
# host sync here serializes the pipeline, so each one must be deliberate
# and say why. Package-relative paths. data/dataset.py is the consumer
# path of the prefetcher — put_batch and the ticket loop run once per
# dispatch group, so a stray readback there stalls every step. The serve
# modules are the continuous batcher's dispatch thread and the service's
# forward — a stray sync there is paid once per live batch.
HOT_PATH_MODULES = ("train/loop.py", "train/steps.py", "infer.py",
                    "data/dataset.py", "serve/batcher.py",
                    "serve/service.py",
                    # The elastic layer is backend-free BY CONTRACT: the
                    # coordinator process supervises N training children
                    # and must never initialize (or sync against) a
                    # device — a host sync creeping in here would wedge
                    # the one process whose job is to outlive the mesh.
                    "elastic/coordinator.py", "elastic/membership.py",
                    "elastic/planner.py",
                    # The serving fleet inherits the same contract: the
                    # router/manager process must survive every replica,
                    # so it owns no device and every request it touches
                    # stays bytes — a host sync here would couple the
                    # fleet's availability to one child's backend. The
                    # connection pool is the per-request wire hop itself
                    # (every forward and probe checks a channel out), so
                    # it sits under the same discipline.
                    "fleet/replica.py", "fleet/router.py",
                    "fleet/loadgen.py", "fleet/pool.py",
                    # The scraper thread shares the manager's channel
                    # pool with the router's forwards — a host sync (or
                    # any device coupling) in its loop would stall the
                    # data plane it is only supposed to observe.
                    "fleet/scraper.py",
                    # The observability plane (tsdb writer, quality
                    # tracker, flight recorder) runs inside the serve
                    # request path and the scrape loop — both
                    # latency-budgeted. The recorder in particular
                    # handles device arrays (it snapshots request
                    # grids), so a readback there would be paid inline
                    # by the request it is recording.
                    "obs/tsdb.py", "obs/quality.py",
                    "serve/recorder.py")


def _is_host_sync(node: ast.Call) -> Optional[str]:
    """The human name of the sync construct, or None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args and not node.keywords:
            return ".item()"
        if f.attr == "device_get":
            return "jax.device_get"
        if f.attr == "block_until_ready":
            return "block_until_ready"
        if (f.attr in ("asarray", "ascontiguousarray")
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")):
            return f"np.{f.attr}"
    elif isinstance(f, ast.Name) and f.id == "block_until_ready":
        return "block_until_ready"
    return None


@register("host-sync")
def host_sync_rule(tree: Tree) -> list[Finding]:
    """Host-device synchronization points in the designated hot-path
    modules (``HOT_PATH_MODULES``): ``.item()``, ``jax.device_get``,
    ``block_until_ready``, and ``np.asarray`` (which forces a readback
    when handed a device value). Each one stalls the async dispatch
    pipeline, so each must either go or carry
    ``# lint: allow-host-sync(<reason>)`` naming why the sync is the
    point (a progress-proof readback, an epilogue aggregation, a
    host-side array that never saw the device).
    """
    findings: list[Finding] = []
    for mod in tree.modules:
        if mod.relpath not in HOT_PATH_MODULES:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            what = _is_host_sync(node)
            if what is None:
                continue
            if mod.suppressed(node.lineno, "host-sync"):
                continue
            findings.append(Finding(
                "host-sync", "host_sync", mod.path, node.lineno,
                f"{what} in hot-path module {mod.relpath} serializes the "
                "dispatch pipeline — remove it or annotate the line with "
                "# lint: allow-host-sync(<why this sync is deliberate>)",
            ))
    return findings


# --- rule 4: concurrency / timing hygiene ------------------------------------

def _is_time_time(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "time"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "time"
    )


def _scope_nodes(scope: ast.AST):
    """Direct nodes of one scope: walk the body but do not descend into
    nested function/class scopes (each is analyzed on its own)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


# Modules whose compiled step / serving hot path is under the
# precision-cast contract: with the reduced-precision policies
# (train/precision.py — bf16_master/fp16_scaled training, bf16/int8
# serving) every fp32 cast in these paths is a numerics decision — a
# stray one silently re-widens part of the working step (or the serving
# forward's input/readback edge) back to fp32, eating the rung's win
# without failing anything. Deliberate casts carry
# ``# lint: allow-precision(<why fp32 here>)``. The serve modules
# joined with the serve-precision ladder (ISSUE 12): infer.py and the
# service own the request edges the bf16/int8 programs consume.
PRECISION_CAST_MODULES = ("train/steps.py", "infer.py",
                         "serve/batcher.py", "serve/service.py")


def _is_fp32_cast(node: ast.Call) -> Optional[str]:
    """The human name of an fp32-cast construct, or None. Both array
    namespaces count: ``jnp`` casts re-widen the compiled step,
    ``np`` casts re-widen the serving host edges."""
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr == "astype" and node.args):
        a = node.args[0]
        if (isinstance(a, ast.Attribute) and a.attr == "float32"
                and isinstance(a.value, ast.Name)
                and a.value.id in ("jnp", "np", "numpy")):
            return f".astype({a.value.id}.float32)"
    if (isinstance(f, ast.Attribute) and f.attr == "float32"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("jnp", "np", "numpy")):
        return f"{f.value.id}.float32(...)"
    return None


@register("hygiene")
def hygiene_rule(tree: Tree) -> list[Finding]:
    """Timing and concurrency footguns the obs/faults layers already paid
    for once each:

    - ``time.time()`` as an operand of duration *subtraction* (directly,
      or via a variable assigned from it in the same scope): wall clock
      steps under NTP and corrupts mid-run durations — use
      ``perf_counter``. Where epoch arithmetic is the point (file-mtime
      ages), annotate ``# lint: allow-wall-clock(<reason>)``.
    - bare ``except:`` — swallows KeyboardInterrupt/SystemExit, which the
      supervisor's exit-code protocol depends on.
    - ``threading.Thread`` without an explicit ``daemon=``: an implicit
      non-daemon worker blocks interpreter exit exactly when the run is
      being torn down by a fault.
    - fp32 casts (``.astype(jnp.float32)`` / ``jnp.float32(...)``) inside
      the compiled train step (``PRECISION_CAST_MODULES``) without a
      ``# lint: allow-precision(<reason>)`` annotation: under the
      bf16_master policy an unexplained widen-back is a silent hole in
      the mixed-precision rung.
    """
    findings: list[Finding] = []
    for mod in tree.modules:
        scopes = [mod.tree] + [
            n for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            # Every plain-name assignment in the scope, with whether it
            # binds a wall-clock reading. Position-aware: a name counts as
            # wall-clock at a use site only if its LAST assignment before
            # that line was time.time() — `now = time.perf_counter()`
            # after an earlier epoch stamp must not taint later math.
            assigns: list[tuple[int, str, bool]] = []
            for node in _scope_nodes(scope):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            assigns.append((node.lineno, t.id,
                                            _is_time_time(node.value)))

            def wall_at(name: str, lineno: int) -> bool:
                last = None
                for ln, n, wall in assigns:
                    if n == name and ln < lineno and (
                            last is None or ln > last[0]):
                        last = (ln, wall)
                return last is not None and last[1]

            for node in _scope_nodes(scope):
                if not (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.Sub)):
                    continue
                wall = any(
                    _is_time_time(s)
                    or (isinstance(s, ast.Name)
                        and wall_at(s.id, node.lineno))
                    for s in (node.left, node.right)
                )
                if not wall:
                    continue
                if mod.suppressed(node.lineno, "wall-clock"):
                    continue
                findings.append(Finding(
                    "hygiene", "wall_clock_arith", mod.path, node.lineno,
                    "duration arithmetic on time.time() — wall clock "
                    "steps under NTP; use time.perf_counter(), or "
                    "annotate # lint: allow-wall-clock(<reason>) where "
                    "epoch time is the point",
                ))
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                if not mod.suppressed(node.lineno, "bare-except"):
                    findings.append(Finding(
                        "hygiene", "bare_except", mod.path, node.lineno,
                        "bare except: swallows KeyboardInterrupt/"
                        "SystemExit (the supervisor's exit protocol) — "
                        "name the exception(s)",
                    ))
            elif isinstance(node, ast.Call):
                f = node.func
                is_thread = (
                    (isinstance(f, ast.Attribute) and f.attr == "Thread"
                     and isinstance(f.value, ast.Name)
                     and f.value.id == "threading")
                    or (isinstance(f, ast.Name) and f.id == "Thread")
                )
                if is_thread and "daemon" not in _kwarg_names(node):
                    if not mod.suppressed(node.lineno, "thread-daemon"):
                        findings.append(Finding(
                            "hygiene", "thread_daemon", mod.path,
                            node.lineno,
                            "threading.Thread without explicit daemon= — "
                            "an implicit non-daemon worker blocks "
                            "interpreter exit during fault teardown",
                        ))
        if mod.relpath in PRECISION_CAST_MODULES:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                what = _is_fp32_cast(node)
                if what is None:
                    continue
                if mod.suppressed(node.lineno, "precision"):
                    continue
                findings.append(Finding(
                    "hygiene", "fp32_cast_in_hot_step", mod.path,
                    node.lineno,
                    f"{what} inside the compiled train step "
                    f"({mod.relpath}) — under the bf16_master policy an "
                    "unexplained fp32 cast silently re-widens the "
                    "working step; annotate the line with # lint: "
                    "allow-precision(<why fp32 here>) or move the cast "
                    "out of the hot step",
                ))
    return findings


# --- rule 5: config / CLI drift ----------------------------------------------

# CLI dests that deliberately do NOT name a Config field 1:1, mapped to the
# field(s) they actually drive (empty tuple = none by design). This table
# is part of the contract: a new indirection flag must be entered here or
# the lint fails.
FLAG_ALIASES: dict[str, tuple[str, ...]] = {
    "config": (),           # preset selector, resolved before overrides
    "debug_nans": (),       # flips a jax global, not run config
    "supervise": (),        # supervisor-process policy, never a field
    "stall_timeout": (),
    "max_restarts": (),
    "supervised_child": (),  # internal respawn marker
    # Elastic coordinator policy (featurenet_tpu.elastic): the world
    # roster and its device footprint belong to the coordinator process,
    # not to the per-child run config (Config.elastic/min_world_size ARE
    # fields and map 1:1).
    "world_size": (),
    "local_devices": (),
    "readmit": (),            # boundary re-admission policy (coordinator)
    "elastic_rank": (),       # internal: child's rank in the generation
    "elastic_world": (),      # internal: generation world size
    "elastic_port": (),       # internal: jax.distributed coordinator port
    "elastic_generation": (),  # internal: membership generation counter
    "no_augment": ("augment",),
    "no_spatial": ("spatial",),
    "no_augment_affine_rotate": ("augment_affine_rotate",),
    "no_stem_s2d": ("arch",),        # arch.stem_s2d
    "conv_backend": ("arch",),       # arch.conv_backend
    # An explicit --steps-per-dispatch also opts out of the membytes clamp.
    "steps_per_dispatch": ("steps_per_dispatch", "clamp_dispatch_k"),
}

# Config fields deliberately not reachable from the CLI, each with the
# reason. The rule flags stale entries (a field that grew a flag, or was
# deleted) so the whitelist can only shrink truthfully.
CLI_EXEMPT_FIELDS: dict[str, str] = {
    "name": "preset identity — selected via --config, never overridden",
    "task": "preset-defined; a different task is a different preset",
    "num_features": "dataset property owned by the seg presets/caches",
    "eval_batches": "eval protocol constant (synthetic streaming only)",
    "test_fraction": "split constant; per-run changes would desync splits",
    "augment_device": "augmentation placement internal (device_augment)",
    "augment_groups": "augmentation internal, preset-owned",
    "seg_features": "arch identity, preset-owned",
    "optimizer": "recipe field, preset-owned",
    "weight_decay": "recipe field, preset-owned",
    "warmup_steps": "recipe field, preset-owned",
    "label_smoothing": "recipe field, preset-owned",
    "mesh_data": "derived: all devices not claimed by mesh_model",
    "max_inflight_steps": "dispatch backpressure internal",
    "profile_start": "profiling window internal (profile_dir is the switch)",
    "profile_steps": "profiling window internal",
    "log_every": "cadence constant, preset-owned",
    "eval_every": "cadence constant, preset-owned",
    "checkpoint_every": "cadence constant, preset-owned",
    "keep_checkpoints": "retention constant, preset-owned",
}

# The CLI functions whose add_argument calls define run-config flags (the
# subcommand-specific parsers — export trees, report, infer paths — are
# their own commands' surfaces, not Config overrides).
_FLAG_FUNCTIONS = ("_add_override_flags", "_add_supervise_flags")


def _config_fields(mod: Module) -> dict[str, int]:
    """Field name -> declaration line of the frozen Config dataclass,
    parsed from the AST (importing config.py would drag in the model zoo)."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {
                stmt.target.id: stmt.lineno
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return {}


def _cli_flags(mod: Module) -> list[tuple[str, str, int, Optional[tuple]]]:
    """(flag, dest, line, choices) for every long-option add_argument in
    the shared override/supervise flag builders; ``choices`` is the
    literal ``choices=[...]`` tuple when present, else None."""
    flags: list[tuple[str, str, int, Optional[tuple]]] = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name in _FLAG_FUNCTIONS):
            continue
        for call in ast.walk(node):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "add_argument"):
                continue
            flag = _str_arg(call)
            if not flag or not flag.startswith("--"):
                continue
            dest = None
            choices = None
            for kw in call.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    dest = kw.value.value
                elif kw.arg == "choices" and isinstance(
                        kw.value, (ast.List, ast.Tuple)):
                    choices = tuple(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                    )
            if dest is None:
                dest = flag[2:].replace("-", "_")
            flags.append((flag, dest, call.lineno, choices))
    return flags


def _self_rooted_attr(node: ast.AST) -> Optional[str]:
    """The trailing attribute name of a ``self``-rooted attribute chain
    (``self.X`` → ``"X"``, ``self.arch.X`` → ``"X"``), or None. Nested
    chains matter because sub-config fields (``arch.conv_backend``) are
    validated through the parent's ``validate()`` but reached by their
    OWN aliased CLI flag (``--conv-backend``)."""
    if not isinstance(node, ast.Attribute):
        return None
    inner = node.value
    while isinstance(inner, ast.Attribute):
        inner = inner.value
    if isinstance(inner, ast.Name) and inner.id == "self":
        return node.attr
    return None


def _validate_sets(cfg_mod: Module) -> dict[str, tuple[set, int]]:
    """Field -> (accepted literal set, line) for every membership refusal
    in ``Config.validate()`` — the ``self.X not in ("a", "b")`` (or
    ``self.arch.X not in (...)``) guards the CLI's ``choices=`` lists
    must agree with. Nested chains are keyed by the trailing attribute,
    matching the aliased flag's dest."""
    out: dict[str, tuple[set, int]] = {}
    for node in ast.walk(cfg_mod.tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "Config"):
            continue
        for fn in node.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "validate"):
                continue
            for cmp in ast.walk(fn):
                if not (isinstance(cmp, ast.Compare)
                        and len(cmp.ops) == 1
                        and isinstance(cmp.ops[0], ast.NotIn)
                        and isinstance(cmp.comparators[0],
                                       (ast.Tuple, ast.List, ast.Set))):
                    continue
                field = _self_rooted_attr(cmp.left)
                if field is None:
                    continue
                values = {
                    e.value for e in cmp.comparators[0].elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
                if values:
                    out[field] = (values, cmp.lineno)
    return out


def _override_keys(mod: Module) -> tuple[list[str], int]:
    """The literal ``keys = [...]`` list inside ``_overrides`` — the dests
    that flow straight into ``dataclasses.replace``."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_overrides":
            for stmt in ast.walk(node):
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == "keys"
                        and isinstance(stmt.value, (ast.List, ast.Tuple))):
                    return [
                        e.value for e in stmt.value.elts
                        if isinstance(e, ast.Constant)
                    ], stmt.lineno
    return [], 0


@register("config-cli")
def config_cli_rule(tree: Tree) -> list[Finding]:
    """CLI flags vs ``Config`` fields, both directions, plus the
    ``_overrides`` routing list — the three surfaces that historically
    drift apart (a flag that parses but never lands in the config, a field
    nobody can set, a stale routing key)."""
    cfg_mod = tree.module("config.py")
    cli_mod = tree.module("cli.py")
    if cfg_mod is None or cli_mod is None:
        return []  # fixture trees without the real package layout
    fields = _config_fields(cfg_mod)
    if not fields:
        return []
    findings: list[Finding] = []
    flags = _cli_flags(cli_mod)
    dests = {d for _, d, _, _ in flags}
    for flag, dest, line, _ in flags:
        if dest in fields or dest in FLAG_ALIASES:
            continue
        findings.append(Finding(
            "config-cli", "unmapped_flag", cli_mod.path, line,
            f"CLI flag {flag} (dest {dest!r}) maps to no Config field and "
            "has no FLAG_ALIASES entry — the override would be dropped "
            "on the floor",
        ))
    # choices= lists vs validate()'s accepted sets (ROADMAP item 5 lint
    # follow-on): a flag narrowing to a different set than the config
    # refuses — or a restricted field whose flag doesn't narrow at all —
    # lets a value parse on one surface and explode (or pass) on the
    # other.
    accepted = _validate_sets(cfg_mod)
    for flag, dest, line, choices in flags:
        acc = accepted.get(dest)
        if dest not in fields and acc is None:
            # Aliased flags without a validate-set contract (world
            # shape, supervision policy) have no choices to mirror; an
            # aliased flag whose trailing field IS restricted (e.g.
            # --conv-backend vs self.arch.conv_backend) stays checked.
            continue
        if choices is not None and acc is not None \
                and set(choices) != acc[0]:
            findings.append(Finding(
                "config-cli", "choices_drift", cli_mod.path, line,
                f"CLI flag {flag} offers choices {sorted(choices)} but "
                f"Config.validate() accepts {sorted(acc[0])} "
                f"(config.py:{acc[1]}) — the two surfaces drifted",
            ))
        elif choices is None and acc is not None:
            findings.append(Finding(
                "config-cli", "missing_choices", cli_mod.path, line,
                f"CLI flag {flag} has no choices= but Config.validate() "
                f"restricts {dest!r} to {sorted(acc[0])} — an invalid "
                "value would parse and only explode at validate time; "
                "mirror the accepted set",
            ))
    keys, keys_line = _override_keys(cli_mod)
    for key in keys:
        if key not in fields:
            findings.append(Finding(
                "config-cli", "stale_override_key", cli_mod.path, keys_line,
                f"_overrides routes key {key!r} which is not a Config "
                "field — dataclasses.replace would raise at runtime",
            ))
    reachable = set(dests)
    for targets in FLAG_ALIASES.values():
        reachable.update(targets)
    for field, line in fields.items():
        if field in reachable:
            continue
        if field in CLI_EXEMPT_FIELDS:
            continue
        findings.append(Finding(
            "config-cli", "unreachable_field", cfg_mod.path, line,
            f"Config field {field!r} is reachable from no CLI flag and "
            "not exempted in CLI_EXEMPT_FIELDS — either expose it or "
            "record why it is preset-only",
        ))
    for field in sorted(CLI_EXEMPT_FIELDS):
        if field not in fields:
            findings.append(Finding(
                "config-cli", "stale_exemption", cfg_mod.path, 0,
                f"CLI_EXEMPT_FIELDS lists {field!r} which is no longer a "
                "Config field — drop the stale entry",
            ))
        elif field in reachable:
            findings.append(Finding(
                "config-cli", "stale_exemption", cfg_mod.path, 0,
                f"CLI_EXEMPT_FIELDS lists {field!r} but the field IS "
                "CLI-reachable — drop the stale entry",
            ))
    return findings


# --- rule 6: span-name drift -------------------------------------------------

@register("spans")
def span_names_rule(tree: Tree) -> list[Finding]:
    """Span-literal call sites vs the report's span registry, both ways.

    The report keys its aggregations off span-name literals
    (``LOOP_CATEGORIES`` for the step-time breakdown,
    ``KNOWN_SPAN_NAMES`` for everything else — serving latency, window
    metrics, the recovery sections). A renamed emit site would silently
    fall out of its section; a ``LOOP_CATEGORIES`` entry whose last call
    site was deleted would render a breakdown row that always reads zero.
    Only obs-owned calls are under the contract (``obs.span(...)`` or a
    bare ``span(...)`` imported from obs); a non-literal name is a
    generic forwarder, unresolvable here by design.
    """
    from featurenet_tpu.obs.report import KNOWN_SPAN_NAMES, LOOP_CATEGORIES

    findings: list[Finding] = []
    seen: set[str] = set()
    for mod in tree.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) != "span":
                continue
            if _call_owner(node) not in (None, "obs"):
                continue
            name = _str_arg(node)
            if name is None:
                continue
            seen.add(name)
            if name not in KNOWN_SPAN_NAMES:
                findings.append(Finding(
                    "spans", "unknown_span", mod.path, node.lineno,
                    f"span name {name!r} is not declared in "
                    "obs.report.KNOWN_SPAN_NAMES — the report/window "
                    "layers would silently ignore it; add it to the "
                    "registry or fix the typo",
                ))
    for cat in LOOP_CATEGORIES:
        if cat not in seen:
            findings.append(Finding(
                "spans", "dead_category", tree.root, 0,
                f"report.LOOP_CATEGORIES attributes {cat!r} but no span "
                "call site emits it — its step-time breakdown row would "
                "always read zero (dead category)",
            ))
    return findings


# --- rule 7: raw-connection discipline ---------------------------------------

# The one module allowed to construct HTTP connections: the fleet's
# channel pool. Every other call site checks a channel out of a pool —
# a raw construction elsewhere is connect-per-request sneaking back in,
# the exact churn PR 15 removed from the serving data plane.
POOL_MODULE = "fleet/pool.py"

_RAW_CONN_NAMES = ("HTTPConnection", "HTTPSConnection")


@register("raw-conn")
def raw_conn_rule(tree: Tree) -> list[Finding]:
    """Raw ``http.client.HTTPConnection(...)`` construction outside
    ``fleet/pool.py``. The pool is where broken-socket retirement,
    max-age/idle bounds, and the ``conn_open``/``conn_reuse``/
    ``conn_retire`` telemetry live — a raw connection bypasses all of
    it and silently reintroduces a handshake per request. A deliberate
    one-shot connection (a single-socket stream client, a test harness
    inside the package) carries
    ``# lint: allow-raw-conn(<why one raw connection is the point>)``.
    """
    findings: list[Finding] = []
    for mod in tree.modules:
        if mod.relpath == POOL_MODULE:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if name not in _RAW_CONN_NAMES:
                continue
            if mod.suppressed(node.lineno, "raw-conn"):
                continue
            findings.append(Finding(
                "raw-conn", "raw_connection", mod.path, node.lineno,
                f"raw {name}(...) outside {POOL_MODULE} — construct "
                "channels through fleet.pool.ConnectionPool (checkout/"
                "post/get) so retirement, bounds, and conn_* telemetry "
                "apply, or annotate the line with # lint: "
                "allow-raw-conn(<why a one-shot connection is the "
                "point>)",
            ))
    return findings


# --- rule 8: alert-rule fragments in docs/help vs known_metrics --------------

# An alert-DSL fragment: metric OP number [":" severity], with NO
# whitespace around the operator (prose like "augment_groups > 0" is not a
# rule example).
_ALERT_FRAGMENT = re.compile(
    r"(?<![A-Za-z0-9_.])([a-z][a-z0-9_]{2,})([<>])"
    r"[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?(?::([a-z]+))?"
)


@register("alerts")
def alert_docs_rule(tree: Tree) -> list[Finding]:
    """Alert-rule examples in docstrings/help text vs the live metric
    universe (``obs.alerts.known_metrics()``) — ROADMAP item 5's last
    lint follow-on. A doc example naming a metric the parser would refuse
    (or a severity outside ``SEVERITIES``) teaches operators a spec that
    fails at config time; a RENAMED metric leaves every doc stale the
    moment the rename lands. Suppress a deliberate non-example with
    ``# lint: allow-alert-doc(<reason>)``."""
    from featurenet_tpu.obs.alerts import SEVERITIES, known_metrics

    valid = known_metrics()
    findings: list[Finding] = []
    for mod in tree.modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            for m in _ALERT_FRAGMENT.finditer(node.value):
                metric, severity = m.group(1), m.group(3)
                # Anchor the finding to the fragment's own line inside a
                # multi-line string (node.lineno is the opening quote).
                # Suppressions are honored at either anchor: a comment
                # cannot live INSIDE a docstring, so the opening-quote
                # line stays the escape hatch for those.
                line = node.lineno + node.value.count("\n", 0, m.start())
                if (mod.suppressed(line, "alert-doc")
                        or mod.suppressed(node.lineno, "alert-doc")):
                    continue
                if metric not in valid:
                    findings.append(Finding(
                        "alerts", "unknown_doc_metric", mod.path,
                        line,
                        f"alert-rule example {m.group(0)!r} names metric "
                        f"{metric!r}, which alerts.known_metrics() does "
                        "not know — the documented spec would be refused "
                        "at config time",
                    ))
                elif severity is not None and severity not in SEVERITIES:
                    findings.append(Finding(
                        "alerts", "unknown_doc_severity", mod.path,
                        line,
                        f"alert-rule example {m.group(0)!r} uses severity "
                        f"{severity!r}; one of {', '.join(SEVERITIES)}",
                    ))
    return findings


# --- rule 9: concurrency (lock discipline / deadlock / thread lifecycle) ------

# The four concurrency checks live in their own module (they carry real
# per-class dataflow machinery); importing it here registers the family
# in the same registry, in declaration order.
from featurenet_tpu.analysis import concurrency as _concurrency  # noqa: E402,F401


# --- rule 10: unused-suppression audit ---------------------------------------

# Which rule family owns each `# lint: allow-<key>(reason)` escape. The
# audit only judges a key when its owning family actually ran (see
# Tree.selected): under `--rule telemetry` a host-sync suppression never
# had the chance to be consumed and must not read as stale.
SUPPRESSION_FAMILIES = {
    "host-sync": "host-sync",
    "wall-clock": "hygiene",
    "bare-except": "hygiene",
    "thread-daemon": "hygiene",
    "precision": "hygiene",
    "raw-conn": "raw-conn",
    "alert-doc": "alerts",
    "unlocked": "concurrency",
    "condvar-if": "concurrency",
    "lock-order": "concurrency",
    "thread-leak": "concurrency",
}


@register("suppressions")
def suppressions_rule(tree: Tree) -> list[Finding]:
    """Stale-escape audit: a ``# lint: allow-<key>(reason)`` comment
    whose rule produced no finding on that line is itself a finding —
    the violation it excused is gone (or moved), and a rotting escape
    is a hole the next real violation walks through. An unknown key
    never matches any rule and is always a finding. ``run_lint`` runs
    this family last, so every other selected rule has already recorded
    which escapes it consumed (``Module.used_suppressions``)."""
    selected = set(tree.selected)
    findings: list[Finding] = []
    for mod in tree.modules:
        for line in sorted(mod.suppressions):
            for key in sorted(mod.suppressions[line]):
                family = SUPPRESSION_FAMILIES.get(key)
                if family is None:
                    findings.append(Finding(
                        "suppressions", "unknown_suppression_key",
                        mod.path, line,
                        f"# lint: allow-{key}(...) names no known rule "
                        f"key; known: {', '.join(sorted(SUPPRESSION_FAMILIES))}",
                    ))
                    continue
                if family not in selected:
                    continue
                if (line, key) not in mod.used_suppressions:
                    findings.append(Finding(
                        "suppressions", "unused_suppression",
                        mod.path, line,
                        f"# lint: allow-{key}(...) suppresses nothing — "
                        f"the {family} rule produced no finding here; "
                        "delete the stale escape (or move it back onto "
                        "the line it excuses)",
                    ))
    return findings
