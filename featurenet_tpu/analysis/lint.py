"""Lint engine: parse the package, run the contract rules, report findings.

The engine is deliberately small: it walks a directory of ``.py`` files,
parses each once (``ast`` + raw source lines, shared by every rule), runs
the registered rules, and drops findings whose source line carries a
matching suppression comment. Rules live in ``rules.py`` and are pure
functions ``(tree) -> [Finding]`` — all repo-specific knowledge (which
event kinds exist, which modules are hot paths) belongs there, not here.

Suppression syntax — one per finding *kind*, never blanket::

    x = np.asarray(dev_val)   # lint: allow-host-sync(readback is the point)
    age = time.time() - mtime # lint: allow-wall-clock(mtime is epoch-based)

The parenthesized reason is mandatory: an unexplained suppression is just
the violation with extra steps. A suppression comment whose key doesn't
match the finding on that line does nothing.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Callable, Iterable, Optional

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*allow-([a-z0-9-]+)\(([^)]+)\)")


@dataclasses.dataclass
class Finding:
    rule: str      # rule family ("telemetry", "host-sync", ...)
    check: str     # specific check within the family ("unknown_kind", ...)
    path: str      # path of the offending file (absolute)
    line: int      # 1-indexed line (0 = whole-file / cross-file finding)
    msg: str

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file, shared by every rule: path, AST, raw lines,
    and the per-line suppression keys already extracted."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line number -> set of allow-keys on that line. Extracted from
        # COMMENT tokens, not raw lines: the suppression-audit rule
        # would otherwise read every docstring that *mentions* the
        # ``# lint: allow-...`` syntax (this package documents it
        # everywhere) as a stale escape. Anchored to the token start for
        # the same reason — a block comment *quoting* the syntax is
        # documentation, only a comment that IS the directive counts.
        self.suppressions: dict[int, set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.match(tok.string)
                if m:
                    self.suppressions.setdefault(
                        tok.start[0], set()
                    ).add(m.group(1))
        except (tokenize.TokenError, IndentationError):
            # The source already parsed (ast above), so this is near-
            # unreachable; degrade to the raw-line scan rather than
            # silently dropping every suppression in the file.
            for i, line in enumerate(self.lines, start=1):
                for m in _SUPPRESS_RE.finditer(line):
                    self.suppressions.setdefault(i, set()).add(m.group(1))
        # (comment line, key) pairs a rule actually consumed this run —
        # the suppression-audit rule flags the rest as stale escapes.
        self.used_suppressions: set[tuple[int, str]] = set()

    def suppressed(self, lineno: int, key: str) -> bool:
        """True when ``lineno`` (or a comment-only line directly above it)
        carries ``# lint: allow-<key>(reason)``. The line-above form keeps
        long statements readable; it must be a pure comment line so the
        suppression can't accidentally cover two statements."""
        if key in self.suppressions.get(lineno, ()):
            self.used_suppressions.add((lineno, key))
            return True
        above = lineno - 1
        if key in self.suppressions.get(above, ()):
            text = self.lines[above - 1].strip() if above >= 1 else ""
            if text.startswith("#"):
                self.used_suppressions.add((above, key))
                return True
        return False


class Tree:
    """The whole lint target: every parsed module under one root."""

    def __init__(self, root: str, modules: list[Module]):
        self.root = root
        self.modules = modules
        # Rule families selected for this run (set by run_lint) — the
        # suppression audit only judges keys whose owning family ran,
        # so `--rule telemetry` can't spray false unused-suppression
        # findings for rules that never had the chance to consume them.
        self.selected: list[str] = list(RULE_NAMES)

    def module(self, relpath: str) -> Optional[Module]:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


def package_root() -> str:
    """Default lint target: the installed ``featurenet_tpu`` package."""
    import featurenet_tpu

    return os.path.dirname(os.path.abspath(featurenet_tpu.__file__))


def load_tree(root: str) -> Tree:
    root = os.path.abspath(root)
    if not os.path.exists(root):
        # A typo'd path must fail loudly: os.walk on a missing dir yields
        # nothing and the "lint" would stay green forever.
        raise FileNotFoundError(f"lint target {root!r} does not exist")
    modules: list[Module] = []
    if os.path.isfile(root):
        with open(root, encoding="utf-8") as fh:
            modules.append(Module(root, os.path.basename(root), fh.read()))
        return Tree(os.path.dirname(root), modules)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(Module(path, os.path.relpath(path, root), source))
    if not modules:
        raise FileNotFoundError(
            f"lint target {root!r} contains no .py files — wrong path?"
        )
    return Tree(root, modules)


# Registered rule families, name -> callable; populated by rules.py at
# import time (a plain dict, not entry points — the rule set IS the repo's
# contract surface and changes only with the contracts themselves).
RULES: dict[str, Callable[[Tree], list[Finding]]] = {}
RULE_NAMES: list[str] = []


def register(name: str):
    def deco(fn):
        RULES[name] = fn
        RULE_NAMES.append(name)
        return fn

    return deco


def _is_under(path: str, root: str) -> bool:
    try:
        return os.path.commonpath([path, root]) == root
    except ValueError:  # different drives (windows) — never under
        return False


def _git_changed_files(root: str) -> Optional[set[str]]:
    """Absolute paths of files the working tree changed vs HEAD
    (tracked modifications plus untracked files), or None when git is
    absent / ``root`` is not inside a work tree — the caller falls back
    to the full-package lint, never a silently-empty one."""
    import subprocess

    def _git(*args: str) -> Optional[list[str]]:
        try:
            proc = subprocess.run(
                ["git", "-C", root, *args],
                capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        return proc.stdout.splitlines()

    top = _git("rev-parse", "--show-toplevel")
    if not top:
        return None
    changed = _git("diff", "--name-only", "HEAD")
    untracked = _git("ls-files", "--others", "--exclude-standard")
    if changed is None or untracked is None:
        return None
    return {
        os.path.abspath(os.path.join(top[0], name))
        for name in changed + untracked
        if name.strip()
    }


def run_lint(root: Optional[str] = None,
             rules: Optional[Iterable[str]] = None,
             changed_only: bool = False) -> list[Finding]:
    """Lint ``root`` (default: the installed package) with the named rules
    (default: all). Findings come back path/line-sorted, suppressions
    already honored.

    A ``root`` *inside* the installed package lints the WHOLE package and
    narrows only the reported per-file findings to the requested subtree:
    the contracts are package-wide, so relpaths must stay package-rooted
    (``train/loop.py`` is a hot-path module no matter how it was named on
    the command line) and the cross-file existence checks (dead event
    kinds, dead fault sites, config/CLI drift) must see every file —
    linting a subpath would otherwise both spray false dead-* positives
    and silently skip the path-keyed rules. Package-level findings
    (``line == 0``) always survive the narrowing: a dead fault site IS
    this file's problem when this file held its last call site. A ``root``
    outside the package is linted as its own tree (fixture snippets)."""
    from featurenet_tpu.analysis import rules as _rules  # noqa: F401

    pkg = package_root()
    target = os.path.abspath(root) if root is not None else pkg
    scope: Optional[str] = None
    if target != pkg and _is_under(target, pkg):
        scope = target
        target = pkg
    elif target != pkg and _is_under(pkg, target):
        # `cli lint .` from a repo checkout: the package lives UNDER the
        # target. Relpaths would come out 'featurenet_tpu/train/loop.py'
        # and silently disarm every path-keyed rule, while the tests tree
        # sprayed fixture noise — re-root to the package, which is the
        # contract surface.
        target = pkg
    tree = load_tree(target)
    selected = list(rules) if rules else list(RULE_NAMES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(
            f"unknown lint rule(s) {unknown}; have {sorted(RULES)}"
        )
    # The suppression audit judges which escapes the OTHER selected
    # rules consumed, so it must run after all of them regardless of
    # the order the caller named the families in.
    if "suppressions" in selected:
        selected = [r for r in selected if r != "suppressions"]
        selected.append("suppressions")
    tree.selected = list(selected)
    findings: list[Finding] = []
    for name in selected:
        findings.extend(RULES[name](tree))
    if scope is not None:
        findings = [
            f for f in findings
            if f.line == 0 or f.path == scope or _is_under(f.path, scope)
        ]
    if changed_only:
        changed = _git_changed_files(tree.root)
        if changed is not None:
            # Package-level (line 0) findings survive: a dead fault site
            # is real no matter which files the diff touched.
            findings = [
                f for f in findings if f.line == 0 or f.path in changed
            ]
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings


def format_findings(findings: list[Finding], as_json: bool = False) -> str:
    """Text: one ``path:line rule/check message`` per finding. JSON: one
    object per line plus a summary record — the same greppable-artifact
    convention as the rest of the repo's tooling."""
    if as_json:
        lines = [json.dumps(f.to_dict()) for f in findings]
        lines.append(json.dumps({
            "lint": "fail" if findings else "ok",
            "findings": len(findings),
        }))
        return "\n".join(lines)
    if not findings:
        return "lint: ok (0 findings)"
    lines = [
        f"{f.location()}: [{f.rule}/{f.check}] {f.msg}" for f in findings
    ]
    lines.append(f"lint: {len(findings)} finding(s)")
    return "\n".join(lines)


def format_sarif(findings: list[Finding]) -> str:
    """SARIF 2.1.0 rendering (``cli lint --format sarif``) so CI systems
    that speak SARIF (code-scanning uploads, inline PR annotations) can
    consume findings with no adapter. One run, one result per finding;
    rule ids are ``family/check``. Package-level findings (line 0) carry
    no region — SARIF regions are 1-indexed."""
    rule_ids = sorted({f"{f.rule}/{f.check}" for f in findings})
    results = []
    for f in findings:
        loc: dict = {
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f.path.replace(os.sep, "/"),
                },
            },
        }
        if f.line:
            loc["physicalLocation"]["region"] = {"startLine": f.line}
        results.append({
            "ruleId": f"{f.rule}/{f.check}",
            "level": "error",
            "message": {"text": f.msg},
            "locations": [loc],
        })
    log = {
        "version": "2.1.0",
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "featurenet-lint",
                    "rules": [{"id": rid} for rid in rule_ids],
                },
            },
            "results": results,
        }],
    }
    return json.dumps(log, indent=2, sort_keys=True)
