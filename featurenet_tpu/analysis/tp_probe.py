"""Layer-by-layer numerics bisection of the tensor-parallel divergence.

The two seed-verified tier-1 failures (tests/test_parallel.py::
``test_tp_matches_single_device`` / ``test_spatial_partitioning_matches_
single_device``, loss 3.0999 vs 3.3043 on this jax line) diverge ~6% in
loss between a ``data=4 x model=2`` mesh and unsharded execution. This
probe localizes WHERE the computation first disagrees instead of
eyeballing the end-to-end loss:

1. **Per-module forward bisection** (flax ``capture_intermediates``):
   every module output of the eval-mode forward compared between the TP
   mesh and a single device — a diverging conv/Dense/BN block shows up
   as the first intermediate over tolerance.
2. **Mechanism A/B**: the full train-mode forward with dropout DISABLED
   vs ENABLED — separating batch-stat BN reduction order (benign float
   noise) from the dropout mask itself.
3. **Fix verification** (optional): re-run the diverging configuration
   under ``jax_threefry_partitionable=True`` and report whether the
   divergence closes.

Finding as of the first run (recorded in ROADMAP): every eval-mode
intermediate matches to float noise (<=1e-4) and train mode WITHOUT
dropout matches too — the first (and only) diverging "layer" is the
**dropout mask**. With ``jax_threefry_partitionable=False`` (this jax
version's default) the bits jax.random generates under GSPMD depend on
how the partitioner shards the consuming computation, so the mask over
the model-axis-sharded ``[B, hidden]`` activation differs from the
single-device mask (~21% of elements). Under
``jax_threefry_partitionable=True`` the TP update matches the
single-device update BITWISE — the fix is the flag, deferred because it
changes every seeded RNG stream in the suite.

Run it:  ``python -m featurenet_tpu.analysis.tp_probe [--no-verify-fix]``
(needs >= 2 devices; CI's 8-CPU-device harness qualifies). Imports are
function-local so ``featurenet_tpu.analysis`` stays importable with no
ML stack (the lint engine's contract).
"""

from __future__ import annotations

import argparse
import json


def _flatten_paths(tree) -> list[tuple[str, object]]:
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        ("/".join(getattr(k, "key", str(k)) for k in path), leaf)
        for path, leaf in flat
    ]


def probe(resolution: int = 16, batch: int = 16, tolerance: float = 1e-3,
          verify_fix: bool = True, seed: int = 0) -> dict:
    """Run the bisection; returns ``{"rows": [...], "verdict": {...}}``.

    Each row is one compared quantity (a module intermediate, a
    mechanism A/B stage) with its max abs difference between the
    ``data x model=2`` mesh and single-device execution. The verdict
    names the first diverging stage and, with ``verify_fix``, whether
    ``jax_threefry_partitionable=True`` closes it.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.models.featurenet import tiny_arch
    from featurenet_tpu.parallel.mesh import (
        batch_shardings,
        make_mesh,
        replicated,
        state_shardings,
    )
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer

    if len(jax.devices()) < 2:
        raise RuntimeError(
            "tp_probe needs >= 2 devices (the CI harness forces 8 CPU "
            "devices; see tests/conftest.py)"
        )
    from featurenet_tpu.config import get_config

    host_batch = generate_batch(
        np.random.default_rng(seed), batch, resolution=resolution
    )
    cfg = get_config("smoke16", global_batch=batch)
    tx = make_optimizer(cfg)
    mesh_tp = make_mesh(model=2)
    mesh_1 = make_mesh(data=1, model=1, devices=jax.devices()[:1])

    def forward(mesh, arch, train, capture):
        """One jitted forward on ``mesh``; returns (logits, intermediates
        or None). fp32 model so only sharding (not bf16 rounding) can
        explain a diff."""
        model = FeatureNet(arch=arch, dtype=jnp.float32)

        def init_fn(r):
            sample = jnp.zeros(host_batch["voxels"].shape, jnp.float32)
            return create_state(model, tx, sample, r)

        abstract = jax.eval_shape(init_fn, jax.random.key(0))
        st_sh = state_shardings(abstract, mesh)
        state = jax.jit(init_fn, out_shardings=st_sh)(jax.random.key(0))
        b_sh = batch_shardings(mesh)

        def fwd(params, stats, vox, r):
            mutable = (["intermediates"] if capture else [])
            mutable += (["batch_stats"] if train else [])
            out = model.apply(
                {"params": params, "batch_stats": stats}, vox, train=train,
                rngs={"dropout": r} if train else None,
                mutable=mutable or False,
                capture_intermediates=capture,
            )
            return out if mutable else (out, {})

        f = jax.jit(fwd, in_shardings=(
            st_sh.params, st_sh.batch_stats, b_sh["voxels"],
            replicated(mesh),
        ))
        logits, mutated = f(
            state.params, state.batch_stats,
            jax.device_put(host_batch["voxels"], b_sh["voxels"]),
            jax.device_put(jax.random.key(seed + 1), replicated(mesh)),
        )
        inter = mutated.get("intermediates") if isinstance(mutated, dict) \
            else None
        return np.asarray(logits), inter

    rows: list[dict] = []
    arch = tiny_arch()

    # --- stage 1: per-module eval-mode bisection ----------------------------
    log_tp, inter_tp = forward(mesh_tp, arch, train=False, capture=True)
    log_1, inter_1 = forward(mesh_1, arch, train=False, capture=True)
    for (path, a), (_, b) in zip(_flatten_paths(inter_tp),
                                 _flatten_paths(inter_1)):
        rows.append({
            "stage": f"forward/eval/{path}",
            "max_abs_diff": float(np.abs(np.asarray(a) - np.asarray(b))
                                  .max()),
        })
    rows.append({"stage": "forward/eval/logits",
                 "max_abs_diff": float(np.abs(log_tp - log_1).max())})

    # --- stage 2: mechanism A/B — batch-stat BN vs the dropout mask ---------
    no_dropout = dataclasses.replace(arch, dropout=0.0)
    for label, a in (("forward/train-no-dropout", no_dropout),
                     ("forward/train-dropout", arch)):
        lt, _ = forward(mesh_tp, a, train=True, capture=False)
        l1, _ = forward(mesh_1, a, train=True, capture=False)
        rows.append({"stage": label,
                     "max_abs_diff": float(np.abs(lt - l1).max())})

    diverging = [r for r in rows if r["max_abs_diff"] > tolerance]
    verdict: dict = {
        "tolerance": tolerance,
        "first_divergence": diverging[0]["stage"] if diverging else None,
        "threefry_partitionable": bool(
            jax.config.jax_threefry_partitionable
        ),
    }

    # --- stage 3: does jax_threefry_partitionable close it? -----------------
    if verify_fix and diverging:
        prev = bool(jax.config.jax_threefry_partitionable)
        try:
            jax.config.update("jax_threefry_partitionable", True)
            lt, _ = forward(mesh_tp, arch, train=True, capture=False)
            l1, _ = forward(mesh_1, arch, train=True, capture=False)
            d = float(np.abs(lt - l1).max())
        finally:
            jax.config.update("jax_threefry_partitionable", prev)
        verdict["partitionable_train_dropout_max_abs_diff"] = d
        verdict["fixed_by_threefry_partitionable"] = d <= tolerance
    return {"rows": rows, "verdict": verdict}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--resolution", type=int, default=16)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--tolerance", type=float, default=1e-3)
    parser.add_argument("--no-verify-fix", action="store_true",
                        help="skip the jax_threefry_partitionable=True "
                             "re-run")
    args = parser.parse_args()
    out = probe(resolution=args.resolution, batch=args.batch,
                tolerance=args.tolerance,
                verify_fix=not args.no_verify_fix)
    for row in out["rows"]:
        print(json.dumps(row))
    print(json.dumps({"verdict": out["verdict"]}))


if __name__ == "__main__":
    main()
