"""Repo-native static analysis: the cross-cutting contracts, machine-checked.

Three PRs of growth (obs layer, multi-host telemetry, fault injection) left
the package with *conventions* that nothing enforced: event kinds and their
required fields are declared in ``obs/report.py`` while the emit sites are
spread across ten files; ``faults.SITES`` declares injection sites whose
``maybe_fail`` call sites thread through six modules (a typo'd site would
silently never fire and the chaos test would pass by testing nothing —
faults.py's own warning); and the hot training loop accumulates host-sync
calls that serialize the dispatch pipeline the ROADMAP's north star depends
on. This package turns those conventions into lint rules over the package's
own AST — in the DL-framework-testing spirit of the reference lineage
(PAPERS.md), pointed at ourselves.

Rules (each a pure function over the parsed tree; see ``rules.py``):

- ``telemetry``   — every ``emit``/``warn`` call site's literal event kind
                    is in ``KNOWN_EVENT_KINDS`` and carries that kind's
                    ``REQUIRED_EVENT_FIELDS`` as literal keyword keys; every
                    known kind has at least one emit site (dead schema).
- ``fault-sites`` — every ``maybe_fail("site", counter=...)`` literal names
                    a ``faults.SITES`` entry with the declared counter; every
                    declared site has at least one call site.
- ``host-sync``   — ``.item()`` / ``jax.device_get`` / ``block_until_ready``
                    / ``np.asarray`` inside the designated hot-path modules
                    (train/loop.py, train/steps.py, infer.py), suppressible
                    only via ``# lint: allow-host-sync(<reason>)``.
- ``hygiene``     — wall-clock (``time.time()``) subtraction in duration
                    arithmetic (must be ``perf_counter``; suppress with
                    ``# lint: allow-wall-clock(<reason>)`` where epoch time
                    is the point, e.g. file-mtime ages), bare ``except:``,
                    and ``threading.Thread`` without an explicit ``daemon=``.
- ``config-cli``  — every CLI override flag maps to a real ``Config`` field
                    and every field is CLI-reachable or explicitly exempted
                    (stale exemptions are themselves findings).
- ``raw-conn``    — ``http.client.HTTPConnection`` construction outside
                    ``fleet/pool.py`` (the one module allowed to open wire
                    channels — everything else checks one out of the pool);
                    suppress a deliberate one-shot with
                    ``# lint: allow-raw-conn(<reason>)``.
- ``concurrency`` — thread-safety as a checked contract (``concurrency.py``):
                    shared ``self._x`` attributes written from multiple
                    methods across threads must hold the class's lock
                    (``allow-unlocked``); ``Condition.wait()`` belongs under
                    ``while``, not ``if`` (``allow-condvar-if``); nested
                    ``with lock:`` acquisition edges are collected
                    package-wide and any cycle is a deadlock finding
                    (``allow-lock-order``); a thread a class starts but no
                    stop/drain/close path joins is a leak
                    (``allow-thread-leak``).
- ``suppressions``— the audit of the escapes themselves: a
                    ``# lint: allow-<key>(reason)`` comment whose rule
                    consumed no finding there is a stale escape and is
                    itself a finding, as is an unknown key. Only judged
                    for families selected this run, so ``--rule telemetry``
                    cannot flag another family's live suppressions.

Surfaced as ``python -m featurenet_tpu.cli lint [--format text|json|sarif]
[--changed] [--rule NAME]`` (exit 2 on findings) and run self-clean inside
tier-1
(``tests/test_analysis.py``), so deleting a ``maybe_fail`` call site or an
emit field breaks the build, not the next chaos run. Everything here is
stdlib + ``ast`` only — the linter must run where no backend exists (CI
preambles, ``bench.py``'s self-check, a laptop without jax configured).
"""

from featurenet_tpu.analysis.lint import (
    Finding,
    RULE_NAMES,
    format_findings,
    format_sarif,
    package_root,
    run_lint,
)

# Populate the rule registry at package-import time: RULE_NAMES/RULES are
# part of the exported surface and must not read empty until the first
# run_lint call lazily imports the rules.
from featurenet_tpu.analysis import rules as _rules  # noqa: E402,F401

__all__ = [
    "Finding",
    "RULE_NAMES",
    "format_findings",
    "format_sarif",
    "package_root",
    "run_lint",
]
