"""Concurrency contract rules: lock discipline, deadlock ordering, and
thread lifecycle across the serving fleet.

The fleet arc turned the package thread-dense — batcher dispatcher,
autoscaler control thread, scraper daemon, checkpoint writer, heartbeat
monitors, router scale loop, probe threads — and fourteen modules now
construct their own ``threading.Lock``/``Condition``, each guarding
ad-hoc invariants that nothing checked. This module makes thread-safety
a statically-checked contract, four checks in one ``concurrency`` rule
family:

- **unlocked_write** — in any class that constructs a lock, a ``self._x``
  attribute written from ≥2 methods, at least one of which runs on a
  spawned thread (``threading.Thread(target=self...)`` targets, their
  in-class call closure, and the ``KNOWN_THREAD_ENTRY`` table of methods
  other components call from their own threads), must be written inside
  ``with <lock>:``. Deliberate single-writer sites carry
  ``# lint: allow-unlocked(<reason>)``. Methods named ``*_locked`` are
  the package's call-with-lock-held convention and count as locked.
- **condvar_wait_if** — a ``Condition.wait()`` whose innermost enclosing
  branch is an ``if`` instead of a ``while`` predicate loop misses the
  spurious-wakeup re-check; suppress with
  ``# lint: allow-condvar-if(<reason>)``. ``wait_for`` (which loops
  internally) and ``Event.wait`` (level-triggered) are exempt — the
  receiver must be condvar-like (assigned from ``threading.Condition``).
- **lock_order_cycle** — nested ``with lockA: ... with lockB:``
  acquisition edges are collected package-wide into a directed graph;
  any cycle is a potential deadlock, reported with file:line per edge.
  Lock identity is ``module:Class.attr`` for ``self`` locks and
  ``module:name`` for module-level locks. Suppress an edge with
  ``# lint: allow-lock-order(<reason>)`` on the inner acquisition line.
- **thread_leak** — a ``threading.Thread`` constructed in a class whose
  methods never ``.join()`` it has no shutdown contract: stop/drain/
  close would strand the thread. Stored threads (``self._t = Thread``)
  need a ``self._t.join(...)`` somewhere in the class; an unstored
  fire-and-forget construction needs a same-function ``join`` or a
  reasoned ``# lint: allow-thread-leak(<reason>)`` (e.g. the replica
  manager's bounded, self-terminating probe threads).

Everything is syntactic (stdlib ``ast``, no imports of the linted code),
like the rest of the analysis package: the linter must run where no
backend exists.
"""

from __future__ import annotations

import ast
from typing import Optional

from featurenet_tpu.analysis.lint import Finding, Module, Tree, register

# threading factory callables that produce a mutex-like object a `with`
# block can hold. Condition is included: `with self._cv:` holds the
# underlying lock, and the batcher/prefetcher guard state with it.
_LOCK_FACTORIES = ("Lock", "RLock", "Condition")

# Methods OTHER components invoke from their own threads — the spawned-
# thread entry points the in-class `threading.Thread(target=self...)`
# scan cannot see because the spawn happens elsewhere. One entry per
# (module relpath, class): the HTTP server's handler threads call the
# batcher's submit and the router's route; the autoscaler thread drives
# the replica manager's roster levers; every telemetry object is written
# from whatever thread held the sample. Growing a new cross-thread
# surface means growing this table — which is the point: the table IS
# the documented threading contract (see README "Static analysis").
KNOWN_THREAD_ENTRY: dict[tuple[str, str], tuple[str, ...]] = {
    # HTTP handler threads (ThreadingHTTPServer) admit requests and read
    # stats; the process main thread drains.
    ("serve/batcher.py", "ContinuousBatcher"): (
        "submit", "stats", "drain",
    ),
    # /admin/reload arrives on a handler thread while the dispatcher
    # serves; /healthz readers race the swap.
    ("serve/service.py", "InferenceService"): (
        "reload", "ready", "reloading", "stats", "drain",
    ),
    # The batcher's dispatcher thread offers every answered request.
    ("serve/recorder.py", "FlightRecorder"): (
        "maybe_capture", "stats", "close",
    ),
    # Router handler threads + the autoscaler thread drive the roster.
    ("fleet/replica.py", "ReplicaManager"): (
        "candidates", "note_inflight", "note_failure", "kill_one",
        "add_one", "shed_one", "ready_count", "stats",
    ),
    # Handler threads route; the manager thread reads scale state.
    ("fleet/router.py", "FleetRouter"): (
        "route", "scale_state", "stats", "drain",
    ),
    # Router request threads and manager probe threads share channels.
    ("fleet/pool.py", "ConnectionPool"): (
        "checkout", "checkin", "retire", "retire_endpoint", "post",
        "get", "close", "stats",
    ),
    # The manager pauses/stops the scrape loop from its own thread.
    ("fleet/scraper.py", "MetricsScraper"): (
        "pause", "stop", "stats",
    ),
    # Every instrumented thread feeds samples; /metrics snapshots.
    ("obs/windows.py", "WindowAggregator"): (
        "observe", "flush", "active_alerts", "snapshot", "samples",
    ),
    # Any thread may emit; close races the last emit.
    ("obs/events.py", "EventSink"): ("emit", "close"),
    # Dispatcher thread observes; /metrics reads stats.
    ("obs/quality.py", "QualityTracker"): ("observe", "stats"),
    # Scraper thread appends; the manager closes and queries.
    ("obs/tsdb.py", "TimeSeriesStore"): ("append", "close", "stats"),
    # The event tap calls on_event from WHATEVER thread emitted (window
    # aggregator threads, the burn evaluator, supervisor); /metrics
    # exporters read open_count; the owning service drains via disarm.
    ("obs/incidents.py", "IncidentManager"): (
        "on_event", "open_count", "open_ids", "stats", "disarm",
    ),
}


# --- shared AST helpers ------------------------------------------------------

def _threading_call(node: ast.AST, names: tuple[str, ...]) -> Optional[str]:
    """The factory name when ``node`` is ``threading.X(...)`` or a bare
    ``X(...)`` for ``X`` in ``names``; None otherwise."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "threading" and f.attr in names):
        return f.attr
    if isinstance(f, ast.Name) and f.id in names:
        return f.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """The attribute name when ``node`` is ``self.<attr>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _assign_pairs(node: ast.AST):
    """(target, value) pairs of an Assign/AnnAssign, tuple targets
    unpacked positionally (``a, self.x = b, None`` pairs ``self.x``
    with ``None``)."""
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)) and \
                    isinstance(node.value, (ast.Tuple, ast.List)) and \
                    len(tgt.elts) == len(node.value.elts):
                yield from zip(tgt.elts, node.value.elts)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    yield el, None
            else:
                yield tgt, node.value
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        yield node.target, node.value


def _write_targets(node: ast.AST):
    """Attribute nodes a statement writes: Assign (incl. tuple unpack),
    AugAssign, AnnAssign."""
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                yield from tgt.elts
            else:
                yield tgt
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        yield node.target


_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


class _ClassScan:
    """Everything the concurrency checks need to know about one class:
    its lock attributes, its thread attributes, which methods run on a
    spawned thread, and every ``self.<attr>`` write with its lock
    context."""

    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: dict[str, ast.AST] = {
            n.name: n for n in node.body if isinstance(n, _FuncDef)
        }
        # lock attr -> (factory kind, lineno of construction)
        self.locks: dict[str, tuple[str, int]] = {}
        # thread attr -> lineno of the Thread construction
        self.thread_attrs: dict[str, int] = {}
        # unstored Thread constructions: (lineno, enclosing method name)
        self.loose_threads: list[tuple[int, str]] = []
        # attrs `.join()`ed anywhere in the class (self.X.join(...))
        self.joined_attrs: set[str] = set()
        # methods that launch threads and the method names they target
        self.thread_targets: set[str] = set()
        for mname, fn in self.methods.items():
            # Locals snapshotting a self attr (`t = self._thread`): the
            # race-free join idiom reads the attr once and joins the
            # local — `t.join()` discharges `self._thread`.
            alias_of: dict[str, str] = {}
            for sub in ast.walk(fn):
                for tgt, val in _assign_pairs(sub):
                    if isinstance(tgt, ast.Name):
                        src = _self_attr(val) if val is not None else None
                        if src is not None:
                            alias_of[tgt.id] = src
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    kind = _threading_call(val, _LOCK_FACTORIES)
                    if kind is not None:
                        self.locks[attr] = (kind, val.lineno)
                    if _threading_call(val, ("Thread",)) is not None:
                        self.thread_attrs[attr] = val.lineno
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "join"):
                    attr = _self_attr(sub.func.value)
                    if attr is None and isinstance(sub.func.value,
                                                   ast.Name):
                        attr = alias_of.get(sub.func.value.id)
                    if attr is not None:
                        self.joined_attrs.add(attr)
                if _threading_call(sub, ("Thread",)) is not None:
                    for kw in (sub.keywords
                               if isinstance(sub, ast.Call) else ()):
                        if kw.arg == "target":
                            tattr = _self_attr(kw.value)
                            if tattr is not None:
                                self.thread_targets.add(tattr)
        self.thread_methods = self._thread_closure()

    def _thread_closure(self) -> set[str]:
        """Methods that (may) run on a spawned thread: the in-class
        ``Thread(target=self.X)`` targets plus the KNOWN_THREAD_ENTRY
        rows for this class, closed over in-class ``self.Y()`` calls."""
        entry = set(self.thread_targets)
        entry.update(KNOWN_THREAD_ENTRY.get(
            (self.mod.relpath, self.name), ()
        ))
        seen: set[str] = set()
        frontier = [m for m in entry if m in self.methods]
        while frontier:
            mname = frontier.pop()
            if mname in seen:
                continue
            seen.add(mname)
            for sub in ast.walk(self.methods[mname]):
                if isinstance(sub, ast.Call):
                    callee = _self_attr(sub.func)
                    if callee in self.methods and callee not in seen:
                        frontier.append(callee)
        return seen

    def writes(self):
        """Every ``self.<attr>`` write outside ``__init__``:
        (attr, method name, lineno, locked) — ``locked`` is True when
        the write sits inside ``with self.<lock>:`` for one of this
        class's lock attrs, or in a ``*_locked`` method (the package's
        call-with-lock-held convention)."""
        out: list[tuple[str, str, int, bool]] = []

        def visit(node: ast.AST, method: str, locked: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquires = any(
                    _self_attr(item.context_expr) in self.locks
                    for item in node.items
                )
                for child in node.body:
                    visit(child, method, locked or acquires)
                return
            for tgt in _write_targets(node):
                attr = _self_attr(tgt)
                if attr is not None:
                    out.append((attr, method, node.lineno, locked))
            for child in ast.iter_child_nodes(node):
                visit(child, method, locked)

        for mname, fn in self.methods.items():
            if mname == "__init__":
                continue
            held = mname.endswith("_locked")
            for stmt in fn.body:
                visit(stmt, mname, held)
        return out


def _class_scans(tree: Tree):
    for mod in tree.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield _ClassScan(mod, node)


# --- check (a): lock discipline ----------------------------------------------

def _unlocked_writes(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    for scan in _class_scans(tree):
        if not scan.locks:
            continue  # lock-less classes guard nothing; out of contract
        writers: dict[str, set[str]] = {}
        for attr, method, _, _ in scan.writes():
            writers.setdefault(attr, set()).add(method)
        guarded = {
            attr for attr, methods in writers.items()
            if len(methods) >= 2 and methods & scan.thread_methods
            and attr not in scan.locks and attr not in scan.thread_attrs
        }
        for attr, method, lineno, locked in scan.writes():
            if attr not in guarded or locked:
                continue
            if scan.mod.suppressed(lineno, "unlocked"):
                continue
            findings.append(Finding(
                "concurrency", "unlocked_write", scan.mod.path, lineno,
                f"{scan.name}.{attr} is written from "
                f"{len(writers[attr])} methods "
                f"({', '.join(sorted(writers[attr]))}) including a "
                f"spawned-thread path, but this write in {method}() "
                f"holds none of the class's locks "
                f"({', '.join(sorted(scan.locks))}) — wrap it in "
                "`with <lock>:` or annotate "
                "# lint: allow-unlocked(<why single-writer>)",
            ))
    return findings


# --- check (b): condvar wait under `if` --------------------------------------

def _condvar_idents(mod: Module) -> set[str]:
    """Identifiers bound to a ``threading.Condition`` anywhere in the
    module: ``self.X`` attrs and bare names (module or function scope).
    Name-keyed module-wide — a rename collision across classes is
    conceivable but only widens the check to more ``.wait()`` sites."""
    idents: set[str] = set()
    for node in ast.walk(mod.tree):
        for tgt, val in _assign_pairs(node):
            if _threading_call(val, ("Condition",)) is None:
                continue
            attr = _self_attr(tgt)
            if attr is not None:
                idents.add(f"self.{attr}")
            elif isinstance(tgt, ast.Name):
                idents.add(tgt.id)
    return idents


def _render_receiver(node: ast.AST) -> Optional[str]:
    attr = _self_attr(node)
    if attr is not None:
        return f"self.{attr}"
    if isinstance(node, ast.Name):
        return node.id
    return None


def _condvar_wait_if(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    for mod in tree.modules:
        condvars = _condvar_idents(mod)
        if not condvars:
            continue

        def visit(node: ast.AST, branch_stack: list[str]):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"
                    and _render_receiver(node.func.value) in condvars):
                innermost = branch_stack[-1] if branch_stack else None
                if innermost == "if" and not mod.suppressed(
                        node.lineno, "condvar-if"):
                    findings.append(Finding(
                        "concurrency", "condvar_wait_if", mod.path,
                        node.lineno,
                        f"{_render_receiver(node.func.value)}.wait() is "
                        "guarded by `if`, not a `while` predicate loop — "
                        "a spurious or stolen wakeup proceeds without "
                        "the condition holding; re-check in a while "
                        "(or annotate # lint: allow-condvar-if(<why>))",
                    ))
            pushed = None
            if isinstance(node, ast.While):
                pushed = "while"
            elif isinstance(node, ast.If):
                pushed = "if"
            if pushed:
                branch_stack.append(pushed)
            for child in ast.iter_child_nodes(node):
                visit(child, branch_stack)
            if pushed:
                branch_stack.pop()

        visit(mod.tree, [])
    return findings


# --- check (c): lock-order graph ---------------------------------------------

def _module_locks(mod: Module) -> dict[str, str]:
    """Module-level lock names -> factory kind."""
    out: dict[str, str] = {}
    for node in mod.tree.body:
        for tgt, val in _assign_pairs(node):
            kind = _threading_call(val, _LOCK_FACTORIES)
            if kind is not None and isinstance(tgt, ast.Name):
                out[tgt.id] = kind
    return out


def _lock_order_edges(tree: Tree):
    """Directed acquisition edges (outer_id, inner_id, mod, lineno) from
    syntactically nested ``with`` blocks, plus each lock's factory kind.
    Lock ids: ``relpath:Class.attr`` for self locks, ``relpath:name``
    for module-level locks."""
    edges: list[tuple[str, str, Module, int]] = []
    kinds: dict[str, str] = {}
    for mod in tree.modules:
        mod_locks = _module_locks(mod)
        for name, kind in mod_locks.items():
            kinds[f"{mod.relpath}:{name}"] = kind

        def lock_id(expr: ast.AST, cls: Optional[_ClassScan]
                    ) -> Optional[str]:
            attr = _self_attr(expr)
            if attr is not None and cls is not None and attr in cls.locks:
                return f"{mod.relpath}:{cls.name}.{attr}"
            if isinstance(expr, ast.Name) and expr.id in mod_locks:
                return f"{mod.relpath}:{expr.id}"
            return None

        def visit(node: ast.AST, held: list[str],
                  cls: Optional[_ClassScan]):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: list[str] = []
                for item in node.items:
                    lid = lock_id(item.context_expr, cls)
                    if lid is None:
                        continue
                    for outer in held + acquired:
                        edges.append((outer, lid, mod, node.lineno))
                    acquired.append(lid)
                for child in node.body:
                    visit(child, held + acquired, cls)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held, cls)

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                scan = _ClassScan(mod, node)
                for kname, (kind, _) in scan.locks.items():
                    kinds[f"{mod.relpath}:{scan.name}.{kname}"] = kind
                for fn in scan.methods.values():
                    for stmt in fn.body:
                        visit(stmt, [], scan)
        # Module-level / free-function nesting (outside any class).
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                visit(node, [], None)
    return edges, kinds


def _find_cycles(graph: dict[str, set[str]]) -> list[list[str]]:
    """Simple cycles via DFS back-edge reconstruction; each cycle is
    canonicalized (rotated to its minimum node) and reported once."""
    cycles: list[list[str]] = []
    seen_keys: set[tuple[str, ...]] = set()

    def dfs(node: str, stack: list[str], on_stack: set[str],
            visited: set[str]):
        visited.add(node)
        stack.append(node)
        on_stack.add(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):]
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in seen_keys:
                    seen_keys.add(canon)
                    cycles.append(list(canon))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return cycles


def _lock_order_cycles(tree: Tree) -> list[Finding]:
    edges, kinds = _lock_order_edges(tree)
    graph: dict[str, set[str]] = {}
    for outer, inner, _, _ in edges:
        if outer == inner and kinds.get(outer) == "RLock":
            continue  # re-entrant self-acquisition is the RLock contract
        graph.setdefault(outer, set()).add(inner)
    findings: list[Finding] = []
    for cycle in _find_cycles(graph):
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        sites: list[tuple[Module, int]] = []
        for a, b in pairs:
            for outer, inner, mod, lineno in edges:
                if (outer, inner) == (a, b):
                    sites.append((mod, lineno))
                    break
        if any(mod.suppressed(lineno, "lock-order")
               for mod, lineno in sites):
            continue
        edge_txt = "; ".join(
            f"{a} -> {b} at {mod.relpath}:{lineno}"
            for (a, b), (mod, lineno) in zip(pairs, sites)
        )
        anchor_mod, anchor_line = sites[0]
        findings.append(Finding(
            "concurrency", "lock_order_cycle", anchor_mod.path,
            anchor_line,
            f"lock acquisition cycle {' -> '.join(cycle + [cycle[0]])} "
            f"— potential deadlock; edges: {edge_txt}. Break the cycle "
            "or annotate an edge with # lint: allow-lock-order(<why>)",
        ))
    return findings


# --- check (d): thread lifecycle ---------------------------------------------

def _thread_leaks(tree: Tree) -> list[Finding]:
    findings: list[Finding] = []
    for scan in _class_scans(tree):
        for attr, lineno in sorted(scan.thread_attrs.items()):
            if attr in scan.joined_attrs:
                continue
            if scan.mod.suppressed(lineno, "thread-leak"):
                continue
            findings.append(Finding(
                "concurrency", "thread_leak", scan.mod.path, lineno,
                f"{scan.name}.{attr} is a threading.Thread no method of "
                f"{scan.name} ever joins — the stop/drain/close path "
                "strands it; join it on shutdown or annotate "
                "# lint: allow-thread-leak(<why unjoined is safe>)",
            ))
        # Unstored constructions: Thread(...) not assigned to self.<attr>
        # and whose local name (if any) is never joined in the same
        # method — fire-and-forget with no shutdown contract.
        for mname, fn in scan.methods.items():
            stored_lines = {
                val.lineno
                for sub in ast.walk(fn)
                for tgt, val in _assign_pairs(sub)
                if _self_attr(tgt) is not None
                and _threading_call(val, ("Thread",)) is not None
            }
            local_joined: set[str] = set()
            local_threads: dict[str, int] = {}
            anonymous: list[int] = []
            for sub in ast.walk(fn):
                for tgt, val in _assign_pairs(sub):
                    if (_threading_call(val, ("Thread",)) is not None
                            and isinstance(tgt, ast.Name)):
                        local_threads[tgt.id] = val.lineno
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "join"
                        and isinstance(sub.func.value, ast.Name)):
                    local_joined.add(sub.func.value.id)
                if (_threading_call(sub, ("Thread",)) is not None
                        and sub.lineno not in stored_lines):
                    anonymous.append(sub.lineno)
            anonymous = [
                ln for ln in anonymous
                if ln not in local_threads.values()
            ]
            for name, ln in sorted(local_threads.items()):
                if name not in local_joined:
                    anonymous.append(ln)
            for ln in sorted(set(anonymous)):
                if scan.mod.suppressed(ln, "thread-leak"):
                    continue
                findings.append(Finding(
                    "concurrency", "thread_leak", scan.mod.path, ln,
                    f"fire-and-forget threading.Thread in "
                    f"{scan.name}.{mname}() is neither stored on self "
                    "nor joined in this method — no shutdown path can "
                    "wait it out; store/join it or annotate "
                    "# lint: allow-thread-leak(<why unjoined is safe>)",
                ))
    return findings


# --- the rule family ---------------------------------------------------------

@register("concurrency")
def concurrency_rule(tree: Tree) -> list[Finding]:
    """Lock discipline, condvar predicates, lock-order cycles, and
    thread lifecycle — the serving fleet's threading contract (see the
    module docstring for each check's exact shape)."""
    findings: list[Finding] = []
    findings.extend(_unlocked_writes(tree))
    findings.extend(_condvar_wait_if(tree))
    findings.extend(_lock_order_cycles(tree))
    findings.extend(_thread_leaks(tree))
    return findings
