"""Frozen run configs — the five BASELINE.json configs as named presets.

The reference drove everything through argparse flags on ``train.py``
(SURVEY.md §2 C8). Here the single source of truth is a frozen dataclass:
hashable (so it can parameterize jit caches), serializable into checkpoints,
and overridable field-by-field from the CLI (``featurenet_tpu.cli``).

Presets map 1:1 onto BASELINE.json's config ladder:
  smoke16 — 16³ single-feature, tiny net, CPU smoke            (config 1)
  xla32   — 32³, full FeatureNet stack, single-chip XLA        (config 2)
  pod64   — 64³ published config, data-parallel over the mesh  (config 3)
  seg64   — 64³ multi-feature per-voxel segmentation           (config 4)
  abc128  — 128³ deeper net, pod-scale, spatial partitioning   (config 5)
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from featurenet_tpu.models.featurenet import (
    FeatureNetArch,
    deep_arch,
    tiny_arch,
)


@dataclasses.dataclass(frozen=True)
class Config:
    name: str = "pod64"
    # Task: "classify" (24-way logits) or "segment" (per-voxel dense logits).
    task: str = "classify"

    # Data. data_cache: path to an offline npz cache (``featurenet_tpu.data
    # .offline``); None = on-the-fly synthetic generation. With a cache, eval
    # runs a full deterministic pass over the held-out test split.
    resolution: int = 64
    global_batch: int = 96
    num_features: int = 1  # features carved per part (>1 for segmentation)
    eval_batches: int = 8
    data_workers: int = 2
    seed: int = 0
    data_cache: Optional[str] = None
    test_fraction: float = 0.2
    # Train-time pose augmentation (cube-group rotations) for cache-backed
    # training; synthetic streaming already randomizes pose at generation.
    # augment_device moves the rotations into the compiled train step
    # (ops/augment.py; classification only) so host workers just gather —
    # augment_groups independent poses per batch.
    augment: bool = True
    augment_device: bool = True
    augment_groups: int = 8
    # Occupancy bit-flip augmentation inside the compiled step (fraction of
    # voxels flipped per sample; 0 = off). Robustness lever: the round-4
    # OOD harness measured 0.5% flips costing the unaugmented flagship 39
    # accuracy points.
    augment_noise: float = 0.0
    # Arbitrary-angle SO(3) rotation + uniform scale resampling inside the
    # compiled step (ops/augment.random_affine_batch_paired) — replaces
    # the cube-group rotation when on. The OOD-robustness training mode:
    # infinite pose diversity (a statically rotated cache overfits).
    # Segmentation warps the per-voxel target with shared transforms
    # (nearest-neighbor).
    augment_affine: bool = False
    # Robust-recipe knobs (round 5, BASELINE.md "robust64"): per-group
    # probability the warp applies (clean/affine batch mixing — the rest
    # of the batch stays on the normalized serving distribution); a linear
    # 0→prob ramp over the first augment_ramp_steps; rotation toggle
    # (off = scale+translate only, the parameter-extrapolation mode);
    # scale window; uniform per-axis translation draw in voxels.
    augment_affine_prob: float = 1.0
    augment_ramp_steps: int = 0
    augment_affine_rotate: bool = True
    augment_scale_range: tuple[float, float] = (0.7, 1.05)
    augment_translate_vox: float = 0.0
    # Warm start: load params + batch_stats (NOT step / optimizer state)
    # from this checkpoint directory at init — fine-tune semantics. A
    # checkpoint in checkpoint_dir still wins (resume beats warm start, so
    # supervised fine-tune runs restart correctly).
    init_from: Optional[str] = None

    # Model.
    arch: FeatureNetArch = dataclasses.field(default_factory=FeatureNetArch)
    seg_features: tuple[int, ...] = (32, 64, 128)
    # Segmentation loss variant (train/steps.segmentation_loss):
    # "balanced_ce", "ce_dice", or "dice".
    seg_loss: str = "balanced_ce"
    # Segmenter architecture levers (round-4, driven by seg_diagnose's gap
    # attribution — see models/segmenter.py): input context channels for
    # the global through/blind signal, and decoder/bottleneck capacity for
    # boundary assignment. Identity fields: they change the param tree.
    seg_input_context: str = "none"
    seg_decoder_blocks: int = 1
    seg_bottleneck_blocks: int = 1

    # Optimization.
    optimizer: str = "adamw"
    peak_lr: float = 1e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    total_steps: int = 3000
    label_smoothing: float = 0.0
    # Global-norm gradient clipping; 0 disables. Recipe-stability lever for
    # rough loss surfaces (the warp64 stride-4 stem's mid-schedule eval
    # collapses — BASELINE.md round-3/4 recipe study).
    grad_clip: float = 0.0
    # Training precision policy (train/precision.py): "fp32" (the
    # identity — fp32 params through the step, unchanged executable),
    # "bf16_master" (the optimizer holds fp32 master weights while the
    # jitted step casts a bf16 working copy for forward/backward, stores
    # bf16 gradients, and upcasts them to fp32 for the update), or
    # "fp16_scaled" (the same master/working split at float16, plus
    # dynamic loss scaling: the scale doubles after N clean steps and a
    # non-finite gradient tree halves it and skips the update bitwise —
    # the skip/scale state rides TrainState, so checkpoints restore it).
    # Masters are what checkpoints persist, so a checkpoint restores
    # bitwise across modes; the runtime registry fingerprints the train
    # executables apart (a bf16-master world never loads an fp32
    # program). Run policy, not identity.
    train_precision: str = "fp32"
    # Serving/eval precision policy (the inference half of the ladder —
    # train/precision.serve_params_cast): "fp32" (identity), "bf16"
    # (the serve/serve_packed programs take a bf16 working copy cast
    # once at Predictor construction — half the weight reads per
    # dispatch; eval_step compiles the cast inside for accuracy-faithful
    # eval; masters and BN stats stay fp32), or "int8" (the per-channel
    # weight-quantized programs, runtime/quantize.py). Selects which
    # serving catalog programs the Predictor/InferenceService build and
    # which cast eval_step compiles; the precision lands in every
    # ProgramSpec and the exec-cache fingerprint exactly as
    # train_precision does, so cross-precision cache hits stay
    # impossible. Every reduced rung is gated by the precision-agnostic
    # agreement check at the paper's 96.7% bar. Run policy, not identity.
    serve_precision: str = "fp32"

    # Parallelism (mesh axis sizes; None = use all available devices on data).
    mesh_data: Optional[int] = None
    mesh_model: int = 1
    # Shard the voxel depth axis over 'model' (XLA conv halo exchange) — the
    # 128³-grids-outgrow-HBM path. Needs mesh_model > 1 to have any effect.
    spatial: bool = False
    # Elastic multi-host training (featurenet_tpu.elastic, `cli train
    # --elastic`): the run is owned by an elastic coordinator that
    # re-forms the mesh at the surviving process count on host loss
    # (resume from the latest checksummed checkpoint, per-host batch
    # rescaled so global_batch is preserved) and re-admits recovered
    # hosts at the next generation boundary. The flag is inert inside a
    # training child (the coordinator launches before any backend);
    # min_world_size is the smallest world the planner may form — fewer
    # surviving hosts forces a full-strength restart instead of a
    # shrink, and an unformable world is the coordinator's give-up
    # verdict.
    elastic: bool = False
    min_world_size: int = 1

    # Planned periodic restart (supervised runs): exit cleanly-for-restart
    # every N steps after checkpointing; the supervisor (train.supervisor)
    # respawns without charging the restart budget. Motivation: this
    # environment's tunneled-TPU client leaks host RSS roughly linearly
    # with steps and throughput decays with it (BASELINE.md seg64 notes)
    # — a fresh process restores full speed and the Orbax resume makes the
    # handoff exact.
    restart_every_steps: Optional[int] = None
    # Device-resident dataset: upload the packed train split into HBM once
    # (sharded over the mesh's data axis) and draw every train batch ON
    # DEVICE (train.steps.make_hbm_multi_train_step) — zero per-step
    # host→device input traffic. The natural fit for this benchmark's
    # scale: the 24×1000 64³ split bit-packed is ~600 MB (seg cache
    # ~0.5 GB) against 16 GB of v5e HBM. Requires data_cache; incompatible
    # with spatial sharding (the resident array shards batch rows, not
    # depth). Augmentation runs in-step on device for both tasks.
    hbm_cache: bool = False
    # Pipelined dispatch: fuse this many train steps into one XLA
    # executable (train.steps.make_multi_train_step), so one host→device
    # dispatch carries k optimizer updates. Amortizes per-step dispatch
    # latency on slow hosts/links (this environment's tunnel charges
    # ~11 ms/call — BASELINE.md round 3); numerics match k sequential
    # single-step dispatches to one-ulp (XLA fusion reassociation only).
    # Logging/eval/checkpoint cadences keep their step semantics but fire
    # on dispatch boundaries.
    steps_per_dispatch: int = 1
    # Clamp steps_per_dispatch against the analytic HBM byte model
    # (ops/membytes.max_feasible_k) before compiling the fused executable.
    # True (the default) protects preset-derived k values degrading onto
    # smaller hardware; the CLI sets False when --steps-per-dispatch is
    # passed explicitly, so an operator can opt out of the first-order
    # model (the warning still fires — the OOM risk is theirs).
    clamp_dispatch_k: bool = True
    # Backpressure: max train steps dispatched ahead of confirmed execution.
    # Async dispatch with no bound pins every in-flight batch in memory; on
    # backends where block_until_ready is unreliable (this environment's
    # tunneled TPU) only a readback confirms progress, so the loop forces a
    # scalar readback of the metrics from `max_inflight_steps` ago.
    max_inflight_steps: int = 8

    # Profiling: when set, steps [profile_start, profile_start+profile_steps)
    # are captured with jax.profiler into this directory (XProf/TensorBoard).
    profile_dir: Optional[str] = None
    profile_start: int = 10
    profile_steps: int = 5

    # Logging / checkpointing. tb_dir: also mirror scalar metrics to
    # TensorBoard event files (CLU metric_writers).
    tb_dir: Optional[str] = None
    # Run-scoped observability (featurenet_tpu.obs): when set, the run
    # writes a manifest (run.json) and an append-only event log
    # (events.jsonl) into this directory — timing spans, gauges, metrics,
    # warnings, heartbeats, supervisor restarts. Analyze post-hoc with
    # `python -m featurenet_tpu.cli report <run_dir>`. None (default) =
    # no obs file I/O and zero dispatch-path overhead.
    run_dir: Optional[str] = None
    # Fault injection (featurenet_tpu.faults): a comma-separated chaos
    # spec like "checkpoint_corrupt@save=2,sigterm@step=120" that makes
    # the run fail in a scripted, deterministic way so the recovery paths
    # (checkpoint fallback, preemption resume, supervisor restart, sink
    # degradation) are *tested* properties, not claims. A
    # ":every=M" suffix (sigterm@step=100:every=50) re-fires the fault on
    # every M-counter stride — soak testing. None (default) = every
    # injection site is a single attribute check — no step-loop overhead.
    # One-shot (or, with every=, per-firing) markers live in run_dir, so a
    # supervised run's respawned children don't re-fire the same fault.
    inject_faults: Optional[str] = None
    # Live SLO alert rules (featurenet_tpu.obs.alerts): comma list of
    # "metric(>|<)threshold[:severity]" entries evaluated over the run's
    # rolling windows — e.g. "data_wait_fraction>0.6:critical,
    # serving_p99_ms>20". None = the default rule set (data-wait
    # fraction, step-time p99/median ratio, heartbeat age, cross-host
    # data-wait spread); an explicit spec replaces it. Violations fire
    # structured `alert` events — rendered by `cli report` (SLO section)
    # and `--follow` — and are never load-bearing. Only meaningful with
    # run_dir (no sink, no windows).
    alert_rules: Optional[str] = None
    # Request-level tracing sample rate (featurenet_tpu.obs.tracing):
    # the fraction of HEALTHY serving requests whose admit→dispatch→done
    # timeline lands in the event stream. The decision is a pure hash of
    # the trace id (deterministic, so every host and the fleet router
    # agree for free) and tail-biased: rejections, forward errors, and
    # requests breaching the serving SLO are ALWAYS sampled regardless
    # of the rate — at 0.0 the stream still carries every bad request.
    # 1.0 (default) traces everything; production fleets lower it to
    # bound log cardinality. Only meaningful with run_dir (no sink, no
    # events); the measured cost is pinned as trace_overhead_pct in the
    # bench gate.
    trace_sample: float = 1.0
    # Persistent AOT executable cache (featurenet_tpu.runtime.cache): when
    # set, every compiled program the runtime registry builds — train
    # steps, eval, serving forwards — is serialized into this directory
    # and later processes (supervisor respawns, preemption resumes,
    # serving cold starts) deserialize instead of re-paying XLA
    # compilation. Loads are guarded (probe-in-subprocess; see the cache
    # module's sandbox-abort hazard note) and any failure degrades to a
    # fresh compile with a cache_reject event. None (default) = no
    # serialization, no deserialization, anywhere. The
    # FEATURENET_EXEC_CACHE_DIR env var supplies a fleet-wide default.
    exec_cache_dir: Optional[str] = None
    # Live device-memory watermark (featurenet_tpu.obs.perf): when on,
    # the Trainer samples jax.local_devices()[i].memory_stats() at every
    # heartbeat — off the dispatch hot path by construction — and emits
    # device_memory events (the report's watermark line and a Chrome-
    # trace counter track). Opt-in because it is extra per-beat work;
    # backends without stats (CPU) degrade silently to no events. Only
    # meaningful with run_dir (no sink, no events).
    poll_device_memory: bool = False
    # Liveness: when set, the Trainer touches this file at every confirmed
    # point of progress (a device readback, an eval, a checkpoint). A
    # supervisor (train.supervisor / `cli train --supervise`) watches the
    # mtime to detect stalled runs — e.g. a hung device tunnel — and
    # restarts from the latest checkpoint.
    heartbeat_file: Optional[str] = None
    log_every: int = 50
    eval_every: int = 500
    checkpoint_every: int = 500
    checkpoint_dir: Optional[str] = None
    keep_checkpoints: int = 3

    @property
    def device_augment(self) -> bool:
        """Whether pose augmentation runs inside the compiled train step
        (ops/augment.py) rather than in host data workers. Single source of
        truth shared by the Trainer and the host-feed benchmark.

        HBM-resident mode: always in-step when augmenting — there is no
        host pass; segment rotates voxels + per-voxel targets jointly
        (random_rotate_batch_paired). Streamed mode: cache-backed
        classification only — synthetic streaming randomizes pose at
        generation, and streamed segmentation rotates on the host."""
        if not (self.augment and self.augment_groups > 0):
            return False
        if self.hbm_cache:
            return True
        return bool(
            self.data_cache and self.augment_device
            and self.task == "classify"
        )

    def validate(self) -> "Config":
        if self.task not in ("classify", "segment"):
            raise ValueError(f"unknown task {self.task!r}")
        if self.inject_faults:
            # A typo'd site/counter must fail at config time — a spec that
            # silently never fires makes a chaos test pass by testing
            # nothing.
            from featurenet_tpu import faults as _faults

            _faults.parse_spec(self.inject_faults)
        if self.alert_rules:
            # Same refusal convention: an alert rule naming a metric that
            # does not exist would silently never evaluate — an SLO that
            # watches nothing.
            from featurenet_tpu.obs.alerts import parse_rules as _rules

            _rules(self.alert_rules)
        if self.seg_loss not in ("balanced_ce", "ce_dice", "dice"):
            raise ValueError(f"unknown seg_loss {self.seg_loss!r}")
        if self.train_precision not in ("fp32", "bf16_master",
                                        "fp16_scaled"):
            # Literal set mirrored by the CLI's --train-precision choices
            # and train.precision.TRAIN_PRECISIONS (the config-cli lint
            # rule cross-checks the CLI surface against this guard).
            raise ValueError(
                f"unknown train_precision {self.train_precision!r}; one "
                "of fp32, bf16_master, fp16_scaled"
            )
        if self.serve_precision not in ("fp32", "bf16", "int8"):
            # Mirrored by --serve-precision / --precision choices and
            # train.precision.SERVE_PRECISIONS (config-cli lint checks).
            raise ValueError(
                f"unknown serve_precision {self.serve_precision!r}; one "
                "of fp32, bf16, int8"
            )
        if self.arch.conv_backend not in ("xla", "pallas", "hybrid_dw",
                                          "fused33"):
            # Mirrored by the CLI's --conv-backend choices. An unknown
            # backend would otherwise silently fall through ConvBNRelu's
            # else-branch and run XLA under the wrong label.
            raise ValueError(
                f"unknown arch.conv_backend {self.arch.conv_backend!r}; "
                "one of xla, pallas, hybrid_dw, fused33"
            )
        if self.seg_input_context not in ("none", "proj", "proj_coords"):
            raise ValueError(
                f"unknown seg_input_context {self.seg_input_context!r}"
            )
        if self.seg_decoder_blocks < 1 or self.seg_bottleneck_blocks < 1:
            raise ValueError(
                "seg_decoder_blocks and seg_bottleneck_blocks must be >= 1"
            )
        if self.min_world_size < 1:
            raise ValueError(
                f"min_world_size must be >= 1, got {self.min_world_size}"
            )
        if self.min_world_size != 1 and not self.elastic:
            # Parse-and-ignore refusal (the same convention as the affine
            # knobs): a world-size floor only means something to the
            # elastic coordinator.
            raise ValueError(
                "min_world_size configured but elastic is off — the floor "
                "would be silently ignored; pass elastic=True (--elastic)"
            )
        if self.restart_every_steps is not None:
            if self.restart_every_steps <= 0:
                raise ValueError(
                    f"restart_every_steps must be positive, got "
                    f"{self.restart_every_steps}"
                )
            if not self.checkpoint_dir:
                raise ValueError(
                    "restart_every_steps requires checkpoint_dir: a "
                    "segmented run resumes from its checkpoint, and "
                    "silently ignoring the flag would leave the RSS-leak "
                    "mitigation off"
                )
        if self.hbm_cache:
            if self.spatial:
                raise ValueError(
                    "hbm_cache is incompatible with spatial sharding: the "
                    "resident dataset shards batch rows over 'data', not "
                    "depth over 'model'"
                )
            if not self.data_cache:
                raise ValueError(
                    "hbm_cache requires data_cache (the split that gets "
                    "uploaded is the offline cache's train split)"
                )
            if self.augment and self.augment_groups < 1:
                raise ValueError(
                    "hbm_cache with augment=True needs augment_groups >= 1:"
                    " the resident dataset's only augmentation path is the"
                    " in-step device rotation"
                )
            if (self.task == "classify" and self.augment
                    and not self.augment_device):
                raise ValueError(
                    "hbm_cache with augment=True requires device "
                    "augmentation (augment_device=True): the resident "
                    "dataset has no host-side augmentation path, so "
                    "augment=True would otherwise be silently ignored — "
                    "pass augment=False to train unaugmented"
                )
        # A list-valued scale range (hand-built Config; config_from_dict
        # already tuple-izes) must compare equal to the tuple default.
        scale_range = tuple(self.augment_scale_range)
        if scale_range != self.augment_scale_range:
            object.__setattr__(self, "augment_scale_range", scale_range)
        if not self.augment_affine:
            # Knobs of a disabled mechanism must not parse-and-ignore (the
            # same refusal convention as the hbm/augment guards below).
            non_default = [
                n for n, d in (
                    ("augment_affine_prob", 1.0),
                    ("augment_ramp_steps", 0),
                    ("augment_affine_rotate", True),
                    ("augment_scale_range", (0.7, 1.05)),
                    ("augment_translate_vox", 0.0),
                ) if getattr(self, n) != d
            ]
            if non_default:
                raise ValueError(
                    f"{', '.join(non_default)} configured but "
                    "augment_affine is off — the knobs would be silently "
                    "ignored; pass augment_affine=True (--augment-affine)"
                )
        if not (0.0 < self.augment_affine_prob <= 1.0):
            raise ValueError(
                f"augment_affine_prob is a per-group probability in "
                f"(0, 1]; got {self.augment_affine_prob}"
            )
        if self.augment_ramp_steps < 0:
            raise ValueError("augment_ramp_steps must be >= 0")
        if self.augment_translate_vox < 0:
            raise ValueError("augment_translate_vox must be >= 0 voxels")
        lo, hi = self.augment_scale_range
        if not (0.0 < lo <= hi):
            raise ValueError(
                f"augment_scale_range must satisfy 0 < lo <= hi; got "
                f"({lo}, {hi})"
            )
        if self.augment_affine and not self.augment_affine_rotate \
                and self.augment_scale_range == (1.0, 1.0) \
                and self.augment_translate_vox == 0.0:
            raise ValueError(
                "augment_affine with rotation off, scale (1,1), and "
                "translate 0 is the identity — disable augment_affine "
                "instead of paying the resample for nothing"
            )
        if self.augment_affine and not self.device_augment:
            raise ValueError(
                "augment_affine runs inside the compiled step and needs "
                "device augmentation active (augment=True, "
                "augment_groups>=1, and a data_cache with "
                "augment_device=True or hbm_cache) — as configured the "
                "flag would be silently ignored"
            )
        if not (0.0 <= self.augment_noise < 0.5):
            raise ValueError(
                f"augment_noise is a per-voxel bit-flip probability in "
                f"[0, 0.5); got {self.augment_noise} (0.01 = 1% of voxels)"
            )
        if not (0.0 <= self.trace_sample <= 1.0):
            raise ValueError(
                f"trace_sample is a probability in [0, 1]; got "
                f"{self.trace_sample}"
            )
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{self.steps_per_dispatch}"
            )
        if self.augment and self.augment_device and self.augment_groups < 1:
            raise ValueError(
                "augment_groups must be >= 1 when device augmentation is "
                "enabled (use augment=False or augment_device=False to "
                "disable augmentation)"
            )
        if self.resolution % 8:
            raise ValueError(
                "resolution must be divisible by 8 (the wire format "
                "bit-packs voxels along the W axis)"
            )
        if self.task == "segment":
            down = 2 ** len(self.seg_features)
            if self.resolution % down:
                raise ValueError(
                    f"segment task: resolution {self.resolution} must be "
                    f"divisible by 2**len(seg_features) = {down}"
                )
        return self


def smoke16() -> Config:
    return Config(
        name="smoke16",
        resolution=16,
        global_batch=32,
        arch=tiny_arch(),
        peak_lr=3e-3,
        warmup_steps=10,
        total_steps=200,
        log_every=20,
        eval_every=100,
        checkpoint_every=100,
        eval_batches=2,
    ).validate()


def xla32() -> Config:
    return Config(
        name="xla32",
        resolution=32,
        global_batch=64,
        total_steps=2000,
    ).validate()


def pod64() -> Config:
    # peak_lr: 1e-3 collapses this config into the uniform-output absorbing
    # state within ~25 steps (loss pins at ln 24, grad norm → 0.1; measured
    # on TPU v5e with fresh-stream 64³ batches — BASELINE.md). 3e-4 with a
    # longer warmup trains stably; 1e-4 works too but slower.
    # global_batch: the *per-chip* batch shard is padded to a multiple of
    # 128 by XLA's tiling (measured single-chip: batch 96 and 128 both take
    # ~53 ms/step, so 96 wasted 25% — BASELINE.md). 128 is the single-chip
    # preset; on an N-chip data mesh set global_batch = 128·N so each shard
    # stays a multiple of 128. Accuracy re-validated at 128 (98.8% at the
    # 576k-sample budget, vs 99.33% at 96 — run-to-run variance).
    return Config(
        name="pod64",
        resolution=64,
        global_batch=128,
        total_steps=5000,
        peak_lr=3e-4,
        warmup_steps=200,
    ).validate()


def fast64() -> Config:
    # The TPU-first 64³ config (round-2 ceiling attack, BASELINE.md):
    # conv2's 5³ window shrinks to 3³ — the 2018 GPU-era 5³ choice put 72%
    # of the step's FLOPs into one Cout=32-starved contraction (25% MXU
    # shape ceiling); at 3³ the same stack measures 5542 samples/sec/chip
    # at batch 256 (2.3× the paper-shape arch, 16.8× the V100 estimate).
    # Accuracy parity with the paper shape is validated on the 24×1000
    # benchmark before this preset is advertised (see BASELINE.md).
    return Config(
        name="fast64",
        resolution=64,
        global_batch=256,
        arch=dataclasses.replace(FeatureNetArch(), kernels=(7, 3, 3, 3)),
        total_steps=4000,  # ~the flagship's 900k-sample budget at batch 256
        peak_lr=3e-4,
        warmup_steps=200,
    ).validate()


def turbo64() -> Config:
    # fast64's successor (round 2, second iteration): additionally pool
    # right after the s2d stem, so conv2 runs at 16³ — 8× fewer voxels on
    # the block that still dominates. The bench.py flagship; measured
    # throughput/MFU and the 24×1000-STL accuracy validation live in
    # BASELINE.md (kept there, not here — benchmark numbers in code
    # comments go stale).
    return Config(
        name="turbo64",
        resolution=64,
        global_batch=256,
        arch=dataclasses.replace(
            FeatureNetArch(),
            kernels=(7, 3, 3, 3),
            pool_after=(True, False, False, True),
        ),
        total_steps=4000,
        peak_lr=3e-4,
        warmup_steps=200,
    ).validate()


def warp64() -> Config:
    # turbo64's successor (round 3): the step profiler showed turbo64's
    # stem is 43% of fwd+bwd *at its MXU shape ceiling* — and that the
    # stride-2-then-pool route computes 8 voxels per output then discards
    # 7. warp64 strides the same 7³ stem by 4 (s2d path, numerically exact,
    # stride-4 parity tested), producing 16³ directly at ⅛ the stem FLOPs:
    # measured +66% over turbo64 back-to-back. Accuracy validated on the
    # 24×1000 STL benchmark: 99.92% held-out at this preset's 8000-step
    # budget (99.52% at 4000 — the rougher loss surface of the strided
    # stem wants the longer cosine; measured trajectories in BASELINE.md).
    return Config(
        name="warp64",
        resolution=64,
        global_batch=256,
        arch=dataclasses.replace(
            FeatureNetArch(),
            kernels=(7, 3, 3, 3),
            strides=(4, 1, 1, 1),
            pool_after=(False, False, False, True),
        ),
        total_steps=8000,
        peak_lr=3e-4,
        warmup_steps=200,
    ).validate()


def sprint64() -> Config:
    # warp64's successor (round 4): the round-3 profile named a 5³/s4 stem
    # as the next lever (coverage stays complete, 5 > stride 4; ~⅔ of the
    # stem's remaining FLOPs shaved) but skipped it because accuracy
    # revalidation cost hours — HBM-resident retrains made it 12 minutes.
    # Measured: 16,334 samples/sec/chip (spread 7.3%; warp64 14,428 same
    # session) and 99.98% held-out (4,799/4,800) on the 24×1000 benchmark
    # at this preset's full 8k budget — one validation run, vs warp64's
    # three across two rounds; BASELINE.md round 4.
    return Config(
        name="sprint64",
        resolution=64,
        global_batch=256,
        arch=dataclasses.replace(
            FeatureNetArch(),
            kernels=(5, 3, 3, 3),
            strides=(4, 1, 1, 1),
            pool_after=(False, False, False, True),
        ),
        total_steps=8000,
        peak_lr=3e-4,
        warmup_steps=200,
    ).validate()


def robust64() -> Config:
    # The accurate-AND-robust preset (round 5; BASELINE.md "robust64
    # recipe search"). Recipe = the measured winner of the round-5 arms:
    # sprint64's arch and budget-doubled schedule, with HALF of every
    # batch affine-warped in-step (uniform SO(3) rotation × scale
    # [0.7, 1.05] — augment_affine_prob 0.5) and 0.5% occupancy bit-flips.
    # Fresh-draw OOD (per-class 25): clean 95.8%, rotation ≤15° 89–91%,
    # scale 87–91%, noise 0.5%/1% 97/91%, tails 89% — vs the unaugmented
    # flagship's chance-level rotation/scale/noise rows. Large rotations
    # (≥45°) remain the serving path's job: `infer` canonicalize+TTA
    # (data/canonicalize.py) realigns the stock before predicting.
    # Ships with the benchmark cache paths baked in (the run of record's
    # exact launch); --data-cache overrides for another corpus. Losing
    # arms, recorded in BASELINE.md: warm-start + full affine at low lr
    # (clean collapses to 32%), warm-start + mix at low lr (clean 99.1%
    # but rotation stalls at 41–47%).
    return Config(
        name="robust64",
        resolution=64,
        global_batch=256,
        arch=dataclasses.replace(
            FeatureNetArch(),
            kernels=(5, 3, 3, 3),
            strides=(4, 1, 1, 1),
            pool_after=(False, False, False, True),
        ),
        total_steps=16000,
        peak_lr=3e-4,
        warmup_steps=200,
        data_cache=".data/cls64_cache",
        hbm_cache=True,
        steps_per_dispatch=8,
        augment_affine=True,
        augment_affine_prob=0.5,
        augment_noise=0.005,
    ).validate()


def seg64() -> Config:
    # seg_loss: ce_dice beat balanced_ce in a matched-budget head-to-head
    # (mean IoU 0.798 vs 0.790 at 10k steps, ahead at every mid-run eval —
    # BASELINE.md round-2 ablation), so it is the default. total_steps:
    # 10k — the 5k runs of both variants were still climbing ~0.01/1k.
    # Round-4 levers are the default: axis-projection+coordinate input
    # context (removed the through/blind family confusion outright) and a
    # 2-block decoder — matched-budget arms measured 0.8092 → 0.8634 (A),
    # 0.8537 (B), 0.8890 combined; the combined model's diagnosis shows
    # zero remaining family-identity cost (BASELINE.md round 4). Note the
    # combined model needs steps_per_dispatch=1 at batch 32 on a 16 GB
    # chip (the 8-fused executable exceeds HBM by ~0.5 GB).
    return Config(
        name="seg64",
        task="segment",
        resolution=64,
        global_batch=32,
        num_features=3,
        total_steps=10000,
        peak_lr=5e-4,
        seg_loss="ce_dice",
        seg_input_context="proj_coords",
        seg_decoder_blocks=2,
    ).validate()


def abc128() -> Config:
    return Config(
        name="abc128",
        resolution=128,
        global_batch=32,
        arch=deep_arch(),
        total_steps=8000,
        # 3e-4 validated end-to-end (100% held-out top-1, BASELINE.md); at
        # 5e-4 the pre-GAP arch sat at chance and 2e-4 collapsed it.
        peak_lr=3e-4,
        # 128³ grids: shard depth over 'model' when mesh_model > 1 so deep
        # nets fit per-chip HBM (BASELINE config 5).
        spatial=True,
        mesh_model=2,
    ).validate()


PRESETS = {
    "smoke16": smoke16,
    "xla32": xla32,
    "pod64": pod64,
    "fast64": fast64,
    "turbo64": turbo64,
    "warp64": warp64,
    "sprint64": sprint64,
    "robust64": robust64,
    "seg64": seg64,
    "abc128": abc128,
}


def get_config(name: str, **overrides) -> Config:
    """Look up a preset and apply field overrides."""
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    cfg = PRESETS[name]()
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides).validate()
    return cfg


# --- checkpoint persistence --------------------------------------------------
# The run config is written next to the checkpoint (train.checkpoint) so every
# consumer — eval, infer, a resumed run — reconstructs the exact model instead
# of re-guessing arch flags (round-1 footgun: a --no-stem-s2d checkpoint was
# unloadable through `infer`, which had no such flag; the config simply was
# not persisted anywhere).

def config_to_dict(cfg: Config) -> dict:
    return dataclasses.asdict(cfg)


def config_from_dict(d: dict) -> Config:
    """Rebuild a ``Config`` from ``config_to_dict`` output.

    Unknown keys are dropped and missing ones take field defaults, so a
    checkpoint written by an older/newer build still loads; list-typed JSON
    round-trips back to the tuples the frozen dataclasses expect.
    """
    from featurenet_tpu.models.featurenet import FeatureNetArch

    d = dict(d)
    arch = d.pop("arch", None)
    known = {f.name for f in dataclasses.fields(Config)}
    kw = {k: v for k, v in d.items() if k in known}
    if arch is not None:
        known_a = {f.name for f in dataclasses.fields(FeatureNetArch)}
        kw["arch"] = FeatureNetArch(**{
            k: tuple(v) if isinstance(v, list) else v
            for k, v in arch.items()
            if k in known_a
        })
    if "seg_features" in kw:
        kw["seg_features"] = tuple(kw["seg_features"])
    if "augment_scale_range" in kw:
        kw["augment_scale_range"] = tuple(kw["augment_scale_range"])
    return Config(**kw).validate()


# Fields that define the trained artifact itself: a checkpoint only restores
# (meaningfully) under these exact values. Everything else — schedules, data
# paths, logging — is run policy and freely overridable at eval/infer time.
IDENTITY_FIELDS = (
    "task", "resolution", "arch", "seg_features",
    "seg_input_context", "seg_decoder_blocks", "seg_bottleneck_blocks",
)


def _identity_view(cfg: Config, field: str):
    """The identity-relevant value of ``field``.

    ``arch.conv_backend`` selects a lowering, not a model: every backend
    shares the same param tree (HybridConv/PallasConv mirror nn.Conv's
    kernel shape/init), so a checkpoint restores under any of them — and
    A/B-ing backends on one trained run is exactly what the flag is for.
    ``stem_s2d`` stays identity: its param tree path differs.
    """
    v = getattr(cfg, field)
    if field == "arch":
        v = dataclasses.replace(v, conv_backend="xla")
    return v


def check_identity(saved: Config, requested: Config) -> None:
    """Hard-error when ``requested`` disagrees with the persisted identity.

    Silent mismatches are the dangerous kind: a GAP-head model restores
    cleanly at the wrong resolution, a wrong-arch restore can even succeed
    structurally and produce confident nonsense.
    """
    bad = [
        f for f in IDENTITY_FIELDS
        if _identity_view(saved, f) != _identity_view(requested, f)
    ]
    if bad:
        # Report the *identity view*, not the raw field: for `arch` the raw
        # repr includes non-identity subfields (conv_backend) that may
        # legitimately differ and would point the user at a non-mismatch.
        detail = "; ".join(
            f"{f}: checkpoint={_identity_view(saved, f)!r} "
            f"requested={_identity_view(requested, f)!r}"
            for f in bad
        )
        raise ValueError(
            "explicit flags contradict the config persisted with this "
            f"checkpoint ({detail}) — drop the flags to use the persisted "
            "config, or point at a checkpoint trained with these settings"
        )
