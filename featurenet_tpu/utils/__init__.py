"""Utilities: metric logging, timing, profiling hooks."""

from featurenet_tpu.utils.logging import MetricLogger

__all__ = ["MetricLogger"]
