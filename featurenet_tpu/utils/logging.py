"""Metric logging: JSON-lines scalars to stdout (+ history for tests),
optionally mirrored to TensorBoard event files via CLU.

The reference printed loss to stdout (SURVEY.md §5 "Metrics"). Here every log
event is one machine-parseable JSON line, and throughput is measured honestly:
``samples/sec`` windows are walled with ``block_until_ready`` on the metric
pytree, so async dispatch can't inflate the number. Pass ``tb_dir`` (CLI
``--tb-dir``) to also write scalar summaries as TB events (CLU
``metric_writers`` — the SURVEY.md §5 observability plan); vector metrics
(e.g. per-class accuracy) stay JSON-only.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any

import jax
import numpy as np

from featurenet_tpu import obs


class MetricLogger:
    def __init__(self, stream=None, tb_dir: str | None = None):
        self.stream = stream or sys.stdout
        self.history: list[dict[str, Any]] = []
        self._window_start: float | None = None
        self._window_samples = 0
        self._tb = None
        if tb_dir:
            from clu import metric_writers

            self._tb = metric_writers.SummaryWriter(tb_dir)

    def start_window(self) -> None:
        self._window_start = time.perf_counter()
        self._window_samples = 0

    def count_samples(self, n: int) -> None:
        self._window_samples += n

    def log(self, step: int, metrics: dict, prefix: str = "train") -> dict:
        # Wall the async stream: metrics must be real before we read the
        # clock. In an obs run this wait is attributed as device time —
        # it is where the host blocks on outstanding execution.
        with obs.span("readback", src="metrics", step=int(step)):
            metrics = jax.block_until_ready(metrics)
        record: dict[str, Any] = {"step": int(step), "kind": prefix}
        for k, v in metrics.items():
            a = np.asarray(v)
            record[k] = float(a) if a.ndim == 0 else a.tolist()
        if self._window_start is not None and self._window_samples:
            dt = time.perf_counter() - self._window_start
            record["samples_per_sec"] = self._window_samples / max(dt, 1e-9)
            self.start_window()
        self.history.append(record)
        print(json.dumps(record), file=self.stream, flush=True)
        # Mirror into the run-scoped event log (no-op without a run_dir):
        # one artifact then holds metrics AND timing/liveness events. The
        # event's required `kind` field is passed as a literal key — the
        # telemetry lint (analysis/rules.py) can't see inside a splat.
        obs.emit("metrics", kind=prefix,
                 **{k: v for k, v in record.items() if k != "kind"})
        if self._tb is not None:
            scalars = {
                f"{prefix}/{k}": v
                for k, v in record.items()
                if isinstance(v, float) and k not in ("step",)
            }
            if scalars:
                self._tb.write_scalars(int(step), scalars)
        return record

    def flush(self) -> None:
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
            self._tb = None
