"""Parametric generator for the 24 FeatureNet machining-feature classes.

The reference benchmark is 24,000 synthetic CAD parts — 1,000 per class — each a
stock cube with one parametric machining feature subtracted (SURVEY.md §0; the
class list follows the FeatureNet paper, Zhang/Jaiswal/Rai CAD 101 (2018)). The
dataset itself is not on disk, so this module regenerates it procedurally,
directly in voxel space: each feature is a boolean removal volume (cylinders,
prisms, half-spaces, …) subtracted from a solid stock cube, with randomized
size/position/orientation. CSG in voxel space skips the STL detour for
training (the STL path exists and is tested separately — ``stl.py`` /
``voxelize.py``); ``featurenet_tpu.data.mesh_primitives`` can emit STL for the
same shapes to exercise the full pipeline.

Every sample also carries a per-voxel segmentation mask (0 = not-a-feature,
``1+class`` on the feature's removal volume clipped to the stock), which is the
dense target for the segmentation head (BASELINE.json config 4). Multi-feature
parts re-orient each extra feature randomly; features may overlap, in which
case a later feature's removal volume only labels voxels not already carved —
a feature in ``labels`` can therefore be partially (rarely fully) occluded in
``seg``, mirroring real multi-feature parts where features intersect.

All randomness flows from a caller-supplied ``np.random.Generator`` so the
dataset is reproducible from a seed.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

CLASS_NAMES: tuple[str, ...] = (
    "o_ring",
    "through_hole",
    "blind_hole",
    "triangular_passage",
    "rectangular_passage",
    "circular_through_slot",
    "triangular_through_slot",
    "rectangular_through_slot",
    "rectangular_blind_slot",
    "triangular_pocket",
    "rectangular_pocket",
    "circular_end_pocket",
    "triangular_blind_step",
    "circular_blind_step",
    "rectangular_blind_step",
    "rectangular_through_step",
    "two_sided_through_step",
    "slanted_through_step",
    "chamfer",
    "round",
    "vertical_circular_end_blind_slot",
    "horizontal_circular_end_blind_slot",
    "six_sided_passage",
    "six_sided_pocket",
)
NUM_CLASSES = len(CLASS_NAMES)  # 24

# Stock cube occupies [MARGIN, 1-MARGIN]^3 of the unit grid.
MARGIN = 0.08

_coord_cache: dict[int, tuple[np.ndarray, np.ndarray, np.ndarray]] = {}


def _coords(R: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Voxel-center coordinate grids in [0,1], cached per resolution."""
    if R not in _coord_cache:
        c = (np.arange(R, dtype=np.float32) + 0.5) / R
        _coord_cache[R] = tuple(np.meshgrid(c, c, c, indexing="ij"))
    return _coord_cache[R]


def stock_mask(R: int) -> np.ndarray:
    X, Y, Z = _coords(R)
    lo, hi = MARGIN, 1.0 - MARGIN
    return (
        (X > lo) & (X < hi) & (Y > lo) & (Y < hi) & (Z > lo) & (Z < hi)
    )


# ---------------------------------------------------------------------------
# Geometric primitives (all return bool [R,R,R] removal masks).
# Conventions: stock spans [LO, HI]^3; "top" is z = HI; features are carved
# in a canonical pose and the finished grid is re-oriented afterwards.
# ---------------------------------------------------------------------------

LO, HI = MARGIN, 1.0 - MARGIN
S = HI - LO  # stock edge length


# --- feature-parameter quantile window (OOD holdout support) ---------------
# Every feature generator draws its sizes/positions through `_u`, so a
# quantile window here restricts ALL parameter draws at once: the round-4
# robustness harness trains on the middle quantiles and evaluates on the
# tails (VERDICT round-3 task 1(ii)). Thread-local because dataset workers
# are threads and two datasets with different windows may generate
# concurrently in one process.
import threading as _threading

PARAM_MID: tuple[float, float] = (0.15, 0.85)
_param_window = _threading.local()


# Sentinel: "no spec given — inherit whatever ambient window is active".
# Distinct from None, which explicitly forces the full range.
_INHERIT = object()


def _resolve_param_range(spec):
    """Normalize a param_range spec to an internal ('window'|'tails', lo, hi).

    ``None`` = full range; ``"mid"`` = the PARAM_MID window; ``"tails"`` =
    the complement of PARAM_MID (draws land in [0,lo)∪(hi,1] of each
    parameter's range); ``(lo, hi)`` = an explicit quantile window.
    """
    if spec is None:
        return None
    if isinstance(spec, str):
        if spec == "mid":
            return ("window",) + PARAM_MID
        if spec == "tails":
            return ("tails",) + PARAM_MID
        raise ValueError(
            f"unknown param_range {spec!r}: expected 'mid', 'tails', a "
            "(lo, hi) pair, or None"
        )
    if not isinstance(spec, (tuple, list)) or len(spec) != 2:
        raise ValueError(
            f"param_range window must be a (lo, hi) pair, got {spec!r}"
        )
    lo, hi = float(spec[0]), float(spec[1])
    if not (0.0 <= lo < hi <= 1.0):
        raise ValueError(f"param_range window must satisfy 0<=lo<hi<=1, "
                         f"got ({lo}, {hi})")
    return ("window", lo, hi)


class _ParamRange:
    """Context manager scoping a feature-parameter quantile window.

    ``_INHERIT`` (the generation entry points' kwarg default) is a no-op:
    the ambient window — e.g. a caller's ``with synthetic.param_range(...)``
    around ``generate_batch`` — stays in effect instead of being clobbered
    back to full range."""

    def __init__(self, spec):
        self._inherit = spec is _INHERIT
        self._spec = None if self._inherit else _resolve_param_range(spec)

    def __enter__(self):
        self._prev = getattr(_param_window, "spec", None)
        if not self._inherit:
            _param_window.spec = self._spec
        return self

    def __exit__(self, *exc):
        if not self._inherit:
            _param_window.spec = self._prev
        return False


# Public alias (generation entry points take a `param_range=` kwarg that
# shadows the name locally, so they use the underscored class).
param_range = _ParamRange


def _u(rng: np.random.Generator, a: float, b: float) -> float:
    spec = getattr(_param_window, "spec", None)
    if spec is None:
        return float(rng.uniform(a, b))
    kind, lo, hi = spec
    if kind == "window":
        q = lo + (hi - lo) * rng.uniform()
    else:  # tails: mass split between [0, lo) and (hi, 1] by width
        u = rng.uniform(0.0, lo + (1.0 - hi))
        q = u if u < lo else hi + (u - lo)
    return float(a + (b - a) * q)


def _cyl_z(R, cx, cy, r, z0, z1):
    X, Y, Z = _coords(R)
    return ((X - cx) ** 2 + (Y - cy) ** 2 < r * r) & (Z >= z0) & (Z <= z1)


def _cyl_x(R, cy, cz, r, x0, x1):
    X, Y, Z = _coords(R)
    return ((Y - cy) ** 2 + (Z - cz) ** 2 < r * r) & (X >= x0) & (X <= x1)


def _box(R, x0, x1, y0, y1, z0, z1):
    X, Y, Z = _coords(R)
    return (
        (X >= x0) & (X <= x1) & (Y >= y0) & (Y <= y1) & (Z >= z0) & (Z <= z1)
    )


def _tri_prism_z(R, cx, cy, w, h, z0, z1):
    """Isoceles-triangle cross-section in (x,y) (apex +y), extruded in z."""
    X, Y, Z = _coords(R)
    # Triangle: base at y = cy, apex at (cx, cy + h); sides slope inward.
    in_tri = (
        (Y >= cy)
        & (Y <= cy + h * (1.0 - np.abs(X - cx) / (w / 2.0)))
    )
    return in_tri & (Z >= z0) & (Z <= z1)


def _hex_prism_z(R, cx, cy, r, z0, z1):
    """Regular-hexagon cross-section (circumradius r·2/√3 flats at r)."""
    X, Y, Z = _coords(R)
    u, v = X - cx, Y - cy
    c30 = np.float32(np.sqrt(3) / 2)
    inside = (
        (np.abs(u) < r)
        & (np.abs(0.5 * u + c30 * v) < r)
        & (np.abs(-0.5 * u + c30 * v) < r)
    )
    return inside & (Z >= z0) & (Z <= z1)


def _stadium_z(R, x0, x1, cy, hw, z0, z1, cap_lo=False, cap_hi=True):
    """Rectangle (x0..x1, cy±hw) with semicircular end caps in plan, in z-range."""
    X, Y, Z = _coords(R)
    rect = (X >= x0) & (X <= x1) & (np.abs(Y - cy) < hw)
    m = rect
    if cap_hi:
        m = m | (((X - x1) ** 2 + (Y - cy) ** 2 < hw * hw) & (X >= x1))
    if cap_lo:
        m = m | (((X - x0) ** 2 + (Y - cy) ** 2 < hw * hw) & (X <= x0))
    return m & (Z >= z0) & (Z <= z1)


# ---------------------------------------------------------------------------
# The 24 feature generators. Each returns a removal mask for a feature carved
# in canonical orientation (top face = +z, "front" side face = -x or -y).
# ---------------------------------------------------------------------------


def _f_o_ring(R, rng):
    r_out = _u(rng, 0.18, 0.32) * S
    r_in = r_out * _u(rng, 0.45, 0.7)
    depth = _u(rng, 0.2, 0.5) * S
    cx = _u(rng, LO + r_out + 0.05 * S, HI - r_out - 0.05 * S)
    cy = _u(rng, LO + r_out + 0.05 * S, HI - r_out - 0.05 * S)
    ring = _cyl_z(R, cx, cy, r_out, HI - depth, 1.0) & ~_cyl_z(
        R, cx, cy, r_in, 0.0, 1.0
    )
    return ring


def _f_through_hole(R, rng):
    r = _u(rng, 0.1, 0.25) * S
    cx = _u(rng, LO + r + 0.05 * S, HI - r - 0.05 * S)
    cy = _u(rng, LO + r + 0.05 * S, HI - r - 0.05 * S)
    return _cyl_z(R, cx, cy, r, 0.0, 1.0)


def _f_blind_hole(R, rng):
    r = _u(rng, 0.1, 0.25) * S
    depth = _u(rng, 0.3, 0.7) * S
    cx = _u(rng, LO + r + 0.05 * S, HI - r - 0.05 * S)
    cy = _u(rng, LO + r + 0.05 * S, HI - r - 0.05 * S)
    return _cyl_z(R, cx, cy, r, HI - depth, 1.0)


def _f_triangular_passage(R, rng):
    w = _u(rng, 0.3, 0.55) * S
    h = _u(rng, 0.3, 0.55) * S
    cx = _u(rng, LO + w / 2 + 0.05 * S, HI - w / 2 - 0.05 * S)
    cy = _u(rng, LO + 0.05 * S, HI - h - 0.05 * S)
    return _tri_prism_z(R, cx, cy, w, h, 0.0, 1.0)


def _f_rectangular_passage(R, rng):
    wx = _u(rng, 0.25, 0.5) * S
    wy = _u(rng, 0.25, 0.5) * S
    x0 = _u(rng, LO + 0.05 * S, HI - wx - 0.05 * S)
    y0 = _u(rng, LO + 0.05 * S, HI - wy - 0.05 * S)
    return _box(R, x0, x0 + wx, y0, y0 + wy, 0.0, 1.0)


def _f_circular_through_slot(R, rng):
    # Half-cylinder channel across the top face, running through in x.
    r = _u(rng, 0.12, 0.28) * S
    cy = _u(rng, LO + r + 0.05 * S, HI - r - 0.05 * S)
    return _cyl_x(R, cy, HI, r, 0.0, 1.0)


def _f_triangular_through_slot(R, rng):
    # V-groove across the top, through in x: apex points down (-z).
    w = _u(rng, 0.25, 0.5) * S
    d = _u(rng, 0.25, 0.5) * S
    cy = _u(rng, LO + w / 2 + 0.05 * S, HI - w / 2 - 0.05 * S)
    X, Y, Z = _coords(R)
    # Width tapers linearly from w at the top plane to 0 at depth d.
    frac = np.clip((Z - (HI - d)) / d, 0.0, 1.0)
    return (np.abs(Y - cy) < (w / 2.0) * frac) & (Z >= HI - d)


def _f_rectangular_through_slot(R, rng):
    w = _u(rng, 0.2, 0.45) * S
    d = _u(rng, 0.25, 0.6) * S
    cy = _u(rng, LO + w / 2 + 0.05 * S, HI - w / 2 - 0.05 * S)
    return _box(R, 0.0, 1.0, cy - w / 2, cy + w / 2, HI - d, 1.0)


def _f_rectangular_blind_slot(R, rng):
    # Open at top and at the -x side face; blind end inside.
    w = _u(rng, 0.2, 0.4) * S
    d = _u(rng, 0.25, 0.55) * S
    reach = _u(rng, 0.35, 0.65) * S
    cy = _u(rng, LO + w / 2 + 0.05 * S, HI - w / 2 - 0.05 * S)
    return _box(R, 0.0, LO + reach, cy - w / 2, cy + w / 2, HI - d, 1.0)


def _f_triangular_pocket(R, rng):
    w = _u(rng, 0.3, 0.55) * S
    h = _u(rng, 0.3, 0.55) * S
    d = _u(rng, 0.25, 0.6) * S
    cx = _u(rng, LO + w / 2 + 0.05 * S, HI - w / 2 - 0.05 * S)
    cy = _u(rng, LO + 0.05 * S, HI - h - 0.05 * S)
    return _tri_prism_z(R, cx, cy, w, h, HI - d, 1.0)


def _f_rectangular_pocket(R, rng):
    wx = _u(rng, 0.25, 0.5) * S
    wy = _u(rng, 0.25, 0.5) * S
    d = _u(rng, 0.25, 0.6) * S
    x0 = _u(rng, LO + 0.05 * S, HI - wx - 0.05 * S)
    y0 = _u(rng, LO + 0.05 * S, HI - wy - 0.05 * S)
    return _box(R, x0, x0 + wx, y0, y0 + wy, HI - d, 1.0)


def _f_circular_end_pocket(R, rng):
    # Stadium-shaped pocket (rect with two semicircular ends) from the top.
    hw = _u(rng, 0.1, 0.2) * S
    length = _u(rng, 0.25, 0.45) * S
    d = _u(rng, 0.25, 0.6) * S
    x0 = _u(rng, LO + hw + 0.05 * S, HI - hw - length - 0.05 * S)
    cy = _u(rng, LO + hw + 0.05 * S, HI - hw - 0.05 * S)
    return _stadium_z(
        R, x0, x0 + length, cy, hw, HI - d, 1.0, cap_lo=True, cap_hi=True
    )


def _f_triangular_blind_step(R, rng):
    # Corner step with a slanted (triangular-in-plan) inner wall, from top.
    a = _u(rng, 0.4, 0.8) * S
    b = _u(rng, 0.4, 0.8) * S
    d = _u(rng, 0.25, 0.55) * S
    X, Y, Z = _coords(R)
    plan = (X - LO) / a + (Y - LO) / b < 1.0
    return plan & (Z >= HI - d)


def _f_circular_blind_step(R, rng):
    # Corner step bounded by a circular arc in plan, from top.
    r = _u(rng, 0.35, 0.65) * S
    d = _u(rng, 0.25, 0.55) * S
    X, Y, Z = _coords(R)
    plan = (X - LO) ** 2 + (Y - LO) ** 2 < r * r
    return plan & (Z >= HI - d)


def _f_rectangular_blind_step(R, rng):
    a = _u(rng, 0.35, 0.65) * S
    b = _u(rng, 0.35, 0.65) * S
    d = _u(rng, 0.25, 0.55) * S
    return _box(R, 0.0, LO + a, 0.0, LO + b, HI - d, 1.0)


def _f_rectangular_through_step(R, rng):
    a = _u(rng, 0.25, 0.55) * S
    d = _u(rng, 0.25, 0.55) * S
    return _box(R, 0.0, LO + a, 0.0, 1.0, HI - d, 1.0)


def _f_two_sided_through_step(R, rng):
    a = _u(rng, 0.18, 0.35) * S
    b = _u(rng, 0.18, 0.35) * S
    d = _u(rng, 0.25, 0.55) * S
    left = _box(R, 0.0, LO + a, 0.0, 1.0, HI - d, 1.0)
    right = _box(R, HI - b, 1.0, 0.0, 1.0, HI - d, 1.0)
    return left | right


def _f_slanted_through_step(R, rng):
    # Through step whose riser wall is a slanted plane.
    a = _u(rng, 0.25, 0.5) * S
    d = _u(rng, 0.25, 0.55) * S
    slope = _u(rng, 0.4, 1.2)
    X, Y, Z = _coords(R)
    # Wall plane: x = LO + a + slope*(HI - z); removal on the -x side, top-down.
    return (X < LO + a + slope * (HI - Z)) & (Z >= HI - d)


def _f_chamfer(R, rng):
    # 45-ish° planar cut along the top +x edge (edge parallel to y).
    c = _u(rng, 0.2, 0.45) * S
    k = _u(rng, 0.7, 1.4)  # wall slope ratio
    X, Y, Z = _coords(R)
    return (X - (HI - c)) + k * (Z - (HI - c)) > c


def _f_round(R, rng):
    # Rounded (filleted) top +x edge: remove material outside the quarter-
    # cylinder of radius r whose axis runs along y at (HI-r, HI-r).
    r = _u(rng, 0.2, 0.42) * S
    X, Y, Z = _coords(R)
    cx, cz = HI - r, HI - r
    outside = (X - cx) ** 2 + (Z - cz) ** 2 > r * r
    return outside & (X > cx) & (Z > cz)


def _f_v_circ_end_blind_slot(R, rng):
    # Slot from the -x side face, top-open, rounded blind end (stadium, one cap).
    hw = _u(rng, 0.1, 0.2) * S
    reach = _u(rng, 0.35, 0.6) * S
    d = _u(rng, 0.25, 0.55) * S
    cy = _u(rng, LO + hw + 0.05 * S, HI - hw - 0.05 * S)
    return _stadium_z(
        R, 0.0, LO + reach, cy, hw, HI - d, 1.0, cap_lo=False, cap_hi=True
    )


def _f_h_circ_end_blind_slot(R, rng):
    # Slot cut into the -y side face, running horizontally (in x), with a
    # rounded blind end; spans a z-interval strictly inside the part, which
    # distinguishes it from the vertical variant (top-open).
    hw = _u(rng, 0.09, 0.16) * S
    # reach is bounded so x0's sample range below stays non-empty.
    reach = _u(rng, 0.3 * S, 0.82 * S - 2.0 * hw - 0.16 * S)
    z0 = _u(rng, LO + 0.1 * S, HI - 0.1 * S - 2.2 * hw)
    x0 = _u(rng, LO + hw + 0.08 * S, HI - hw - 0.08 * S - reach)
    X, Y, Z = _coords(R)
    cz = z0 + 1.1 * hw
    rect = (X >= x0) & (X <= x0 + reach) & (np.abs(Z - cz) < hw)
    cap = ((X - (x0 + reach)) ** 2 + (Z - cz) ** 2 < hw * hw) & (X >= x0 + reach)
    return (rect | cap) & (Y <= LO + _u(rng, 0.3, 0.6) * S)


def _f_six_sided_passage(R, rng):
    # Flat radius large enough that the hexagon's corners stand ~2+ voxels
    # proud of the inscribed circle at 64³ — below that the feature is
    # unresolvable from a round hole (measured: 49% of six-sided passages
    # classified as through_hole at r≥0.15 before this floor was raised).
    r = _u(rng, 0.22, 0.33) * S
    cx = _u(rng, LO + r * 1.2 + 0.05 * S, HI - r * 1.2 - 0.05 * S)
    cy = _u(rng, LO + r * 1.2 + 0.05 * S, HI - r * 1.2 - 0.05 * S)
    return _hex_prism_z(R, cx, cy, r, 0.0, 1.0)


def _f_six_sided_pocket(R, rng):
    r = _u(rng, 0.22, 0.33) * S  # resolvable hex flats — see passage note
    d = _u(rng, 0.25, 0.6) * S
    cx = _u(rng, LO + r * 1.2 + 0.05 * S, HI - r * 1.2 - 0.05 * S)
    cy = _u(rng, LO + r * 1.2 + 0.05 * S, HI - r * 1.2 - 0.05 * S)
    return _hex_prism_z(R, cx, cy, r, HI - d, 1.0)


_FEATURE_FNS: tuple[Callable, ...] = (
    _f_o_ring,
    _f_through_hole,
    _f_blind_hole,
    _f_triangular_passage,
    _f_rectangular_passage,
    _f_circular_through_slot,
    _f_triangular_through_slot,
    _f_rectangular_through_slot,
    _f_rectangular_blind_slot,
    _f_triangular_pocket,
    _f_rectangular_pocket,
    _f_circular_end_pocket,
    _f_triangular_blind_step,
    _f_circular_blind_step,
    _f_rectangular_blind_step,
    _f_rectangular_through_step,
    _f_two_sided_through_step,
    _f_slanted_through_step,
    _f_chamfer,
    _f_round,
    _f_v_circ_end_blind_slot,
    _f_h_circ_end_blind_slot,
    _f_six_sided_passage,
    _f_six_sided_pocket,
)
assert len(_FEATURE_FNS) == NUM_CLASSES


def random_orientation(rng: np.random.Generator):
    """One of the 24 rotations of the cube group, as a grid transform.

    The paper augments each part with its 24 axis-aligned orientations
    (SURVEY.md §2 C3); applying a random one at generation time gives the
    model the same orientation invariance pressure. Also applied at train
    time by ``offline.VoxelCacheDataset(augment=True)`` so a fixed on-disk
    dataset still sees all 24 poses of every part.
    """
    perm = list(rng.permutation(3))
    flips = [bool(rng.integers(0, 2)) for _ in range(3)]
    # Restrict to proper rotations (determinant +1): parity(perm) must equal
    # parity of the number of flips.
    perm_parity = int(
        sum(1 for i in range(3) for j in range(i + 1, 3) if perm[i] > perm[j])
    ) % 2
    if (sum(flips) % 2) != perm_parity:
        flips[0] = not flips[0]

    def apply(grid: np.ndarray) -> np.ndarray:
        g = np.transpose(grid, perm)
        for ax, f in enumerate(flips):
            if f:
                g = np.flip(g, axis=ax)
        return np.ascontiguousarray(g)

    return apply


def carve(
    labels: np.ndarray,
    removals: list[np.ndarray],
    order: Sequence[int] | None = None,
    resolution: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Carve feature removal volumes from stock, in ``order``.

    Returns ``(part bool [R³], seg int32 [R³])``. The *part* is
    order-invariant (``stock & ~union(removals)``); the *seg* labeling is
    not — a voxel covered by several removals keeps the label of whichever
    came first. Exposing the order makes that ambiguity measurable
    (``data.seg_oracle``): any two orders are equally likely under the
    generator's iid feature draws, so every ``carve(labels, removals, π)``
    is an equally valid ground truth for the same observable part.
    ``resolution`` is only needed for the degenerate no-features case
    (plain stock, all-zero seg).
    """
    if not len(removals):
        if resolution is None:
            raise ValueError("carve with no removals needs resolution")
        R = resolution
        return stock_mask(R).copy(), np.zeros((R, R, R), dtype=np.int32)
    R = removals[0].shape[0]
    part = stock_mask(R).copy()
    seg = np.zeros((R, R, R), dtype=np.int32)
    for k in order if order is not None else range(len(removals)):
        carved = removals[k] & part
        seg[carved] = int(labels[k]) + 1
        part &= ~removals[k]
    return part, seg


def generate_sample_with_removals(
    rng: np.random.Generator,
    resolution: int = 64,
    label: int | None = None,
    num_features: int = 1,
    orient: bool = True,
    param_range=_INHERIT,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, list[np.ndarray]]:
    """`generate_sample` that also returns each feature's removal volume.

    Returns ``(voxels, labels, seg, removals)`` with ``removals`` a list of
    ``bool [R³]`` grids in the *final* (post-orientation) frame and in
    generation order, so ``carve(labels, removals)`` reproduces
    ``(voxels, seg)`` exactly. The rng stream is identical to
    ``generate_sample``'s — same seed, same sample.
    """
    R = resolution
    labels = np.empty(num_features, dtype=np.int32)
    removals: list[np.ndarray] = []

    with _ParamRange(param_range):
        for k in range(num_features):
            cls = (
                int(rng.integers(0, NUM_CLASSES))
                if label is None else int(label)
            )
            labels[k] = cls
            removal = _FEATURE_FNS[cls](R, rng)
            if num_features > 1:
                # Re-orient each extra feature randomly so multi-feature
                # parts don't stack every feature on the same (top/-x)
                # faces. Overlap is possible; carving uses the *remaining*
                # part so overlapped voxels keep the earlier feature's
                # label.
                removal = random_orientation(rng)(removal)
            removals.append(removal)

    if orient:
        # The stock cube is symmetric under the cube group, so orienting the
        # removals and carving commutes with carving then orienting — and
        # keeps the removals aligned with the returned part/seg.
        o = random_orientation(rng)
        removals = [o(r) for r in removals]
    part, seg = carve(labels, removals, resolution=R)
    return part, labels, seg, removals


def generate_sample(
    rng: np.random.Generator,
    resolution: int = 64,
    label: int | None = None,
    num_features: int = 1,
    orient: bool = True,
    param_range=_INHERIT,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Generate one part.

    Returns ``(voxels bool [R³], labels int32 [num_features], seg int32 [R³])``
    where seg is 0 on non-feature voxels and ``1+class`` on each feature's
    removal volume (clipped to the stock). With ``num_features == 1`` this is
    the classification sample; more features serve the segmentation config.
    ``param_range``: feature-parameter quantile window (see ``_ParamRange``)
    — ``"mid"``/``"tails"``/``(lo, hi)``/None.
    """
    part, labels, seg, _ = generate_sample_with_removals(
        rng, resolution, label=label, num_features=num_features,
        orient=orient, param_range=param_range,
    )
    return part, labels, seg


def generate_batch(
    rng: np.random.Generator,
    batch_size: int,
    resolution: int = 64,
    balanced: bool = True,
    num_features: int = 1,
    orient: bool = True,
    param_range=_INHERIT,
) -> dict[str, np.ndarray]:
    """Generate a batch dict: voxels [B,R,R,R,1] f32, label [B] i32,
    seg [B,R³] i32, mask [B] f32 (all-ones; padding masks come from exact
    epoch passes in ``offline.VoxelCacheDataset``)."""
    R = resolution
    voxels = np.empty((batch_size, R, R, R, 1), dtype=np.float32)
    seg = np.empty((batch_size, R, R, R), dtype=np.int32)
    labels = np.empty((batch_size,), dtype=np.int32)
    for i in range(batch_size):
        forced = (i % NUM_CLASSES) if balanced and num_features == 1 else None
        part, labs, s = generate_sample(
            rng, R, label=forced, num_features=num_features, orient=orient,
            param_range=param_range,
        )
        voxels[i, ..., 0] = part
        labels[i] = labs[0]
        seg[i] = s
    return {
        "voxels": voxels,
        "label": labels,
        "seg": seg,
        "mask": np.ones(batch_size, dtype=np.float32),
    }


def pack_voxels(voxels: np.ndarray) -> np.ndarray:
    """Bit-pack occupancy ``[B, R, R, R]`` (or ``[...,1]``) → ``[B,R,R,R/8]``.

    The host→device wire format for classification: 8 voxels per byte, 32x
    smaller than float32. The jitted step unpacks on device
    (``train.steps.unpack_voxels``) — host/PCIe (or, in this dev environment,
    tunnel) bandwidth is the input pipeline's scarce resource, device flops
    for the unpack are free.
    """
    if voxels.ndim == 5:
        voxels = voxels[..., 0]
    if voxels.shape[-1] % 8:
        raise ValueError(f"W={voxels.shape[-1]} not divisible by 8")
    return np.packbits(voxels.astype(bool), axis=-1)


# Keys of each task's wire dict — the single source of truth shared by
# to_wire, the Trainer's batch shardings, and bench.py.
WIRE_KEYS = {
    "classify": ("voxels", "label", "mask"),
    "segment": ("voxels", "seg", "mask"),
}


def to_wire(batch: dict[str, np.ndarray], task: str) -> dict[str, np.ndarray]:
    """Shrink a rich ``generate_batch`` dict to the per-task wire format.

    Voxels are bit-packed for both tasks (the occupancy grid is binary
    either way; the jitted step unpacks on device). classify additionally
    drops the per-voxel target; segment ships ``seg`` as int8 (class ids
    fit comfortably).
    """
    if task == "classify":
        return {
            "voxels": pack_voxels(batch["voxels"]),
            "label": batch["label"],
            "mask": batch["mask"],
        }
    if task == "segment":
        return {
            "voxels": pack_voxels(batch["voxels"]),
            "seg": batch["seg"].astype(np.int8),
            "mask": batch["mask"],
        }
    raise ValueError(f"unknown task {task!r}")
