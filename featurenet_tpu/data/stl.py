"""First-party STL (stereolithography) mesh reader/writer.

The reference pipeline starts from STL triangle soups (reference:
``data/voxelize.py`` — see SURVEY.md §2 C2; the mount was empty at survey time
so the citation is to the survey's reconstruction). No third-party mesh library
is used: binary STL is a fixed-layout record format (80-byte header, uint32
triangle count, then ``count`` 50-byte records of ``normal(3f) v0(3f) v1(3f)
v2(3f) attr(u16)``), and ASCII STL is a trivial keyword grammar. Both parse to
a single ``float32 [n, 3, 3]`` vertex array (triangle-major, vertex-minor).
"""

from __future__ import annotations

import os
import struct

import numpy as np

_BINARY_HEADER_BYTES = 80
_RECORD_BYTES = 50  # 12 float32 + uint16 attribute

# Structured dtype matching one binary-STL triangle record.
_RECORD_DTYPE = np.dtype(
    [
        ("normal", "<f4", (3,)),
        ("verts", "<f4", (3, 3)),
        ("attr", "<u2"),
    ]
)


def _is_binary_stl(path: str) -> bool:
    """Decide binary vs ASCII by record arithmetic, not by the 'solid' prefix.

    Many binary exporters write headers that begin with ``solid``, so the only
    reliable test is whether the file size matches the binary layout.
    """
    size = os.path.getsize(path)
    if size < _BINARY_HEADER_BYTES + 4:
        return False
    with open(path, "rb") as f:
        f.seek(_BINARY_HEADER_BYTES)
        (count,) = struct.unpack("<I", f.read(4))
    return size == _BINARY_HEADER_BYTES + 4 + count * _RECORD_BYTES


def load_stl(path: str) -> np.ndarray:
    """Load an STL file (binary or ASCII) into a ``float32 [n, 3, 3]`` array.

    Axis layout: ``[triangle, vertex, xyz]``. Facet normals are discarded —
    the voxelizer derives geometry from vertices alone.
    """
    if _is_binary_stl(path):
        return _load_binary(path)
    return _load_ascii(path)


def _load_binary(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        f.seek(_BINARY_HEADER_BYTES)
        (count,) = struct.unpack("<I", f.read(4))
        records = np.fromfile(f, dtype=_RECORD_DTYPE, count=count)
    if records.shape[0] != count:
        raise ValueError(
            f"truncated binary STL: header claims {count} triangles, "
            f"found {records.shape[0]}"
        )
    return np.ascontiguousarray(records["verts"], dtype=np.float32)


def _load_ascii(path: str) -> np.ndarray:
    verts: list[float] = []
    with open(path, "r", errors="replace") as f:
        for line in f:
            parts = line.split()
            if len(parts) == 4 and parts[0] == "vertex":
                verts.extend((float(parts[1]), float(parts[2]), float(parts[3])))
    arr = np.asarray(verts, dtype=np.float32)
    if arr.size == 0 or arr.size % 9 != 0:
        # A binary file whose size doesn't match its record count also lands
        # here (it fails the binary layout check); name both possibilities.
        raise ValueError(
            f"malformed STL {path!r}: not a valid binary layout (size/record "
            "mismatch — possibly truncated) and not parseable as ASCII"
        )
    return arr.reshape(-1, 3, 3)


def save_stl(path: str, triangles: np.ndarray, name: str = "featurenet") -> None:
    """Write ``float32 [n, 3, 3]`` triangles as binary STL (normals recomputed)."""
    tris = np.asarray(triangles, dtype=np.float32)
    if tris.ndim != 3 or tris.shape[1:] != (3, 3):
        raise ValueError(f"expected [n, 3, 3] triangles, got {tris.shape}")
    e1 = tris[:, 1] - tris[:, 0]
    e2 = tris[:, 2] - tris[:, 0]
    normals = np.cross(e1, e2)
    lens = np.linalg.norm(normals, axis=1, keepdims=True)
    normals = np.where(lens > 0, normals / np.maximum(lens, 1e-30), 0.0)

    records = np.zeros(tris.shape[0], dtype=_RECORD_DTYPE)
    records["normal"] = normals.astype(np.float32)
    records["verts"] = tris
    header = name.encode()[: _BINARY_HEADER_BYTES].ljust(_BINARY_HEADER_BYTES, b"\0")
    with open(path, "wb") as f:
        f.write(header)
        f.write(struct.pack("<I", tris.shape[0]))
        records.tofile(f)
