"""First-party STL (stereolithography) mesh reader/writer.

The reference pipeline starts from STL triangle soups (reference:
``data/voxelize.py`` — see SURVEY.md §2 C2; the mount was empty at survey time
so the citation is to the survey's reconstruction). No third-party mesh library
is used: binary STL is a fixed-layout record format (80-byte header, uint32
triangle count, then ``count`` 50-byte records of ``normal(3f) v0(3f) v1(3f)
v2(3f) attr(u16)``), and ASCII STL is a trivial keyword grammar. Both parse to
a single ``float32 [n, 3, 3]`` vertex array (triangle-major, vertex-minor).
"""

from __future__ import annotations

import struct

import numpy as np

_BINARY_HEADER_BYTES = 80
_RECORD_BYTES = 50  # 12 float32 + uint16 attribute

# Structured dtype matching one binary-STL triangle record.
_RECORD_DTYPE = np.dtype(
    [
        ("normal", "<f4", (3,)),
        ("verts", "<f4", (3, 3)),
        ("attr", "<u2"),
    ]
)


def _is_binary_stl(data: bytes) -> bool:
    """Decide binary vs ASCII by record arithmetic, not by the 'solid' prefix.

    Many binary exporters write headers that begin with ``solid``, so the only
    reliable test is whether the payload size matches the binary layout.
    """
    if len(data) < _BINARY_HEADER_BYTES + 4:
        return False
    (count,) = struct.unpack_from("<I", data, _BINARY_HEADER_BYTES)
    return len(data) == _BINARY_HEADER_BYTES + 4 + count * _RECORD_BYTES


def parse_stl(data: bytes) -> np.ndarray:
    """Parse STL bytes (binary or ASCII) into ``float32 [n, 3, 3]``.

    The serving upload path: a CAD part arrives as request-body bytes and
    must never touch the filesystem to be understood. ``load_stl`` is the
    file wrapper over this. Axis layout ``[triangle, vertex, xyz]``;
    facet normals are discarded — the voxelizer derives geometry from
    vertices alone."""
    if _is_binary_stl(data):
        return _parse_binary(data)
    return _parse_ascii(data.decode("utf-8", errors="replace"))


def load_stl(path: str) -> np.ndarray:
    """Load an STL file (binary or ASCII) into a ``float32 [n, 3, 3]`` array
    (see ``parse_stl`` for the layout)."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        return parse_stl(data)
    except ValueError as e:
        raise ValueError(f"{path!r}: {e}") from None


def _parse_binary(data: bytes) -> np.ndarray:
    (count,) = struct.unpack_from("<I", data, _BINARY_HEADER_BYTES)
    records = np.frombuffer(
        data, dtype=_RECORD_DTYPE, count=count,
        offset=_BINARY_HEADER_BYTES + 4,
    )
    return np.ascontiguousarray(records["verts"], dtype=np.float32)


def _parse_ascii(text: str) -> np.ndarray:
    verts: list[float] = []
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 4 and parts[0] == "vertex":
            verts.extend((float(parts[1]), float(parts[2]), float(parts[3])))
    arr = np.asarray(verts, dtype=np.float32)
    if arr.size == 0 or arr.size % 9 != 0:
        # A binary payload whose size doesn't match its record count also
        # lands here (it fails the binary layout check); name both.
        raise ValueError(
            "malformed STL: not a valid binary layout (size/record "
            "mismatch — possibly truncated) and not parseable as ASCII"
        )
    return arr.reshape(-1, 3, 3)


def save_stl(path: str, triangles: np.ndarray, name: str = "featurenet") -> None:
    """Write ``float32 [n, 3, 3]`` triangles as binary STL (normals recomputed)."""
    tris = np.asarray(triangles, dtype=np.float32)
    if tris.ndim != 3 or tris.shape[1:] != (3, 3):
        raise ValueError(f"expected [n, 3, 3] triangles, got {tris.shape}")
    e1 = tris[:, 1] - tris[:, 0]
    e2 = tris[:, 2] - tris[:, 0]
    normals = np.cross(e1, e2)
    lens = np.linalg.norm(normals, axis=1, keepdims=True)
    normals = np.where(lens > 0, normals / np.maximum(lens, 1e-30), 0.0)

    records = np.zeros(tris.shape[0], dtype=_RECORD_DTYPE)
    records["normal"] = normals.astype(np.float32)
    records["verts"] = tris
    header = name.encode()[: _BINARY_HEADER_BYTES].ljust(_BINARY_HEADER_BYTES, b"\0")
    with open(path, "wb") as f:
        f.write(header)
        f.write(struct.pack("<I", tris.shape[0]))
        records.tofile(f)
