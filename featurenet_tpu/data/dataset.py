"""Host-side input pipeline feeding device-resident voxel batches.

The reference used a ``torch.utils.data.Dataset`` + ``DataLoader`` with a
``DistributedSampler`` (SURVEY.md §2 C3/C5). The TPU-native shape of that is:
each *host* produces only its shard of the global batch, batches are built in
background threads, and arrays land in HBM via ``jax.device_put`` with the
batch's ``NamedSharding`` — so the addressable slice of a globally-sharded
batch is exactly what this host generated, and XLA never sees a host→host
copy. On a single host the same code degenerates to plain prefetching.

Threading model: parallel workers never share an iterator. Each worker owns an
independent, seed-decorrelated stream (``SyntheticVoxelDataset.worker_iter``)
and a fixed residue class of the ticket space (worker w fills tickets
w, w+W, w+2W, …), so the merged stream is deterministic for a given
(seed, num_workers) regardless of thread scheduling. Worker exceptions and
exhaustion propagate to the consumer instead of hanging it.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Iterator

import numpy as np

from featurenet_tpu import faults, obs
from featurenet_tpu.data.synthetic import generate_batch, to_wire


class ProducerError(RuntimeError):
    """A prefetch producer worker died; raised in the *consumer*.

    Carries the worker id and the worker thread's formatted traceback in
    the message, so the train loop's crash names the real culprit (the
    cache read, the generator bug) instead of a bare queue timeout — and
    never deadlocks the consumer waiting on a ticket that will never be
    filled. The original exception is chained as ``__cause__``.
    """

    def __init__(self, worker: int, tb: str):
        self.worker = worker
        self.worker_traceback = tb
        super().__init__(
            f"prefetch producer worker {worker} died; worker traceback:\n"
            f"{tb}"
        )


class SyntheticVoxelDataset:
    """Infinite, seeded, sharded stream of synthetic feature batches.

    Args:
      resolution: voxel grid edge (16/32/64/128).
      global_batch: total batch across all hosts.
      num_hosts / host_id: data-parallel process grid; this host generates
        ``global_batch // num_hosts`` samples per step, decorrelated by seed.
      num_features: 1 for classification, >1 for segmentation parts.
      seed: base seed; per-host and per-worker streams are independent
        ``SeedSequence`` folds of it.
      task: wire format to emit (``data.synthetic.to_wire``) — classify ships
        bit-packed voxels and no per-voxel target; None yields the rich
        float batch (tests / custom consumers).
    """

    def __init__(
        self,
        resolution: int = 64,
        global_batch: int = 96,
        num_hosts: int = 1,
        host_id: int = 0,
        num_features: int = 1,
        balanced: bool = True,
        seed: int = 0,
        task: str | None = None,
    ):
        if global_batch % num_hosts != 0:
            raise ValueError("global_batch must divide evenly across hosts")
        self.resolution = resolution
        self.global_batch = global_batch
        self.local_batch = global_batch // num_hosts
        self.num_features = num_features
        self.balanced = balanced
        self.seed = seed
        self.host_id = host_id
        self.task = task

    def worker_iter(
        self, worker_id: int = 0, num_workers: int = 1
    ) -> Iterator[dict[str, np.ndarray]]:
        """An independent infinite stream for one producer worker."""
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.host_id, worker_id])
        )
        while True:
            batch = generate_batch(
                rng,
                self.local_batch,
                self.resolution,
                balanced=self.balanced,
                num_features=self.num_features,
            )
            yield to_wire(batch, self.task) if self.task else batch

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return self.worker_iter(0, 1)


class _WorkerDone:
    pass


# next() sentinel: the producer's timing wrapper must see exhaustion as a
# value, not an exception, so its try block stays exception-transparent.
_DONE = object()


# (sharding, local_shape) -> slices; tiny, but put_batch is per-step.
_block_cache: dict = {}


def _local_block(sharding, local_shape: tuple) -> tuple:
    """The slices of the host feed this process's devices actually need.

    The feed produces its data-row group's rows at *full* spatial extent
    (``parallel.mesh.feed_shards``). When every non-batch dim is unsharded
    — pure DP, or tensor parallelism on params only — the block is the
    whole feed and this returns full slices. Under spatial sharding with
    the ``model`` axis spanning processes, this process's devices hold only
    a depth sub-range of the shared rows, and
    ``make_array_from_process_local_data`` expects exactly that block — so
    the feed must be sliced before assembly. Computed once per
    (sharding, shape) from the sharding's own index map; cached because it
    runs per training step.
    """
    import jax

    key = (sharding, tuple(local_shape))
    hit = _block_cache.get(key)
    if hit is not None:
        return hit

    # Global rows = feed rows × feed groups; feed_shards is the single
    # source of truth for the process→row-group mapping (and validates
    # contiguity/divisibility, which a local re-derivation would skip).
    from featurenet_tpu.parallel.mesh import feed_shards

    num_groups, _ = feed_shards(sharding.mesh)
    global_rows = local_shape[0] * num_groups
    global_shape = (global_rows,) + tuple(local_shape[1:])
    imap = sharding.devices_indices_map(global_shape)
    mine = [imap[d] for d in sharding.addressable_devices]
    out = []
    for dim in range(len(global_shape)):
        starts = [s[dim].start or 0 for s in mine]
        stops = [
            s[dim].stop if s[dim].stop is not None else global_shape[dim]
            for s in mine
        ]
        lo, hi = min(starts), max(stops)
        # The [lo, hi) bounding box is only a valid block if the addressable
        # slices tile it densely: a sharding whose local slices were
        # non-contiguous in this dim would otherwise feed a wrong block with
        # only an indirect downstream failure.
        ivals = sorted(set(zip(starts, stops)))
        cursor = lo
        for s0, s1 in ivals:
            if s0 > cursor:
                raise ValueError(
                    f"addressable shards are non-contiguous in dim {dim}: "
                    f"gap [{cursor}, {s0}) inside block [{lo}, {hi}); "
                    "put_batch requires a dense local block per dim"
                )
            cursor = max(cursor, s1)
        if dim == 0:
            # Rows: the feed is exactly this block; keep feed-relative.
            if hi - lo != local_shape[0]:
                raise ValueError(
                    f"feed rows {local_shape[0]} != addressable row block "
                    f"{hi - lo}; dataset sharding must use "
                    "parallel.mesh.feed_shards"
                )
            out.append(slice(None))
        else:
            out.append(slice(lo, hi) if (lo, hi) != (0, global_shape[dim])
                       else slice(None))
    _block_cache[key] = tuple(out)
    return _block_cache[key]


def put_batch(batch, sharding):
    """Place a host-local batch under a (possibly multi-host) sharding.

    Single-process: plain ``device_put``. Multi-process: each host holds
    only its data-row group of the global batch, so the global array is
    assembled from process-local blocks
    (``make_array_from_process_local_data``) — the device_put path would
    wrongly treat the local slice as the global array. ``_local_block``
    narrows the feed to the addressable sub-block first, which is what
    makes meshes whose ``model`` axis spans processes (tensor-parallel
    kernels, spatially-sharded 128³ grids) assemble correctly.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)

    def assemble(x, s):
        block = _local_block(s, x.shape)
        if any(b != slice(None) for b in block):
            # lint: allow-host-sync(host feed block copy; x is host numpy)
            x = np.ascontiguousarray(x[block])
        return jax.make_array_from_process_local_data(s, x)

    return jax.tree_util.tree_map(assemble, batch, sharding)


def prefetch_to_device(
    source,
    sharding=None,
    buffer_size: int = 2,
    num_workers: int = 1,
) -> Iterator[dict]:
    """Overlap host-side batch generation with device compute.

    Args:
      source: a ``SyntheticVoxelDataset`` (or any object with ``worker_iter``)
        for multi-worker production, or a plain iterator/iterable (then
        ``num_workers`` is capped at 1 — a shared iterator is not thread-safe).
      sharding: optional ``jax.sharding.Sharding``; batches are ``device_put``
        with it. None leaves batches on host (CPU tests).
      buffer_size: max ready-but-unconsumed batches per worker.
      num_workers: producer threads; numpy releases the GIL for the heavy
        boolean ops so generation genuinely parallelizes.

    Termination: a finite source ends the stream cleanly (StopIteration);
    a producer exception re-raises in the consumer.
    """
    import jax

    if hasattr(source, "worker_iter"):
        W = max(1, num_workers)
        iters = [source.worker_iter(w, W) for w in range(W)]
    else:
        W = 1
        iters = [iter(source)]

    lock = threading.Lock()
    cond = threading.Condition(lock)
    out: dict[int, object] = {}  # ticket -> batch | _WorkerDone | Exception
    stop = threading.Event()
    # Each producer may run at most `lookahead` tickets past the consumer.
    # Bounding lookahead (not total buffer occupancy) is what makes this
    # deadlock-free: the worker owning the ticket the consumer waits on is
    # by construction within bounds and can always make progress.
    lookahead = max(1, buffer_size) * W
    nxt_box = [0]  # consumer's next ticket, shared under `cond`

    def producer(w: int):
        ticket = w
        try:
            it = iters[w]
            while True:
                # Chaos sites (zero-cost when faults are off): a scripted
                # worker death exercises the structured-error path below; a
                # scripted hang starves the consumer so the supervisor's
                # stale-heartbeat kill is the recovery that gets tested.
                if faults.maybe_fail("producer_crash", batch=ticket):
                    raise faults.InjectedFault(
                        f"producer_crash at ticket {ticket}"
                    )
                if faults.maybe_fail("producer_hang", batch=ticket):
                    while not stop.is_set():
                        time.sleep(0.05)
                    return
                if faults.maybe_fail("producer_slow", batch=ticket):
                    # Latency, not death: the slow-producer shape (a cold
                    # cache, a contended host) that starves the device
                    # without tripping any crash path — exactly what the
                    # data-wait SLO alert must catch (with :every=, a
                    # sustained drag rather than one hiccup).
                    time.sleep(faults.SLOW_SLEEP_S)
                # Per-batch generation timing (obs gauge): how long this
                # worker spent producing, independent of backpressure
                # waits — the report's "is generation the bottleneck"
                # signal. Clock reads only while a run is active.
                if obs.active():
                    t0 = time.perf_counter()
                    item = next(it, _DONE)
                    if item is not _DONE:
                        obs.gauge("producer_batch_s",
                                  round(time.perf_counter() - t0, 6),
                                  worker=w)
                else:
                    item = next(it, _DONE)
                if item is _DONE:
                    break
                with cond:
                    while (
                        ticket >= nxt_box[0] + lookahead and not stop.is_set()
                    ):
                        cond.wait(0.1)
                    if stop.is_set():
                        return
                    out[ticket] = item
                    cond.notify_all()
                ticket += W
            result: object = _WorkerDone()
        except BaseException as e:  # propagate to consumer, don't hang it
            # Structured surfacing: the consumer re-raises a ProducerError
            # whose message embeds THIS thread's traceback — the stack the
            # operator needs is the worker's, not the train loop's.
            err = ProducerError(w, traceback.format_exc())
            err.__cause__ = e
            result = err
        with cond:
            out[ticket] = result
            cond.notify_all()

    threads = [
        threading.Thread(target=producer, args=(w,), daemon=True)
        for w in range(W)
    ]
    for t in threads:
        t.start()

    done_workers: set[int] = set()
    nxt = 0
    try:
        while len(done_workers) < W:
            if nxt % W in done_workers:
                nxt += 1
                with cond:
                    nxt_box[0] = nxt
                    cond.notify_all()
                continue
            with cond:
                while nxt not in out:
                    cond.wait(0.1)
                item = out.pop(nxt)
                depth = len(out)  # ready batches left AFTER taking ours
                nxt_box[0] = nxt + 1
                cond.notify_all()
            # Queue depth at every consumer pop, measured after the pop so
            # a starved pipeline (consumer waited for the very batch it
            # took) reads 0: pinned at 0 = the device is starving; pinned
            # at max = producers saturate the lookahead and the device is
            # the bottleneck.
            obs.gauge("prefetch_queue_depth", depth)
            obs.observe("queue_depth", depth)
            if isinstance(item, _WorkerDone):
                done_workers.add(nxt % W)
            elif isinstance(item, BaseException):
                if isinstance(item, ProducerError):
                    # The recovery breadcrumb: a supervised run's restart
                    # verdict pairs with this warning in events.jsonl, so
                    # the report shows *why* the child died.
                    obs.warn(
                        "producer_error",
                        f"prefetch worker {item.worker} died: "
                        f"{item.__cause__!r}",
                        worker=item.worker,
                    )
                raise item
            else:
                if sharding is not None:
                    item = put_batch(item, sharding)
                yield item
            nxt += 1
    finally:
        stop.set()
        with cond:
            cond.notify_all()
