"""Pose canonicalization: undo an arbitrary SO(3) rotation before serving.

Why this exists (round 5): the OOD harness measured every trained model —
clean or augmented — degrading at large arbitrary rotations (the affine-mix
robust64 handles ≤15° at 82–86% but collapses at 45°; the clean flagship
collapses at 5°). Augmentation buys a *band* of invariance; machining parts
offer something better: the stock is a rectangular block, so the pose is
*recoverable from the geometry itself*. Serving can therefore normalize the
pose by construction and let the model run on the distribution it was
trained on — preprocessing invariance where it is exact, augmentation
robustness only for what preprocessing cannot undo (noise, morphology).

Method — min-volume axis-aligned bounding box over rotations: for a
(possibly feature-carved) rectangular stock, the AABB volume over all
rotations of the part is minimized exactly when the stock's faces are
axis-aligned. The search is coarse-to-fine over SO(3):

1. Coarse: score a few hundred quasi-uniform quaternion samples.
   A rotated AABB only needs the part's BOUNDARY voxel coordinates
   (~10⁴ points at 64³) — each candidate is one [3×3]·[3,N] matmul
   and six min/max reductions.
2. Refine: Nelder–Mead-free local descent — axis-angle perturbations of
   shrinking magnitude around the incumbent (derivative-free; the
   objective is piecewise-smooth with kinks at support changes).

The result is the stock orientation up to the 24-element cube group
(an AABB cannot distinguish them). ``infer.Predictor`` resolves that
ambiguity with cube-group test-time voting: classify all 24 axis-aligned
re-orientations (``ops.augment.rotate_grids`` — pure layout ops on TPU)
and take the class with the highest mean probability. The re-voxelization
goes through the benchmark's exact mesh pipeline (``voxels_to_mesh`` →
rotate → ``voxelize`` at the training margin), so a canonicalized part
re-enters the model's training distribution, scale normalization included.
"""

from __future__ import annotations

import numpy as np

from featurenet_tpu.data.voxel_to_mesh import rotate_mesh, voxels_to_mesh
from featurenet_tpu.data.voxelize import voxelize


def _boundary_coords(grid: np.ndarray) -> np.ndarray:
    """[N, 3] float coords of boundary-occupied voxels (center-origin).

    Interior voxels never touch the AABB, so the 6-neighborhood boundary
    (~R² points instead of ~R³) carries the whole objective.
    """
    g = grid.astype(bool)
    interior = np.ones_like(g)
    for ax in range(3):
        for d in (1, -1):
            interior &= np.roll(g, d, axis=ax)
    surf = g & ~interior
    if not surf.any():  # degenerate (empty/full) — fall back to all voxels
        surf = g
    pts = np.argwhere(surf).astype(np.float64)
    return pts - (np.array(grid.shape, np.float64) - 1.0) / 2.0


def _aabb_volume(pts: np.ndarray, rot: np.ndarray) -> float:
    q = pts @ rot.T
    ext = q.max(axis=0) - q.min(axis=0)
    return float(ext[0] * ext[1] * ext[2])


def _axis_angle(axis: np.ndarray, angle: float) -> np.ndarray:
    a = axis / np.linalg.norm(axis)
    K = np.array([
        [0, -a[2], a[1]],
        [a[2], 0, -a[0]],
        [-a[1], a[0], 0],
    ])
    return np.eye(3) + np.sin(angle) * K + (1 - np.cos(angle)) * (K @ K)


def _quat_rot(q: np.ndarray) -> np.ndarray:
    q = q / np.linalg.norm(q)
    w, x, y, z = q
    return np.array([
        [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
        [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
        [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
    ])


def estimate_canonical_rotation(
    grid: np.ndarray,
    coarse_samples: int = 384,
    refine_rounds: int = 24,
    seed: int = 0,
) -> np.ndarray:
    """Rotation matrix R minimizing the AABB volume of ``R @ part``.

    Applying the returned R to the part aligns the stock's faces with the
    grid axes (up to cube-group ambiguity). Deterministic given ``seed``.
    """
    if not np.asarray(grid).astype(bool).any():
        return np.eye(3)  # empty grid: nothing to orient
    pts = _boundary_coords(grid)
    rng = np.random.default_rng(seed)

    best_rot = np.eye(3)
    best_vol = _aabb_volume(pts, best_rot)
    # Coarse pass: iid-normal quaternions are uniform on SO(3).
    for q in rng.normal(size=(coarse_samples, 4)):
        rot = _quat_rot(q)
        v = _aabb_volume(pts, rot)
        if v < best_vol:
            best_vol, best_rot = v, rot

    # Refinement: shrinking random axis-angle perturbations (accept-greedy).
    step = 0.2  # radians
    for i in range(refine_rounds):
        improved = False
        for axis in rng.normal(size=(8, 3)):
            for sign in (1.0, -1.0):
                rot = _axis_angle(axis, sign * step) @ best_rot
                v = _aabb_volume(pts, rot)
                if v < best_vol:
                    best_vol, best_rot, improved = v, rot, True
        if not improved:
            step *= 0.5
            if step < 1e-3:
                break
    return best_rot


def canonicalize(
    grid: np.ndarray,
    margin: float = 0.05,
    **estimate_kw,
) -> np.ndarray:
    """Re-orient a voxel part to its canonical (stock-axis-aligned) pose.

    Exact surface mesh → estimated inverse rotation → re-voxelize through
    the benchmark pipeline at ``margin`` — i.e. the output re-enters the
    STL-cache training distribution (pose AND scale normalized). The
    residual cube-group ambiguity is left to the caller (24-pose TTA)."""
    R = grid.shape[0]
    g = np.asarray(grid).astype(bool)
    if not g.any():
        return g  # empty grid: no surface to remesh
    rot = estimate_canonical_rotation(g, **estimate_kw)
    tris = rotate_mesh(voxels_to_mesh(g), rot)
    return voxelize(tris, R, fill=True, margin=margin)
