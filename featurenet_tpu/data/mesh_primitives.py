"""Triangle-mesh emitters for simple solids, used to exercise the STL→voxel path.

The synthetic trainer carves features directly in voxel space (``synthetic.py``)
but the framework must also support the reference's actual input modality —
STL files on disk (SURVEY.md §3.2). These generators produce watertight
triangle soups for boxes and cylinders so tests can round-trip
mesh → ``save_stl`` → ``load_stl`` → ``voxelize`` and compare against the
analytic occupancy.
"""

from __future__ import annotations

import numpy as np


def mesh_box(lo=(0.2, 0.2, 0.2), hi=(0.8, 0.8, 0.8)) -> np.ndarray:
    """12-triangle watertight axis-aligned box, ``[12, 3, 3]`` float32."""
    x0, y0, z0 = lo
    x1, y1, z1 = hi
    # 8 corners.
    c = np.array(
        [
            [x0, y0, z0], [x1, y0, z0], [x1, y1, z0], [x0, y1, z0],
            [x0, y0, z1], [x1, y0, z1], [x1, y1, z1], [x0, y1, z1],
        ],
        dtype=np.float32,
    )
    quads = [
        (0, 3, 2, 1),  # z0 (floor, outward -z)
        (4, 5, 6, 7),  # z1
        (0, 1, 5, 4),  # y0
        (2, 3, 7, 6),  # y1
        (0, 4, 7, 3),  # x0
        (1, 2, 6, 5),  # x1
    ]
    tris = []
    for a, b, cc, d in quads:
        tris.append([c[a], c[b], c[cc]])
        tris.append([c[a], c[cc], c[d]])
    return np.asarray(tris, dtype=np.float32)


def mesh_cylinder(
    center=(0.5, 0.5), radius=0.25, z0=0.2, z1=0.8, segments: int = 48
) -> np.ndarray:
    """Closed cylinder along z as a triangle soup, ``[4*segments, 3, 3]``."""
    cx, cy = center
    ang = np.linspace(0.0, 2 * np.pi, segments, endpoint=False)
    nxt = np.roll(np.arange(segments), -1)
    xb = cx + radius * np.cos(ang)
    yb = cy + radius * np.sin(ang)
    tris = []
    for i in range(segments):
        j = nxt[i]
        a0 = (xb[i], yb[i], z0)
        b0 = (xb[j], yb[j], z0)
        a1 = (xb[i], yb[i], z1)
        b1 = (xb[j], yb[j], z1)
        cb = (cx, cy, z0)
        ct = (cx, cy, z1)
        tris.append([a0, b1, b0])  # side
        tris.append([a0, a1, b1])
        tris.append([cb, b0, a0])  # bottom cap (outward -z)
        tris.append([ct, a1, b1])  # top cap
    return np.asarray(tris, dtype=np.float32)
