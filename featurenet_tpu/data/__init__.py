"""Data pipeline: STL parsing, voxelization, synthetic feature generation."""

from featurenet_tpu.data.stl import load_stl, save_stl
from featurenet_tpu.data.voxelize import normalize_mesh, voxelize
from featurenet_tpu.data.synthetic import (
    CLASS_NAMES,
    NUM_CLASSES,
    generate_sample,
    generate_batch,
    pack_voxels,
    to_wire,
)
from featurenet_tpu.data.dataset import (
    SyntheticVoxelDataset,
    prefetch_to_device,
    put_batch,
)
from featurenet_tpu.data.offline import (
    SegCacheDataset,
    VoxelCacheDataset,
    build_cache,
    export_seg_cache,
    export_synthetic_cache,
)

__all__ = [
    "load_stl",
    "save_stl",
    "normalize_mesh",
    "voxelize",
    "CLASS_NAMES",
    "NUM_CLASSES",
    "generate_sample",
    "generate_batch",
    "pack_voxels",
    "to_wire",
    "SyntheticVoxelDataset",
    "prefetch_to_device",
    "put_batch",
    "SegCacheDataset",
    "VoxelCacheDataset",
    "build_cache",
    "export_seg_cache",
    "export_synthetic_cache",
]
