"""Voxel occupancy grid → watertight triangle mesh (boundary-face surface).

Closes the reverse arc of the data loop. The reference ships a 24k-model STL
benchmark and a one-way STL→voxel preprocessor (SURVEY.md §2 C2); that
dataset is not present in this environment, so training runs on the
parametric voxel generator. This module lets the generator *materialize an
actual STL benchmark on disk*: every boundary face between an occupied and
an empty voxel becomes two triangles, producing a closed, consistently
outward-wound surface that the STL→voxel front end (``data.voxelize``,
``cli build-cache``) can ingest like any external dataset.

Geometry contract: vertices lie on voxel-cell corners at coordinates
``index / R`` in the unit cube. Faces therefore sit on planes ``j / R``
while the voxelizer's parity fill casts rays through voxel *centers*
``(i + 0.5) / R`` — never on a face plane — so
``voxelize(voxels_to_mesh(g), R, fill=True, normalize=False)`` reproduces
``g`` exactly (tested), and ``build-cache`` (which re-normalizes like it
must for arbitrary external STL) reproduces it up to the normalization
margin.
"""

from __future__ import annotations

import numpy as np

# One entry per face direction: (axis, positive_side, quad corner offsets).
# Corner offsets are in the face plane's own 2D basis (u, v) and are wound
# counter-clockwise when viewed from outside (normal = outward).
_DIRECTIONS = (
    (0, True), (0, False),
    (1, True), (1, False),
    (2, True), (2, False),
)


def _face_quads(cells: np.ndarray, axis: int, positive: bool) -> np.ndarray:
    """Quad corners ``[n, 4, 3]`` (float32, voxel-index coords) for boundary
    faces of ``cells [n, 3]`` in direction ``axis``/``positive``."""
    base = cells.astype(np.float32)
    if positive:
        base[:, axis] += 1.0
    u_axis, v_axis = [a for a in (0, 1, 2) if a != axis]
    quads = np.repeat(base[:, None, :], 4, axis=1)  # [n, 4, 3]
    # CCW from outside: for a +axis face the (u, v) winding keeps the
    # right-hand normal along +axis; a -axis face reverses it.
    order = (
        ((0, 0), (1, 0), (1, 1), (0, 1))
        if (axis in (0, 2)) == positive
        else ((0, 0), (0, 1), (1, 1), (1, 0))
    )
    for corner, (du, dv) in enumerate(order):
        quads[:, corner, u_axis] += du
        quads[:, corner, v_axis] += dv
    return quads


def voxels_to_mesh(grid: np.ndarray, scale: float | None = None) -> np.ndarray:
    """Extract the boundary surface of a ``bool [R, R, R]`` grid.

    Returns ``float32 [n, 3, 3]`` triangles (two per boundary face),
    consistently wound with outward normals. ``scale`` multiplies vertex
    coordinates; default ``1 / R`` places the grid in the unit cube (the
    layout ``save_stl`` + ``voxelize(normalize=False)`` round-trip exactly).
    An empty grid returns zero triangles.
    """
    g = np.asarray(grid).astype(bool)
    if g.ndim != 3:
        raise ValueError(f"expected [R, R, R] grid, got {g.shape}")
    if scale is None:
        scale = 1.0 / max(g.shape)
    padded = np.pad(g, 1, constant_values=False)
    quad_list = []
    for axis, positive in _DIRECTIONS:
        shift = np.roll(padded, -1 if positive else 1, axis=axis)
        exposed = (padded & ~shift)[1:-1, 1:-1, 1:-1]
        cells = np.argwhere(exposed)
        quad_list.append(_face_quads(cells, axis, positive))
    quads = np.concatenate(quad_list, axis=0)
    # Quad [A, B, C, D] → triangles [A, B, C] and [A, C, D]; both inherit
    # the quad's winding, so outward orientation is preserved.
    tris = np.concatenate([quads[:, (0, 1, 2)], quads[:, (0, 2, 3)]], axis=0)
    return (tris * np.float32(scale)).astype(np.float32)


def export_stl_tree(
    out_root: str,
    per_class: int = 10,
    resolution: int = 64,
    seed: int = 0,
) -> dict:
    """Materialize the synthetic benchmark as an STL class tree on disk.

    Layout matches what ``cli build-cache`` ingests (the reference dataset's
    shape): ``out_root/<class_name>/<class_name>_<i>.stl``. Returns
    ``{"counts": {class_name: n}}``.
    """
    import os

    from featurenet_tpu.data.stl import save_stl
    from featurenet_tpu.data.synthetic import CLASS_NAMES, generate_sample

    counts = {}
    for cls_id, cls in enumerate(CLASS_NAMES):
        # Per-class seed stream (same scheme as offline.export_synthetic_
        # cache): sample i of class c is identical regardless of per_class
        # or which other classes are exported.
        rng = np.random.default_rng(np.random.SeedSequence([seed, cls_id]))
        cdir = os.path.join(out_root, cls)
        os.makedirs(cdir, exist_ok=True)
        for i in range(per_class):
            voxels, _labels, _seg = generate_sample(
                rng, resolution, label=cls_id
            )
            save_stl(
                os.path.join(cdir, f"{cls}_{i:04d}.stl"),
                voxels_to_mesh(voxels),
                name=f"{cls}_{i}",
            )
        counts[cls] = per_class
    return {"counts": counts}
