"""Voxel occupancy grid → watertight triangle mesh (boundary-face surface).

Closes the reverse arc of the data loop. The reference ships a 24k-model STL
benchmark and a one-way STL→voxel preprocessor (SURVEY.md §2 C2); that
dataset is not present in this environment, so training runs on the
parametric voxel generator. This module lets the generator *materialize an
actual STL benchmark on disk*: every boundary face between an occupied and
an empty voxel becomes two triangles, producing a closed, consistently
outward-wound surface that the STL→voxel front end (``data.voxelize``,
``cli build-cache``) can ingest like any external dataset.

Geometry contract: vertices lie on voxel-cell corners at coordinates
``index / R`` in the unit cube. Faces therefore sit on planes ``j / R``
while the voxelizer's parity fill casts rays through voxel *centers*
``(i + 0.5) / R`` — never on a face plane — so
``voxelize(voxels_to_mesh(g), R, fill=True, normalize=False)`` reproduces
``g`` exactly (tested), and ``build-cache`` (which re-normalizes like it
must for arbitrary external STL) reproduces it up to the normalization
margin.
"""

from __future__ import annotations

import numpy as np

# One entry per face direction: (axis, positive_side, quad corner offsets).
# Corner offsets are in the face plane's own 2D basis (u, v) and are wound
# counter-clockwise when viewed from outside (normal = outward).
_DIRECTIONS = (
    (0, True), (0, False),
    (1, True), (1, False),
    (2, True), (2, False),
)


def random_rotation_matrix(
    rng: np.random.Generator, angle_deg=None
) -> np.ndarray:
    """Random 3D rotation: uniform over SO(3) (``angle_deg=None``, via a
    normalized quaternion) or a fixed angle about a uniformly random axis
    (Rodrigues). Shared by the OOD harness (mesh-space rotation
    perturbation) and pose-augmented exports."""
    if angle_deg is None:
        q = rng.normal(size=4)
        w, x, y, z = q / np.linalg.norm(q)
        return np.array([
            [1 - 2 * (y * y + z * z), 2 * (x * y - w * z),
             2 * (x * z + w * y)],
            [2 * (x * y + w * z), 1 - 2 * (x * x + z * z),
             2 * (y * z - w * x)],
            [2 * (x * z - w * y), 2 * (y * z + w * x),
             1 - 2 * (x * x + y * y)],
        ], dtype=np.float64)
    axis = rng.normal(size=3)
    axis /= np.linalg.norm(axis)
    a = np.deg2rad(float(angle_deg))
    K = np.array([
        [0, -axis[2], axis[1]],
        [axis[2], 0, -axis[0]],
        [-axis[1], axis[0], 0],
    ])
    return np.eye(3) + np.sin(a) * K + (1 - np.cos(a)) * (K @ K)


def rotate_mesh(tris: np.ndarray, rot: np.ndarray) -> np.ndarray:
    """Rotate ``[n, 3, 3]`` triangles about their bounding-box center."""
    pts = tris.reshape(-1, 3)
    center = (pts.min(0) + pts.max(0)) / 2.0
    return ((pts - center) @ rot.T + center).reshape(-1, 3, 3).astype(
        np.float32
    )


def _face_quads(cells: np.ndarray, axis: int, positive: bool) -> np.ndarray:
    """Quad corners ``[n, 4, 3]`` (float32, voxel-index coords) for boundary
    faces of ``cells [n, 3]`` in direction ``axis``/``positive``."""
    base = cells.astype(np.float32)
    if positive:
        base[:, axis] += 1.0
    u_axis, v_axis = [a for a in (0, 1, 2) if a != axis]
    quads = np.repeat(base[:, None, :], 4, axis=1)  # [n, 4, 3]
    # CCW from outside: for a +axis face the (u, v) winding keeps the
    # right-hand normal along +axis; a -axis face reverses it.
    order = (
        ((0, 0), (1, 0), (1, 1), (0, 1))
        if (axis in (0, 2)) == positive
        else ((0, 0), (0, 1), (1, 1), (1, 0))
    )
    for corner, (du, dv) in enumerate(order):
        quads[:, corner, u_axis] += du
        quads[:, corner, v_axis] += dv
    return quads


def voxels_to_mesh(grid: np.ndarray, scale: float | None = None) -> np.ndarray:
    """Extract the boundary surface of a ``bool [R, R, R]`` grid.

    Returns ``float32 [n, 3, 3]`` triangles (two per boundary face),
    consistently wound with outward normals. ``scale`` multiplies vertex
    coordinates; default ``1 / R`` places the grid in the unit cube (the
    layout ``save_stl`` + ``voxelize(normalize=False)`` round-trip exactly).
    An empty grid returns zero triangles.
    """
    g = np.asarray(grid).astype(bool)
    if g.ndim != 3:
        raise ValueError(f"expected [R, R, R] grid, got {g.shape}")
    if scale is None:
        scale = 1.0 / max(g.shape)
    padded = np.pad(g, 1, constant_values=False)
    quad_list = []
    for axis, positive in _DIRECTIONS:
        shift = np.roll(padded, -1 if positive else 1, axis=axis)
        exposed = (padded & ~shift)[1:-1, 1:-1, 1:-1]
        cells = np.argwhere(exposed)
        quad_list.append(_face_quads(cells, axis, positive))
    quads = np.concatenate(quad_list, axis=0)
    # Quad [A, B, C, D] → triangles [A, B, C] and [A, C, D]; both inherit
    # the quad's winding, so outward orientation is preserved.
    tris = np.concatenate([quads[:, (0, 1, 2)], quads[:, (0, 2, 3)]], axis=0)
    return (tris * np.float32(scale)).astype(np.float32)


def export_seg_stl_tree(
    out_root: str,
    num_parts: int = 100,
    resolution: int = 64,
    num_features: int = 3,
    shard_size: int = 200,
    seed: int = 0,
    label_order: str = "canonical",
) -> dict:
    """Materialize the segmentation benchmark as STL files + label sidecars.

    The reference modality for every config: meshes on disk, ingested by the
    voxelizing front end (SURVEY.md §3.2). Classification got that shape in
    round 2 (``export_stl_tree``); this is the segmentation counterpart —
    the last config that only trained from the voxel-native cache (round-2
    verdict item 7). Layout::

        out_root/index.json                  {"kind": "segment_stl", ...}
        out_root/parts/part_0000000.stl      boundary-surface mesh, unit cube
        out_root/parts/part_0000000.seg.npy  int8 [R,R,R] per-voxel labels

    Per-voxel ground truth cannot live in the STL itself (a triangle soup
    has no voxel identity), so each part carries a sidecar label grid in the
    same unit-cube frame as the mesh; ``index.json``'s ``aligned_unit_cube``
    tells the ingester (``offline.build_seg_cache``) to voxelize with
    ``normalize=False`` so grid and sidecar stay voxel-exact (the
    normalization margin would otherwise shift the part against its
    labels).

    Sampling uses ``export_seg_cache``'s exact per-shard seed streams, so
    ``build_seg_cache`` over this tree reproduces the voxel-native cache of
    the same ``(num_parts, resolution, num_features, seed, label_order)``
    bit-for-bit — tested.
    """
    import json
    import os

    from featurenet_tpu.data.offline import _generate_seg_sample
    from featurenet_tpu.data.stl import save_stl

    pdir = os.path.join(out_root, "parts")
    os.makedirs(pdir, exist_ok=True)
    done = 0
    shard_id = 0
    while done < num_parts:
        n = min(shard_size, num_parts - done)
        rng = np.random.default_rng(np.random.SeedSequence([seed, shard_id]))
        for i in range(n):
            part, seg = _generate_seg_sample(
                rng, resolution, num_features, label_order
            )
            stem = os.path.join(pdir, f"part_{done + i:07d}")
            save_stl(stem + ".stl", voxels_to_mesh(part),
                     name=f"part_{done + i}")
            np.save(stem + ".seg.npy", seg.astype(np.int8))
        done += n
        shard_id += 1
    index = {
        "kind": "segment_stl",
        "resolution": resolution,
        "num_parts": num_parts,
        "num_features": num_features,
        "shard_size": shard_size,
        "seed": seed,
        "label_order": label_order,
        "aligned_unit_cube": True,
    }
    with open(os.path.join(out_root, "index.json"), "w") as fh:
        json.dump(index, fh, indent=1)
    return index


def export_stl_tree(
    out_root: str,
    per_class: int = 10,
    resolution: int = 64,
    seed: int = 0,
) -> dict:
    """Materialize the synthetic benchmark as an STL class tree on disk.

    Layout matches what ``cli build-cache`` ingests (the reference dataset's
    shape): ``out_root/<class_name>/<class_name>_<i>.stl``. Returns
    ``{"counts": {class_name: n}}``.
    """
    import os

    from featurenet_tpu.data.stl import save_stl
    from featurenet_tpu.data.synthetic import CLASS_NAMES, generate_sample

    counts = {}
    for cls_id, cls in enumerate(CLASS_NAMES):
        # Per-class seed stream (same scheme as offline.export_synthetic_
        # cache): sample i of class c is identical regardless of per_class
        # or which other classes are exported.
        rng = np.random.default_rng(np.random.SeedSequence([seed, cls_id]))
        cdir = os.path.join(out_root, cls)
        os.makedirs(cdir, exist_ok=True)
        for i in range(per_class):
            voxels, _labels, _seg = generate_sample(
                rng, resolution, label=cls_id
            )
            save_stl(
                os.path.join(cdir, f"{cls}_{i:04d}.stl"),
                voxels_to_mesh(voxels),
                name=f"{cls}_{i}",
            )
        counts[cls] = per_class
    return {"counts": counts}
