"""STL→voxel rasterization (reference capability: ``data/voxelize.py``, SURVEY.md §2 C2).

Design: first-party, vectorized numpy — no mesh library, no external ``binvox``
binary (the reference leaned on one or the other; see SURVEY.md §2's
native-component ledger). Pipeline:

1. ``normalize_mesh`` — center the triangle soup and uniformly scale it into
   the unit cube with a configurable margin (so a part voxelized at any
   resolution lands on the same relative geometry; scale/translate invariance
   is a unit-tested contract, SURVEY.md §4).
2. Surface rasterization — every triangle is covered with a dense barycentric
   sample grid whose pitch is < half a voxel, so no voxel the surface passes
   through is missed; samples are scatter-marked into the grid. This is
   conservative-by-sampling rather than exact SAT; the optional native C++
   path (``featurenet_tpu.native``) does exact triangle-box tests when built.
3. Solid fill — parity ray casting: one vertical ray per (x, y) voxel-center
   column, crossings accumulated per triangle and reduced with a z-cumsum
   parity. A voxel is solid iff its *center* is inside the watertight mesh —
   the exact occupancy semantic the classifier trains on, with no half-voxel
   surface bias. (An exterior flood fill is kept as a fallback for meshes
   that are not parity-clean.)

The output is a ``bool [R, R, R]`` occupancy grid, index order ``[x, y, z]``.
"""

from __future__ import annotations

import numpy as np

from featurenet_tpu import obs


def normalize_mesh(triangles: np.ndarray, margin: float = 0.05) -> np.ndarray:
    """Center + uniformly scale triangles into [margin, 1-margin]³.

    Uniform (isotropic) scaling preserves aspect ratio — a long part stays
    long. The margin keeps the surface off the grid boundary so the exterior
    flood fill always has a connected outside region.
    """
    tris = np.asarray(triangles, dtype=np.float32)
    lo = tris.reshape(-1, 3).min(axis=0)
    hi = tris.reshape(-1, 3).max(axis=0)
    center = (lo + hi) / 2.0
    extent = float((hi - lo).max())
    if extent <= 0:
        raise ValueError("degenerate mesh: zero spatial extent")
    scale = (1.0 - 2.0 * margin) / extent
    return (tris - center) * scale + 0.5


def _rasterize_surface(tris: np.ndarray, resolution: int) -> np.ndarray:
    """Mark every voxel touched by a dense point sampling of each triangle."""
    R = resolution
    grid = np.zeros((R, R, R), dtype=bool)
    # Work in voxel coordinates: voxel i spans [i, i+1).
    v = tris * R
    # Per-triangle sample density from the longest edge, pitch < 0.5 voxel.
    e01 = np.linalg.norm(v[:, 1] - v[:, 0], axis=1)
    e02 = np.linalg.norm(v[:, 2] - v[:, 0], axis=1)
    e12 = np.linalg.norm(v[:, 2] - v[:, 1], axis=1)
    max_edge = np.maximum(np.maximum(e01, e02), e12)
    n_sub = np.clip(np.ceil(max_edge * 2.0).astype(np.int64), 1, 4096)

    # Group triangles by subdivision count so each group is one vectorized op.
    for n in np.unique(n_sub):
        sel = v[n_sub == n]  # [t, 3, 3]
        # Barycentric lattice: (i/n, j/n) with i+j<=n, at sub-half-voxel pitch.
        i, j = np.meshgrid(np.arange(n + 1), np.arange(n + 1), indexing="ij")
        keep = (i + j) <= n
        a = (i[keep] / n).astype(np.float32)
        b = (j[keep] / n).astype(np.float32)
        c = 1.0 - a - b
        # points[t, s, 3] = a*v0 + b*v1 + c*v2
        pts = (
            a[None, :, None] * sel[:, None, 0]
            + b[None, :, None] * sel[:, None, 1]
            + c[None, :, None] * sel[:, None, 2]
        ).reshape(-1, 3)
        idx = np.clip(np.floor(pts).astype(np.int64), 0, R - 1)
        grid[idx[:, 0], idx[:, 1], idx[:, 2]] = True
    return grid


def _voxelize_parity(tris: np.ndarray, resolution: int) -> np.ndarray:
    """Center-inside solid voxelization by vertical-ray parity counting.

    For each triangle, find the (x, y) voxel-center rays piercing its xy
    projection, compute the z of the piercing point, and toggle every voxel
    center above it; a cumulative parity along z then yields inside/outside.
    Rays are jittered by a sub-voxel epsilon so shared triangle edges don't
    double-count. Exact (to fp32) for watertight meshes.
    """
    R = resolution
    v = np.asarray(tris, dtype=np.float64) * R
    toggles = np.zeros((R, R, R + 1), dtype=np.int64)
    # Incommensurate jitter keeps rays off shared edges/vertices.
    ex, ey = 7.3e-7, 3.1e-7
    for tri in v:
        (x0, y0, z0), (x1, y1, z1), (x2, y2, z2) = tri
        det = (y1 - y2) * (x0 - x2) + (x2 - x1) * (y0 - y2)
        if abs(det) < 1e-12:
            continue  # degenerate or vertical: no xy area, no crossing
        ix_lo = max(0, int(np.ceil(min(x0, x1, x2) - 0.5 - ex)))
        ix_hi = min(R - 1, int(np.floor(max(x0, x1, x2) - 0.5 - ex)))
        iy_lo = max(0, int(np.ceil(min(y0, y1, y2) - 0.5 - ey)))
        iy_hi = min(R - 1, int(np.floor(max(y0, y1, y2) - 0.5 - ey)))
        if ix_lo > ix_hi or iy_lo > iy_hi:
            continue
        px = np.arange(ix_lo, ix_hi + 1, dtype=np.float64) + 0.5 + ex
        py = np.arange(iy_lo, iy_hi + 1, dtype=np.float64) + 0.5 + ey
        PX, PY = np.meshgrid(px, py, indexing="ij")
        a = ((y1 - y2) * (PX - x2) + (x2 - x1) * (PY - y2)) / det
        b = ((y2 - y0) * (PX - x2) + (x0 - x2) * (PY - y2)) / det
        c = 1.0 - a - b
        hit = (a >= 0) & (b >= 0) & (c >= 0)
        if not hit.any():
            continue
        zstar = a * z0 + b * z1 + c * z2
        # First voxel-center index strictly above the crossing.
        k = np.ceil(zstar - 0.5).astype(np.int64)
        ii, jj = np.nonzero(hit)
        kk = np.clip(k[hit], 0, R)  # k == R toggles nothing (virtual layer)
        np.add.at(toggles, (ii + ix_lo, jj + iy_lo, kk), 1)
    inside = (np.cumsum(toggles[:, :, :R], axis=2) % 2).astype(bool)
    return inside


def _fill_interior(surface: np.ndarray) -> np.ndarray:
    """Exterior flood fill by iterative dilation, then complement.

    Vectorized frontier BFS: the exterior region grows from all six grid faces
    through empty voxels; everything never reached (surface + enclosed volume)
    is solid. Runs in O(R) dilation sweeps, each a cheap boolean shift.
    """
    R = surface.shape[0]
    empty = ~surface
    exterior = np.zeros_like(surface)
    for axis in range(3):
        face = [slice(None)] * 3
        face[axis] = 0
        exterior[tuple(face)] = empty[tuple(face)]
        face[axis] = R - 1
        exterior[tuple(face)] = empty[tuple(face)]
    while True:
        grown = exterior.copy()
        grown[1:, :, :] |= exterior[:-1, :, :]
        grown[:-1, :, :] |= exterior[1:, :, :]
        grown[:, 1:, :] |= exterior[:, :-1, :]
        grown[:, :-1, :] |= exterior[:, 1:, :]
        grown[:, :, 1:] |= exterior[:, :, :-1]
        grown[:, :, :-1] |= exterior[:, :, 1:]
        grown &= empty
        if (grown == exterior).all():
            break
        exterior = grown
    return ~exterior


def voxelize(
    triangles: np.ndarray,
    resolution: int = 64,
    fill: bool = True,
    normalize: bool = True,
    margin: float = 0.05,
    backend: str = "auto",
    fill_method: str = "parity",
) -> np.ndarray:
    """Voxelize a triangle soup to a ``bool [R, R, R]`` occupancy grid.

    Args:
      triangles: ``[n, 3, 3]`` vertex array (e.g. from ``load_stl``).
      resolution: grid edge length R (reference supports 16/32/64; 128 stretch).
      fill: if True, return the center-inside solid (parity ray casting);
        if False, return the conservative surface shell (sampling rasterizer).
        The two use different semantics on purpose: the solid is unbiased for
        training occupancy grids, the shell is a superset of surface voxels.
      normalize: run ``normalize_mesh`` first (disable if already in [0,1]³).
      margin: normalization margin (fraction of the unit cube per side).
      backend: "auto" | "native" | "numpy". "auto" uses the C++ rasterizer if
        the shared library is built, else numpy. "native" requires it.
      fill_method: "parity" (exact, watertight meshes) or "flood" (surface
        rasterize + exterior flood fill — conservative, tolerates small holes).
    """
    tris = np.asarray(triangles, dtype=np.float32)
    # Batch-preprocessing span (no-op without an active run): export /
    # build-cache pipelines run this per mesh, and the per-mesh wall is
    # what sets ingest throughput (BASELINE.md's meshes/s line). Pool
    # workers carry no sink, so the parallel path stays dark and free.
    with obs.span("voxelize", tris=int(tris.shape[0]),
                  resolution=resolution, fill=bool(fill)):
        if normalize:
            tris = normalize_mesh(tris, margin=margin)
        # The native path implements the parity fill and the exact shell;
        # a "flood" fill request (hole-tolerant meshes) must stay on the
        # numpy implementation rather than silently getting parity
        # semantics.
        native_ok = (not fill) or fill_method == "parity"
        if backend == "native" and not native_ok:
            raise ValueError(
                "backend='native' has no flood fill; use "
                "fill_method='parity' or backend='numpy'/'auto'"
            )
        if backend != "numpy" and native_ok:
            try:
                from featurenet_tpu.native import voxelize_native

                return voxelize_native(tris, resolution, fill)
            except Exception:
                if backend == "native":
                    raise
        if not fill:
            return _rasterize_surface(tris, resolution)
        if fill_method == "flood":
            return _fill_interior(_rasterize_surface(tris, resolution))
        return _voxelize_parity(tris, resolution)
