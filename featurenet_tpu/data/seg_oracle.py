"""Measured IoU ceiling for segmentation under the generator's label ambiguity.

The seg64 run plateaus at mean IoU ~0.80 and the round-2 claim was "label
ambiguity, not undertraining" — asserted, never quantified (round-2 verdict
weak item 3). This module measures the ceiling, model-free.

The ambiguity mechanism is exact, not a vibe: ``generate_sample`` carves
features in generation order and a voxel covered by several removal volumes
keeps the *earlier* feature's label, while the observable part
(``stock & ~union(removals)``) is order-invariant. Features are drawn iid,
so for any permutation π of a part's features, ``carve(labels, removals, π)``
is an *equally likely* ground truth for the *identical* input grid. No
predictor, however good, can tell which order the generator used.

Two measured numbers (both use the exact eval metric from
``train.steps.aggregate_eval``: per-class intersection/union summed over the
whole set, IoU per class, mean over classes present):

- ``iou_random_pair`` — expected IoU between two independently ordered
  ground truths for the same parts. This is what an ideal predictor that
  reconstructs the geometry perfectly but guesses the order uniformly
  scores in expectation.
- ``iou_canonical`` — IoU of the *best deterministic tie-break* we know
  (label multi-covered voxels by a fixed canonical order) against the
  generator's random order. A deterministic predictor can commit to one
  valid labeling; this is the measured ceiling for that strategy and the
  number 0.798 should be judged against.

Also reported: the ambiguous-voxel fraction (labeled voxels covered by ≥2
removals — the voxels whose label is unknowable) and per-class ceilings so
the step/slot families' shares are visible.

Round-5 addition — the OVERLAPPING-EXTENT ceiling for canonical labels
(round-4 verdict task 7). Canonical ordering makes the label of a multi-
covered voxel deterministic *given the features' true extents* — but the
observable part only shows the carved UNION: where removal volumes
overlap, how far each feature's extent continues inside already-removed
space is not generally recoverable from the input. The combined seg64
model's residual 0.11 gap was attributed to "inter-feature boundary
assignment"; these bounds quantify what that assignment is worth:

- ``iou_extent_guess`` — expected IoU of a predictor that reconstructs
  geometry and classes perfectly but, on every multi-covered carved
  voxel, guesses uniformly among the covering features instead of
  knowing the canonical-first one. The extent-blind ceiling: a model
  scoring near this number has learned everything except extent
  inference through overlaps.
- ``iou_overlap_worst`` — the same, but every multi-covered voxel gets
  the canonically-LAST cover (the adversarial valid assignment): the
  hard floor of valid-alternative disagreement.
- ``overlap_error_share_at_0889`` — what fraction of the measured
  model's gap (1 − 0.889) the extent-guess disagreement alone accounts
  for, so "geometry, not semantics" is a number, not a vibe.

Run:  python -m featurenet_tpu.data.seg_oracle [--resolution 64]
          [--num-features 3] [--samples 1024] [--seed 0]
"""

from __future__ import annotations

import argparse
import json

import numpy as np

from featurenet_tpu.data.synthetic import (
    NUM_CLASSES,
    carve,
    generate_sample_with_removals,
)


def _accumulate_iou(inter, union, seg_true, seg_pred, n_cls):
    """Add one sample's per-class intersection/union counts (exact sums,
    same aggregation as train.steps.make_eval_step)."""
    t = seg_true.ravel()
    p = seg_pred.ravel()
    agree = t == p
    inter += np.bincount(t[agree], minlength=n_cls)[:n_cls]
    union += (
        np.bincount(t, minlength=n_cls)[:n_cls]
        + np.bincount(p, minlength=n_cls)[:n_cls]
        - np.bincount(t[agree], minlength=n_cls)[:n_cls]
    )


def _mean_iou(inter, union):
    present = union > 0
    iou = np.where(present, inter / np.maximum(union, 1), 0.0)
    return float(iou.sum() / max(int(present.sum()), 1)), iou, present


def measure_ceiling(
    resolution: int = 64,
    num_features: int = 3,
    samples: int = 1024,
    seed: int = 0,
) -> dict:
    """Monte-Carlo estimate of the ambiguity IoU ceiling. Returns a dict of
    aggregate numbers (see module docstring)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 7001]))
    n_cls = NUM_CLASSES + 1  # + background
    inter_rp = np.zeros(n_cls, np.int64)
    union_rp = np.zeros(n_cls, np.int64)
    inter_cn = np.zeros(n_cls, np.int64)
    union_cn = np.zeros(n_cls, np.int64)
    inter_eg = np.zeros(n_cls, np.int64)
    union_eg = np.zeros(n_cls, np.int64)
    inter_ow = np.zeros(n_cls, np.int64)
    union_ow = np.zeros(n_cls, np.int64)
    ambiguous = 0
    labeled = 0
    for _ in range(samples):
        _, labels, seg, removals = generate_sample_with_removals(
            rng, resolution, num_features=num_features
        )
        # Two more equally-valid ground truths for the same part: one with a
        # fresh random order (the "another draw of the generator" labeling)
        # and one with the canonical deterministic order (sort by class id,
        # index-stable) a committed predictor would pick.
        perm = rng.permutation(num_features)
        _, seg_perm = carve(labels, removals, order=perm)
        canon = np.argsort(labels, kind="stable")
        _, seg_canon = carve(labels, removals, order=canon)
        _accumulate_iou(inter_rp, union_rp, seg, seg_perm, n_cls)
        _accumulate_iou(inter_cn, union_cn, seg, seg_canon, n_cls)
        # Ambiguous voxels: in the part's carved region and covered by >=2
        # removals — swapping those two features' order flips the label.
        cover = np.stack([r.astype(bool) for r in removals])
        cover_n = cover.sum(axis=0)
        multi = (cover_n >= 2) & (seg_canon > 0)
        ambiguous += int(multi.sum())
        labeled += int((seg_canon > 0).sum())

        # Overlapping-extent bounds against the canonical GT: reassign each
        # multi-covered voxel (a) to a uniformly-guessed covering feature
        # (extent-blind expected case) and (b) to the canonically-LAST
        # cover (worst valid assignment). Single-cover voxels are fully
        # determined by visible geometry and stay put.
        if multi.any():
            cov_m = cover[:, multi]  # [k, n_multi]
            lab_sorted = labels[canon]
            cov_sorted = cov_m[canon]
            u = rng.random(cov_sorted.shape) * cov_sorted
            seg_guess = seg_canon.copy()
            seg_guess[multi] = 1 + lab_sorted[np.argmax(u, axis=0)]
            seg_worst = seg_canon.copy()
            k = cov_sorted.shape[0]
            last_idx = (k - 1) - np.argmax(cov_sorted[::-1], axis=0)
            seg_worst[multi] = 1 + lab_sorted[last_idx]
        else:
            seg_guess = seg_canon
            seg_worst = seg_canon
        _accumulate_iou(inter_eg, union_eg, seg_canon, seg_guess, n_cls)
        _accumulate_iou(inter_ow, union_ow, seg_canon, seg_worst, n_cls)

    miou_rp, iou_rp, present = _mean_iou(inter_rp, union_rp)
    miou_cn, iou_cn, _ = _mean_iou(inter_cn, union_cn)
    miou_eg, _, _ = _mean_iou(inter_eg, union_eg)
    miou_ow, _, _ = _mean_iou(inter_ow, union_ow)
    out_extra = {}
    if (resolution, num_features) == (64, 3):
        # Only meaningful at the shapes the combined seg64 model (0.889,
        # BASELINE.md round 4) was measured at — at other shapes the share
        # would compare incommensurable numbers.
        out_extra["overlap_error_share_at_0889"] = round(
            (1.0 - miou_eg) / (1.0 - 0.889), 3
        )
    return {
        "resolution": resolution,
        "num_features": num_features,
        "samples": samples,
        "iou_random_pair": round(miou_rp, 4),
        "iou_canonical": round(miou_cn, 4),
        "iou_extent_guess": round(miou_eg, 4),
        "iou_overlap_worst": round(miou_ow, 4),
        **out_extra,
        "ambiguous_voxel_fraction": round(ambiguous / max(labeled, 1), 4),
        "per_class_iou_canonical": [
            round(float(v), 4) if p else None
            for v, p in zip(iou_cn, present)
        ],
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--resolution", type=int, default=64)
    parser.add_argument("--num-features", type=int, default=3)
    parser.add_argument("--samples", type=int, default=1024)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()
    out = measure_ceiling(
        args.resolution, args.num_features, args.samples, args.seed
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
