"""Offline dataset passes: STL tree → voxel cache, and synthetic → cache.

The reference's pipeline voxelizes the 24-class STL benchmark once and trains
from cached arrays (SURVEY.md §3.2 — "offline pass → save .npy / in-memory
cache"). This module is that pass, plus the cache reader:

- Disk layout (input): ``root/<class_name>/<part>.stl`` — 24 class dirs, the
  reference benchmark layout.
- Cache layout (output, ``storage: "packed"`` in ``index.json``): one
  ``<cls>.npy`` per class holding **bit-packed** ``uint8 [N, R, R, R/8]``
  voxels — byte-identical to the host→device wire format
  (``data.synthetic.pack_voxels``) — plus ``<cls>.files.json`` provenance
  and a top-level ``index.json``. Packed-on-disk is 8× smaller than the
  round-1 unpacked layout and is read with ``np.load(mmap_mode='r')``:
  training from a reference-scale 128³ cache touches only the pages the
  sampler draws, so host RSS stays bounded by the working set instead of
  the cache size (round-2 verdict items 1 and 5). Legacy ``.npz`` caches
  (unpacked, deflated) still load — packed once at open, 8× less resident
  than before.
- ``VoxelCacheDataset`` streams shuffled, host-sharded batches from the
  cache in the classify wire format (``data.synthetic.to_wire``: bit-packed
  voxels + label + mask; STL parts carry no per-voxel ground truth, so
  there is no segment wire from a cache), the same contract as
  ``SyntheticVoxelDataset(task="classify")`` — the Trainer is
  source-agnostic.
- ``export_synthetic_cache`` materializes the parametric generator into the
  same cache format, giving a fixed, reproducible on-disk dataset (the
  train/test split used for the accuracy numbers in BASELINE.md).
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Sequence

import numpy as np

from featurenet_tpu import faults, obs
from featurenet_tpu.data.stl import load_stl
from featurenet_tpu.data.synthetic import (
    CLASS_NAMES,
    carve,
    generate_sample,
    generate_sample_with_removals,
    pack_voxels,
    random_orientation,
)
from featurenet_tpu.data.voxelize import voxelize


def _maybe_cache_read_fault(ds) -> None:
    """Shared ``cache_read_error`` injection site for both cache datasets:
    counts gathers on the dataset instance and raises on the spec's Nth
    (the shape of an mmapped shard vanishing under a live reader)."""
    ds._reads = getattr(ds, "_reads", 0) + 1
    if faults.maybe_fail("cache_read_error", read=ds._reads):
        raise faults.InjectedFault(
            f"cache_read_error at gather #{ds._reads} (the mmapped "
            "shard behind this batch went away)"
        )


def _voxelize_stl_packed(args: tuple[str, int, str, bool]) -> np.ndarray:
    """Worker: one STL file → bit-packed ``uint8 [R, R, R/8]`` occupancy.

    Module-level (picklable) so a multiprocessing pool can fan the
    embarrassingly-parallel per-file work out across cores; imports stay
    jax-free on this path so spawned workers start cheap and never touch
    the device client. ``normalize=False`` is the aligned-tree path
    (segmentation sidecars must stay voxel-exact with the mesh).
    """
    path, resolution, backend, normalize = args
    tris = load_stl(path)
    grid = voxelize(
        tris, resolution, fill=True, backend=backend, normalize=normalize
    )
    return pack_voxels(grid)


def build_cache(
    stl_root: str,
    out_root: str,
    resolution: int = 64,
    classes: Sequence[str] | None = None,
    backend: str = "auto",
    workers: int | None = None,
) -> dict:
    """Voxelize an STL class tree into packed per-class shards.

    Returns the index dict. ``workers``: process-pool width for the
    per-file voxelization (None = ``os.cpu_count()``; <=1 = inline). The
    output is bit-exact regardless of worker count — the pool preserves
    file order and each file's rasterization is independent.

    Labeling: the index's ``label_ids`` pins every class directory whose
    name matches a canonical CLASS_NAMES entry to that entry's id — even in
    a partial tree — so cache-trained checkpoints agree with the
    Predictor's id→name mapping (a positional/alphabetical scheme silently
    permuted labels: eval looked fine, infer answered nonsense). Unknown
    directory names get ids after the canonical block; training on those
    needs a config whose ``num_classes`` covers them.
    """
    if resolution % 8:
        raise ValueError("resolution must be divisible by 8 (packed wire)")
    os.makedirs(out_root, exist_ok=True)
    if classes is None:
        found = {
            d for d in os.listdir(stl_root)
            if os.path.isdir(os.path.join(stl_root, d))
        }
        classes = [c for c in CLASS_NAMES if c in found] + sorted(
            found - set(CLASS_NAMES)
        )
    else:
        classes = list(classes)
    known = {c: i for i, c in enumerate(CLASS_NAMES)}
    next_id = len(CLASS_NAMES)
    label_ids = {}
    for cls in classes:
        if cls in known:
            label_ids[cls] = known[cls]
        else:
            label_ids[cls] = next_id
            next_id += 1
    if next_id > len(CLASS_NAMES):
        unknown = [c for c in classes if c not in known]
        obs.warn(
            "build_cache_warning",
            "non-canonical class dirs (typo'd benchmark name, or "
            "a custom class) get label ids past the canonical "
            f"block; training them needs num_classes >= {next_id} "
            "(stock presets have 24 — the Trainer refuses "
            "out-of-range labels)",
            dirs=unknown,
        )
    index = {
        "resolution": resolution,
        "storage": "packed",
        "classes": [],
        "counts": {},
        "label_ids": label_ids,
    }
    if workers is None:
        workers = os.cpu_count() or 1
    pool = None
    if workers > 1:
        import multiprocessing

        # spawn, not fork: build_cache may run in a process that already
        # holds a live device client (the CLI, a test with jax imported);
        # forking that state wedges the tunnel. Spawned workers import only
        # the numpy-level data modules.
        pool = multiprocessing.get_context("spawn").Pool(workers)
    try:
        for cls in classes:
            cdir = os.path.join(stl_root, cls)
            files = sorted(
                f for f in os.listdir(cdir) if f.lower().endswith(".stl")
            )
            # One span per class shard (the unit of visible progress —
            # per-file timing lives in pool workers, where no sink is
            # installed and the hook is a no-op).
            with obs.span("build_cache_class", cls=cls, files=len(files),
                          workers=workers):
                packed = np.zeros(
                    (len(files), resolution, resolution, resolution // 8),
                    dtype=np.uint8,
                )
                work = [
                    (os.path.join(cdir, f), resolution, backend, True)
                    for f in files
                ]
                if pool is not None:
                    rows = pool.imap(
                        _voxelize_stl_packed, work,
                        chunksize=max(1, len(work) // (workers * 4) or 1),
                    )
                else:
                    rows = map(_voxelize_stl_packed, work)
                for i, row in enumerate(rows):
                    packed[i] = row
                np.save(os.path.join(out_root, f"{cls}.npy"), packed)
                with open(
                    os.path.join(out_root, f"{cls}.files.json"), "w"
                ) as fh:
                    json.dump(files, fh)
            index["classes"].append(cls)
            index["counts"][cls] = len(files)
    except BaseException:
        if pool is not None:
            # terminate, don't close: close+join would drain every queued
            # voxelization of doomed work before the error surfaces.
            pool.terminate()
            pool.join()
            pool = None
        raise
    finally:
        if pool is not None:
            pool.close()
            pool.join()
    with open(os.path.join(out_root, "index.json"), "w") as fh:
        json.dump(index, fh, indent=1)
    return index


def export_synthetic_cache(
    out_root: str,
    per_class: int = 100,
    resolution: int = 64,
    seed: int = 0,
    orient: bool = True,
    param_range=None,
    mesh_pose: str = "none",
    margin_jitter: tuple | None = None,
) -> dict:
    """Materialize the parametric generator into the packed cache format.

    Gives a *fixed* dataset (reproducible from the seed) with a stable
    train/test split downstream — the on-disk analog of the reference's
    24 × 1000 benchmark. ``param_range`` restricts every feature
    generator's size/position draws to a quantile window
    (``"mid"``/``"tails"``/``(lo, hi)`` — see ``synthetic._ParamRange``);
    the OOD holdout protocol trains on a ``"mid"`` cache and evaluates on
    tail draws.

    ``mesh_pose``: route each part through the STL pipeline
    (``voxels_to_mesh`` → ``voxelize``) before packing — ``"remesh"``
    keeps the identity pose (STL normalization only, matching
    ``build-cache`` output), ``"so3"`` additionally applies a uniform
    random rotation (the OOD-robust training cache: arbitrary poses with
    exact parity-filled geometry). ``margin_jitter=(lo, hi)`` draws the
    normalization margin per sample — scale augmentation against the
    margin-shift brittleness the round-4 OOD harness measured.
    """
    if mesh_pose not in ("none", "remesh", "so3"):
        raise ValueError(
            f"mesh_pose {mesh_pose!r}: expected 'none', 'remesh', or 'so3'"
        )
    use_mesh = mesh_pose != "none" or margin_jitter is not None
    if resolution % 8:
        raise ValueError("resolution must be divisible by 8 (packed wire)")
    os.makedirs(out_root, exist_ok=True)
    index = {
        "resolution": resolution,
        "storage": "packed",
        "classes": [],
        "counts": {},
        "seed": seed,
        # Canonical ids, explicit: the full canonical tree makes positional
        # labels coincide with these anyway, but readers should never have
        # to rely on that coincidence.
        "label_ids": {cls: i for i, cls in enumerate(CLASS_NAMES)},
        # Provenance for OOD-holdout caches ("mid"/"tails"/[lo, hi]/None).
        "param_range": (
            list(param_range) if isinstance(param_range, (tuple, list))
            else param_range
        ),
        "mesh_pose": mesh_pose,
        "margin_jitter": list(margin_jitter) if margin_jitter else None,
    }
    for cls_id, cls in enumerate(CLASS_NAMES):
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, cls_id])
        )
        # Pack per sample into the packed class array: peak transient RAM
        # is one unpacked grid, not one unpacked class (2 GB at 128³×1000).
        packed = np.zeros(
            (per_class, resolution, resolution, resolution // 8),
            dtype=np.uint8,
        )
        with obs.span("export_class", cls=cls, n=per_class,
                      mesh_pose=mesh_pose):
            for i in range(per_class):
                part, _, _ = generate_sample(
                    rng, resolution, label=cls_id, orient=orient,
                    param_range=param_range,
                )
                if use_mesh:
                    from featurenet_tpu.data.voxel_to_mesh import (
                        random_rotation_matrix,
                        rotate_mesh,
                        voxels_to_mesh,
                    )
                    from featurenet_tpu.data.voxelize import voxelize

                    tris = voxels_to_mesh(part.astype(bool))
                    if mesh_pose == "so3":
                        tris = rotate_mesh(tris, random_rotation_matrix(rng))
                    m = (
                        0.05 if margin_jitter is None
                        else float(rng.uniform(*margin_jitter))
                    )
                    part = voxelize(tris, resolution, fill=True, margin=m)
                packed[i] = pack_voxels(part)
            np.save(os.path.join(out_root, f"{cls}.npy"), packed)
        with open(os.path.join(out_root, f"{cls}.files.json"), "w") as fh:
            json.dump([f"synthetic_{i:05d}" for i in range(per_class)], fh)
        index["classes"].append(cls)
        index["counts"][cls] = per_class
    with open(os.path.join(out_root, "index.json"), "w") as fh:
        json.dump(index, fh, indent=1)
    return index


def _generate_seg_sample(
    rng: np.random.Generator,
    resolution: int,
    num_features: int,
    label_order: str,
) -> tuple[np.ndarray, np.ndarray]:
    """One segmentation sample ``(part bool [R³], seg int32 [R³])``.

    ``label_order`` picks the ground-truth labeling of voxels covered by
    several features' removal volumes (the observable part is identical
    either way — ``data.seg_oracle``):

    - ``"canonical"``: carve in class-id-sorted order. Deterministic given
      the part's feature multiset, so the label function is learnable; this
      removes the order ambiguity the oracle measures (~0.10 mean-IoU at
      the seg64 shapes) and is the default for exported datasets.
    - ``"generation"``: the generator's draw order (round-2 behavior) — a
      random choice among equally-valid labelings; kept for reproducing the
      round-2 numbers and for ceiling experiments.
    """
    part, labels, seg, removals = generate_sample_with_removals(
        rng, resolution, num_features=num_features
    )
    if label_order == "canonical":
        _, seg = carve(labels, removals,
                       order=np.argsort(labels, kind="stable"),
                       resolution=resolution)
    elif label_order != "generation":
        raise ValueError(f"unknown label_order {label_order!r}")
    return part, seg


def export_seg_cache(
    out_root: str,
    num_parts: int = 2400,
    resolution: int = 64,
    num_features: int = 3,
    shard_size: int = 200,
    seed: int = 0,
    label_order: str = "canonical",
) -> dict:
    """Materialize multi-feature parts with per-voxel ground truth.

    Segmentation parts carry several features each, so the per-class shard
    layout of the classification cache doesn't apply; shards are flat
    ``seg_{i:04d}.voxels.npy`` (bit-packed ``uint8 [N,R,R,R/8]``, the wire
    format) + ``seg_{i:04d}.seg.npy`` (``int8 [N,R,R,R]``, 0 = stock/air,
    1+class = feature removal volume) pairs, mmap-read like the classify
    cache. ``index.json`` carries ``{"kind": "segment"}`` so the reader
    picks the right dataset class. ``label_order``: see
    ``_generate_seg_sample`` — "canonical" (default) makes overlap labels
    deterministic; "generation" reproduces the round-2 dataset.
    """
    if resolution % 8:
        raise ValueError("resolution must be divisible by 8 (packed wire)")
    os.makedirs(out_root, exist_ok=True)
    index = {
        "kind": "segment",
        "resolution": resolution,
        "storage": "packed",
        "num_features": num_features,
        "shards": [],
        "seed": seed,
        "label_order": label_order,
    }
    done = 0
    shard_id = 0
    while done < num_parts:
        n = min(shard_size, num_parts - done)
        rng = np.random.default_rng(np.random.SeedSequence([seed, shard_id]))
        stem = f"seg_{shard_id:04d}"
        with obs.span("export_seg_shard", shard=stem, n=n):
            voxels = np.zeros(
                (n, resolution, resolution, resolution // 8), np.uint8
            )
            seg = np.zeros((n, resolution, resolution, resolution), np.int8)
            for i in range(n):
                part, s = _generate_seg_sample(
                    rng, resolution, num_features, label_order
                )
                voxels[i] = pack_voxels(part)
                seg[i] = s.astype(np.int8)
            np.save(os.path.join(out_root, f"{stem}.voxels.npy"), voxels)
            np.save(os.path.join(out_root, f"{stem}.seg.npy"), seg)
        index["shards"].append({"stem": stem, "count": n})
        done += n
        shard_id += 1
    with open(os.path.join(out_root, "index.json"), "w") as fh:
        json.dump(index, fh, indent=1)
    return index


def build_seg_cache(
    stl_root: str,
    out_root: str,
    backend: str = "auto",
    workers: int | None = None,
    shard_size: int | None = None,
) -> dict:
    """Ingest a segmentation STL tree (mesh + per-voxel label sidecars) into
    the packed seg-cache format ``SegCacheDataset`` reads.

    The segmentation analog of ``build_cache`` — the full reference
    modality for config 4: STL files on disk in front, voxelizing ingest in
    the middle, mmap-read shards behind (round-2 verdict item 7). Input is
    ``voxel_to_mesh.export_seg_stl_tree``'s layout (or anything matching
    it: ``parts/*.stl`` with ``<stem>.seg.npy`` sidecars and an
    ``index.json`` of kind ``segment_stl``).

    Meshes are voxelized with ``normalize=False`` when the tree declares
    ``aligned_unit_cube`` (sidecar labels live on the mesh's own voxel
    grid; re-normalizing would shift the part against its labels — refused
    below when the tree doesn't declare alignment, because silently
    training on misaligned labels is the invisible kind of wrong). A
    consistency check per part enforces the alignment: a labeled voxel
    (seg > 0, a feature's *removed* volume) must be air in the voxelized
    part.
    """
    index_path = os.path.join(stl_root, "index.json")
    with open(index_path) as fh:
        tree = json.load(fh)
    if tree.get("kind") != "segment_stl":
        raise ValueError(
            f"{stl_root} is not a segmentation STL tree (export with "
            "`cli export-stl-data --seg`); classification trees go "
            "through build_cache"
        )
    if not tree.get("aligned_unit_cube"):
        raise ValueError(
            "segmentation ingest needs aligned_unit_cube trees: per-voxel "
            "sidecars are only meaningful in the mesh's own grid frame, "
            "and normalization would shift the part against its labels"
        )
    resolution = int(tree["resolution"])
    if shard_size is None:
        shard_size = int(tree.get("shard_size", 200))
    pdir = os.path.join(stl_root, "parts")
    stems = sorted(
        f[:-4] for f in os.listdir(pdir) if f.lower().endswith(".stl")
    )
    if not stems:
        raise ValueError(f"no .stl parts under {pdir}")
    os.makedirs(out_root, exist_ok=True)
    index = {
        "kind": "segment",
        "resolution": resolution,
        "storage": "packed",
        "num_features": tree.get("num_features"),
        "shards": [],
        "source": {"stl_tree": os.path.abspath(stl_root),
                   "label_order": tree.get("label_order")},
    }
    if workers is None:
        workers = os.cpu_count() or 1
    pool = None
    if workers > 1:
        import multiprocessing

        # spawn, not fork — same rationale as build_cache.
        pool = multiprocessing.get_context("spawn").Pool(workers)
    try:
        work = [
            (os.path.join(pdir, s + ".stl"), resolution, backend, False)
            for s in stems
        ]
        if pool is not None:
            rows = pool.imap(
                _voxelize_stl_packed, work,
                chunksize=max(1, len(work) // (workers * 4) or 1),
            )
        else:
            rows = map(_voxelize_stl_packed, work)
        shard_id = 0
        vox_buf, seg_buf = [], []

        def flush():
            nonlocal shard_id
            stem = f"seg_{shard_id:04d}"
            with obs.span("seg_cache_flush", shard=stem, n=len(vox_buf)):
                np.save(os.path.join(out_root, f"{stem}.voxels.npy"),
                        np.stack(vox_buf))
                np.save(os.path.join(out_root, f"{stem}.seg.npy"),
                        np.stack(seg_buf))
            index["shards"].append({"stem": stem, "count": len(vox_buf)})
            vox_buf.clear()
            seg_buf.clear()
            shard_id += 1

        with obs.span("build_seg_cache", parts=len(stems), workers=workers):
            for stem, packed in zip(stems, rows):
                seg = np.load(os.path.join(pdir, stem + ".seg.npy"))
                if seg.shape != (resolution,) * 3:
                    raise ValueError(
                        f"{stem}: sidecar shape {seg.shape} != grid "
                        f"{(resolution,) * 3}"
                    )
                part = np.unpackbits(packed, axis=-1).astype(bool)
                if (part & (seg > 0)).any():
                    raise ValueError(
                        f"{stem}: labeled voxels occupied in the voxelized "
                        "part — mesh and sidecar are misaligned (was the "
                        "tree exported aligned_unit_cube?)"
                    )
                vox_buf.append(packed)
                seg_buf.append(seg.astype(np.int8))
                if len(vox_buf) >= shard_size:
                    flush()
            if vox_buf:
                flush()
    except BaseException:
        if pool is not None:
            pool.terminate()
            pool.join()
            pool = None
        raise
    finally:
        if pool is not None:
            pool.close()
            pool.join()
    with open(os.path.join(out_root, "index.json"), "w") as fh:
        json.dump(index, fh, indent=1)
    return index


# One open per (cache dir, index mtime) per process: the Trainer builds
# train+test instances over the same cache, and both index into the memo's
# per-class arrays — no dataset-private copy of the grids exists. Packed
# caches are held as read-only memmaps (resident = the sampler's working
# set, reclaimable page cache); legacy npz caches decompress once and are
# bit-packed in RAM (8× less resident than the round-1 unpacked memo).
_cache_memo: dict = {}


def _open_packed(cache_root: str, name: str) -> np.ndarray:
    """mmap one packed shard; fancy-indexing it copies out only the drawn
    rows' pages, so a reference-scale cache never fully materializes."""
    return np.load(os.path.join(cache_root, f"{name}.npy"), mmap_mode="r")


def _load_cache(cache_root: str):
    index_path = os.path.join(cache_root, "index.json")
    key = (os.path.abspath(cache_root), os.path.getmtime(index_path))
    if key not in _cache_memo:
        with open(index_path) as fh:
            index = json.load(fh)
        if index.get("kind") == "segment":
            raise ValueError(
                f"{cache_root} is a segmentation cache; use it with "
                "task='segment' (SegCacheDataset), not a classify config"
            )
        packed = {}
        for cls in index["classes"]:
            if index.get("storage") == "packed":
                packed[cls] = _open_packed(cache_root, cls)
            else:
                with np.load(os.path.join(cache_root, f"{cls}.npz")) as z:
                    packed[cls] = pack_voxels(z["voxels"])  # validates W%8
        _cache_memo.clear()  # hold at most one cache resident
        _cache_memo[key] = (index, packed)
    return _cache_memo[key]


def _hash_split_rows(n: int, split: str, test_fraction: float) -> np.ndarray:
    """Deterministic per-index hash split, shared by both cache datasets:
    the same samples are held out regardless of host count or epoch."""
    h = (np.arange(n) * 2654435761 % 1000) / 1000.0
    keep = h >= test_fraction if split == "train" else h < test_fraction
    return np.nonzero(keep)[0].astype(np.int64)


def _epoch_index_batches(
    n: int, batch: int, num_shards: int = 1, shard_id: int = 0
):
    """Exact-pass index batches; the final partial batch wraps to the front
    with mask=0 rows so masked sums count every sample exactly once while
    batch shapes stay static. Shared by both cache datasets.

    ``num_shards``/``shard_id`` decimate the pass for multi-host eval: shard
    ``i`` takes samples ``i, i+num_shards, …`` — every sample lands in
    exactly one shard, so when each host feeds its shard into its slice of
    the global eval batch the globally-reduced masked sums count each
    held-out sample exactly once (instead of ``process_count`` times, the
    round-1 redundancy). All shards yield the same number of batches —
    required, because hosts dispatch the jitted eval step in lockstep.
    """
    if n <= 0:  # constructors refuse empty splits; belt and braces here
        raise ValueError("epoch over an empty split")
    mine = np.arange(shard_id, n, num_shards, dtype=np.int64)
    # ceil over the *largest* shard so every host emits equally many batches.
    largest = (n + num_shards - 1) // num_shards
    n_batches = max((largest + batch - 1) // batch, 1)
    for b in range(n_batches):
        idx = mine[b * batch:(b + 1) * batch]
        mask = np.ones(batch, dtype=np.float32)
        if len(idx) < batch:
            mask[len(idx):] = 0.0
            pad = np.arange(batch - len(idx)) % n
            idx = np.concatenate([idx, pad])
        yield idx, mask


def _load_seg_cache(cache_root: str):
    """Returns (index, voxels_shards, seg_shards) — *lists* of per-shard
    arrays (packed voxels / int8 labels), memmapped for packed caches so a
    big seg cache never fully materializes. Concatenating here would defeat
    the mmap."""
    index_path = os.path.join(cache_root, "index.json")
    key = ("seg", os.path.abspath(cache_root), os.path.getmtime(index_path))
    if key not in _cache_memo:
        with open(index_path) as fh:
            index = json.load(fh)
        if index.get("kind") != "segment":
            raise ValueError(
                f"{cache_root} is not a segmentation cache (export with "
                "export_seg_cache / `cli export-seg-data`)"
            )
        voxels, seg = [], []
        for sh in index["shards"]:
            if index.get("storage") == "packed":
                voxels.append(_open_packed(cache_root, sh["stem"] + ".voxels"))
                seg.append(_open_packed(cache_root, sh["stem"] + ".seg"))
            else:
                with np.load(os.path.join(cache_root, sh["file"])) as z:
                    voxels.append(pack_voxels(z["voxels"]))  # validates W%8
                    seg.append(z["seg"])
        _cache_memo.clear()  # hold at most one cache resident
        _cache_memo[key] = (index, voxels, seg)
    return _cache_memo[key]


class SegCacheDataset:
    """Shuffled, host-sharded stream over a segmentation cache.

    Emits the segment wire format (``data.synthetic.WIRE_KEYS["segment"]``):
    ``voxels`` bit-packed uint8 ``[B,R,R,R/8]``, ``seg`` int8 ``[B,R,R,R]``,
    ``mask``. ``augment=True`` applies one cube-group rotation per sample to
    voxels and seg jointly, before packing (per-voxel targets must rotate
    with the part, so the device-side classify augmentation does not apply
    here). ``split`` uses the same deterministic index-hash rule as
    ``VoxelCacheDataset``.
    """

    def __init__(
        self,
        cache_root: str,
        global_batch: int = 32,
        split: str = "train",
        test_fraction: float = 0.2,
        num_hosts: int = 1,
        host_id: int = 0,
        seed: int = 0,
        augment: bool = False,
    ):
        if global_batch % num_hosts != 0:
            raise ValueError("global_batch must divide evenly across hosts")
        self.index, self._voxels, self._seg = _load_seg_cache(cache_root)
        self.resolution = int(self.index["resolution"])
        self.global_batch = global_batch
        self.local_batch = global_batch // num_hosts
        self.seed = seed
        self.host_id = host_id
        self.augment = augment
        # Shard-local addressing over the memo's per-shard (possibly
        # memmapped) arrays: global row g lives at
        # voxels[_shard_pos[g]][_row_in_shard[g]].
        counts = [v.shape[0] for v in self._voxels]
        self._shard_pos = np.repeat(
            np.arange(len(counts), dtype=np.int32), counts
        )
        self._row_in_shard = np.concatenate(
            [np.arange(c, dtype=np.int64) for c in counts]
        ) if counts else np.zeros(0, np.int64)
        self.rows = _hash_split_rows(int(sum(counts)), split, test_fraction)
        if len(self.rows) == 0:
            raise ValueError(f"empty split {split!r} in {cache_root}")

    def __len__(self) -> int:
        return len(self.rows)

    def _gather(self, idx, rng=None):
        """Materialize (packed voxels [n,R,R,R/8], seg int8 [n,R,R,R]) for
        split rows ``idx``. Without augmentation this is pure fancy
        indexing of the packed storage — no per-sample Python work, no
        packbits (the stored bytes *are* the wire format). Augmentation
        unpacks once per batch, rotates voxels+seg jointly per sample
        (per-voxel targets must rotate with the part), repacks once.
        """
        _maybe_cache_read_fault(self)
        g = self.rows[idx]
        sh, rw = self._shard_pos[g], self._row_in_shard[g]
        R = self.resolution
        vox = np.empty((len(g), R, R, R // 8), np.uint8)
        seg = np.empty((len(g), R, R, R), np.int8)
        for p in np.unique(sh):
            m = sh == p
            vox[m] = self._voxels[p][rw[m]]
            seg[m] = self._seg[p][rw[m]]
        if rng is not None:
            grids = np.unpackbits(vox, axis=-1)
            rot_v, rot_s = [], []
            for v, s in zip(grids, seg):
                rot = random_orientation(rng)
                rot_v.append(rot(v))
                rot_s.append(rot(s))
            vox = pack_voxels(np.stack(rot_v))
            seg = np.stack(rot_s)
        return vox, seg

    def materialize_split(
        self, multiple_of: int = 1, num_shards: int = 1, shard_id: int = 0
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """This host's block of the device-resident (HBM) seg dataset.

        Same contract as ``VoxelCacheDataset.materialize_split``; returns
        ``(packed_voxels, seg_int8, n_global)``. Augmentation happens on
        device (paired voxel+seg rotation inside the compiled step), so
        the block is raw rows.
        """
        n = len(self.rows)
        keep = n - (n % max(multiple_of, 1))
        if keep < num_shards:
            raise ValueError(
                f"split has {n} rows; {keep} after trimming to a multiple "
                f"of {multiple_of} — too few for {num_shards} feed groups"
            )
        order = np.random.default_rng(
            np.random.SeedSequence([self.seed, 0x4B10C5])
        ).permutation(n)[:keep]
        lo = keep * shard_id // num_shards
        hi = keep * (shard_id + 1) // num_shards
        vox, seg = self._gather(order[lo:hi])
        return vox, seg, keep

    def worker_iter(self, worker_id: int = 0, num_workers: int = 1
                    ) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.host_id, worker_id])
        )
        n = len(self.rows)
        while True:
            idx = rng.integers(0, n, size=self.local_batch)
            v, s = self._gather(idx, rng if self.augment else None)
            yield {
                "voxels": v,
                "seg": s,
                "mask": np.ones(self.local_batch, dtype=np.float32),
            }

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return self.worker_iter(0, 1)

    def epoch_batches(
        self, batch: int, num_shards: int = 1, shard_id: int = 0
    ) -> Iterator[dict[str, np.ndarray]]:
        """One exact pass; the final partial batch wraps with mask=0 rows.
        ``num_shards``/``shard_id`` split the pass disjointly (multi-host)."""
        for idx, mask in _epoch_index_batches(
            len(self.rows), batch, num_shards, shard_id
        ):
            v, s = self._gather(idx)
            yield {"voxels": v, "seg": s, "mask": mask}


class VoxelCacheDataset:
    """Shuffled, host-sharded, infinite batch stream over a voxel cache.

    Emits the classify wire format (``data.synthetic.WIRE_KEYS["classify"]``):
    ``voxels`` bit-packed uint8 ``[B, R, R, R/8]``, ``label`` int32, ``mask``
    float32 — same contract as ``SyntheticVoxelDataset(task="classify")``, so
    ``prefetch_to_device`` and the Trainer work unchanged. ``split``: "train"
    or "test" — a deterministic hash split per sample index (test_fraction of
    each class held out).

    ``augment=True`` applies a random rotation from the 24-element cube group
    to every sample drawn (train-time pose augmentation — the paper's ×24
    orientation augmentation, SURVEY.md §2 C3 — on top of whatever pose was
    baked in at export time). Machining-feature class is pose-invariant, so
    the label is unchanged. Exact epoch passes (eval) never augment.
    """

    def __init__(
        self,
        cache_root: str,
        global_batch: int = 96,
        split: str = "train",
        test_fraction: float = 0.2,
        num_hosts: int = 1,
        host_id: int = 0,
        seed: int = 0,
        augment: bool = False,
    ):
        if global_batch % num_hosts != 0:
            raise ValueError("global_batch must divide evenly across hosts")
        self.index, packed = _load_cache(cache_root)
        self.resolution = int(self.index["resolution"])
        self.global_batch = global_batch
        self.local_batch = global_batch // num_hosts
        self.seed = seed
        self.host_id = host_id
        self.augment = augment

        # Index into the shared memo arrays (bit-packed, possibly
        # memmapped) instead of copying rows out: sample m is
        # self._packed[self._cls_pos[m]][self.rows[m]]. Only the per-batch
        # gather below materializes sample copies.
        #
        # Storage position != semantic label: ``label_ids`` in the index
        # (written by build_cache) pins each class name to its canonical
        # CLASS_NAMES id so a partial tree still trains the same label the
        # Predictor will report. Caches without the field (old exports,
        # export_synthetic_cache's always-complete canonical tree) fall
        # back to position.
        self._packed = [packed[cls] for cls in self.index["classes"]]
        label_ids = self.index.get("label_ids")
        if label_ids is None:
            # Pre-label_ids cache: positional labels are only safe when the
            # stored class order already agrees with the canonical ids —
            # otherwise this is exactly the silent label permutation the
            # label_ids field was added to kill (eval self-consistent,
            # infer reports wrong names). Refuse, don't warn: the failure
            # mode is invisible downstream.
            mismatched = [
                (pos, cls)
                for pos, cls in enumerate(self.index["classes"])
                if cls in CLASS_NAMES and CLASS_NAMES.index(cls) != pos
            ]
            if mismatched:
                pos, cls = mismatched[0]
                raise ValueError(
                    f"cache {cache_root!r} predates the label_ids index "
                    f"field and stores {cls!r} at position {pos} (canonical "
                    f"id {CLASS_NAMES.index(cls)}); positional labels would "
                    "silently permute class names. Rebuild the cache "
                    "(`cli build-cache` / `cli export-data`)."
                )
            label_ids = {
                cls: pos for pos, cls in enumerate(self.index["classes"])
            }
        rows, labels, cls_pos = [], [], []
        for pos, cls in enumerate(self.index["classes"]):
            n = self._packed[pos].shape[0]
            r = _hash_split_rows(n, split, test_fraction)
            rows.append(r)
            cls_pos.append(np.full(len(r), pos, dtype=np.int32))
            labels.append(np.full(len(r), int(label_ids[cls]), dtype=np.int32))
        self.rows = np.concatenate(rows)
        self.labels = np.concatenate(labels)
        self._cls_pos = np.concatenate(cls_pos)
        if len(self.labels) == 0:
            raise ValueError(f"empty split {split!r} in {cache_root}")

    def _gather(
        self, idx: np.ndarray, rng: np.random.Generator | None = None
    ) -> np.ndarray:
        """Materialize bit-packed ``[len(idx), R, R, R/8]`` uint8 voxels for
        samples ``idx`` (the classify wire format — the jitted step unpacks
        on device). The stored bytes *are* the wire format, so the default
        path (device-side augmentation, or eval) is pure fancy indexing of
        the packed storage — the round-2 per-sample Python+packbits loop is
        gone, and what remains is a memcpy of 32 KB/sample at 64³. Host
        pose augmentation (``rng`` given) unpacks once per batch, rotates,
        repacks once."""
        _maybe_cache_read_fault(self)
        rows = self.rows[idx]
        cls = self._cls_pos[idx]
        R = self.resolution
        out = np.empty((len(idx), R, R, R // 8), np.uint8)
        for p in np.unique(cls):
            m = cls == p
            out[m] = self._packed[p][rows[m]]
        if rng is not None:
            grids = np.unpackbits(out, axis=-1)
            out = pack_voxels(
                np.stack([random_orientation(rng)(g) for g in grids])
            )
        return out

    def __len__(self) -> int:
        return len(self.labels)

    def materialize_split(
        self, multiple_of: int = 1, num_shards: int = 1, shard_id: int = 0
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """This host's block of the DEVICE-RESIDENT (HBM) dataset.

        Returns ``(packed_voxels, labels, n_global)``: the rows of a
        seed-shuffled global order that fall in feed-group ``shard_id``'s
        contiguous block. The global order is trimmed to a multiple of
        ``multiple_of`` (the mesh's data-axis size — shard_map needs even
        dim-0 shards; at most ``multiple_of - 1`` rows are dropped, and
        which rows is seed-deterministic). The shuffle is what makes each
        device's block a random subset, so the on-device block-stratified
        draw (train.steps.make_hbm_multi_train_step) samples the whole
        class distribution from every shard.
        """
        n = len(self.labels)
        keep = n - (n % max(multiple_of, 1))
        if keep < num_shards:
            raise ValueError(
                f"split has {n} rows; {keep} after trimming to a multiple "
                f"of {multiple_of} — too few for {num_shards} feed groups"
            )
        order = np.random.default_rng(
            np.random.SeedSequence([self.seed, 0x4B10C5])
        ).permutation(n)[:keep]
        lo = keep * shard_id // num_shards
        hi = keep * (shard_id + 1) // num_shards
        rows = order[lo:hi]
        return self._gather(rows), self.labels[rows], keep

    def worker_iter(
        self, worker_id: int = 0, num_workers: int = 1
    ) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.host_id, worker_id])
        )
        n = len(self.labels)
        while True:
            idx = rng.integers(0, n, size=self.local_batch)
            voxels = self._gather(idx, rng if self.augment else None)
            yield {
                "voxels": voxels,
                "label": self.labels[idx],
                "mask": np.ones(self.local_batch, dtype=np.float32),
            }

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return self.worker_iter(0, 1)

    def epoch_batches(
        self, batch: int, num_shards: int = 1, shard_id: int = 0
    ) -> Iterator[dict[str, np.ndarray]]:
        """One exact pass over the split, every sample exactly once.

        The final partial batch is padded (wrapping to the front) with
        ``mask=0`` rows, so downstream masked sums count each held-out
        sample exactly once while batch shapes stay static.
        ``num_shards``/``shard_id`` split the pass disjointly (multi-host
        eval: each host feeds only its shard, globally reduced sums still
        count every sample once).
        """
        for idx, mask in _epoch_index_batches(
            len(self.labels), batch, num_shards, shard_id
        ):
            yield {
                "voxels": self._gather(idx),
                "label": self.labels[idx],
                "mask": mask,
            }
