"""Microbenchmarks for the custom ops — the evidence behind backend defaults.

Run on TPU:  python -m featurenet_tpu.ops.bench_ops
Prints one JSON line per case; measured results are recorded in BASELINE.md.

Timing method: the op is chained N times inside one compiled ``lax.scan``
(output projected back to the input's channel count between iterations), so a
measurement is a single dispatch — per-call dispatch latency through this
environment's tunneled TPU is milliseconds-scale and noisy, which would swamp
sub-millisecond kernels. Per-op time = (wall(scan 2N) - wall(scan N)) / N,
with a device→host readback as the sync point (``block_until_ready`` returns
early on the tunneled backend; a readback is the honest wall).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _chain(f, iters):
    import jax
    import jax.numpy as jnp

    def run(x, w):
        cin = x.shape[-1]

        def body(c, _):
            y = f(c, w)
            if y.shape == x.shape:
                nxt = y
            elif y.shape[-1] >= cin and y.shape[:-1] == x.shape[:-1]:
                nxt = y[..., :cin]
            else:
                # Strided op: shape changes — re-feed x, but thread a tiny
                # data dependency on y through the carry so the scan body
                # cannot be dead-code-eliminated.
                nxt = x + (jnp.tanh(jnp.mean(y)) * 1e-12).astype(x.dtype)
            return nxt.astype(x.dtype), ()

        out, _ = jax.lax.scan(body, x, None, length=iters)
        return out

    return jax.jit(run)


def scan_time(f, x, w, iters: int = 128) -> float:
    """Per-op seconds via scan-chained slope timing (see module docstring)."""
    import jax.numpy as jnp

    short, long_ = _chain(f, iters), _chain(f, 2 * iters)

    def wall(g, repeats: int = 5):
        y = g(x, w)  # warm/compile
        float(jnp.sum(y[(0,) * (y.ndim - 1)]))
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            y = g(x, w)
            float(jnp.sum(y[(0,) * (y.ndim - 1)]))
            best = min(best, time.perf_counter() - t0)
        return best

    return (wall(long_) - wall(short)) / iters


def main() -> None:
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.ops.conv3d import conv3d_p, pallas_conv_supported
    from featurenet_tpu.ops.stem import space_to_depth_conv

    rng = np.random.default_rng(0)

    def xla_conv(stride):
        def f(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (stride,) * 3, "SAME",
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            )
        return f

    # --- stem: direct stride-2 vs space-to-depth ----------------------------
    B, R, K, Cout = 96, 64, 7, 32
    x = jnp.asarray(rng.standard_normal((B, R, R, R, 1)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((K, K, K, 1, Cout)) * 0.1, jnp.bfloat16)
    t_direct = scan_time(xla_conv(2), x, w, iters=32)
    t_s2d = scan_time(
        lambda x, w: space_to_depth_conv(x, w, 2), x, w, iters=32
    )
    flops = 2 * B * (R // 2) ** 3 * K ** 3 * Cout
    for name, t in [("stem7_direct", t_direct), ("stem7_s2d", t_s2d)]:
        print(json.dumps({
            "metric": name, "value": round(t * 1e3, 3), "unit": "ms",
            "tflops": round(flops / t / 1e12, 1),
        }))
    print(json.dumps({
        "metric": "stem7_s2d_speedup", "value": round(t_direct / t_s2d, 2),
        "unit": "x",
    }))

    # --- stride-1 blocks: XLA vs Pallas (fp32 — kernel dtype constraint) ----
    for name, B, R, Cin, Cout, K in [
        ("conv2_32r_k5", 32, 32, 32, 32, 5),
        ("conv3_16r_k3", 32, 16, 32, 64, 3),
        ("conv4_16r_k3", 32, 16, 64, 64, 3),
    ]:
        x = jnp.asarray(rng.standard_normal((B, R, R, R, Cin)), jnp.float32)
        w = jnp.asarray(
            rng.standard_normal((K, K, K, Cin, Cout)) * 0.1, jnp.float32
        )
        t_xla = scan_time(xla_conv(1), x, w)
        row = {"metric": f"{name}_xla_fp32", "value": round(t_xla * 1e3, 3),
               "unit": "ms"}
        if pallas_conv_supported(x.shape, K, Cout, x.dtype):
            t_pal = scan_time(conv3d_p, x, w)
            row["pallas_ms"] = round(t_pal * 1e3, 3)
            row["pallas_vs_xla"] = round(t_xla / t_pal, 2)
        print(json.dumps(row))

    # --- conv2 weight grad: XLA VJP vs the tap-folded Pallas kernel ---------
    # The measured pod64 bottleneck (BASELINE.md: ~18 ms, ~60 TF/s — Cout=32
    # fills 32/128 MXU columns). conv_dw_folded moves k x-taps onto the
    # column side (N = k·Cout); both paths accumulate fp32 from bf16 inputs,
    # matching the real training step's dtypes.
    from featurenet_tpu.ops.conv_dw import conv_dw_folded, dw_folded_supported

    for name, B, R, Cin, Cout, K in [
        ("conv2_dw_b128_k5", 128, 32, 32, 32, 5),
        ("conv3_dw_b128_k3", 128, 16, 32, 64, 3),
    ]:
        x = jnp.asarray(rng.standard_normal((B, R, R, R, Cin)), jnp.bfloat16)
        g = jnp.asarray(rng.standard_normal((B, R, R, R, Cout)), jnp.bfloat16)
        w0 = jnp.zeros((K, K, K, Cin, Cout), jnp.float32)

        def xla_dw(x, g):
            _, vjp = jax.vjp(
                lambda w: jax.lax.conv_general_dilated(
                    x, w.astype(x.dtype), (1, 1, 1), "SAME",
                    dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
                ),
                w0,
            )
            return vjp(g)[0]

        flops = 2 * B * R ** 3 * K ** 3 * Cin * Cout
        t_xla = scan_time(xla_dw, x, g, iters=16)
        row = {"metric": f"{name}_xla", "value": round(t_xla * 1e3, 3),
               "unit": "ms", "tflops": round(flops / t_xla / 1e12, 1)}
        if dw_folded_supported(x.shape, K, Cout, x.dtype):
            t_fold = scan_time(
                lambda x, g: conv_dw_folded(x, g, K), x, g, iters=16
            )
            row["folded_ms"] = round(t_fold * 1e3, 3)
            row["folded_tflops"] = round(flops / t_fold / 1e12, 1)
            row["folded_vs_xla"] = round(t_xla / t_fold, 2)
        print(json.dumps(row))


if __name__ == "__main__":
    main()
