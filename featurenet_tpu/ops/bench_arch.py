"""Architecture-variant throughput sweep (the round-2 ceiling attack).

Round-1 profiling pinned the pod64 step's cost on the conv2 5³ weight-grad:
a [125·32, B·32³, 32]-shaped contraction whose C_out=32 fills 32/128 MXU
columns (~25% shape ceiling, BASELINE.md "where the milliseconds go"). Two
levers follow, both expressible as arch configs without touching the model:

- **k3**: shrink conv2's kernel 5³→3³ (FLOPs ×27/125 on the dominant block).
  The 5³ window was a 2018 GPU-era choice; at 64³ with a s2 stem in front,
  the effective receptive field loss is small — accuracy must be (and is)
  re-validated on the full benchmark before this becomes a preset.
- **wide**: double channels (C_out ≥ 64) so the dW contraction fills ≥50%
  of the MXU — more FLOPs/sample but run at proportionally better
  efficiency; the MFU row quantifies the shape ceiling directly.

Run on the real chip: ``python -m featurenet_tpu.ops.bench_arch``
(one JSON line per variant × batch; ~1 min total).

Measurement core: ``featurenet_tpu.benchmark.measure_train_step``, which
builds the swept step as the runtime registry's ``train_step`` program
(``featurenet_tpu.runtime``) — the sweep times exactly the executable the
Trainer dispatches, sharding/donation decisions included, and an
``--exec-cache-dir``-style persistent cache can serve repeat sweeps.
"""

from __future__ import annotations

import dataclasses
import json

from featurenet_tpu.config import get_config
from featurenet_tpu.models.featurenet import FeatureNetArch


VARIANTS = {
    "paper": FeatureNetArch(),
    "paper_hybrid_dw": dataclasses.replace(
        FeatureNetArch(), conv_backend="hybrid_dw"
    ),
    "k3": dataclasses.replace(FeatureNetArch(), kernels=(7, 3, 3, 3)),
    "wide": dataclasses.replace(
        FeatureNetArch(), features=(64, 64, 128, 128)
    ),
    "wide_k3": dataclasses.replace(
        FeatureNetArch(), features=(64, 64, 128, 128), kernels=(7, 3, 3, 3)
    ),
    # turbo64 as shipped: 7^3/s2 stem -> pool -> 3^3 blocks at 16^3.
    "turbo": dataclasses.replace(
        FeatureNetArch(), kernels=(7, 3, 3, 3),
        pool_after=(True, False, False, True),
    ),
    # Round-3 profiler levers (BASELINE.md "where turbo64's ms go"): the
    # stem is 43% of fwd+bwd at its Cout=32 shape ceiling, and the flatten
    # head is ~14% at 1.2 TF/s.
    # s4: same 7^3 receptive field, stride 4 -> 16^3 directly (1/8 the stem
    # FLOPs of turbo's stem+pool route; pooling after a stride-2 stem
    # computes 8 voxels then discards 7).
    "s4": dataclasses.replace(
        FeatureNetArch(), kernels=(7, 3, 3, 3), strides=(4, 1, 1, 1),
        pool_after=(False, False, False, True),
    ),
    # s4 + GAP head: kills the 32768-wide flatten Dense (thin-K dW, 16.8 MB
    # fp32 params) in favor of a 64-vector head.
    "s4_gap": dataclasses.replace(
        FeatureNetArch(), kernels=(7, 3, 3, 3), strides=(4, 1, 1, 1),
        pool_after=(False, False, False, True), head_gap=True,
    ),
    # GAP alone (stem unchanged) to separate the two levers' contributions.
    "turbo_gap": dataclasses.replace(
        FeatureNetArch(), kernels=(7, 3, 3, 3),
        pool_after=(True, False, False, True), head_gap=True,
    ),
    # Round-12 roofline lever (ops/conv33.py): the 3^3 stride-1 blocks
    # lowered as 27 tap-unrolled channels-last matmuls instead of XLA's
    # generic conv — the memory-bound-program attack the PR-9 roofline
    # justifies. fused33 on the paper shape specializes its two 3^3
    # blocks; k3_fused33 is the apples-to-apples against "k3" (all
    # non-stem blocks 3^3, so the specialization covers the FLOPs bulk).
    "fused33": dataclasses.replace(
        FeatureNetArch(), conv_backend="fused33"
    ),
    "k3_fused33": dataclasses.replace(
        FeatureNetArch(), kernels=(7, 3, 3, 3), conv_backend="fused33"
    ),
}


def main(batches=(128, 256), variants=None, repeats: int = 3) -> list[dict]:
    # repeats=3 per row: sweep-derived decisions (which variant becomes the
    # flagship) must not ride on one ±13% slope sample through the tunnel
    # (round-2 verdict weak #8); rows report best + spread_pct.
    from featurenet_tpu.benchmark import measure_train_step

    rows = []
    for name, arch in (variants or VARIANTS).items():
        for b in batches:
            cfg = dataclasses.replace(get_config("pod64"), arch=arch)
            row = {
                "variant": name,
                **measure_train_step(cfg, b, repeats=repeats),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    main()
