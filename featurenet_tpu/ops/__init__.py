"""TPU-first custom ops.

The reference (torch FeatureNet, SURVEY.md §2 C1/C6) leans on cuDNN for its
3D convolutions; here the compute path is XLA — and where XLA's lowering is
measurably weak, this package supplies the fix:

- ``stem``: space-to-depth reformulation of strided convolutions. XLA:TPU
  lowers the paper's 7³/stride-2/1-channel stem at ~10 TF/s (measured,
  BASELINE.md); the s2d-transformed equivalent runs at the MXU's preferred
  shapes for a measured 5.3x layer speedup. Numerically identical.
- ``conv3d``: a Pallas shift-and-matmul 3D convolution (fp32, stride 1) with
  a custom VJP, as an alternative backend to XLA's conv lowering, plus the
  microbenchmark that decides which backend the model uses.
"""

from featurenet_tpu.ops.stem import SpaceToDepthConv, space_to_depth_conv
from featurenet_tpu.ops.conv3d import conv3d_p, pallas_conv_supported

__all__ = [
    "SpaceToDepthConv",
    "space_to_depth_conv",
    "conv3d_p",
    "pallas_conv_supported",
]
